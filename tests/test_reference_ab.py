"""Cross-implementation A/B tests against the LITERAL reference code.

Everything else in tests/ checks this repo against hand-written re-derivations
of the reference's semantics (tests/oracles.py). These tests close the loop by
running the reference's own files as oracles — possible because torch (CPU)
and networkx are installed here:

- ``/root/reference/evaluation/evaluate.py`` (torch+numpy) scores the same
  npz predictions + GT txt as ``maskclustering_tpu.evaluation``; the result
  CSVs must agree to 1e-6, class-aware and class-agnostic.
- ``/root/reference/graph/iterative_clustering.py`` + ``graph/node.py`` run
  the reference's node-merging loop on the same (visible, contained) tensors
  as ``maskclustering_tpu.models.clustering``; the final partitions of mask
  indices must be identical.

The only shims are environmental, never semantic: ``torch.Tensor.cuda`` is
made a no-op (no GPU here; placement only — every op the reference runs is
device-agnostic), and ``open3d`` is stubbed for ``graph.node`` (Node only
touches it in get_point_cloud, which these tests never call).
"""

import os
import subprocess
import sys
import textwrap
import types

import numpy as np
import pytest

REFERENCE = os.environ.get("MCT_REFERENCE_DIR", "/root/reference")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REFERENCE, "evaluation")),
    reason="reference checkout not available")

torch = pytest.importorskip("torch")


# ---------------------------------------------------------------- evaluator

def _synth_scan(rng, n=3000):
    """One scan exercising every protocol branch: exact matches, partial
    overlaps, confidence ties (duplicate detection), void coverage,
    sub-min-region instances and predictions, and an invalid pred class."""
    gt = np.zeros(n, dtype=np.int64)
    # instances: (start, stop, gt_id) — scannet ids 3=cabinet, 4=bed, 5=chair
    spans = [(0, 400, 3001), (400, 750, 3002), (750, 1050, 4003),
             (1050, 1300, 5004), (1300, 1380, 5005),  # 80 verts: sub-min GT
             (1380, 1530, 99006),  # label 99 not in vocab -> void
             ]
    for a, b, gid in spans:
        gt[a:b] = gid
    # predictions
    cols = []
    scores = []
    classes = []

    def pred(a, b, score, cls):
        m = np.zeros(n, dtype=bool)
        m[a:b] = True
        cols.append(m)
        scores.append(score)
        classes.append(cls)

    pred(0, 280, 0.95, 3)       # IoU 0.70 with 3001: in at 0.5-0.65, out above
    pred(0, 400, 0.95, 3)       # exact later duplicate at equal confidence
    pred(400, 560, 0.80, 3)     # IoU 0.46 with 3002: in at 0.25, out at 0.5
    pred(380, 760, 0.75, 3)     # straddles 3001/3002 at low IoU with each
    pred(750, 1050, 0.90, 4)    # exact match of 4003
    pred(760, 900, 0.70, 4)     # duplicate at lower confidence, partial
    pred(1050, 1300, 0.60, 5)   # exact match of 5004
    pred(1300, 1380, 0.99, 5)   # matches only the sub-min-region GT
    pred(1380, 1530, 0.85, 3)   # entirely on void -> ignored, not FP
    pred(1600, 1650, 0.85, 3)   # 50 verts: below min region size, skipped
    pred(2000, 2400, 0.50, 77)  # class id not in vocabulary
    pred(2000, 2500, float(rng.random()), 3)  # FP on unannotated points
    masks = np.stack(cols, axis=1)
    return gt, masks, np.asarray(scores), np.asarray(classes, dtype=np.int32)


def _write_scans(tmp_path, seeds, synth=None):
    synth = synth or _synth_scan
    gt_dir = tmp_path / "gt"
    pred_dir = tmp_path / "pred"
    gt_dir.mkdir()
    pred_dir.mkdir()
    for i, seed in enumerate(seeds):
        rng = np.random.default_rng(seed)
        gt, masks, scores, classes = synth(rng)
        name = f"scene{i:04d}_00"
        np.savetxt(gt_dir / f"{name}.txt", gt, fmt="%d")
        np.savez(pred_dir / f"{name}.npz", pred_masks=masks,
                 pred_score=scores, pred_classes=classes)
    return gt_dir, pred_dir


def _assert_evaluators_agree(tmp_path, gt_dir, pred_dir, no_class,
                             dataset="scannet"):
    """Run both evaluators on the scans in pred_dir/gt_dir and compare the
    full result CSVs to 1e-6 (nan == nan)."""
    from maskclustering_tpu.evaluation import evaluate_scans

    names = sorted(p.name[:-4] for p in pred_dir.glob("*.npz"))
    suffix = "_class_agnostic" if no_class else ""
    ref_out = tmp_path / f"ref{suffix}.txt"  # pre-suffixed: the reference
    # renames outputs lacking 'class_agnostic' in --no_class mode
    _run_reference_evaluator(pred_dir, gt_dir, ref_out, no_class, dataset)
    repo_out = tmp_path / "repo.txt"
    evaluate_scans([str(pred_dir / f"{n}.npz") for n in names],
                   [str(gt_dir / f"{n}.txt") for n in names],
                   dataset, no_class=no_class, output_file=str(repo_out),
                   verbose=False)
    ref_rows = _parse_result_csv(ref_out)
    repo_rows = _parse_result_csv(repo_out)
    assert len(ref_rows) == len(repo_rows)
    for ref_row, repo_row in zip(ref_rows, repo_rows):
        np.testing.assert_allclose(repo_row, ref_row, atol=1e-6, rtol=0,
                                   equal_nan=True)


def _run_reference_evaluator(pred_dir, gt_dir, out_file, no_class,
                             dataset="scannet"):
    """Run the reference evaluator file as __main__ in a subprocess.

    sys.argv is set before runpy because evaluate.py parses flags at import
    time (reference evaluation/evaluate.py:7-13)."""
    argv = ["evaluate.py", "--pred_path", str(pred_dir), "--gt_path",
            str(gt_dir), "--dataset", dataset, "--output_file", str(out_file)]
    if no_class:
        argv.append("--no_class")
    runner = textwrap.dedent(f"""
        import runpy, sys
        sys.path.insert(0, {REFERENCE!r})
        import torch
        torch.Tensor.cuda = lambda self, *a, **k: self  # CPU shim
        sys.argv = {argv!r}
        runpy.run_path({os.path.join(REFERENCE, 'evaluation', 'evaluate.py')!r},
                       run_name="__main__")
    """)
    subprocess.run([sys.executable, "-c", runner], check=True,
                   cwd=str(pred_dir), stdout=subprocess.DEVNULL)


def _parse_result_csv(path):
    """-> (header-less list of float rows); nan-safe."""
    rows = []
    for line in path.read_text().splitlines()[1:]:
        cells = line.split(",")
        vals = cells[-3:] if len(cells) >= 5 else cells  # class rows vs avg row
        rows.append([float(v) for v in vals])
    return rows


def _random_scan(rng, n, gt_pool, pred_pool):
    """Unstructured random scan: random instance spans and predictions with
    random extents/scores/classes drawn from the given class pools —
    sweeps protocol-branch combinations the crafted scan doesn't
    enumerate."""
    gt = np.ones(n, dtype=np.int64)  # unannotated
    cur = 0
    inst = 1
    while cur < n - 100:
        span = int(rng.integers(60, 400))
        cls = int(gt_pool[rng.integers(0, len(gt_pool))])
        gt[cur:cur + span] = cls * 1000 + inst
        inst += 1
        cur += span + int(rng.integers(0, 120))
    cols, scores, classes = [], [], []
    for _ in range(int(rng.integers(6, 14))):
        a = int(rng.integers(0, n - 60))
        b = a + int(rng.integers(40, 500))
        m = np.zeros(n, dtype=bool)
        m[a:min(b, n)] = True
        cols.append(m)
        scores.append(float(np.round(rng.random(), 2)))  # coarse -> real ties
        classes.append(int(pred_pool[rng.integers(0, len(pred_pool))]))
    return gt, np.stack(cols, axis=1), np.asarray(scores), \
        np.asarray(classes, dtype=np.int32)


def _synth_random_scan(rng, n=2500):
    # 99 = void label in GT; predictions draw valid scannet ids only
    return _random_scan(rng, n, gt_pool=[3, 4, 5, 7, 99],
                        pred_pool=[3, 4, 5, 7])


def _make_vocab_synth(ids):
    """Dataset-generic random-scan synth: GT instances and prediction
    classes sampled from the dataset's benchmark vocabulary, plus a void
    label (not in the vocabulary) and one invalid prediction class."""
    ids = sorted(ids)
    void = ids[-1] + 1
    # deterministic spread across the vocabulary incl. both extremes
    pool = sorted({ids[0], ids[len(ids) // 3], ids[len(ids) // 2],
                   ids[(2 * len(ids)) // 3], ids[-1]})

    def synth(rng, n=2500):
        # void id doubles as an invalid prediction class
        return _random_scan(rng, n, gt_pool=pool + [void],
                            pred_pool=pool + [void])

    return synth


@pytest.mark.parametrize("dataset", ["matterport3d", "scannetpp"])
@pytest.mark.parametrize("no_class", [False, True])
def test_evaluator_matches_reference_other_vocabs(tmp_path, dataset, no_class):
    """Protocol parity beyond ScanNet: the matterport3d (157-class) and
    scannetpp (1554-class) vocabularies through both evaluators — same
    1e-6 CSV agreement, including the full-vocabulary class-AP table."""
    from maskclustering_tpu.semantics.vocab import get_vocab

    _, ids = get_vocab(dataset)
    gt_dir, pred_dir = _write_scans(tmp_path, (13, 29),
                                    synth=_make_vocab_synth(ids))
    _assert_evaluators_agree(tmp_path, gt_dir, pred_dir, no_class,
                             dataset=dataset)


@pytest.mark.parametrize("no_class", [False, True])
@pytest.mark.parametrize("seeds", [(41, 59), (71, 83, 97)])
def test_evaluator_matches_reference_on_random_scans(tmp_path, seeds, no_class):
    gt_dir, pred_dir = _write_scans(tmp_path, seeds, synth=_synth_random_scan)
    _assert_evaluators_agree(tmp_path, gt_dir, pred_dir, no_class)


@pytest.mark.parametrize("no_class", [False, True])
def test_evaluator_matches_reference_bit_level(tmp_path, no_class):
    gt_dir, pred_dir = _write_scans(tmp_path, seeds=(11, 23))
    _assert_evaluators_agree(tmp_path, gt_dir, pred_dir, no_class)


def test_matterport_loader_matches_reference(tmp_path, monkeypatch):
    """Our MatterportDataset vs the literal reference dataset/matterport.py
    on the same .conf + depth PNGs: frame list, per-frame intrinsics, the
    GL->CV extrinsic flip, and the 0.25 mm depth decode."""
    pytest.importorskip("cv2")
    from PIL import Image

    _open3d_stub()
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import dataset.matterport as ref_mod  # noqa: PLC0415

    from maskclustering_tpu.datasets.matterport import MatterportDataset

    seq = "17DRP5sb8fy"
    base = tmp_path / "data" / "matterport3d" / "scans" / seq / seq
    (base / "undistorted_camera_parameters").mkdir(parents=True)
    (base / "undistorted_depth_images").mkdir()
    rng = np.random.default_rng(4)

    def ext_line(i):
        # non-identity rotation + translation: distinguishes the GL->CV
        # COLUMN flip from a row-flip bug, which coincide on identity
        th = 0.3 + 0.2 * i
        c, s = np.cos(th), np.sin(th)
        ext = np.eye(4)
        ext[:3, :3] = [[c, -s, 0], [s, c, 0], [0, 0, 1.0]]
        ext[:3, 3] = [1.0 + i, -2.0, 0.5 * i]
        return " ".join(str(float(x)) for x in ext.flatten())

    # real Matterport layout: each intrinsics_matrix governs the 6 scans
    # after it (the reference indexes scan i into 6 appended copies; ours
    # carries the current block forward — identical exactly per-format)
    conf = ["dataset matterport",
            "intrinsics_matrix 1000 0 640  0 1000 512  0 0 1"]
    conf += [f"scan d{i}.png c{i}.jpg {ext_line(i)}" for i in range(6)]
    conf += ["intrinsics_matrix 1077 0 630  0 1077 500  0 0 1",
             f"scan d6.png c6.jpg {ext_line(6)}"]
    (base / "undistorted_camera_parameters" / f"{seq}.conf").write_text(
        "\n".join(conf) + "\n")
    for i in range(7):
        Image.fromarray(rng.integers(2000, 8000, size=(32, 40))
                        .astype(np.uint16)).save(
            base / "undistorted_depth_images" / f"d{i}.png")

    monkeypatch.chdir(tmp_path)  # the reference hardcodes ./data/...
    ref = ref_mod.MatterportDataset(seq)
    ours = MatterportDataset(seq, data_root=str(tmp_path / "data"))

    assert list(ref.get_frame_list(1)) == list(ours.get_frame_list(1))
    for fid in ours.get_frame_list(1):
        pin = ref.get_intrinsics(fid)
        k = ours.get_intrinsics(fid)
        np.testing.assert_allclose(
            [pin.fx, pin.fy, pin.cx, pin.cy],
            [k[0, 0], k[1, 1], k[0, 2], k[1, 2]])
        np.testing.assert_array_equal(ref.get_extrinsic(fid),
                                      ours.get_extrinsic(fid))
        d_ref = ref.get_depth(fid)
        d_ours = ours.get_depth(fid)
        assert d_ref.dtype == d_ours.dtype == np.float32
        np.testing.assert_allclose(d_ours, d_ref, rtol=3e-7, atol=0)


def test_scannetpp_loader_matches_reference(tmp_path, monkeypatch):
    """Our ScanNetPPDataset vs the literal reference dataset/scannetpp.py on
    the same COLMAP text + render_depth: frame ids, quaternion->c2w
    extrinsics (inv of world_to_camera), intrinsics, depth decode."""
    pytest.importorskip("cv2")
    from PIL import Image

    _open3d_stub()
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import dataset.scannetpp as ref_mod  # noqa: PLC0415

    from maskclustering_tpu.datasets.scannetpp import ScanNetPPDataset

    seq = "abc123"
    base = tmp_path / "data" / "scannetpp" / "data" / seq
    colmap = base / "iphone" / "colmap"
    colmap.mkdir(parents=True)
    (base / "iphone" / "render_depth").mkdir()
    (tmp_path / "data" / "scannetpp" / "pcld_0.25").mkdir()
    (colmap / "cameras.txt").write_text(
        "# cameras\n1 PINHOLE 1920 1440 1500 1500 960 720\n")
    (colmap / "images.txt").write_text(
        "# images\n"
        "1 1 0 0 0 1 2 3 1 frame_000000.jpg\n"
        "0.0 0.0 -1\n"
        "2 0.7071067811865476 0 0.7071067811865476 0 0 0 0 1 frame_000010.jpg\n"
        "\n"
        # rotation AND translation together: c2w = [R^T | -R^T t] — catches
        # the classic analytic-inverse bug [R^T | -t]
        "3 0.7071067811865476 0 0.7071067811865476 0 1 2 3 1 frame_000020.jpg\n"
        "1.0 -2.0 5\n")
    rng = np.random.default_rng(6)
    for i in (0, 10, 20):
        Image.fromarray(rng.integers(500, 3000, size=(24, 32))
                        .astype(np.uint16)).save(
            base / "iphone" / "render_depth" / f"frame_{i:06d}.png")
    # a tensor payload: the reference's bare torch.load runs under the
    # torch>=2.6 weights_only default, which rejects pickled numpy arrays
    torch.save({"sampled_coords": torch.tensor(rng.normal(size=(40, 3)))},
               tmp_path / "data" / "scannetpp" / "pcld_0.25" / f"{seq}.pth")

    monkeypatch.chdir(tmp_path)
    ref = ref_mod.ScanNetPPDataset(seq)
    ours = ScanNetPPDataset(seq, data_root=str(tmp_path / "data"))

    assert list(ref.get_frame_list(1)) == list(ours.get_frame_list(1))
    assert list(ref.get_frame_list(2)) == list(ours.get_frame_list(2))
    for fid in ours.get_frame_list(1):
        pin = ref.get_intrinsics(fid)
        k = ours.get_intrinsics(fid)
        np.testing.assert_allclose(
            [pin.fx, pin.fy, pin.cx, pin.cy],
            [k[0, 0], k[1, 1], k[0, 2], k[1, 2]])
        np.testing.assert_allclose(ref.get_extrinsic(fid),
                                   ours.get_extrinsic(fid), atol=1e-12)
        d_ref = ref.get_depth(fid)
        d_ours = ours.get_depth(fid)
        assert d_ref.dtype == d_ours.dtype == np.float32
        np.testing.assert_allclose(d_ours, d_ref, rtol=3e-7, atol=0)
    np.testing.assert_array_equal(ref.get_scene_points(),
                                  ours.get_scene_points())


def test_sens_writer_parses_with_reference_sensordata(tmp_path):
    """Binary .sens contract: a file produced by our write_sens must parse
    bit-for-bit in the LITERAL reference parser (preprocess/scannet/
    SensorData.py) — header fields, per-frame poses, zlib depth, jpeg color.

    Only the `png` module (absent here) is stubbed; it is used by the
    reference's exporter methods, never by the parser under test."""
    pytest.importorskip("cv2")
    pytest.importorskip("imageio")
    if "png" not in sys.modules:
        sys.modules["png"] = types.ModuleType("png")
    ref_dir = os.path.join(REFERENCE, "preprocess", "scannet")
    if ref_dir not in sys.path:
        sys.path.insert(0, ref_dir)
    import SensorData as ref_sens  # noqa: PLC0415

    from test_preprocess import _make_sens  # noqa: PLC0415 — shared fixture

    path = str(tmp_path / "scene.sens")
    header, depths, poses = _make_sens(path, n_frames=5, dw=10, dh=8)

    sd = ref_sens.SensorData(path)
    assert sd.sensor_name.decode() == header.sensor_name
    assert sd.depth_shift == header.depth_shift
    assert (sd.color_width, sd.color_height) == (header.color_width,
                                                 header.color_height)
    assert (sd.depth_width, sd.depth_height) == (header.depth_width,
                                                 header.depth_height)
    assert sd.color_compression_type == "jpeg"
    assert sd.depth_compression_type == "zlib_ushort"
    np.testing.assert_array_equal(sd.intrinsic_depth, header.intrinsic_depth)
    assert len(sd.frames) == 5
    for i, frame in enumerate(sd.frames):
        np.testing.assert_array_equal(frame.camera_to_world, poses[i])
        depth = np.frombuffer(
            frame.decompress_depth("zlib_ushort"), dtype=np.uint16
        ).reshape(8, 10)
        np.testing.assert_array_equal(depth, depths[i])
        rgb = frame.decompress_color("jpeg")
        assert rgb.shape == (12, 16, 3)


def test_gt_encoding_matches_reference_prepare_gt(tmp_path):
    """GT preparation A/B: our scannet_scene_gt vs the literal reference
    preprocess/scannet/prepare_gt.py handle_process on the same segs.json +
    aggregation.json + label tsv — byte-identical GT txt, including the
    invalid-label zeroing, group-id+1 instances, and overlap overwrite."""
    pytest.importorskip("pandas")
    import json as json_mod

    import pandas as pd

    ref_dir = os.path.join(REFERENCE, "preprocess", "scannet")
    if ref_dir not in sys.path:
        sys.path.insert(0, ref_dir)
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)  # prepare_gt imports evaluation.constants
    import prepare_gt as ref_gt  # noqa: PLC0415

    from maskclustering_tpu.preprocess.scannet import (
        load_label_map,
        scannet_scene_gt,
    )

    seq = "scene0042_00"
    scene = tmp_path / "scans" / seq
    scene.mkdir(parents=True)
    rng = np.random.default_rng(12)
    seg_indices = rng.integers(0, 40, size=500).tolist()
    groups = [
        {"id": 0, "label": "chair", "segments": [0, 1, 2, 3]},
        {"id": 1, "label": "weird", "segments": [4, 5]},  # non-benchmark id
        {"id": 2, "label": "nosuch", "segments": [6]},  # absent from tsv
        {"id": 3, "label": "bed", "segments": [7, 8, 9]},
        # overlapping segments with group 0: later group overwrites
        {"id": 4, "label": "table", "segments": [3, 10, 11]},
    ]
    (scene / f"{seq}_vh_clean_2.0.010000.segs.json").write_text(
        json_mod.dumps({"segIndices": seg_indices}))
    (scene / f"{seq}.aggregation.json").write_text(
        json_mod.dumps({"segGroups": groups}))
    tsv = tmp_path / "labels.tsv"
    tsv.write_text("id\traw_category\tcategory\n"
                   "5\tchair\tchair\n999\tweird\tweird\n"
                   "4\tbed\tbed\n7\ttable\ttable\n")

    ref_out = tmp_path / "ref_gt"
    ref_out.mkdir()
    labels_pd = pd.read_csv(tsv, sep="\t", header=0)
    ref_gt.handle_process(str(scene), str(ref_out), labels_pd)

    ours = scannet_scene_gt(str(scene), str(tmp_path / "our_gt" / f"{seq}.txt"),
                            load_label_map(str(tsv)))
    ref_ids = np.loadtxt(ref_out / f"{seq}.txt", dtype=np.int64)
    np.testing.assert_array_equal(ours, ref_ids)
    # non-degenerate: several distinct encodings incl. label 0 groups
    assert len(np.unique(ref_ids)) >= 5


# --------------------------------------------------------------- postprocess

def _import_reference_postprocess():
    """utils/post_process.py imports numpy/torch/utils.geometry only; the
    open3d-touching dbscan_process is never called by these tests."""
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import utils.post_process as ref_pp  # noqa: PLC0415
    return ref_pp


def test_overlap_merge_matches_reference():
    """_merge_overlapping vs the literal merge_overlapping_objects
    (post_process.py:7-37): same survivors in the same order, including the
    scan-order asymmetry (i dies on the first test, j on the elif)."""
    ref_pp = _import_reference_postprocess()
    rng = np.random.default_rng(5)
    objs, bboxes, masks = [], [], []
    base = rng.choice(5000, size=600, replace=False)
    # deaths cover BOTH branches of the reference's asymmetric test:
    # 0 dies via the i-branch (|0∩1|/|0| = 360/400 > 0.8 with j=1);
    # 2 dies via the i-branch against 3; 3 then dies against 4;
    # 6 (strict subset of surviving 1, |6|/|1| = 200/360 <= 0.8) dies via
    # the ELIF j-branch (|1∩6|/|6| = 1.0 > 0.8);
    # 5 shares ids with 1 but its bbox is displaced: prefilter skips it
    objs.append(base[:400])
    objs.append(base[:360])
    objs.append(base[400:520])
    objs.append(np.concatenate([base[400:520], base[520:560]]))
    objs.append(base[200:600])
    objs.append(base[:100])
    objs.append(base[:200])
    pts3d = rng.random((5000, 3)) * 4.0
    for i, o in enumerate(objs):
        lo, hi = pts3d[o].min(axis=0), pts3d[o].max(axis=0)
        if i == 5:
            lo, hi = lo + 100.0, hi + 100.0  # disjoint bbox despite shared ids
        bboxes.append((lo, hi))
        masks.append([("f", i, 0.5)])

    from maskclustering_tpu.models.postprocess import _merge_overlapping

    ref_ids, ref_masks = ref_pp.merge_overlapping_objects(
        [o.copy() for o in objs], [tuple(b) for b in bboxes],
        [list(m) for m in masks], 0.8)
    our_ids, our_masks = _merge_overlapping(
        [o.copy() for o in objs], list(bboxes), [list(m) for m in masks], 0.8)
    assert len(ref_ids) == len(our_ids)
    for r, o in zip(ref_ids, our_ids):
        np.testing.assert_array_equal(np.sort(r), np.sort(o))
    assert ref_masks == our_masks


def test_representative_masks_match_reference():
    ref_pp = _import_reference_postprocess()
    from maskclustering_tpu.models.postprocess import representative_masks

    rng = np.random.default_rng(9)
    infos = [("f%d" % i, i, round(float(c), 6))
             for i, c in enumerate(rng.random(9))]
    infos.append(("tie", 99, infos[3][2]))  # duplicate coverage: stable order
    ours = representative_masks(list(infos))
    ref = ref_pp.find_represent_mask(list(infos))
    assert ours == ref


def test_node_filter_pipeline_matches_reference_filter_point():
    """End-to-end node post-filtering A/B: the literal filter_point
    (post_process.py:40-101) on a crafted node vs postprocess_scene run on
    the equivalent claim tensors. Exercises the OVIR-3D detection ratio,
    best-overlap mask->object assignment with coverage, the < 2-mask object
    drop, and the spatial split — same objects, same mask lists."""
    from types import SimpleNamespace

    from maskclustering_tpu.models.postprocess import postprocess_scene

    ref_pp = _import_reference_postprocess()
    rng = np.random.default_rng(31)
    n, f = 480, 10
    # three far-apart blobs -> unambiguous spatial split at eps 0.5
    pts3d = np.empty((n, 3), dtype=np.float32)
    blob_a = np.arange(0, 220)
    blob_b = np.arange(220, 400)
    blob_c = np.arange(400, 480)
    pts3d[blob_a] = rng.random((len(blob_a), 3))
    pts3d[blob_b] = rng.random((len(blob_b), 3)) + 10.0
    pts3d[blob_c] = rng.random((len(blob_c), 3)) + 20.0

    # node masks: 3 on blob A (frames 0-2), 2 on blob B (frames 3-4), one
    # straddler on frame 5 majority-A, and a SINGLE mask on blob C — whose
    # object must be dropped by the < 2-mask rule on both sides
    mask_defs = [
        (0, 1, rng.choice(blob_a, 150, replace=False)),
        (1, 1, rng.choice(blob_a, 160, replace=False)),
        (2, 2, rng.choice(blob_a, 140, replace=False)),
        (3, 1, rng.choice(blob_b, 120, replace=False)),
        (4, 1, rng.choice(blob_b, 130, replace=False)),
        (5, 1, np.concatenate([rng.choice(blob_a, 90, replace=False),
                               rng.choice(blob_b, 40, replace=False)])),
        (6, 1, rng.choice(blob_c, 60, replace=False)),
    ]
    node_frames = np.zeros(f, dtype=bool)
    node_frames[[d[0] for d in mask_defs]] = True
    # visibility: every claimed point visible in its frame, plus noise
    # visibility in non-node frames (dilutes the denominator for some points)
    point_frame = rng.random((n, f)) < 0.3
    for fid, _, pids in mask_defs:
        point_frame[pids, fid] = True

    # ---- reference side ----
    torch_node = SimpleNamespace(
        visible_frame=torch.tensor(node_frames),
        mask_list=[(fid, mid) for fid, mid, _ in mask_defs])
    mask_point_clouds = {f"{fid}_{mid}": set(map(int, pids))
                        for fid, mid, pids in mask_defs}
    node_point_ids = sorted({int(p) for _, _, pids in mask_defs for p in pids})
    grp_a = np.asarray([p for p in node_point_ids if p < 220])
    grp_b = np.asarray([p for p in node_point_ids if 220 <= p < 400])
    grp_c = np.asarray([p for p in node_point_ids if p >= 400])
    pcld_list = [SimpleNamespace(points=pts3d[g]) for g in (grp_a, grp_b, grp_c)]
    ref_ids, ref_bboxes, ref_masks = ref_pp.filter_point(
        point_frame, torch_node, pcld_list, [grp_a, grp_b, grp_c],
        mask_point_clouds, list(range(f)),
        SimpleNamespace(point_filter_threshold=0.5))
    # the single-mask blob-C object must be dropped by the < 2-mask rule
    assert len(ref_ids) == 2
    assert all(ids.max() < 400 for ids in ref_ids)

    # ---- repo side: same node as claim tensors through postprocess_scene ----
    k_max = 3
    first = np.zeros((f, n), dtype=np.int32)
    last = np.zeros((f, n), dtype=np.int32)
    for fid, mid, pids in mask_defs:
        first[fid, pids] = mid
        last[fid, pids] = mid
    m_pad = len(mask_defs)
    mask_frame = np.asarray([d[0] for d in mask_defs], dtype=np.int32)
    mask_id = np.asarray([d[1] for d in mask_defs], dtype=np.int32)
    node_visible = np.zeros((m_pad, f), dtype=bool)
    node_visible[0] = node_frames  # all masks assigned to rep slot 0
    objects = postprocess_scene(
        pts3d, first, last, point_frame.T.copy(), mask_frame, mask_id,
        np.ones(m_pad, dtype=bool), np.zeros(m_pad, dtype=np.int32),
        node_visible, list(range(f)), k_max=k_max,
        point_filter_threshold=0.5, dbscan_eps=0.5, dbscan_min_points=1,
        overlap_merge_ratio=0.8)

    ref_set = {(frozenset(map(int, ids)),
                frozenset((fid, mid, round(cov, 9)) for fid, mid, cov in ml))
               for ids, ml in zip(ref_ids, ref_masks)}
    our_set = {(frozenset(map(int, ids)),
                frozenset((fid, mid, round(cov, 9)) for fid, mid, cov in ml))
               for ids, ml in zip(objects.point_ids_list, objects.mask_list)}
    assert ref_set == our_set


# ------------------------------------------------------------------- query

def test_query_stage_matches_reference(tmp_path, monkeypatch):
    """maskclustering_tpu.semantics.assign_labels vs the LITERAL reference
    semantics/open-voc_query.py main(): same object_dict + mask features +
    label features -> identical class ids and prediction masks."""
    import runpy
    from types import SimpleNamespace

    from maskclustering_tpu.semantics import assign_labels, l2_normalize
    from maskclustering_tpu.semantics.vocab import get_vocab

    _open3d_stub()
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    # executes the module (imports utils.config -> dataset/* under the
    # open3d stub); returns its globals so main() can run with an injected
    # dataset below
    g = runpy.run_path(os.path.join(REFERENCE, "semantics", "open-voc_query.py"))

    labels, valid_ids = get_vocab("scannet")
    label2id = {l: int(i) for l, i in zip(labels, valid_ids)}
    rng = np.random.default_rng(21)
    dim, n_pts = 64, 4000
    text = l2_normalize(rng.standard_normal((len(labels), dim)).astype(np.float32))
    label_features = {l: text[i] for i, l in enumerate(labels)}

    object_dict = {}
    clip_features = {}
    for o in range(7):
        repre = [(f"fr{o}", m) for m in range(1 + o % 2)]
        for frame, mid in repre:
            clip_features[f"{frame}_{mid}"] = l2_normalize(
                rng.standard_normal(dim).astype(np.float32))
        object_dict[o] = {
            "point_ids": set(rng.choice(n_pts, size=200 + 10 * o, replace=False)
                             .tolist()),
            "repre_mask_list": repre,
        }
    object_dict[7] = {"point_ids": {3}, "repre_mask_list": []}  # featureless

    obj_dir = tmp_path / "obj" / "cfg"
    obj_dir.mkdir(parents=True)
    np.save(obj_dir / "object_dict.npy", object_dict, allow_pickle=True)
    np.save(obj_dir / "open-vocabulary_features.npy", clip_features,
            allow_pickle=True)

    ds = SimpleNamespace(
        object_dict_dir=str(tmp_path / "obj"),
        get_scene_points=lambda: np.zeros((n_pts, 3), dtype=np.float32),
        get_label_features=lambda: label_features,
        get_label_id=lambda: (label2id, {v: k for k, v in label2id.items()}),
    )
    monkeypatch.chdir(tmp_path)  # the reference writes ./data/prediction/...
    main_fn = g["main"]
    # runpy.run_path returns a COPY of the module globals; patch the dict
    # the function actually closes over
    main_fn.__globals__["get_dataset"] = lambda args: ds
    main_fn(SimpleNamespace(config="cfg", seq_name="s0"))
    ref = np.load(tmp_path / "data" / "prediction" / "cfg" / "s0.npz")

    ours = assign_labels(object_dict, clip_features, label_features,
                         label2id, n_pts)
    np.testing.assert_array_equal(ours["pred_classes"], ref["pred_classes"])
    np.testing.assert_array_equal(ours["pred_masks"], ref["pred_masks"])
    np.testing.assert_array_equal(ours["pred_score"], ref["pred_score"])


# ---------------------------------------------------------------- clustering

def _import_reference_graph():
    """Import graph.node + graph.iterative_clustering from the reference.

    open3d is absent from this image; a bare module stub satisfies node.py's
    import (only get_point_cloud uses it, never called here)."""
    if "open3d" not in sys.modules:
        sys.modules["open3d"] = types.ModuleType("open3d")
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import graph.iterative_clustering as ref_ic  # noqa: PLC0415
    import graph.node as ref_node  # noqa: PLC0415
    return ref_node, ref_ic


import contextlib


@contextlib.contextmanager
def _no_cuda():
    """Make torch.Tensor.cuda a placement no-op (no GPU here; every op the
    reference runs under it is device-agnostic)."""
    orig_cuda = torch.Tensor.cuda
    torch.Tensor.cuda = lambda self, *a, **k: self
    try:
        yield
    finally:
        torch.Tensor.cuda = orig_cuda


def _reference_partition(visible, contained, schedule, threshold):
    """Run the literal reference clustering loop -> set of frozen mask-id sets."""
    ref_node, ref_ic = _import_reference_graph()
    with _no_cuda():
        nodes = [
            ref_node.Node([i], torch.tensor(visible[i], dtype=torch.float32),
                          torch.tensor(contained[i], dtype=torch.float32),
                          {i}, (0, i), set())
            for i in range(visible.shape[0])
        ]
        out = ref_ic.iterative_clustering(nodes, list(schedule), threshold,
                                          debug=False)
    return {frozenset(n.mask_list) for n in out}


def _repo_partition(visible, contained, schedule, threshold):
    import jax.numpy as jnp

    from maskclustering_tpu.models.clustering import iterative_clustering

    m = visible.shape[0]
    sched = jnp.asarray(list(schedule) + [np.inf] * 3, dtype=jnp.float32)
    res = iterative_clustering(
        jnp.asarray(visible), jnp.asarray(contained),
        jnp.ones(m, dtype=bool), sched, view_consensus_threshold=threshold)
    assign = np.asarray(res.assignment)
    parts = {}
    for i in range(m):
        parts.setdefault(int(assign[i]), set()).add(i)
    return {frozenset(p) for p in parts.values()}


def _open3d_stub():
    """open3d stub rich enough for the reference dataset loader: the only
    o3d surface it touches is camera.PinholeCameraIntrinsic.set_intrinsics
    (dataset/scannet.py:38-40); everything else is numpy/cv2."""
    mod = sys.modules.get("open3d")
    if mod is None:
        mod = types.ModuleType("open3d")
        sys.modules["open3d"] = mod
    if not hasattr(mod, "camera"):
        class _Pinhole:
            def set_intrinsics(self, w, h, fx, fy, cx, cy):
                self.width, self.height = w, h
                self.fx, self.fy, self.cx, self.cy = fx, fy, cx, cy

        cam = types.ModuleType("open3d.camera")
        cam.PinholeCameraIntrinsic = _Pinhole
        mod.camera = cam
    return mod


def test_scannet_loader_matches_reference(tmp_path, monkeypatch):
    """Our ScanNetDataset and the LITERAL reference loader (dataset/
    scannet.py, cv2-based) read the same on-disk scene identically: frame
    list, poses, intrinsics, segmentation ids, and depth to 1 ulp (the
    documented f32-multiply vs f64-divide decode difference, io/image.py)."""
    pytest.importorskip("cv2")
    _open3d_stub()
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import dataset.scannet as ref_mod  # noqa: PLC0415

    from maskclustering_tpu.datasets.scannet import ScanNetDataset
    from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

    scene = make_scene(num_boxes=3, num_frames=6, image_hw=(48, 64), seed=5)
    write_scannet_layout(scene, str(tmp_path / "data"), "scene0777_00")
    monkeypatch.chdir(tmp_path)  # the reference hardcodes ./data/...

    ref = ref_mod.ScanNetDataset("scene0777_00")
    ref.image_size = (64, 48)  # reference hardcodes 640x480; ours derives it
    ours = ScanNetDataset("scene0777_00", data_root=str(tmp_path / "data"))

    assert ref.get_frame_list(2) == ours.get_frame_list(2)
    for fid in ours.get_frame_list(2):
        np.testing.assert_array_equal(ref.get_extrinsic(fid),
                                      ours.get_extrinsic(fid))
        np.testing.assert_array_equal(
            ref.get_segmentation(fid, align_with_depth=True),
            ours.get_segmentation(fid, align_with_depth=True))
        d_ref = ref.get_depth(fid)
        d_ours = ours.get_depth(fid)
        assert d_ref.dtype == d_ours.dtype == np.float32
        np.testing.assert_allclose(d_ours, d_ref, rtol=3e-7, atol=0)

    pin = ref.get_intrinsics(0)
    ours_k = ours.get_intrinsics(0)
    np.testing.assert_allclose(
        [pin.fx, pin.fy, pin.cx, pin.cy],
        [ours_k[0, 0], ours_k[1, 1], ours_k[0, 2], ours_k[1, 2]])


def _import_reference_construction():
    """Import graph.construction (only get_observer_num_thresholds is used).

    construction.py transitively imports pytorch3d + open3d (absent here);
    bare stubs satisfy the imports — the schedule function touches neither."""
    if "open3d" not in sys.modules:
        sys.modules["open3d"] = types.ModuleType("open3d")
    if "pytorch3d" not in sys.modules:
        p3d = types.ModuleType("pytorch3d")
        ops = types.ModuleType("pytorch3d.ops")
        ops.ball_query = None
        p3d.ops = ops
        sys.modules["pytorch3d"] = p3d
        sys.modules["pytorch3d.ops"] = ops
    if REFERENCE not in sys.path:
        sys.path.insert(0, REFERENCE)
    import graph.construction as ref_con  # noqa: PLC0415
    return ref_con


@pytest.mark.parametrize("seed,m,f,density", [
    (3, 40, 30, 0.4), (11, 64, 100, 0.15), (17, 16, 12, 0.6),
    # disjoint single-frame visibility: every positive observer count is 1,
    # exercising the <=1 clamp + percentile<50 termination branch
    (0, 10, 10, -1.0),
])
def test_observer_schedule_matches_reference(seed, m, f, density):
    """The histogram-derived percentile schedule (models/graph.py) equals the
    literal reference get_observer_num_thresholds (construction.py:80-96) on
    shared visibility tensors."""
    import jax.numpy as jnp

    from maskclustering_tpu.models.graph import (
        observer_schedule,
        observer_schedule_device,
    )

    ref_con = _import_reference_construction()
    rng = np.random.default_rng(seed)
    if density < 0:
        visible = np.eye(m, f, dtype=bool)  # mask i visible only in frame i
    else:
        visible = rng.random((m, f)) < density
        visible[np.arange(m), rng.integers(0, f, m)] = True

    ref = ref_con.get_observer_num_thresholds(
        torch.tensor(visible, dtype=torch.float32))

    obs = visible.astype(np.int64) @ visible.T.astype(np.int64)
    hist = np.bincount(obs.ravel(), minlength=f + 1)
    repo = observer_schedule(hist, max_len=20)
    repo_trim = [v for v in repo.tolist() if np.isfinite(v)]
    np.testing.assert_allclose(repo_trim, ref, rtol=0, atol=1e-9)

    dev = np.asarray(observer_schedule_device(jnp.asarray(hist), max_len=20))
    dev_trim = [v for v in dev.tolist() if np.isfinite(v)]
    np.testing.assert_allclose(dev_trim, ref, rtol=0, atol=1e-4)


def test_clustering_matches_reference_at_bench_scale():
    """512 masks x 256 frames with 16 planted clusters: bf16-operand
    affinity counts and the f32 consensus rate must merge identically to
    the reference's float32 torch loop at real scale."""
    rng = np.random.default_rng(97)
    m, f, blocks = 512, 256, 16
    per = m // blocks
    visible = np.zeros((m, f), dtype=bool)
    contained = np.eye(m, dtype=bool)
    for b in range(blocks):
        sl = slice(b * per, (b + 1) * per)
        frames = rng.choice(f, size=40, replace=False)
        # members co-visible on most block frames, plus private noise frames
        for i in range(b * per, (b + 1) * per):
            visible[i, frames[rng.random(40) < 0.8]] = True
            visible[i, rng.integers(0, f, 3)] = True
        contained[sl, sl] = rng.random((per, per)) < 0.9
    schedule = [12.0, 8.0, 5.0, 3.0, 2.0, 1.0]

    ref_parts = _reference_partition(visible, contained, schedule, 0.9)
    repo_parts = _repo_partition(visible, contained, schedule, 0.9)
    assert repo_parts == ref_parts
    assert len(ref_parts) < m  # real merging happened at scale


@pytest.mark.parametrize("seed,m,f", [(7, 24, 40), (13, 48, 64), (29, 32, 25)])
def test_clustering_matches_reference_oracle(seed, m, f):
    """Identical partitions from the reference's networkx/torch loop and the
    repo's while_loop'd assignment-vector formulation, on shared random
    (visible, contained) tensors over a multi-step threshold schedule."""
    rng = np.random.default_rng(seed)
    visible = rng.random((m, f)) < 0.35
    visible[np.arange(m), rng.integers(0, f, m)] = True  # every mask seen once
    contained = rng.random((m, m)) < 0.25
    np.fill_diagonal(contained, True)
    schedule = [8.0, 5.0, 3.0, 2.0, 1.0]

    ref_parts = _reference_partition(visible, contained, schedule, 0.9)
    repo_parts = _repo_partition(visible, contained, schedule, 0.9)
    assert repo_parts == ref_parts


def test_clustering_matches_reference_on_hub_structure():
    """A deliberate multi-iteration merge: chain blocks that only connect
    after earlier iterations aggregate their features."""
    m, f = 30, 60
    rng = np.random.default_rng(3)
    visible = np.zeros((m, f), dtype=bool)
    contained = np.eye(m, dtype=bool)
    # 6 blocks of 5 masks; masks in a block co-occur heavily and contain
    # each other; adjacent blocks share a weaker bridge mask
    for b in range(6):
        sl = slice(5 * b, 5 * b + 5)
        frames = rng.choice(f, size=12, replace=False)
        visible[sl, frames[:8]] = True
        contained[sl, sl] = True
        if b > 0:
            bridge = 5 * b
            prev = slice(5 * (b - 1), 5 * b)
            visible[bridge, visible[prev].any(axis=0)] = True
            contained[bridge, prev] = True
            contained[prev, bridge] = True
    schedule = [6.0, 4.0, 2.0, 1.0]

    ref_parts = _reference_partition(visible, contained, schedule, 0.7)
    repo_parts = _repo_partition(visible, contained, schedule, 0.7)
    assert repo_parts == ref_parts


# ---------------------------------------------------------------- graph stats

def _synth_mask_scene(rng, n_points, n_frames, max_masks=5):
    """Reference-convention point-in-mask inputs with genuine overlaps.

    Replays build_point_in_mask_matrix's zeroing semantics (reference
    graph/construction.py:55-64): points hit by >= 2 masks of one frame
    become that frame's boundary (matrix entry zeroed, point added to the
    GLOBAL boundary set), while mask_point_clouds keeps the full original
    point sets — process_one_mask subtracts the global boundary itself."""
    point_in_mask = np.zeros((n_points, n_frames), dtype=np.uint16)
    boundary = set()
    mask_point_clouds = {}
    frame_list = [f"{j:05d}" for j in range(n_frames)]
    global_list = []
    for j in range(n_frames):
        appeared: set = set()
        frame_boundary: set = set()
        for mid in range(1, int(rng.integers(1, max_masks + 1)) + 1):
            size = int(rng.integers(8, max(9, n_points // 6)))
            pts = {int(p) for p in rng.choice(n_points, size=size, replace=False)}
            frame_boundary |= pts & appeared
            mask_point_clouds[f"{frame_list[j]}_{mid}"] = set(pts)
            point_in_mask[list(pts), j] = mid
            appeared |= pts
            global_list.append((frame_list[j], mid))
        point_in_mask[list(frame_boundary), j] = 0
        boundary |= frame_boundary
    return frame_list, global_list, point_in_mask, boundary, mask_point_clouds


def _reference_process_masks(frame_list, global_list, point_in_mask, boundary,
                             mask_point_clouds):
    ref_con = _import_reference_construction()
    args = types.SimpleNamespace(debug=False, mask_visible_threshold=0.3,
                                 contained_threshold=0.8,
                                 undersegment_filter_threshold=0.3)
    with _no_cuda():
        visible, contained, under = ref_con.process_masks(
            frame_list, list(global_list), point_in_mask, set(boundary),
            mask_point_clouds, args)
    return (visible.numpy().astype(bool), contained.numpy().astype(bool),
            sorted(under))


def _repo_graph_stats(frame_list, global_list, point_in_mask, boundary,
                      k_max=8):
    import jax.numpy as jnp

    from maskclustering_tpu.models.graph import compute_graph_stats

    n_points, n_frames = point_in_mask.shape
    m = len(global_list)
    frame_index = {fid: j for j, fid in enumerate(frame_list)}
    # compute_graph_stats requires columns sorted by (frame, id); the
    # reference's global list is built frame-major with ascending local ids,
    # so the orders coincide — assert rather than remap
    keys = [(frame_index[fid], mid) for fid, mid in global_list]
    assert keys == sorted(keys)
    # pad with the production sentinels (build_mask_table: frame=F, id=-1 —
    # an id no point can carry, so padding columns of c are exactly zero)
    m_pad = -(-m // 8) * 8
    mask_frame = np.full(m_pad, n_frames, dtype=np.int32)
    mask_id = np.full(m_pad, -1, dtype=np.int32)
    mask_frame[:m] = [k[0] for k in keys]
    mask_id[:m] = [k[1] for k in keys]
    mask_active = np.zeros(m_pad, dtype=bool)
    mask_active[:m] = True
    bnd = np.zeros(n_points, dtype=bool)
    bnd[list(boundary)] = True
    stats = compute_graph_stats(
        jnp.asarray(point_in_mask.T.astype(np.int32)), jnp.asarray(bnd),
        jnp.asarray(mask_frame), jnp.asarray(mask_id),
        jnp.asarray(mask_active), k_max=k_max, point_chunk=1024)
    visible = np.asarray(stats.visible)
    contained = np.asarray(stats.contained)
    under = np.asarray(stats.undersegment)
    # padding columns/rows must stay inert
    assert not visible[m:].any() and not contained[m:].any() \
        and not contained[:, m:].any() and not under[m:].any()
    return visible[:m], contained[:m, :m], sorted(np.flatnonzero(under[:m]).tolist())


@pytest.mark.parametrize("seed,n_points,n_frames", [
    (5, 1500, 12), (23, 3000, 30), (41, 800, 6),
])
def test_graph_stats_match_reference_process_masks(seed, n_points, n_frames):
    """compute_graph_stats (models/graph.py) vs the literal reference
    process_masks (graph/construction.py:103-171) on shared point-in-mask
    tensors: identical visible/contained matrices (post undersegment-undo)
    and identical undersegment verdicts, including the boundary-point
    subtraction and the lowest-id argmax tie-break."""
    rng = np.random.default_rng(seed)
    scene = _synth_mask_scene(rng, n_points, n_frames)
    ref_vis, ref_con_m, ref_under = _reference_process_masks(*scene)
    our_vis, our_con, our_under = _repo_graph_stats(*scene[:4])
    assert our_under == ref_under
    np.testing.assert_array_equal(our_vis, ref_vis)
    np.testing.assert_array_equal(our_con, ref_con_m)


def test_graph_stats_big_mask_and_all_boundary_edges():
    """Two crafted edge cases through both implementations: the >= 500
    visible-point override (reference process_one_mask's `< 500` clause
    admits a big mask whose visible ratio is below the threshold) and a
    fully-boundary mask pair (zero valid points -> undersegmented)."""
    n_points, n_frames = 6000, 4
    point_in_mask = np.zeros((n_points, n_frames), dtype=np.uint16)
    frame_list = [f"{j:05d}" for j in range(n_frames)]
    # frame 0: one giant mask (2000 pts)
    point_in_mask[:2000, 0] = 1
    # frame 1: covers 550 of the giant mask's points: ratio 0.275 < 0.3 but
    # 550 >= 500 -> visible via the big-mask clause
    point_in_mask[:550, 1] = 1
    # frame 2: two identical masks -> every point is frame-2 boundary
    dup = set(range(2500, 2600))
    point_in_mask[2500:2600, 2] = 0  # zeroed by the boundary rule
    # frame 3: a clean small mask, disjoint from the boundary points
    point_in_mask[3000:3200, 3] = 1
    boundary = set(dup)
    mask_point_clouds = {
        "00000_1": set(range(2000)),
        "00001_1": set(range(550)),
        "00002_1": set(dup),
        "00002_2": set(dup),
        "00003_1": set(range(3000, 3200)),
    }
    global_list = [("00000", 1), ("00001", 1), ("00002", 1), ("00002", 2),
                   ("00003", 1)]
    scene = (frame_list, global_list, point_in_mask, boundary,
             mask_point_clouds)
    ref_vis, ref_con_m, ref_under = _reference_process_masks(*scene)
    our_vis, our_con, our_under = _repo_graph_stats(*scene[:4])
    assert our_under == ref_under
    assert 2 in ref_under and 3 in ref_under  # the all-boundary pair
    assert ref_vis[0, 1]  # the big-mask clause fired in the reference...
    assert our_vis[0, 1]  # ...and in the repo path
    np.testing.assert_array_equal(our_vis, ref_vis)
    np.testing.assert_array_equal(our_con, ref_con_m)
