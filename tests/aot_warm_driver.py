"""Subprocess driver for the cross-process AOT warm-start unit
(tests/test_aot_cache.py): run ONE tiny scene's device+host phases with
the retrace sanitizer + AOT cache armed, print one JSON digest line.

Usage: python tests/aot_warm_driver.py AOT_DIR XLA_DIR DATA_ROOT
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    aot_dir, xla_dir, data_root = sys.argv[1:4]
    from maskclustering_tpu.analysis import retrace_sanitizer

    retrace_sanitizer.install()
    from maskclustering_tpu.config import load_config
    from maskclustering_tpu.models.pipeline import (run_scene_device,
                                                    run_scene_host)
    from maskclustering_tpu.utils import aot_cache
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    cfg = load_config("scannet").replace(
        data_root=data_root, config_name="aotwarm", step=1,
        distance_threshold=0.05, mask_pad_multiple=32,
        aot_cache_dir=aot_dir, compilation_cache_dir=xla_dir)
    warm = aot_cache.warm_start(cfg)
    t = to_scene_tensors(make_scene(num_boxes=3, num_frames=6,
                                    image_hw=(48, 64), spacing=0.08,
                                    seed=11))
    handoff = run_scene_device(t, cfg, seq_name="aot-probe")
    result = run_scene_host(handoff, cfg, export=False)
    d = retrace_sanitizer.digest()
    print(json.dumps({
        "warm": warm,
        "compiles": d["compiles"],
        "raw_compiles": d["raw_compiles"],
        "cache_hits": d["cache_hits"],
        "aot_restores": d["aot_restores"],
        "violations": len(d["violations"]),
        "num_objects": len(result.objects.point_ids_list),
        "assignment_sum": int(result.assignment.sum()),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
