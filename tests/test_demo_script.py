"""Smoke test for the zero-download demo golden path (scripts/demo.py).

The reference's equivalent is demo.sh (clustering + visualization on a
downloaded scene); ours generates the scene, so the whole path — layout
write, seven-step orchestrator, artifact fan-out, AP print — must work in
one subprocess command with no inputs.
"""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO_ROOT, "scripts", "demo.py")


def test_demo_end_to_end(tmp_path):
    proc = subprocess.run(
        [sys.executable, DEMO, "--platform", "cpu", "--out", str(tmp_path),
         "--frames", "12", "--objects", "3", "--image-h", "120",
         "--image-w", "160"],
        capture_output=True, text=True, timeout=420, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
    assert "3 objects recovered (planted: 3)" in proc.stdout
    assert "MISSING" not in proc.stdout
    # every step ran without a FAILED marker
    assert "FAILED" not in proc.stdout
    # the resume path: a second invocation reuses the scene and artifacts
    proc2 = subprocess.run(
        [sys.executable, DEMO, "--platform", "cpu", "--out", str(tmp_path),
         "--frames", "12", "--objects", "3", "--image-h", "120",
         "--image-w", "160"],
        capture_output=True, text=True, timeout=180, cwd=REPO_ROOT)
    assert proc2.returncode == 0
    assert "reusing generated scene" in proc2.stdout

    # parameter mismatch on an existing scene dir is refused loudly, not
    # silently evaluated against the stale GT
    proc3 = subprocess.run(
        [sys.executable, DEMO, "--platform", "cpu", "--out", str(tmp_path),
         "--frames", "12", "--objects", "5", "--image-h", "120",
         "--image-w", "160"],
        capture_output=True, text=True, timeout=180, cwd=REPO_ROOT)
    assert proc3.returncode == 2
    assert "pick a different --out" in proc3.stderr
