"""Cost-observatory contract tests (obs/cost.py + sharded stage hooks).

Two layers:

- pure HLO-text parsing units (no jax) — shape-byte arithmetic and the
  collective/op censuses over canned module text;
- real AOT compiles on the 8-virtual-CPU-device mesh (conftest) across the
  scene/frame divisor lattice of 8 — pinning the VERDICT Weak #5 claim as
  a test: frame-sharded configs compile to a non-empty collective census,
  pure scene-DP compiles to zero DATA collectives (the only cross-scene
  traffic is O(1)-byte while-loop predicates).
"""

import json

import pytest

from maskclustering_tpu.obs.cost import (
    collective_census,
    compare_dtypes,
    dot_census,
    dot_operand_bytes,
    ici_bytes,
    observe_costs,
    op_census,
    shape_bytes,
)

# ---------------------------------------------------------------------------
# HLO text parsing (no jax)
# ---------------------------------------------------------------------------


def test_shape_bytes_plain_scalar_tuple():
    assert shape_bytes("f32[64,8]{0,1}") == 64 * 8 * 4
    assert shape_bytes("pred[]") == 1
    assert shape_bytes("u16[480,640]{1,0}") == 480 * 640 * 2
    assert shape_bytes("(f32[8,2]{1,0}, u8[4]{0})") == 8 * 2 * 4 + 4
    assert shape_bytes("bf16[128]") == 256
    # unknown primitive types contribute 0, never raise
    assert shape_bytes("mystery9[10]") == 0


_CANNED_HLO = """\
HloModule canned, is_scheduled=true

%fused_computation (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %t = f32[8]{0} transpose(f32[8]{0} %p0), dimensions={0}
}

ENTRY %main (a: f32[64,2]) -> f32[8] {
  %a = f32[64,2]{1,0} parameter(0)
  %ag = f32[64,8]{0,1} all-gather(f32[64,2]{0,1} %a), channel_id=1
  %cp = f32[64,8]{1,0} copy(f32[64,8]{0,1} %ag)
  %ags = f32[64,16]{0,1} all-gather-start(f32[64,2]{0,1} %a), channel_id=3
  %agd = f32[64,16]{0,1} all-gather-done(f32[64,16]{0,1} %ags)
  %cps = (f32[1024]{0}, f32[1024]{0}, u32[], u32[]) collective-permute-start(f32[1024]{0} %a), channel_id=4
  %cpd = f32[1024]{0} collective-permute-done((f32[1024]{0}, f32[1024]{0}, u32[], u32[]) %cps)
  %f = f32[8]{0} fusion(f32[8]{0} %a2), kind=kLoop, calls=%fused_computation
  ROOT %ar = pred[] all-reduce(pred[] %x), channel_id=2
}
"""


def test_collective_census_counts_and_bytes():
    census = collective_census(_CANNED_HLO)
    # -start counted once, -done never (that would double-count)
    assert census["all-gather"]["count"] == 2
    assert census["all-gather"]["bytes"] == 64 * 8 * 4 + 64 * 16 * 4
    assert census["all-reduce"] == {"count": 1, "bytes": 1.0}
    # an async start's tuple aliases operand AND result buffers (plus u32
    # context scalars): payload is the LARGEST element, never the tuple sum
    assert census["collective-permute"] == {"count": 1, "bytes": 1024 * 4}
    assert "reduce-scatter" not in census
    assert ici_bytes(census) == 64 * 8 * 4 + 64 * 16 * 4 + 1 + 1024 * 4


def test_op_census_counts():
    ops = op_census(_CANNED_HLO)
    assert ops["fusion"] == 1
    assert ops["copy"] == 1
    assert ops["transpose"] == 1


_CANNED_STABLEHLO = """\
module @jit_fn {
  func.func public @main(%arg0: tensor<8x16xi8>) -> tensor<8x8xi32> {
    %0 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0], \
precision = [DEFAULT, DEFAULT] : (tensor<8x16xi8>, tensor<16x8xi8>) -> tensor<8x8xi32>
    %1 = stablehlo.dot_general %a, %b, batching_dims = [0] x [0], \
contracting_dims = [2] x [1] : (tensor<4x6x5xbf16>, tensor<4x5x7xbf16>) -> tensor<4x6x7xf32>
    %2 = stablehlo.dot_general %c, %d, contracting_dims = [1] x [0] : \
(tensor<8x3xf32>, tensor<3x3xf32>) -> tensor<8x3xf32>
    %3 = stablehlo.dot_general %c, %d, contracting_dims = [1] x [0] : \
(tensor<8x3xf32>, tensor<3x3xf32>) -> tensor<8x3xf32>
  }
}
"""


def test_dot_census_classes_and_bytes():
    census = dot_census(_CANNED_STABLEHLO)
    assert census["i8xi8->i32"] == {"count": 1,
                                    "operand_bytes": 8 * 16 + 16 * 8}
    assert census["bf16xbf16->f32"] == {
        "count": 1, "operand_bytes": (4 * 6 * 5 + 4 * 5 * 7) * 2.0}
    assert census["f32xf32->f32"]["count"] == 2
    assert dot_operand_bytes(census) == (
        8 * 16 + 16 * 8 + (4 * 6 * 5 + 4 * 5 * 7) * 2.0
        + 2 * (8 * 3 + 3 * 3) * 4.0)


# ---------------------------------------------------------------------------
# real AOT compiles on the 8-virtual-device CPU mesh
# ---------------------------------------------------------------------------

_TINY = dict(frames=8, points=512, image_hw=(16, 24), k_max=7)

# the full divisor lattice of 8: every (scene, frame) factorization,
# plus the canonical point-sharded (scene, frame, point) cell
_LATTICE = [(1, 8), (2, 4), (4, 2), (8, 1), (1, 2, 4)]


@pytest.fixture()
def lattice_rows(fused_lattice_aot):
    """One fused-step census per lattice mesh — the SESSION-scoped conftest
    sweep (shared with test_analysis's IR gate, which reads the same
    lowerings' texts; compiles are the expensive part and now happen once
    per tier-1 run, at the analyzer's canonical shape)."""
    return fused_lattice_aot


def test_lattice_covers_all_meshes(lattice_rows):
    assert set(lattice_rows) == set(_LATTICE)
    for row in lattice_rows.values():
        assert "error" not in row, row


def test_frame_sharded_census_non_empty(lattice_rows):
    """Any mesh with a frame axis > 1 must show real ICI traffic: the
    consensus matmuls all-gather their row shards."""
    for mesh in ((1, 8), (2, 4), (4, 2)):
        row = lattice_rows[mesh]
        census = row["collectives"]
        assert census, f"mesh {mesh}: empty collective census"
        assert census.get("all-gather", {}).get("count", 0) > 0, \
            f"mesh {mesh}: no all-gather in a frame-sharded compile"
        # payload must be real data, not just control scalars
        assert row["ici_bytes"] > 1024, f"mesh {mesh}: {row['ici_bytes']}"


def test_pure_scene_dp_has_no_data_collectives(lattice_rows):
    """VERDICT Weak #5 as a test: scene data-parallelism compiles to no
    cross-scene DATA movement. XLA still emits O(1)-byte pred[] all-reduces
    for while-loop termination agreement — bounded here so a future graph
    change that introduces real cross-scene traffic fails loudly."""
    row = lattice_rows[(8, 1)]
    census = row["collectives"]
    for op in ("all-gather", "reduce-scatter", "collective-permute",
               "all-to-all"):
        assert op not in census, f"scene-DP compile grew a {op}"
    # while-predicate all-reduces only: a handful of scalar bytes
    assert row["ici_bytes"] <= 64, row["ici_bytes"]


def test_stage_rows_roofline_fields_and_post_claims_census():
    """tier-1 smoke: every stage row carries rooflines + censuses, and the
    post.claims kernel (postprocess) has a static fusion census with zero
    collectives — the kernel-vs-tunnel question's static half."""
    rows = observe_costs([(1, 8)], **_TINY)
    assert [r["stage"] for r in rows] == [
        "backprojection", "graph", "clustering", "postprocess", "fused"]
    for row in rows:
        assert "error" not in row, row
        assert row["flops"] and row["flops"] > 0
        assert row["hbm_bytes"] and row["hbm_bytes"] > 0
        assert row["peak_bytes"] is not None
        assert row["ops"]["fusion"] > 0
        json.dumps(row)  # every row must be JSON-able (the event contract)
    post = rows[3]
    assert post["collectives"] == {}  # per-scene kernel: no ICI story
    assert post["ops"]["fusion"] > 0
    # the fused program must see the ICI the stage compiles predict
    assert rows[4]["ici_bytes"] > 0


def test_report_cost_renders_from_events(tmp_path, capsys):
    """cost events round-trip through the sink into `report --cost`."""
    from maskclustering_tpu.obs.events import EventSink
    from maskclustering_tpu.obs.report import main

    path = str(tmp_path / "cost_events.jsonl")
    sink = EventSink(path)
    rows = observe_costs([(1, 8)], stages=("graph",), sink=sink, **_TINY)
    sink.close()
    assert rows and "error" not in rows[0]
    assert main([path, "--cost"]) == 0
    out = capsys.readouterr().out
    assert "cost observatory" in out
    assert "mesh scene=1 x frame=8" in out
    assert "graph" in out and "ici" in out
    assert "v5e" in out


def test_compare_dtypes_halves_counting_operand_bytes(tmp_path, capsys):
    """The dtype census A/B: on the clustering stage (all of whose dots are
    counting contractions) the int8 variant must show exactly the bf16
    classes replaced by i8xi8->i32 at HALF the operand bytes, with the
    render carrying the ratio and the int16-plane line."""
    from maskclustering_tpu.obs.cost import claim_plane_bytes
    from maskclustering_tpu.obs.report import render_dtype_compare

    rows_by, diffs = compare_dtypes([(1, 8)], stages=("clustering",), **_TINY)
    assert len(diffs) == 1
    d = diffs[0]
    assert set(d["narrowed_bf16"]) == {"bf16xbf16->f32"}
    assert set(d["narrowed_int8"]) == {"i8xi8->i32"}
    assert d["narrowed_int8"]["i8xi8->i32"]["count"] == \
        d["narrowed_bf16"]["bf16xbf16->f32"]["count"]
    assert d["operand_byte_ratio"] == pytest.approx(2.0)
    assert d["narrowed_bytes_bf16"] == 2 * d["narrowed_bytes_int8"]
    json.dumps(diffs)  # diff rows must be JSON-able
    out = render_dtype_compare(
        diffs, planes=claim_plane_bytes(_TINY["frames"], _TINY["points"]))
    assert "2.00x" in out
    assert "claim planes" in out and "halved" in out
    # claim-plane arithmetic: 2 planes x F x N x bytes/el
    planes = claim_plane_bytes(8, 512)
    assert planes["int16"] == 2 * 8 * 512 * 2
    assert planes["int32_historical"] == 2 * planes["int16"]


def test_mesh_that_does_not_fit_is_skipped():
    rows = observe_costs([(3, 5)], stages=("graph",), **_TINY)
    assert rows == []  # 15 devices never fit the 8-device backend


def test_render_cost_survives_error_rows():
    """A stage that failed to compile renders as one ERROR row — it must
    not crash the table that carries the successful stages."""
    from maskclustering_tpu.obs.report import render_cost

    rows = [
        {"stage": "graph", "mesh": [1, 8], "flops": 1e9, "hbm_bytes": 1e6,
         "peak_bytes": 2e6, "ici_bytes": 512.0,
         "collectives": {"all-gather": {"count": 2, "bytes": 512.0}},
         "ops": {"fusion": 3, "copy": 1, "transpose": 0},
         "out_bytes": 100.0, "compile_s": 0.1,
         "fingerprint": {"frames": 8, "points": 512, "k_max": 7}},
        {"stage": "clustering", "mesh": [1, 8],
         "error": "XlaRuntimeError: boom",
         "fingerprint": {"frames": 8, "points": 512, "k_max": 7}},
    ]
    out = render_cost(rows)
    assert "graph" in out and "ERROR" in out and "clustering" in out
