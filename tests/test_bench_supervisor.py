"""End-to-end contract tests for the bench.py supervisor/worker pair.

BENCH_r04 was lost to a single backend-init timeout; these pin the
hardening: exactly one JSON line on stdout in every outcome, attempt
accounting, retry-then-give-up on init failures, and exit codes that shell
callers (deploy/setup_tpu_vm.sh under set -e) can trust.
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench.py")

TINY = ["--frames", "6", "--points", "2048", "--boxes", "3",
        "--image-h", "48", "--image-w", "64", "--repeats", "2",
        "--spacing", "0.08"]


def _run(argv, timeout=420):
    env = dict(os.environ, MCT_BENCH_BACKOFF_SCALE="0.05")  # fast retries
    env.pop("MCT_BENCH_SUPERVISED", None)  # never inherit supervisor mode
    return subprocess.run([sys.executable, BENCH] + argv, env=env,
                          capture_output=True, timeout=timeout, cwd=REPO_ROOT)


def test_supervisor_success_emits_one_json_line():
    proc = _run(["--platform", "cpu"] + TINY)
    out_lines = proc.stdout.decode().strip().splitlines()
    assert proc.returncode == 0, proc.stderr[-800:]
    assert len(out_lines) == 1, out_lines  # the whole stdout contract
    d = json.loads(out_lines[0])
    assert d["value"] is not None
    assert d["attempts"] == 1
    assert len(d["runs"]) == 2
    assert "spread_pct" in d and "stages" in d
    assert "INIT_OK" not in proc.stdout.decode()


def test_supervisor_retries_init_failure_then_gives_up():
    proc = _run(["--platform", "nosuch", "--init-attempts", "2",
                 "--retry-budget", "60"], timeout=180)
    out_lines = proc.stdout.decode().strip().splitlines()
    assert proc.returncode == 2  # worker's init-failure class preserved
    assert len(out_lines) == 1
    d = json.loads(out_lines[0])
    assert d["value"] is None
    assert d["attempts"] == 2
    assert "backend init failed" in d["error"]
    # the supervisor narrated both attempts on stderr
    assert proc.stderr.decode().count("attempt ") == 2


def test_direct_worker_keeps_one_line_contract():
    proc = _run(["--worker", "--platform", "cpu"] + TINY)
    out_lines = proc.stdout.decode().strip().splitlines()
    assert proc.returncode == 0
    assert len(out_lines) == 1
    d = json.loads(out_lines[0])
    assert d["value"] is not None
    assert "attempts" not in d  # supervisor-only annotation


def test_supervisor_retries_post_init_hang(tmp_path):
    # init succeeds, then the worker wedges before producing any JSON (the
    # chip-wedge mode PROFILE.md round 5 observed: devices() answers in
    # seconds, the first device op stalls). The supervisor must kill the
    # worker at --worker-timeout and retry; the flag file makes the second
    # worker healthy, so the final line is a real result with attempts=2.
    env = dict(os.environ, MCT_BENCH_BACKOFF_SCALE="0.05",
               MCT_BENCH_TEST_HANG_AFTER_INIT=str(tmp_path / "hung-once"))
    env.pop("MCT_BENCH_SUPERVISED", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--platform", "cpu", "--worker-timeout", "30",
         "--init-timeout", "60"] + TINY,
        env=env, capture_output=True, timeout=420, cwd=REPO_ROOT)
    out_lines = proc.stdout.decode().strip().splitlines()
    assert proc.returncode == 0, proc.stderr[-800:]
    assert len(out_lines) == 1, out_lines
    d = json.loads(out_lines[0])
    assert d["value"] is not None
    assert d["attempts"] == 2
    assert "post-init run allowance" in proc.stderr.decode()


def test_supervisor_sigterm_still_emits_json_line(tmp_path):
    # An external kill (driver-side timeout) mid-supervision must degrade
    # to a value=null JSON line, not to an empty stdout: the hang knob
    # wedges the first worker post-init, and SIGTERM arrives while the
    # supervisor is waiting out --worker-timeout.
    import signal
    import time

    env = dict(os.environ, MCT_BENCH_BACKOFF_SCALE="0.05",
               MCT_BENCH_TEST_HANG_AFTER_INIT=str(tmp_path / "hung-once"))
    env.pop("MCT_BENCH_SUPERVISED", None)
    proc = subprocess.Popen(
        [sys.executable, BENCH, "--platform", "cpu", "--worker-timeout",
         "300", "--init-timeout", "120"] + TINY,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=REPO_ROOT)
    # wait for the hang flag: proves the first worker is past init and the
    # supervisor is in its long post-init wait
    deadline = time.time() + 180
    while time.time() < deadline and not (tmp_path / "hung-once").exists():
        time.sleep(0.5)
    assert (tmp_path / "hung-once").exists(), "worker never reached the hang"
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    out_lines = out.decode().strip().splitlines()
    assert proc.returncode == 3
    assert len(out_lines) == 1, out_lines
    d = json.loads(out_lines[0])
    assert d["value"] is None
    assert "signal" in d["error"]
    assert d["attempts"] == 1
