import os

import numpy as np
import pytest
from PIL import Image

from maskclustering_tpu.datasets import get_dataset
from maskclustering_tpu.io.ply import write_ply_points


def _write_png16(path, arr):
    Image.fromarray(arr.astype(np.uint16)).save(path)


def _make_scannet_scene(root, seq="scene0000_00", n_frames=2, hw=(480, 640)):
    h, w = hw
    base = os.path.join(root, "scannet", "processed", seq)
    for d in ("color", "depth", "pose", "intrinsic", "output/mask"):
        os.makedirs(os.path.join(base, d), exist_ok=True)
    rng = np.random.default_rng(0)
    np.savetxt(os.path.join(base, "intrinsic", "intrinsic_depth.txt"),
               np.array([[500.0, 0, 320, 0], [0, 500, 240, 0], [0, 0, 1, 0], [0, 0, 0, 1]]))
    for i in range(0, n_frames * 10, 10):
        Image.new("RGB", (w, h)).save(os.path.join(base, "color", f"{i}.jpg"))
        _write_png16(os.path.join(base, "depth", f"{i}.png"),
                     rng.integers(500, 3000, size=(h, w)))
        np.savetxt(os.path.join(base, "pose", f"{i}.txt"), np.eye(4))
        Image.fromarray(rng.integers(0, 5, size=(h, w)).astype(np.uint8)).save(
            os.path.join(base, "output", "mask", f"{i}.png"))
    write_ply_points(os.path.join(base, f"{seq}_vh_clean_2.ply"),
                     rng.normal(size=(50, 3)).astype(np.float32))
    return seq


def test_scannet_loader(tmp_path):
    root = str(tmp_path)
    seq = _make_scannet_scene(root)
    ds = get_dataset("scannet", seq, data_root=root)
    frames = ds.get_frame_list(10)
    assert frames == [0, 10]
    k = ds.get_intrinsics(0)
    assert k.shape == (3, 3) and k[0, 0] == 500
    assert ds.get_extrinsic(0).shape == (4, 4)
    d = ds.get_depth(0)
    assert d.shape == (480, 640) and d.dtype == np.float32 and 0.4 < d.mean() < 3.5
    seg = ds.get_segmentation(0, align_with_depth=True)
    assert seg.shape == (480, 640)
    assert ds.get_scene_points().shape == (50, 3)
    tensors = ds.load_scene_tensors(stride=10)
    assert tensors.num_frames == 2
    assert tensors.frame_valid.all()


def test_scannet_invalid_pose_marked(tmp_path):
    root = str(tmp_path)
    seq = _make_scannet_scene(root)
    bad = np.eye(4)
    bad[0, 0] = np.inf
    np.savetxt(os.path.join(root, "scannet", "processed", seq, "pose", "10.txt"), bad)
    ds = get_dataset("scannet", seq, data_root=root)
    tensors = ds.load_scene_tensors(stride=10)
    np.testing.assert_array_equal(tensors.frame_valid, [True, False])


def test_matterport_conf_parsing(tmp_path):
    root = str(tmp_path)
    seq = "17DRP5sb8fy"
    base = os.path.join(root, "matterport3d", "scans", seq, seq)
    os.makedirs(os.path.join(base, "undistorted_camera_parameters"))
    os.makedirs(os.path.join(base, "undistorted_depth_images"))
    os.makedirs(os.path.join(base, "house_segmentations"))
    ext = np.eye(4)
    ext_line = " ".join(str(float(x)) for x in ext.flatten())
    with open(os.path.join(base, "undistorted_camera_parameters", f"{seq}.conf"), "w") as f:
        f.write("dataset matterport\n")
        f.write("intrinsics_matrix 1000 0 640  0 1000 512  0 0 1\n")
        f.write(f"scan d0.png c0.jpg {ext_line}\n")
        f.write(f"scan d1.png c1.jpg {ext_line}\n")
    rng = np.random.default_rng(1)
    for name in ("d0.png", "d1.png"):
        _write_png16(os.path.join(base, "undistorted_depth_images", name),
                     rng.integers(2000, 8000, size=(32, 40)))
    write_ply_points(os.path.join(base, "house_segmentations", f"{seq}.ply"),
                     rng.normal(size=(30, 3)).astype(np.float32))

    ds = get_dataset("matterport3d", seq, data_root=root)
    assert ds.get_frame_list(1) == [0, 1]
    k = ds.get_intrinsics(0)
    assert k[0, 0] == 1000 and k[1, 2] == 512
    e = ds.get_extrinsic(0)
    # GL->CV flip: columns 1,2 of the identity rotation are negated
    np.testing.assert_allclose(e[:3, 1], [0, -1, 0])
    np.testing.assert_allclose(e[:3, 2], [0, 0, -1])
    d = ds.get_depth(0)
    assert d.shape == (32, 40)
    # 0.25mm per unit scale
    assert 0.4 < d.mean() < 2.1
    assert ds.get_scene_points().shape == (30, 3)


def test_scannetpp_colmap_parsing(tmp_path):
    import torch

    root = str(tmp_path)
    seq = "abc123"
    base = os.path.join(root, "scannetpp", "data", seq)
    colmap = os.path.join(base, "iphone", "colmap")
    os.makedirs(colmap)
    os.makedirs(os.path.join(base, "iphone", "render_depth"))
    os.makedirs(os.path.join(root, "scannetpp", "pcld_0.25"))
    with open(os.path.join(colmap, "cameras.txt"), "w") as f:
        f.write("# cameras\n1 PINHOLE 1920 1440 1500 1500 960 720\n")
    # identity quaternion, translation (1,2,3): w2c -> c2w has t = -(1,2,3)
    with open(os.path.join(colmap, "images.txt"), "w") as f:
        f.write("# images\n")
        f.write("1 1 0 0 0 1 2 3 1 frame_000000.jpg\n")
        f.write("0.0 0.0 -1\n")
        f.write("2 0.7071067811865476 0 0.7071067811865476 0 0 0 0 1 frame_000010.jpg\n")
        f.write("\n")
    rng = np.random.default_rng(2)
    for i in (0, 10):
        _write_png16(os.path.join(base, "iphone", "render_depth", f"frame_{i:06d}.png"),
                     rng.integers(500, 3000, size=(24, 32)))
    torch.save({"sampled_coords": rng.normal(size=(40, 3))},
               os.path.join(root, "scannetpp", "pcld_0.25", f"{seq}.pth"))

    ds = get_dataset("scannetpp", seq, data_root=root)
    assert ds.get_frame_list(1) == [0, 10]
    assert ds.get_frame_list(2) == [0]
    k = ds.get_intrinsics(0)
    assert k[0, 0] == 1500 and k[0, 2] == 960
    e0 = ds.get_extrinsic(0)
    np.testing.assert_allclose(e0[:3, 3], [-1, -2, -3], atol=1e-12)
    e1 = ds.get_extrinsic(10)
    # 90-degree rotation about y
    np.testing.assert_allclose(e1[:3, :3] @ e1[:3, :3].T, np.eye(3), atol=1e-12)
    assert ds.get_depth(0).shape == (24, 32)
    assert ds.get_scene_points().shape == (40, 3)


def test_tasmap_string_frame_ids(tmp_path):
    root = str(tmp_path)
    seq = "task1"
    base = os.path.join(root, "tasmap", "processed", seq)
    for d in ("color", "depth", "pose", "intrinsic"):
        os.makedirs(os.path.join(base, d))
    for fid in ("3", "12", "101"):
        Image.new("RGB", (8, 8)).save(os.path.join(base, "color", f"{fid}.jpg"))
    ds = get_dataset("tasmap", seq, data_root=root)
    assert ds.get_frame_list(1) == ["3", "12", "101"]
    assert ds.get_frame_list(2) == ["3", "101"]
    assert ds.image_size == (1024, 1024)


def test_unknown_dataset():
    with pytest.raises(KeyError):
        get_dataset("nope", "seq")
