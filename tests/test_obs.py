"""Contract tests for the obs subsystem (tracer, metrics, events, report).

Pins the properties the retrofit depends on: disabled mode is a true
no-op (no events, NO device syncs), spans nest and carry attrs, the JSONL
schema round-trips (including torn-final-line crash tolerance), counters
aggregate per process, and the report CLI renders/diffs captures.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.obs.metrics import Histogram, Registry


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Every test starts disarmed with an empty registry and ends the same,
    so obs state never leaks between tests (the tracer/registry are
    process-global by design)."""
    obs.disable()
    obs.registry().reset()
    yield
    obs.disable()
    obs.registry().reset()


class _SyncProbe:
    """Pytree leaf that records block_until_ready calls (jax protocol)."""

    def __init__(self):
        self.calls = 0

    def block_until_ready(self):
        self.calls += 1
        return self


# ---------------------------------------------------------------------------
# no-op (disarmed) mode
# ---------------------------------------------------------------------------


def test_noop_mode_is_null_tracer_singleton():
    assert obs.get_tracer() is obs.NULL_TRACER
    assert not obs.enabled()
    assert obs.events_path() is None


def test_noop_mode_emits_nothing_and_never_syncs(tmp_path):
    probe = _SyncProbe()
    with obs.span("stage", scene="s0") as sp:
        out = sp.sync(probe)
    assert out is probe
    assert probe.calls == 0, "disabled obs must not add device syncs"
    # shared null span: no per-call allocation
    assert obs.span("a") is obs.span("b")
    obs.record_span("x", 1.0)
    obs.flush_metrics()
    assert list(tmp_path.iterdir()) == []  # nothing ever written anywhere


def test_scene_tracer_times_without_emitting():
    """run_scene's fallback: spans measure wall time but fence/emit nothing."""
    tracer = obs.scene_tracer()
    assert tracer.enabled and not tracer.fence
    probe = _SyncProbe()
    with tracer.span("stage") as sp:
        time.sleep(0.01)
        sp.sync(probe)
    assert sp.duration >= 0.01
    assert probe.calls == 0  # timing-only tracer never fences
    assert obs.registry().snapshot()["histograms"] == {}  # and never aggregates


# ---------------------------------------------------------------------------
# armed mode: spans, nesting, fencing, events
# ---------------------------------------------------------------------------


def test_span_nesting_timing_attrs_and_fencing(tmp_path):
    path = str(tmp_path / "events.jsonl")
    obs.configure(path, fence=True, sample_memory=False,
                  meta={"tool": "test"})
    assert obs.enabled() and obs.events_path() == path
    probe = _SyncProbe()
    with obs.span("outer", scene="s1", n_pad=2048) as outer:
        with obs.span("inner") as inner:
            time.sleep(0.012)
            inner.set(k_max=63)
            inner.sync(probe)
    obs.record_span("post.claims", 0.25, parent="postprocess")
    obs.disable()

    assert probe.calls == 1, "armed fencing must block_until_ready"
    events = list(obs.read_events(path))
    metas = [e for e in events if e["kind"] == "meta"]
    assert metas and metas[0]["tool"] == "test"
    spans = {e["name"]: e for e in events if e["kind"] == "span"}
    assert set(spans) == {"outer", "inner", "post.claims"}
    assert spans["inner"]["parent"] == "outer"
    assert spans["inner"]["depth"] == 1
    assert spans["inner"]["attrs"] == {"k_max": 63}
    assert spans["inner"]["dur_s"] >= 0.012
    assert spans["outer"]["depth"] == 0
    assert spans["outer"]["attrs"] == {"scene": "s1", "n_pad": 2048}
    assert spans["outer"]["dur_s"] >= spans["inner"]["dur_s"]
    assert spans["post.claims"]["dur_s"] == 0.25
    assert spans["post.claims"]["parent"] == "postprocess"
    # every event carries the schema envelope
    for e in events:
        assert e["v"] == obs.SCHEMA_VERSION
        assert {"kind", "ts", "pid"} <= set(e)


def test_traced_decorator_and_exception_attr(tmp_path):
    path = str(tmp_path / "events.jsonl")
    obs.configure(path, sample_memory=False)

    @obs.traced("work", tag="deco")
    def work(x):
        return x * 2

    assert work(21) == 42
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    obs.disable()
    spans = {e["name"]: e for e in obs.read_events(path) if e["kind"] == "span"}
    assert spans["work"]["attrs"] == {"tag": "deco"}
    assert spans["boom"]["attrs"]["error"] == "ValueError"


def test_jsonl_round_trip_tolerates_torn_line_and_foreign_versions(tmp_path):
    path = str(tmp_path / "events.jsonl")
    obs.configure(path, sample_memory=False)
    with obs.span("ok"):
        pass
    obs.flush_metrics()
    obs.disable()
    with open(path, "a") as f:
        f.write(json.dumps({"v": 999, "kind": "span", "name": "future"}) + "\n")
        f.write('{"v": 1, "kind": "span", "name": "torn", "dur')  # crash cut
    events = list(obs.read_events(path))
    names = [e.get("name") for e in events if e["kind"] == "span"]
    assert names == ["ok"], "unknown versions and torn lines must be skipped"
    assert any(e["kind"] == "metrics" for e in events)
    # kind filter
    assert all(e["kind"] == "span"
               for e in obs.read_events(path, kinds=["span"]))


def test_configure_truncate_starts_fresh(tmp_path):
    """Single-owner paths (run.py's derived events file) must not pool a
    rerun's spans into a stale capture."""
    path = str(tmp_path / "events.jsonl")
    obs.configure(path, sample_memory=False)
    with obs.span("old"):
        pass
    obs.disable()
    obs.configure(path, sample_memory=False, truncate=True)
    with obs.span("new"):
        pass
    obs.disable()
    names = [e["name"] for e in obs.read_events(path) if e["kind"] == "span"]
    assert names == ["new"]
    # default (no truncate) appends — the bench multi-process contract
    obs.configure(path, sample_memory=False)
    with obs.span("appended"):
        pass
    obs.disable()
    names = [e["name"] for e in obs.read_events(path) if e["kind"] == "span"]
    assert names == ["new", "appended"]


def test_configure_truncate_resets_metrics_registry(tmp_path):
    """A truncating owner starts a fresh capture: counters from an earlier
    run in this process must not pool into the new digest."""
    obs.count("run.scenes_ok", 7)
    obs.configure(str(tmp_path / "a.jsonl"), sample_memory=False,
                  truncate=True)
    assert obs.registry().snapshot()["counters"] == {}
    obs.count("run.scenes_ok", 1)
    obs.disable()
    # append mode (bench multi-process contract) keeps accumulating
    obs.configure(str(tmp_path / "a.jsonl"), sample_memory=False)
    assert obs.registry().snapshot()["counters"]["run.scenes_ok"] == 1
    obs.disable()


def test_sink_failure_disables_not_raises(tmp_path):
    path = str(tmp_path / "events.jsonl")
    tracer = obs.configure(path, sample_memory=False)
    tracer.sink._f.close()  # simulate a dead disk under the sink
    with obs.span("after-death"):
        pass  # must not raise
    assert tracer.sink._dead
    obs.disable()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_registry_counters_gauges_histograms():
    reg = Registry()
    reg.count("c")
    reg.count("c", 4)
    reg.gauge("g", 7.0)
    reg.gauge_max("hw", 5.0)
    reg.gauge_max("hw", 3.0)  # lower: ignored
    for v in range(100):
        reg.observe("h", float(v))
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"] == {"g": 7.0, "hw": 5.0}
    h = snap["histograms"]["h"]
    assert h["count"] == 100 and h["total"] == sum(range(100))
    assert 45 <= h["p50"] <= 55 and 90 <= h["p95"] <= 99


def test_histogram_bounded_memory():
    h = Histogram()
    for v in range(100_000):
        h.observe(float(v))
    assert h.count == 100_000
    assert len(h.values) < 5000, "reservoir must stay bounded"
    assert 40_000 <= h.percentile(50) <= 60_000


def test_count_transfer_per_stage_and_total():
    obs.count_transfer("d2h", 1000, "post.claims")
    obs.count_transfer("d2h", 500, "post.claims")
    obs.count_transfer("h2d", 64, "associate")
    c = obs.registry().snapshot()["counters"]
    assert c["d2h.bytes.post.claims"] == 1500
    assert c["d2h.bytes"] == 1500
    assert c["h2d.bytes.associate"] == 64


def test_compile_cache_bucket_counters():
    from maskclustering_tpu.utils.compile_cache import (record_shape_bucket,
                                                        reset_shape_buckets)

    reset_shape_buckets()
    try:
        assert record_shape_bucket("obs_test", 1, 2)
        assert not record_shape_bucket("obs_test", 1, 2)
        assert record_shape_bucket("obs_test", 3, 4)
        snap = obs.registry().snapshot()
        assert snap["counters"]["compile_cache.bucket_new"] == 2
        assert snap["counters"]["compile_cache.bucket_hit"] == 1
        assert snap["gauges"]["compile_cache.distinct_buckets"] == 2
    finally:
        reset_shape_buckets()


# ---------------------------------------------------------------------------
# report CLI
# ---------------------------------------------------------------------------


def _canned_events(tmp_path, name="events.jsonl", scale=1.0):
    path = str(tmp_path / name)
    obs.configure(path, sample_memory=False, meta={"tool": "canned"})
    for i in range(4):
        obs.record_span("associate", 0.10 * scale, scene=f"s{i}")
        obs.record_span("graph", 0.02 * scale)
        obs.record_span("cluster", 0.03 * scale, sync_s=0.02 * scale)
        obs.record_span("postprocess", 0.40 * scale)
        obs.record_span("post.claims", 0.30 * scale, parent="postprocess")
    obs.count_transfer("d2h", 4 * 1024 * 1024, "post.claims")
    obs.count_transfer("h2d", 64 * 1024 * 1024, "associate.feed")
    obs.flush_metrics()
    obs.disable()
    return path


def test_report_cli_smoke(tmp_path, capsys):
    from maskclustering_tpu.obs.report import main

    path = _canned_events(tmp_path)
    assert main([path]) == 0
    out = capsys.readouterr().out
    for stage in ("associate", "graph", "cluster", "postprocess",
                  "post.claims"):
        assert stage in out
    assert "dev.p50" in out and "host.p50" in out
    assert "4.0MB" in out  # the post.claims d2h column
    assert "64.0MB" in out  # h2d total line


def test_report_cli_as_module(tmp_path):
    """The documented entrypoint: python -m maskclustering_tpu.obs.report."""
    path = _canned_events(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "maskclustering_tpu.obs.report", path,
         "--json"],
        capture_output=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-500:]
    summary = json.loads(proc.stdout)
    assert summary["stages"]["cluster"]["device_p50_s"] == pytest.approx(0.02)
    assert summary["h2d_bytes"] == 64 * 1024 * 1024


def test_report_diff(tmp_path, capsys):
    from maskclustering_tpu.obs.report import main

    a = _canned_events(tmp_path, "a.jsonl", scale=1.0)
    b = _canned_events(tmp_path, "b.jsonl", scale=2.0)
    assert main([a, "--diff", b]) == 0
    out = capsys.readouterr().out
    assert "obs diff" in out
    assert "-50.0%" in out  # every A stage is half of B's p50


def test_read_events_counts_skipped_lines(tmp_path):
    """Satellite robustness contract: torn + unknown-version lines are
    skipped WITH A COUNT (silent loss made a report lie by omission)."""
    path = str(tmp_path / "events.jsonl")
    obs.configure(path, sample_memory=False)
    with obs.span("ok"):
        pass
    obs.disable()
    with open(path, "a") as f:
        f.write(json.dumps({"v": 99, "kind": "span", "name": "future"}) + "\n")
        f.write('{"v": 1, "kind": "span", "na')  # torn final line
    stats = obs.ReadStats()
    names = [e.get("name") for e in obs.read_events(path, stats=stats)
             if e["kind"] == "span"]
    assert names == ["ok"]
    assert stats.torn == 1 and stats.unknown_version == 1
    assert stats.skipped == 2


def test_report_render_warns_on_skipped_lines(tmp_path):
    from maskclustering_tpu.obs.report import RunData, render_report

    path = _canned_events(tmp_path)
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "span"')  # crash cut
    run = RunData(path)
    assert run.read_stats.torn == 1
    out = render_report(run)
    assert "WARNING: skipped" in out and "1 torn" in out


def test_xprof_span_triggered_capture(tmp_path):
    """xprof_dir + xprof_spans: the named span's first opening brackets a
    real jax.profiler trace; later openings respect the capture limit."""
    events = str(tmp_path / "events.jsonl")
    xdir = str(tmp_path / "xprof")
    obs.configure(events, sample_memory=False, xprof_dir=xdir,
                  xprof_spans=("cluster",), xprof_limit=1)
    tracer = obs.get_tracer()
    assert tracer.xprof is not None
    with obs.span("associate"):
        pass  # unarmed span: no capture
    with obs.span("cluster"):
        pass
    with obs.span("cluster"):
        pass  # second opening: over the limit, no second trace
    obs.disable()
    assert tracer.xprof.captured == {"cluster": 1}
    assert os.path.isdir(os.path.join(xdir, "cluster-0"))
    assert not os.path.isdir(os.path.join(xdir, "cluster-1"))


def test_xprof_arm_is_bounded_and_non_reentrant(tmp_path, monkeypatch):
    import jax.profiler

    from maskclustering_tpu.obs.xprof import XprofArm, parse_spans

    assert parse_spans("cluster,post.claims.kernel") == (
        "cluster", "post.claims.kernel")
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    arm = XprofArm(str(tmp_path), ["a", "b"], limit=1)
    assert arm.maybe_start("a")
    # non-reentrant: a second armed span cannot steal the session
    assert not arm.maybe_start("b")
    arm.stop("b")  # non-owner stop is a no-op
    assert arm.active_span == "a"
    arm.stop("a")
    assert arm.active_span is None
    assert not arm.maybe_start("a")  # limit reached
    assert arm.maybe_start("b")
    arm.close()  # closes the open trace and disarms
    assert arm.dead and arm.active_span is None
    assert [c[0] for c in calls] == ["start", "stop", "start", "stop"]


def test_report_merges_counters_across_pids(tmp_path):
    """One file, several processes (bench worker attempts + supervisor):
    counters sum across pids but stay last-write within one pid."""
    from maskclustering_tpu.obs.report import RunData

    path = str(tmp_path / "events.jsonl")
    obs.configure(path, sample_memory=False)
    obs.count("bench.attempts", 1)
    obs.flush_metrics()
    obs.count("bench.attempts", 1)  # now 2; same pid, later flush supersedes
    obs.flush_metrics()
    obs.disable()
    with open(path, "a") as f:  # a second process's flush
        f.write(json.dumps({
            "v": 1, "kind": "metrics", "ts": 0.0, "pid": -1,
            "metrics": {"counters": {"bench.attempts": 3},
                        "gauges": {"hbm.high_water_bytes": 123.0}}}) + "\n")
    run = RunData(path)
    assert run.summary()["counters"]["bench.attempts"] == 5
    assert run.hbm_high_water == 123.0
