"""mct-blackbox contract tests (obs/flight.py + obs/slo.py + tenant plane).

Unit tier, all CPU-cheap: the flight recorder's ring bounds and
snapshot-delta shape, crash-safe dump round-trips (render, request
filter, resolve-newest, CLI exit codes, unarmed no-op), SLO spec
validation naming the bad field, the two-window burn-rate rule
(one bad window must NOT page), tenant-scoped objectives, per-tenant
window/cumulative accounting parity plus the overflow cap, the
empty-window render guards (obs.top / report Serving+SLO clean on zero
requests), the --regress tenant-dimension fence both ways, the
obs.trace --blackbox merge (dedup + zero-width marks), and the
disarmed-path AST pin: no device-path module may import the recorder.
"""

import json
import types

from maskclustering_tpu.analysis import ast_checks
from maskclustering_tpu.obs import flight, ledger as led, slo, telemetry
from maskclustering_tpu.obs import metrics as obs_metrics
from maskclustering_tpu.obs.report import (main as report_main,
                                           render_slo, render_tenants)
from maskclustering_tpu.obs.top import render_top
from maskclustering_tpu.obs.trace import assemble_trace


# ---------------------------------------------------------------------------
# flight recorder: ring, snapshot deltas, dumps
# ---------------------------------------------------------------------------


def test_flight_ring_bounds_and_snapshot_delta():
    rec = flight.FlightRecorder(capacity=16)
    for i in range(40):
        rec.record(flight.KIND_ADMIT, event="admit", request=f"r-{i}")
    assert len(rec) == 16  # bounded: old events evicted, never grown
    rows, seq = rec.snapshot()
    assert seq == 40
    assert [r["request"] for r in rows] == [f"r-{i}" for i in range(24, 40)]
    assert all(r["seq"] == 25 + i for i, r in enumerate(rows))
    # delta semantics: the child heartbeat ships only what is new
    delta, seq2 = rec.snapshot(seq)
    assert delta == [] and seq2 == seq
    rec.record_span("serve.request", 1.25, 0.5, {"request": "r-40"})
    delta, seq3 = rec.snapshot(seq)
    assert seq3 == 41 and len(delta) == 1
    sp = delta[0]
    assert sp["kind"] == "span" and sp["name"] == "serve.request"
    assert sp["dur_s"] == 1.25 and sp["sync_s"] == 0.5
    assert sp["attrs"] == {"request": "r-40"}


def test_flight_dump_round_trip_render_and_filter(tmp_path, monkeypatch):
    monkeypatch.delenv(flight.ENV_DIR, raising=False)
    rec = flight.FlightRecorder(capacity=32)
    rec.record(flight.KIND_REQUEST, event="received", request="r-1",
               tenant="A")
    rec.record_span("serve.request", 2.0, 0.1,
                    {"request": "r-1", "scene": "s0"})
    rec.record(flight.KIND_CRASH, request="r-2", signal=9)
    # unarmed (no dir, no env) -> counted no-op, never a failure source
    assert rec.dump("watchdog") is None

    rec.arm(str(tmp_path))
    path = rec.dump("worker_crash",
                    extra_rows=[{"kind": flight.KIND_HB, "pid": 777,
                                 "age_s": 3.0}])
    assert path is not None and path.endswith("-worker_crash.jsonl")
    meta, rows = flight.read_dump(path)
    assert meta["kind"] == flight.KIND_META
    assert meta["reason"] == "worker_crash"
    assert meta["events"] == 4 == len(rows)
    assert rows[-1]["pid"] == 777  # extra (relayed) rows keep their pid
    text = flight.render_dump(meta, rows)
    assert "worker_crash" in text and "serve.request" in text
    # request filter: only r-1's lifecycle + spans survive
    only = flight.render_dump(meta, rows, request="r-1")
    assert "r-1" in only and "r-2" not in only
    assert "2 event(s) for request r-1" in only


def test_flight_resolve_dump_and_cli(tmp_path, capsys):
    rec = flight.FlightRecorder()
    rec.record(flight.KIND_SIGNAL, event="stop")
    old = rec.dump("sigterm", path=str(tmp_path / "flight-1-01-a.jsonl"))
    new = rec.dump("watchdog", path=str(tmp_path / "flight-1-02-b.jsonl"))
    # a directory resolves to its newest dump; files resolve to themselves
    assert flight.resolve_dump(str(tmp_path)) == new
    assert flight.resolve_dump(old) == old
    assert flight.resolve_dump(str(tmp_path / "nope.jsonl")) is None

    assert flight.main([str(tmp_path)]) == 0
    assert "watchdog" in capsys.readouterr().out
    assert flight.main([str(tmp_path / "nope.jsonl")]) == 1
    capsys.readouterr()
    assert flight.main([new, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["meta"]["reason"] == "watchdog"
    assert doc["events"][0]["kind"] == flight.KIND_SIGNAL


# ---------------------------------------------------------------------------
# slo: spec validation + two-window burn rates
# ---------------------------------------------------------------------------


def _win(requests, *, status="ok", p95=1.0, tenants=None, **extra):
    row = {"t0": 0.0, "dur_s": 5.0, "requests": requests,
           "by_status": {status: requests}, "rejects": {}, "crashes": 0,
           "respawns": 0, "requeued": 0, "aot_hits": 0,
           "post_warm_compiles": 0, "queue_depth": 0,
           "latency": {"b": {"count": requests, "p50_s": p95 / 2,
                             "p95_s": p95, "max_s": p95}}}
    if tenants:
        row["tenants"] = tenants
    row.update(extra)
    return row


def test_slo_validate_spec_names_bad_field():
    import pytest

    base = {"name": "s", "windows": {"short": 1, "long": 5},
            "objectives": [{"name": "o", "kind": "error_rate",
                            "threshold": 0.1}]}
    assert slo.validate_spec(base)["objectives"][0]["threshold"] == 0.1
    cases = [
        (dict(base, windows={"short": 3, "long": 2}), "windows"),
        (dict(base, objectives=[]), "objectives"),
        (dict(base, objectives=[{"name": "o", "kind": "bogus",
                                 "threshold": 1}]), "unknown kind"),
        (dict(base, objectives=base["objectives"] * 2), "duplicate"),
        (dict(base, objectives=[{"name": "o", "kind": "error_rate",
                                 "threshold": -1}]), "threshold"),
        (dict(base, objectives=[{"name": "o", "kind": "error_rate",
                                 "threshold": 1, "tenant": ""}]), "tenant"),
    ]
    for spec, needle in cases:
        with pytest.raises(ValueError, match=needle):
            slo.validate_spec(spec)
    # the canned default is itself valid and loads without a file
    spec = slo.load_spec(None)
    assert spec["name"] == "serve-default"
    assert {o["kind"] for o in spec["objectives"]} <= set(slo.KINDS)


def test_slo_two_window_rule_and_violation_naming():
    spec = slo.validate_spec({
        "name": "t", "windows": {"short": 1, "long": 5},
        "objectives": [{"name": "errors", "kind": "error_rate",
                        "threshold": 0.05},
                       {"name": "lat", "kind": "latency_p95",
                        "threshold": 10.0}]})
    healthy = {"windows": [_win(10) for _ in range(5)]}
    res = slo.evaluate(spec, healthy)
    assert res["ok"] and slo.violated(res) == []
    assert all(o["state"] == "ok" for o in res["objectives"])

    # ONE bad window (the short one) must not page: the long window's
    # error rate 2/42 stays inside the 5% budget
    spike = {"windows": [_win(10) for _ in range(4)]
             + [_win(2, status="deadline")]}
    res = slo.evaluate(spec, spike)
    errors = [o for o in res["objectives"] if o["name"] == "errors"][0]
    assert errors["state"] == "ok" and errors["burn_short"] > 1.0
    assert res["ok"]

    # sustained burn: every window bad -> both windows past budget
    burn = {"windows": [_win(2, status="deadline") for _ in range(5)]}
    res = slo.evaluate(spec, burn)
    assert not res["ok"] and slo.violated(res) == ["errors"]
    # crashes count against the same budget as error statuses
    crashy = {"windows": [_win(2, crashes=2) for _ in range(5)]}
    assert slo.violated(slo.evaluate(spec, crashy)) == ["errors"]


def test_slo_tenant_scope_zero_threshold_and_no_data():
    spec = slo.validate_spec({
        "name": "t", "windows": {"short": 1, "long": 2},
        "objectives": [
            {"name": "a-errors", "kind": "error_rate", "threshold": 0.05,
             "tenant": "A"},
            {"name": "no-compiles", "kind": "post_warm_compiles",
             "threshold": 0}]})
    # tenant A burns while the global window (and tenant B) stay healthy
    rows = [_win(10, tenants={"A": {"requests": 1,
                                    "by_status": {"failed": 1}},
                              "B": {"requests": 9}})
            for _ in range(2)]
    res = slo.evaluate(spec, {"windows": rows})
    a = [o for o in res["objectives"] if o["name"] == "a-errors"][0]
    assert a["tenant"] == "A" and a["state"] == "violated"
    # zero-threshold count objective: the burn IS the count, so repeated
    # occurrences in both windows page (a single one burns at exactly 1.0
    # and stays on the right side of the strict > threshold)
    rows2 = [_win(5, post_warm_compiles=2) for _ in range(2)]
    res2 = slo.evaluate(spec, {"windows": rows2})
    assert "no-compiles" in slo.violated(res2)
    one = [_win(5, post_warm_compiles=1), _win(5)]
    assert slo.violated(slo.evaluate(spec, {"windows": one})) == []
    # no traffic -> no_data verdicts, never a fake pass/fail number
    res3 = slo.evaluate(spec, {"windows": []})
    assert res3["ok"] and all(o["state"] == "no_data"
                              for o in res3["objectives"])
    assert "no evaluation" in slo.render_result(None)[0]
    assert any("--" in ln for ln in slo.render_result(res3))


# ---------------------------------------------------------------------------
# telemetry: per-tenant window + cumulative accounting
# ---------------------------------------------------------------------------


def test_aggregator_tenant_accounting_parity():
    agg = telemetry.WindowAggregator(window_s=60.0)
    reg = obs_metrics.registry()
    reg.count("device.seconds", 2.0)  # consumed before A's completion
    agg.record_request("b6", 1.0, tenant="A")
    agg.record_request("b6", 2.0, tenant="A", status="failed")
    agg.record_request("b6", 3.0, tenant="B")
    reg.count("device.seconds", 1.5)
    agg.record_request("b6", 4.0)  # untenanted: books globally only,
    agg.record_queue_wait(0.5, tenant="A")  # and advances the baseline
    agg.record_request("b6", 5.0, tenant="B")

    row = agg.roll()
    t = row["tenants"]
    # sums-to-global: every tenanted completion appears exactly once
    assert sum(s["requests"] for s in t.values()) == 4
    assert t["A"]["requests"] == 2 and t["B"]["requests"] == 2
    assert t["A"]["by_status"] == {"ok": 1, "failed": 1}
    assert t["A"]["latency"]["b6"]["count"] == 2
    assert t["A"]["queue_wait"]["count"] == 1
    # attribution: the device-seconds delta since the previous completion
    # lands on the finishing tenant; the untenanted request's 1.5s is
    # charged to no one (the baseline still advances past it)
    assert t["A"]["device_s"] == 2.0
    assert "device_s" not in t["B"]  # zero elided from the wire row

    # the window slot clears at roll; cumulative accounting persists
    row2 = agg.roll()
    assert "tenants" not in row2
    cum = agg.snapshot()["cumulative"]["tenants"]
    assert cum["A"]["requests"] == 2 and cum["B"]["requests"] == 2
    assert cum["A"]["latency"]["all"]["count"] == 2
    assert cum["A"]["device_s"] == 2.0


def test_aggregator_tenant_overflow_attribution_and_rebase():
    agg = telemetry.WindowAggregator(window_s=60.0)
    for i in range(telemetry._TENANT_CAP + 8):
        agg.record_request("b", 1.0, tenant=f"t{i:03d}")
    agg.record_reject("t000")
    agg.record_crash("t001")
    agg.record_reject("")  # empty tenant: a no-op, never a slot
    agg.record_crash("")
    row = agg.roll()
    t = row["tenants"]
    # bounded store: _TENANT_CAP named slots + the shared overflow bucket
    assert len(t) == telemetry._TENANT_CAP + 1
    assert t[telemetry._TENANT_OVERFLOW]["requests"] == 8
    assert sum(s["requests"] for s in t.values()) == telemetry._TENANT_CAP + 8
    assert t["t000"]["rejects"] == 1 and t["t001"]["crashes"] == 1

    # rebase re-anchors the window clock and drops current-window slots
    # (warm-up charges no tenant) without touching the cumulative store
    agg.record_request("b", 1.0, tenant="t000")
    agg.rebase()
    assert "tenants" not in agg.roll()
    cum = agg.snapshot()["cumulative"]["tenants"]
    assert cum["t000"]["requests"] == 2


# ---------------------------------------------------------------------------
# empty-window render guards
# ---------------------------------------------------------------------------


def test_empty_window_renders_are_clean():
    # a daemon polled before its first request: no windows, no tenants,
    # no percentiles of nothing — every panel renders, nothing divides
    frame = render_top({}, now=0.0)
    assert "mct-serve top" in frame and "requests: none yet" in frame
    frame = render_top({"telemetry": {"windows": [], "current": {},
                                      "cumulative": {}},
                        "slo": slo.evaluate(slo.load_spec(None),
                                            {"windows": []})}, now=0.0)
    assert "slo [serve-default]" in frame and "Traceback" not in frame
    assert render_tenants([]) == []
    assert render_tenants([{"requests": 5}]) == []  # untenanted windows
    # report SLO section: absent (not crashing) without telemetry rows
    assert render_slo(types.SimpleNamespace(telemetry_rows=[])) is None


# ---------------------------------------------------------------------------
# --regress: the tenant-dimension fence, both ways
# ---------------------------------------------------------------------------


def _serve_verdict(value, tenants=None):
    v = {"metric": "serve s/request (p50)", "value": value,
         "unit": "s/request", "tool": "serve"}
    if tenants:
        v["tenants"] = tenants
    return v


def test_regress_tenant_dimension_fences_both_ways(tmp_path, capsys):
    assert not led.tenant_dimension(None)
    assert not led.tenant_dimension({"value": 1.0})
    tenants = {"A": {"requests": 3}, "B": {"requests": 1}}
    assert led.tenant_dimension(led.serve_row(_serve_verdict(1.0, tenants)))

    # untenanted baseline: a newer tenant-mix row (its latency is the
    # mix's) must not gate — the fence picks the comparable row instead
    baseline = str(tmp_path / "base.json")
    with open(baseline, "w") as f:
        json.dump(_serve_verdict(1.0), f)
    ledger = str(tmp_path / "ledger.jsonl")
    led.append_row(ledger, led.serve_row(_serve_verdict(1.05)))
    led.append_row(ledger, led.serve_row(_serve_verdict(9.0, tenants)))
    assert report_main(["--ledger", ledger, "--regress", baseline]) == 0
    assert "1.050" in capsys.readouterr().out

    # the other way: a tenanted baseline never gates untenanted rows
    base2 = str(tmp_path / "base2.json")
    with open(base2, "w") as f:
        json.dump(_serve_verdict(1.0, tenants), f)
    ledger2 = str(tmp_path / "ledger2.jsonl")
    led.append_row(ledger2, led.serve_row(_serve_verdict(1.05, tenants)))
    led.append_row(ledger2, led.serve_row(_serve_verdict(9.0)))
    assert report_main(["--ledger", ledger2, "--regress", base2]) == 0
    capsys.readouterr()

    # same dimension still gates: an in-fence regression exits non-zero
    ledger3 = str(tmp_path / "ledger3.jsonl")
    led.append_row(ledger3, led.serve_row(_serve_verdict(9.0, tenants)))
    assert report_main(["--ledger", ledger3, "--regress", base2]) == 2
    assert "REGRESSION" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# obs.trace --blackbox: merge, dedup, zero-width marks
# ---------------------------------------------------------------------------


def test_trace_blackbox_merge_dedups_and_marks(tmp_path):
    t0 = 1000.0
    events = str(tmp_path / "events.jsonl")
    wait = {"v": 1, "kind": "span", "ts": t0 + 1.0, "pid": 1,
            "name": "serve.queue_wait", "dur_s": 1.0,
            "attrs": {"request": "r-1", "scene": "s0"}}
    with open(events, "w") as f:
        f.write(json.dumps(wait) + "\n")

    # the postmortem ring: the victim's child-side execution span the
    # relay never shipped, its lifecycle mark, the parent-side crash row,
    # a duplicate of the live wait span (must dedup), another request's
    # mark (must filter)
    rec = flight.FlightRecorder()
    dump_dir = tmp_path / "flight"
    path = str(dump_dir / "flight-9-01-worker_crash.jsonl")
    rec.dump("worker_crash", path=path)  # empty decoy: newest wins below
    rows = [
        dict(wait, seq=1),
        {"kind": flight.KIND_REQUEST, "ts": t0 + 2.0, "seq": 2,
         "event": "received", "request": "r-1", "tenant": "A", "pid": 9},
        {"kind": "span", "ts": t0 + 3.0, "seq": 3, "name": "serve.request",
         "dur_s": 1.0, "attrs": {"request": "r-1", "end_ts": t0 + 3.0,
                                 "worker_pid": 9}},
        {"kind": flight.KIND_CRASH, "ts": t0 + 3.5, "seq": 4,
         "request": "r-2", "signal": 9},
        {"kind": flight.KIND_CRASH, "ts": t0 + 3.6, "seq": 5,
         "request": "r-1", "signal": 9},
    ]
    rec.dump("worker_crash", extra_rows=rows,
             path=str(dump_dir / "flight-9-02-worker_crash.jsonl"))

    tr = assemble_trace("r-1", events, blackbox=str(dump_dir))
    kinds = [s["kind"] for s in tr["segments"]]
    assert kinds == ["queue_wait", "blackbox", "attempt", "blackbox"]
    marks = [s for s in tr["segments"] if s["kind"] == "blackbox"]
    assert marks[0]["label"] == "blackbox received (pid 9)"
    assert "tenant=A" in marks[0]["detail"]
    assert marks[1]["label"] == "blackbox WORKER CRASH"
    attempt = [s for s in tr["segments"] if s["kind"] == "attempt"][0]
    assert "worker pid 9" in attempt["detail"]
    # r-2's crash never leaks into r-1's timeline; the duplicated wait
    # span stays a single segment
    assert len([s for s in tr["segments"]
                if s["kind"] == "queue_wait"]) == 1


# ---------------------------------------------------------------------------
# analysis hygiene: the recorder stays off the device path
# ---------------------------------------------------------------------------


def test_flight_stays_off_device_path_and_in_scan_roots():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # disarmed-path pin: no device-path module may touch the recorder or
    # SLO plane — a ring append is host work the fused lattice must never
    # pay for, and the analyzer only host-sync-audits these modules
    for rel in ast_checks.DEVICE_PATH_MODULES:
        with open(os.path.join(repo, rel), encoding="utf-8") as f:
            src = f.read()
        assert "obs.flight" not in src and "obs.slo" not in src, rel
        assert "import flight" not in src and "import slo" not in src, rel
    # the new planes are inside the analyzer's jurisdiction, not beside it
    scanned = {os.path.relpath(p, repo).replace(os.sep, "/")
               for p in ast_checks._iter_py_files(repo)}
    assert "maskclustering_tpu/obs/flight.py" in scanned
    assert "maskclustering_tpu/obs/slo.py" in scanned
    assert "scripts/load_gen.py" in scanned
