"""Streaming incremental clustering: the ISSUE-15 acceptance matrix.

- accumulator additivity units vs the one-shot contraction (both
  count_dtype encodings — the counting accumulators make chunked sums
  exact);
- chunk >= F: the streaming path is BYTE-IDENTICAL to the batch path
  (both encodings);
- warm-start re-cluster equivalence: restarting the iterative merge from
  prior labels reproduces the cold solve whenever the prior partition
  refines the final components (and is idempotent at a fixpoint);
- multi-chunk convergence: final instances match the batch answer on the
  solvable synthetic scene within the pinned tolerance;
- a mid-stream FaultPlan fault retries the CHUNK (accumulator intact) and
  heals; the journaled accumulator resumes mid-stream;
- per-chunk residency (stream.max_plane_bytes) stays strictly under the
  full-scene plane set, and chunks 2..K add ZERO new shape buckets.

Scenes reuse the tier-1 suite's tiny shape family (48x64 frames, 0.05
spacing, mask_pad_multiple 32) so jit caches hit across files.
"""

import os
import time

import numpy as np
import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.config import load_config
from maskclustering_tpu.models.pipeline import bucket_k_max, run_scene
from maskclustering_tpu.models.streaming import (
    StreamAccumulator,
    slice_scene_frames,
    stream_scene,
)
from maskclustering_tpu.utils import faults
from maskclustering_tpu.utils.compile_cache import max_seg_id, scene_pads
from maskclustering_tpu.utils.synthetic import (
    make_scene,
    to_scene_tensors,
    write_scannet_layout,
)

SCENE = "scene0001_00"
# 16 frames at chunk 4: four full chunks. The scene must stay at a size
# where the chunked consensus matches batch exactly (at 14 frames the
# 4-chunk stream oversplits — fewer common visible frames per cross-chunk
# pair); partial-last-chunk padding is pinned by the resume test's
# clamped slice and exercised by any non-divisor chunk in production
FRAMES = 16


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.set_plan(None)
    faults.clear_stop()
    yield
    faults.set_plan(None)
    faults.clear_stop()


@pytest.fixture(scope="module")
def scene_pack(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("stream_data"))
    scene = make_scene(num_boxes=3, num_frames=FRAMES, image_hw=(48, 64),
                       seed=7, spacing=0.05)
    write_scannet_layout(scene, root, SCENE)
    return {"root": root, "scene": scene,
            "tensors": to_scene_tensors(scene)}


def _cfg(root, **kw):
    return load_config("scannet").replace(
        data_root=root, config_name="streamtest", step=1,
        distance_threshold=0.05, mask_pad_multiple=32,
        frame_pad_multiple=4, point_chunk=2048, retry_backoff_s=0.01, **kw)


@pytest.fixture(scope="module")
def batch_result(scene_pack):
    return run_scene(scene_pack["tensors"], _cfg(scene_pack["root"]),
                     seq_name=SCENE)


@pytest.fixture(scope="module")
def stream4_result(scene_pack):
    """The module's one multi-chunk stream (chunk 4 over 16 frames);
    shared by the convergence, fault-heal and residency assertions."""
    return stream_scene(scene_pack["tensors"],
                        _cfg(scene_pack["root"], streaming_chunk=4),
                        seq_name=SCENE)


def _assert_objects_equal(a, b):
    assert len(a.point_ids_list) == len(b.point_ids_list)
    for pa, pb in zip(a.point_ids_list, b.point_ids_list):
        assert np.array_equal(pa, pb)
    assert a.mask_list == b.mask_list
    assert a.num_points == b.num_points


# ---------------------------------------------------------------------------
# additivity units
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_observer_accumulation_additive_over_frame_chunks(rng, count_dtype):
    """Sum of per-chunk observer contractions == the one-shot contraction
    (exact integer summands in the encoding's accumulator)."""
    from maskclustering_tpu.ops import counting

    vis = rng.random((48, 32)) < 0.3  # (M, F)
    one_shot = np.asarray(counting.count_dot(vis, vis.T,
                                             count_dtype=count_dtype))
    acc = np.zeros_like(one_shot)
    for s in range(0, 32, 8):
        chunk = vis[:, s:s + 8]
        acc = acc + np.asarray(counting.count_dot(
            chunk, chunk.T, count_dtype=count_dtype))
    np.testing.assert_array_equal(acc, one_shot)
    np.testing.assert_array_equal(one_shot, (vis.astype(np.int64)
                                             @ vis.T.astype(np.int64)))


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_rep_cross_contraction_matches_oracle(rng, count_dtype):
    """The merge program's rep x chunk-mask count (one-hot membership
    against chunk claims) equals the dense int64 numpy contraction."""
    from maskclustering_tpu.ops import counting

    n, m, mk = 4096, 24, 12
    rep_plane = rng.integers(0, m + 1, n).astype(np.int32)  # 0 = none
    claims = rng.integers(0, mk, n).astype(np.int32)
    a = np.zeros((n, m), np.int64)
    idx = np.nonzero(rep_plane > 0)[0]
    a[idx, rep_plane[idx] - 1] = 1
    w = np.zeros((n, mk), np.int64)
    w[np.arange(n), claims] = 1
    oracle = a.T @ w
    got = np.asarray(counting.count_dot(
        (rep_plane[:, None] == np.arange(1, m + 1)[None, :]).T,
        (claims[:, None] == np.arange(mk)[None, :]),
        count_dtype=count_dtype))
    np.testing.assert_array_equal(got, oracle)


# ---------------------------------------------------------------------------
# warm-start re-cluster equivalence
# ---------------------------------------------------------------------------


def test_warm_start_recluster_equivalence(rng):
    """Warm-starting the merge from a REFINEMENT of the final components
    (which every previous-chunk assignment is, under the same affinity)
    reproduces the cold solve; warm-starting from the cold fixpoint is
    idempotent."""
    from maskclustering_tpu.models.clustering import iterative_clustering

    m, f = 64, 12
    visible = np.asarray(rng.random((m, f)) < 0.4)
    contained = np.asarray(rng.random((m, m)) < 0.15)
    active = np.ones(m, bool)
    schedule = np.full(20, np.inf, np.float32)
    schedule[:3] = [3.0, 2.0, 1.0]

    cold = iterative_clustering(visible, contained, active, schedule)
    cold_assign = np.asarray(cold.assignment)

    # a refinement: split every cold component by the parity of the slot
    # index — each refined cluster sits inside exactly one final component
    refine = np.asarray(
        [min(j for j in range(m)
             if cold_assign[j] == cold_assign[i] and j % 2 == i % 2)
         for i in range(m)], dtype=np.int32)
    warm = iterative_clustering(visible, contained, active, schedule,
                                refine)
    np.testing.assert_array_equal(np.asarray(warm.assignment), cold_assign)
    np.testing.assert_array_equal(np.asarray(warm.node_visible),
                                  np.asarray(cold.node_visible))

    again = iterative_clustering(visible, contained, active, schedule,
                                 cold_assign)
    np.testing.assert_array_equal(np.asarray(again.assignment), cold_assign)


# ---------------------------------------------------------------------------
# chunk >= F byte identity (both encodings)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_single_chunk_stream_byte_identical_to_batch(scene_pack,
                                                     count_dtype):
    cfg = _cfg(scene_pack["root"], count_dtype=count_dtype)
    batch = run_scene(scene_pack["tensors"], cfg, seq_name=SCENE)
    stream = stream_scene(scene_pack["tensors"],
                          cfg.replace(streaming_chunk=FRAMES),
                          seq_name=SCENE)
    _assert_objects_equal(batch.objects, stream.objects)
    np.testing.assert_array_equal(batch.assignment, stream.assignment)
    np.testing.assert_array_equal(batch.table.frame, stream.table.frame)
    np.testing.assert_array_equal(batch.table.mask_id, stream.table.mask_id)


@pytest.mark.slow
def test_single_chunk_stream_artifacts_byte_identical(scene_pack, tmp_path):
    """The on-disk artifact pair (npz + object_dict) is bit-for-bit the
    batch file for a chunk that covers the whole scene. Slow tier: the
    in-memory identity above is tier-1 (both encodings) and ci.sh's rc-9
    streaming smoke byte-compares the on-disk pair every CI run."""
    cfg = _cfg(scene_pack["root"])
    outs = {}
    for tag, c in (("batch", cfg),
                   ("stream", cfg.replace(streaming_chunk=FRAMES))):
        od_dir = str(tmp_path / tag / "object_dicts")
        pred = str(tmp_path / tag / "prediction")
        if c.streaming_chunk:
            stream_scene(scene_pack["tensors"], c, seq_name=SCENE,
                         export=True, object_dict_dir=od_dir,
                         prediction_root=pred)
        else:
            run_scene(scene_pack["tensors"], c, seq_name=SCENE, export=True,
                      object_dict_dir=od_dir, prediction_root=pred)
        npz = os.path.join(pred, cfg.config_name + "_class_agnostic",
                           f"{SCENE}.npz")
        od = os.path.join(od_dir, cfg.config_name, "object_dict.npy")
        outs[tag] = (open(npz, "rb").read(), open(od, "rb").read())
    assert outs["batch"][0] == outs["stream"][0]
    assert outs["batch"][1] == outs["stream"][1]


# ---------------------------------------------------------------------------
# multi-chunk convergence + residency + bucket stability
# ---------------------------------------------------------------------------


def _best_gt_ious(objects, gt_instance):
    out = []
    for pids in objects.point_ids_list:
        pred = np.zeros(len(gt_instance), bool)
        pred[pids] = True
        best = 0.0
        for k in range(1, int(gt_instance.max()) + 1):
            g = gt_instance == k
            inter = (pred & g).sum()
            best = max(best, inter / max((pred | g).sum(), 1))
        out.append(best)
    return out


def test_multichunk_stream_converges_to_batch(scene_pack, batch_result,
                                              stream4_result):
    """The 4-chunk stream's final instances match the batch answer on the
    solvable synthetic scene: same instance count, and every instance's
    best-GT IoU within the pinned tolerance of the batch instance's."""
    gt = scene_pack["scene"].gt_instance
    b = sorted(_best_gt_ious(batch_result.objects, gt))
    s = sorted(_best_gt_ious(stream4_result.objects, gt))
    assert len(s) == len(b)
    for si, bi in zip(s, b):
        assert si >= bi - 0.05, (s, b)


def test_multichunk_residency_and_bucket_stability(scene_pack):
    """Chunks 2..K add ZERO new shape buckets (the steady state
    dispatches the programs chunk 1 compiled) and the per-chunk plane
    residency stays strictly under the full-scene plane set."""
    from maskclustering_tpu.utils import compile_cache

    tensors = scene_pack["tensors"]
    cfg = _cfg(scene_pack["root"], streaming_chunk=4)
    acc = StreamAccumulator(
        cfg, total_frames=FRAMES, num_points=tensors.num_points,
        k_max=bucket_k_max(max_seg_id(tensors.segmentations)),
        seq_name=SCENE)
    assert acc.n_chunks == 4
    partials, plane_bytes = [], []
    for ci in range(acc.n_chunks):
        before = set(compile_cache.seen_shape_buckets())
        digest = acc.push_chunk(slice_scene_frames(
            tensors, ci * 4, min((ci + 1) * 4, FRAMES)))
        new = set(compile_cache.seen_shape_buckets()) - before
        if ci > 0:
            assert not new, f"chunk {ci} created shape bucket(s) {new}"
        partials.append(digest["partial_instances"])
        plane_bytes.append(digest["plane_bytes"])
    # anytime contract: partial instances are live from the first chunk
    # and settle at the scene's true instance count
    assert partials[0] > 0
    assert partials[-1] == 3

    # per-chunk residency strictly under the full-scene plane set (the
    # gauge_max stream.max_plane_bytes folds the same per-chunk values;
    # asserted on the digest here because the module's chunk==F identity
    # streams already drove the process-global gauge to the full size)
    f_full, n_pad = scene_pads(cfg, FRAMES, tensors.num_points)
    full_set = f_full * n_pad * (4 + 2 + 2 + 1) + n_pad
    assert max(plane_bytes) < full_set
    assert obs.registry().snapshot()["gauges"][
        "stream.max_plane_bytes"] >= max(plane_bytes)
    assert len(acc.finalize().objects.point_ids_list) == 3


# ---------------------------------------------------------------------------
# fault tolerance: chunk retry + journal resume
# ---------------------------------------------------------------------------


def test_midstream_fault_retries_chunk_and_heals(scene_pack, stream4_result):
    """A scripted chunk-seam fault costs one chunk retry, not the scene:
    the stream completes with artifacts identical to the fault-free one
    and books exactly one stream.chunk_retries."""
    faults.set_plan(faults.FaultPlan.from_spec(f"flaky:{SCENE}.chunk:1"))
    before = obs.registry().snapshot()["counters"].get(
        "stream.chunk_retries", 0.0)
    result = stream_scene(scene_pack["tensors"],
                          _cfg(scene_pack["root"], streaming_chunk=4),
                          seq_name=SCENE)
    after = obs.registry().snapshot()["counters"].get(
        "stream.chunk_retries", 0.0)
    assert after - before == 1.0
    _assert_objects_equal(result.objects, stream4_result.objects)
    np.testing.assert_array_equal(result.assignment,
                                  stream4_result.assignment)


def test_terminal_midstream_fault_fails_scene(scene_pack):
    """A terminal chunk fault must NOT burn the retry budget — it raises
    straight through to the scene supervisor."""
    faults.set_plan(faults.FaultPlan.from_spec(f"terminal:{SCENE}.chunk:1"))
    before = obs.registry().snapshot()["counters"].get(
        "stream.chunk_retries", 0.0)
    with pytest.raises(faults.InjectedFault):
        stream_scene(scene_pack["tensors"],
                     _cfg(scene_pack["root"], streaming_chunk=4),
                     seq_name=SCENE)
    after = obs.registry().snapshot()["counters"].get(
        "stream.chunk_retries", 0.0)
    assert after == before


def test_abandoned_chunk_attempt_cannot_double_bind(scene_pack):
    """The epoch fence: a watchdog-abandoned push_chunk keeps running on
    its daemon thread (call_with_deadline semantics) — when a retry
    supersedes it, the stale attempt's bind must DROP (StaleChunkAttempt
    on the abandoned thread) instead of accumulating the chunk twice."""
    import threading

    from maskclustering_tpu.models.streaming import StaleChunkAttempt

    tensors = scene_pack["tensors"]
    cfg = _cfg(scene_pack["root"], streaming_chunk=4)
    acc = StreamAccumulator(
        cfg, total_frames=FRAMES, num_points=tensors.num_points,
        k_max=bucket_k_max(max_seg_id(tensors.segmentations)),
        seq_name=SCENE)
    chunk = slice_scene_frames(tensors, 0, 4)

    # the "abandoned" attempt stalls at the pull seam (one firing, so
    # the superseding attempt below runs clean past it)
    faults.set_plan(faults.FaultPlan.from_spec(f"stall:{SCENE}.pull:1",
                                               stall_s=2.0))
    raised = []

    def abandoned():
        try:
            acc.push_chunk(chunk)
        except Exception as e:  # noqa: BLE001 — asserting the type below
            raised.append(e)

    t = threading.Thread(target=abandoned, daemon=True)
    t.start()
    time.sleep(0.5)  # the abandoned attempt is inside its stall
    digest = acc.push_chunk(chunk)  # the retry supersedes it
    t.join(30.0)
    assert not t.is_alive()
    assert len(raised) == 1 and isinstance(raised[0], StaleChunkAttempt), \
        raised
    # exactly ONE chunk accumulated, and the drop is on the books
    assert acc.chunks_done == 1 and acc.frames_done == 4
    assert digest["chunk"] == 0
    assert obs.registry().snapshot()["counters"][
        "stream.stale_binds_dropped"] == 1.0


def test_resume_from_journal_midstream(scene_pack, stream4_result, tmp_path):
    """The journaled accumulator resumes a killed stream mid-scan: a
    fresh accumulator loads the chunk-2 snapshot, finishes chunks 3..4
    and produces the uninterrupted stream's exact answer."""
    tensors = scene_pack["tensors"]
    cfg = _cfg(scene_pack["root"], streaming_chunk=4)
    k_max = bucket_k_max(max_seg_id(tensors.segmentations))
    path = str(tmp_path / f"{SCENE}.stream.npz")

    acc1 = StreamAccumulator(cfg, total_frames=FRAMES,
                             num_points=tensors.num_points, k_max=k_max,
                             seq_name=SCENE)
    for ci in range(2):  # the "process" dies after chunk 2's journal
        acc1.push_chunk(slice_scene_frames(tensors, ci * 4, (ci + 1) * 4))
        acc1.save_state(path)

    acc2 = StreamAccumulator(cfg, total_frames=FRAMES,
                             num_points=tensors.num_points, k_max=k_max,
                             seq_name=SCENE)
    assert acc2.load_state(path)
    assert acc2.chunks_done == 2 and acc2.frames_done == 8
    for ci in range(2, 4):
        acc2.push_chunk(slice_scene_frames(tensors, ci * 4, (ci + 1) * 4))
    resumed = acc2.finalize()
    _assert_objects_equal(resumed.objects, stream4_result.objects)

    # a mismatched stream (different chunking) must refuse the snapshot
    acc3 = StreamAccumulator(cfg.replace(streaming_chunk=8),
                             total_frames=FRAMES,
                             num_points=tensors.num_points, k_max=k_max,
                             seq_name=SCENE)
    assert not acc3.load_state(path)


def test_stream_scene_resumes_and_cleans_journal(scene_pack, tmp_path,
                                                 stream4_result):
    """The run.py-facing driver: a state file left by a dead process is
    picked up by the next stream_scene call (resume counter books) and
    removed once the scene completes."""
    from maskclustering_tpu.models.streaming import stream_state_path

    tensors = scene_pack["tensors"]
    cfg = _cfg(scene_pack["root"], streaming_chunk=4)
    k_max = bucket_k_max(max_seg_id(tensors.segmentations))
    state_dir = str(tmp_path / "state")
    path = stream_state_path(state_dir, SCENE)

    acc = StreamAccumulator(cfg, total_frames=FRAMES,
                            num_points=tensors.num_points, k_max=k_max,
                            seq_name=SCENE)
    acc.push_chunk(slice_scene_frames(tensors, 0, 4))
    acc.save_state(path)

    before = obs.registry().snapshot()["counters"].get(
        "stream.state_resumes", 0.0)
    result = stream_scene(tensors, cfg, seq_name=SCENE,
                          state_dir=state_dir, resume=True)
    after = obs.registry().snapshot()["counters"].get(
        "stream.state_resumes", 0.0)
    assert after - before == 1.0
    assert not os.path.exists(path), "a finished stream must drop its state"
    _assert_objects_equal(result.objects, stream4_result.objects)


# ---------------------------------------------------------------------------
# run.py integration + serving (slow tier)
# ---------------------------------------------------------------------------


def test_cluster_scene_routes_streaming(scene_pack):
    """run.py's scene queue routes a streaming config through the
    accumulator (stream timings on the status) and exports the artifact."""
    from maskclustering_tpu.run import cluster_scene

    cfg = _cfg(scene_pack["root"]).replace(streaming_chunk=4,
                                           config_name="streamrun")
    st = cluster_scene(cfg, SCENE, resume=False)
    assert st.status == "ok", st.error
    assert st.num_objects == 3
    assert "stream.total" in st.timings
    npz = os.path.join(scene_pack["root"], "prediction",
                       "streamrun_class_agnostic", f"{SCENE}.npz")
    assert os.path.exists(npz)


@pytest.mark.slow
def test_serve_stream_ops_end_to_end(tmp_path):
    """The live-scan serving flow: stream_chunk ops accumulate with
    per-chunk partial-instance statuses, stream_end exports, and the
    artifact matches a one-shot streaming run of the same scene."""
    from maskclustering_tpu.serve.client import ServeClient
    from maskclustering_tpu.serve.daemon import ServeDaemon

    root = str(tmp_path / "data")
    sock = str(tmp_path / "mct.sock")
    cfg = _cfg(root).replace(config_name="servedstream")
    daemon = ServeDaemon(cfg, socket_path=sock, capacity=8,
                         journal_dir=str(tmp_path / "journals"),
                         freeze_after_warm=False)
    daemon.start()
    syn = {"num_boxes": 3, "num_frames": FRAMES, "image_hw": [48, 64],
           "spacing": 0.05, "seed": 7}
    try:
        with ServeClient(sock, timeout_s=300.0) as c:
            final, chunk_events = c.stream_scene("live-a", chunk=4,
                                                 synthetic=syn)
            assert final["status"] == "ok", final
            assert final["num_objects"] == 3
            assert len(chunk_events) == 4
            assert [e["frames_done"] for e in chunk_events] == [4, 8, 12, 16]
            assert all(e["partial_instances"] > 0 for e in chunk_events)
            assert chunk_events[-1]["done"] is True
            # double-end answers a typed failure, not a daemon crash
            ev, _ = c.stream_end("live-a")
            assert ev["status"] == "failed"
            # a FAILED finalize must keep the session: the client simply
            # resends stream_end (the review-hardened pop-after-success)
            ev, _ = c.stream_chunk("live-b", chunk=8, synthetic=syn)
            assert ev["status"] == "ok"
            faults.set_plan(faults.FaultPlan.from_spec("fail:live-b.export"))
            ev, _ = c.stream_end("live-b")
            assert ev["status"] == "failed", ev
            faults.set_plan(None)
            ev, _ = c.stream_end("live-b")
            assert ev["status"] == "ok" and ev["num_objects"] >= 1, ev
            # the daemon still serves classic ops afterwards
            stats = c.stats()
            assert stats["counts"]["ok"] >= 7
        npz = os.path.join(root, "prediction",
                           "servedstream_class_agnostic", "live-a.npz")
        assert os.path.exists(npz)
    finally:
        daemon.request_stop()
        daemon.shutdown()
