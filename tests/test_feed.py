"""Compact host->device feed codec: lossless-or-fallback guarantees.

The codec may only engage when the uint16 round trip is bit-exact
(io/feed.py); these tests pin the engage/fallback decisions and prove the
association results are identical through either path.
"""

import numpy as np
import jax.numpy as jnp

from maskclustering_tpu.io.feed import (
    decode_depth,
    decode_seg,
    encode_depth,
    encode_seg,
    to_device_frames,
)


def _mm_depth(rng, shape, scale=1000.0):
    """Depth exactly as read_depth_png produces it from a uint16 PNG."""
    raw = rng.integers(0, 8000, size=shape).astype(np.uint16)
    return raw.astype(np.float32) * np.float32(1.0 / scale)


def test_depth_mm_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    d = _mm_depth(rng, (3, 24, 32))
    enc, scale = encode_depth(d)
    assert enc.dtype == np.uint16 and scale == 1000.0
    dec = np.asarray(decode_depth(jnp.asarray(enc), scale))
    np.testing.assert_array_equal(dec.view(np.uint32), d.view(np.uint32))  # bitwise


def test_depth_quarter_mm_uses_4000_scale():
    rng = np.random.default_rng(1)
    # odd quanta ensure the 1000-scale attempt cannot round-trip
    raw = (rng.integers(0, 8000, size=(2, 16, 16)) * 4 + 1).astype(np.uint16)
    d = raw.astype(np.float32) * np.float32(1.0 / 4000.0)
    enc, scale = encode_depth(d)
    assert scale == 4000.0
    dec = np.asarray(decode_depth(jnp.asarray(enc), scale))
    np.testing.assert_array_equal(dec, d)


def test_depth_noisy_falls_back_to_f32():
    rng = np.random.default_rng(2)
    d = rng.random((2, 8, 8)).astype(np.float32) * 3.0  # not mm-quantized
    enc, scale = encode_depth(d)
    assert scale == 0.0 and enc.dtype == np.float32
    np.testing.assert_array_equal(np.asarray(decode_depth(jnp.asarray(enc), scale)), d)


def test_depth_out_of_range_and_nonfinite_fall_back():
    big = np.full((1, 2, 2), 70.0, np.float32)  # 70 m -> 70000 mm > u16
    assert encode_depth(big)[1] == 0.0
    bad = np.array([[[np.nan, 1.0]]], np.float32)
    assert encode_depth(bad)[1] == 0.0


def test_seg_encoding():
    assert encode_seg(np.array([[0, 5, 65535]], np.int32)).dtype == np.uint16
    assert encode_seg(np.array([[0, 70000]], np.int32)).dtype == np.int32
    assert encode_seg(np.array([[-1, 3]], np.int32)).dtype == np.int32
    s = np.array([[1, 2], [3, 4]], np.int32)
    np.testing.assert_array_equal(
        np.asarray(decode_seg(jnp.asarray(encode_seg(s)))), s)


def test_association_identical_through_codec():
    """Full association on mm-quantized depth: codec path == f32 path."""
    from maskclustering_tpu.models.backprojection import associate_scene

    rng = np.random.default_rng(3)
    f, h, w, n = 3, 24, 32, 500
    depths = _mm_depth(rng, (f, h, w))
    segs = rng.integers(0, 4, size=(f, h, w)).astype(np.int32)
    intr = np.tile(np.array([[30.0, 0, 16], [0, 30.0, 12], [0, 0, 1]],
                            np.float32), (f, 1, 1))
    c2w = np.tile(np.eye(4, dtype=np.float32), (f, 1, 1))
    fv = np.ones(f, bool)
    pts = rng.random((n, 3)).astype(np.float32) * 2 - 1

    kw = dict(k_max=7, distance_threshold=0.05)
    a = associate_scene(jnp.asarray(pts), jnp.asarray(depths), jnp.asarray(segs),
                        jnp.asarray(intr), jnp.asarray(c2w), jnp.asarray(fv), **kw)
    d_dev, s_dev = to_device_frames(depths, segs)
    b = associate_scene(jnp.asarray(pts), d_dev, s_dev,
                        jnp.asarray(intr), jnp.asarray(c2w), jnp.asarray(fv), **kw)
    for name in ("mask_of_point", "first_id", "last_id", "mask_valid"):
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)), err_msg=name)


def test_codec_engages_through_padding_layer():
    """pad_scene_tensors must keep host frames host-side: an upstream jnp
    pad would upload f32 before the codec ever sees the arrays, silently
    disabling the compact feed on every bucketed (= every real) scene.
    """
    import dataclasses

    from maskclustering_tpu.models.pipeline import pad_scene_tensors
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    scene = make_scene(num_boxes=2, num_frames=5, image_hw=(24, 32), seed=9)
    t = to_scene_tensors(scene)
    dq = (np.rint(np.asarray(t.depths) * 1000).clip(0, 65535).astype(np.uint16)
          .astype(np.float32) * np.float32(0.001))
    t = dataclasses.replace(t, depths=dq,
                            segmentations=np.asarray(t.segmentations, np.int32))
    padded = pad_scene_tensors(t, f_pad=8, n_pad=t.num_points + 64)
    assert isinstance(padded.depths, np.ndarray)  # stayed host-side
    enc, scale = encode_depth(padded.depths)
    assert scale == 1000.0 and enc.dtype == np.uint16
    assert encode_seg(padded.segmentations).dtype == np.uint16


def test_fused_step_decodes_uint16_feed():
    """build_fused_step output must be identical for the uint16-mm feed and
    the equivalent f32 feed (the decode is the loader's exact f32 multiply).
    """
    from maskclustering_tpu.parallel import build_fused_step, fused_step_example_args
    from maskclustering_tpu.config import PipelineConfig

    cfg = PipelineConfig(config_name="t", dataset="demo", distance_threshold=0.06,
                         few_points_threshold=10, point_chunk=1024,
                         max_cluster_iterations=20)
    step = build_fused_step(None, cfg, k_max=7)
    args = list(fused_step_example_args(num_scenes=1, num_frames=6))
    # mm-quantize so both encodings describe the same f32 values
    dq16 = np.rint(args[1] * 1000).clip(0, 65535).astype(np.uint16)
    args[1] = dq16.astype(np.float32) * np.float32(0.001)
    a = step(*map(jnp.asarray, args))
    args_u16 = list(args)
    args_u16[1] = dq16
    args_u16[2] = args[2].astype(np.uint16)
    b = step(*map(jnp.asarray, args_u16))
    for name in ("assignment", "mask_active", "first_id", "last_id", "num_objects"):
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)), err_msg=name)


def test_pad_scene_batch_engages_codec():
    import dataclasses

    from maskclustering_tpu.parallel.batch import pad_scene_batch
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    scene = make_scene(num_boxes=2, num_frames=4, image_hw=(24, 32), seed=11)
    t = to_scene_tensors(scene)
    dq = (np.rint(np.asarray(t.depths) * 1000).clip(0, 65535).astype(np.uint16)
          .astype(np.float32) * np.float32(0.001))
    t = dataclasses.replace(t, depths=dq)
    _, depths, segs, _, _, _ = pad_scene_batch([t], f_pad=8, n_pad=t.num_points, num_scenes=1)
    assert depths.dtype == np.uint16
    assert segs.dtype == np.uint16
    # noisy depth falls back to f32
    t2 = to_scene_tensors(make_scene(num_boxes=2, num_frames=4, image_hw=(24, 32), seed=12))
    _, depths2, _, _, _, _ = pad_scene_batch([t2], f_pad=8, n_pad=t2.num_points, num_scenes=1)
    assert depths2.dtype == np.float32
