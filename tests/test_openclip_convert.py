"""open_clip -> HF CLIP state-dict conversion (VERDICT r5 Next #5).

The reference's exact checkpoint (ViT-H-14 laion2b_s32b_b79k) lands on disk
in the open_clip cache layout; ``find_local_clip_checkpoint`` detects it but
HFCLIPEncoder could not load it. These tests pin the converter on a tiny
RANDOM open_clip-layout fixture built by inverse-mapping a known HF CLIP
model: the round trip must reproduce the HF layout key-for-key and the
converted model's forward pass must match the original bitwise-close.
"""

import json
import os

import numpy as np
import pytest

from maskclustering_tpu.semantics.encoder import (
    convert_open_clip_state_dict,
    is_open_clip_layout,
)

VOCAB = ["l", "o", "w", "e", "r", "s", "t", "i", "d", "n",
         "lo", "l</w>", "w</w>", "r</w>", "t</w>",
         "low</w>", "er</w>", "lowest</w>", "newer</w>", "wider",
         "<unk>", "<|startoftext|>", "<|endoftext|>"]
MERGES = ["#version: 0.2", "l o", "lo w</w>", "e r</w>"]

# tiny geometry shared by every fixture in this module
WIDTH, LAYERS, HEADS, PATCH, IMAGE, PROJ, INTER = 32, 2, 4, 8, 32, 16, 64


def _tiny_hf_config():
    from transformers import CLIPConfig, CLIPTextConfig, CLIPVisionConfig

    return CLIPConfig.from_text_vision_configs(
        CLIPTextConfig(vocab_size=len(VOCAB), hidden_size=WIDTH,
                       intermediate_size=INTER, num_hidden_layers=LAYERS,
                       num_attention_heads=HEADS, max_position_embeddings=77,
                       projection_dim=PROJ),
        CLIPVisionConfig(hidden_size=WIDTH, intermediate_size=INTER,
                         num_hidden_layers=LAYERS, num_attention_heads=HEADS,
                         image_size=IMAGE, patch_size=PATCH,
                         projection_dim=PROJ),
        projection_dim=PROJ)


# inverse of the converter's per-block map — used to BUILD the open_clip
# fixture from a known HF model, so the test pins semantics, not just names
_BLOCK_INV = (
    ("layer_norm1.weight", "ln_1.weight"),
    ("layer_norm1.bias", "ln_1.bias"),
    ("self_attn.out_proj.weight", "attn.out_proj.weight"),
    ("self_attn.out_proj.bias", "attn.out_proj.bias"),
    ("layer_norm2.weight", "ln_2.weight"),
    ("layer_norm2.bias", "ln_2.bias"),
    ("mlp.fc1.weight", "mlp.c_fc.weight"),
    ("mlp.fc1.bias", "mlp.c_fc.bias"),
    ("mlp.fc2.weight", "mlp.c_proj.weight"),
    ("mlp.fc2.bias", "mlp.c_proj.bias"),
)


def _hf_to_open_clip(sd):
    """HF CLIPModel state dict (torch tensors) -> open_clip layout."""
    import torch

    out = {
        "visual.class_embedding": sd["vision_model.embeddings.class_embedding"],
        "visual.positional_embedding":
            sd["vision_model.embeddings.position_embedding.weight"],
        "visual.conv1.weight": sd["vision_model.embeddings.patch_embedding.weight"],
        "visual.ln_pre.weight": sd["vision_model.pre_layrnorm.weight"],
        "visual.ln_pre.bias": sd["vision_model.pre_layrnorm.bias"],
        "visual.ln_post.weight": sd["vision_model.post_layernorm.weight"],
        "visual.ln_post.bias": sd["vision_model.post_layernorm.bias"],
        "visual.proj": sd["visual_projection.weight"].t().contiguous(),
        "token_embedding.weight": sd["text_model.embeddings.token_embedding.weight"],
        "positional_embedding":
            sd["text_model.embeddings.position_embedding.weight"],
        "ln_final.weight": sd["text_model.final_layer_norm.weight"],
        "ln_final.bias": sd["text_model.final_layer_norm.bias"],
        "text_projection": sd["text_projection.weight"].t().contiguous(),
        "logit_scale": sd["logit_scale"],
        "attn_mask": torch.zeros(2, 2),  # derived buffer: must be ignored
    }
    for tower, oc_root in (("vision_model", "visual.transformer"),
                           ("text_model", "transformer")):
        for i in range(LAYERS):
            hf = f"{tower}.encoder.layers.{i}."
            oc = f"{oc_root}.resblocks.{i}."
            for hf_name, oc_name in _BLOCK_INV:
                out[oc + oc_name] = sd[hf + hf_name]
            out[oc + "attn.in_proj_weight"] = torch.cat(
                [sd[hf + f"self_attn.{p}.weight"] for p in ("q_proj", "k_proj", "v_proj")])
            out[oc + "attn.in_proj_bias"] = torch.cat(
                [sd[hf + f"self_attn.{p}.bias"] for p in ("q_proj", "k_proj", "v_proj")])
    return out


@pytest.fixture(scope="module")
def open_clip_dir(tmp_path_factory):
    """Tiny random open_clip-layout checkpoint dir + the HF original.

    Built from a seeded HF CLIPModel so the expected outputs are known;
    tokenizer/processor files ride along (the fixture mirrors what a user
    must place beside the reference's downloaded weights).
    """
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    d = tmp_path_factory.mktemp("open_clip_ckpt")
    torch.manual_seed(0)
    model = transformers.CLIPModel(_tiny_hf_config())
    torch.save(_hf_to_open_clip(model.state_dict()),
               os.path.join(d, "open_clip_pytorch_model.bin"))
    with open(os.path.join(d, "open_clip_config.json"), "w") as f:
        json.dump({"model_cfg": {
            "embed_dim": PROJ,
            # the HF fixture model uses CLIPConfig's default quick_gelu,
            # so the open_clip config must declare it (laion checkpoints
            # omit it and get exact GeLU — covered by the converter test)
            "quick_gelu": True,
            "vision_cfg": {"image_size": IMAGE, "patch_size": PATCH,
                           "layers": LAYERS, "width": WIDTH,
                           "head_width": WIDTH // HEADS},
            "text_cfg": {"context_length": 77, "vocab_size": len(VOCAB),
                         "width": WIDTH, "heads": HEADS, "layers": LAYERS},
        }}, f)
    # tokenizer + image processor (weight-independent companion files)
    vocab_file = d / "vocab.json"
    merges_file = d / "merges.txt"
    vocab_file.write_text(json.dumps({tok: i for i, tok in enumerate(VOCAB)}))
    merges_file.write_text("\n".join(MERGES))
    transformers.CLIPTokenizer(str(vocab_file), str(merges_file)).save_pretrained(str(d))
    transformers.CLIPImageProcessor(
        size={"shortest_edge": IMAGE},
        crop_size={"height": IMAGE, "width": IMAGE}).save_pretrained(str(d))
    return str(d), model


def test_convert_pure_numpy_key_mapping():
    """The converter itself is torch-free: a numpy open_clip-layout dict
    maps to the exact HF key set with the q/k/v split and transposes."""
    rng = np.random.default_rng(3)
    sd = {
        "visual.class_embedding": rng.standard_normal((WIDTH,)).astype(np.float32),
        "visual.positional_embedding":
            rng.standard_normal(((IMAGE // PATCH) ** 2 + 1, WIDTH)).astype(np.float32),
        "visual.conv1.weight":
            rng.standard_normal((WIDTH, 3, PATCH, PATCH)).astype(np.float32),
        "visual.ln_pre.weight": np.ones(WIDTH, np.float32),
        "visual.ln_pre.bias": np.zeros(WIDTH, np.float32),
        "visual.ln_post.weight": np.ones(WIDTH, np.float32),
        "visual.ln_post.bias": np.zeros(WIDTH, np.float32),
        "visual.proj": rng.standard_normal((WIDTH, PROJ)).astype(np.float32),
        "token_embedding.weight":
            rng.standard_normal((len(VOCAB), WIDTH)).astype(np.float32),
        "positional_embedding": rng.standard_normal((77, WIDTH)).astype(np.float32),
        "ln_final.weight": np.ones(WIDTH, np.float32),
        "ln_final.bias": np.zeros(WIDTH, np.float32),
        "text_projection": rng.standard_normal((WIDTH, PROJ)).astype(np.float32),
        "logit_scale": np.float32(2.6593),
    }
    for oc_root in ("visual.transformer", "transformer"):
        for i in range(LAYERS):
            p = f"{oc_root}.resblocks.{i}."
            sd[p + "attn.in_proj_weight"] = \
                rng.standard_normal((3 * WIDTH, WIDTH)).astype(np.float32)
            sd[p + "attn.in_proj_bias"] = \
                rng.standard_normal((3 * WIDTH,)).astype(np.float32)
            for _, oc_name in _BLOCK_INV:
                shape = {"mlp.c_fc.weight": (INTER, WIDTH),
                         "mlp.c_fc.bias": (INTER,),
                         "mlp.c_proj.weight": (WIDTH, INTER)}.get(
                             oc_name, (WIDTH, WIDTH) if oc_name.endswith("weight")
                             and "ln" not in oc_name else (WIDTH,))
                sd[p + oc_name] = rng.standard_normal(shape).astype(np.float32)

    out = convert_open_clip_state_dict(sd)
    # q/k/v split: rows of in_proj in order
    inp = sd["visual.transformer.resblocks.0.attn.in_proj_weight"]
    np.testing.assert_array_equal(
        out["vision_model.encoder.layers.0.self_attn.q_proj.weight"], inp[:WIDTH])
    np.testing.assert_array_equal(
        out["vision_model.encoder.layers.0.self_attn.v_proj.weight"], inp[2 * WIDTH:])
    # projections transpose into Linear convention
    np.testing.assert_array_equal(out["visual_projection.weight"],
                                  sd["visual.proj"].T)
    np.testing.assert_array_equal(out["text_projection.weight"],
                                  sd["text_projection"].T)
    # the full HF key set and nothing else (position_ids are derived buffers)
    transformers = pytest.importorskip("transformers")
    want = {k for k in transformers.CLIPModel(_tiny_hf_config()).state_dict()
            if not k.endswith("position_ids")}
    assert set(out) == want

    # config derivation: widths/depths/intermediates come from the weights;
    # activation follows open_clip semantics (exact GeLU unless the config
    # opts into OpenAI's quick_gelu — laion checkpoints omit the flag)
    from maskclustering_tpu.semantics.encoder import hf_clip_config_from_open_clip

    cfg = hf_clip_config_from_open_clip(
        {"model_cfg": {"embed_dim": PROJ,
                       "vision_cfg": {"head_width": WIDTH // HEADS},
                       "text_cfg": {"heads": HEADS}}}, sd)
    assert cfg.vision_config.hidden_act == "gelu"
    assert cfg.text_config.hidden_act == "gelu"
    assert cfg.vision_config.hidden_size == WIDTH
    assert cfg.vision_config.intermediate_size == INTER
    assert cfg.vision_config.num_attention_heads == HEADS
    assert cfg.text_config.num_hidden_layers == LAYERS
    cfg_q = hf_clip_config_from_open_clip(
        {"model_cfg": {"embed_dim": PROJ, "quick_gelu": True}}, sd)
    assert cfg_q.vision_config.hidden_act == "quick_gelu"


def test_unknown_keys_raise():
    with pytest.raises((ValueError, KeyError)):
        convert_open_clip_state_dict({"visual.unknown_thing": np.zeros(3)})


def test_custom_text_clip_prefix_normalizes():
    """The CustomTextCLIP variant nests the text tower under 'text.'; both
    the converter and the config deriver must see through it."""
    from maskclustering_tpu.semantics.encoder import _strip_text_prefix

    sd = {"text.token_embedding.weight": np.zeros((5, 4)),
          "text.transformer.resblocks.0.ln_1.weight": np.ones(4),
          "visual.conv1.weight": np.zeros((4, 3, 2, 2)),
          "logit_scale": np.float32(1.0)}
    out = _strip_text_prefix(sd)
    assert set(out) == {"token_embedding.weight",
                        "transformer.resblocks.0.ln_1.weight",
                        "visual.conv1.weight", "logit_scale"}


def test_loaded_checkpoint_matches_original_forward(open_clip_dir):
    """load_open_clip_checkpoint reproduces the original model's features
    exactly — the conversion is semantic, not just a renaming."""
    torch = pytest.importorskip("torch")
    from maskclustering_tpu.semantics.encoder import load_open_clip_checkpoint

    path, original = open_clip_dir
    assert is_open_clip_layout(path)
    model = load_open_clip_checkpoint(path)

    torch.manual_seed(1)
    pixels = torch.randn(2, 3, IMAGE, IMAGE)
    ids = torch.tensor([[22, 15, 16, 21], [22, 17, 13, 21]])
    with torch.no_grad():
        a_img = original.get_image_features(pixel_values=pixels)
        b_img = model.get_image_features(pixel_values=pixels)
        a_txt = original.get_text_features(input_ids=ids)
        b_txt = model.get_text_features(input_ids=ids)
    np.testing.assert_allclose(a_img.numpy(), b_img.numpy(), atol=1e-6)
    np.testing.assert_allclose(a_txt.numpy(), b_txt.numpy(), atol=1e-6)


def test_hfclip_encoder_serves_open_clip_layout(open_clip_dir):
    """HFCLIPEncoder transparently loads the open_clip cache layout — the
    exact deployment shape of the reference's ViT-H-14 checkpoint."""
    pytest.importorskip("torch")
    from maskclustering_tpu.semantics import HFCLIPEncoder

    path, _ = open_clip_dir
    enc = HFCLIPEncoder(path)
    assert enc.feature_dim == PROJ
    rng = np.random.default_rng(0)
    imgs = [rng.integers(0, 255, (40, 52, 3), dtype=np.uint8) for _ in range(2)]
    feats = enc.encode_images(imgs)
    assert feats.shape == (2, PROJ)
    np.testing.assert_allclose(np.linalg.norm(feats, axis=1), 1.0, rtol=1e-5)
    tfeats = enc.encode_texts(["lower", "wider"])
    assert tfeats.shape == (2, PROJ)
    np.testing.assert_allclose(np.linalg.norm(tfeats, axis=1), 1.0, rtol=1e-5)
