import jax.numpy as jnp
import numpy as np
import pytest

from maskclustering_tpu.models.backprojection import associate_scene
from maskclustering_tpu.models.graph import build_mask_table, compute_graph_stats, observer_schedule
from tests.oracles import oracle_graph_stats, oracle_observer_thresholds
from maskclustering_tpu.utils.synthetic import make_scene

DT = 0.03
K_MAX = 15


@pytest.fixture(scope="module")
def assoc_and_scene():
    scene = make_scene(num_boxes=4, num_frames=8, seed=7)
    out = associate_scene(
        jnp.asarray(scene.scene_points),
        jnp.asarray(scene.depths),
        jnp.asarray(scene.segmentations),
        jnp.asarray(scene.intrinsics),
        jnp.asarray(scene.cam_to_world),
        jnp.asarray(scene.frame_valid),
        k_max=K_MAX, window=1, distance_threshold=DT,
        few_points_threshold=25, coverage_threshold=0.3,
    )
    return scene, out


def _mask_sets_from_assoc(first, last, mask_valid):
    """Per-mask point sets incl. boundary points, from the claim tensors."""
    sets = {}
    f_num = first.shape[0]
    for f in range(f_num):
        for k in np.nonzero(mask_valid[f])[0]:
            pts = set(np.nonzero(first[f] == k)[0].tolist())
            pts |= set(np.nonzero(last[f] == k)[0].tolist())
            if pts:
                sets[(f, int(k))] = pts
    return sets


THRESH = dict(mask_visible_threshold=0.3, contained_threshold=0.8,
              undersegment_filter_threshold=0.3, big_mask_point_count=500)


def test_graph_stats_match_oracle(assoc_and_scene):
    scene, out = assoc_and_scene
    first = np.asarray(out.first_id)
    last = np.asarray(out.last_id)
    mop = np.asarray(out.mask_of_point)
    boundary = set(np.nonzero(np.asarray(out.boundary))[0].tolist())
    mask_valid = np.asarray(out.mask_valid)

    mask_sets = _mask_sets_from_assoc(first, last, mask_valid)
    o_masks, o_visible, o_contained, o_under = oracle_graph_stats(
        mop, mask_sets, boundary, **THRESH)

    table = build_mask_table(mask_valid, pad_multiple=64)
    # table must enumerate the same masks in the same (frame, id) order
    got_masks = list(zip(table.frame[: table.num_masks].tolist(),
                         table.mask_id[: table.num_masks].tolist()))
    assert got_masks == o_masks

    stats = compute_graph_stats(
        jnp.asarray(mop), jnp.asarray(out.boundary),
        jnp.asarray(table.frame), jnp.asarray(table.mask_id), jnp.asarray(table.valid),
        k_max=K_MAX, point_chunk=1024, **THRESH)

    m = table.num_masks
    np.testing.assert_array_equal(np.asarray(stats.undersegment)[:m], o_under)
    np.testing.assert_array_equal(np.asarray(stats.visible)[:m], o_visible)
    np.testing.assert_array_equal(np.asarray(stats.contained)[:m, :m], o_contained)
    # padding rows must stay silent
    assert not np.asarray(stats.visible)[m:].any()
    assert not np.asarray(stats.contained)[m:].any()
    assert not np.asarray(stats.undersegment)[m:].any()

    # n_tot = |mask set minus boundary|
    for mi, mk in enumerate(o_masks):
        expect = len(mask_sets[mk] - boundary)
        assert int(np.asarray(stats.n_tot)[mi]) == expect

    # observer percentile schedule matches np.percentile (f64) exactly
    o_thresholds = oracle_observer_thresholds(o_visible)
    sched = observer_schedule(stats.observer_hist)
    np.testing.assert_allclose(sched[: len(o_thresholds)],
                               np.asarray(o_thresholds, dtype=np.float32), rtol=0)
    assert np.isinf(sched[len(o_thresholds):]).all()


def test_graph_stats_random_claims():
    """Adversarial random claim matrices (not geometrically consistent)."""
    rng = np.random.default_rng(11)
    f_num, n, kk = 6, 400, 5
    for trial in range(3):
        first = np.zeros((f_num, n), dtype=np.int32)
        last = np.zeros((f_num, n), dtype=np.int32)
        for f in range(f_num):
            claims = rng.integers(0, kk + 1, size=n)
            second = np.where((rng.random(n) < 0.15) & (claims > 0),
                              rng.integers(1, kk + 1, size=n), claims)
            first[f] = np.minimum(claims, second)
            last[f] = np.maximum(claims, second)
        mop = np.where(first == last, first, 0)
        boundary_arr = (first != last).any(axis=0)
        boundary = set(np.nonzero(boundary_arr)[0].tolist())
        mask_valid = np.zeros((f_num, K_MAX + 1), dtype=bool)
        for f in range(f_num):
            for k in range(1, kk + 1):
                mask_valid[f, k] = ((first[f] == k) | (last[f] == k)).sum() >= 5

        # zero claims of invalid masks the way associate_frame would
        for f in range(f_num):
            inv_f = ~mask_valid[f]
            kill_first = inv_f[first[f]]
            kill_last = inv_f[last[f]]
            nf = np.where(kill_first, np.where(kill_last, 0, last[f]), first[f])
            nl = np.where(kill_last, np.where(kill_first, 0, first[f]), last[f])
            first[f], last[f] = nf, nl
        mop = np.where((first == last), first, 0)
        boundary_arr = ((first != last) & (first > 0)).any(axis=0)
        boundary = set(np.nonzero(boundary_arr)[0].tolist())

        mask_sets = _mask_sets_from_assoc(first, last, mask_valid)
        if not mask_sets:
            continue
        o_masks, o_visible, o_contained, o_under = oracle_graph_stats(
            mop, mask_sets, boundary, **THRESH)
        table = build_mask_table(mask_valid, pad_multiple=64)
        stats = compute_graph_stats(
            jnp.asarray(mop), jnp.asarray(boundary_arr),
            jnp.asarray(table.frame), jnp.asarray(table.mask_id), jnp.asarray(table.valid),
            k_max=K_MAX, point_chunk=128, **THRESH)
        m = table.num_masks
        np.testing.assert_array_equal(np.asarray(stats.undersegment)[:m], o_under, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(np.asarray(stats.visible)[:m], o_visible, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(np.asarray(stats.contained)[:m, :m], o_contained, err_msg=f"trial {trial}")


def test_observer_schedule_device_matches_host():
    """Device (f32 + exact integer ranks) vs host (f64) schedule parity,
    and both against np.percentile over the expanded distribution."""
    import jax.numpy as jnp

    from maskclustering_tpu.models.graph import observer_schedule, observer_schedule_device
    from tests.oracles import oracle_observer_thresholds_from_counts

    rng = np.random.default_rng(11)
    for trial in range(6):
        m2 = int(rng.integers(50, 4000))
        n_zero = int(rng.integers(0, m2 // 2))
        counts = np.concatenate([
            np.zeros(n_zero, np.int64),
            rng.integers(1, 40, size=m2 - n_zero)])
        hist = np.bincount(counts, minlength=41)
        host = observer_schedule(hist)
        dev = np.asarray(observer_schedule_device(jnp.asarray(hist, jnp.int32)))
        finite = np.isfinite(host)
        assert (np.isfinite(dev) == finite).all(), (trial, host, dev)
        np.testing.assert_allclose(dev[finite], host[finite], rtol=1e-6)
        # host vs literal np.percentile over the positive counts
        want = oracle_observer_thresholds_from_counts(counts)
        np.testing.assert_allclose(host[: len(want)], np.asarray(want, np.float32),
                                   rtol=0)
        assert np.isinf(host[len(want):]).all()


def test_observer_schedule_edge_cases():
    """cnt_pos == 0 -> all-inf; all-ones counts terminate at q=45 with 1.0
    entries down to q=50 (reference construction.py:86-94 break rule)."""
    import jax.numpy as jnp

    from maskclustering_tpu.models.graph import observer_schedule, observer_schedule_device

    # no positive observers at all: every iteration must be inert
    empty = np.zeros(11, np.int64)
    empty[0] = 500
    host = observer_schedule(empty)
    dev = np.asarray(observer_schedule_device(jnp.asarray(empty, jnp.int32)))
    assert np.isinf(host).all() and np.isinf(dev).all()

    # every positive count is exactly 1: percentiles 95..50 clamp to 1.0,
    # then the q=45 entry (<= 1 and percentile < 50) terminates the schedule
    ones = np.zeros(3, np.int64)
    ones[0], ones[1] = 40, 60
    host = observer_schedule(ones)
    dev = np.asarray(observer_schedule_device(jnp.asarray(ones, jnp.int32)))
    want_len = len(range(95, 45, -5))  # 95..50 inclusive
    assert (host[:want_len] == 1.0).all() and np.isinf(host[want_len:]).all()
    np.testing.assert_array_equal(np.isinf(dev), np.isinf(host))
    np.testing.assert_allclose(dev[:want_len], host[:want_len])

    # histogram shorter than any padding assumptions: single bin value
    single = np.array([0, 0, 0, 7], np.int64)  # seven pairs all at count 3
    host = observer_schedule(single)
    assert (host[: len(range(95, -5, -5))] == 3.0).all()
