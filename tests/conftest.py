"""Test environment: force an 8-virtual-device CPU mesh.

All device-code tests run on a CPU mesh standing in for a TPU slice; the
same pjit/shard_map code paths compile identically (SURVEY.md §4's
CPU-device test strategy).

Note: the environment preloads jax with a TPU ('axon') platform via
sitecustomize, so JAX_PLATFORMS set here is too late — the platform must be
switched through jax.config before any backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _hermetic_perf_ledger(tmp_path, monkeypatch):
    """bench.py / run.py append perf-ledger rows by DEFAULT; tests must not
    grow the repo's PERF_LEDGER.jsonl, so every test gets a throwaway one
    (subprocess-based tests inherit it through the environment)."""
    monkeypatch.setenv("MCT_PERF_LEDGER", str(tmp_path / "perf_ledger.jsonl"))


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def fused_lattice_aot():
    """ONE AOT sweep of the fused step over the divisor lattice of 8
    (plus the canonical point-sharded cell), at the analyzer's shape.

    test_cost.py (collective/dot census assertions), test_analysis.py
    (the IR invariant gate), test_retrace.py (the surface census) and
    test_point_sharding.py used to each perform their own fused-step
    lowering+compile sweep; session-scoping the sweep here pays the
    compiles once per tier-1 run. ``keep_texts`` attaches the StableHLO /
    optimized-HLO text per row so ``analyze_ir(lowerings=...)`` reads the
    same programs the cost rows describe.
    """
    from maskclustering_tpu.analysis.ir_checks import (
        CANONICAL_SHAPE,
        FULL_LATTICE,
    )
    from maskclustering_tpu.obs.cost import observe_costs

    rows = observe_costs(FULL_LATTICE, stages=("fused",), keep_texts=True,
                         **CANONICAL_SHAPE)
    assert len(rows) == len(FULL_LATTICE), \
        "every lattice mesh must fit the 8 devices"
    return {tuple(r["mesh"]): r for r in rows}
