"""Device postprocess vs host postprocess: byte-identical artifacts.

The device path (models/postprocess_device.py) consumes the (F, N) claim
planes in HBM — grid-DBSCAN split, group structures, mask assignment and
the merge intersection counts all run on device, and only the emit-only
drain (surviving objects' bit-packed planes + O(M+S) scalars) crosses to
host. It must reproduce the host path (models/postprocess.py) exactly —
same objects, same point ids, same mask lists in the same order — because
both implement reference utils/post_process.py:40-170 semantics.

Budget note: pipeline-running tests here use spacing-0.04/0.05 synthetic
clouds (10-16k points) — the CPU cost of the grid-DBSCAN pack pass scales
with cloud density, and the full-density (63k) identity run plus the mesh
lattice sweep are slow-marked.
"""

import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.pipeline import run_scene
from maskclustering_tpu.models.postprocess_device import _pack_bits, _unpack_bits
from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors


def _config(**kw):
    return PipelineConfig(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.03, step=1, mask_pad_multiple=64,
        point_chunk=2048, **kw,
    )


def test_pack_unpack_roundtrip(rng):
    for n in (8, 13, 256, 1000):
        x = rng.random((4, n)) < 0.3
        packed = np.asarray(_pack_bits(x))
        assert packed.shape == (4, -(-n // 8))
        np.testing.assert_array_equal(_unpack_bits(packed, n), x)


@pytest.fixture(scope="module")
def mid_density_pair():
    """ONE mid-density parity scene, run through BOTH postprocess paths.

    Tier-1 wall budget (ISSUE-9 reclaim): the mid-density parity variants
    used to run four pipelines across two parametrized cases (~14 s);
    this module-scoped fixture pays the (seed 21, 4 boxes) pair once and
    the parity + chunked-drain tests below read it. The second variant
    (seed 5, 6 boxes) is slow-marked with the full-density run.
    spacing 0.04: ~16k-point clouds keep real DBSCAN structure (~20
    in-eps neighbors at eps 0.1) at 1/4 the full-density cloud.
    """
    scene = make_scene(num_boxes=4, num_frames=10, seed=21, spacing=0.04)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(tensors, _config(device_postprocess=False), k_max=15)
    res_dev = run_scene(tensors, _config(device_postprocess=True), k_max=15)
    return {"tensors": tensors, "host": res_host, "device": res_dev}


def _assert_objects_identical(oh, od):
    assert len(oh.point_ids_list) == len(od.point_ids_list)
    assert oh.num_points == od.num_points
    for ph, pd in zip(oh.point_ids_list, od.point_ids_list):
        # exact order too: both paths emit ascending ids, and object_dict.npy
        # serializes them in emission order (byte-identity contract)
        np.testing.assert_array_equal(ph, pd)
    assert oh.mask_list == od.mask_list


def test_device_matches_host_postprocess(mid_density_pair):
    _assert_objects_identical(mid_density_pair["host"].objects,
                              mid_density_pair["device"].objects)


@pytest.mark.slow
def test_device_matches_host_postprocess_second_variant():
    """The (seed 5, 6 boxes) parity variant — slow tier with the
    full-density run; tier-1 keeps the fixture pair + chunk fallbacks."""
    scene = make_scene(num_boxes=6, num_frames=10, seed=5, spacing=0.04)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(tensors, _config(device_postprocess=False), k_max=15)
    res_dev = run_scene(tensors, _config(device_postprocess=True), k_max=15)
    _assert_objects_identical(res_host.objects, res_dev.objects)


@pytest.mark.parametrize("num_frames,fpm,expect_chunk", [
    (3, 1, 1),   # F_pad 3 -> odd, chunk falls to 1
    (6, 1, 2),   # F_pad 6 -> chunk 2
    (12, 4, 4),  # F_pad 12 -> chunk 4
])
def test_frame_chunk_selection(num_frames, fpm, expect_chunk):
    from maskclustering_tpu.models.pipeline import bucket_size
    from maskclustering_tpu.models.postprocess_device import _frame_chunk

    assert _frame_chunk(bucket_size(num_frames, fpm)) == expect_chunk


@pytest.mark.parametrize("num_frames,fpm", [
    (3, 1),   # chunk 1: the degenerate scan
    # the chunk-2 (6, 1) and chunk-4 (12, 4) pipeline runs live in the
    # slow tier — the selection unit above still pins every divisor, the
    # degenerate chunk-1 run plus the default chunk-8 path (exercised by
    # every other pipeline test) bracket them (tier-1 wall reclaim,
    # ISSUE-9)
])
def test_device_postprocess_chunk_fallbacks(num_frames, fpm):
    """Byte-identity must hold on every frame-chunk divisor of the claims
    scan (8/4/2/1), not just the default-padded chunk=8 path."""
    scene = make_scene(num_boxes=3, num_frames=num_frames, seed=11,
                       spacing=0.04)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(
        tensors, _config(device_postprocess=False, frame_pad_multiple=fpm),
        k_max=15)
    res_dev = run_scene(
        tensors, _config(device_postprocess=True, frame_pad_multiple=fpm),
        k_max=15)
    assert len(res_host.objects.point_ids_list) == len(res_dev.objects.point_ids_list)
    for ph, pd in zip(res_host.objects.point_ids_list,
                      res_dev.objects.point_ids_list):
        np.testing.assert_array_equal(ph, pd)
    assert res_host.objects.mask_list == res_dev.objects.mask_list


@pytest.mark.slow
@pytest.mark.parametrize("num_frames,fpm", [(6, 1), (12, 4)])
def test_device_postprocess_chunk_variants_slow(num_frames, fpm):
    """The chunk-2 and chunk-4 pipeline identities — slow tier."""
    scene = make_scene(num_boxes=3, num_frames=num_frames, seed=11,
                       spacing=0.04)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(
        tensors, _config(device_postprocess=False, frame_pad_multiple=fpm),
        k_max=15)
    res_dev = run_scene(
        tensors, _config(device_postprocess=True, frame_pad_multiple=fpm),
        k_max=15)
    _assert_objects_identical(res_host.objects, res_dev.objects)


def test_device_postprocess_empty_scene():
    """A scene with no recoverable masks yields an empty object list."""
    scene = make_scene(num_boxes=2, num_frames=4, seed=3, spacing=0.04)
    tensors = to_scene_tensors(scene)
    # zero out every segmentation -> no masks -> no live reps
    import dataclasses

    tensors = dataclasses.replace(
        tensors, segmentations=np.zeros_like(tensors.segmentations))
    res = run_scene(tensors, _config(device_postprocess=True), k_max=15)
    assert res.objects.point_ids_list == []
    assert res.objects.mask_list == []


def test_node_stats_kernel_dedupes_same_rep_claims():
    """num counts one (rep, point, frame) triple even when two DIFFERENT
    masks of the same representative claim one (frame, point) cell — the
    matmul formulation subtracts the duplicate via a one-hot correction,
    and id 0 (= no claim) must contribute nothing.
    """
    import jax.numpy as jnp

    from maskclustering_tpu.models.postprocess_device import _node_stats_kernel

    f, n, k2, r_pad = 3, 16, 6, 8
    first = np.zeros((f, n), np.int32)
    last = np.zeros((f, n), np.int32)
    # masks: frame 0 has ids 1, 2 (both rep 0) and 3 (rep 1); frame 1 has 1 (rep 0)
    rep_tab = np.full((f, k2), -1, np.int32)
    rep_tab[0, 1] = rep_tab[0, 2] = 0
    rep_tab[0, 3] = 1
    rep_tab[1, 1] = 0

    first[0, 0], last[0, 0] = 1, 2  # same rep twice -> ONE triple for rep 0
    first[0, 1], last[0, 1] = 1, 3  # reps 0 and 1 -> one triple each
    first[0, 2], last[0, 2] = 2, 2  # a == b -> one triple for rep 0
    first[1, 0], last[1, 0] = 1, 1  # second frame claim on point 0
    first[2, 5], last[2, 5] = 4, 4  # id with no rep mapping -> nothing

    m_pad = 4
    node_visible = np.zeros((m_pad, f), bool)
    node_visible[0, :2] = True  # rep slot 0 visible in frames 0, 1
    node_visible[1, 0] = True  # rep slot 1 visible in frame 0
    live_slots = np.zeros(r_pad, np.int32)
    live_slots[:2] = [0, 1]
    live_valid = np.zeros(r_pad, bool)
    live_valid[:2] = True

    claimed_d, ratio_d, nv_rep = _node_stats_kernel(
        jnp.asarray(first), jnp.asarray(last), jnp.asarray(rep_tab),
        jnp.asarray(node_visible), jnp.asarray(live_slots),
        jnp.asarray(live_valid), r_pad=r_pad, point_filter_threshold=0.5)
    claimed = np.asarray(claimed_d)

    want_claimed = np.zeros((r_pad, n), bool)
    want_claimed[0, [0, 1, 2]] = True  # rep 0 claims points 0 (x2 frames), 1, 2
    want_claimed[1, 1] = True  # rep 1 claims point 1
    np.testing.assert_array_equal(claimed, want_claimed)

    # ratio numerator must count point 0 / rep 0 as 1 triple in frame 0 plus
    # 1 in frame 1 = 2; denominator = 2 visible frames -> ratio 1.0 > 0.5
    ratio_ok = np.asarray(ratio_d)
    assert ratio_ok[0, 0] and ratio_ok[0, 1] and ratio_ok[0, 2]
    assert ratio_ok[1, 1]
    assert not ratio_ok[0, 5] and not ratio_ok[1, 5]

    # discriminating threshold: a failed dedupe would give point 0 / rep 0
    # num = 3 over den = 2 (ratio 1.5 > 1.25); the correct unique-triple
    # count gives exactly 1.0, which must NOT pass
    _, ratio_hi_d, _ = _node_stats_kernel(
        jnp.asarray(first), jnp.asarray(last), jnp.asarray(rep_tab),
        jnp.asarray(node_visible), jnp.asarray(live_slots),
        jnp.asarray(live_valid), r_pad=r_pad, point_filter_threshold=1.25)
    assert not np.asarray(ratio_hi_d)[0, 0]


def test_chunked_claims_pull_identity(mid_density_pair):
    """The chunked double-buffered bit-plane drain (claims_pull_chunk)
    reproduces the other chunkings byte-for-byte — 1-row chunks are the
    adversarial maximum (every live rep drains as its own slice), compared
    against the module fixture's default-chunk (64) device run; the
    chunk-0 single-blocking-pull leg is covered by test_row_chunks below
    plus the fixture's host path."""
    res_many = run_scene(mid_density_pair["tensors"],
                         _config(claims_pull_chunk=1), k_max=15)
    _assert_objects_identical(mid_density_pair["device"].objects,
                              res_many.objects)


def test_row_chunks_cover_exactly():
    """_row_chunks slices [0, rows) with no gap/overlap at any chunk size."""
    import jax.numpy as jnp

    from maskclustering_tpu.models.postprocess_device import _row_chunks

    arr = jnp.arange(44 * 3).reshape(44, 3)
    for rows in (1, 7, 44):
        for chunk in (0, 1, 5, 44, 100):
            chunks = _row_chunks(arr, rows, chunk)
            got = np.concatenate([np.asarray(c) for c in chunks], axis=0)
            np.testing.assert_array_equal(got, np.asarray(arr[:rows]))
            if chunk > 0:
                assert all(c.shape[0] <= chunk for c in chunks)


# ---------------------------------------------------------------------------
# grid DBSCAN (ops/grid_dbscan.py): device split vs the host dispatch
# ---------------------------------------------------------------------------


def test_grid_dbscan_matches_host_dispatch():
    """The device voxel-grid kernel reproduces the host DBSCAN dispatch
    (ops/dbscan.dbscan_labels — native C++ or sklearn) label-for-label:
    same cluster numbering (ascending min core point index), same border
    attachment, same noise, per instance row."""
    from maskclustering_tpu.ops.dbscan import dbscan_labels
    from maskclustering_tpu.ops.grid_dbscan import (
        build_grid, grid_dbscan_reference)

    for seed, n, eps, min_pts in [(0, 400, 0.25, 4), (1, 700, 0.15, 6),
                                  (2, 150, 0.4, 3), (3, 500, 0.08, 2)]:
        r = np.random.default_rng(seed)
        pts = (r.random((n, 3)) * 2.0).astype(np.float32)
        valid = r.random((5, n)) < 0.35
        valid[4] = False  # an empty instance row must stay all-noise
        grid = build_grid(pts, eps)
        out = grid_dbscan_reference(pts, valid, grid, neighbor_cap=512,
                                    eps=eps, min_points=min_pts)
        for row in range(5):
            ids = np.nonzero(valid[row])[0]
            if len(ids):
                np.testing.assert_array_equal(
                    out[row][ids], dbscan_labels(pts[ids], eps, min_pts),
                    err_msg=f"seed={seed} row={row}")
            assert np.all(out[row][~valid[row]] == -1)


def test_build_grid_excludes_sentinel_pads():
    """Shape-bucket pad points share ONE sentinel coordinate; binning them
    would put the whole pad run in a single voxel and blow the static
    candidate window (cell_cap) up by orders of magnitude. n_real keeps
    them out of the grid entirely."""
    from maskclustering_tpu.ops.grid_dbscan import build_grid

    r = np.random.default_rng(0)
    real = (r.random((500, 3)) * 3.0).astype(np.float32)
    padded = np.concatenate(
        [real, np.full((2000, 3), -100.0, np.float32)], axis=0)
    g_pad = build_grid(padded, 0.25)
    g_real = build_grid(padded, 0.25, n_real=500)
    assert g_pad.cell_cap >= 2000  # the pad voxel dominates
    assert g_real.cell_cap == build_grid(real, 0.25).cell_cap
    assert len(g_real.order) == 500
    np.testing.assert_array_equal(g_real.start, build_grid(real, 0.25).start)


def test_merge_from_counts_matches_set_merge():
    """The device-counted merge replays the reference's greedy suppression
    over precomputed intersection integers — same survivors, same order,
    as the frozenset loop, including the first-passing-test-wins
    asymmetry."""
    from maskclustering_tpu.models.postprocess import (
        _merge_overlapping, merge_from_counts)

    r = np.random.default_rng(7)
    for trial in range(8):
        num = int(r.integers(2, 9))
        pool = np.arange(300)
        point_ids, bboxes, masks = [], [], []
        for i in range(num):
            k = int(r.integers(5, 120))
            ids = np.sort(r.choice(pool, size=k, replace=False)).astype(np.int32)
            point_ids.append(ids)
            # coordinates proportional to ids so heavy point overlap =>
            # overlapping bboxes (and disjoint sets can still overlap)
            lo = np.array([ids.min() / 100.0] * 3, np.float32)
            hi = np.array([ids.max() / 100.0 + 0.01] * 3, np.float32)
            bboxes.append((lo, hi))
            masks.append([("f", i, 1.0)])
        inter = np.zeros((num, num), np.float32)
        for i in range(num):
            for j in range(num):
                inter[i, j] = len(
                    frozenset(point_ids[i].tolist())
                    & frozenset(point_ids[j].tolist()))
        sizes = np.array([len(p) for p in point_ids])
        ref_p, ref_m = _merge_overlapping(point_ids, bboxes, masks, 0.6)
        got_p, got_m = merge_from_counts(point_ids, bboxes, masks, sizes,
                                         inter, 0.6)
        assert ref_m == got_m, f"trial {trial}"
        assert len(ref_p) == len(got_p)
        for a, b in zip(ref_p, got_p):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# emit-only drain + capacity ladder
# ---------------------------------------------------------------------------


def _tiny_scene(seed=70):
    scene = make_scene(num_boxes=2, num_frames=6, image_hw=(40, 56),
                       spacing=0.05, seed=seed)
    return to_scene_tensors(scene)


def _tiny_config(**kw):
    return PipelineConfig(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.05, step=1, mask_pad_multiple=32,
        frame_pad_multiple=4, point_chunk=2048, **kw)


def test_emit_only_drain_books_no_plane_pull():
    """Acceptance: the default (device) path never pulls an (F, N) claim
    plane — its whole d2h budget is the final compact drain — while the
    host path's first transfer alone is two full planes. Counter-based
    twin of test_executor's span pin."""
    from maskclustering_tpu.obs.metrics import registry

    tensors = _tiny_scene()
    reg = registry()

    reg.reset()
    res_dev = run_scene(tensors, _tiny_config(device_postprocess=True),
                        k_max=15)
    dev = reg.snapshot()["counters"]

    reg.reset()
    res_host = run_scene(tensors, _tiny_config(device_postprocess=False),
                         k_max=15)
    host = reg.snapshot()["counters"]

    f_pad, n_pad = 8, 2048  # 6 frames -> pad 8; tiny point bucket
    plane_bytes = f_pad * n_pad * 2  # one (F, N) int16 plane
    # host path: the host_pull drains BOTH planes (+ node_visible)
    assert host.get("d2h.bytes.postprocess", 0) >= 2 * plane_bytes
    # device path: nothing booked to the host-pull stage, and the whole
    # emit-only drain stays under the host path's pull even at this TINY
    # shape, where the O(M_pad + S) scalar payload is at its relative
    # worst (the drain does not scale with F x N — at the honest bucket
    # the planes are ~98 MB and the drain ~0.1 MB, see claims_diag)
    assert "d2h.bytes.postprocess" not in dev
    assert 0 < dev["d2h.bytes.post.drain"] < host["d2h.bytes.postprocess"]
    # exactly one pipeline host sync (the mask-table bucket pull)
    assert dev["pipeline.host_sync"] == 1
    # identity between the two runs (belt and braces at this shape)
    assert len(res_dev.objects.point_ids_list) == \
        len(res_host.objects.point_ids_list)
    for a, b in zip(res_dev.objects.point_ids_list,
                    res_host.objects.point_ids_list):
        np.testing.assert_array_equal(a, b)


def test_postprocess_capacity_overflow_is_device_class():
    """Overflowing a device post-process bucket raises the typed capacity
    error (device class -> the ladder's host-postprocess rung re-runs the
    scene) instead of exporting truncated groups."""
    from maskclustering_tpu.models.postprocess_device import (
        PostprocessCapacityError)
    from maskclustering_tpu.utils import faults

    tensors = _tiny_scene()
    with pytest.raises(PostprocessCapacityError) as gi:
        run_scene(tensors, _tiny_config(post_group_cap=2), k_max=15)
    assert faults.classify_error(gi.value) == "device"
    assert "post_group_cap" in str(gi.value)

    with pytest.raises(PostprocessCapacityError) as ni:
        run_scene(tensors, _tiny_config(post_neighbor_cap=1), k_max=15)
    assert faults.classify_error(ni.value) == "device"
    assert "post_neighbor_cap" in str(ni.value)


@pytest.mark.slow
def test_device_matches_host_postprocess_full_density():
    """Full-density (63k-point cloud) identity at the default synthetic
    shape — the honest-scale twin of the fast spacing-0.04 tests above.
    Slow-marked: the CPU grid-DBSCAN pack pass alone is ~6 s here."""
    scene = make_scene(num_boxes=4, num_frames=10, seed=21)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(tensors, _config(device_postprocess=False), k_max=15)
    res_dev = run_scene(tensors, _config(device_postprocess=True), k_max=15)
    oh, od = res_host.objects, res_dev.objects
    assert len(oh.point_ids_list) == len(od.point_ids_list)
    for ph, pd in zip(oh.point_ids_list, od.point_ids_list):
        np.testing.assert_array_equal(ph, pd)
    assert oh.mask_list == od.mask_list


@pytest.mark.slow
@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2), (8, 1)])
def test_mesh_device_postprocess_identity_lattice(mesh_shape):
    """Full-divisor-lattice sweep: the fused mesh path with the
    device-resident post-process produces artifacts byte-identical to the
    single-chip HOST post-process, on every (scene, frame) factorization
    of the 8-device mesh. Slow-marked: 4 fused-step compiles."""
    from maskclustering_tpu.parallel import make_mesh
    from maskclustering_tpu.parallel.batch import cluster_scene_batch
    from maskclustering_tpu.utils.synthetic import make_scene as _ms

    cfg = PipelineConfig(
        config_name="meshpost", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=1024, frame_pad_multiple=8,
        mask_pad_multiple=8)
    tensors = [to_scene_tensors(_ms(
        num_boxes=3, num_frames=8, image_hw=(32, 48), spacing=0.08, seed=s))
        for s in (0, 1, 2)]
    refs = [run_scene(t, cfg.replace(device_postprocess=False),
                      k_max=7).objects for t in tensors]
    mesh = make_mesh(mesh_shape)
    objs = cluster_scene_batch(cfg, mesh, tensors, k_max=7)
    for om, ref in zip(objs, refs):
        assert om.num_points == ref.num_points
        assert len(om.point_ids_list) == len(ref.point_ids_list)
        for a, b in zip(om.point_ids_list, ref.point_ids_list):
            np.testing.assert_array_equal(a, b)
        assert om.mask_list == ref.mask_list
