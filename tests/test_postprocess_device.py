"""Device postprocess vs host postprocess: byte-identical artifacts.

The device path (models/postprocess_device.py) keeps the (F, N) claim
tensors in HBM and transfers only bit-packed planes; it must reproduce the
host path (models/postprocess.py) exactly — same objects, same point ids,
same mask lists in the same order — because both implement reference
utils/post_process.py:40-170 semantics.
"""

import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.pipeline import run_scene
from maskclustering_tpu.models.postprocess_device import _pack_bits, _unpack_bits
from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors


def _config(**kw):
    return PipelineConfig(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.03, step=1, mask_pad_multiple=64,
        point_chunk=2048, **kw,
    )


def test_pack_unpack_roundtrip(rng):
    for n in (8, 13, 256, 1000):
        x = rng.random((4, n)) < 0.3
        packed = np.asarray(_pack_bits(x))
        assert packed.shape == (4, -(-n // 8))
        np.testing.assert_array_equal(_unpack_bits(packed, n), x)


@pytest.mark.parametrize("seed,num_boxes", [(21, 4), (5, 6)])
def test_device_matches_host_postprocess(seed, num_boxes):
    scene = make_scene(num_boxes=num_boxes, num_frames=10, seed=seed)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(tensors, _config(device_postprocess=False), k_max=15)
    res_dev = run_scene(tensors, _config(device_postprocess=True), k_max=15)

    oh, od = res_host.objects, res_dev.objects
    assert len(oh.point_ids_list) == len(od.point_ids_list)
    assert oh.num_points == od.num_points
    for ph, pd in zip(oh.point_ids_list, od.point_ids_list):
        # exact order too: both paths emit ascending ids, and object_dict.npy
        # serializes them in emission order (byte-identity contract)
        np.testing.assert_array_equal(ph, pd)
    assert oh.mask_list == od.mask_list


def test_device_postprocess_empty_scene():
    """A scene with no recoverable masks yields an empty object list."""
    scene = make_scene(num_boxes=2, num_frames=4, seed=3)
    tensors = to_scene_tensors(scene)
    # zero out every segmentation -> no masks -> no live reps
    import dataclasses

    tensors = dataclasses.replace(
        tensors, segmentations=np.zeros_like(tensors.segmentations))
    res = run_scene(tensors, _config(device_postprocess=True), k_max=15)
    assert res.objects.point_ids_list == []
    assert res.objects.mask_list == []
