"""Device postprocess vs host postprocess: byte-identical artifacts.

The device path (models/postprocess_device.py) keeps the (F, N) claim
tensors in HBM and transfers only bit-packed planes; it must reproduce the
host path (models/postprocess.py) exactly — same objects, same point ids,
same mask lists in the same order — because both implement reference
utils/post_process.py:40-170 semantics.
"""

import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.pipeline import run_scene
from maskclustering_tpu.models.postprocess_device import _pack_bits, _unpack_bits
from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors


def _config(**kw):
    return PipelineConfig(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.03, step=1, mask_pad_multiple=64,
        point_chunk=2048, **kw,
    )


def test_pack_unpack_roundtrip(rng):
    for n in (8, 13, 256, 1000):
        x = rng.random((4, n)) < 0.3
        packed = np.asarray(_pack_bits(x))
        assert packed.shape == (4, -(-n // 8))
        np.testing.assert_array_equal(_unpack_bits(packed, n), x)


@pytest.mark.parametrize("seed,num_boxes", [(21, 4), (5, 6)])
def test_device_matches_host_postprocess(seed, num_boxes):
    scene = make_scene(num_boxes=num_boxes, num_frames=10, seed=seed)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(tensors, _config(device_postprocess=False), k_max=15)
    res_dev = run_scene(tensors, _config(device_postprocess=True), k_max=15)

    oh, od = res_host.objects, res_dev.objects
    assert len(oh.point_ids_list) == len(od.point_ids_list)
    assert oh.num_points == od.num_points
    for ph, pd in zip(oh.point_ids_list, od.point_ids_list):
        # exact order too: both paths emit ascending ids, and object_dict.npy
        # serializes them in emission order (byte-identity contract)
        np.testing.assert_array_equal(ph, pd)
    assert oh.mask_list == od.mask_list


@pytest.mark.parametrize("num_frames,fpm,expect_chunk", [
    (3, 1, 1),   # F_pad 3 -> odd, chunk falls to 1
    (6, 1, 2),   # F_pad 6 -> chunk 2
    (12, 4, 4),  # F_pad 12 -> chunk 4
])
def test_device_postprocess_chunk_fallbacks(num_frames, fpm, expect_chunk):
    """Byte-identity must hold on every frame-chunk divisor of the claims
    scan (8/4/2/1), not just the default-padded chunk=8 path."""
    from maskclustering_tpu.models.pipeline import bucket_size
    from maskclustering_tpu.models.postprocess_device import _frame_chunk

    f_pad = bucket_size(num_frames, fpm)
    assert _frame_chunk(f_pad) == expect_chunk

    scene = make_scene(num_boxes=3, num_frames=num_frames, seed=11)
    tensors = to_scene_tensors(scene)
    res_host = run_scene(
        tensors, _config(device_postprocess=False, frame_pad_multiple=fpm),
        k_max=15)
    res_dev = run_scene(
        tensors, _config(device_postprocess=True, frame_pad_multiple=fpm),
        k_max=15)
    assert len(res_host.objects.point_ids_list) == len(res_dev.objects.point_ids_list)
    for ph, pd in zip(res_host.objects.point_ids_list,
                      res_dev.objects.point_ids_list):
        np.testing.assert_array_equal(ph, pd)
    assert res_host.objects.mask_list == res_dev.objects.mask_list


def test_device_postprocess_empty_scene():
    """A scene with no recoverable masks yields an empty object list."""
    scene = make_scene(num_boxes=2, num_frames=4, seed=3)
    tensors = to_scene_tensors(scene)
    # zero out every segmentation -> no masks -> no live reps
    import dataclasses

    tensors = dataclasses.replace(
        tensors, segmentations=np.zeros_like(tensors.segmentations))
    res = run_scene(tensors, _config(device_postprocess=True), k_max=15)
    assert res.objects.point_ids_list == []
    assert res.objects.mask_list == []


def test_node_stats_kernel_dedupes_same_rep_claims():
    """num counts one (rep, point, frame) triple even when two DIFFERENT
    masks of the same representative claim one (frame, point) cell — the
    matmul formulation subtracts the duplicate via a one-hot correction,
    and id 0 (= no claim) must contribute nothing.
    """
    import jax.numpy as jnp

    from maskclustering_tpu.models.postprocess_device import (
        _node_stats_kernel, _unpack_bits)

    f, n, k2, r_pad = 3, 16, 6, 8
    first = np.zeros((f, n), np.int32)
    last = np.zeros((f, n), np.int32)
    # masks: frame 0 has ids 1, 2 (both rep 0) and 3 (rep 1); frame 1 has 1 (rep 0)
    rep_tab = np.full((f, k2), -1, np.int32)
    rep_tab[0, 1] = rep_tab[0, 2] = 0
    rep_tab[0, 3] = 1
    rep_tab[1, 1] = 0

    first[0, 0], last[0, 0] = 1, 2  # same rep twice -> ONE triple for rep 0
    first[0, 1], last[0, 1] = 1, 3  # reps 0 and 1 -> one triple each
    first[0, 2], last[0, 2] = 2, 2  # a == b -> one triple for rep 0
    first[1, 0], last[1, 0] = 1, 1  # second frame claim on point 0
    first[2, 5], last[2, 5] = 4, 4  # id with no rep mapping -> nothing

    m_pad = 4
    node_visible = np.zeros((m_pad, f), bool)
    node_visible[0, :2] = True  # rep slot 0 visible in frames 0, 1
    node_visible[1, 0] = True  # rep slot 1 visible in frame 0
    live_slots = np.zeros(r_pad, np.int32)
    live_slots[:2] = [0, 1]
    live_valid = np.zeros(r_pad, bool)
    live_valid[:2] = True

    claimed_p, ratio_p, nv_rep = _node_stats_kernel(
        jnp.asarray(first), jnp.asarray(last), jnp.asarray(rep_tab),
        jnp.asarray(node_visible), jnp.asarray(live_slots),
        jnp.asarray(live_valid), r_pad=r_pad, point_filter_threshold=0.5)
    claimed = _unpack_bits(np.asarray(claimed_p), n)

    want_claimed = np.zeros((r_pad, n), bool)
    want_claimed[0, [0, 1, 2]] = True  # rep 0 claims points 0 (x2 frames), 1, 2
    want_claimed[1, 1] = True  # rep 1 claims point 1
    np.testing.assert_array_equal(claimed, want_claimed)

    # ratio numerator must count point 0 / rep 0 as 1 triple in frame 0 plus
    # 1 in frame 1 = 2; denominator = 2 visible frames -> ratio 1.0 > 0.5
    ratio_ok = _unpack_bits(np.asarray(ratio_p), n)
    assert ratio_ok[0, 0] and ratio_ok[0, 1] and ratio_ok[0, 2]
    assert ratio_ok[1, 1]
    assert not ratio_ok[0, 5] and not ratio_ok[1, 5]

    # discriminating threshold: a failed dedupe would give point 0 / rep 0
    # num = 3 over den = 2 (ratio 1.5 > 1.25); the correct unique-triple
    # count gives exactly 1.0, which must NOT pass
    _, ratio_hi_p, _ = _node_stats_kernel(
        jnp.asarray(first), jnp.asarray(last), jnp.asarray(rep_tab),
        jnp.asarray(node_visible), jnp.asarray(live_slots),
        jnp.asarray(live_valid), r_pad=r_pad, point_filter_threshold=1.25)
    assert not _unpack_bits(np.asarray(ratio_hi_p), n)[0, 0]


def test_chunked_claims_pull_identity():
    """The chunked double-buffered bit-plane drain (claims_pull_chunk)
    reproduces the single blocking pull byte-for-byte — 1-row chunks are
    the adversarial maximum (every live rep drains as its own slice)."""
    scene = make_scene(num_boxes=4, num_frames=10, seed=21)
    tensors = to_scene_tensors(scene)
    res_one = run_scene(tensors, _config(claims_pull_chunk=0), k_max=15)
    res_many = run_scene(tensors, _config(claims_pull_chunk=1), k_max=15)
    assert len(res_one.objects.point_ids_list) == len(res_many.objects.point_ids_list)
    for a, b in zip(res_one.objects.point_ids_list, res_many.objects.point_ids_list):
        np.testing.assert_array_equal(a, b)
    assert res_one.objects.mask_list == res_many.objects.mask_list


def test_row_chunks_cover_exactly():
    """_row_chunks slices [0, rows) with no gap/overlap at any chunk size."""
    import jax.numpy as jnp

    from maskclustering_tpu.models.postprocess_device import _row_chunks

    arr = jnp.arange(44 * 3).reshape(44, 3)
    for rows in (1, 7, 44):
        for chunk in (0, 1, 5, 44, 100):
            chunks = _row_chunks(arr, rows, chunk)
            got = np.concatenate([np.asarray(c) for c in chunks], axis=0)
            np.testing.assert_array_equal(got, np.asarray(arr[:rows]))
            if chunk > 0:
                assert all(c.shape[0] <= chunk for c in chunks)
