"""Integer counting path: dtype dispatch, exactness, and artifact parity.

The counting helpers (ops/counting.py) must produce IDENTICAL results under
both operand encodings — bf16+f32 and int8+s32 — because every consumer
(graph stats, clustering affinities, postprocess claim kernels, AP
intersections) compares or ratios the counts against thresholds, and a
single ULP of difference would flip an artifact byte. These tests pin:

- helper-level exactness vs int64 numpy for random 0/1 operands;
- the overflow guard: the honest bench bucket's worst-case counts
  (N = 192k points, F = 256 frames) sit far inside s32 accumulation AND
  inside f32's 2^24 exact-integer range (the out_dtype conversion);
- scene-artifact byte identity between ``count_dtype="bf16"`` and
  ``"int8"`` on the single-chip path (device and host postprocess, chunked
  drain) and on the 8-virtual-device fused mesh path;
- the int16 first/last claim planes: emit dtype and round-trip through
  the postprocess consumers.

Wall budget: every scene here is the small shared synthetic shape
(<= 4 boxes, <= 10 frames) — tier-1 must stay under the 800 s soft budget
(scripts/ci.sh), so no fresh full-depth scenes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.pipeline import run_scene
from maskclustering_tpu.ops import counting
from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

# the honest bench bucket (bench.py defaults): the worst-case single count
HONEST_POINTS = 196608
HONEST_FRAMES = 256


def _config(**kw):
    return PipelineConfig(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.03, step=1, mask_pad_multiple=64,
        point_chunk=2048, **kw,
    )


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_count_dot_exact_vs_numpy(rng, count_dtype):
    a = rng.random((33, 70)) < 0.4
    b = rng.random((70, 41)) < 0.4
    want = a.astype(np.int64) @ b.astype(np.int64)
    got = counting.count_dot(jnp.asarray(a), jnp.asarray(b),
                             count_dtype=count_dtype)
    assert got.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.float32))
    raw = counting.count_dot(jnp.asarray(a), jnp.asarray(b),
                             count_dtype=count_dtype, out_dtype=None)
    assert raw.dtype == counting.accumulator_dtype(count_dtype)
    np.testing.assert_array_equal(np.asarray(raw, dtype=np.int64), want)


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_count_dot_general_batched_exact(rng, count_dtype):
    # the node-stats kernel's shape: contract over (batch, k) at once
    w = (rng.random((4, 6, 5)) < 0.5)
    m = (rng.random((4, 5, 7)) < 0.5)
    want = np.einsum("cik,ckn->in", w.astype(np.int64), m.astype(np.int64))
    got = counting.count_dot_general(
        jnp.asarray(w), jnp.asarray(m), (((0, 2), (0, 1)), ((), ())),
        count_dtype=count_dtype)
    np.testing.assert_array_equal(np.asarray(got), want.astype(np.float32))


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_count_onehot_dtype_and_drop(count_dtype):
    ids = jnp.asarray([0, 2, -1, 5], jnp.int16)  # -1/5: sentinel + overflow
    oh = counting.count_onehot(ids, 4, count_dtype=count_dtype)
    assert oh.dtype == counting.operand_dtype(count_dtype)
    want = np.zeros((4, 4))
    want[0, 0] = want[1, 2] = 1  # out-of-range rows stay all-zero
    np.testing.assert_array_equal(np.asarray(oh, dtype=np.float64), want)


def test_unknown_count_dtype_rejected():
    with pytest.raises(ValueError, match="count_dtype"):
        counting.operand_dtype("fp64")
    with pytest.raises(ValueError, match="count_dtype"):
        PipelineConfig(config_name="x", dataset="demo", count_dtype="f32")


def test_honest_bucket_counts_within_int32():
    """Overflow guard for the int8 path at the honest bench bucket.

    Every counting contraction's single-entry maximum is bounded by its
    contraction depth: co-occurrence / group counts by N (one mask claiming
    every point), observers / node-stats numerators and denominators by F.
    Those bounds must sit inside s32 accumulation AND inside f32's exact
    integer range (counts convert to f32 for the threshold math).
    """
    worst = max(HONEST_POINTS, HONEST_FRAMES)
    assert worst < 2 ** 24  # f32 out_dtype conversion stays exact
    assert worst * 4 < 2 ** 31  # s32 accumulator headroom, 4x margin
    # empirical: an all-ones contraction at the honest point depth — the
    # single worst accumulation the pipeline can produce — is exact
    ones = jnp.ones((1, HONEST_POINTS), jnp.bool_)
    got = counting.count_dot(ones, ones.T, count_dtype="int8", out_dtype=None)
    assert int(np.asarray(got)[0, 0]) == HONEST_POINTS
    got_f = counting.count_dot(ones, ones.T, count_dtype="int8")
    assert float(np.asarray(got_f)[0, 0]) == float(HONEST_POINTS)


def _assert_objects_equal(a, b, tag):
    assert len(a.point_ids_list) == len(b.point_ids_list), tag
    assert a.num_points == b.num_points, tag
    for pa, pb in zip(a.point_ids_list, b.point_ids_list):
        np.testing.assert_array_equal(pa, pb, err_msg=tag)
    assert a.mask_list == b.mask_list, tag


def test_scene_artifacts_identical_across_count_dtype():
    """CPU byte-identity of single-chip scene artifacts, bf16 vs int8 —
    covering the device postprocess, the chunked int16-plane-era claims
    drain (claims_pull_chunk=1: adversarial 1-row slices), and the host
    postprocess path (which pulls the full int16 planes)."""
    scene = make_scene(num_boxes=4, num_frames=10, seed=21, spacing=0.04)
    tensors = to_scene_tensors(scene)
    base = run_scene(tensors, _config(count_dtype="bf16"), k_max=15)
    for kw, tag in (
        (dict(count_dtype="int8"), "int8 device-post"),
        (dict(count_dtype="int8", claims_pull_chunk=1), "int8 chunked drain"),
        (dict(count_dtype="int8", device_postprocess=False), "int8 host-post"),
    ):
        res = run_scene(tensors, _config(**kw), k_max=15)
        _assert_objects_equal(base.objects, res.objects, tag)
        np.testing.assert_array_equal(base.assignment, res.assignment, tag)


def test_claim_planes_emit_int16_and_roundtrip():
    """Association emits int16 first/last planes; values round-trip exactly
    through the int32 formulation (the planes are ids <= k_max + 1)."""
    from maskclustering_tpu.models.backprojection import associate_scene_tensors

    scene = make_scene(num_boxes=3, num_frames=6, seed=7)
    tensors = to_scene_tensors(scene)
    assoc = associate_scene_tensors(tensors, _config(), k_max=15)
    assert assoc.first_id.dtype == jnp.int16
    assert assoc.last_id.dtype == jnp.int16
    first = np.asarray(assoc.first_id)
    last = np.asarray(assoc.last_id)
    # ids are within the int16-safe range and the int32 widening is lossless
    assert int(last.max(initial=0)) <= 16
    np.testing.assert_array_equal(first.astype(np.int32).astype(np.int16), first)
    # boundary/visibility derivations agree with the widened formulation
    np.testing.assert_array_equal(
        np.asarray(assoc.boundary), (first.astype(np.int32)
                                     != last.astype(np.int32)).any(axis=0))


@pytest.mark.parametrize("mesh_shape", [(2, 4)])
def test_fused_mesh_identical_across_count_dtype(mesh_shape):
    """The fused multi-chip step compiles BOTH count_dtype variants and
    their full result bundles match bit-for-bit on an 8-virtual-device
    mesh (int16 planes included)."""
    import jax

    from maskclustering_tpu.parallel.mesh import make_mesh
    from maskclustering_tpu.parallel.sharded import (
        build_fused_step,
        fused_step_example_args,
    )

    cfg = PipelineConfig(config_name="t", dataset="demo",
                         distance_threshold=0.01, few_points_threshold=25,
                         point_chunk=256)
    args = fused_step_example_args(num_scenes=mesh_shape[0] * 2, num_frames=8,
                                   num_points=4096)
    mesh = make_mesh(mesh_shape)
    outs = {}
    for cd in ("bf16", "int8"):
        step = build_fused_step(mesh, cfg.replace(count_dtype=cd), k_max=15)
        outs[cd] = jax.block_until_ready(step(*args))
    assert outs["bf16"].first_id.dtype == jnp.int16
    for field in outs["bf16"]._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(outs["bf16"], field)),
            np.asarray(getattr(outs["int8"], field)), err_msg=field)
