"""AP-protocol tests against analytically known cases.

The reference ships no tests (SURVEY.md §4); these pin the protocol semantics
of reference evaluation/evaluate.py: greedy matching, min-region filtering,
void ignore, duplicate-detection false positives, and the AP integration.
"""

import numpy as np
import pytest

from maskclustering_tpu.evaluation import (
    assign_instances_for_scan,
    compute_averages,
    evaluate_matches,
    evaluate_scans,
    group_instances,
)

LABELS = ["cabinet", "bed"]
VALID_IDS = [3, 4]
ID2LABEL = {3: "cabinet", 4: "bed"}
N = 1000


def _gt_two_instances():
    """Two cabinet instances of 300 points each; the rest unannotated."""
    gt = np.zeros(N, dtype=np.int64)
    gt[:300] = 3001
    gt[300:600] = 3002
    return gt


def _matches(gt_ids, masks, scores, classes, **kw):
    gt2pred, pred2gt = assign_instances_for_scan(
        masks, scores, classes, gt_ids, LABELS, VALID_IDS, **kw)
    return {"scan": {"gt": gt2pred, "pred": pred2gt}}


def test_perfect_predictions_give_ap_one():
    gt = _gt_two_instances()
    masks = np.zeros((N, 2), dtype=bool)
    masks[:300, 0] = True
    masks[300:600, 1] = True
    m = _matches(gt, masks, np.ones(2), np.full(2, 3))
    aps = evaluate_matches(m, LABELS)
    avgs = compute_averages(aps, LABELS)
    assert avgs["classes"]["cabinet"]["ap"] == pytest.approx(1.0)
    assert avgs["classes"]["bed"]["ap"] != avgs["classes"]["bed"]["ap"]  # NaN: no GT, no pred
    assert avgs["all_ap"] == pytest.approx(1.0)  # nanmean skips bed


def test_half_overlap_passes_ap25_fails_ap50():
    """IoU = 150/450 = 1/3: counts at 0.25 threshold, misses at 0.5."""
    gt = _gt_two_instances()
    masks = np.zeros((N, 2), dtype=bool)
    masks[150:450, 0] = True  # straddles both instances, IoU 1/3 with each
    masks[300:600, 1] = True  # exact match of 3002
    m = _matches(gt, masks, np.array([0.9, 1.0]), np.full(2, 3))
    aps = evaluate_matches(m, LABELS)
    avgs = compute_averages(aps, LABELS)
    assert avgs["classes"]["cabinet"]["ap25%"] == pytest.approx(1.0)
    # at IoU 0.5 only instance 3002 is found; the straddler is a false positive
    assert avgs["classes"]["cabinet"]["ap50%"] < 1.0
    assert avgs["classes"]["cabinet"]["ap50%"] > 0.0


def test_small_predictions_are_skipped():
    gt = _gt_two_instances()
    masks = np.zeros((N, 1), dtype=bool)
    masks[:50, 0] = True  # below the 100-vertex minimum region size
    _, pred2gt = assign_instances_for_scan(
        masks, np.ones(1), np.full(1, 3), gt, LABELS, VALID_IDS)
    assert pred2gt["cabinet"] == []


def test_void_coverage_is_not_a_false_positive():
    """A prediction mostly on unannotated points is ignored, not penalized."""
    gt = _gt_two_instances()
    masks = np.zeros((N, 3), dtype=bool)
    masks[:300, 0] = True
    masks[300:600, 1] = True
    masks[600:900, 2] = True  # entirely void
    m = _matches(gt, masks, np.ones(3), np.full(3, 3))
    aps = evaluate_matches(m, LABELS)
    avgs = compute_averages(aps, LABELS)
    assert avgs["classes"]["cabinet"]["ap"] == pytest.approx(1.0)


def test_duplicate_detection_becomes_false_positive():
    """Two perfect copies of one GT: the duplicate counts as an FP.

    With a *lower* confidence duplicate the protocol still yields AP = 1.0
    (the FP sits at a cutoff below full recall); with *equal* confidence the
    FP shares the cutoff and AP = 0.75 (precision 0.5 at recall 1.0,
    precision 1.0 at the artificial endpoint, trapezoid-integrated).
    """
    gt = np.zeros(N, dtype=np.int64)
    gt[:300] = 3001
    masks = np.zeros((N, 2), dtype=bool)
    masks[:300, 0] = True
    masks[:300, 1] = True

    m = _matches(gt, masks, np.array([1.0, 0.5]), np.full(2, 3))
    avgs = compute_averages(evaluate_matches(m, LABELS), LABELS)
    assert avgs["classes"]["cabinet"]["ap50%"] == pytest.approx(1.0)

    m = _matches(gt, masks, np.array([1.0, 1.0]), np.full(2, 3))
    avgs = compute_averages(evaluate_matches(m, LABELS), LABELS)
    assert avgs["classes"]["cabinet"]["ap50%"] == pytest.approx(0.75)


def test_missed_instance_halves_recall():
    gt = _gt_two_instances()
    masks = np.zeros((N, 1), dtype=bool)
    masks[:300, 0] = True  # only 3001 found
    m = _matches(gt, masks, np.ones(1), np.full(1, 3))
    aps = evaluate_matches(m, LABELS)
    avgs = compute_averages(aps, LABELS)
    # precision 1 up to recall 0.5, then 0: AP = 0.5
    assert avgs["classes"]["cabinet"]["ap50%"] == pytest.approx(0.5)


def test_no_class_mode_collapses_labels():
    gt = np.zeros(N, dtype=np.int64)
    gt[:300] = 3001  # cabinet
    # bed; instance numbers are scene-unique (GT prep assigns inst ids
    # globally, so id % 1000 stays distinct after the no_class remap)
    gt[300:600] = 4002
    gt[600:] = 4003  # cover every vertex: see phantom-instance test below
    masks = np.zeros((N, 3), dtype=bool)
    masks[:300, 0] = True
    masks[300:600, 1] = True
    masks[600:, 2] = True
    # predicted classes are garbage; no_class ignores them
    m = _matches(gt, masks, np.ones(3), np.array([99, 77, 55]), no_class=True)
    aps = evaluate_matches(m, LABELS)
    avgs = compute_averages(aps, LABELS)
    assert avgs["classes"]["cabinet"]["ap"] == pytest.approx(1.0)


def test_no_class_phantom_instance_from_unannotated():
    """Protocol quirk parity (reference evaluate.py:261-262): in no_class
    mode the remap ``id % 1000 + first*1000`` turns unannotated vertices
    (encoded as 1 by GT prep, prepare_gt.py:23) into a phantom instance that
    is never matched, costing a hard false negative."""
    gt = np.full(N, 1, dtype=np.int64)  # reference encoding for unannotated
    gt[:300] = 3002
    masks = np.zeros((N, 1), dtype=bool)
    masks[:300, 0] = True
    m = _matches(gt, masks, np.ones(1), np.full(1, 3), no_class=True)
    avgs = compute_averages(evaluate_matches(m, LABELS), LABELS)
    # real instance matched, phantom missed: precision 1, recall 1/2 -> AP 0.5
    assert avgs["classes"]["cabinet"]["ap50%"] == pytest.approx(0.5)


def test_group_instances_skips_void_and_zero():
    gt = np.zeros(N, dtype=np.int64)
    gt[:200] = 3001
    gt[200:400] = 99001  # label 99 not in vocabulary -> void
    grouped = group_instances(gt, VALID_IDS, LABELS, ID2LABEL)
    assert len(grouped["cabinet"]) == 1
    assert grouped["cabinet"][0].vert_count == 200
    assert grouped["bed"] == []


def test_evaluate_scans_end_to_end(tmp_path):
    """File-level round trip: npz + txt in, result file out."""
    gt = np.zeros(N, dtype=np.int64)
    gt[:300] = 3001  # label 3 = "cabinet" in the scannet vocabulary
    gt[300:] = 3002  # all vertices annotated (no no_class phantom)
    gt_dir = tmp_path / "gt"
    pred_dir = tmp_path / "pred"
    gt_dir.mkdir()
    pred_dir.mkdir()
    np.savetxt(gt_dir / "scene0000_00.txt", gt, fmt="%d")
    masks = np.zeros((N, 2), dtype=bool)
    masks[:300, 0] = True
    masks[300:, 1] = True
    np.savez(pred_dir / "scene0000_00.npz",
             pred_masks=masks, pred_score=np.ones(2),
             pred_classes=np.zeros(2, dtype=np.int32))
    out = tmp_path / "result.txt"
    avgs = evaluate_scans(
        [str(pred_dir / "scene0000_00.npz")],
        [str(gt_dir / "scene0000_00.txt")],
        "scannet", no_class=True, output_file=str(out), verbose=False)
    assert avgs["all_ap"] == pytest.approx(1.0)
    lines = out.read_text().splitlines()
    assert lines[0] == "class,class id,ap,ap50,ap25"
    assert len(lines) > 2


def test_evaluator_memory_streams_scans(tmp_path):
    """Peak RSS must stay bounded over a ~50-scan evaluation: the per-scan
    dense one-hot/intersection tensors are transient; only the small match
    records accumulate (VERDICT r3 task 8; ref evaluate.py:383-400 loads
    everything per scan too but never at 311-scene scale in one process)."""
    import resource

    n, scans = 200_000, 50
    gt = np.zeros(n, dtype=np.int64)
    inst = 20
    block = n // inst
    for i in range(inst):
        gt[i * block : (i + 1) * block] = 3001 + i
    gt_dir = tmp_path / "gt"
    pred_dir = tmp_path / "pred"
    gt_dir.mkdir()
    pred_dir.mkdir()
    masks = np.zeros((n, inst), dtype=bool)
    for i in range(inst):
        masks[i * block : (i + 1) * block, i] = True
    np.savetxt(gt_dir / "s.txt", gt, fmt="%d")
    np.savez(pred_dir / "s.npz", pred_masks=masks,
             pred_score=np.ones(inst), pred_classes=np.zeros(inst, np.int32))
    pred_files = [str(pred_dir / "s.npz")] * scans
    gt_files = [str(gt_dir / "s.txt")] * scans

    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    avgs = evaluate_scans(pred_files, gt_files, "scannet", no_class=True,
                          verbose=False)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    assert avgs["all_ap"] == pytest.approx(1.0)
    # one scan's transient tensors are ~25 MB; 50 scans leaked would be
    # > 1 GB. Allow generous slack for allocator/jit overhead.
    assert rss_after - rss_before < 0.6, (rss_before, rss_after)


def test_evaluation_cli_main(tmp_path, monkeypatch):
    """``python -m maskclustering_tpu.evaluation`` smoke: args -> result txt,
    missing-GT error path returns nonzero without writing anything."""
    from maskclustering_tpu.evaluation.__main__ import main

    gt = np.zeros(N, dtype=np.int64)
    gt[:300] = 3001
    gt[300:] = 3002
    gt_dir = tmp_path / "gt"
    pred_dir = tmp_path / "pred"
    gt_dir.mkdir()
    pred_dir.mkdir()
    np.savetxt(gt_dir / "scene0000_00.txt", gt, fmt="%d")
    masks = np.zeros((N, 2), dtype=bool)
    masks[:300, 0] = True
    masks[300:, 1] = True
    np.savez(pred_dir / "scene0000_00.npz",
             pred_masks=masks, pred_score=np.ones(2),
             pred_classes=np.zeros(2, dtype=np.int32))

    out = tmp_path / "res.txt"
    rc = main(["--pred_path", str(pred_dir), "--gt_path", str(gt_dir),
               "--dataset", "scannet", "--no_class", "--output_file", str(out)])
    assert rc == 0
    # --no_class appends the suffix when absent from the name
    suffixed = tmp_path / "res_class_agnostic.txt"
    assert suffixed.exists()
    assert suffixed.read_text().startswith("class,class id,ap,ap50,ap25")

    # a prediction without GT is a loud failure, not a silent skip — and it
    # must write nothing (chdir keeps any regression's default-path output
    # inside tmp_path where the assertion can see it)
    monkeypatch.chdir(tmp_path)
    np.savez(pred_dir / "scene9999_00.npz",
             pred_masks=masks, pred_score=np.ones(2),
             pred_classes=np.zeros(2, dtype=np.int32))
    rc = main(["--pred_path", str(pred_dir), "--gt_path", str(gt_dir),
               "--dataset", "scannet", "--no_class"])
    assert rc == 1
    assert not (tmp_path / "data").exists()
