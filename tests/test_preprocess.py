"""L0 preprocessing tests: .sens round-trip, GT prep, converters.

Synthetic fixtures throughout — no real dataset downloads. Oracle
behaviors are cited from the reference preprocess/ scripts.
"""

import io
import json
import os
import struct
import zlib

import numpy as np
import pytest

from maskclustering_tpu.io.image import read_depth_png
from maskclustering_tpu.io.ply import read_ply_mesh, read_ply_points
from maskclustering_tpu.preprocess import (
    SensHeader,
    convert_matterport_gt,
    convert_tasmap_scene,
    export_sens_scene,
    iter_sens_frames,
    omni_intrinsics,
    pose_to_extrinsic,
    prepare_scannet_gt,
    write_sens,
    write_toolkit_configs,
)
from maskclustering_tpu.preprocess.scannet import SensFrame, load_label_map


def _jpeg_bytes(rgb: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(rgb).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def _make_sens(path, n_frames=6, dw=8, dh=6, cw=16, ch=12):
    rng = np.random.default_rng(0)
    intr_d = np.eye(4, dtype=np.float32)
    intr_d[0, 0], intr_d[1, 1] = 5.0, 5.0
    intr_d[0, 2], intr_d[1, 2] = dw / 2, dh / 2
    header = SensHeader(
        sensor_name="StructureSensor", intrinsic_color=np.eye(4, dtype=np.float32),
        extrinsic_color=np.eye(4, dtype=np.float32), intrinsic_depth=intr_d,
        extrinsic_depth=np.eye(4, dtype=np.float32),
        color_compression="jpeg", depth_compression="zlib_ushort",
        color_width=cw, color_height=ch, depth_width=dw, depth_height=dh,
        depth_shift=1000.0, num_frames=n_frames)
    frames, depths, poses = [], [], []
    for i in range(n_frames):
        depth = rng.integers(500, 3000, size=(dh, dw)).astype(np.uint16)
        pose = np.eye(4, dtype=np.float32)
        pose[:3, 3] = [i * 0.1, 0.0, 0.0]
        rgb = rng.integers(0, 255, size=(ch, cw, 3)).astype(np.uint8)
        frames.append(SensFrame(
            index=i, camera_to_world=pose, timestamp_color=i, timestamp_depth=i,
            color_bytes=_jpeg_bytes(rgb),
            depth_bytes=zlib.compress(depth.tobytes())))
        depths.append(depth)
        poses.append(pose)
    write_sens(path, header, frames)
    return header, depths, poses


class TestSens:
    def test_roundtrip_stream(self, tmp_path):
        path = str(tmp_path / "scene.sens")
        header, depths, poses = _make_sens(path)
        seen = 0
        for hdr, frame in iter_sens_frames(path):
            assert hdr.sensor_name == "StructureSensor"
            assert hdr.depth_shift == 1000.0
            np.testing.assert_array_equal(frame.depth(hdr), depths[frame.index])
            np.testing.assert_allclose(frame.camera_to_world, poses[frame.index])
            assert frame.color(hdr).shape == (12, 16, 3)
            seen += 1
        assert seen == 6

    def test_export_layout_and_stride(self, tmp_path):
        sens = str(tmp_path / "scene.sens")
        out = str(tmp_path / "processed")
        _, depths, poses = _make_sens(sens, n_frames=7)
        # frame_skip=3 keeps frames 0,3,6 (reference reader.py exports
        # frame_skip=10 over the full capture)
        n = export_sens_scene(sens, out, frame_skip=3)
        assert n == 3
        assert sorted(os.listdir(os.path.join(out, "depth"))) == [
            "0.png", "3.png", "6.png"]
        d3 = read_depth_png(os.path.join(out, "depth", "3.png"), depth_scale=1000.0)
        np.testing.assert_allclose(d3 * 1000.0, depths[3], atol=0.5)
        p6 = np.loadtxt(os.path.join(out, "pose", "6.txt"))
        np.testing.assert_allclose(p6, poses[6], atol=1e-5)
        intr = np.loadtxt(os.path.join(out, "intrinsic", "intrinsic_depth.txt"))
        assert intr[0, 0] == pytest.approx(5.0)
        assert os.path.exists(os.path.join(out, "color", "0.jpg"))


class TestScanNetGT:
    def _write_scene(self, root, scene_id, seg_indices, groups):
        scene = root / scene_id
        scene.mkdir(parents=True)
        with open(scene / f"{scene_id}_vh_clean_2.0.010000.segs.json", "w") as f:
            json.dump({"segIndices": seg_indices}, f)
        with open(scene / f"{scene_id}.aggregation.json", "w") as f:
            json.dump({"segGroups": groups}, f)

    def test_gt_encoding(self, tmp_path):
        # 6 vertices in segments [0,0,1,1,2,3]; group 0 = chair (id 5, valid),
        # group 1 = raw category unknown to the tsv -> label 0
        tsv = tmp_path / "labels.tsv"
        tsv.write_text("id\traw_category\tcategory\n5\tchair\tchair\n999\tweird\tweird\n")
        self._write_scene(
            tmp_path / "scans", "scene0000_00",
            [0, 0, 1, 1, 2, 3],
            [{"id": 0, "label": "chair", "segments": [0, 1]},
             {"id": 1, "label": "nosuch", "segments": [2]}])
        prepare_scannet_gt(str(tmp_path / "scans"), str(tmp_path / "gt"),
                           str(tsv), ["scene0000_00"], num_workers=1)
        gt = np.loadtxt(tmp_path / "gt" / "scene0000_00.txt", dtype=np.int64)
        # grouped chair verts: 5*1000 + (0+1) + 1 (prepare_gt.py:23-24,70)
        np.testing.assert_array_equal(gt[:4], [5002] * 4)
        # group with unknown label -> label 0, instance 2: 0*1000+2+1
        assert gt[4] == 3
        # ungrouped vertex: label 0 instance 0 -> 1
        assert gt[5] == 1

    def test_invalid_label_zeroed(self, tmp_path):
        # id 999 exists in the tsv but is not a benchmark id -> label 0
        tsv = tmp_path / "labels.tsv"
        tsv.write_text("id\traw_category\n999\tweird\n")
        self._write_scene(tmp_path / "scans", "scene0001_00", [0, 0],
                          [{"id": 0, "label": "weird", "segments": [0]}])
        prepare_scannet_gt(str(tmp_path / "scans"), str(tmp_path / "gt"),
                           str(tsv), ["scene0001_00"], num_workers=1)
        gt = np.loadtxt(tmp_path / "gt" / "scene0001_00.txt", dtype=np.int64)
        np.testing.assert_array_equal(gt, [2, 2])  # 0*1000 + 1 + 1

    def test_label_map_parsing(self, tmp_path):
        tsv = tmp_path / "labels.tsv"
        tsv.write_text("id\traw_category\n3\ttable\nx\tbroken\n")
        m = load_label_map(str(tsv))
        assert m == {"table": 3}


def _write_matterport_scene(root, seq, verts, faces, category_ids,
                            face_segments, instance_groups):
    """Binary-little-endian mesh ply + fsegs/semseg jsons."""
    d = root / seq / seq / "house_segmentations"
    d.mkdir(parents=True)
    n_v, n_f = len(verts), len(faces)
    header = (
        "ply\nformat binary_little_endian 1.0\n"
        f"element vertex {n_v}\n"
        "property float x\nproperty float y\nproperty float z\n"
        f"element face {n_f}\n"
        "property list uchar int vertex_indices\n"
        "property int category_id\n"
        "end_header\n")
    with open(d / f"{seq}.ply", "wb") as f:
        f.write(header.encode("ascii"))
        f.write(np.asarray(verts, dtype="<f4").tobytes())
        for face, cid in zip(faces, category_ids):
            f.write(struct.pack("<B3ii", 3, *[int(v) for v in face], int(cid)))
    with open(d / f"{seq}.fsegs.json", "w") as f:
        json.dump({"segIndices": face_segments}, f)
    with open(d / f"{seq}.semseg.json", "w") as f:
        json.dump({"segGroups": [{"segments": g} for g in instance_groups]}, f)


class TestMatterportGT:
    def test_convert(self, tmp_path):
        # 6 verts, 2 triangles; face 0 raw cat 1 -> nyu 7 (valid),
        # face 1 raw cat 2 -> nyu 42 (not valid -> 0)
        tsv = tmp_path / "category_mapping.tsv"
        tsv.write_text("index\traw_category\tnyuId\n1\tchair\t7\n2\tblob\t42\n")
        verts = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0],
                          [2, 0, 0], [3, 0, 0], [2, 1, 0]], dtype=np.float32)
        _write_matterport_scene(
            tmp_path, "houseA", verts,
            faces=[[0, 1, 2], [3, 4, 5]], category_ids=[1, 2],
            face_segments=[0, 1], instance_groups=[[0], [1]])
        gt = convert_matterport_gt(str(tmp_path), "houseA", str(tmp_path / "gt"),
                                   str(tsv), valid_ids=[7])
        # verts of face 0: nyu 7, instance 0 -> 7*1000 + 0 + 1
        np.testing.assert_array_equal(gt[:3], [7001] * 3)
        # verts of face 1: nyu 42 invalid -> 0, instance 1 -> 2
        np.testing.assert_array_equal(gt[3:], [2] * 3)
        on_disk = np.loadtxt(tmp_path / "gt" / "houseA.txt", dtype=np.int64)
        np.testing.assert_array_equal(on_disk, gt)

    def test_mesh_reader_face_props(self, tmp_path):
        verts = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0]], dtype=np.float32)
        _write_matterport_scene(tmp_path, "h", verts, faces=[[0, 1, 2]],
                                category_ids=[9], face_segments=[0],
                                instance_groups=[[0]])
        path = str(tmp_path / "h" / "h" / "house_segmentations" / "h.ply")
        v, f, props = read_ply_mesh(path)
        np.testing.assert_allclose(v, verts, atol=1e-6)
        np.testing.assert_array_equal(f, [[0, 1, 2]])
        np.testing.assert_array_equal(props["category_id"], [9])


class TestScanNetPPConfigs:
    def test_emission(self, tmp_path):
        paths = write_toolkit_configs(str(tmp_path), data_root="/data/spp",
                                      sample_factor=0.25)
        assert set(paths) == {
            "download_scannetpp.yml", "prepare_iphone_data.yml", "render.yml",
            "prepare_training_data.yml", "prepare_semantic_gt.yml"}
        train = open(paths["prepare_training_data.yml"]).read()
        assert "sample_factor: 0.25" in train
        assert "sample_points_on_mesh" in train
        gt = open(paths["prepare_semantic_gt.yml"]).read()
        assert "inst_gt_format: true" in gt
        render = open(paths["render.yml"]).read()
        assert "near: 0.05" in render and "far: 20.0" in render


class TestTasmap:
    def test_intrinsics_model(self):
        fx, fy, cx, cy = omni_intrinsics()
        # fx = W*f/aperture (tasmap2mct_format.py:44-47); square sensor -> fx==fy
        assert fx == pytest.approx(1024 * 17.0 / 20.954999923706055)
        assert fx == pytest.approx(fy)
        assert cx == cy == 512.0

    def test_pose_identity_quat(self):
        # identity orientation: camera axes are (+x, -y, -z) -> R rows
        w2c, c2w = pose_to_extrinsic(np.array([1.0, 2.0, 3.0]),
                                     np.array([0.0, 0.0, 0.0, 1.0]))
        np.testing.assert_allclose(w2c[:3, :3],
                                   np.diag([1.0, -1.0, -1.0]), atol=1e-12)
        np.testing.assert_allclose(w2c @ np.array([1.0, 2.0, 3.0, 1.0]),
                                   [0, 0, 0, 1], atol=1e-12)
        np.testing.assert_allclose(c2w @ w2c, np.eye(4), atol=1e-12)

    def test_convert_scene(self, tmp_path):
        from PIL import Image

        rng = np.random.default_rng(1)
        extra = tmp_path / "extra_info"
        for i in range(3):
            fdir = extra / f"{i:05d}"
            fdir.mkdir(parents=True)
            rgb = rng.integers(0, 255, size=(8, 8, 3)).astype(np.uint8)
            Image.fromarray(rgb).save(fdir / "original_image.png")
            depth = np.full((8, 8), 2.0, dtype=np.float32)  # 2 m plane
            np.save(fdir / "depth.npy", depth)
            np.save(fdir / "pose_ori.npy",
                    np.array([np.zeros(3), np.array([0, 0, 0, 1.0])],
                             dtype=object))
        out = tmp_path / "processed"
        ply = convert_tasmap_scene(str(extra), str(out), "scene0000_00",
                                   voxel_size=0.05, buffer_size=2)
        for sub in ("color", "depth", "pose", "intrinsic", "depth_npy"):
            assert os.path.isdir(out / sub)
        d = read_depth_png(str(out / "depth" / "00000.png"))
        np.testing.assert_allclose(d, 2.0, atol=1e-3)
        pose = np.loadtxt(out / "pose" / "00001.txt")
        np.testing.assert_allclose(pose[:3, :3], np.diag([1.0, -1.0, -1.0]),
                                   atol=1e-6)
        pts = read_ply_points(ply)
        assert len(pts) > 0
        # identity pose at origin, cam frame flipped: all points at world z=-2
        np.testing.assert_allclose(pts[:, 2], -2.0, atol=0.05)


class TestPlyRobustness:
    def test_binary_ragged_leading_quad_at_eof(self, tmp_path):
        # first face is a quad, rest triangles, face element last in file:
        # the uniform fast path over-reads and must fall back to the walk
        header = (
            "ply\nformat binary_little_endian 1.0\n"
            "element vertex 5\n"
            "property float x\nproperty float y\nproperty float z\n"
            "element face 2\n"
            "property list uchar int vertex_indices\n"
            "end_header\n")
        path = tmp_path / "ragged.ply"
        with open(path, "wb") as f:
            f.write(header.encode("ascii"))
            f.write(np.zeros((5, 3), dtype="<f4").tobytes())
            f.write(struct.pack("<B4i", 4, 0, 1, 2, 3))
            f.write(struct.pack("<B3i", 3, 2, 3, 4))
        verts, faces, _ = read_ply_mesh(str(path))
        assert len(verts) == 5
        np.testing.assert_array_equal(faces, [[0, 1, 2], [2, 3, 4]])

    def test_ascii_quads_truncate_to_triangles(self, tmp_path):
        path = tmp_path / "quads.ply"
        path.write_text(
            "ply\nformat ascii 1.0\n"
            "element vertex 4\n"
            "property float x\nproperty float y\nproperty float z\n"
            "element face 2\n"
            "property list uchar int vertex_indices\n"
            "end_header\n"
            "0 0 0\n1 0 0\n1 1 0\n0 1 0\n"
            "4 0 1 2 3\n"
            "3 0 2 3\n")
        _, faces, _ = read_ply_mesh(str(path))
        assert faces.shape == (2, 3)
        np.testing.assert_array_equal(faces[0], [0, 1, 2])

    def test_binary_uniform_quads_truncate(self, tmp_path):
        header = (
            "ply\nformat binary_little_endian 1.0\n"
            "element vertex 4\n"
            "property float x\nproperty float y\nproperty float z\n"
            "element face 2\n"
            "property list uchar int vertex_indices\n"
            "end_header\n")
        path = tmp_path / "uq.ply"
        with open(path, "wb") as f:
            f.write(header.encode("ascii"))
            f.write(np.zeros((4, 3), dtype="<f4").tobytes())
            f.write(struct.pack("<B4i", 4, 0, 1, 2, 3))
            f.write(struct.pack("<B4i", 4, 3, 2, 1, 0))
        _, faces, _ = read_ply_mesh(str(path))
        assert faces.shape == (2, 3)
        np.testing.assert_array_equal(faces, [[0, 1, 2], [3, 2, 1]])


class TestReviewRegressions:
    def test_matterport_out_of_range_raw_id_is_unknown(self, tmp_path):
        tsv = tmp_path / "category_mapping.tsv"
        tsv.write_text("index\traw_category\tnyuId\n1\tchair\t7\n2\tblob\t42\n")
        verts = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0]], dtype=np.float32)
        _write_matterport_scene(tmp_path, "h2", verts, faces=[[0, 1, 2]],
                                category_ids=[5000], face_segments=[0],
                                instance_groups=[[0]])
        gt = convert_matterport_gt(str(tmp_path), "h2", str(tmp_path / "gt"),
                                   str(tsv), valid_ids=[7, 42])
        np.testing.assert_array_equal(gt, [1, 1, 1])  # unknown, not clipped

    def test_export_zero_frame_sens_writes_intrinsics(self, tmp_path):
        from maskclustering_tpu.preprocess import SensHeader, write_sens
        intr = np.eye(4, dtype=np.float32)
        intr[0, 0] = 7.0
        hdr = SensHeader("empty", np.eye(4, dtype=np.float32),
                         np.eye(4, dtype=np.float32), intr,
                         np.eye(4, dtype=np.float32), "jpeg", "zlib_ushort",
                         4, 4, 4, 4, 1000.0, 0)
        sens = str(tmp_path / "empty.sens")
        write_sens(sens, hdr, [])
        n = export_sens_scene(sens, str(tmp_path / "out"))
        assert n == 0
        got = np.loadtxt(tmp_path / "out" / "intrinsic" / "intrinsic_depth.txt")
        assert got[0, 0] == pytest.approx(7.0)
