"""Reference-scale semantics: ViT-H geometry over the full ScanNet++ vocab.

VERDICT r4 task 6: the semantics path had only run at toy dimensions. Real
ViT-H-14 weights cannot exist in this offline image (README documents the
PrecomputedFeatures deployment path), but every DIMENSION the reference runs
at can be pinned offline: D = 1024 projection (open_clip ViT-H-14, reference
get_open-voc_features.py:101-107) and the 1554-label scannetpp vocabulary
(reference evaluation/constants.py:48-50).

Planted-feature construction: each synthetic object's representative-mask
features are noisy copies of its GT class's text feature, so classification
must recover every class through the softmax over all 1554 labels
(open-voc_query.py:43-47), and the class-aware AP protocol then runs over the
full vocabulary.
"""

import numpy as np
import pytest

from maskclustering_tpu.evaluation import evaluate_scans
from maskclustering_tpu.semantics import (
    HashEncoder,
    assign_labels,
    extract_label_features,
    l2_normalize,
    pool_scale_features,
)
from maskclustering_tpu.semantics.vocab import get_vocab

VIT_H_DIM = 1024  # open_clip ViT-H-14 projection dim (the reference encoder)
N_OBJECTS = 12
POINTS_PER_OBJ = 150  # > MIN_REGION_SIZE so every object is evaluated


@pytest.fixture(scope="module")
def scannetpp_vocab():
    labels, valid_ids = get_vocab("scannetpp")
    assert len(labels) == 1554, "reference constants.py scannetpp vocab size"
    return labels, valid_ids


@pytest.fixture(scope="module")
def planted(scannetpp_vocab):
    """Objects whose mask features point at known vocabulary entries."""
    labels, valid_ids = scannetpp_vocab
    rng = np.random.default_rng(42)
    text_feats = l2_normalize(
        rng.standard_normal((len(labels), VIT_H_DIM)).astype(np.float32))
    class_idx = rng.choice(len(labels), size=N_OBJECTS, replace=False)

    object_dict = {}
    mask_features = {}
    for o in range(N_OBJECTS):
        repre = [(f"f{o}", m) for m in range(1 + o % 3)]
        for frame, mid in repre:
            noisy = text_feats[class_idx[o]] + 0.05 * rng.standard_normal(
                VIT_H_DIM).astype(np.float32)
            mask_features[f"{frame}_{mid}"] = l2_normalize(noisy)
        object_dict[o] = {
            "point_ids": set(range(o * POINTS_PER_OBJ, (o + 1) * POINTS_PER_OBJ)),
            "repre_mask_list": repre,
        }
    # one object with NO features on record: must stay class 0 / all-False
    object_dict[N_OBJECTS] = {"point_ids": {N_OBJECTS * POINTS_PER_OBJ},
                              "repre_mask_list": [("missing", 0)]}
    label_features = {label: text_feats[i] for i, label in enumerate(labels)}
    return object_dict, mask_features, label_features, text_feats, class_idx


def test_query_recovers_classes_over_full_vocab(scannetpp_vocab, planted):
    labels, valid_ids = scannetpp_vocab
    object_dict, mask_features, label_features, _, class_idx = planted
    label_to_id = {l: int(i) for l, i in zip(labels, valid_ids)}
    n_pts = (N_OBJECTS + 1) * POINTS_PER_OBJ

    pred = assign_labels(object_dict, mask_features, label_features,
                         label_to_id, n_pts)
    assert pred["pred_masks"].shape == (n_pts, N_OBJECTS + 1)
    want = np.asarray([valid_ids[i] for i in class_idx], dtype=np.int32)
    np.testing.assert_array_equal(pred["pred_classes"][:N_OBJECTS], want)
    # the featureless object: class 0, empty mask column (open-voc_query.py:33-35)
    assert pred["pred_classes"][N_OBJECTS] == 0
    assert not pred["pred_masks"][:, N_OBJECTS].any()


def test_class_aware_ap_over_full_vocab(tmp_path, scannetpp_vocab, planted):
    """features -> query -> class-aware AP at (1024-dim, 1554 classes)."""
    labels, valid_ids = scannetpp_vocab
    object_dict, mask_features, label_features, _, class_idx = planted
    label_to_id = {l: int(i) for l, i in zip(labels, valid_ids)}
    n_pts = (N_OBJECTS + 1) * POINTS_PER_OBJ

    pred = assign_labels(object_dict, mask_features, label_features,
                         label_to_id, n_pts)
    np.savez(tmp_path / "scene.npz", **pred)

    gt = np.ones(n_pts, dtype=np.int64)  # unannotated = 1 (void)
    for o in range(N_OBJECTS):
        cid = valid_ids[class_idx[o]]
        gt[o * POINTS_PER_OBJ:(o + 1) * POINTS_PER_OBJ] = cid * 1000 + o + 1
    np.savetxt(tmp_path / "scene.txt", gt, fmt="%d")

    avgs = evaluate_scans([str(tmp_path / "scene.npz")],
                          [str(tmp_path / "scene.txt")],
                          "scannetpp", no_class=False, verbose=False)
    # every planted class recovered exactly; all other 1542 classes are NaN
    assert avgs["all_ap"] == pytest.approx(1.0)
    assert avgs["all_ap_50%"] == pytest.approx(1.0)
    planted_labels = {labels[i] for i in class_idx}
    for label in planted_labels:
        assert avgs["classes"][label]["ap"] == pytest.approx(1.0)
    some_absent = next(l for l in labels if l not in planted_labels)
    assert np.isnan(avgs["classes"][some_absent]["ap"])


def test_label_feature_extraction_at_vocab_scale(tmp_path, scannetpp_vocab):
    """extract_label_featrues.py-equivalent stage at full (1554, 1024)."""
    labels, _ = scannetpp_vocab
    enc = HashEncoder(feature_dim=VIT_H_DIM)
    path = extract_label_features(labels, enc, str(tmp_path / "text.npy"))
    stored = np.load(path, allow_pickle=True).item()
    assert len(stored) == 1554
    first = np.asarray(next(iter(stored.values())))
    assert first.shape == (VIT_H_DIM,)
    np.testing.assert_allclose(np.linalg.norm(first), 1.0, rtol=1e-5)


def test_scale_pooling_at_vit_h_dim():
    """(B*3, 1024) crop features -> (B, 1024) mask features, plain mean."""
    rng = np.random.default_rng(0)
    feats = l2_normalize(rng.standard_normal((8 * 3, VIT_H_DIM)).astype(np.float32))
    pooled = pool_scale_features(feats)
    assert pooled.shape == (8, VIT_H_DIM)
    # f32 mean reduction order differs between the pooled path and the
    # oracle (BLAS/threading dependent); observed deltas are ~1e-9 absolute
    np.testing.assert_allclose(pooled[0], feats[:3].mean(axis=0),
                               rtol=1e-5, atol=1e-8)
