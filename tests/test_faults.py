"""Fault-tolerance layer: watchdogs, retry/degradation, journal, injection.

Pins the acceptance contract of the fault-tolerant scene executor
(utils/faults.py + the run.py scene supervisor):

- a canned FaultPlan (one persistent load failure, one device stall, one
  flaky-then-ok scene, one persistent post-seam capacity fault) through a
  4-scene CPU run yields: the flaky scene succeeds on retry, the stalled
  scene raises DeviceStallError within the watchdog deadline and the run
  degrades one ladder rung, the post-capacity scene rides the ladder down
  to the host-postprocess rung and heals there (its artifacts still
  byte-identical), exactly ONE scene ends failed, the journal replays to
  the report's exact verdict, and every passing scene's artifacts are
  byte-identical to a fault-free run;
- SIGTERM mid-run journals in-flight scenes, writes a valid partial
  run_report.json, and the rerun skips journaled-done scenes, re-runs
  in-flight ones, and ends with artifacts byte-identical to an
  uninterrupted run;
- the fault-injected overlapped executor keeps failure attribution on the
  correct scene at prefetch depths 0/1/2;
- journal round-trips survive a torn final line (the shared obs read
  policy), sub-second watchdog deadlines fire as DeviceStallError, and
  bench.py's supervisor backoff shape is preserved by the shared
  RetryPolicy.

Scenes use the TINY shape bucket (2 boxes, 6 frames, 40x56, point_chunk
2048, frame_pad 4 — scripts/fault_smoke.py's shape), where a warm device
phase is ~2 s of pure dispatch overhead on CPU. The integration watchdog
budget is 25 s — ~12x over the worst warm phase (no flaky timeouts on a
loaded machine) while still bounding the 600 s injected stall to one
deadline's wall; the SUB-SECOND deadline contract is pinned by the unit
tests, where the guarded call is a sleep, not real dispatch. The clean
reference run executes FIRST so the faulted run's watchdogs only ever
time warm dispatches.
"""

import json
import os
import signal
import time

import numpy as np
import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.config import load_config
from maskclustering_tpu.utils import faults
from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

SCENES = [f"scene{i:04d}_00" for i in range(4)]
# ~12x the worst warm tiny-bucket device phase: a loaded box (observed
# 1.7x suite-wide slowdowns) must never time a HEALTHY dispatch out, or
# the acceptance counts flake with spurious degradations
WATCHDOG_S = 25.0
# the abandoned stall thread sleeps far past the whole tier-1 wall, so it
# never wakes mid-suite to run a ghost device phase against later tests
STALL_S = 600.0


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Every test starts (and leaves) with no plan and no stop request."""
    faults.set_plan(None)
    faults.clear_stop()
    yield
    faults.set_plan(None)
    faults.clear_stop()


def _cfg(data_root, **kw):
    return load_config("scannet").replace(
        data_root=data_root, step=1, distance_threshold=0.05,
        mask_pad_multiple=32, frame_pad_multiple=4, point_chunk=2048,
        retry_backoff_s=0.01, **kw)


# ---------------------------------------------------------------------------
# unit: watchdog / heartbeat / policy / classification / plan / ladder
# ---------------------------------------------------------------------------


def test_deadline_passthrough_and_subsecond_stall():
    assert faults.call_with_deadline(lambda: 41 + 1, 0.0) == 42  # inline
    assert faults.call_with_deadline(lambda: "ok", 5.0, seam="pull") == "ok"
    t0 = time.perf_counter()
    with pytest.raises(faults.DeviceStallError) as ei:
        faults.call_with_deadline(lambda: time.sleep(10), 0.05,
                                  seam="device", scene="sX")
    assert time.perf_counter() - t0 < 1.0  # sub-second deadline, sub-second raise
    assert ei.value.seam == "device" and ei.value.scene == "sX"
    assert ei.value.budget_s == 0.05
    assert faults.classify_error(ei.value) == "device"


def test_deadline_reraises_workload_error_not_stall():
    with pytest.raises(OSError, match="disk"):
        faults.call_with_deadline(
            lambda: (_ for _ in ()).throw(OSError("disk gone")), 5.0)


def test_heartbeat_rearms_on_progress():
    hb = faults.Heartbeat(0.2, seam="host", scene="sY")
    for _ in range(3):  # slow-but-alive: beats keep it armed past budget
        time.sleep(0.1)
        hb.beat()
        hb.check()
    time.sleep(0.3)  # no beat: expires within the budget
    assert hb.expired()
    with pytest.raises(faults.DeviceStallError):
        hb.check()


def test_retry_policy_shapes(monkeypatch):
    exp = faults.RetryPolicy(base_s=0.25, cap_s=2.0)
    assert [exp.backoff(a) for a in (1, 2, 3, 4, 5)] == [0.25, 0.5, 1.0, 2.0, 2.0]
    # bench.py's historical supervisor shape, preserved exactly
    bench = faults.RetryPolicy(base_s=20.0, cap_s=120.0, style="linear",
                               scale_env="MCT_BENCH_BACKOFF_SCALE")
    monkeypatch.delenv("MCT_BENCH_BACKOFF_SCALE", raising=False)
    assert [bench.backoff(a) for a in (1, 2, 3, 6, 7)] == [20, 40, 60, 120, 120]
    monkeypatch.setenv("MCT_BENCH_BACKOFF_SCALE", "0.05")
    assert bench.backoff(1) == 1.0
    monkeypatch.setenv("MCT_BENCH_BACKOFF_SCALE", "not-a-number")
    assert bench.backoff(1) == 20.0  # malformed knob falls back, never raises
    monkeypatch.setenv("MCT_BENCH_BACKOFF_SCALE", "-3")
    assert bench.backoff(1) == 0.0  # clamped, never negative
    with pytest.raises(ValueError):
        faults.RetryPolicy(style="fancy")


def test_error_classification():
    assert faults.classify_error(OSError("io")) == "retryable"
    assert faults.classify_error(RuntimeError("?")) == "retryable"
    assert faults.classify_error(ValueError("bad cfg")) == "terminal"
    assert faults.classify_error(KeyError("k")) == "terminal"
    assert faults.classify_error(MemoryError()) == "device"
    assert faults.classify_error(faults.InjectedFault("x")) == "retryable"
    assert faults.classify_error(
        faults.InjectedFault("x", retryable=False)) == "terminal"

    class XlaRuntimeError(Exception):  # jaxlib's name, matched by name
        pass

    assert faults.classify_error(XlaRuntimeError("wedged")) == "device"

    # a device post-process capacity overflow must route device-class so
    # the ladder's host-postprocess rung can heal it
    from maskclustering_tpu.models.postprocess_device import (
        PostprocessCapacityError,
    )

    err = PostprocessCapacityError("DBSCAN group", 600, 512, "post_group_cap")
    assert faults.classify_error(err) == "device"
    assert "post_group_cap" in str(err) and "600 > 512" in str(err)


def test_fault_plan_parse_and_fire():
    plan = faults.FaultPlan.from_spec(
        "load:s2, stall:s4.device, flaky:s5:2, fail:s3.export:1, terminal:s6",
        stall_s=0.01)
    kinds = {(e.kind, e.seam, e.scene): e.remaining for e in plan.entries}
    assert kinds == {("load", "load", "s2"): None,
                     ("stall", "device", "s4"): 1,
                     ("flaky", "device", "s5"): 2,
                     ("fail", "export", "s3"): 1,
                     ("terminal", "device", "s6"): None}
    # flaky: fires exactly twice, then heals
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            plan.fire("device", "s5")
    plan.fire("device", "s5")  # healed
    # terminal classification rides the exception
    with pytest.raises(faults.InjectedFault) as ei:
        plan.fire("device", "s6")
    assert not ei.value.retryable
    # stall: sleeps (bounded here), returns
    t0 = time.perf_counter()
    plan.fire("device", "s4")
    assert 0.005 <= time.perf_counter() - t0 < 1.0
    plan.fire("device", "s4")  # count exhausted: no-op
    plan.fire("device", "unlisted")  # unmatched scene: no-op
    for bad in ("boom:s1", "load:s1.warp", "stall:s1:0", "load:", "justload"):
        with pytest.raises(ValueError):
            faults.FaultPlan.from_spec(bad)

    # the post seam raises the production capacity error type, so the
    # injected fault classifies device and drives the real ladder path
    from maskclustering_tpu.models.postprocess_device import (
        PostprocessCapacityError,
    )

    post_plan = faults.FaultPlan.from_spec("fail:s7.post:1")
    with pytest.raises(PostprocessCapacityError) as pe:
        post_plan.fire("post", "s7")
    assert faults.classify_error(pe.value) == "device"
    post_plan.fire("post", "s7")  # count exhausted: no-op


def test_fault_plan_env_activation(monkeypatch):
    monkeypatch.setenv("MCT_FAULT_PLAN", "load:envscene")
    faults.set_plan(None)
    assert faults.active_plan() is None  # explicit set_plan(None) wins
    faults._PLAN_LOADED = False  # force a fresh env read
    plan = faults.active_plan()
    assert plan is not None and plan.entries[0].scene == "envscene"
    with pytest.raises(faults.InjectedFault):
        faults.inject("load", "envscene")
    faults.inject("device", "envscene")  # other seams untouched


def test_degradation_ladder_order_and_overrides():
    cfg = _cfg(".", mesh_shape=(2, 4))
    ladder = faults.DegradationLadder(cfg)
    assert ladder.rung == 0 and ladder.apply(cfg) == cfg
    assert ladder.degrade() == "sequential-executor"
    assert ladder.degrade() == "single-chip"
    assert ladder.degrade() == "donation-off"
    assert ladder.degrade() == "host-postprocess"
    assert ladder.degrade() is None and ladder.exhausted
    final = ladder.apply(cfg)
    assert (final.scene_overlap, final.mesh_shape, final.donate_buffers,
            final.device_postprocess) == (False, (), False, False)
    # rungs the config already satisfies are skipped at construction
    lean = faults.DegradationLadder(_cfg(".", scene_overlap=False,
                                         donate_buffers=False))
    assert lean.degrade() == "host-postprocess"
    assert lean.degrade() is None


def test_journal_roundtrip_with_torn_final_line(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    jr = faults.RunJournal(path, "cfgA")
    jr.begin_run()
    jr.attempt("s0", 1, 0)
    jr.outcome("s0", "ok", attempt=1, rung=0, num_objects=3, seconds=1.0)
    jr.attempt("s1", 1, 0)
    jr.outcome("s1", "failed", attempt=1, rung=0, error_class="retryable",
               error="Traceback...\nInjectedFault: boom")
    jr.attempt("s1", 2, 1)  # in flight when the "crash" hits
    jr.close()
    other = faults.RunJournal(path, "cfgB")  # another config, same file
    other.outcome("s9", "ok", attempt=1)
    other.close()
    with open(path, "a") as f:  # a SIGKILL tears the final line
        f.write('{"v": 1, "kind": "scene", "seq": "s1", "event"')
    stats = obs.ReadStats()
    replay = faults.replay_journal(path, config="cfgA", stats=stats)
    assert stats.torn == 1  # counted, not fatal — the shared read policy
    assert replay["s0"] == {"status": "ok", "attempts": 1,
                            "degradation_rung": 0, "error_class": "",
                            "num_objects": 3}
    assert replay["s1"]["status"] == "in-flight"  # attempt 2 never resolved
    assert replay["s1"]["attempts"] == 2
    assert "s9" not in replay  # config isolation
    assert faults.resume_done(path, config="cfgA") == {"s0"}
    assert faults.resume_done(path, config="cfgB") == {"s9"}
    assert faults.resume_done(str(tmp_path / "absent.jsonl")) == set()


def test_ledger_stamps_and_regress_attribution():
    from maskclustering_tpu.obs import ledger as led

    report = {"config_name": "flt",
              "scenes": [{"status": "ok", "seconds": 1.0}],
              "faults": {"scene_retries": 3, "device_stalls": 1,
                         "degradations": {"sequential-executor": 1},
                         "final_rung": 1, "journal_skips": 0,
                         "interrupted": False}}
    row = led.run_row(report)
    assert row["retries"] == 3 and row["degradations"] == 1
    assert row["device_stalls"] == 1 and row["final_rung"] == 1
    assert "interrupted" not in row  # only stamped when true
    clean = led.run_row({"config_name": "c", "scenes": [], "faults": {}})
    assert "retries" not in clean and "degradations" not in clean
    ok, lines = led.check_regression(
        dict(row, value=2.0, metric="m"), {"value": 1.9, "metric": "m"},
        threshold=0.15)
    assert ok
    assert any("fault attribution" in ln for ln in lines)


def test_render_faults_section():
    from maskclustering_tpu.obs.report import render_faults

    assert render_faults({"run.scenes_ok": 4.0}) is None  # clean run: no section
    text = render_faults({"run.scene_retries": 5.0, "run.device_stalls": 1.0,
                          "run.degradations.sequential-executor": 1.0,
                          "faults.injected.device": 3.0,
                          "run.scenes_failed": 1.0})
    assert "== faults ==" in text
    assert "scene retries 5" in text and "device stalls 1" in text
    assert "sequential-executor x1" in text
    assert "injected (fault plan): device x3" in text


# ---------------------------------------------------------------------------
# integration: the canned-FaultPlan acceptance run
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fault_run(tmp_path_factory):
    """Four disk scenes, clustered twice: clean reference first (pays the
    jit compiles so the faulted run's watchdogs only see warm dispatches),
    then under the canned acceptance FaultPlan with obs armed."""
    from maskclustering_tpu.run import run_pipeline

    faults.set_plan(None)
    faults.clear_stop()
    root = str(tmp_path_factory.mktemp("data"))
    for i, seq in enumerate(SCENES):
        write_scannet_layout(
            make_scene(num_boxes=2, num_frames=6, image_hw=(40, 56),
                       spacing=0.05,  # ~8k-point clouds: the faulted run
                       # re-runs scenes up to 4x, and the device
                       # post-process split is paid per attempt
                       seed=70 + i),  # the tiny bucket (see module doc)
            root, seq)

    # ledger=False on the fixture runs: module-scoped fixtures initialize
    # BEFORE the function-scoped hermetic MCT_PERF_LEDGER monkeypatch, so
    # a default-on append here would grow the repo's committed ledger
    ref = run_pipeline(_cfg(root, config_name="ref"), SCENES,
                       steps=("cluster",), resume=False, journal=False,
                       ledger=False)
    assert [s.status for s in ref.scenes] == ["ok"] * 4

    plan = faults.FaultPlan.from_spec(
        f"load:{SCENES[0]}, stall:{SCENES[1]}.device, flaky:{SCENES[2]}:2, "
        f"fail:{SCENES[3]}.post",
        stall_s=STALL_S)
    events = os.path.join(root, "flt_events.jsonl")
    report_path = os.path.join(root, "flt_report.json")
    faults.set_plan(plan)
    # the faulted run doubles as the lock-sanitizer acceptance drive
    # (MCT_LOCK_SANITIZER=1): plan locks, watchdogs, the overlapped
    # executor's metrics bumps and the journal sink all acquire under
    # instrumentation, and the observed order graph is snapped for the
    # embeds-in-static cross-check — one expensive 4-scene run, two gates
    from maskclustering_tpu.analysis import lock_sanitizer

    os.environ[lock_sanitizer.ENV_FLAG] = "1"
    lock_sanitizer.arm(True)
    lock_sanitizer.reset()
    undo_locks = lock_sanitizer.instrument_known_locks()
    try:
        # DEFAULT retry budget (scene_retries=2) on purpose: the
        # persistent post-seam capacity fault needs three degradation
        # rounds (sequential-executor -> donation-off -> host-postprocess)
        # and only reaches the healing host rung via the supervisor's
        # device-class ladder extension — the exact default-config path a
        # real capacity overflow takes
        flt = run_pipeline(
            _cfg(root, config_name="flt", watchdog_device_s=WATCHDOG_S),
            SCENES, steps=("cluster",), resume=False,
            report_path=report_path, obs_events=events, ledger=False)
    finally:
        lock_edges = lock_sanitizer.observed_edges()
        lock_report = lock_sanitizer.report()
        undo_locks()
        lock_sanitizer.arm(None)
        os.environ.pop(lock_sanitizer.ENV_FLAG, None)
        lock_sanitizer.reset()
        faults.set_plan(None)
        obs.disable()
    return {"root": root, "ref": ref, "flt": flt, "events": events,
            "report_path": report_path,
            "lock_edges": lock_edges, "lock_report": lock_report,
            "journal": os.path.join(root, "run_journal.jsonl")}


def test_acceptance_statuses_and_attribution(fault_run):
    """The ISSUE's acceptance matrix: flaky heals on retry, the stall is a
    typed in-deadline failure that degrades the run one rung, the
    persistent post-seam capacity fault rides the ladder down to the
    host-postprocess rung and heals there, and exactly one scene (the
    persistent load failure) ends failed."""
    by = {s.seq_name: s for s in fault_run["flt"].scenes}
    assert [s.seq_name for s in fault_run["flt"].scenes] == SCENES
    # exactly one scene ends failed: the persistent load failure, after
    # the full RETRYABLE budget (1 + 2 retries — the ladder extension is
    # device-class only, so the load fault does NOT get a fourth attempt)
    assert [s.seq_name for s in fault_run["flt"].failed] == [SCENES[0]]
    assert by[SCENES[0]].attempts == 3
    assert by[SCENES[0]].error_class == "retryable"
    assert "InjectedFault" in by[SCENES[0]].error
    # the stalled scene: DeviceStallError within the deadline, then healed
    # on the retry one ladder rung down
    assert by[SCENES[1]].status == "ok"
    assert by[SCENES[1]].attempts == 2
    assert by[SCENES[1]].degradation_rung == 1
    # the flaky scene: two scripted failures, third attempt succeeds
    assert by[SCENES[2]].status == "ok"
    assert by[SCENES[2]].attempts == 3
    # the post-capacity scene: the device-class PostprocessCapacityError
    # keeps firing while cfg.device_postprocess holds; the budget covers
    # rounds 2-3 and the device-class ladder extension grants round 4,
    # where the host-postprocess rung finally heals it
    assert by[SCENES[3]].status == "ok"
    assert by[SCENES[3]].attempts == 4
    assert by[SCENES[3]].degradation_rung == 3

    faults_digest = fault_run["flt"].faults
    # exactly one: the injected stall fires once and the pull seams do not
    # nest a second same-budget deadline that would double-count it
    assert faults_digest["device_stalls"] == 1
    assert faults_digest["degradations"] == {
        "sequential-executor": 1, "donation-off": 1, "host-postprocess": 1}
    assert faults_digest["final_rung"] == 3
    assert not faults_digest["interrupted"]
    # retry rounds: 4 scenes retried after round 1, 3 after round 2,
    # 1 (the ladder extension) after round 3
    assert faults_digest["scene_retries"] == 8


def test_acceptance_stall_is_deadline_bounded(fault_run):
    """The stalled scene failed IN TIME: its recorded failure wall is the
    watchdog budget (~2.5s), not the 30s injected stall — the wedge was
    abandoned, not outwaited."""
    journal_rows = faults.read_journal(fault_run["journal"], config="flt")
    stall_fail = [r for r in journal_rows
                  if r.get("event") == "outcome" and r.get("seq") == SCENES[1]
                  and r.get("status") == "failed"]
    assert len(stall_fail) == 1
    assert stall_fail[0]["error_class"] == "device"
    assert "DeviceStallError" in stall_fail[0]["error"]
    assert stall_fail[0]["seconds"] < 120.0 < STALL_S  # abandoned, not outwaited


def test_acceptance_artifacts_byte_identical_to_fault_free(fault_run):
    """Every scene that passed under faults produced artifacts
    byte-identical to the fault-free reference run — retries and
    degradation reorder EXECUTION, never results."""
    root = fault_run["root"]
    pred = os.path.join(root, "prediction")
    for seq in SCENES[1:]:
        a = np.load(os.path.join(pred, "flt_class_agnostic", f"{seq}.npz"))
        b = np.load(os.path.join(pred, "ref_class_agnostic", f"{seq}.npz"))
        for key in ("pred_masks", "pred_score", "pred_classes"):
            np.testing.assert_array_equal(a[key], b[key])
    # the failed scene exported nothing (no partial artifacts to latch)
    assert not os.path.exists(
        os.path.join(pred, "flt_class_agnostic", f"{SCENES[0]}.npz"))


def test_acceptance_journal_replays_report(fault_run):
    """The journal alone reconstructs the report's exact per-scene verdict
    (status/attempts/rung/error_class/num_objects) — a crash that eats
    run_report.json loses no attribution."""
    replay = faults.replay_journal(fault_run["journal"], config="flt")
    saved = json.load(open(fault_run["report_path"]))
    assert saved["faults"]["degradations"] == {
        "sequential-executor": 1, "donation-off": 1, "host-postprocess": 1}
    for scene in saved["scenes"]:
        r = replay[scene["seq_name"]]
        assert r["status"] == scene["status"], scene
        assert r["attempts"] == scene["attempts"], scene
        assert r["degradation_rung"] == scene["degradation_rung"], scene
        assert r["error_class"] == scene["error_class"], scene
        assert r["num_objects"] == scene["num_objects"], scene


def test_acceptance_obs_faults_surfaces(fault_run):
    """The Faults section renders from the captured events and the summary
    carries the fault counters (the report CLI acceptance path)."""
    from maskclustering_tpu.obs.report import RunData, render_report

    run = RunData(fault_run["events"])
    text = render_report(run)
    assert "== faults ==" in text
    assert "scene retries 8" in text
    assert "sequential-executor x1" in text
    assert "host-postprocess x1" in text
    assert "injected (fault plan)" in text
    counters = run.summary()["counters"]
    assert counters["run.scene_retries"] == 8
    assert counters["run.degradations.sequential-executor"] == 1
    assert counters["run.degradations.donation-off"] == 1
    assert counters["run.degradations.host-postprocess"] == 1
    assert counters["faults.injected.load"] == 3  # one per attempt
    assert counters["faults.injected.device"] == 3  # 1 stall + 2 flaky
    # the post-seam capacity fault fired on every device-postprocess
    # attempt (rungs 1-3); the healed host-rung attempt reaches no seam
    assert counters["faults.injected.post"] == 3


def test_acceptance_lock_sanitizer_embeds_in_static_graph(fault_run):
    """The concurrency-family cross-check: the lock acquisition orders
    OBSERVED while the canned 4-scene fault plan ran under
    MCT_LOCK_SANITIZER=1 must embed in the STATIC lock-order graph — an
    observed edge the AST cannot see is exactly the deadlock surface the
    sanitizer exists for (and the Faults section renders the digest)."""
    from maskclustering_tpu.analysis.concurrency import build_lock_order_graph
    from maskclustering_tpu.analysis.lock_sanitizer import check_embeds

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = fault_run["lock_report"]
    # the sanitizer was live: the faulted run's plan lock, per-entry fire
    # locks, watchdog heartbeat and metrics registry all acquired under it
    assert sum(report["acquisitions"].values()) > 0
    assert "faults._PLAN_LOCK" in report["acquisitions"]
    assert "obs.metrics.Registry._lock" in report["acquisitions"]
    nodes, static_edges = build_lock_order_graph(repo_root)
    violations = check_embeds(fault_run["lock_edges"], static_edges, nodes)
    assert violations == [], "\n".join(violations)


# ---------------------------------------------------------------------------
# integration: SIGTERM mid-run -> journal resume
# ---------------------------------------------------------------------------


def test_sigterm_journals_and_resumes_byte_identical(fault_run):
    """SIGTERM mid-run: the run stops at the scene boundary with a valid
    partial report, the journal marks the in-flight scene, and the rerun
    skips journaled-done scenes (journal, not artifact, attribution),
    re-runs in-flight/never-started ones, and the final artifacts are
    byte-identical to an uninterrupted run."""
    from maskclustering_tpu.run import run_pipeline

    root = fault_run["root"]
    names = SCENES[:3]
    report_a = os.path.join(root, "sig_report.json")
    cfg = _cfg(root, config_name="sig", scene_overlap=False, prefetch_depth=0)
    # the plan delivers a REAL SIGTERM to this process during the second
    # scene's load; the installed handler converts it to a cooperative stop
    old_handler = faults.install_sigterm_handler()
    faults.set_plan(faults.FaultPlan.from_spec(f"sigterm:{names[1]}.load"))
    try:
        rep_a = run_pipeline(cfg, names, steps=("cluster",),
                             report_path=report_a)
    finally:
        faults.set_plan(None)
        signal.signal(signal.SIGTERM, old_handler)
    assert [s.status for s in rep_a.scenes] == ["ok", "interrupted",
                                                "interrupted"]
    assert not rep_a.ok and rep_a.faults["interrupted"]
    saved = json.load(open(report_a))  # the partial report is valid JSON
    assert [s["status"] for s in saved["scenes"]] == ["ok", "interrupted",
                                                      "interrupted"]
    journal_path = os.path.join(root, "run_journal.jsonl")
    replay = faults.replay_journal(journal_path, config="sig")
    assert replay[names[0]]["status"] == "ok"
    assert replay[names[1]]["status"] == "interrupted"  # in flight: re-run
    assert replay[names[1]]["attempts"] == 1
    assert replay[names[2]]["attempts"] == 0  # never started: re-run

    # rerun: journal-resume skips the done scene BEFORE any artifact
    # check, re-runs the rest
    faults.clear_stop()
    rep_b = run_pipeline(cfg, names, steps=("cluster",),
                         report_path=os.path.join(root, "sig_report_b.json"))
    assert [s.status for s in rep_b.scenes] == ["skipped", "ok", "ok"]
    assert rep_b.scenes[0].attempts == 0  # journal skip, not artifact skip
    assert rep_b.faults["journal_skips"] == 1
    assert rep_b.ok

    # A + B together == one uninterrupted run, byte for byte
    pred = os.path.join(root, "prediction")
    for seq in names:
        a = np.load(os.path.join(pred, "sig_class_agnostic", f"{seq}.npz"))
        b = np.load(os.path.join(pred, "ref_class_agnostic", f"{seq}.npz"))
        for key in ("pred_masks", "pred_score", "pred_classes"):
            np.testing.assert_array_equal(a[key], b[key])


# ---------------------------------------------------------------------------
# integration: overlapped-executor attribution at prefetch depths 0/1/2
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 1, 2])
def test_overlapped_fault_attribution_at_depth(fault_run, depth):
    """A FaultPlan-injected load failure through the REAL overlapped
    executor attributes to the failing scene alone at every prefetch
    depth — at depth 2 the failing FIRST scene's load resolves while its
    neighbor's lookahead load is already in flight, and the failure must
    not smear onto it. (Ordering combinatorics with synthetic loads are
    covered by test_executor.TestPrefetchDepth; this pins the FaultPlan ->
    executor wiring on the real pipeline at minimal wall cost.)"""
    from maskclustering_tpu.run import cluster_scenes

    names = [SCENES[1], SCENES[2]]  # fail the first, its neighbor survives
    faults.set_plan(faults.FaultPlan.from_spec(f"load:{names[0]}"))
    try:
        out = cluster_scenes(
            _cfg(fault_run["root"], config_name=f"d{depth}", scene_retries=0,
                 prefetch_depth=depth),
            names, resume=False)
    finally:
        faults.set_plan(None)
    assert [s.seq_name for s in out] == names
    assert [s.status for s in out] == ["failed", "ok"]
    assert out[0].error_class == "retryable"
    assert "InjectedFault" in out[0].error and names[0] in out[0].error
