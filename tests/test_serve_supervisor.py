"""Crash-contained serving (serve/supervisor.py + serve/worker_main.py).

Stub tier (tests/worker_stub.py — the pipe protocol in milliseconds, no
jax): heartbeat-silence SIGKILL, crash -> typed worker_crash status ->
requeue -> respawned-worker ok, poison-pill bounded failure, idle-crash
respawn, drain with a request in flight, and the crash/wedge FaultPlan
grammar + WorkerCrashError classification.

Acceptance tier (one real worker subprocess pair on the tiny 6-frame
bucket): a scripted ``crash:...device`` SIGKILLs the device-owning child
under an exporting request; the supervisor respawns, requeues, the
respawned worker answers ok with artifacts byte-identical to a one-shot
run, its ready digest books ZERO compiles (AOT + persistent-cache warm
start), and the per-request journal carries the crash-stamped
``interrupted`` row next to the final ok.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from maskclustering_tpu.config import load_config
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.serve.supervisor import (MAX_REQUEST_CRASHES,
                                                 WorkerSupervisor)
from maskclustering_tpu.utils import faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO_ROOT, "tests", "worker_stub.py")


def _cfg(tmp_path, **kw):
    base = dict(data_root=str(tmp_path), config_name="sup", step=1,
                distance_threshold=0.05, mask_pad_multiple=32,
                worker_heartbeat_s=1.0, retry_backoff_s=0.05)
    base.update(kw)
    return load_config("scannet").replace(**base)


class _Client:
    """Collects one request's events; done on the terminal one."""

    def __init__(self):
        self.events = []
        self.done = threading.Event()

    def send(self, ev):
        self.events.append(ev)
        if ev.get("kind") in ("result", "reject"):
            self.done.set()

    @property
    def terminal(self):
        return self.events[-1] if self.events else None

    def states(self):
        return [e.get("state") for e in self.events
                if e.get("kind") == "status"]


def _submit(queue, scene, i, **kw):
    client = _Client()
    req = protocol.build_request({"op": "scene", "scene": scene, **kw},
                                 f"r-{i:06d}")
    req.send = client.send
    queue.submit(req)
    return client


@pytest.fixture()
def stub_sup(tmp_path, monkeypatch):
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    cfg = _cfg(tmp_path)
    queue = AdmissionQueue(8)
    sup = WorkerSupervisor(cfg, queue, Router(cfg),
                           journal_dir=str(tmp_path / "journals"),
                           child_argv=[sys.executable, STUB],
                           start_timeout_s=15.0, poll_s=0.05)
    sup.start()
    yield sup, queue
    sup.stop(timeout_s=10.0)


def test_stub_serves_and_drains_in_flight(stub_sup):
    sup, queue = stub_sup
    c = _submit(queue, "stub-ok", 1)
    assert c.done.wait(10.0) and c.terminal["status"] == "ok"
    # the pump books counts after the reader answers the client: sync on
    # idle before reading them
    assert sup.wait_idle(5.0)
    assert sup.stats()["counts"]["ok"] == 1
    assert sup.last_ready.get("kind") == "ready"
    # drain with a slow request in flight: it still answers
    slow = _submit(queue, "stub-slow", 2)
    time.sleep(0.3)
    assert sup.stop(timeout_s=15.0)
    assert slow.done.wait(5.0) and slow.terminal["status"] == "ok"


def test_stub_canary_round_over_pipe(stub_sup):
    """mct-sentinel over the isolated-worker pipe: run_canary posts the
    op for the PUMP thread to ship (the child's stdin keeps its
    single-writer invariant — no lock ever wraps the pipe IO) and
    returns the child's probe rows; real traffic interleaves cleanly."""
    sup, queue = stub_sup
    probes = sup.run_canary(timeout_s=10.0)
    assert probes and probes[0]["coord"] == "k63:f32:n16384|bf16|single|r0|c0"
    assert probes[0]["digest"]["plane"] == "aaaaaaaa"
    c = _submit(queue, "stub-ok", 7)
    probes2 = sup.run_canary(timeout_s=10.0)
    assert c.done.wait(10.0) and c.terminal["status"] == "ok"
    assert probes2 and probes2[0]["scene"] == "A"


def test_stub_crash_respawns_requeues_and_pre_degrades(stub_sup):
    """A SIGKILL mid-request: typed worker_crash status (requeued), the
    respawned worker serves it pre-degraded (crashes -> rung), neighbors
    queued behind are untouched, and the journal carries the crash row."""
    sup, queue = stub_sup
    crash = _submit(queue, "stub-crash", 1)
    neighbor = _submit(queue, "stub-ok", 2)
    assert crash.done.wait(30.0), "crashed request never answered"
    assert neighbor.done.wait(30.0), "neighbor never answered"
    assert "worker_crash" in crash.states()
    crash_ev = next(e for e in crash.events
                    if e.get("state") == "worker_crash")
    assert crash_ev["requeued"] is True and crash_ev["crashes"] == 1
    assert crash.terminal["status"] == "ok"
    # the stub echoes the forwarded crash count: the respawned execution
    # saw crashes=1 (the worker pre-degrades its ladder by exactly that)
    assert crash.terminal["crashes_seen"] == 1
    assert neighbor.terminal["status"] == "ok"
    assert sup.crashes == 1 and sup.respawns == 1
    # crash-stamped journal attribution: interrupted row for the request
    replay = faults.replay_journal(
        os.path.join(sup.journal_dir, "r-000001.jsonl"), request="r-000001")
    assert replay["stub-crash"]["status"] == "interrupted"
    assert replay["stub-crash"]["error_class"] == "device"


def test_stub_wedge_heartbeat_sigkill_heals(stub_sup):
    """Heartbeat silence (the GIL-held-hang simulation): the supervisor
    SIGKILLs within the budget and the request heals on the respawn."""
    sup, queue = stub_sup
    t0 = time.monotonic()
    c = _submit(queue, "stub-wedge", 1)
    assert c.done.wait(30.0), "wedged request never answered"
    assert "worker_crash" in c.states()
    assert c.terminal["status"] == "ok"
    # detection is the heartbeat budget's business, not a long timeout:
    # budget 1s + spawn/respawn overhead, well under the 30s wait above
    assert time.monotonic() - t0 < 20.0
    assert sup.crashes == 1


def test_stub_poison_pill_fails_typed_after_bounded_crashes(stub_sup):
    sup, queue = stub_sup
    c = _submit(queue, "stub-crash-always", 1)
    assert c.done.wait(60.0), "poison pill never answered"
    assert c.terminal["kind"] == "result"
    assert c.terminal["status"] == "failed"
    assert c.terminal["error_class"] == "device"
    assert c.terminal["worker_crashes"] == MAX_REQUEST_CRASHES
    assert "worker crashed" in c.terminal["error"]
    assert sup.crashes == MAX_REQUEST_CRASHES
    # the daemon survives to serve the next request
    ok = _submit(queue, "stub-ok", 2)
    assert ok.done.wait(20.0) and ok.terminal["status"] == "ok"


def test_stub_idle_death_respawns(tmp_path, monkeypatch):
    """A worker that dies while IDLE (right after ready) is respawned
    without any request being harmed."""
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    monkeypatch.setenv("STUB_START_BEHAVIOR", "dead")
    cfg = _cfg(tmp_path)
    queue = AdmissionQueue(4)
    sup = WorkerSupervisor(cfg, queue, Router(cfg),
                           child_argv=[sys.executable, STUB],
                           start_timeout_s=15.0, poll_s=0.05)
    sup.start()
    try:
        c = _submit(queue, "stub-ok", 1)
        assert c.done.wait(20.0) and c.terminal["status"] == "ok"
        assert sup.crashes >= 1 and sup.respawns >= 1
    finally:
        sup.stop(timeout_s=10.0)


def test_crash_wedge_grammar_and_classification():
    plan = faults.FaultPlan.from_spec("crash:s1.device, wedge:s2.post:1")
    kinds = {(e.kind, e.seam, e.remaining) for e in plan.entries}
    assert kinds == {("crash", "device", 1), ("wedge", "post", 1)}
    with pytest.raises(ValueError):
        faults.FaultPlan.from_spec("crash:")
    err = faults.WorkerCrashError("sceneX", "rc -9")
    assert faults.classify_error(err) == "device"
    assert "sceneX" in str(err)


def test_scene_supervisor_initial_rungs():
    from maskclustering_tpu.run import SceneSupervisor

    cfg = load_config("scannet").replace(data_root="/tmp", config_name="x")
    sup = SceneSupervisor(cfg, initial_rungs=1)
    assert sup.ladder.rung == 1
    assert sup.ladder.applied_names == ["sequential-executor"]
    # over-asking clamps at the ladder depth instead of raising
    deep = SceneSupervisor(cfg, initial_rungs=99)
    assert deep.ladder.exhausted


# ---------------------------------------------------------------------------
# acceptance: a real SIGKILL'd device worker, end to end
# ---------------------------------------------------------------------------


@pytest.mark.slow  # minutes of real subprocess warm-up; ci.sh gates the
# same contract end to end via the rc-8 crash-respawn smoke
def test_real_worker_crash_respawn_byte_identical_zero_compiles(tmp_path):
    """The ISSUE-12 acceptance on a real worker subprocess pair: a
    scripted SIGKILL under an exporting request -> typed worker_crash +
    requeue -> the RESPAWNED worker (AOT + persistent-cache warm start,
    frozen sanitizer) answers ok with zero compiles booked and artifacts
    byte-identical to a one-shot run."""
    from maskclustering_tpu.analysis import retrace_sanitizer
    from maskclustering_tpu.run import run_pipeline
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    scene = "scene0000_00"
    spec = dict(num_boxes=3, num_frames=6, image_hw=(48, 64), spacing=0.08,
                seed=11)
    root = str(tmp_path / "data")
    write_scannet_layout(make_scene(**spec), root, scene)

    # byte-identity reference: the one-shot pipeline in this process
    ref_cfg = _cfg(root, config_name="isoref")
    ref = run_pipeline(ref_cfg, [scene], steps=("cluster",), resume=False,
                       journal=False, ledger=False)
    assert [s.status for s in ref.scenes] == ["ok"]

    cfg = _cfg(root, config_name="iso",
               aot_cache_dir=str(tmp_path / "aot"),
               worker_heartbeat_s=30.0, retry_backoff_s=0.1)
    queue = AdmissionQueue(4)
    prev_armed = retrace_sanitizer.enabled()
    retrace_sanitizer.arm(True)  # the child inherits --retrace-sanitizer
    sup = WorkerSupervisor(
        cfg, queue, Router(cfg),
        journal_dir=str(tmp_path / "journals"),
        warm_scenes=(scene,), freeze_after_warm=True,
        fault_plan_spec=f"crash:{scene}.device:1",
        start_timeout_s=300.0, poll_s=0.1)
    try:
        sup.start()
        c = _submit(queue, scene, 1)
        assert c.done.wait(300.0), "request never answered"
        assert "worker_crash" in c.states(), c.events
        assert c.terminal["status"] == "ok", c.terminal
        # the respawned worker served it pre-degraded by the crash
        assert c.terminal["rung"] >= 1
        assert sup.crashes == 1 and sup.respawns == 1
        # zero compiles on the respawned worker: its ready digest (AOT
        # restore + compilation-cache hits paid the warmth from disk)
        retrace = sup.last_ready.get("retrace") or {}
        assert retrace.get("frozen") is True
        assert retrace.get("compiles") == 0, retrace
        # crash-stamped journal: the interrupted row then the final ok
        replay = faults.replay_journal(
            os.path.join(sup.journal_dir, "r-000001.jsonl"),
            request="r-000001")
        assert replay[scene]["status"] == "ok"
        rows = faults.read_journal(
            os.path.join(sup.journal_dir, "r-000001.jsonl"),
            request="r-000001")
        assert any(r.get("status") == "interrupted" for r in rows)
    finally:
        retrace_sanitizer.arm(True if prev_armed else None)
        sup.stop(timeout_s=60.0)

    # artifacts byte-identical to the one-shot reference
    pred = os.path.join(root, "prediction")
    a = np.load(os.path.join(pred, "iso_class_agnostic", f"{scene}.npz"))
    b = np.load(os.path.join(pred, "isoref_class_agnostic", f"{scene}.npz"))
    assert set(a.files) == set(b.files)
    for key in a.files:
        np.testing.assert_array_equal(a[key], b[key])
    # the supervisor's verdict fields the Serving report renders
    w = sup.stats()["worker"]
    assert w["isolated"] and w["crashes"] == 1 and w["respawns"] == 1
    assert json.dumps(w)  # JSON-able for the daemon digest line
