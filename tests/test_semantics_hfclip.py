"""Real-CLIP execution path: HFCLIPEncoder driven by a tiny local fixture.

VERDICT r3 task 7: the class-aware semantics path had only ever executed
with HashEncoder; HFCLIPEncoder was dead code. This module vendors a few-MB
random-weight HuggingFace CLIP layout (config + Flax AND torch weights +
tokenizer + image processor) at test time — no network — and drives:

- Flax encode (the TPU path) and the torch-CPU fallback, numerically equal
  on the same weights;
- the full pipeline features -> label features -> query -> class-aware eval
  with encoder_spec="hf:<path>" (reference semantics stage,
  get_open-voc_features.py:101-143 / open-voc_query.py:32-55).
"""

import os

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

VOCAB = ["l", "o", "w", "e", "r", "s", "t", "i", "d", "n",
         "lo", "l</w>", "w</w>", "r</w>", "t</w>",
         "low</w>", "er</w>", "lowest</w>", "newer</w>", "wider",
         "<unk>", "<|startoftext|>", "<|endoftext|>"]
MERGES = ["#version: 0.2", "l o", "lo w</w>", "e r</w>"]


@pytest.fixture(scope="module")
def tiny_clip_dir(tmp_path_factory):
    import json

    from transformers import (
        CLIPConfig,
        CLIPImageProcessor,
        CLIPModel,
        CLIPTextConfig,
        CLIPTokenizer,
        CLIPVisionConfig,
        FlaxCLIPModel,
    )

    d = tmp_path_factory.mktemp("tiny_clip")
    vocab_file = d / "vocab.json"
    merges_file = d / "merges.txt"
    vocab_file.write_text(json.dumps({tok: i for i, tok in enumerate(VOCAB)}))
    merges_file.write_text("\n".join(MERGES))
    tok = CLIPTokenizer(str(vocab_file), str(merges_file))
    tok.save_pretrained(str(d))
    CLIPImageProcessor(size={"shortest_edge": 32},
                       crop_size={"height": 32, "width": 32}).save_pretrained(str(d))

    cfg = CLIPConfig.from_text_vision_configs(
        CLIPTextConfig(vocab_size=len(VOCAB), hidden_size=32,
                       intermediate_size=64, num_hidden_layers=2,
                       num_attention_heads=4, max_position_embeddings=77,
                       projection_dim=16),
        CLIPVisionConfig(hidden_size=32, intermediate_size=64,
                         num_hidden_layers=2, num_attention_heads=4,
                         image_size=32, patch_size=8, projection_dim=16),
        projection_dim=16,
    )
    flax_model = FlaxCLIPModel(cfg, seed=0)
    flax_model.save_pretrained(str(d))
    # same weights in torch format so the fallback path is comparable
    # (from_pretrained(from_flax=True) meta-init breaks save on this
    # transformers version; convert the params in-place instead)
    from transformers.modeling_flax_pytorch_utils import (
        load_flax_weights_in_pytorch_model,
    )

    pt_model = CLIPModel(cfg)
    load_flax_weights_in_pytorch_model(pt_model, flax_model.params)
    pt_model.save_pretrained(str(d), safe_serialization=False)
    return str(d)


def test_flax_and_torch_paths_agree(tiny_clip_dir, monkeypatch, rng):
    from maskclustering_tpu.semantics import HFCLIPEncoder

    enc = HFCLIPEncoder(tiny_clip_dir)
    assert enc._flax, "expected the Flax (TPU) path to load"
    assert enc.feature_dim == 16
    images = [rng.integers(0, 255, size=(40, 50, 3), dtype=np.uint8)
              for _ in range(3)]
    feats = enc.encode_images(images)
    assert feats.shape == (3, 16)
    np.testing.assert_allclose(np.linalg.norm(feats, axis=1), 1.0, rtol=1e-5)
    tfeats = enc.encode_texts(["lower", "newer"])
    assert tfeats.shape == (2, 16)

    # force the torch fallback and compare on identical weights
    import transformers as tf_mod

    def boom(*a, **k):
        raise OSError("flax disabled for test")

    monkeypatch.setattr(tf_mod.FlaxCLIPModel, "from_pretrained",
                        staticmethod(boom))
    enc_pt = HFCLIPEncoder(tiny_clip_dir)
    assert enc_pt._torch, "expected the torch fallback"
    feats_pt = enc_pt.encode_images(images)
    np.testing.assert_allclose(feats, feats_pt, rtol=2e-2, atol=2e-3)
    np.testing.assert_allclose(tfeats, enc_pt.encode_texts(["lower", "newer"]),
                               rtol=2e-2, atol=2e-3)


def test_class_aware_pipeline_with_real_clip(tiny_clip_dir, tmp_path):
    """features -> label features -> query -> class-aware eval, never
    touching HashEncoder."""
    from maskclustering_tpu.config import load_config
    from maskclustering_tpu.run import run_pipeline
    from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

    data_root = str(tmp_path / "data")
    scene = make_scene(num_boxes=3, num_frames=8, image_hw=(60, 80), seed=7)
    write_scannet_layout(scene, data_root, "scene0042_00")
    cfg = load_config("scannet").replace(
        data_root=data_root, config_name="cliprun", step=1,
        distance_threshold=0.05, mask_pad_multiple=32)
    report = run_pipeline(
        cfg, ["scene0042_00"],
        steps=("cluster", "eval_ca", "features", "label_features", "query",
               "eval"),
        encoder_spec=f"hf:{tiny_clip_dir}")
    assert [s.status for s in report.scenes] == ["ok"]
    assert not report.step_errors, report.step_errors

    aware = np.load(os.path.join(data_root, "prediction", "cliprun",
                                 "scene0042_00.npz"))
    assert aware["pred_masks"].shape[1] == 3
    assert (aware["pred_classes"] > 0).all()
    # label feature artifact has the checkpoint's projection dim
    lf = np.load(os.path.join(data_root, "text_features", "scannet.npy"),
                 allow_pickle=True).item()
    dims = {np.asarray(v).shape[-1] for v in lf.values()}
    assert dims == {16}
