import numpy as np
import jax.numpy as jnp
import pytest

from maskclustering_tpu.ops.neighbor import ball_query, ball_query_brute


def _random_problem(rng, b=3, p=40, s=70):
    query = rng.uniform(0, 1, size=(b, p, 3)).astype(np.float32)
    cand = rng.uniform(0, 1, size=(b, s, 3)).astype(np.float32)
    ql = rng.integers(1, p + 1, size=b)
    cl = rng.integers(1, s + 1, size=b)
    return query, cand, ql, cl


@pytest.mark.parametrize("seed,k,radius", [(0, 5, 0.2), (1, 3, 0.1), (2, 20, 0.35)])
def test_ball_query_matches_brute(seed, k, radius):
    rng = np.random.default_rng(seed)
    query, cand, ql, cl = _random_problem(rng)
    got = np.asarray(ball_query(jnp.asarray(query), jnp.asarray(cand),
                                jnp.asarray(ql), jnp.asarray(cl),
                                k=k, radius=radius, query_chunk=16))
    want = ball_query_brute(query, cand, ql, cl, k, radius)
    np.testing.assert_array_equal(got, want)


def test_ball_query_padding_rows_are_minus_one():
    rng = np.random.default_rng(3)
    query, cand, ql, cl = _random_problem(rng)
    ql[:] = 5
    got = np.asarray(ball_query(jnp.asarray(query), jnp.asarray(cand),
                                jnp.asarray(ql), jnp.asarray(cl), k=4, radius=0.3))
    assert (got[:, 5:, :] == -1).all()


def test_native_dbscan_matches_sklearn():
    from maskclustering_tpu.native import native_available

    if not native_available():
        from maskclustering_tpu.native.build import build

        build()
    from maskclustering_tpu.native import native_dbscan
    from sklearn.cluster import DBSCAN

    rng = np.random.default_rng(4)
    for trial in range(3):
        centers = rng.uniform(-3, 3, size=(4, 3))
        pts = np.concatenate(
            [c + rng.normal(0, 0.08, (rng.integers(30, 120), 3)) for c in centers]
            + [rng.uniform(-6, 6, (15, 3))]
        )
        for eps, mp in [(0.3, 4), (0.25, 8)]:
            lab = native_dbscan(pts, eps, mp)
            sk = DBSCAN(eps=eps, min_samples=mp).fit(pts).labels_
            # compare partitions over core-deterministic structure: noise sets
            # equal, and cluster memberships identical up to relabeling
            assert set(np.nonzero(lab == -1)[0]) == set(np.nonzero(sk == -1)[0])
            for l in np.unique(lab[lab >= 0]):
                members = lab == l
                assert len(np.unique(sk[members])) == 1


def test_native_connected_components_vs_networkx():
    import networkx as nx

    from maskclustering_tpu.native import native_available, native_connected_components

    if not native_available():
        pytest.skip("native lib not built")
    rng = np.random.default_rng(5)
    n = 200
    edges = rng.integers(0, n, size=(300, 2))
    labels = native_connected_components(edges[:, 0], edges[:, 1], n)
    g = nx.Graph()
    g.add_nodes_from(range(n))
    g.add_edges_from(edges.tolist())
    for comp in nx.connected_components(g):
        comp = sorted(comp)
        assert all(labels[c] == comp[0] for c in comp)


def test_native_outlier_removal():
    from maskclustering_tpu.native import native_available, native_statistical_outliers

    if not native_available():
        pytest.skip("native lib not built")
    rng = np.random.default_rng(6)
    cloud = rng.normal(0, 0.1, size=(500, 3))
    outliers = np.array([[5, 5, 5.0], [-4, 6, 2.0]])
    keep = native_statistical_outliers(np.concatenate([cloud, outliers]), 20, 2.0)
    assert not keep[-1] and not keep[-2]
    assert keep[:-2].mean() > 0.9


def test_dbscan_fixed_jax_long_chain():
    """A >64-hop chain of core points must collapse to ONE cluster.

    Regression: one-hop-per-iteration propagation with a fixed budget split
    long thin components; pointer jumping runs to fixpoint.
    """
    import jax.numpy as jnp

    from maskclustering_tpu.ops.dbscan import dbscan_fixed_jax, dbscan_labels

    n = 300
    pts = np.stack([np.arange(n) * 0.05, np.zeros(n), np.zeros(n)], axis=1)
    valid = np.ones(n, dtype=bool)
    lab = np.asarray(dbscan_fixed_jax(jnp.asarray(pts, jnp.float32), jnp.asarray(valid),
                                      eps=0.06, min_points=2))
    assert (lab >= 0).all()
    assert len(np.unique(lab)) == 1
    ref = dbscan_labels(pts, eps=0.06, min_points=2)
    assert len(np.unique(ref[ref >= 0])) == 1


def test_dbscan_fixed_jax_matches_host():
    """Cluster count parity with host DBSCAN on random blobs, incl. padding."""
    import jax.numpy as jnp

    from maskclustering_tpu.ops.dbscan import dbscan_fixed_jax, dbscan_labels

    rng = np.random.default_rng(3)
    blobs = [rng.normal(c, 0.03, size=(40, 3)) for c in
             [(0, 0, 0), (1, 0, 0), (0, 1, 0)]]
    pts = np.concatenate(blobs)
    pad = 8
    pts_pad = np.concatenate([pts, np.full((pad, 3), 50.0)])
    valid = np.concatenate([np.ones(len(pts), bool), np.zeros(pad, bool)])
    lab = np.asarray(dbscan_fixed_jax(jnp.asarray(pts_pad, jnp.float32),
                                      jnp.asarray(valid), eps=0.2, min_points=4))
    ref = dbscan_labels(pts, eps=0.2, min_points=4)
    assert (lab[len(pts):] == -1).all()
    n_jax = len(np.unique(lab[:len(pts)][lab[:len(pts)] >= 0]))
    n_ref = len(np.unique(ref[ref >= 0]))
    assert n_jax == n_ref == 3


def test_native_dbscan_dense_cloud_near_linear():
    """Complexity guard: 50k densely-packed points must cluster in seconds.

    The per-point neighbor-list formulation degenerated to O(n * density *
    eps^3) on dense clouds (~10 s at this shape); the grid/union-find
    version runs it in ~35 ms. The generous bound stays robust on a loaded
    CI host while still failing any quadratic regression by an order of
    magnitude.
    """
    import time

    from maskclustering_tpu.native import native_available, native_dbscan

    if not native_available():
        pytest.skip("native lib not built")
    # call native_dbscan directly: dbscan_labels dispatches on an
    # import-time-frozen flag, which would silently time the sklearn
    # fallback when the .so was built mid-session by an earlier test
    rng = np.random.default_rng(7)
    n = 50_000
    side = int(np.ceil(n ** (1 / 3)))
    grid = np.stack(np.meshgrid(*[np.arange(side)] * 3, indexing="ij"),
                    -1).reshape(-1, 3)[:n] * 0.008
    pts = grid + rng.normal(0, 0.002, grid.shape)
    t0 = time.perf_counter()
    labels = native_dbscan(pts, 0.1, 4)
    dt = time.perf_counter() - t0
    assert labels.max() == 0 and (labels >= 0).all()  # one dense cluster
    assert dt < 5.0, f"dense DBSCAN took {dt:.1f}s — complexity regression"
