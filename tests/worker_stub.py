"""A jax-free stand-in for serve/worker_main.py (supervisor unit tests).

Speaks the exact stdio pipe protocol (ready / hb / telem / status /
result / bye) in milliseconds, so the supervisor's heartbeat,
SIGKILL-on-wedge, respawn, requeue, drain AND telemetry-relay logic are
all testable without paying two jax startups. Each request emits one
``telem`` line (counter deltas + a relayed ``serve.request`` span) before
its result, mirroring worker_main's request-boundary flush. Scene names
script behaviors; "once-only" behaviors leave a marker file in $STUB_DIR
so the RESPAWNED stub serves the same scene cleanly:

    stub-ok     answer ok after 50 ms
    stub-crash  SIGKILL this process mid-request (once; then ok)
    stub-wedge  silence heartbeats and hang (once; then ok)
    stub-dead   SIGKILL while idle, right after ready (once)
    stub-slow   answer ok after ~1.5 s (drain-with-in-flight cases)
"""

import json
import os
import signal
import sys
import threading
import time

STUB_DIR = os.environ.get("STUB_DIR", "/tmp")


def emit(doc):
    sys.stdout.write(json.dumps(doc) + "\n")
    sys.stdout.flush()


def once(name) -> bool:
    """True the FIRST time this behavior fires across stub generations."""
    marker = os.path.join(STUB_DIR, f"stub_{name}.fired")
    if os.path.exists(marker):
        return False
    with open(marker, "w"):
        pass
    return True


def main():
    hb_stop = threading.Event()
    seq = [0]  # telem sequence counter (one line per served request)

    def hb():
        while not hb_stop.wait(0.05):
            emit({"kind": "hb"})

    threading.Thread(target=hb, daemon=True).start()
    emit({"kind": "ready", "pid": os.getpid(), "warmup_s": 0.0,
          "aot": {"restored": 0}, "retrace": {"compiles": 0, "frozen": True}})
    if once("spawncount"):
        pass  # first generation marker (tests read the .fired files)
    with open(os.path.join(STUB_DIR, f"stub_gen_{os.getpid()}.pid"), "w"):
        pass
    if "dead" in os.environ.get("STUB_START_BEHAVIOR", "") and once("dead"):
        os.kill(os.getpid(), signal.SIGKILL)
    for line in sys.stdin:
        if not line.strip():
            continue
        doc = json.loads(line)
        if doc.get("op") == "shutdown":
            break
        if doc.get("op") == "canary":
            # worker_main's mct-sentinel answer, in miniature: one probe
            # row per warm bucket (the supervisor relays these verbatim)
            emit({"kind": "canary", "probes": [
                {"coord": "k63:f32:n16384|bf16|single|r0|c0", "scene": "A",
                 "digest": {"v": 1, "bucket": "k63:f32:n16384",
                            "count_dtype": "bf16", "plane": "aaaaaaaa",
                            "artifact": "bbbbbbbb", "nan_inf": 0}}]})
            continue
        if doc.get("op") in ("stream_chunk", "stream_end"):
            # live-scan session, in miniature: every chunk answers ok /
            # not-done (the supervisor's open-stream tracker latches),
            # stream_end closes it. The crash scenes behave as for
            # "scene" ops, so stream-loss-on-crash is testable here.
            rid, scene = doc["id"], doc["scene"]
            emit({"kind": "status", "id": rid, "state": "running",
                  "scene": scene})
            if scene == "stub-crash" and once("crash"):
                os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(0.05)
            done = doc["op"] == "stream_end"
            emit({"kind": "result", "id": rid, "status": "ok",
                  "seconds": 0.05, "done": done, "partial_instances": 1,
                  "frames_seen": 2})
            continue
        if doc.get("op") != "scene":
            continue
        rid, scene = doc["id"], doc["scene"]
        emit({"kind": "status", "id": rid, "state": "running",
              "scene": scene})
        if scene == "stub-crash" and once("crash"):
            os.kill(os.getpid(), signal.SIGKILL)
        if scene == "stub-crash-always":  # the poison pill: every worker dies
            os.kill(os.getpid(), signal.SIGKILL)
        if scene == "stub-wedge" and once("wedge"):
            hb_stop.set()
            while True:
                time.sleep(60)
        dur = 1.5 if scene == "stub-slow" else 0.05
        time.sleep(dur)
        # worker_main's request-boundary telemetry flush, in miniature:
        # counter deltas fold into the parent registry, the span replays
        seq[0] += 1
        emit({"kind": "telem", "v": 1, "seq": seq[0],
              "metrics": {"counters": {"serve.requests": 1,
                                       "serve.requests_ok": 1,
                                       "d2h.bytes": 4096,
                                       "pipeline.host_sync": 1},
                          "gauges": {}},
              "spans": [{"name": "serve.request", "dur_s": dur,
                         "sync_s": 0.0, "depth": 0, "ts": time.time(),
                         "attrs": {"request": rid, "scene": scene}}]})
        emit({"kind": "result", "id": rid, "status": "ok", "seconds": 0.05,
              "attempts": 1, "rung": doc.get("crashes", 0),
              "buckets_new": 0, "crashes_seen": doc.get("crashes", 0)})
    emit({"kind": "bye", "retrace": {"compiles": 0}})
    return 0


if __name__ == "__main__":
    sys.exit(main())
