"""L2 mask-prediction tests: id-map contract + pluggable predictors.

The oracle is reference mask_predict.py:94-114: keep masks with
confidence >= 0.5, iterate in ascending score order assigning ids 1..K,
skip sub-400-pixel masks without consuming an id, later (higher
confidence) masks overwrite earlier ones.
"""

import os

import numpy as np

from maskclustering_tpu.io.image import read_mask_png
from maskclustering_tpu.mask_prediction import (
    GridSegmenter,
    _connected_components,
    predict_scene_masks,
    rasterize_id_map,
)


def _reference_rasterize(masks, scores, conf=0.5, min_px=400):
    """Literal re-statement of the reference loop as the test oracle."""
    keep = scores >= conf
    masks, scores = masks[keep], scores[keep]
    h, w = masks.shape[1:]
    out = np.zeros((h, w), dtype=np.int64)
    mask_id = 1
    for index in np.argsort(scores, kind="stable"):
        if masks[index].sum() < min_px:
            continue
        out[masks[index]] = mask_id
        mask_id += 1
    return out


class TestRasterize:
    def test_matches_reference_loop_random(self):
        rng = np.random.default_rng(0)
        for trial in range(5):
            h, w = 40, 50
            k = 8
            masks = np.zeros((k, h, w), dtype=bool)
            for i in range(k):
                y, x = rng.integers(0, h - 25), rng.integers(0, w - 30)
                masks[i, y:y + rng.integers(8, 25), x:x + rng.integers(10, 30)] = True
            scores = rng.uniform(0.2, 1.0, size=k).astype(np.float32)
            got = rasterize_id_map(masks, scores, min_pixels=100)
            want = _reference_rasterize(masks, scores, min_px=100)
            np.testing.assert_array_equal(got, want)

    def test_overwrite_order(self):
        # two overlapping masks: the higher-confidence one wins the overlap
        masks = np.zeros((2, 30, 30), dtype=bool)
        masks[0, :20, :20] = True  # low conf
        masks[1, 10:, 10:] = True  # high conf
        scores = np.array([0.6, 0.9])
        out = rasterize_id_map(masks, scores, min_pixels=10)
        assert out[5, 5] == 1  # only low-conf mask
        assert out[15, 15] == 2  # overlap -> high conf id
        assert out[25, 25] == 2

    def test_small_masks_skip_without_consuming_id(self):
        masks = np.zeros((3, 40, 40), dtype=bool)
        masks[0, :20, :20] = True  # 400 px, kept (id from order)
        masks[1, 0, :5] = True  # 5 px, skipped
        masks[2, 20:, 20:] = True  # 400 px, kept
        scores = np.array([0.7, 0.8, 0.9])
        out = rasterize_id_map(masks, scores)
        # skipped mask consumes no id: ids are 1 (mask0) and 2 (mask2)
        assert set(np.unique(out)) == {0, 1, 2}
        assert out[0, 0] == 1 and out[30, 30] == 2

    def test_confidence_filter_and_empty(self):
        masks = np.ones((1, 30, 30), dtype=bool)
        out = rasterize_id_map(masks, np.array([0.3]))
        assert out.dtype == np.uint8 and out.max() == 0
        out2 = rasterize_id_map(np.zeros((0, 8, 8), dtype=bool), np.zeros(0))
        assert out2.shape == (8, 8) and out2.max() == 0

    def test_uint16_when_many_masks(self):
        k, h, w = 300, 40, 600
        masks = np.zeros((k, h, w), dtype=bool)
        for i in range(k):
            masks[i, :, 2 * i:2 * i + 2] = True  # 80 px each
        scores = np.linspace(0.5, 1.0, k)
        out = rasterize_id_map(masks, scores, min_pixels=50)
        assert out.dtype == np.uint16
        assert out.max() == k


class TestConnectedComponents:
    def test_two_regions(self):
        key = np.array([[1, 1, 2], [1, 2, 2]])
        labels = _connected_components(key)
        assert labels[0, 0] == labels[0, 1] == labels[1, 0]
        assert labels[0, 2] == labels[1, 1] == labels[1, 2]
        assert labels[0, 0] != labels[0, 2]

    def test_diagonal_not_connected(self):
        key = np.array([[1, 2], [2, 1]])
        labels = _connected_components(key)
        assert labels[0, 0] != labels[1, 1]  # 4-connectivity only


class _FakeDataset:
    """Duck-typed dataset exposing just what predict_scene_masks uses."""

    def __init__(self, root, frames, rgbs):
        self.segmentation_dir = os.path.join(root, "output", "mask")
        self._frames = frames
        self._rgbs = rgbs

    def get_frame_list(self, stride):
        return self._frames[::stride]

    def get_rgb(self, frame_id):
        return self._rgbs[self._frames.index(frame_id)]


class TestPredictSceneMasks:
    def _rgb_two_blocks(self):
        rgb = np.zeros((40, 60, 3), dtype=np.uint8)
        rgb[:, :30] = [200, 30, 30]
        rgb[:, 30:] = [30, 200, 30]
        return rgb

    def test_grid_segmenter_end_to_end(self, tmp_path):
        rgb = self._rgb_two_blocks()
        ds = _FakeDataset(str(tmp_path), [0, 1, 2], [rgb, rgb, rgb])
        written = predict_scene_masks(ds, GridSegmenter(), stride=2)
        assert len(written) == 2  # frames 0 and 2
        seg = read_mask_png(os.path.join(ds.segmentation_dir, "0.png"))
        assert seg.shape == (40, 60)
        # the two color blocks become two distinct non-zero ids
        left, right = seg[20, 10], seg[20, 50]
        assert left != 0 and right != 0 and left != right

    def test_resume_skips_existing(self, tmp_path):
        rgb = self._rgb_two_blocks()
        ds = _FakeDataset(str(tmp_path), [0], [rgb])
        first = predict_scene_masks(ds, GridSegmenter())
        second = predict_scene_masks(ds, GridSegmenter())
        assert len(first) == 1 and len(second) == 0

    def test_pipeline_masks_step_uses_predictor(self, tmp_path):
        from maskclustering_tpu.config import load_config
        from maskclustering_tpu.run import check_masks
        from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout
        import shutil

        scene = make_scene(num_boxes=2, num_frames=4, image_hw=(48, 64), seed=3)
        root = str(tmp_path / "data")
        write_scannet_layout(scene, root, "scene0000_00")
        # remove the oracle masks so the step must regenerate them
        seg_dir = os.path.join(root, "scannet", "processed", "scene0000_00",
                               "output", "mask")
        shutil.rmtree(seg_dir)
        cfg = load_config("scannet").replace(data_root=root, step=1)
        missing = check_masks(cfg, ["scene0000_00"],
                              mask_predictor=GridSegmenter())
        assert missing == []
        assert len(os.listdir(seg_dir)) == 4


class TestReviewRegressions:
    def test_cc_snake_region(self):
        # serpentine region exercises multi-sweep convergence
        key = np.zeros((8, 8), dtype=np.int64)
        key[0, :] = 1
        key[1:, -1] = 1
        key[-1, :] = 1
        labels = _connected_components(key)
        snake = labels[key == 1]
        assert len(np.unique(snake)) == 1
        assert labels[0, 0] != labels[4, 0]

    def test_cc_fast_on_large_frame(self):
        import time

        rng = np.random.default_rng(0)
        key = rng.integers(0, 4, size=(480, 640))
        t0 = time.perf_counter()
        labels = _connected_components(key)
        assert time.perf_counter() - t0 < 10.0
        assert labels.shape == key.shape

    def test_quant_hash_no_collision(self):
        rgb = np.zeros((30, 40, 3), dtype=np.uint8)
        rgb[:, :20] = [0, 1, 0]
        rgb[:, 20:] = [0, 0, 200]
        masks, _ = GridSegmenter(quant=1, min_region=50)(rgb)
        assert len(masks) == 2  # distinct colors stay distinct

    def test_writes_dataset_contract_paths(self, tmp_path):
        class ContractDS:
            segmentation_dir = str(tmp_path / "seg")

            def get_frame_list(self, stride):
                return [5]

            def get_frame_path(self, fid):
                return (str(tmp_path / "rgb" / f"frame_{fid:06d}.jpg"),
                        str(tmp_path / "seg" / f"frame_{fid:06d}.png"))

            def get_rgb(self, fid):
                rgb = np.zeros((40, 60, 3), dtype=np.uint8)
                rgb[:, :30] = [200, 30, 30]
                rgb[:, 30:] = [30, 200, 30]
                return rgb

        written = predict_scene_masks(ContractDS(), GridSegmenter())
        assert written == [str(tmp_path / "seg" / "frame_000005.png")]
        assert os.path.exists(written[0])

    def test_draw_bbox_at_origin_keeps_all_edges(self):
        from maskclustering_tpu.visualize import draw_bbox

        rgb = np.zeros((50, 50, 3), dtype=np.uint8)
        out = draw_bbox(rgb, (0, 0, 10, 10), thickness=4)
        assert tuple(out[10, 5]) == (255, 0, 0)  # bottom edge drawn
        assert tuple(out[5, 10]) == (255, 0, 0)  # right edge drawn
        assert tuple(out[49, 5]) == (0, 0, 0)  # no wraparound
