"""Perf-ledger contract tests (obs/ledger.py + report --history/--regress).

Pins: schema-versioned append/read round-trip, crash-tolerant reads with
counted skips, baseline loading from both ledger JSONL and bench-verdict
JSON documents, and the CI gate — `report --regress` exits non-zero on an
injected >15% p50 regression and zero inside the threshold.
"""

import json

from maskclustering_tpu.obs import ledger as led
from maskclustering_tpu.obs.events import ReadStats
from maskclustering_tpu.obs.report import main as report_main


def _verdict(value, stages=None, **kw):
    v = {"metric": "bench s/scene", "value": value, "unit": "s/scene"}
    if stages:
        v["stages"] = stages
    v.update(kw)
    return v


def test_append_read_round_trip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    assert led.append_row(path, led.bench_row(
        _verdict(3.2, stages={"associate": 1.1}, vs_baseline=23.4,
                 attempts=1)))
    assert led.append_row(path, led.bench_row(_verdict(None, error="wedge")))
    rows = led.read_ledger(path)
    assert len(rows) == 2
    assert rows[0]["v"] == led.LEDGER_SCHEMA_VERSION
    assert rows[0]["tool"] == "bench"
    assert rows[0]["value"] == 3.2
    assert rows[0]["stages"] == {"associate": 1.1}
    assert rows[0]["vs_baseline"] == 23.4
    assert "ts" in rows[0] and "pid" in rows[0]
    assert rows[1]["value"] is None and rows[1]["error"] == "wedge"
    # newest NUMERIC row wins; a null verdict is history, not a baseline
    assert led.latest_value_row(rows)["value"] == 3.2


def test_run_row_digest(tmp_path):
    report = {
        "config_name": "demo",
        "scenes": [
            {"status": "ok", "seconds": 2.0},
            {"status": "ok", "seconds": 4.0},
            {"status": "ok", "seconds": 3.0},
            {"status": "failed", "seconds": 9.9},
        ],
        "obs": {"stages": {"associate": {"p50_s": 1.2},
                           "cluster": {"p50_s": 0.3}}},
    }
    row = led.run_row(report)
    assert row["tool"] == "run"
    assert row["value"] == 3.0  # median of ok scenes; failures excluded
    assert row["scenes_ok"] == 3 and row["scenes_failed"] == 1
    assert row["stages"] == {"associate": 1.2, "cluster": 0.3}


def test_read_tolerates_torn_and_unknown_lines_with_counts(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    led.append_row(path, led.bench_row(_verdict(1.0)))
    with open(path, "a") as f:
        f.write(json.dumps({"v": 999, "value": 0.5}) + "\n")
        f.write('{"v": 1, "value": 2.0, "tru')  # crash mid-write
    stats = ReadStats()
    rows = led.read_ledger(path, stats=stats)
    assert [r["value"] for r in rows] == [1.0]
    assert stats.torn == 1 and stats.unknown_version == 1
    assert stats.skipped == 2
    assert "1 torn" in stats.describe()


def test_check_regression_thresholds():
    base = {"value": 1.0, "stages": {"associate": 0.5}}
    ok, _ = led.check_regression({"value": 1.10}, base)
    assert ok  # +10% is inside the 15% gate
    ok, lines = led.check_regression(
        {"value": 1.30, "stages": {"associate": 0.9}}, base)
    assert not ok
    assert any("REGRESSION" in ln for ln in lines)
    assert any("stage associate" in ln for ln in lines)  # advisory drift
    ok, _ = led.check_regression(None, base)
    assert not ok  # an empty trajectory must not pass a CI gate
    ok, _ = led.check_regression({"value": 1.0}, None)
    assert not ok


def test_bench_row_carries_dtype_attribution():
    row = led.bench_row(_verdict(3.2, count_dtype="int8",
                                 plane_dtype="int16",
                                 postprocess_path="host"))
    assert row["count_dtype"] == "int8"
    assert row["plane_dtype"] == "int16"
    assert row["postprocess_path"] == "host"
    # rows predating the knob simply lack the keys — no synthesized default
    assert "count_dtype" not in led.bench_row(_verdict(3.2))
    assert "postprocess_path" not in led.bench_row(_verdict(3.2))


def test_check_regression_flags_postprocess_path_flip():
    """A --host-postprocess A/B row must be attributed to the knob, not
    read as drift; pre-knob rows compare as the device default."""
    base = {"value": 1.0, "postprocess_path": "device"}
    ok, lines = led.check_regression(
        {"value": 1.1, "postprocess_path": "host"}, base)
    assert ok
    assert any("postprocess_path: device -> host" in ln for ln in lines)
    # no flip (current device vs keyless pre-knob baseline) -> no noise
    ok, lines = led.check_regression(
        {"value": 1.0, "postprocess_path": "device"}, {"value": 1.0})
    assert not any("postprocess_path" in ln for ln in lines)


def test_check_regression_flags_dtype_flip():
    """A headline delta coinciding with a count_dtype flip must be called
    out as knob attribution, not silently read as code drift; rows without
    the keys compare as the historical defaults (bf16 / int32 planes)."""
    base = {"value": 1.0, "count_dtype": "bf16", "plane_dtype": "int16"}
    ok, lines = led.check_regression(
        {"value": 1.05, "count_dtype": "int8", "plane_dtype": "int16"}, base)
    assert ok
    assert any("count_dtype: bf16 -> int8" in ln for ln in lines)
    assert not any("plane_dtype" in ln for ln in lines)
    # a pre-knob baseline row (no keys) vs a current int16-plane row
    ok, lines = led.check_regression({"value": 1.0, "plane_dtype": "int16"},
                                     {"value": 1.0})
    assert ok
    assert any("plane_dtype: int32 -> int16" in ln for ln in lines)
    # no flip, no noise
    ok, lines = led.check_regression({"value": 1.0}, {"value": 1.0})
    assert not any("dtype" in ln for ln in lines)


def test_report_regress_exit_codes(tmp_path, capsys):
    """The acceptance gate: injected 15%+ regression -> non-zero exit."""
    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        json.dump(_verdict(1.0), f)
    ledger = str(tmp_path / "ledger.jsonl")

    led.append_row(ledger, led.bench_row(_verdict(1.2)))  # +20%: regression
    rc = report_main(["--ledger", ledger, "--regress", baseline])
    assert rc == 2
    assert "REGRESSION" in capsys.readouterr().out

    ledger2 = str(tmp_path / "ledger2.jsonl")
    led.append_row(ledger2, led.bench_row(_verdict(1.05)))  # +5%: fine
    rc = report_main(["--ledger", ledger2, "--regress", baseline])
    assert rc == 0
    # custom threshold flag tightens the gate
    rc = report_main(["--ledger", ledger2, "--regress", baseline,
                      "--regress-threshold", "0.01"])
    assert rc == 2
    capsys.readouterr()


def test_report_regress_baseline_from_ledger(tmp_path, capsys):
    base_ledger = str(tmp_path / "base.jsonl")
    led.append_row(base_ledger, led.bench_row(_verdict(2.0)))
    led.append_row(base_ledger, led.bench_row(_verdict(None, error="x")))
    cur = str(tmp_path / "cur.jsonl")
    led.append_row(cur, led.bench_row(_verdict(2.1)))
    # baseline = newest NUMERIC row of the baseline ledger (2.0); +5% passes
    assert report_main(["--ledger", cur, "--regress", base_ledger]) == 0
    capsys.readouterr()


def test_regress_gates_comparable_metric_rows(tmp_path, capsys):
    """A newer run-row (different metric) must not hijack the gate when a
    comparable bench row exists; with no comparable row the gate falls
    back to the newest numeric row WITH a printed warning."""
    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        json.dump(_verdict(1.0), f)  # metric: "bench s/scene"
    ledger = str(tmp_path / "ledger.jsonl")
    led.append_row(ledger, led.bench_row(_verdict(1.05)))
    # a big slow run-row lands AFTER the bench row, with its own metric
    led.append_row(ledger, {"tool": "run", "metric": "run s/scene",
                            "value": 9.0, "unit": "s/scene"})
    rc = report_main(["--ledger", ledger, "--regress", baseline])
    out = capsys.readouterr().out
    assert rc == 0, out  # gated 1.05 vs 1.0, not 9.0 vs 1.0
    assert "1.050" in out

    # only the incomparable row present -> fallback + warning, still gates
    ledger2 = str(tmp_path / "ledger2.jsonl")
    led.append_row(ledger2, {"tool": "run", "metric": "run s/scene",
                             "value": 9.0, "unit": "s/scene"})
    rc = report_main(["--ledger", ledger2, "--regress", baseline])
    out = capsys.readouterr().out
    assert rc == 2
    assert "no ledger row matches baseline metric" in out


def test_report_json_is_one_document_across_sections(tmp_path, capsys):
    """--json with --history/--regress must keep stdout one parseable JSON
    document (no tables after it)."""
    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        json.dump(_verdict(1.0), f)
    ledger = str(tmp_path / "ledger.jsonl")
    led.append_row(ledger, led.bench_row(_verdict(1.3)))
    rc = report_main(["--ledger", ledger, "--json", "--history",
                      "--regress", baseline])
    assert rc == 2  # the gate verdict still drives the exit code
    doc = json.loads(capsys.readouterr().out)  # parseable => contract holds
    assert [r["value"] for r in doc["history"]] == [1.3]
    assert doc["regress"]["ok"] is False
    assert doc["regress"]["current"]["value"] == 1.3


def test_latest_value_row_metric_filter():
    rows = [{"value": 1.0, "metric": "a"}, {"value": None, "metric": "a"},
            {"value": 2.0, "metric": "b"}]
    assert led.latest_value_row(rows)["value"] == 2.0
    assert led.latest_value_row(rows, metric="a")["value"] == 1.0
    assert led.latest_value_row(rows, metric="zzz") is None


def test_report_history_renders(tmp_path, capsys):
    ledger = str(tmp_path / "ledger.jsonl")
    led.append_row(ledger, led.bench_row(
        _verdict(3.206, stages={"associate": 1.091}, vs_baseline=23.39)))
    led.append_row(ledger, led.bench_row(_verdict(None, error="backend init "
                                                  "timed out")))
    assert report_main(["--ledger", ledger, "--history"]) == 0
    out = capsys.readouterr().out
    assert "perf ledger" in out and "2 rows" in out
    assert "3.206" in out and "23.4x" in out
    assert "backend init" in out  # null verdicts stay on the record


def test_bench_appends_ledger_row_by_default(tmp_path, monkeypatch):
    """bench.py --worker on CPU: the verdict line lands in the ledger
    (MCT_PERF_LEDGER routes it; conftest sets a per-test default)."""
    import os
    import subprocess
    import sys

    ledger = str(tmp_path / "bench_ledger.jsonl")
    env = dict(os.environ, MCT_PERF_LEDGER=ledger, JAX_PLATFORMS="cpu")
    env.pop("MCT_BENCH_SUPERVISED", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "bench.py", "--worker", "--platform", "cpu",
         "--frames", "4", "--points", "1024", "--boxes", "2",
         "--image-h", "32", "--image-w", "48", "--repeats", "1",
         "--spacing", "0.1", "--k-max", "7"],
        capture_output=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-800:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    rows = led.read_ledger(ledger)
    assert len(rows) == 1
    assert rows[0]["value"] == verdict["value"]
    assert rows[0]["tool"] == "bench"
    assert rows[0]["v"] == led.LEDGER_SCHEMA_VERSION
