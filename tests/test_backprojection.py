import jax.numpy as jnp
import numpy as np
import pytest

from maskclustering_tpu.models.backprojection import (
    FrameAssociation,
    associate_frame,
    associate_scene,
)
from maskclustering_tpu.utils.synthetic import make_scene

# looser-than-real thresholds sized for the synthetic scene's point spacing
DT = 0.03
COV = 0.3


def _assoc_frame(scene, f, **kw):
    args = dict(
        k_max=15, window=1, distance_threshold=DT, depth_trunc=20.0,
        few_points_threshold=25, coverage_threshold=COV,
    )
    args.update(kw)
    return associate_frame(
        jnp.asarray(scene.scene_points),
        jnp.asarray(scene.depths[f]),
        jnp.asarray(scene.segmentations[f]),
        jnp.asarray(scene.intrinsics[f]),
        jnp.asarray(scene.cam_to_world[f]),
        jnp.asarray(scene.frame_valid[f]),
        **args,
    )


@pytest.fixture(scope="module")
def scene():
    return make_scene(num_boxes=4, num_frames=8, seed=3)


def test_points_land_on_their_own_object(scene):
    fa = _assoc_frame(scene, 0)
    mop = np.asarray(fa.mask_of_point)
    obj_of_mask = scene.object_of_mask[0]
    claimed = mop > 0
    # a healthy fraction of box points should be claimed in a frame seeing them
    assert claimed.sum() > 500
    # claimed points must overwhelmingly carry their own gt object's mask id
    got_obj = obj_of_mask[mop[claimed]]
    agree = (got_obj == scene.gt_instance[claimed]).mean()
    assert agree > 0.97, f"agreement {agree}"


def test_floor_points_unclaimed(scene):
    fa = _assoc_frame(scene, 0)
    mop = np.asarray(fa.mask_of_point)
    floor = scene.gt_instance == 0
    # floor is background (seg id 0) so floor points must stay unclaimed
    assert (mop[floor] > 0).mean() < 0.01


def test_occluded_points_not_claimed(scene):
    """Points on the far side of a box (occluded) must not be claimed."""
    fa = _assoc_frame(scene, 0)
    mop = np.asarray(fa.mask_of_point)
    # world points more than 2*DT behind the rendered depth at their pixel
    from maskclustering_tpu.ops.geometry import invert_se3

    w2c = np.asarray(invert_se3(jnp.asarray(scene.cam_to_world[0])))
    cam = scene.scene_points @ w2c[:3, :3].T + w2c[:3, 3]
    h, w = scene.depths[0].shape
    fx, fy = scene.intrinsics[0][0, 0], scene.intrinsics[0][1, 1]
    cx, cy = scene.intrinsics[0][0, 2], scene.intrinsics[0][1, 2]
    u = np.round(cam[:, 0] / cam[:, 2] * fx + cx).astype(int)
    v = np.round(cam[:, 1] / cam[:, 2] * fy + cy).astype(int)
    inb = (u >= 0) & (u < w) & (v >= 0) & (v < h) & (cam[:, 2] > 0)
    d = np.zeros(len(cam))
    d[inb] = scene.depths[0][v[inb], u[inb]]
    occluded = inb & (cam[:, 2] > d + 4 * DT) & (d > 0)
    assert occluded.sum() > 50  # scene has occlusions at all
    assert (mop[occluded] > 0).mean() < 0.02


def test_ghost_mask_rejected_by_coverage():
    """A mask over geometry missing from the scene cloud must be dropped."""
    scene = make_scene(num_boxes=3, num_frames=6, seed=5, ghost_box=True)
    ghost_obj = 4  # boxes are objects 1..3, ghost is 4
    hits = 0
    for f in range(6):
        fa = _assoc_frame(scene, f)
        valid = np.asarray(fa.mask_valid)
        ghost_mask_id = np.nonzero(scene.object_of_mask[f] == ghost_obj)[0]
        real_ids = np.nonzero((scene.object_of_mask[f] > 0) & (scene.object_of_mask[f] != ghost_obj))[0]
        npix = np.asarray(fa.n_pixels)
        if len(ghost_mask_id) and npix[ghost_mask_id[0]] > 100:
            hits += 1
            assert not valid[ghost_mask_id[0]], f"ghost mask survived in frame {f}"
        # at least some real masks valid
        assert valid[real_ids].sum() >= 1
    assert hits >= 2  # the ghost was actually visible in several frames


def test_tiny_mask_rejected(scene):
    fa = _assoc_frame(scene, 0, few_points_threshold=10 ** 9)
    assert not np.asarray(fa.mask_valid).any()


def test_boundary_points_zeroed_but_tracked():
    """Points claimed by two masks are boundary: id 0 but first/last kept."""
    scene = make_scene(num_boxes=4, num_frames=8, seed=3)
    out = associate_scene(
        jnp.asarray(scene.scene_points),
        jnp.asarray(scene.depths),
        jnp.asarray(scene.segmentations),
        jnp.asarray(scene.intrinsics),
        jnp.asarray(scene.cam_to_world),
        jnp.asarray(scene.frame_valid),
        k_max=15, window=1, distance_threshold=DT,
        few_points_threshold=25, coverage_threshold=COV,
    )
    first = np.asarray(out.first_id)
    last = np.asarray(out.last_id)
    mop = np.asarray(out.mask_of_point)
    bnd_ff = first != last
    # wherever first != last the matrix entry must be zeroed
    assert (mop[bnd_ff] == 0).all()
    # wherever a unique claim exists the matrix carries it
    uniq = (first == last) & (first > 0)
    assert (mop[uniq] == first[uniq]).all()
    # global boundary = any frame boundary
    np.testing.assert_array_equal(np.asarray(out.boundary), bnd_ff.any(axis=0))
    # visibility = claimed by >= 1 valid mask
    np.testing.assert_array_equal(np.asarray(out.point_visible), first > 0)


def test_ids_above_k_max_dropped_not_merged(scene):
    """Ids > k_max must vanish, never alias into mask k_max (ref handles
    arbitrary uint16 ids, mask_backprojection.py:89-94)."""
    k_max = 15
    fa_ref = _assoc_frame(scene, 0)
    seg = np.asarray(scene.segmentations[0])
    big = int(seg.max())
    assert big > 0
    seg_big = np.where(seg == big, k_max + 37, seg).astype(np.int32)
    fa = associate_frame(
        jnp.asarray(scene.scene_points),
        jnp.asarray(scene.depths[0]),
        jnp.asarray(seg_big),
        jnp.asarray(scene.intrinsics[0]),
        jnp.asarray(scene.cam_to_world[0]),
        jnp.asarray(scene.frame_valid[0]),
        k_max=k_max, window=1, distance_threshold=DT, depth_trunc=20.0,
        few_points_threshold=25, coverage_threshold=COV,
    )
    mop_ref = np.asarray(fa_ref.mask_of_point)
    # points the relabeled mask uniquely claimed are unclaimed now
    assert (np.asarray(fa.mask_of_point)[mop_ref == big] == 0).all()
    # and no other mask absorbed them: per-mask claim counts unchanged
    n_ref = np.asarray(fa_ref.n_claimed)
    n_new = np.asarray(fa.n_claimed)
    keep = np.arange(k_max + 1) != big
    np.testing.assert_array_equal(n_new[keep], n_ref[keep])
    assert n_new[big] == 0


def test_invalid_frame_produces_nothing(scene):
    fa = _assoc_frame(scene, 0)
    fa_invalid = associate_frame(
        jnp.asarray(scene.scene_points),
        jnp.asarray(scene.depths[0]),
        jnp.asarray(scene.segmentations[0]),
        jnp.asarray(scene.intrinsics[0]),
        jnp.asarray(scene.cam_to_world[0]),
        jnp.asarray(False),
        k_max=15, window=1, distance_threshold=DT,
        few_points_threshold=25, coverage_threshold=COV,
    )
    assert np.asarray(fa.mask_valid).any()
    assert not np.asarray(fa_invalid.mask_valid).any()
    assert (np.asarray(fa_invalid.mask_of_point) == 0).all()


def test_spacing_estimate_and_duplicates():
    """estimate_spacing recovers grid spacing; duplicates/sentinels ignored."""
    from maskclustering_tpu.models.backprojection import estimate_spacing

    g = np.stack(np.meshgrid(np.arange(40) * 0.02, np.arange(40) * 0.02,
                             indexing="ij"), axis=-1).reshape(-1, 2)
    pts = np.concatenate([g, np.zeros((len(g), 1))], axis=1).astype(np.float32)
    est = float(estimate_spacing(jnp.asarray(pts)))
    assert 0.018 <= est <= 0.022, est
    # tile-padding duplicates and a block of far sentinel points must not
    # drag the estimate toward zero
    padded = np.concatenate([pts, pts[:400],
                             np.full((400, 3), 1.0e4, np.float32)])
    est2 = float(estimate_spacing(jnp.asarray(padded)))
    assert 0.018 <= est2 <= 0.022, est2
    # MAJORITY sentinel padding (the fused batch path pads small scenes to
    # the batch max): a sentinel's finite distance to the nearest real point
    # must not blow the median up either
    mostly_pad = np.concatenate([pts, np.full((5 * len(pts), 3), 1.0e4, np.float32)])
    est3 = float(estimate_spacing(jnp.asarray(mostly_pad)))
    assert 0.018 <= est3 <= 0.022, est3


def test_reference_radius_on_sparse_cloud():
    """At the reference's radius 0.01 a ~2 cm cloud must still associate:
    the coverage voxel grid self-calibrates to the cloud's density
    (reference analog: voxel-downsampled mask points in the coverage ratio,
    mask_backprojection.py:105,143-145)."""
    # 480x640 (ScanNet depth size): pixel backprojections ~5 mm apart at
    # 3 m, inside the radius (at the tiny default 96x128 the pixel grid
    # itself is ~2 cm — sparser than the radius — and nothing could claim,
    # reference or not)
    scene = make_scene(num_boxes=3, num_frames=6, seed=11, spacing=0.02,
                       image_hw=(480, 640))
    out = associate_scene(
        jnp.asarray(scene.scene_points),
        jnp.asarray(scene.depths),
        jnp.asarray(scene.segmentations),
        jnp.asarray(scene.intrinsics),
        jnp.asarray(scene.cam_to_world),
        jnp.asarray(scene.frame_valid),
        k_max=15, window=1, distance_threshold=0.01,
        few_points_threshold=25, coverage_threshold=COV,
    )
    valid = np.asarray(out.mask_valid)
    # every frame observes all 3 boxes head-on; the masks must survive
    assert valid[:, 1:].sum() >= 3 * scene.depths.shape[0] * 0.8, valid.sum()
    # most object points are claimed in >= 1 of the 6 views (oblique
    # surfaces miss at r=0.01 — adjacent-pixel backprojections sit > 1 cm
    # apart in 3D there, for the reference's ball query just as much —
    # and only more viewpoints recover them)
    first = np.asarray(out.first_id)
    claimed_frac = (first > 0).any(axis=0)[scene.gt_instance > 0].mean()
    assert claimed_frac > 0.6, claimed_frac


@pytest.mark.parametrize("window", [1, 2])
def test_strip_and_full_tile_tables_agree(scene, window):
    """The window-row strip path (linear in window, used for window > 1 to
    bound the fused path's F-fold HBM footprint, ADVICE r4) produces
    byte-identical associations to the single-take full table."""
    full = _assoc_frame(scene, 2, window=window, full_tile_table=True)
    strip = _assoc_frame(scene, 2, window=window, full_tile_table=False)
    for name in FrameAssociation._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(full, name)), np.asarray(getattr(strip, name)),
            err_msg=f"{name} differs at window={window}")


@pytest.mark.parametrize("frame_batch", [3, 8])
def test_frame_batch_matches_sequential(frame_batch):
    """lax.map batch_size (association_frame_batch) is a pure scheduling
    knob: batched association must be byte-identical to the sequential
    map, including at a batch that does not divide the frame count."""
    scene = make_scene(num_boxes=4, num_frames=8, seed=11)
    args = (jnp.asarray(scene.scene_points), jnp.asarray(scene.depths),
            jnp.asarray(scene.segmentations), jnp.asarray(scene.intrinsics),
            jnp.asarray(scene.cam_to_world), jnp.asarray(scene.frame_valid))
    kw = dict(k_max=15, window=1, distance_threshold=DT,
              few_points_threshold=25, coverage_threshold=COV)
    seq = associate_scene(*args, frame_batch=1, **kw)
    bat = associate_scene(*args, frame_batch=frame_batch, **kw)
    for field in ("mask_of_point", "first_id", "last_id", "mask_valid",
                  "boundary", "point_visible"):
        np.testing.assert_array_equal(np.asarray(getattr(bat, field)),
                                      np.asarray(getattr(seq, field)),
                                      err_msg=field)


def test_associate_donation_gating_and_identity():
    """cfg.donate_buffers donates the codec-uploaded frame buffers into the
    association jit: results are identical to the non-donating path, and
    DEVICE-RESIDENT caller frames (the bench's HBM-rendered scenes) are
    never donated — they survive the call readable."""
    import dataclasses

    import jax.numpy as jnp

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.backprojection import associate_scene_tensors
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    # the module's standard scene + DT: the non-donating reference call
    # reuses the associate program other tests here already compiled
    scene = make_scene(num_boxes=4, num_frames=8, seed=3)
    t = to_scene_tensors(scene)
    cfg = PipelineConfig(config_name="don", dataset="demo", backend="cpu",
                         distance_threshold=DT)
    a_don = associate_scene_tensors(t, cfg, k_max=15)
    a_ref = associate_scene_tensors(t, cfg.replace(donate_buffers=False), k_max=15)
    np.testing.assert_array_equal(np.asarray(a_don.mask_of_point),
                                  np.asarray(a_ref.mask_of_point))
    np.testing.assert_array_equal(np.asarray(a_don.first_id),
                                  np.asarray(a_ref.first_id))
    np.testing.assert_array_equal(np.asarray(a_don.mask_valid),
                                  np.asarray(a_ref.mask_valid))

    t_dev = dataclasses.replace(t, depths=jnp.asarray(t.depths),
                                segmentations=jnp.asarray(t.segmentations))
    a_dev = associate_scene_tensors(t_dev, cfg, k_max=15)
    assert not t_dev.depths.is_deleted()
    assert not t_dev.segmentations.is_deleted()
    np.testing.assert_array_equal(np.asarray(a_dev.mask_of_point),
                                  np.asarray(a_ref.mask_of_point))
