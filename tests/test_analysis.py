"""mct-check contract tests (maskclustering_tpu/analysis/).

Three layers, mirroring the analyzer's families:

- pure units (no jax): finding ids, the baseline/ratchet policy, the AST
  lint on known-bad fixture snippets, and the IR text checks on canned
  StableHLO/HLO — each of the four IR invariants (counting dtype, 2-sync
  census, donation, collective budget) has a DELIBERATE-BREAK case here,
  proving the analyzer detects regressions rather than blessing whatever
  the current tree does;
- real lowerings: donation aliasing read from an actual jit lowering
  (marker present vs dropped), and one full ``analyze_ir`` run on the
  8x1 scene-DP mesh asserting the tree is clean modulo the baselined
  CPU-unaliasable donations;
- the runtime sanitizer: a 2-scene synthetic CPU pipeline under
  ``transfer_guard`` with artifacts byte-identical to the unguarded run
  (the ISSUE-6 Family-3 acceptance bar).

The full-lattice CLI integration is slow-marked; scripts/ci.sh runs the
same gate fatally anyway.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from maskclustering_tpu.analysis.concurrency import (
    analyze_concurrency,
    build_lock_order_graph,
    thread_markers,
)
from maskclustering_tpu.analysis.ast_checks import (
    analyze_ast,
    check_bare_except,
    check_host_syncs,
    check_jit_purity,
    check_thread_shared_state,
    collect_thread_targets,
)
from maskclustering_tpu.analysis.findings import (
    Finding,
    load_baseline,
    make_id,
    partition_findings,
    stale_in_scope,
    write_baseline,
)
from maskclustering_tpu.analysis.ir_checks import (
    EXPECTED_WIDE_DOTS,
    check_claim_planes,
    check_collective_budget,
    check_donation,
    check_donation_wiring,
    check_dot_classes,
    check_host_transfers,
    check_narrowing_ab,
    check_no_f64,
    check_source_sync_sites,
    donated_param_aliases,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _f(check="X", fid=None, **kw):
    return Finding(id=fid or make_id(check, "k"), check=check,
                   family="ast", message="m", **kw)


# ---------------------------------------------------------------------------
# findings + baseline policy
# ---------------------------------------------------------------------------


def test_make_id_is_content_coordinates_no_lines():
    fid = make_id("AST.HOSTSYNC", "a/b.py", "fn", "np.asarray", 2)
    assert fid == "AST.HOSTSYNC:a/b.py:fn:np.asarray:2"


def test_partition_findings_split_and_stale():
    live = [_f(fid="A"), _f(fid="B")]
    unsup, sup, stale = partition_findings(live, {"B": "why", "GONE": "old"})
    assert [f.id for f in unsup] == ["A"]
    assert [f.id for f in sup] == ["B"]
    assert stale == ["GONE"]


def test_stale_scoped_to_families_and_meshes_actually_run():
    stale = ["AST.HOSTSYNC:a.py:f:np.asarray:1",
             "IR.DONATION:fused@2x4:arg1",
             "IR.DONATION:post.group_counts:arg0"]
    # an ast-only run never re-derives IR findings: only the AST entry
    # may be called stale
    assert stale_in_scope(stale, ["ast"]) == [stale[0]]
    # a mesh-filtered ir run covered only fused@1x8: the fused@2x4 entry
    # stays, mesh-independent IR entries (group_counts) are in scope
    assert stale_in_scope(stale, ["ast", "ir"], {"fused@1x8"}) == [
        stale[0], stale[2]]
    # the full run reports everything
    assert stale_in_scope(
        stale, ["ast", "ir"],
        {"fused@1x8", "fused@2x4", "fused@4x2", "fused@8x1"}) == stale


def test_load_baseline_rejects_missing_and_todo_justifications(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"version": 1, "suppressions": [
        {"id": "A", "justification": ""}]}))
    with pytest.raises(ValueError):
        load_baseline(str(p))
    # write_baseline's TODO placeholder is deliberate friction, not a pass
    write_baseline(str(p), [_f(fid="A")])
    with pytest.raises(ValueError):
        load_baseline(str(p))
    # a human replaces the TODO -> loads
    doc = json.loads(p.read_text())
    doc["suppressions"][0]["justification"] = "accepted trade"
    p.write_text(json.dumps(doc))
    assert load_baseline(str(p)) == {"A": "accepted trade"}


def test_load_baseline_rejects_wrong_version(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"version": 99, "suppressions": []}))
    with pytest.raises(ValueError):
        load_baseline(str(p))


def test_repo_baseline_loads_with_real_justifications():
    # the committed gate baseline: loadable, every entry human-justified
    baseline = load_baseline(os.path.join(REPO_ROOT, "analysis_baseline.json"))
    assert baseline  # non-empty: the accepted trades are named, not hidden
    assert all(len(why) > 10 for why in baseline.values())


# ---------------------------------------------------------------------------
# Family 2: AST lint on fixture snippets
# ---------------------------------------------------------------------------


def _lint(src, check_fn, **kw):
    src = textwrap.dedent(src)
    tree = ast.parse(src)
    return check_fn(tree, "maskclustering_tpu/models/pipeline.py",
                    src.splitlines(), **kw)


def test_hostsync_flags_unsanctioned_pulls():
    out = _lint("""
        def device_phase(x):
            a = np.asarray(x)          # unsanctioned
            b = x.item()               # unsanctioned
            c = float(compute(x))      # unsanctioned
            return a, b, c
    """, check_host_syncs)
    assert sorted(f.id.split(":")[-2] for f in out) == [
        ".item", "float(<call>)", "np.asarray"]
    assert all(f.check == "AST.HOSTSYNC" and f.line for f in out)


def test_hostsync_sanctioned_seams_and_optout_pass():
    out = _lint("""
        def device_phase(x, sp):
            with sanctioned_pull("mask_valid"):
                a = np.asarray(x)                  # family-3 seam
            with tracer.span("post.claims_pull", scene=s):
                b = np.asarray(x)                  # pull-named span
            c = np.asarray(x)  # mct-ok: AST.HOSTSYNC
            return a, b, c
    """, check_host_syncs)
    assert out == []


def test_hostsync_body_markers_do_not_sanction_the_whole_block():
    # a booked pull inside a span must NOT blind the lint to a SECOND
    # pull added to the same 30-line block (the seam is the with item,
    # not the body vocabulary)
    out = _lint("""
        def device_phase(x, sp):
            with tracer.span("graph", scene=s) as sp2:
                b = np.asarray(x)
                obs.count("pipeline.host_sync")
            return b
    """, check_host_syncs)
    assert [f.check for f in out] == ["AST.HOSTSYNC"]


def test_jitpurity_flags_wallclock_reachable_from_jit():
    out = _lint("""
        import jax, time

        def helper():
            return time.perf_counter()   # reachable from the jitted root

        @jax.jit
        def step(x):
            return x + helper()

        def host_only():
            return time.time()           # NOT reachable from any trace
    """, check_jit_purity)
    assert [f.check for f in out] == ["AST.JITPURITY"]
    assert "helper" in out[0].id


def test_threads_flags_unlocked_module_state():
    src = """
        registry = {}
        _lock = threading.Lock()

        def worker(k):
            registry[k] = 1        # unlocked mutation on a thread target

        def locked_worker(k):
            with _lock:
                registry[k] = 1    # guarded: fine

        t = threading.Thread(target=worker)
        u = threading.Thread(target=locked_worker)
    """
    src = textwrap.dedent(src)
    tree = ast.parse(src)
    targets = collect_thread_targets(tree)
    assert targets == {"worker", "locked_worker"}
    out = check_thread_shared_state(tree, "m.py", src.splitlines(), targets)
    assert [f.check for f in out] == ["AST.THREADS"]
    assert "worker" in out[0].id and "locked_worker" not in out[0].id


def test_thread_targets_collect_pool_and_executor_receivers():
    # `pool.map(fn, ...)` (semantics/features.py's io pool spelling) and
    # `ex.submit(fn)` both make fn a thread root; an unrelated receiver
    # (`mymap.map`) does not
    tree = ast.parse(textwrap.dedent("""
        crops = pool.map(load_crops, chunk)
        fut = ex.submit(drain)
        other = mymap.map(transform, rows)
    """))
    assert collect_thread_targets(tree) == {"load_crops", "drain"}


def test_bare_except_flagged_typed_except_not():
    out = _lint("""
        try:
            risky()
        except:
            pass
        try:
            risky()
        except Exception:
            pass
    """, check_bare_except)
    assert [f.check for f in out] == ["AST.EXCEPT"]


def test_analyze_ast_driver_on_a_bad_tmp_tree(tmp_path):
    pkg = tmp_path / "maskclustering_tpu" / "models"
    pkg.mkdir(parents=True)
    # device-path module (path matches DEVICE_PATH_MODULES) with both an
    # unsanctioned sync and a bare except
    (pkg / "pipeline.py").write_text(textwrap.dedent("""
        def run_scene_device(x):
            try:
                return np.asarray(x)
            except:
                pass
    """))
    findings = analyze_ast(str(tmp_path))
    assert {f.check for f in findings} == {"AST.HOSTSYNC", "AST.EXCEPT"}


# ---------------------------------------------------------------------------
# concurrency family: seeded-defect fixtures (exact finding ids) + sanitizer
# ---------------------------------------------------------------------------

_CONC_REL = "maskclustering_tpu/models/conc_fix.py"


def _conc(root, src, rel=_CONC_REL):
    """Write one seeded-defect module into a tmp tree, run the family."""
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return analyze_concurrency(str(root))


def test_thread_marker_grammar():
    lines = [
        "def loader():  # mct-thread: root",
        "X = {}  # mct-thread: immutable",
        "threading.Thread(target=f)  # mct-thread: abandon(watchdog outwaits)",
        "plain line",
    ]
    m = thread_markers(lines)
    assert m[1] == ("root", "")
    assert m[2] == ("immutable", "")
    assert m[3] == ("abandon", "watchdog outwaits")
    assert 4 not in m


def test_conc_lockorder_cycle_fixture(tmp_path):
    # DELIBERATE BREAK: two functions take the same two locks in opposite
    # orders — the classic two-thread deadlock
    findings = _conc(tmp_path / "bad", """
        a = mct_lock("fix.A")
        b = mct_lock("fix.B")

        def fwd():
            with a:
                with b:
                    pass

        def rev():
            with b:
                with a:
                    pass
    """)
    ids = {f.id for f in findings}
    assert "CONC.LOCKORDER:fix.A+fix.B" in ids
    # the nested acquisitions under a held lock are themselves findings
    assert any(f.check == "CONC.BLOCKING" and "lock:fix" in f.id
               for f in findings)
    # one global order is clean: same locks, one nesting direction
    clean = _conc(tmp_path / "ok", """
        a = mct_lock("fix.A")
        b = mct_lock("fix.B")

        def fwd():
            with a:
                with b:  # mct-ok: CONC.BLOCKING
                    pass
    """)
    assert not any(f.check == "CONC.LOCKORDER" for f in clean)


def test_conc_shared_unguarded_dict_fixture(tmp_path):
    # DELIBERATE BREAK: a module dict mutated from two thread roots with
    # no lock; the guarded / immutable-marked / queue-typed legs stay clean
    findings = _conc(tmp_path, """
        import threading
        from collections import deque

        registry = {}
        CACHE = {}  # mct-thread: immutable
        q = deque()
        _lock = threading.Lock()

        def worker_a():
            registry["k"] = 1
            CACHE["warm"] = 1
            q.append(1)

        def worker_b():
            registry.update(k=2)

        def locked_worker():
            with _lock:
                registry["k"] = 3

        ta = threading.Thread(target=worker_a)
        tb = threading.Thread(target=worker_b)
        tc = threading.Thread(target=locked_worker)
        ta.join(1.0)
        tb.join(1.0)
        tc.join(1.0)
    """)
    assert sorted(f.id for f in findings if f.check == "CONC.SHARED") == [
        f"CONC.SHARED:{_CONC_REL}:worker_a:registry:1",
        f"CONC.SHARED:{_CONC_REL}:worker_b:registry:1"]


def test_conc_blocking_call_under_lock_fixture(tmp_path):
    # DELIBERATE BREAK: file IO and a sleep inside `with lock:` bodies
    findings = _conc(tmp_path, """
        import threading
        import time

        _lock = threading.Lock()

        def writer(f, data):
            with _lock:
                f.write(data)

        def sleeper():
            with _lock:
                time.sleep(0.1)

        def fine(f, data):
            f.write(data)
            with _lock:
                pass

        def _helper(f, data):
            f.write(data)

        def indirect(f, data):
            with _lock:
                _helper(f, data)  # IO moved into a helper stays caught
    """)
    assert sorted(f.id for f in findings if f.check == "CONC.BLOCKING") == [
        f"CONC.BLOCKING:{_CONC_REL}:indirect:.write via _helper:1",
        f"CONC.BLOCKING:{_CONC_REL}:sleeper:time.sleep:1",
        f"CONC.BLOCKING:{_CONC_REL}:writer:.write:1"]


def test_conc_signal_handler_that_allocates_fixture(tmp_path):
    # DELIBERATE BREAK: a handler that opens a file and serializes JSON;
    # the flag-only handler next to it stays clean
    findings = _conc(tmp_path, """
        import json
        import signal
        import threading

        _STOP = threading.Event()

        def _bad_handler(signum, frame):
            data = {"sig": signum}
            json.dump(data, open("/tmp/x", "w"))

        def _good_handler(signum, frame):
            _STOP.set()

        signal.signal(signal.SIGTERM, _bad_handler)
        signal.signal(signal.SIGINT, _good_handler)
    """)
    sig = [f for f in findings if f.check == "CONC.SIGNAL"]
    assert [f.id for f in sig] == [f"CONC.SIGNAL:{_CONC_REL}:_bad_handler"]
    assert "json.dump" in sig[0].message and "open" in sig[0].message


def test_conc_join_contract_fixture(tmp_path):
    # DELIBERATE BREAKS: a spawn never joined, an unbounded join, and an
    # abandon marker with no rationale; bounded join + justified abandon
    # are the two sanctioned shapes
    findings = _conc(tmp_path, """
        import threading

        def unjoined(fn):
            t = threading.Thread(target=fn)
            t.start()

        def unbounded(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        def bounded(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join(2.0)

        def abandoned(fn):
            threading.Thread(  # mct-thread: abandon(fixture: the watchdog outwaits the call)
                target=fn, daemon=True).start()

        def empty_abandon(fn):
            threading.Thread(  # mct-thread: abandon()
                target=fn, daemon=True).start()
    """)
    assert sorted(f.id for f in findings if f.check == "CONC.JOIN") == [
        f"CONC.JOIN:{_CONC_REL}:empty_abandon:empty-rationale",
        f"CONC.JOIN:{_CONC_REL}:unbounded:t-unbounded-join:1",
        f"CONC.JOIN:{_CONC_REL}:unjoined:t:1"]


def test_conc_result_without_timeout_fixture(tmp_path):
    findings = _conc(tmp_path, """
        def wait_all(futs):
            return [f.result() for f in futs]

        def bounded_wait(fut):
            return fut.result(timeout=5.0)

        def opted_out(fut):
            return fut.result()  # mct-ok: CONC.RESULT
    """)
    assert [f.id for f in findings if f.check == "CONC.RESULT"] == [
        f"CONC.RESULT:{_CONC_REL}:wait_all:1"]


def test_analyze_concurrency_repo_clean_modulo_baseline():
    findings = analyze_concurrency(REPO_ROOT)
    baseline = load_baseline(os.path.join(REPO_ROOT, "analysis_baseline.json"))
    assert [f.id for f in findings if f.id not in baseline] == []


def test_static_lock_order_graph_shared_vocabulary_and_acyclic():
    from maskclustering_tpu.analysis.concurrency import _find_cycles

    nodes, edges = build_lock_order_graph(REPO_ROOT)
    # the named pipeline locks speak mct_lock's literal-name vocabulary —
    # the same ids the runtime sanitizer stamps on observations
    for name in ("faults._PLAN_LOCK", "faults.Heartbeat._lock",
                 "faults._FaultEntry.lock", "obs.metrics.Registry._lock",
                 "obs.events.EventSink._lock"):
        assert name in nodes, name
    assert _find_cycles(edges) == []


def test_cli_concurrency_family_green_on_repo_and_red_on_bad_tree(tmp_path):
    from maskclustering_tpu.analysis.__main__ import main

    assert main(["--families", "concurrency", "--root", REPO_ROOT]) == 0
    pkg = tmp_path / "maskclustering_tpu" / "models"
    pkg.mkdir(parents=True)
    (pkg / "pipeline.py").write_text(textwrap.dedent("""
        a = mct_lock("fix.A")
        b = mct_lock("fix.B")

        def fwd():
            with a:
                with b:
                    pass

        def rev():
            with b:
                with a:
                    pass
    """))
    assert main(["--families", "concurrency", "--root", str(tmp_path)]) == 2


def test_mct_lock_arming_and_instrumented_type(monkeypatch):
    from maskclustering_tpu.analysis import lock_sanitizer as ls

    monkeypatch.delenv(ls.ENV_FLAG, raising=False)
    ls.arm(None)
    try:
        assert isinstance(ls.mct_lock("x"), type(threading.Lock()))
        monkeypatch.setenv(ls.ENV_FLAG, "1")
        lk = ls.mct_lock("x")
        assert isinstance(lk, ls.InstrumentedLock) and lk.name == "x"
        ls.arm(False)  # explicit arm beats the environment
        assert not ls.enabled()
    finally:
        ls.arm(None)


def test_sanitizer_records_orders_holds_and_cross_checks(monkeypatch):
    from maskclustering_tpu.analysis import lock_sanitizer as ls

    monkeypatch.setenv("MCT_LOCK_HOLD_WARN_S", "0.01")
    ls.reset()
    try:
        a, b = ls.InstrumentedLock("A"), ls.InstrumentedLock("B")
        with a:
            with b:
                pass
        with b:
            time.sleep(0.02)  # crosses the (test-tightened) hold threshold
        rep = ls.report()
        assert rep["acquisitions"] == {"A": 1, "B": 2}
        assert ls.observed_edges() == {("A", "B")}
        assert any(h["name"] == "B" for h in rep["long_holds"])
        # the embed cross-check: a known edge passes, an order the static
        # graph does not carry is the violation, out-of-vocabulary locks
        # (ad-hoc test locks) are out of scope
        assert ls.check_embeds({("A", "B")}, {("A", "B")}, {"A", "B"}) == []
        out = ls.check_embeds({("A", "B")}, set(), {"A", "B"})
        assert len(out) == 1 and "A -> B" in out[0]
        assert ls.check_embeds({("A", "Z")}, set(), {"A", "B"}) == []
    finally:
        ls.reset()


# ---------------------------------------------------------------------------
# Family 1: IR invariants — text-level units with deliberate breaks
# ---------------------------------------------------------------------------


def _dots(**classes):
    return {cls: {"count": float(n), "operand_bytes": 0.0}
            for cls, n in classes.items()}


def test_dtype_conforming_census_is_clean():
    dots = _dots(**{"bf16xbf16->f32": 11, "f32xf32->f32": EXPECTED_WIDE_DOTS})
    assert check_dot_classes(dots, "bf16", "fused@1x8") == []


def test_dtype_break_forced_f32_counting_dot_fails():
    # DELIBERATE BREAK: a counting contraction regressed to f32 — the wide
    # census grows past the audited set and the invariant fires
    dots = _dots(**{"bf16xbf16->f32": 10,
                    "f32xf32->f32": EXPECTED_WIDE_DOTS + 1})
    out = check_dot_classes(dots, "bf16", "fused@1x8")
    assert [f.check for f in out] == ["IR.DTYPE.WIDE"]


def test_dtype_break_foreign_class_fails():
    dots = _dots(**{"i8xi8->i32": 11, "f16xf16->f32": 1,
                    "f32xf32->f32": EXPECTED_WIDE_DOTS})
    out = check_dot_classes(dots, "int8", "fused@8x1")
    assert [f.check for f in out] == ["IR.DTYPE.CLASS"]
    assert "f16xf16->f32" in out[0].id


def test_f64_widening_fails():
    assert check_no_f64("tensor<8xf32>", "l") == []
    out = check_no_f64("tensor<8xf64>", "l")
    assert [f.check for f in out] == ["IR.DTYPE.F64"]


_SIG_I16 = ('-> (tensor<4x8xi16> {jax.result_info = ".first_id"}, '
            'tensor<4x8xi16> {jax.result_info = ".last_id"})')
_SIG_I32 = ('-> (tensor<4x8xi32> {jax.result_info = ".first_id"}, '
            'tensor<4x8xi16> {jax.result_info = ".last_id"})')


def test_claim_planes_stay_s16():
    assert check_claim_planes(_SIG_I16, "l") == []
    # DELIBERATE BREAK: a widened plane (the PR-4 regression) fires
    out = check_claim_planes(_SIG_I32, "l")
    assert [f.check for f in out] == ["IR.DTYPE.PLANE"]
    assert "first_id" in out[0].id and "i32" in out[0].id
    # a missing output is a finding too (contract unverifiable != pass)
    assert len(check_claim_planes("func @main()", "l")) == 2


def test_host_transfer_census_zero_crossings():
    clean = "%ar = pred[] all-reduce(pred[] %x), channel_id=1"
    assert check_host_transfers(clean, "l") == []
    # DELIBERATE BREAK: a send/outfeed pair mid-program (a host callback
    # or debug print that survived into the compiled step)
    bad = ("%s = (f32[8], u32[], token[]) send(f32[8] %a, token[] %t)\n"
           "%o = token[] outfeed(f32[8] %b, token[] %t)\n")
    out = check_host_transfers(bad, "l")
    assert sorted(f.id.split(":")[-1] for f in out) == ["outfeed", "send"]


def test_collective_budget_scene_dp_two_bytes():
    ok = {"all-reduce": {"count": 2, "bytes": 2.0}}
    assert check_collective_budget(2.0, ok, (8, 1), "l") == []
    # DELIBERATE BREAK 1: a data collective appeared under pure scene-DP
    bad = {"all-gather": {"count": 1, "bytes": 1024.0}, **ok}
    out = check_collective_budget(1026.0, bad, (8, 1), "l")
    assert {f.id.split(":")[-1] for f in out} == {"data", "bytes"}
    # DELIBERATE BREAK 2: predicate payload crept past 2 bytes
    out = check_collective_budget(10.0, ok, (8, 1), "l")
    assert [f.id.split(":")[-1] for f in out] == ["bytes"]


def test_collective_budget_frame_sharded_envelope():
    colls = {"all-gather": {"count": 12, "bytes": 90000.0}}
    assert check_collective_budget(9e4, colls, (1, 8), "l") == []
    out = check_collective_budget(2e5, colls, (1, 8), "l")
    assert [f.check for f in out] == ["IR.COLLECTIVE.FRAME"]
    # off-canonical shapes carry no envelope (budgets are shape-dependent)
    assert check_collective_budget(2e5, colls, (1, 8), "l",
                                   canonical_shape=False) == []


def test_donation_aliasing_read_from_a_real_lowering():
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct((128,), jnp.float32)
    donating = jax.jit(lambda x: x + 1, donate_argnums=(0,)).lower(sds)
    aliases = donated_param_aliases(donating.as_text())
    assert aliases.get(0) is not None  # tf.aliasing_output present
    assert check_donation(donating.as_text(), (0,), "l") == []
    # DELIBERATE BREAK: the donation dropped from the jit wrapper — no
    # marker in the lowering, the finding names the missing arg
    plain = jax.jit(lambda x: x + 1).lower(sds)
    out = check_donation(plain.as_text(), (0,), "l")
    assert [f.check for f in out] == ["IR.DONATION"]
    assert "arg0" in out[0].id


def test_donation_wiring_present_in_tree_and_break_detected(tmp_path):
    # the real tree carries every pinned donate_argnums tuple
    assert check_donation_wiring(REPO_ROOT) == []
    # DELIBERATE BREAK: a tree whose donate wiring was deleted
    for rel in ("maskclustering_tpu/parallel/sharded.py",
                "maskclustering_tpu/models/postprocess_device.py"):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("def build(): return jax.jit(f)\n")
    out = check_donation_wiring(str(tmp_path))
    assert [f.check for f in out] == ["IR.DONATION.WIRING"] * 2


def test_source_sync_sites_contract(tmp_path):
    real = os.path.join(REPO_ROOT, "maskclustering_tpu/models/pipeline.py")
    assert check_source_sync_sites(real) == []
    # DELIBERATE BREAK: a third pull sneaks into the device phase
    p = tmp_path / "pipeline.py"
    p.write_text(textwrap.dedent("""
        def run_scene_device(t):
            obs.count("pipeline.host_sync")
            obs.count("pipeline.host_sync")
            obs.count("pipeline.host_sync")
    """))
    out = check_source_sync_sites(str(p))
    assert [f.check for f in out] == ["IR.SYNC.SOURCE"]
    assert "3" in out[0].message


def test_narrowing_ab_detects_a_stuck_counting_path():
    good = {"bf16": _dots(**{"bf16xbf16->f32": 11, "f32xf32->f32": 3}),
            "int8": _dots(**{"i8xi8->i32": 11, "f32xf32->f32": 3})}
    assert check_narrowing_ab(good, "l") == []
    # DELIBERATE BREAK: count_dtype stopped dispatching — both lowerings
    # identical means no contraction actually narrows
    stuck = {"bf16": good["bf16"], "int8": good["bf16"]}
    out = check_narrowing_ab(stuck, "l")
    assert [f.check for f in out] == ["IR.DTYPE.NARROW"]


# ---------------------------------------------------------------------------
# Family 1 integration: one real mesh of the lattice
# ---------------------------------------------------------------------------


def test_analyze_ir_scene_dp_clean_modulo_baseline(fused_lattice_aot):
    from maskclustering_tpu.analysis.ir_checks import analyze_ir

    # the fused 8x1 lowering comes from the session-scoped conftest sweep
    # (shared with test_cost) — analyze_ir only re-lowers the int8 A/B
    # variant and the group-counts kernel
    pre = fused_lattice_aot[(8, 1)]
    findings, rows = analyze_ir(
        meshes=[(8, 1)], repo_root=REPO_ROOT,
        lowerings={(8, 1): (pre["stablehlo"], pre["compiled_text"])})
    # CPU lowers the fused/groupcounts donations away (unusable) — those
    # are the committed baseline entries; NOTHING else may fire
    baseline = load_baseline(os.path.join(REPO_ROOT, "analysis_baseline.json"))
    extra = [f.id for f in findings if f.id not in baseline]
    assert extra == []
    assert all(f.check == "IR.DONATION" for f in findings)
    # and the scene-DP census itself pins the 2-byte contract
    assert rows and rows[0]["ici_bytes"] <= 2.0


# ---------------------------------------------------------------------------
# CLI + events + report section
# ---------------------------------------------------------------------------


def test_cli_ast_family_green_on_repo_and_red_on_bad_tree(tmp_path):
    from maskclustering_tpu.analysis.__main__ import main

    # the repo itself: every AST finding is a justified baseline entry
    assert main(["--families", "ast", "--root", REPO_ROOT]) == 0

    # a bad tree with no baseline: exit 2 (the gate)
    pkg = tmp_path / "maskclustering_tpu" / "models"
    pkg.mkdir(parents=True)
    (pkg / "pipeline.py").write_text(
        "def run_scene_device(x):\n    return np.asarray(x)\n")
    argv = ["--families", "ast", "--root", str(tmp_path)]
    assert main(argv) == 2

    # ratchet round-trip: --write-baseline, human justifies, gate greens
    bl = tmp_path / "bl.json"
    main(argv + ["--write-baseline", str(bl)])
    doc = json.loads(bl.read_text())
    with pytest.raises(ValueError):
        load_baseline(str(bl))  # TODO placeholders are rejected
    for e in doc["suppressions"]:
        e["justification"] = "fixture: accepted for the round-trip test"
    bl.write_text(json.dumps(doc))
    assert main(argv + ["--baseline", str(bl)]) == 0


def test_cli_events_render_in_obs_report(tmp_path):
    from maskclustering_tpu.analysis.__main__ import main
    from maskclustering_tpu.obs.report import RunData, render_analysis

    events = tmp_path / "events.jsonl"
    rc = main(["--families", "ast", "--root", REPO_ROOT,
               "--events", str(events)])
    assert rc == 0
    run = RunData(str(events))
    assert run.analysis_rows  # one event per finding + a summary row
    section = render_analysis(run.analysis_rows)
    assert section is not None and "mct-check" in section
    assert "clean" in section  # the summary row's verdict


def test_report_analysis_section_picks_newest_run():
    from maskclustering_tpu.obs.report import latest_analysis_run

    rows = [
        {"check": "A", "suppressed": False}, {"summary": True, "clean": False},
        {"check": "B", "suppressed": False}, {"summary": True, "clean": True},
    ]
    findings, summary = latest_analysis_run(rows)
    assert [r["check"] for r in findings] == ["B"]
    assert summary["clean"] is True


def test_report_analysis_orphan_rows_not_attributed_to_next_run():
    from maskclustering_tpu.obs.report import latest_analysis_run

    # pid 1 died before its summary (CI timeout); pid 2 ran clean after —
    # pid 1's orphans must not render under pid 2's clean summary
    rows = [
        {"check": "DEAD", "pid": 1, "suppressed": False},
        {"check": "B", "pid": 2, "suppressed": True},
        {"summary": True, "clean": True, "pid": 2},
    ]
    findings, summary = latest_analysis_run(rows)
    assert [r["check"] for r in findings] == ["B"]
    assert summary["clean"] is True
    # ...and with no later run at all, the dead run renders summary-less
    findings, summary = latest_analysis_run(rows[:1])
    assert [r["check"] for r in findings] == ["DEAD"] and summary is None


# ---------------------------------------------------------------------------
# Family 3: the transfer-guard sanitizer
# ---------------------------------------------------------------------------


def _small_cfg():
    from maskclustering_tpu.config import PipelineConfig

    return PipelineConfig(config_name="synthetic", dataset="demo",
                          backend="cpu", distance_threshold=0.03, step=1,
                          mask_pad_multiple=64, point_chunk=2048)


def test_transfer_guard_env_and_arm_precedence(monkeypatch):
    from maskclustering_tpu.analysis import transfer_guard as tg

    monkeypatch.delenv(tg.ENV_FLAG, raising=False)
    tg.arm(None)
    assert not tg.enabled()
    monkeypatch.setenv(tg.ENV_FLAG, "1")
    assert tg.enabled()
    tg.arm(False)  # explicit arm beats the environment
    try:
        assert not tg.enabled()
    finally:
        tg.arm(None)


def test_transfer_guard_trips_on_an_implicit_transfer():
    import jax
    import jax.numpy as jnp

    from maskclustering_tpu.analysis import transfer_guard as tg

    x = jnp.arange(8.0)
    tg.arm(True)
    try:
        with tg.device_phase_guard():
            with pytest.raises(jax.errors.JaxRuntimeError):
                # an eager python-scalar upload — exactly the io/feed bug
                # the guard originally surfaced
                _ = (x * np.float32(2.0)) + 1.0  # noqa: F841
            with tg.sanctioned_pull("ok"):
                assert np.asarray(x).shape == (8,)
    finally:
        tg.arm(None)


def test_transfer_guard_two_scene_pipeline_byte_identity():
    """ISSUE-6 acceptance: a 2-scene synthetic CPU pipeline end-to-end
    under the guard, zero violations, artifacts byte-identical."""
    from maskclustering_tpu.analysis import transfer_guard as tg
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    cfg = _small_cfg()
    scenes = [make_scene(num_boxes=3, num_frames=6, seed=s, spacing=0.05)
              for s in (3, 4)]

    def run_all():
        return [run_scene(to_scene_tensors(s), cfg, k_max=15)
                for s in scenes]

    plain = run_all()
    tg.arm(True)
    try:
        guarded = run_all()  # any implicit transfer raises here
    finally:
        tg.arm(None)
    for a, b in zip(plain, guarded):
        assert np.array_equal(a.assignment, b.assignment)
        assert a.objects.num_points == b.objects.num_points
        assert len(a.objects.point_ids_list) == len(b.objects.point_ids_list)
        for pa, pb in zip(a.objects.point_ids_list, b.objects.point_ids_list):
            assert pa.tobytes() == pb.tobytes()
        for ma, mb in zip(a.objects.mask_list, b.objects.mask_list):
            assert ma == mb


# ---------------------------------------------------------------------------
# the full gate, exactly as CI runs it (slow: ~15 s of lattice compiles)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cli_full_gate_green_on_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "maskclustering_tpu.analysis"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mct-check: clean" in proc.stdout
