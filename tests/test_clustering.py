import jax.numpy as jnp
import numpy as np
import pytest

from maskclustering_tpu.models.clustering import _connected_components, iterative_clustering
from tests.oracles import oracle_clustering


def _canon(labels, active):
    """Canonicalize a partition for comparison: map each label to the min
    active member index of its group."""
    labels = np.asarray(labels)
    out = np.full_like(labels, -1)
    for lab in np.unique(labels[active]):
        members = np.nonzero((labels == lab) & active)[0]
        out[members] = members.min()
    return out


def test_connected_components_vs_networkx():
    import networkx as nx

    rng = np.random.default_rng(5)
    for n, p in [(16, 0.1), (64, 0.03), (128, 0.01)]:
        adj = rng.random((n, n)) < p
        adj = adj | adj.T
        np.fill_diagonal(adj, False)
        labels = np.asarray(_connected_components(jnp.asarray(adj)))
        g = nx.from_numpy_array(adj)
        for comp in nx.connected_components(g):
            comp = sorted(comp)
            assert len({labels[i] for i in comp}) == 1
            assert labels[comp[0]] == min(comp)


def _random_problem(rng, m, f):
    visible = rng.random((m, f)) < 0.4
    contained = rng.random((m, m)) < 0.15
    np.fill_diagonal(contained, True)
    active = rng.random(m) < 0.85
    thresholds = sorted(rng.integers(1, max(2, f // 2), size=4).tolist(), reverse=True)
    return visible, contained, active, thresholds


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_iterative_clustering_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    m, f = 96, 12
    visible, contained, active, thresholds = _random_problem(rng, m, f)
    # inactive masks contribute nothing, mirroring init_nodes exclusion
    o_labels = oracle_clustering(visible, contained, active, thresholds, 0.9)

    sched = np.full(8, np.inf, dtype=np.float32)
    sched[: len(thresholds)] = thresholds
    res = iterative_clustering(
        jnp.asarray(visible), jnp.asarray(contained), jnp.asarray(active),
        jnp.asarray(sched), view_consensus_threshold=0.9)
    got = np.asarray(res.assignment)

    np.testing.assert_array_equal(_canon(got, active), _canon(o_labels, active))
    # inactive masks must remain singletons
    inactive = ~active
    np.testing.assert_array_equal(got[inactive], np.arange(m)[inactive])


def test_clustering_inf_schedule_is_identity():
    rng = np.random.default_rng(9)
    m, f = 32, 6
    visible, contained, active, _ = _random_problem(rng, m, f)
    sched = jnp.full((5,), jnp.inf, dtype=jnp.float32)
    res = iterative_clustering(jnp.asarray(visible), jnp.asarray(contained),
                               jnp.asarray(active), sched)
    np.testing.assert_array_equal(np.asarray(res.assignment), np.arange(m))


def test_node_visible_aggregates_members():
    m, f = 8, 4
    visible = np.zeros((m, f), dtype=bool)
    visible[0, 0] = visible[1, 1] = True
    visible[0, 2] = visible[1, 2] = True  # both see frame 2 -> observers=1? no: 2 shared
    contained = np.eye(m, dtype=bool)
    contained[0, 1] = contained[1, 0] = True
    active = np.zeros(m, dtype=bool)
    active[:2] = True
    # observers(0,1) = shared visible frames = 1 (frame 2); supporters = 2
    sched = jnp.asarray(np.array([1.0, np.inf, np.inf], dtype=np.float32))
    res = iterative_clustering(jnp.asarray(visible), jnp.asarray(contained),
                               jnp.asarray(active), sched, view_consensus_threshold=0.9)
    a = np.asarray(res.assignment)
    assert a[0] == a[1] == 0
    nv = np.asarray(res.node_visible)
    np.testing.assert_array_equal(nv[0], visible[0] | visible[1])
    assert np.asarray(res.node_active)[0]
    assert not np.asarray(res.node_active)[1]
