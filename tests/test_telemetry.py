"""mct-telemetry: the live serving telemetry plane (ISSUE-13 acceptance).

Unit tier: metrics snapshot-delta/merge helpers, relay sink bounds,
telem folding (counters + replayed spans + the ``worker.`` process tag),
window aggregation (ring bounds, reject/crash deltas, sample caps),
ticker rows on the events file, histogram summaries riding run digests,
the tier1 ledger row + --regress fence, the status-op detail validation,
the obs.top renderer, and obs.trace assembly over a synthetic timeline.

Stub tier (tests/worker_stub.py): the supervisor folds relayed telem
lines; a SIGKILL mid-window loses at most the unshipped delta — the
parent registry keeps the crash counters and every folded line (relay
loss != registry tear); obs.trace reconstructs the crash -> requeue ->
respawn request end-to-end with queue-wait segments.

Acceptance tier (one real worker subprocess): the same 4-request
mixed-bucket soak in-process and under --isolate-worker must render the
SAME Serving report section and book the SAME serve./d2h./h2d./pipeline.
counter names and values (modulo the worker.* relay tag) — the topology-
invariance contract. Scene shapes reuse the tier-1 suite's existing warm
buckets (test_serve's seed-40 scene + the supervisor acceptance's 6-frame
bucket), so jit and persistent caches hit across files.
"""

import json
import os
import re
import sys
import threading
import time

import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.config import load_config
from maskclustering_tpu.obs import telemetry
from maskclustering_tpu.obs import metrics as obs_metrics
from maskclustering_tpu.obs.events import KIND_TELEMETRY
from maskclustering_tpu.obs.report import (RunData, render_report,
                                           render_serving,
                                           render_telemetry_windows)
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.utils import faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO_ROOT, "tests", "worker_stub.py")


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    telemetry.install(None)
    yield
    telemetry.install(None)
    faults.set_plan(None)
    faults.clear_stop()


# ---------------------------------------------------------------------------
# units: metrics snapshot-delta / merge helpers
# ---------------------------------------------------------------------------


def test_snapshot_delta_and_merge_roundtrip():
    prev = {"counters": {"a": 2.0, "b": 5.0}, "gauges": {"g": 1.0}}
    cur = {"counters": {"a": 3.5, "b": 5.0, "c": 1.0},
           "gauges": {"g": 2.0, "serve.queue_depth_high_water": 7.0}}
    delta = obs_metrics.snapshot_delta(prev, cur)
    assert delta["counters"] == {"a": 1.5, "c": 1.0}  # unchanged b dropped
    assert delta["gauges"] == {"g": 2.0,
                               "serve.queue_depth_high_water": 7.0}

    reg = obs_metrics.Registry()
    reg.count("a", 10.0)
    reg.gauge("serve.queue_depth_high_water", 9.0)
    obs_metrics.merge_snapshot_delta(delta, reg)
    snap = reg.snapshot()
    assert snap["counters"] == {"a": 11.5, "c": 1.0}
    assert snap["gauges"]["g"] == 2.0
    # high-water gauges keep max-ever semantics across the fold
    assert snap["gauges"]["serve.queue_depth_high_water"] == 9.0
    # an empty delta folds to a no-op
    obs_metrics.merge_snapshot_delta({}, reg)
    assert reg.snapshot()["counters"] == {"a": 11.5, "c": 1.0}


def test_relay_sink_bounds_and_child_relay_sequences():
    sink = telemetry.RelaySink(cap=4)
    for i in range(6):
        sink.emit("span", {"name": f"s{i}", "dur_s": 0.1})
    sink.emit("metrics", {"metrics": {}})  # non-span kinds are ignored
    spans, dropped = sink.drain()
    assert [s["name"] for s in spans] == ["s2", "s3", "s4", "s5"]
    assert dropped == 2
    assert sink.drain() == ([], 0)  # drained clean

    relay = telemetry.ChildRelay(telemetry.RelaySink())
    obs.count("telem.unit.counter", 3)
    doc = relay.collect()
    assert doc["kind"] == "telem" and doc["seq"] == 1
    assert doc["metrics"]["counters"]["telem.unit.counter"] >= 3
    # nothing changed since: the idle flush costs zero pipe traffic
    assert relay.collect() is None
    obs.count("telem.unit.counter")
    doc2 = relay.collect()
    assert doc2["seq"] == 2
    assert doc2["metrics"]["counters"] == {"telem.unit.counter": 1.0}


def test_fold_telem_counters_spans_and_process_tag(tmp_path):
    events = str(tmp_path / "fold_events.jsonl")
    obs.configure(events, truncate=True, meta={"tool": "serve"})
    try:
        ts = time.time()
        telemetry.fold_telem(
            {"kind": "telem", "v": 1, "seq": 1,
             "metrics": {"counters": {"d2h.bytes.post.drain": 512.0,
                                      "serve.requests_ok": 2.0},
                         "gauges": {"retrace.live.post_freeze": 0.0}},
             "spans": [{"name": "serve.request", "dur_s": 0.25,
                        "sync_s": 0.01, "ts": ts,
                        "attrs": {"request": "r-000042", "scene": "x"}}],
             "spans_dropped": 3},
            child_pid=4242)
        # an unknown schema version folds nothing but counts itself
        telemetry.fold_telem({"kind": "telem", "v": 99, "seq": 2,
                              "metrics": {"counters": {"d2h.bytes": 1e9}}})
        obs.flush_metrics()
    finally:
        obs.disable()
    run = RunData(events)
    c = run._counters
    # counters landed under their own flat names (topology invariance)...
    assert c["d2h.bytes.post.drain"] == 512.0
    assert c["serve.requests_ok"] == 2.0
    assert "d2h.bytes" not in c  # the unknown-version line folded nothing
    # ...with the relay's own bookkeeping as the worker. process tag
    assert c["worker.telem_messages"] == 1.0
    assert c["worker.telem_spans"] == 1.0
    assert c["worker.telem_spans_dropped"] == 3.0
    assert c["worker.telem_unknown_version"] == 1.0
    # the span replayed into the events file, tagged and time-anchored
    row = run.spans["serve.request"][0]
    assert row["dur_s"] == 0.25
    assert row["attrs"]["request"] == "r-000042"
    assert row["attrs"]["worker_pid"] == 4242
    assert abs(row["attrs"]["end_ts"] - ts) < 1e-6


# ---------------------------------------------------------------------------
# units: windowed aggregation + the ticker
# ---------------------------------------------------------------------------


def test_window_aggregator_rolls_deltas_and_ring_bounds():
    agg = telemetry.WindowAggregator(window_s=0.05, ring=3)
    base = obs.registry().snapshot()["counters"]
    agg.roll()  # prime the delta baseline against the shared registry
    obs.count("serve.requests", 2)
    obs.count("serve.requests_ok", 2)
    obs.count("serve.admission.rejects.queue_full")
    obs.count("serve.rejects.deadline")
    obs.count("serve.worker_crashes")
    obs.gauge("serve.queue_depth", 3)
    agg.record_request((16, 16, 8192), 0.5)
    agg.record_request((16, 16, 8192), 1.5)
    agg.record_request(None, 0.2)
    agg.record_queue_wait(0.1)
    row = agg.roll()
    assert row["requests"] == 2 and row["by_status"] == {"ok": 2}
    assert row["rejects"] == {"queue_full": 1, "deadline": 1}
    assert row["crashes"] == 1 and row["queue_depth"] == 3
    lat = row["latency"]["16x16x8192"]
    assert lat["count"] == 2 and lat["max_s"] == 1.5
    assert row["latency"]["all"]["count"] == 1
    assert row["queue_wait"]["count"] == 1

    # deltas reset per window; the ring stays bounded
    for _ in range(5):
        assert agg.roll()["requests"] == 0
    snap = agg.snapshot()
    assert len(snap["windows"]) == 3  # ring=3
    assert snap["window_s"] == 0.05
    # cumulative latency histograms survive the window resets
    assert snap["cumulative"]["latency"]["16x16x8192"]["count"] == 2
    assert "current" in snap and "t0" in snap["current"]
    # the whole snapshot is wire-safe
    json.dumps(snap)
    del base


def test_window_aggregator_sample_cap_counts_drops():
    agg = telemetry.WindowAggregator(window_s=1.0)
    for _ in range(telemetry._SAMPLE_CAP + 10):
        agg.record_request(None, 0.1)
    # queue waits cap independently — a wait burst must not starve the
    # latency view (and vice versa)
    for _ in range(telemetry._SAMPLE_CAP + 5):
        agg.record_queue_wait(0.01)
    row = agg.roll()
    assert row["latency"]["all"]["count"] == telemetry._SAMPLE_CAP
    assert row["queue_wait"]["count"] == telemetry._SAMPLE_CAP
    assert row["samples_dropped"] == 15
    # the cumulative histogram observed EVERY sample, capped list or not
    cum = agg.snapshot()["cumulative"]["latency"]["all"]
    assert cum["count"] == telemetry._SAMPLE_CAP + 10


def test_ticker_appends_schema_versioned_rows(tmp_path):
    events = str(tmp_path / "tick_events.jsonl")
    obs.configure(events, truncate=True, meta={"tool": "serve"})
    try:
        agg = telemetry.WindowAggregator(window_s=0.05)
        ticker = telemetry.TelemetryTicker(agg)
        ticker.start()
        agg.record_request((8, 16, 1024), 0.3)
        time.sleep(0.2)
        ticker.stop()
        obs.count("serve.requests")  # a Serving section trigger
        obs.flush_metrics()
    finally:
        obs.disable()
    run = RunData(events)
    assert run.telemetry_rows, "ticker appended no telemetry rows"
    assert all(r["kind"] == KIND_TELEMETRY for r in run.telemetry_rows)
    line = render_telemetry_windows(run.telemetry_rows)
    assert line.startswith("telemetry:") and "window(s)" in line
    # the Serving section carries the windows digest
    serving = render_serving(run)
    assert "telemetry:" in serving
    # and the rows are crash-safe JSONL like everything else in the file
    assert render_report(run)


def test_record_helpers_route_to_installed_aggregator(tmp_path):
    events = str(tmp_path / "helper_events.jsonl")
    req = protocol.build_request({"op": "scene", "scene": "s1"}, "r-000009")
    telemetry.record_request((1, 2, 3), 0.5)  # no-op uninstalled
    telemetry.record_queue_wait(req, 0.25)  # no-op uninstalled
    agg = telemetry.WindowAggregator(window_s=5.0)
    telemetry.install(agg)
    obs.configure(events, truncate=True)
    try:
        telemetry.record_request((1, 2, 3), 0.5)
        telemetry.record_queue_wait(req, 0.25)
        obs.flush_metrics()
    finally:
        obs.disable()
        telemetry.install(None)
    row = agg.roll()
    assert row["latency"]["1x2x3"]["count"] == 1
    assert row["queue_wait"]["count"] == 1
    run = RunData(events)
    # the queue wait books a trace-able span + an explicit histogram
    wait_span = run.spans["serve.queue_wait"][0]
    assert wait_span["attrs"]["request"] == "r-000009"
    assert run._histograms["serve.queue_wait_s"]["count"] >= 1


def test_run_digest_carries_histogram_summaries(tmp_path):
    """Satellite: the registry's bounded histogram summaries ride the
    report digest (and hence run digests), not just counters/gauges."""
    events = str(tmp_path / "hist_events.jsonl")
    obs.configure(events, truncate=True)
    try:
        for v in (0.1, 0.2, 0.3, 0.4):
            obs.observe("queue.wait_s", v)
        with obs.span("histspan"):
            pass
        obs.flush_metrics()
    finally:
        obs.disable()
    digest = RunData(events).summary()
    h = digest["histograms"]["queue.wait_s"]
    assert h["count"] == 4 and abs(h["total"] - 1.0) < 1e-6
    assert h["p50"] is not None and h["max"] == 0.4
    # span.* series stay with the stage table, not duplicated here
    assert not any(k.startswith("span.") for k in digest["histograms"])
    assert "histspan" in digest["stages"]


# ---------------------------------------------------------------------------
# units: protocol detail, client accessor shape, ledger fence, top, trace
# ---------------------------------------------------------------------------


def test_status_detail_validation():
    assert protocol.parse_line('{"op": "status"}')["op"] == "status"
    doc = protocol.parse_line('{"op": "status", "detail": "telemetry"}')
    assert doc["detail"] == "telemetry"
    with pytest.raises(protocol.ProtocolError):
        protocol.parse_line('{"op": "status", "detail": "bogus"}')


def test_tier1_ledger_row_and_regress_fence(tmp_path):
    from maskclustering_tpu.obs import ledger as led
    from maskclustering_tpu.obs.report import _regress_eval

    path = str(tmp_path / "ledger.jsonl")
    led.append_row(path, {"tool": "bench", "metric": "mask-clustering "
                          "s/scene", "value": 3.2, "unit": "s/scene"})
    row = led.tier1_row(712.4, 430)
    assert row["tool"] == "tier1" and row["passed"] == 430
    assert led.append_row(path, row)

    # a bench-style baseline gates the BENCH row even though the tier1
    # row is newer (the tool fence keeps the trajectories apart)
    base = str(tmp_path / "base.json")
    with open(base, "w") as f:
        json.dump({"value": 3.0}, f)
    rc, _lines, record = _regress_eval(path, base, 0.15)
    assert record["current"]["tool"] == "bench"

    # a tier1 baseline gates tier1 rows (and a 20% wall growth fails)
    tier1_base = str(tmp_path / "tier1_base.json")
    with open(tier1_base, "w") as f:
        json.dump(led.tier1_row(600.0, 430), f)
    rc, _lines, record = _regress_eval(path, tier1_base, 0.15)
    assert rc == 2 and record["current"]["tool"] == "tier1"

    led.append_row(path, led.tier1_row(610.0, 431))
    rc, _lines, record = _regress_eval(path, tier1_base, 0.15)
    assert rc == 0 and record["current"]["value"] == 610.0


def test_top_sparkline_and_render():
    from maskclustering_tpu.obs.top import render_top, sparkline

    assert sparkline([]) == ""
    assert sparkline([0, 0]) == "▁▁"
    line = sparkline([0, 1, 2, 4], width=4)
    assert len(line) == 4 and line[-1] == "█"

    stats = {
        "config": "served", "uptime_s": 12.5, "draining": False,
        "counts": {"requests": 5, "ok": 4, "failed": 1},
        "queue": {"depth": 1, "capacity": 8, "high_water": 3,
                  "admitted": 5},
        "warm_buckets": [[16, 16, 8192]],
        "worker": {"pid": 777, "hb_age_s": 0.4, "spawns": 2,
                   "consecutive_respawns": 1, "inflight_crashes": 1},
        "telemetry": {
            "window_s": 5.0,
            "windows": [
                {"dur_s": 5.0, "requests": 2, "queue_depth": 2,
                 "rejects": {"queue_full": 1}, "crashes": 1,
                 "respawns": 1, "requeued": 1, "post_warm_compiles": 1,
                 "latency": {"16x16x8192": {"count": 2, "p50_s": 1.0,
                                            "p95_s": 2.0, "max_s": 2.0}},
                 "queue_wait": {"count": 2, "p50_s": 0.1, "p95_s": 0.2,
                                "max_s": 0.2}},
                {"dur_s": 5.0, "requests": 3, "queue_depth": 0,
                 "rejects": {}, "crashes": 0, "respawns": 0,
                 "latency": {}},
            ],
            "cumulative": {
                "counters": {"aot_cache.hits": 4,
                             "worker.telem_messages": 9,
                             "worker.telem_spans": 30},
                "gauges": {"retrace.live.post_freeze": 1},
                "latency": {"16x16x8192": {"count": 5, "p50": 1.1,
                                           "p95": 2.2, "max": 2.2,
                                           "total": 6.0}}}},
    }
    text = render_top(stats, now=1000.0)
    assert "mct-serve top" in text and "config served" in text
    assert "depth 1/8" in text and "▁" in text  # sparkline rendered
    assert "bucket 16x16x8192" in text
    assert "window p50 1.000s" in text and "cum p50 1.100s" in text
    assert "queue wait: p50 0.100s" in text
    assert "queue_full x1" in text and "crashes 1" in text
    assert "consecutive respawns 1" in text and "in-flight crashes 1" in text
    assert "post-warm 1 [VIOLATION]" in text
    assert "aot-cache hits 4" in text
    assert "relay: 9 telem line(s)" in text
    # an empty daemon renders without crashing
    assert "requests: none yet" in render_top({"counts": {}})


def _span_line(name, end_ts, dur, **attrs):
    return {"v": 1, "kind": "span", "ts": end_ts, "pid": 1, "name": name,
            "dur_s": dur, "sync_s": 0.0, "depth": 0,
            "attrs": dict(attrs, end_ts=end_ts)}


def test_trace_assembly_orders_segments_and_nests_stages(tmp_path):
    from maskclustering_tpu.obs.trace import assemble_trace, render_trace

    events = str(tmp_path / "trace_events.jsonl")
    t = 1000.0
    rows = [
        _span_line("serve.queue_wait", t + 1.0, 1.0, request="r-000001",
                   scene="s"),
        _span_line("serve.request", t + 4.0, 3.0, request="r-000001",
                   scene="s"),
        # stage spans inside the execution window nest under it
        _span_line("associate", t + 2.0, 0.8),
        _span_line("graph", t + 3.0, 0.5),
        # an unrelated span outside the window stays out
        _span_line("associate", t + 20.0, 0.5),
        # another request's skeleton stays out entirely
        _span_line("serve.request", t + 9.0, 1.0, request="r-000002",
                   scene="z"),
    ]
    with open(events, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write('{"torn')  # the reader's torn-line policy applies here too
    trace = assemble_trace("r-000001", events)
    kinds = [s["kind"] for s in trace["segments"]]
    assert kinds == ["queue_wait", "attempt"]
    execution = trace["segments"][1]
    assert [c["label"] for c in execution["children"]] == ["associate",
                                                           "graph"]
    assert execution["dur_s"] == 3.0
    assert any("torn" in w for w in trace["warnings"])
    text = render_trace(trace)
    assert "queue wait" in text and "execution" in text
    assert "· associate" in text
    # an unknown request id answers loudly, not emptily
    missing = assemble_trace("r-999999", events)
    assert not missing["segments"] and missing["warnings"]


def test_trace_cli_json_and_exit_codes(tmp_path):
    from maskclustering_tpu.obs import trace as trace_mod

    events = str(tmp_path / "cli_events.jsonl")
    with open(events, "w") as f:
        f.write(json.dumps(_span_line("serve.request", 1000.0, 1.0,
                                      request="r-000001")) + "\n")
    assert trace_mod.main(["r-000001", "--events", events, "--json"]) == 0
    assert trace_mod.main(["r-404404", "--events", events]) == 1


# ---------------------------------------------------------------------------
# stub tier: relay folding, relay loss under SIGKILL, crash-trace assembly
# ---------------------------------------------------------------------------


class _Client:
    def __init__(self):
        self.events = []
        self.done = threading.Event()

    def send(self, ev):
        self.events.append(ev)
        if ev.get("kind") in ("result", "reject"):
            self.done.set()

    @property
    def terminal(self):
        return self.events[-1] if self.events else None


def _submit(queue, scene, i, **kw):
    client = _Client()
    req = protocol.build_request({"op": "scene", "scene": scene, **kw},
                                 f"r-{i:06d}")
    req.send = client.send
    queue.submit(req)
    return client


def _counter(name):
    return obs.registry().snapshot()["counters"].get(name, 0.0)


def test_stub_relay_folds_and_crash_loses_window_not_registry(
        tmp_path, monkeypatch):
    """Relay-loss unit: a worker SIGKILL mid-window loses at most the
    unshipped delta — the parent registry keeps every folded counter AND
    the parent-booked crash counters; obs.trace then reconstructs the
    crash -> requeue -> respawn request end-to-end."""
    from maskclustering_tpu.obs.trace import assemble_trace, render_trace
    from maskclustering_tpu.serve.admission import AdmissionQueue
    from maskclustering_tpu.serve.router import Router
    from maskclustering_tpu.serve.supervisor import WorkerSupervisor

    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    events = str(tmp_path / "stub_events.jsonl")
    obs.configure(events, truncate=True, meta={"tool": "serve"})
    agg = telemetry.WindowAggregator(window_s=0.2)
    telemetry.install(agg)
    cfg = load_config("scannet").replace(
        data_root=str(tmp_path), config_name="stubtel", step=1,
        worker_heartbeat_s=1.0, retry_backoff_s=0.05)
    queue = AdmissionQueue(8)
    sup = WorkerSupervisor(cfg, queue, Router(cfg),
                           journal_dir=str(tmp_path / "journals"),
                           child_argv=[sys.executable, STUB],
                           start_timeout_s=15.0, poll_s=0.05)
    sup.start()
    try:
        crash = _submit(queue, "stub-crash", 1)
        assert crash.done.wait(30.0) and crash.terminal["status"] == "ok"
        ok = _submit(queue, "stub-ok", 2)
        assert ok.done.wait(30.0) and ok.terminal["status"] == "ok"
        assert sup.wait_idle(5.0)
        # the satellite status surface: liveness visible BEFORE a wedge
        w = sup.stats()["worker"]
        assert w["alive"] is True
        assert w["hb_age_s"] < 5.0 and w["hb_budget_s"] == 1.0
        assert w["consecutive_respawns"] == 0  # reset on the healthy ready
        assert w["inflight"] is None and w["inflight_crashes"] == 0
        json.dumps(w)
    finally:
        stopped = sup.stop(timeout_s=10.0)
        telemetry.install(None)
        row = agg.roll()
        obs.flush_metrics()
        obs.disable()
    assert stopped

    # folded relay state: the stub shipped one telem line per SERVED
    # request (the crashed first execution died before its flush — that
    # window's delta is lost, nothing else is)
    assert _counter("worker.telem_messages") >= 2.0
    assert _counter("serve.requests_ok") >= 2.0
    assert _counter("d2h.bytes") >= 2 * 4096
    assert _counter("pipeline.host_sync") >= 2.0
    # the parent-booked crash accounting survived the relay loss
    assert _counter("serve.worker_crashes") == 1.0
    assert _counter("serve.requests_requeued") == 1.0
    # and the windowed plane booked the crash + both latencies
    assert row["crashes"] + sum(
        wd.get("crashes", 0) for wd in agg.snapshot()["windows"]) >= 1

    # obs.trace: crash -> requeue -> respawn, end to end, with queue waits
    trace = assemble_trace("r-000001", events,
                           journal_dir=str(tmp_path / "journals"))
    kinds = [s["kind"] for s in trace["segments"]]
    assert kinds.count("queue_wait") >= 2, kinds  # admission + requeue
    assert "crash" in kinds
    assert "attempt" in kinds  # the respawned worker's relayed execution
    assert any(s["kind"] == "journal" and "INTERRUPTED" in s["label"]
               for s in trace["segments"])
    # causality: the crash precedes the (respawned) relayed execution
    assert kinds.index("crash") < kinds.index("attempt")
    text = render_trace(trace)
    assert "WORKER CRASH" in text and "queue wait" in text

    # the Serving report over the same events file shows the relayed
    # counters and the crash containment lines — nothing stranded
    run = RunData(events)
    serving = render_serving(run)
    assert "worker crashes 1" in serving
    assert run._counters["serve.requests_ok"] >= 2.0


# ---------------------------------------------------------------------------
# acceptance: topology parity — in-process vs --isolate-worker
# ---------------------------------------------------------------------------

SPEC_SMALL = {"num_boxes": 3, "num_frames": 6, "image_hw": (48, 64),
              "spacing": 0.08, "seed": 11}   # == test_serve_supervisor's
SPEC_A = {"num_boxes": 3, "num_frames": 10, "image_hw": (60, 80),
          "spacing": 0.06, "seed": 40}       # == test_serve / test_executor
SCENE_SMALL, SCENE_A = "scene0000_00", "scene0002_00"

PARITY_FAMILIES = ("serve.", "d2h.", "h2d.", "pipeline.", "run.")


def _family_counters(counters):
    return {k: v for k, v in counters.items()
            if k.startswith(PARITY_FAMILIES)
            and not k.startswith("serve.queue_depth")}


def _normalize_serving(text):
    """Serving sections compare structurally: latency/telemetry numbers
    are timing, everything else must match verbatim."""
    out = []
    for line in text.splitlines():
        if line.startswith(("request latency:", "telemetry:")):
            out.append(re.sub(r"\d+(\.\d+)?", "#", line))
        else:
            out.append(line)
    return "\n".join(out)


def _soak(root, tmp_path, label, isolate):
    from maskclustering_tpu.serve.client import ServeClient
    from maskclustering_tpu.serve.daemon import ServeDaemon

    events = str(tmp_path / f"{label}_events.jsonl")
    sock = str(tmp_path / f"{label}.sock")
    cfg = load_config("scannet").replace(
        data_root=root, config_name=label, step=1,
        distance_threshold=0.05, mask_pad_multiple=32,
        worker_heartbeat_s=60.0)
    obs.configure(events, truncate=True, meta={"tool": "serve",
                                               "config": label})
    daemon = ServeDaemon(cfg, socket_path=sock, capacity=4,
                         journal_dir=str(tmp_path / f"{label}_journals"),
                         warm_scenes=(SCENE_SMALL, SCENE_A),
                         freeze_after_warm=False,
                         isolate_worker=isolate,
                         telemetry_window_s=0.5)
    telemetry_doc = None
    try:
        daemon.start()
        with ServeClient(sock, timeout_s=600.0) as client:
            for i, (scene, spec) in enumerate(
                    [(SCENE_SMALL, SPEC_SMALL), (SCENE_A, SPEC_A)] * 2):
                terminal, _st, _lat = client.run_scene(
                    scene,
                    synthetic=dict(spec, image_hw=list(spec["image_hw"])),
                    tag=f"par-{i}")
                assert terminal.get("status") == "ok", (label, terminal)
            telemetry_doc = client.telemetry()
    finally:
        daemon.request_stop()
        daemon.shutdown()
        daemon.emit_serve_counters()
        obs.flush_metrics()
        counters = dict(obs.registry().snapshot()["counters"])
        obs.disable()
    return {"events": events, "counters": counters,
            "telemetry": telemetry_doc, "daemon": daemon}


def test_topology_parity_serving_report_and_relayed_counters(tmp_path):
    """ISSUE-13 acceptance: the same 4-request mixed-bucket soak renders
    the same Serving section and books the same serve./d2h./h2d./pipeline.
    counter names AND values in-process and under --isolate-worker
    (modulo the worker.* relay tag) — the production topology reports
    exactly what the test topology does."""
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    root = str(tmp_path / "data")
    for seq, spec in ((SCENE_SMALL, SPEC_SMALL), (SCENE_A, SPEC_A)):
        write_scannet_layout(make_scene(**spec), root, seq)

    inproc = _soak(root, tmp_path, "telin", isolate=False)
    iso = _soak(root, tmp_path, "teliso", isolate=True)

    # counter parity: same names, same values, modulo the process tag
    a = _family_counters(inproc["counters"])
    b = _family_counters(iso["counters"])
    assert a, "in-process soak booked no parity-family counters"
    assert set(a) == set(b), (set(a) ^ set(b))
    mismatched = {k: (a[k], b[k]) for k in a if a[k] != b[k]}
    assert not mismatched, mismatched
    # the relay tag exists only on the isolated side
    assert iso["counters"].get("worker.telem_messages", 0) >= 1
    assert "worker.telem_messages" not in inproc["counters"]

    # Serving report parity (rendered from each topology's events file)
    run_a, run_b = RunData(inproc["events"]), RunData(iso["events"])
    sec_a, sec_b = render_serving(run_a), render_serving(run_b)
    assert "requests 4" in sec_a and "ok 4" in sec_a
    assert _normalize_serving(sec_a) == _normalize_serving(sec_b), \
        f"--- in-process ---\n{sec_a}\n--- isolated ---\n{sec_b}"
    # span-table parity of names: the relayed child spans land under the
    # same stage names the in-process run books directly
    for name in ("serve.request", "serve.queue_wait", "associate"):
        assert name in run_a.spans, name
        assert name in run_b.spans, name

    # the telemetry op answered live in BOTH topologies, and the isolated
    # stats carry the worker-liveness satellite fields
    for res in (inproc, iso):
        tel = res["telemetry"]["telemetry"]
        assert tel["windows"], "no telemetry window closed during the soak"
        assert tel["cumulative"]["counters"]["serve.requests"] >= 4
    w = iso["telemetry"]["worker"]
    assert w["alive"] is True and w["consecutive_respawns"] == 0
    assert isinstance(w["hb_age_s"], float) and w["hb_age_s"] < 60.0

    # obs.trace assembles a served request end-to-end from the ISOLATED
    # topology's events: queue wait + relayed execution with stage spans
    from maskclustering_tpu.obs.trace import assemble_trace

    rid = "r-000001"
    trace = assemble_trace(rid, iso["events"],
                           journal_dir=str(tmp_path / "teliso_journals"))
    kinds = [s["kind"] for s in trace["segments"]]
    assert "queue_wait" in kinds and "attempt" in kinds
    execution = next(s for s in trace["segments"] if s["kind"] == "attempt")
    assert "worker pid" in execution["detail"]
    assert any(c["label"] == "associate" for c in execution["children"])
