"""Orchestrator end-to-end test: all 7 steps over a synthetic on-disk scene.

Exercises the full reference pipeline shape (run.py:85-105) in-process:
precomputed masks -> clustering -> class-agnostic AP -> CLIP features (hash
encoder) -> label features -> open-vocab query -> class-aware AP, plus
resume skipping and failure capture.
"""

import os

import numpy as np
import pytest

from maskclustering_tpu.config import load_config
from maskclustering_tpu.run import (
    DEFAULT_STEPS,
    check_masks,
    cluster_scene,
    get_seq_name_list,
    make_encoder,
    run_pipeline,
)
from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout


@pytest.fixture(scope="module")
def scene_root(tmp_path_factory):
    data_root = str(tmp_path_factory.mktemp("data"))
    scene = make_scene(num_boxes=3, num_frames=10, image_hw=(60, 80), seed=7,
                       spacing=0.05)
    write_scannet_layout(scene, data_root, "scene0001_00")
    return data_root


def _cfg(data_root):
    return load_config("scannet").replace(
        data_root=data_root, config_name="testrun", step=1,
        distance_threshold=0.05, mask_pad_multiple=32)


def test_full_pipeline(scene_root):
    import json

    from maskclustering_tpu import obs

    cfg = _cfg(scene_root)
    events = os.path.join(scene_root, "events.jsonl")
    try:
        report = run_pipeline(
            cfg, ["scene0001_00"], steps=DEFAULT_STEPS, resume=True,
            encoder_spec="hash:16", obs_events=events,
            report_path=os.path.join(scene_root, "report.json"))
    finally:
        obs.disable()
    assert not obs.enabled(), "run_pipeline must disarm what it armed"
    assert [s.status for s in report.scenes] == ["ok"]
    assert report.scenes[0].num_objects == 3
    assert set(report.step_seconds) == set(DEFAULT_STEPS)

    # obs wiring: the digest is embedded in the saved report, its stage set
    # covers the legacy per-scene timings keys, and the report CLI renders
    # a table from the same events file (the observability acceptance path)
    saved = json.load(open(os.path.join(scene_root, "report.json")))
    assert saved["obs"]["events"] == events
    assert set(report.scenes[0].timings) <= set(saved["obs"]["stages"])
    assert saved["obs"]["counters"]["run.scenes_ok"] >= 1
    assert saved["obs"]["h2d_bytes"] > 0 and saved["obs"]["d2h_bytes"] > 0
    from maskclustering_tpu.obs.report import RunData, render_report

    table = render_report(RunData(events))
    for key in report.scenes[0].timings:
        assert key in table

    # perf-ledger wiring: a reported run appends one schema-versioned
    # trajectory row (routed to a per-test tmp ledger via MCT_PERF_LEDGER)
    from maskclustering_tpu.obs import ledger as led

    rows = led.read_ledger(led.default_ledger_path())
    assert len(rows) == 1
    assert rows[0]["tool"] == "run" and rows[0]["config"] == "testrun"
    assert rows[0]["v"] == led.LEDGER_SCHEMA_VERSION
    assert rows[0]["value"] is not None and rows[0]["scenes_ok"] == 1
    assert rows[0]["stages"]  # obs digest stages rode along

    pred_dir = os.path.join(scene_root, "prediction")
    ca = np.load(os.path.join(pred_dir, "testrun_class_agnostic", "scene0001_00.npz"))
    assert ca["pred_masks"].shape[1] == 3
    aware = np.load(os.path.join(pred_dir, "testrun", "scene0001_00.npz"))
    assert aware["pred_masks"].shape == ca["pred_masks"].shape
    assert (aware["pred_classes"] > 0).all()  # every object got a vocab label

    # class-agnostic AP on clean synthetic data should be perfect except the
    # floor phantom (no_class remap); eval files written under data_root
    eval_txt = os.path.join(scene_root, "evaluation", "scannet",
                            "testrun_class_agnostic.txt")
    assert os.path.exists(eval_txt)
    assert os.path.exists(os.path.join(scene_root, "report.json"))

    # resume: a second run skips everything
    report2 = run_pipeline(cfg, ["scene0001_00"], steps=("cluster",), resume=True)
    assert [s.status for s in report2.scenes] == ["skipped"]


def test_cluster_scenes_worker_pool(scene_root):
    """workers > 1 ships the config object itself to spawn workers, so
    programmatic replace() fields survive (no reload from configs/)."""
    from maskclustering_tpu.run import cluster_scenes

    cfg = _cfg(scene_root).replace(backend="cpu")
    statuses = cluster_scenes(cfg, ["scene0001_00"], workers=2, resume=False)
    assert [s.status for s in statuses] == ["ok"]
    assert statuses[0].num_objects == 3


def test_cluster_scenes_mesh_writes_identical_artifacts(tmp_path):
    """cfg.mesh_shape routes the cluster step through the fused mesh path
    and produces the same npz + object_dict artifacts as the host path."""
    from maskclustering_tpu.run import cluster_scenes

    root = str(tmp_path / "data")
    names = []
    for i in range(3):
        scene = make_scene(num_boxes=3, num_frames=8, image_hw=(48, 64),
                           spacing=0.05, seed=20 + i)
        names.append(f"scene{i:04d}_00")
        write_scannet_layout(scene, root, names[-1])
    base = load_config("scannet").replace(
        data_root=root, config_name="meshrun", step=1,
        distance_threshold=0.05, mask_pad_multiple=32, frame_pad_multiple=4,
        point_chunk=2048)

    host = cluster_scenes(base.replace(config_name="hostrun"), names, resume=False)
    meshed = cluster_scenes(base.replace(mesh_shape=(2, 4)), names, resume=False)
    assert [s.status for s in host] == ["ok"] * 3
    assert [s.status for s in meshed] == ["ok"] * 3

    pred = os.path.join(root, "prediction")
    for name in names:
        a = np.load(os.path.join(pred, "hostrun_class_agnostic", f"{name}.npz"))
        b = np.load(os.path.join(pred, "meshrun_class_agnostic", f"{name}.npz"))
        for key in ("pred_masks", "pred_score", "pred_classes"):
            np.testing.assert_array_equal(a[key], b[key])
        od_dir = os.path.join(root, "scannet", "processed", name, "output", "object")
        od_a = np.load(os.path.join(od_dir, "hostrun", "object_dict.npy"),
                       allow_pickle=True).item()
        od_b = np.load(os.path.join(od_dir, "meshrun", "object_dict.npy"),
                       allow_pickle=True).item()
        assert od_a.keys() == od_b.keys()
        for k in od_a:
            np.testing.assert_array_equal(od_a[k]["point_ids"], od_b[k]["point_ids"])
            assert od_a[k]["mask_list"] == od_b[k]["mask_list"]
            assert od_a[k]["repre_mask_list"] == od_b[k]["repre_mask_list"]


def test_failure_is_captured_not_raised(scene_root):
    cfg = _cfg(scene_root)
    status = cluster_scene(cfg, "scene_does_not_exist", resume=False)
    assert status.status == "failed"
    assert "Error" in status.error or "Traceback" in status.error


def test_missing_gt_is_a_recorded_failure(tmp_path):
    """A mispointed gt_dir must fail the run (reference evaluate.py:407-411
    raises), recorded in RunReport.step_errors — not a silent no-AP pass."""
    import shutil

    from maskclustering_tpu.run import run_pipeline

    root = str(tmp_path / "data")
    scene = make_scene(num_boxes=2, num_frames=8, image_hw=(48, 64), seed=5,
                       spacing=0.05)
    write_scannet_layout(scene, root, "scene0009_00")
    shutil.rmtree(os.path.join(root, "scannet", "gt"))
    cfg = _cfg(root).replace(config_name="nogt")
    report = run_pipeline(cfg, ["scene0009_00"], steps=("cluster", "eval_ca"))
    assert [s.status for s in report.scenes] == ["ok"]
    assert "eval_ca" in report.step_errors
    assert not report.ok


def test_check_masks_reports_missing(scene_root):
    cfg = _cfg(scene_root)
    assert check_masks(cfg, ["scene0001_00"]) == []
    assert check_masks(cfg, ["scene0001_00", "ghost"]) == ["ghost"]


def test_seq_name_list_sources(tmp_path):
    (tmp_path / "scannet.txt").write_text("a\nb\n\n")
    assert get_seq_name_list("scannet", str(tmp_path)) == ["a", "b"]
    assert get_seq_name_list("scannet", str(tmp_path), "x+y") == ["x", "y"]
    with pytest.raises(FileNotFoundError):
        get_seq_name_list("matterport3d", str(tmp_path))


def test_make_encoder_specs():
    assert make_encoder("hash").feature_dim == 64
    assert make_encoder("hash:8").feature_dim == 8
    with pytest.raises(ValueError):
        make_encoder("magic")


def test_unknown_step_rejected(scene_root):
    with pytest.raises(ValueError):
        run_pipeline(_cfg(scene_root), [], steps=("clutser",))


class TestTasmapVariantSteps:
    def test_vis_and_top_images_steps(self, tmp_path):
        """TASMAP_STEPS variant: cluster -> vis -> top_images end to end."""
        import os

        from maskclustering_tpu.config import load_config
        from maskclustering_tpu.run import TASMAP_STEPS, run_pipeline
        from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

        scene = make_scene(num_boxes=2, num_frames=8, image_hw=(48, 64), seed=11,
                           spacing=0.05)
        root = str(tmp_path / "data")
        write_scannet_layout(scene, root, "scene0003_00")
        cfg = load_config("scannet").replace(
            data_root=root, config_name="tvar", step=1,
            distance_threshold=0.03, mask_pad_multiple=64)
        report = run_pipeline(cfg, ["scene0003_00"], steps=TASMAP_STEPS)
        assert set(report.step_seconds) == set(TASMAP_STEPS)
        vis_dir = os.path.join(root, "vis", "scene0003_00")
        assert os.path.exists(os.path.join(vis_dir, "instances.ply"))
        grids = os.listdir(os.path.join(vis_dir, "top_images", "grid"))
        assert len(grids) >= 1

    def test_clean_output(self, tmp_path):
        import os

        from maskclustering_tpu.config import load_config
        from maskclustering_tpu.utils.clean_output import clean_scene_outputs
        from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

        scene = make_scene(num_boxes=1, num_frames=4, image_hw=(32, 40), seed=5)
        root = str(tmp_path / "data")
        write_scannet_layout(scene, root, "scene0004_00")
        cfg = load_config("scannet").replace(data_root=root)
        out_dir = os.path.join(root, "scannet", "processed", "scene0004_00", "output")
        assert os.path.isdir(out_dir)
        listed = clean_scene_outputs(cfg, ["scene0004_00"], dry_run=True)
        assert listed == [out_dir] and os.path.isdir(out_dir)
        removed = clean_scene_outputs(cfg, ["scene0004_00"], dry_run=False)
        assert removed == [out_dir] and not os.path.exists(out_dir)


def test_init_backend_or_die_cpu():
    """Watchdog-wrapped backend init returns devices on a healthy backend."""
    from maskclustering_tpu.run import init_backend_or_die

    devices = init_backend_or_die(60, platform="cpu")
    assert len(devices) >= 1


class TestDaemonFuture:
    """The prefetcher's one-shot future (utils/daemon_future.py)."""

    def test_result_returns_value(self):
        from maskclustering_tpu.utils.daemon_future import DaemonFuture

        assert DaemonFuture(lambda: 41 + 1).result() == 42

    def test_exception_reraises_in_consumer(self):
        from maskclustering_tpu.utils.daemon_future import DaemonFuture

        fut = DaemonFuture(lambda: (_ for _ in ()).throw(OSError("disk gone")))
        with pytest.raises(OSError, match="disk gone"):
            fut.result()

    def test_abandoned_late_result_and_error_are_dropped(self):
        """After a timed-out consumer calls abandon(), a late value (or a
        late error) is dropped instead of living on the future — and the
        drop is booked as run.abandoned_results (rendered in Faults)."""
        import threading

        from maskclustering_tpu.obs import metrics
        from maskclustering_tpu.utils.daemon_future import DaemonFuture

        before = metrics.registry().snapshot()["counters"].get(
            "run.abandoned_results", 0.0)
        for outcome in ("value", "error"):
            gate = threading.Event()

            def wedged(kind=outcome):
                gate.wait(5.0)
                if kind == "error":
                    raise OSError("late failure")
                return {"big": "scene tensors"}

            fut = DaemonFuture(wedged, name=f"late-{outcome}")
            with pytest.raises(TimeoutError):
                fut.result(timeout=0.02)
            fut.abandon()
            gate.set()
            assert fut._done.wait(5.0)
            assert fut._value is None and fut._exc is None  # dropped
        after = metrics.registry().snapshot()["counters"].get(
            "run.abandoned_results", 0.0)
        assert after - before == 2.0

    def test_runs_on_daemon_thread(self):
        """The whole point vs ThreadPoolExecutor: an abandoned blocking load
        must never stall interpreter shutdown."""
        import threading

        from maskclustering_tpu.utils.daemon_future import DaemonFuture

        seen = {}
        DaemonFuture(lambda: seen.setdefault(
            "daemon", threading.current_thread().daemon)).result()
        assert seen["daemon"] is True
