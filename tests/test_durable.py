"""mct-durable: the durability plane (ISSUE-20 acceptance).

Unit tier: the admission WAL's round-trip / torn-tail / first-admit-wins
/ compaction contract (serve/wal.py), idempotency-key protocol
validation, the ``die`` FaultPlan kind (daemon-level SIGKILL at the
admission seam), journal + stream-snapshot retention pruning, the
durability config knobs, and the perf-ledger durability fence.

Stub tier (jax-free worker stub, milliseconds): stream-session failover
— a crashed/retired/recarved slice with a per-chunk snapshot on disk
RE-OPENS the session on a surviving slice instead of answering the typed
``stream_lost`` (which remains the contract when no snapshot exists —
pinned by tests/test_serve_pool.py).

Integration tier (real in-process worker over the suite's shared tiny
shape bucket): WAL dedupe on a live daemon, then a restart over the same
journal dir — with a torn WAL tail — that must replay the
journaled-but-unanswered request and settle a keyed resubmit ok. The
real-subprocess daemon-death acceptance is ci.sh's rc-13 chaos drill
(scripts/load_gen.py --chaos-drill).
"""

import json
import os
import sys
import time

import pytest

from maskclustering_tpu.config import load_config
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve import wal
from maskclustering_tpu.serve.admission import AdmissionQueue
from maskclustering_tpu.serve.client import ServeClient
from maskclustering_tpu.serve.daemon import ServeDaemon
from maskclustering_tpu.serve.pool import WorkerPool
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.serve.supervisor import WorkerSupervisor
from maskclustering_tpu.utils import faults
from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO_ROOT, "tests", "worker_stub.py")

# the suite's shared tiny bucket (byte-identical to test_serve SPEC_A /
# test_executor scene0: in a full run its programs are process-warm)
SPEC_A = {"num_boxes": 3, "num_frames": 10, "image_hw": (60, 80),
          "spacing": 0.06, "seed": 40}
SCENE_A = "scene0000_00"


def _cfg(data_root, **kw):
    base = dict(data_root=str(data_root), config_name="durable", step=1,
                distance_threshold=0.05, mask_pad_multiple=32,
                worker_heartbeat_s=1.0, retry_backoff_s=0.05)
    base.update(kw)
    return load_config("scannet").replace(**base)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.set_plan(None)
    faults.clear_stop()
    yield
    faults.set_plan(None)
    faults.clear_stop()


# ---------------------------------------------------------------------------
# units: the admission WAL file contract
# ---------------------------------------------------------------------------


def _doc(scene="s1", idem=""):
    d = {"op": "scene", "scene": scene}
    if idem:
        d["idem"] = idem
    return d


def test_wal_round_trip_pending_answered_max_id(tmp_path):
    path = str(tmp_path / wal.WAL_FILENAME)
    w = wal.AdmissionWal(path)
    w.admit("r-000003", _doc("a", "k-a"), idem="k-a")
    w.admit("r-000007", _doc("b"))
    w.admit("r-000010", _doc("c", "k-c"), idem="k-c")
    w.dispatch("r-000003")
    w.terminal("r-000003", {"kind": "result", "id": "r-000003",
                            "status": "ok"}, idem="k-a")
    w.close()

    state = wal.read_wal(path)
    # admission order preserved; the settled request is NOT pending
    assert [(rid, d["scene"], idem) for rid, d, idem in state.pending] == \
        [("r-000007", "b", ""), ("r-000010", "c", "k-c")]
    # keyed terminals populate the dedupe cache; unkeyed admits never do
    assert set(state.answered) == {"k-a"}
    assert state.answered["k-a"]["status"] == "ok"
    assert state.max_id == 10
    assert state.rows == 5 and state.stats.torn == 0
    # a missing file is an EMPTY state, never an error
    empty = wal.read_wal(str(tmp_path / "nope.jsonl"))
    assert empty.pending == [] and empty.max_id == 0


def test_wal_torn_tail_and_first_admit_wins(tmp_path):
    path = str(tmp_path / wal.WAL_FILENAME)
    w = wal.AdmissionWal(path)
    w.admit("r-000001", _doc("a", "k1"), idem="k1")
    w.admit("r-000001", _doc("DUPE"))  # duplicate rid: first admit wins
    w.admit("r-000002", _doc("b"))
    w.close()
    # the crash-torn tail: a half-written line with no newline terminator
    with open(path, "ab") as f:
        f.write(b'{"v": 1, "kind": "wal.admit", "request": "r-0000')

    state = wal.read_wal(path)
    assert state.stats.torn == 1
    assert [(rid, d["scene"]) for rid, d, _ in state.pending] == \
        [("r-000001", "a"), ("r-000002", "b")]
    assert state.pending[0][2] == "k1"


def test_wal_compact_rewrites_to_recovered_state(tmp_path):
    path = str(tmp_path / wal.WAL_FILENAME)
    w = wal.AdmissionWal(path)
    for i in range(6):
        w.admit(f"r-{i:06d}", _doc(f"s{i}", f"k{i}"), idem=f"k{i}")
        w.terminal(f"r-{i:06d}", {"kind": "result", "id": f"r-{i:06d}",
                                  "status": "ok"}, idem=f"k{i}")
    w.admit("r-000099", _doc("live", "k-live"), idem="k-live")
    w.close()
    before = wal.read_wal(path)
    assert len(before.pending) == 1 and len(before.answered) == 6

    wal.compact(path, before)
    # compaction is lossless for recovery: same pending, same cache, and
    # the settled requests' admit+terminal pairs collapsed to one row each
    after = wal.read_wal(path)
    assert after.pending == before.pending
    assert after.answered == before.answered
    assert after.max_id == before.max_id
    assert after.rows == 7 < 13


# ---------------------------------------------------------------------------
# units: idempotency keys on the wire
# ---------------------------------------------------------------------------


def test_protocol_idem_validation_and_build():
    doc = protocol.parse_line(json.dumps(
        {"op": "scene", "scene": "s1", "idem": "client-42"}))
    req = protocol.build_request(doc, "r-000001")
    assert req.idem == "client-42"
    # no key -> empty string, never None
    bare = protocol.build_request(protocol.parse_line(
        '{"op": "scene", "scene": "s2"}'), "r-000002")
    assert bare.idem == ""

    for bad in ({"op": "scene", "scene": "a", "idem": 7},
                {"op": "scene", "scene": "a", "idem": ""},
                {"op": "scene", "scene": "a", "idem": "x/y"},
                {"op": "scene", "scene": "a",
                 "idem": "k" * (protocol.IDEM_MAX_LEN + 1)}):
        with pytest.raises(protocol.ProtocolError):
            protocol.parse_line(json.dumps(bad))
    # the boundary itself is legal
    ok = protocol.parse_line(json.dumps(
        {"op": "scene", "scene": "a", "idem": "k" * protocol.IDEM_MAX_LEN}))
    assert len(ok["idem"]) == protocol.IDEM_MAX_LEN

    # supervisor forwarding never propagates the key: dedupe is a DAEMON
    # contract, a worker resubmit must not re-enter the cache
    fwd = protocol.forward_request(protocol.build_request(
        protocol.parse_line(json.dumps(
            {"op": "scene", "scene": "a", "idem": "k1"})), "r-000003"))
    assert "idem" not in fwd


# ---------------------------------------------------------------------------
# units: post-freeze cache deserializes are not compile violations
# ---------------------------------------------------------------------------


def test_sanitizer_retracts_post_freeze_on_persistent_cache_hit():
    """A restarted daemon replaying WAL work traces its programs again,
    but the persistent compilation cache serves the bytes — the sanitizer
    must read that as a warm restart (zero compiles), not a post_freeze
    violation. A post-freeze cache MISS stays a violation."""
    from maskclustering_tpu.analysis import retrace_sanitizer as rs

    rs.reset()
    try:
        st = rs._STATE
        st.frozen = True
        st.on_compile("stream_probe", "f32[3]")
        assert [v["kind"] for v in st.violations] == ["post_freeze"]
        st.on_cache_event(True)  # persistent-cache deserialize
        assert st.violations == []
        s = rs.summary()
        assert (s["post_freeze"], s["compiles"], s["cache_hits"]) \
            == (0, 0, 1)
        # a miss (a genuinely new build after freeze) is still flagged
        st.on_compile("stream_probe", "f32[4]")
        st.on_cache_event(False)
        assert rs.summary()["post_freeze"] == 1
    finally:
        rs.reset()


# ---------------------------------------------------------------------------
# units: the `die` FaultPlan kind (the chaos drill's daemon-death seam)
# ---------------------------------------------------------------------------


def test_fault_plan_die_parses_with_admission_defaults():
    plan = faults.FaultPlan.from_spec("die:sceneA")
    (e,) = plan.entries
    assert (e.kind, e.seam, e.scene, e.remaining) == \
        ("die", "admission", "sceneA", 1)
    e2 = faults.FaultPlan.from_spec("die:sceneB.admission:2").entries[0]
    assert (e2.seam, e2.remaining) == ("admission", 2)


def test_fault_plan_die_sigkills_self_at_admission_seam(monkeypatch):
    import signal as _signal

    kills = []
    monkeypatch.setattr(faults.os, "kill",
                        lambda pid, sig: kills.append((pid, sig)))
    faults.set_plan(faults.FaultPlan.from_spec("die:sceneA.admission:1"))
    faults.inject("admission", "other-scene")  # scene mismatch: no fire
    assert kills == []
    faults.inject("admission", "sceneA")
    assert kills == [(os.getpid(), _signal.SIGKILL)]
    faults.inject("admission", "sceneA")  # count 1: exhausted
    assert len(kills) == 1


# ---------------------------------------------------------------------------
# units: retention pruning
# ---------------------------------------------------------------------------


def test_prune_dir_keep_age_floor_and_wal_skip(tmp_path):
    d = str(tmp_path)
    now = time.time()

    def put(name, age_s):
        p = os.path.join(d, name)
        with open(p, "w") as f:
            f.write("x")
        os.utime(p, (now - age_s, now - age_s))
        return p

    old = [put(f"r-{i:06d}.jsonl", 3600 + i) for i in range(4)]
    fresh = put("r-000099.jsonl", 1.0)       # under MIN_PRUNE_AGE_S
    walfile = put(wal.WAL_FILENAME, 7200)    # skipped by NAME, always
    other = put("snapshot.stream.npz", 7200)  # suffix-filtered out

    # keep-N: the 2 oldest .jsonl beyond keep=2 go; the fresh file is
    # exempt from counting AND from deletion (the live-state floor)
    removed = wal.prune_dir(d, keep=2, max_age_s=0.0, suffixes=(".jsonl",),
                            now=now)
    assert removed == 2
    # oldest-first: old[3] and old[2] (the two oldest .jsonl) are pruned
    assert not os.path.exists(old[3]) and not os.path.exists(old[2])
    assert os.path.exists(old[0]) and os.path.exists(fresh)
    assert os.path.exists(walfile) and os.path.exists(other)

    # age policy on the snapshot suffix
    assert wal.prune_dir(d, keep=0, max_age_s=600.0,
                         suffixes=(".stream.npz",), now=now) == 1
    assert not os.path.exists(other)

    # both policies disabled -> no scan, no deletions
    assert wal.prune_dir(d, keep=0, max_age_s=0.0,
                         suffixes=(".jsonl",), now=now) == 0
    assert wal.prune_dir(str(tmp_path / "missing"), keep=1, max_age_s=1.0,
                         suffixes=(".jsonl",)) == 0


def test_config_validates_durability_knobs(tmp_path):
    cfg = _cfg(tmp_path, serve_journal_keep=8, serve_journal_max_age_s=60.0,
               serve_prune_interval_s=5.0)
    assert cfg.serve_journal_keep == 8
    for bad in (dict(serve_journal_keep=-1),
                dict(serve_journal_max_age_s=-0.5),
                dict(serve_prune_interval_s=-1.0),
                dict(stream_journal_every=-1)):
        with pytest.raises(ValueError):
            _cfg(tmp_path, **bad)


# ---------------------------------------------------------------------------
# units: the perf-ledger durability fence
# ---------------------------------------------------------------------------


def test_ledger_serve_row_carries_durability_and_fences():
    from maskclustering_tpu.obs import ledger as led

    row = led.serve_row({"metric": "m", "value": 1.0, "unit": "s/request",
                         "streams_resumed": 2, "wal_replayed": 3,
                         "wal_deduped": 4, "journals_pruned": 5})
    assert (row["streams_resumed"], row["wal_replayed"],
            row["wal_deduped"], row["journals_pruned"]) == (2, 3, 4, 5)
    assert led.durability_dimension(row)
    assert led.durability_dimension({"wal_replayed": 1})
    assert not led.durability_dimension({"wal_replayed": 0})
    assert not led.durability_dimension({"value": 1.0})
    assert not led.durability_dimension(None)


# ---------------------------------------------------------------------------
# stream-session failover on the jax-free stub (supervisor + pool)
# ---------------------------------------------------------------------------


class _Client:
    def __init__(self):
        import threading

        self.events = []
        self.done = threading.Event()

    def send(self, ev):
        self.events.append(ev)
        if ev.get("kind") in ("result", "reject"):
            self.done.set()

    @property
    def terminal(self):
        return self.events[-1] if self.events else None

    def states(self):
        return [e.get("state") for e in self.events
                if e.get("kind") == "status"]


def _submit(target, scene, i, *, op="scene", **kw):
    client = _Client()
    req = protocol.build_request({"op": op, "scene": scene, **kw},
                                 f"d-{i:06d}")
    req.send = client.send
    target.submit(req) if isinstance(target, AdmissionQueue) \
        else target.admit(req)
    return client


def _touch_snapshot(state_dir, scene):
    from maskclustering_tpu.models.streaming import stream_state_path

    os.makedirs(state_dir, exist_ok=True)
    path = stream_state_path(state_dir, scene)
    with open(path, "wb") as f:
        f.write(b"\x00")  # existence is the parent-side resumability test
    return path


def test_supervisor_stream_resumes_from_snapshot(tmp_path, monkeypatch):
    """A worker crash with an open stream AND a per-chunk snapshot on
    disk: the next op RE-OPENS the session on the respawned child
    (streams_resumed books) instead of answering stream_lost — the
    no-snapshot twin (tests/test_serve_pool.py) keeps stream_lost as the
    typed fallback."""
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    state_dir = str(tmp_path / "stream_state")
    queue = AdmissionQueue(8)
    sup = WorkerSupervisor(_cfg(tmp_path), queue, Router(_cfg(tmp_path)),
                           journal_dir=str(tmp_path / "journals"),
                           stream_state_dir=state_dir,
                           child_argv=[sys.executable, STUB],
                           start_timeout_s=15.0, poll_s=0.05)
    sup.start()
    try:
        opened = _submit(queue, "stream-x", 1, op="stream_chunk")
        assert opened.done.wait(15.0) and opened.terminal["status"] == "ok"
        assert sup.stats()["worker"]["open_streams"] == 1
        _touch_snapshot(state_dir, "stream-x")
        crash = _submit(queue, "stub-crash", 2)
        assert crash.done.wait(30.0) and crash.terminal["status"] == "ok"
        resumed = _submit(queue, "stream-x", 3, op="stream_chunk")
        assert resumed.done.wait(15.0)
        assert resumed.terminal["status"] == "ok"
        assert "stream_lost" not in resumed.states()
        st = sup.stats()["worker"]
        assert st["streams_resumed"] == 1
        assert st["lost_streams"] == 0
        fin = _submit(queue, "stream-x", 4, op="stream_end")
        assert fin.done.wait(15.0) and fin.terminal["done"] is True
    finally:
        sup.stop(timeout_s=10.0)


def test_pool_stream_fails_over_to_surviving_slice(tmp_path, monkeypatch):
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    state_dir = str(tmp_path / "stream_state")
    pool = WorkerPool(_cfg(tmp_path, serve_workers=2), AdmissionQueue(32),
                      Router(_cfg(tmp_path)),
                      journal_dir=str(tmp_path / "journals"),
                      stream_state_dir=state_dir,
                      child_argv=[sys.executable, STUB],
                      start_timeout_s=15.0, poll_s=0.05)
    pool.start()
    try:
        c1 = _submit(pool, "stream-f", 1, op="stream_chunk")
        assert c1.done.wait(15.0) and c1.terminal["status"] == "ok"
        owner = pool._stream_owner["stream-f"]
        _touch_snapshot(state_dir, "stream-f")
        with pool._lock:
            pool._dead.add(owner)  # simulate a retired owner slice
        try:
            c2 = _submit(pool, "stream-f", 2, op="stream_chunk")
            assert c2.done.wait(15.0)
            assert c2.terminal["status"] == "ok"
            assert "stream_lost" not in c2.states()
            # the session re-pinned to a SURVIVING slice
            assert pool._stream_owner["stream-f"] != owner
        finally:
            with pool._lock:
                pool._dead.discard(owner)
    finally:
        pool.stop(timeout_s=15.0)


def test_recarve_during_live_stream_resumes_from_snapshot(tmp_path,
                                                          monkeypatch):
    """The recarve contract for live streams, pinned: sessions die with
    the old slices (`_stream_owner` cleared), and the next op on a scene
    WITH a snapshot routes as a new stream whose fresh child resumes from
    disk — answered ok, never stream_lost."""
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    state_dir = str(tmp_path / "stream_state")
    pool = WorkerPool(_cfg(tmp_path, serve_workers=2), AdmissionQueue(32),
                      Router(_cfg(tmp_path)),
                      journal_dir=str(tmp_path / "journals"),
                      stream_state_dir=state_dir,
                      child_argv=[sys.executable, STUB],
                      start_timeout_s=15.0, poll_s=0.05)
    pool.start()
    try:
        c1 = _submit(pool, "stream-r", 1, op="stream_chunk")
        assert c1.done.wait(15.0) and c1.terminal["status"] == "ok"
        _touch_snapshot(state_dir, "stream-r")
        out = pool.recarve(workers=1, timeout_s=30.0)
        assert out["ok"] is True
        assert "stream-r" not in pool._stream_owner
        c2 = _submit(pool, "stream-r", 2, op="stream_chunk")
        assert c2.done.wait(15.0)
        assert c2.terminal["status"] == "ok"
        assert "stream_lost" not in c2.states()
        assert pool._stream_owner["stream-r"] == 0
        fin = _submit(pool, "stream-r", 3, op="stream_end")
        assert fin.done.wait(15.0) and fin.terminal["done"] is True
    finally:
        pool.stop(timeout_s=15.0)


# ---------------------------------------------------------------------------
# integration: WAL dedupe live + replay across a daemon restart
# ---------------------------------------------------------------------------


def test_daemon_wal_dedupe_and_restart_replay(tmp_path):
    """The WAL contract end to end on the real in-process worker: a keyed
    resubmit on a live daemon answers from cache (`deduped`), a restarted
    daemon over the same journal dir — with a crash-torn WAL tail —
    replays the journaled-but-unanswered request and settles a keyed
    resubmit ok. The real-subprocess SIGKILL version is the rc-13 chaos
    drill."""
    root = str(tmp_path / "data")
    write_scannet_layout(make_scene(**SPEC_A), root, SCENE_A)
    journals = str(tmp_path / "journals")
    syn = dict(SPEC_A, image_hw=list(SPEC_A["image_hw"]))
    sock1 = str(tmp_path / "mct1.sock")

    d1 = ServeDaemon(_cfg(root, config_name="durable1"), socket_path=sock1,
                     capacity=8, journal_dir=journals,
                     freeze_after_warm=False)
    d1.start()
    try:
        with ServeClient(sock1, timeout_s=300.0) as c:
            first, _st, _lat = c.run_scene(SCENE_A, synthetic=syn,
                                           idem="key-1", tag="t1")
            assert first["status"] == "ok" and "deduped" not in first
            again, _st, lat = c.run_scene(SCENE_A, synthetic=syn,
                                          idem="key-1", tag="t2")
            # answered from the WAL cache: no re-run, the resubmit's tag,
            # and the cached terminal's payload intact
            assert again["deduped"] is True and again["tag"] == "t2"
            assert again["status"] == "ok"
            assert again["id"] == first["id"]
            stats = c.stats()
            assert stats["durable"]["wal"] is True
            assert stats["durable"]["wal_deduped"] == 1
    finally:
        d1.request_stop()
        d1.shutdown()

    # the predecessor "died" with one journaled-but-unanswered keyed
    # request (appended post-shutdown = admitted, never answered) and a
    # torn final line — the worst recoverable WAL
    wal_path = os.path.join(journals, wal.WAL_FILENAME)
    assert os.path.exists(wal_path)
    w = wal.AdmissionWal(wal_path)
    w.admit("r-000097", {"op": "scene", "scene": SCENE_A, "synthetic": syn,
                         "idem": "key-2"}, idem="key-2")
    w.close()
    with open(wal_path, "ab") as f:
        f.write(b'{"v": 1, "kind": "wal.admit", "request": "r-0')

    # it also left settled per-request journals behind: retention at the
    # successor's start keeps serve_journal_keep newest, skips the WAL
    now = time.time()
    for i in range(6):
        p = os.path.join(journals, f"r-{i:06d}.jsonl")
        with open(p, "w") as f:
            f.write("{}\n")
        os.utime(p, (now - 3600 - i, now - 3600 - i))

    sock2 = str(tmp_path / "mct2.sock")
    d2 = ServeDaemon(_cfg(root, config_name="durable2",
                          serve_journal_keep=2),
                     socket_path=sock2, capacity=8, journal_dir=journals,
                     freeze_after_warm=False)
    d2.start()
    try:
        assert d2._ids >= 97  # id counter seeded past the replayed rid
        left = sorted(os.listdir(journals))
        assert wal.WAL_FILENAME in left
        # oldest-first: r-000005..r-000002 pruned, the 2 youngest stay
        assert os.path.exists(os.path.join(journals, "r-000000.jsonl"))
        assert os.path.exists(os.path.join(journals, "r-000001.jsonl"))
        assert not os.path.exists(os.path.join(journals, "r-000002.jsonl"))
        assert d2.stats()["durable"]["journals_pruned"] == 4
        with ServeClient(sock2, timeout_s=300.0) as c:
            stats = c.stats()
            assert stats["durable"]["wal_replayed"] == 1
            # the reconnecting client resubmits its key: re-attach to the
            # live replay or dedupe its cached terminal — either way the
            # SAME request id answers ok, exactly once
            term, _st, _lat = c.run_scene(SCENE_A, synthetic=syn,
                                          idem="key-2", tag="t3")
            assert term["status"] == "ok"
            assert term["id"] == "r-000097"
            stats = c.stats()
            assert stats["durable"]["wal_deduped"] \
                + stats["durable"]["wal_reattached"] >= 1
            # key-1's cache survived the restart (and the compaction)
            old, _st, _lat = c.run_scene(SCENE_A, synthetic=syn,
                                         idem="key-1")
            assert old["deduped"] is True and old["status"] == "ok"
    finally:
        d2.request_stop()
        d2.shutdown()
