"""Family 5 (retrace): compile-surface analyzer + runtime sanitizer.

Covers the ISSUE-9 acceptance set: seeded-defect fixtures asserting exact
ids for RETRACE.CAPTURE/BRANCH/STATIC/SURFACE, the census ratchet against
the committed compile_surface_baseline.json, repo-clean-modulo-baseline
(sharing the session-scoped ``fused_lattice_aot`` AOT sweep — no second
lattice lowering), CLI red on a fixture tree with an injected closure
capture, the serve-many sanitizer contract (a warm same-bucket scene
books ZERO compiles, across BOTH scene executors), and the
degradation-rung surface pin (donation-off adds only its baselined
variants; the exact-set variant runs cold in the slow tier).

Tier-1 wall budget (ISSUE-9): ~20 s for this file net of the
postprocess-fixture reclaim — the AST/census/report tests are
sub-second, the sanitizer units compile O(1) tiny programs, and the two
pipeline tests reuse tiny scenes + the process-warm jit caches.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from maskclustering_tpu.analysis.retrace import (
    RUNG_SURFACE,
    analyze_retrace,
    check_surface,
    compile_surface,
    fused_surface_rows,
    load_surface_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REL = "maskclustering_tpu/models/retrace_fix.py"


def _retrace(root, src, rel=_REL):
    """Write one seeded-defect module into a tmp tree, run the family
    (pure-AST mode: no census marker, no lowering)."""
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return analyze_retrace(str(root), lower_missing=False)


# ---------------------------------------------------------------------------
# seeded-defect fixtures: exact finding ids
# ---------------------------------------------------------------------------


def test_capture_fixture_flags_per_scene_closure(tmp_path):
    # DELIBERATE BREAK: a traced closure bakes `tensors` (per-scene state)
    # into its program; cfg/k_max are compile-stable and stay clean
    findings = _retrace(tmp_path / "bad", """
        import jax

        def build(cfg, k_max, tensors):
            def step(x):
                return x * tensors.scale + cfg.threshold + k_max
            return jax.jit(step)
    """)
    assert [f.id for f in findings if f.check == "RETRACE.CAPTURE"] == [
        f"RETRACE.CAPTURE:{_REL}:build:step:tensors"]

    # clean: compile-stable captures only, builder cached
    clean = _retrace(tmp_path / "ok", """
        import functools
        import jax

        @functools.lru_cache(maxsize=None)
        def build(cfg, k_max):
            def step(x):
                return x * k_max + cfg.threshold
            return jax.jit(step)
    """)
    assert not [f for f in clean
                if f.check in ("RETRACE.CAPTURE", "RETRACE.STATIC")]


def test_capture_fixture_flags_jit_partial_binding(tmp_path):
    # DELIBERATE BREAK: jit(partial(...)) binds a per-scene value — the
    # partial route must be checked exactly like a closure
    findings = _retrace(tmp_path, """
        import functools
        import jax

        def impl(x, *, scale):
            return x * scale

        def build(cfg, scene_scale):
            return jax.jit(functools.partial(impl, scale=scene_scale))
    """)
    ids = [f.id for f in findings if f.check == "RETRACE.CAPTURE"]
    assert ids == [f"RETRACE.CAPTURE:{_REL}:build:impl:scene_scale"]


def test_branch_fixture_flags_shape_branching(tmp_path):
    # DELIBERATE BREAK: trace-time `.shape` branch in a jit root, a
    # len() ternary in a module-local helper it calls, and a branch in a
    # NESTED def (reported once, under the nested fn — not double-counted
    # under the enclosing root too); the audited (mct-ok) and
    # dtype-branching functions stay clean
    findings = _retrace(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            def inner(y):
                if y.shape[0] > 2:
                    return y - 1
                return y

            y = helper(inner(x))
            if x.shape[0] > 4:
                return y + 1
            return y

        def helper(y):
            return y * 2 if len(y) > 3 else y

        @jax.jit
        def audited(x):
            if x.shape[0] > 4:  # mct-ok: RETRACE.BRANCH
                return x + 1
            return x

        @jax.jit
        def dtype_ok(x):
            if x.dtype == jnp.uint16:
                return x + 1
            return x
    """)
    ids = sorted(f.id for f in findings if f.check == "RETRACE.BRANCH")
    assert ids == [f"RETRACE.BRANCH:{_REL}:helper:1",
                   f"RETRACE.BRANCH:{_REL}:inner:1",
                   f"RETRACE.BRANCH:{_REL}:step:1"]


def test_call_form_decorator_is_one_site_and_helpers_are_stable(tmp_path):
    """Review regressions: a call-form `@jax.jit(...)` decorator must not
    mint a phantom second (anonymous, 'fresh') site, and a traced
    function calling a SIBLING nested helper captures a compile-stable
    callable, not per-scene state."""
    findings = _retrace(tmp_path, """
        import functools
        import jax

        @jax.jit(donate_argnums=(0,))
        def kernel(x):
            return x

        @functools.lru_cache(maxsize=None)
        def build(cfg):
            def helper(y):
                return y * cfg.scale

            def step(x):
                return helper(x)

            return jax.jit(step)
    """)
    assert not [f for f in findings
                if f.check in ("RETRACE.STATIC", "RETRACE.CAPTURE")]
    # exactly the two named roots need classification — no "<anon>"
    assert sorted(f.id for f in findings
                  if f.check == "RETRACE.SURFACE") == [
        f"RETRACE.SURFACE:{_REL}:unclassified:kernel",
        f"RETRACE.SURFACE:{_REL}:unclassified:step"]


def test_static_fixture_flags_nonliteral_and_fresh_wrapper(tmp_path):
    # DELIBERATE BREAKS: a computed static_argnames vocabulary, and a jit
    # wrapper rebuilt inside a plain (uncached) function
    findings = _retrace(tmp_path, """
        import jax

        NAMES = ("a", "b")

        def inner(y):
            return y

        def rebuild(x):
            return jax.jit(lambda y: y + 1)(x)

        def computed(x):
            return jax.jit(inner, static_argnames=NAMES)(x)
    """)
    ids = sorted(f.id for f in findings if f.check == "RETRACE.STATIC")
    assert ids == [
        f"RETRACE.STATIC:{_REL}:computed:inner:fresh",
        f"RETRACE.STATIC:{_REL}:inner:static_argnames:nonliteral",
        f"RETRACE.STATIC:{_REL}:rebuild:<lambda>:fresh",
    ]


def test_surface_fixture_flags_unclassified_jit_site(tmp_path):
    # DELIBERATE BREAK: a jit site tracing a function in neither
    # SERVING_PROGRAMS nor AUX_PROGRAMS — the source-level surface ratchet
    findings = _retrace(tmp_path, """
        import jax

        @jax.jit
        def brand_new_kernel(x):
            return x
    """)
    assert [f.id for f in findings if f.check == "RETRACE.SURFACE"] == [
        f"RETRACE.SURFACE:{_REL}:unclassified:brand_new_kernel"]
    # ...and the inline audit marker sanctions a classified-elsewhere site
    clean = _retrace(tmp_path / "ok", """
        import jax

        @jax.jit  # mct-ok: RETRACE.SURFACE
        def diagnostics_only(x):
            return x
    """)
    assert not [f for f in clean if f.check == "RETRACE.SURFACE"]


# ---------------------------------------------------------------------------
# the census ratchet vs the committed baseline
# ---------------------------------------------------------------------------


def test_census_matches_committed_baseline(fused_lattice_aot):
    """The committed compile_surface_baseline.json IS the current census
    — serving rows, rung surface, and the fused rows read from the SAME
    session-scoped AOT sweep the cost/IR tests use (no second lowering).
    """
    baseline = load_surface_baseline(
        os.path.join(REPO_ROOT, "compile_surface_baseline.json"))
    assert baseline is not None, "the surface baseline must stay committed"
    lows = {mesh: (row["stablehlo"], row["compiled_text"])
            for mesh, row in fused_lattice_aot.items()}
    assert check_surface(compile_surface(), baseline,
                         fused_surface_rows(lows)) == []
    # rung vocabulary: baseline and analyzer constant stay ONE vocabulary
    assert baseline["rungs"] == {k: sorted(v)
                                 for k, v in RUNG_SURFACE.items()}


def test_surface_ratchet_flags_growth_and_shrinkage():
    census = compile_surface()
    baseline = json.loads(json.dumps(census))
    removed = baseline["surface"].pop(0)
    baseline["surface"].append("fn=phantom bucket=<config>")
    ids = {f.id for f in check_surface(census, baseline)}
    assert f"RETRACE.SURFACE:serving:grew:{removed}" in ids
    assert ("RETRACE.SURFACE:serving:shrank:fn=phantom bucket=<config>"
            in ids)
    # a rung losing its enumerated variants is growth of the CHECKED set
    baseline2 = json.loads(json.dumps(census))
    baseline2["rungs"]["donation-off"] = []
    assert any(":rung:donation-off:grew:" in f.id
               for f in check_surface(census, baseline2))


def test_analyze_retrace_repo_clean(fused_lattice_aot):
    """The repo itself is clean — no baseline suppressions needed for the
    retrace family (defects found while building it were fixed, not
    baselined: the grid_dbscan_reference fresh-wrapper and the anonymous
    association partial)."""
    lows = {mesh: (row["stablehlo"], row["compiled_text"])
            for mesh, row in fused_lattice_aot.items()}
    findings = analyze_retrace(REPO_ROOT, lowerings=lows)
    assert [f.id for f in findings] == []


def test_cli_retrace_red_on_fixture_tree_green_on_repo(tmp_path):
    from maskclustering_tpu.analysis.__main__ import main

    # injected closure capture -> exit 2 (pure AST on a fixture tree: the
    # census marker is absent, so no lowering happens)
    pkg = tmp_path / "maskclustering_tpu" / "models"
    pkg.mkdir(parents=True)
    (pkg / "pipeline.py").write_text(textwrap.dedent("""
        import jax

        def build(tensors):
            def step(x):
                return x + tensors.n_real
            return jax.jit(step)
    """))
    assert main(["--families", "retrace", "--root", str(tmp_path)]) == 2


# ---------------------------------------------------------------------------
# the runtime sanitizer
# ---------------------------------------------------------------------------


@pytest.fixture
def sanitizer():
    from maskclustering_tpu.analysis import retrace_sanitizer as rs

    rs.reset()
    rs.install()
    yield rs
    rs.uninstall()
    rs.reset()


def test_sanitizer_env_and_arm_precedence(monkeypatch):
    from maskclustering_tpu.analysis import retrace_sanitizer as rs

    monkeypatch.delenv(rs.ENV_FLAG, raising=False)
    rs.arm(None)
    assert not rs.enabled()
    monkeypatch.setenv(rs.ENV_FLAG, "1")
    assert rs.enabled()
    rs.arm(False)  # explicit arm beats the environment
    try:
        assert not rs.enabled()
    finally:
        rs.arm(None)


def test_sanitizer_records_repeats_contexts_and_freeze(sanitizer):
    import jax
    import jax.numpy as jnp

    def make_step():
        # a FRESH function object per call — the rebuilt-closure pattern.
        # (jax dedupes `jax.jit(f)` wrappers of the SAME function object
        # through its C++ cache, so only a genuinely new trace retraces —
        # which is exactly what a per-call closure produces.)
        def retrace_probe(x):
            return x * 2 + 1

        return jax.jit(retrace_probe)

    make_step()(jnp.ones(3))
    assert any(fn == "retrace_probe"
               for fn, _, _ in sanitizer.snapshot_keys())
    assert sanitizer.violations() == []
    # rebuilding the closure = same (fn, signature) compiled again =
    # jit-cache thrash = repeat violation
    make_step()(jnp.ones(3))
    assert any(v["kind"] == "repeat" and v["fn"] == "retrace_probe"
               for v in sanitizer.violations())
    # a ladder-context switch makes the same rebuild a NEW key (the
    # donation-off rung's enumerated surface), not another repeat
    sanitizer.set_context("donation-off")
    make_step()(jnp.ones(3))
    repeats = [v for v in sanitizer.violations()
               if v["fn"] == "retrace_probe" and v["kind"] == "repeat"]
    assert len(repeats) == 1
    # frozen: a brand-new signature is a post-freeze violation
    sanitizer.set_context("baseline")
    sanitizer.freeze()
    make_step()(jnp.ones(5))
    assert any(v["kind"] == "post_freeze" for v in sanitizer.violations())
    d = sanitizer.digest()
    assert d["compiles"] >= 4 and d["by_fn"]["retrace_probe"] >= 4


def test_frozen_rung_drop_sanctions_only_enumerated_programs(sanitizer):
    """A FROZEN process that drops a ladder rung (the serving daemon's
    life story) may rebuild exactly the rung's baselined programs; any
    other post-freeze compile under that context stays a violation."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones(9)  # eager materialization compiles BEFORE the freeze
    sanitizer.freeze()
    sanitizer.set_context("sequential-executor+donation-off")

    def _mask_group_counts_impl(x):  # a RUNG_SURFACE["donation-off"] name
        return x + 1

    def some_other_kernel(x):
        return x - 1

    jax.jit(_mask_group_counts_impl)(x)
    assert sanitizer.violations() == []  # enumerated rung surface
    jax.jit(some_other_kernel)(x)
    assert [v["fn"] for v in sanitizer.violations()
            if v["kind"] == "post_freeze"] == ["some_other_kernel"]


def test_sanitizer_suppresses_compile_log_chatter(sanitizer, caplog):
    import logging

    import jax
    import jax.numpy as jnp

    with caplog.at_level(logging.DEBUG):
        jax.jit(lambda x: x - 3)(jnp.ones(7))
    assert not [r for r in caplog.records
                if r.getMessage().startswith("Compiling ")]


def test_sanitizer_counts_new_buckets_via_classifier(sanitizer):
    from maskclustering_tpu.utils.compile_cache import record_shape_bucket

    before = sanitizer.digest()["buckets_new"]
    assert record_shape_bucket("retrace-test", 1, 2, 3) is True
    assert record_shape_bucket("retrace-test", 1, 2, 3) is False  # repeat
    after = sanitizer.digest()["buckets_new"]
    assert after == before + 1


def test_serve_many_zero_postwarm_compiles_both_executors(tmp_path,
                                                          sanitizer):
    """ISSUE-9 acceptance: a mixed-bucket CPU run books ZERO post-warm
    compiles for repeated buckets — under the overlapped executor AND the
    sequential one. Scenes 2/3 are byte-identical re-materializations of
    scenes 0/1 (same seeds), so every shape bucket repeats.

    Tier-1 budget: bucket A reuses test_executor's exact scene shape and
    config (seed 40, 10 frames, 60x80, spacing 0.06, the scannet config
    at mask_pad_multiple 32), so in a full suite run its programs are
    process-warm; only bucket B's denser cloud compiles cold here."""
    from maskclustering_tpu.config import load_config
    from maskclustering_tpu.run import cluster_scenes
    from maskclustering_tpu.utils.compile_cache import scene_bucket
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    to_scene_tensors,
                                                    write_scannet_layout)

    root = str(tmp_path)
    # scene A == test_executor's scene0 byte-for-byte (shared warm shapes);
    # scene B's thinner 4-box cloud lands one n_pad bucket up; scene A2 is
    # A re-materialized under a new name (the repeated bucket)
    specs = [("scene0000_00", 3, 0.06, 40), ("scene0001_00", 4, 0.05, 50),
             ("scene0002_00", 3, 0.06, 40)]
    cfg = load_config("scannet").replace(
        data_root=root, step=1, distance_threshold=0.05,
        mask_pad_multiple=32)
    buckets = set()
    for name, boxes, spacing, seed in specs:
        sc = make_scene(num_boxes=boxes, num_frames=10,
                        image_hw=(60, 80), spacing=spacing, seed=seed)
        t = to_scene_tensors(sc)
        buckets.add(scene_bucket(cfg, t.num_frames, t.num_points,
                                 int(np.max(t.segmentations))))
        write_scannet_layout(sc, root, name)
    assert len(buckets) == 2, f"workload must be mixed-bucket: {buckets}"
    names = [s[0] for s in specs]

    warm = cluster_scenes(cfg, names[:2], resume=False)  # overlapped (default)
    assert [s.status for s in warm] == ["ok", "ok"]
    sanitizer.freeze()
    before = sanitizer.snapshot_keys()

    # overlapped executor, warm: the repeated-bucket scene plus a re-run
    # of B — every bucket repeats, so ZERO compiles may book
    over = cluster_scenes(cfg, [names[2], names[1]], resume=False)
    assert [s.status for s in over] == ["ok", "ok"]
    # sequential executor, warm: same contract on the serialized loop
    seq = cluster_scenes(cfg.replace(scene_overlap=False), [names[2]],
                         resume=False)
    assert [s.status for s in seq] == ["ok"]

    assert sanitizer.snapshot_keys() == before
    assert sanitizer.violations() == []


def test_donation_off_rung_adds_only_baselined_surface(sanitizer):
    """The ladder's donation-off rung may only compile its enumerated
    variants (compile_surface_baseline.json "rungs"). In-process jit
    caches may already hold some variants warm, so tier-1 pins the subset
    relation; the slow-marked cold-process test pins exact equality."""
    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    baseline = load_surface_baseline(
        os.path.join(REPO_ROOT, "compile_surface_baseline.json"))
    cfg = PipelineConfig(config_name="synthetic", dataset="demo",
                         backend="cpu", distance_threshold=0.03, step=1,
                         mask_pad_multiple=64, point_chunk=2048)

    def scene():
        return to_scene_tensors(make_scene(num_boxes=3, num_frames=6,
                                           seed=3, spacing=0.05))

    run_scene(scene(), cfg, k_max=15)  # warm at full config
    before = sanitizer.snapshot_keys()
    sanitizer.set_context("donation-off")
    run_scene(scene(), cfg.replace(donate_buffers=False), k_max=15)
    new_fns = {fn for fn, _, _ in sanitizer.snapshot_keys() - before}
    assert new_fns <= set(baseline["rungs"]["donation-off"])
    assert sanitizer.violations() == []  # new context, no repeats


@pytest.mark.slow
def test_donation_off_rung_exact_surface_cold_process():
    """Cold-process exactness: donation-off adds EXACTLY its baselined
    variants (in-process warmth can hide members of the set, so the exact
    pin runs in a subprocess with cold jit caches)."""
    script = textwrap.dedent("""
        import json, sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        from maskclustering_tpu.analysis import retrace_sanitizer as rs
        rs.install()
        from maskclustering_tpu.config import PipelineConfig
        from maskclustering_tpu.models.pipeline import run_scene
        from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

        cfg = PipelineConfig(config_name="synthetic", dataset="demo",
                             backend="cpu", distance_threshold=0.03, step=1,
                             mask_pad_multiple=64, point_chunk=2048)
        def scene():
            return to_scene_tensors(make_scene(num_boxes=3, num_frames=6,
                                               seed=3, spacing=0.05))
        run_scene(scene(), cfg, k_max=15)
        before = rs.snapshot_keys()
        rs.set_context("donation-off")
        run_scene(scene(), cfg.replace(donate_buffers=False), k_max=15)
        new_fns = sorted({fn for fn, _, _ in rs.snapshot_keys() - before})
        print(json.dumps(new_fns))
    """)
    proc = subprocess.run([sys.executable, "-c", script], cwd=REPO_ROOT,
                          capture_output=True, text=True, timeout=420,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    new_fns = json.loads(proc.stdout.strip().splitlines()[-1])
    baseline = load_surface_baseline(
        os.path.join(REPO_ROOT, "compile_surface_baseline.json"))
    assert new_fns == baseline["rungs"]["donation-off"]


# ---------------------------------------------------------------------------
# report + ledger integration
# ---------------------------------------------------------------------------


def test_render_retrace_line_and_violations():
    from maskclustering_tpu.obs.report import render_retrace

    assert render_retrace({}) is None
    line = render_retrace({"retrace.compiles": 5.0,
                           "retrace.distinct_programs": 4.0,
                           "retrace.buckets_new": 2.0})
    assert "5 compile(s)" in line and "2 new bucket(s)" in line
    assert "VIOLATIONS" not in line
    line2 = render_retrace({"retrace.compiles": 5.0,
                            "retrace.repeat_compiles": 1.0})
    assert "VIOLATIONS: 1 repeat" in line2


def test_run_row_stamps_retrace_counters():
    from maskclustering_tpu.obs.ledger import run_row

    report = {"scenes": [{"status": "ok", "seconds": 2.0}],
              "obs": {"counters": {"retrace.compiles": 7.0,
                                   "compile_cache.bucket_new": 3.0}}}
    row = run_row(report)
    assert row["retrace_compiles"] == 7
    assert row["buckets_new"] == 3
    # a fully-warm armed run's ZERO is stamped too — it is the baseline
    # row the 0 -> N compile-regression attribution anchors on
    warm = run_row({"scenes": [{"status": "ok", "seconds": 1.0}],
                    "obs": {"counters": {"retrace.compiles": 0.0}}})
    assert warm["retrace_compiles"] == 0


def test_regress_attributes_retrace_deltas():
    from maskclustering_tpu.obs.ledger import check_regression

    base = {"value": 1.0, "retrace_compiles": 18}
    cur = {"value": 1.0, "retrace_compiles": 30, "retrace_repeats": 2}
    ok, lines = check_regression(cur, base)
    joined = "\n".join(lines)
    assert ok  # advisory only: the headline did not regress
    assert "retrace: sanitizer recorded 18 -> 30" in joined
    assert "surface growth or a cold process" in joined
    assert "retrace VIOLATION" in joined and "2 repeat compile(s)" in joined
    # with a knob flip on record, the advisory attributes the flip first
    cur2 = {"value": 1.0, "retrace_compiles": 30, "count_dtype": "int8"}
    _, lines2 = check_regression(cur2, base)
    assert "flipped knob" in "\n".join(lines2)
