"""Exact-parity association tests: Pallas/jnp ball query agreement, the
reference's denoise + outlier-removal semantics, and end-to-end parity of
the exact path against the dense projective path."""

import numpy as np
import jax.numpy as jnp
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.exact_backprojection import (
    associate_scene_exact,
    denoise_mask_points,
    frame_backprojection_exact,
    statistical_outlier_mask,
)
from maskclustering_tpu.ops.neighbor import ball_query_brute
from maskclustering_tpu.ops.pallas.ball_query import ball_query_pallas


class TestPallasBallQuery:
    """Interpret mode on the CPU test backend; the real Mosaic lowering is
    exercised by the TPU drive (same kernel body)."""

    def test_matches_oracle_ragged(self):
        rng = np.random.default_rng(1)
        b, p, s, k = 4, 70, 260, 6
        q = rng.uniform(-1, 1, (b, p, 3)).astype(np.float32)
        c = rng.uniform(-1, 1, (b, s, 3)).astype(np.float32)
        ql = np.array([70, 33, 0, 64], np.int32)
        cl = np.array([260, 100, 50, 1], np.int32)
        out = np.asarray(ball_query_pallas(
            jnp.asarray(q), jnp.asarray(c), jnp.asarray(ql), jnp.asarray(cl),
            k=k, radius=0.4, interpret=True))
        ref = ball_query_brute(q, c, ql, cl, k, 0.4)
        np.testing.assert_array_equal(out, ref)

    def test_first_k_scan_order_not_nearest(self):
        # candidate 0 is farther than candidate 1 but still within radius:
        # pytorch3d keeps FIRST K by index, so slot 0 must be candidate 0
        q = np.zeros((1, 1, 3), np.float32)
        c = np.array([[[0.3, 0, 0], [0.1, 0, 0], [0.2, 0, 0]]], np.float32)
        out = np.asarray(ball_query_pallas(
            jnp.asarray(q), jnp.asarray(c), jnp.asarray([1], dtype=jnp.int32),
            jnp.asarray([3], dtype=jnp.int32), k=2, radius=0.5, interpret=True))
        np.testing.assert_array_equal(out[0, 0], [0, 1])

    def test_batch_chunking(self):
        # b > batch_chunk exercises the lax.map grouping
        rng = np.random.default_rng(2)
        b, p, s = 10, 16, 40
        q = rng.uniform(-1, 1, (b, p, 3)).astype(np.float32)
        c = rng.uniform(-1, 1, (b, s, 3)).astype(np.float32)
        ql = np.full(b, p, np.int32)
        cl = np.full(b, s, np.int32)
        out = np.asarray(ball_query_pallas(
            jnp.asarray(q), jnp.asarray(c), jnp.asarray(ql), jnp.asarray(cl),
            k=4, radius=0.5, batch_chunk=4, interpret=True))
        ref = ball_query_brute(q, c, ql, cl, 4, 0.5)
        np.testing.assert_array_equal(out, ref)


class TestDenoise:
    def test_statistical_outlier(self):
        rng = np.random.default_rng(0)
        cluster = rng.normal(scale=0.01, size=(100, 3))
        outlier = np.array([[5.0, 5.0, 5.0]])
        pts = np.concatenate([cluster, outlier])
        keep = statistical_outlier_mask(pts, nb_neighbors=20, std_ratio=2.0)
        assert not keep[-1]
        assert keep[:-1].mean() > 0.9

    def test_small_component_dropped(self):
        # 100-point main blob + 10-point far blob (10% < 20% cutoff,
        # reference utils/geometry.py:14-16)
        rng = np.random.default_rng(1)
        main = rng.normal(scale=0.01, size=(100, 3))
        minor = rng.normal(scale=0.01, size=(10, 3)) + 10.0
        kept = denoise_mask_points(np.concatenate([main, minor]))
        assert np.all(kept < 100)
        assert len(kept) > 80


def _plane_scene(n_side=40, z=2.0):
    """A flat square of scene points seen head-on by an identity camera.

    Depth carries +-2mm deterministic jitter: a perfectly flat plane makes
    the reference's STRICT bbox crop (scene > min & < max,
    mask_backprojection.py:59-67) degenerate in z — faithful behavior, so
    the fixture avoids it the way real sensor noise does.
    """
    xs = np.linspace(-0.5, 0.5, n_side)
    gx, gy = np.meshgrid(xs, xs)
    pts = np.stack([gx.ravel(), gy.ravel(), np.full(n_side * n_side, z)], axis=1)
    h = w = 64
    intr = np.array([[60.0, 0, 32], [0, 60.0, 32], [0, 0, 1]])
    jitter = 0.002 * np.sin(np.arange(h * w)).reshape(h, w).astype(np.float32)
    depth = np.full((h, w), z, dtype=np.float32) + jitter
    # the plane spans pixels ~17..47 (x = (u-32)/60*z in [-0.5, 0.5]);
    # both masks must sit inside it or the coverage filter rejects them
    seg = np.zeros((h, w), dtype=np.int32)
    seg[18:31, 18:31] = 1
    seg[34:46, 34:46] = 2
    return pts, depth, seg, intr


class TestFrameExact:
    def test_two_masks_claim_disjoint_regions(self):
        pts, depth, seg, intr = _plane_scene()
        info = frame_backprojection_exact(
            pts, depth, seg, intr, np.eye(4),
            distance_threshold=0.05, few_points_threshold=10)
        assert set(info) == {1, 2}
        assert len(np.intersect1d(info[1], info[2])) == 0
        # mask 1 covers the upper-left of the plane -> points with x,y < 0
        sel = pts[info[1]]
        assert sel[:, 0].max() < 0.1 and sel[:, 1].max() < 0.1

    def test_invalid_extrinsics_skip(self):
        pts, depth, seg, intr = _plane_scene()
        bad = np.full((4, 4), np.inf)
        assert frame_backprojection_exact(pts, depth, seg, intr, bad) == {}

    def test_absent_object_rejected_by_coverage(self):
        # mask 2's pixels see depth at z=1 where NO scene points exist
        pts, depth, seg, intr = _plane_scene()
        depth = depth.copy()
        depth[34:46, 34:46] = 1.0
        info = frame_backprojection_exact(
            pts, depth, seg, intr, np.eye(4),
            distance_threshold=0.05, few_points_threshold=10)
        assert 1 in info and 2 not in info


class TestExactPipelineParity:
    def test_matches_dense_path_end_to_end(self):
        from maskclustering_tpu.models.pipeline import run_scene
        from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

        scene = make_scene(num_boxes=3, num_frames=10, image_hw=(60, 80), seed=4)
        tensors = to_scene_tensors(scene)
        base = PipelineConfig(config_name="parity", dataset="demo",
                              distance_threshold=0.03, few_points_threshold=10,
                              mask_pad_multiple=64)
        dense = run_scene(tensors, base, k_max=31, export=False)
        exact = run_scene(tensors, base.replace(use_exact_ball_query=True),
                          k_max=31, export=False)
        assert len(dense.objects.point_ids_list) == 3
        # The exact path may fragment a sparse box (DBSCAN split eps 0.1 on
        # the sparser ball-query claims), so parity is judged on purity and
        # coverage, not object count: every exact object belongs to one GT
        # instance, and every GT instance is recovered.
        gt = scene.gt_instance
        assert 3 <= len(exact.objects.point_ids_list) <= 5
        covered = set()
        for pids in exact.objects.point_ids_list:
            vals, counts = np.unique(gt[pids], return_counts=True)
            top = vals[np.argmax(counts)]
            assert top != 0, "an exact-path object is mostly background"
            assert counts.max() / counts.sum() > 0.9, "impure exact object"
            covered.add(int(top))
        assert covered == {1, 2, 3}

    def test_association_tensor_shapes(self):
        from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

        scene = make_scene(num_boxes=2, num_frames=4, image_hw=(48, 64), seed=6)
        tensors = to_scene_tensors(scene)
        cfg = PipelineConfig(config_name="p", dataset="demo",
                             distance_threshold=0.03, few_points_threshold=10)
        assoc = associate_scene_exact(tensors, cfg, k_max=31)
        f = len(tensors.frame_ids)
        n = len(tensors.scene_points)
        assert assoc.mask_of_point.shape == (f, n)
        assert assoc.mask_valid.shape == (f, 32)
        # boundary points are zeroed in the id matrix
        mop = np.asarray(assoc.mask_of_point)
        first = np.asarray(assoc.first_id)
        last = np.asarray(assoc.last_id)
        shared = (first != last) & (last > 0)
        assert not np.any(mop[shared])


def test_ball_query_pallas_non_interpret_on_tpu():
    """Mosaic-lowered kernel vs the jnp path on a live chip (VERDICT r3
    task 6); every other test runs interpret=True on CPU.

    Runs in a SUBPROCESS with a fresh jax: conftest.py pins this process to
    the CPU platform before any test imports, so an in-process backend
    check would skip forever even on a TPU VM. The child sees the machine's
    real default backend and reports tpu-absence via exit code 42.
    """
    import subprocess
    import sys

    child = r"""
import sys
import numpy as np
import jax
if jax.default_backend() != "tpu":
    sys.exit(42)
import jax.numpy as jnp
from maskclustering_tpu.ops.neighbor import ball_query
from maskclustering_tpu.ops.pallas.ball_query import ball_query_pallas
rng = np.random.default_rng(0)
q = jnp.asarray(rng.random((2, 200, 3)), jnp.float32)
c = jnp.asarray(rng.random((2, 500, 3)), jnp.float32)
ql = jnp.asarray([200, 150], jnp.int32)
cl = jnp.asarray([500, 333], jnp.int32)
got = np.asarray(ball_query_pallas(q, c, ql, cl, k=8, radius=0.1, interpret=False))
want = np.asarray(ball_query(q, c, ql, cl, k=8, radius=0.1))
np.testing.assert_array_equal(got, want)
"""
    import os

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # cheap probe first: a WEDGED chip hangs inside backend init with no
    # exception, and this skip used to cost the full 300 s kernel budget —
    # a third of the tier-1 wall — every time the chip was down. A healthy
    # backend inits in seconds (init_backend watchdog experience), so the
    # 15 s default cleanly separates "no usable TPU" from "kernel still
    # running" while a chipless tier-1 run burns half what the 30 s probe
    # did (ISSUE-9 wall reclaim; MCT_TPU_PROBE_S raises it for a slow but
    # healthy rig — the probe skips, never fails, so a too-short budget
    # costs coverage on-chip, not correctness)
    probe_s = float(os.environ.get("MCT_TPU_PROBE_S", "15"))
    probe = ("import sys, jax; "
             "sys.exit(42 if jax.default_backend() != 'tpu' else 0)")
    try:
        p = subprocess.run([sys.executable, "-c", probe], env=env,
                           capture_output=True, text=True, timeout=probe_s)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU backend init timed out (chip busy or held elsewhere)")
    if p.returncode == 42:
        pytest.skip("non-interpret Pallas needs a real TPU (Mosaic lowering)")
    try:
        proc = subprocess.run([sys.executable, "-c", child], env=env,
                              capture_output=True, text=True, timeout=300)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU backend init timed out (chip busy or held elsewhere)")
    if proc.returncode == 42:  # chip grabbed between the probe and the run
        pytest.skip("non-interpret Pallas needs a real TPU (Mosaic lowering)")
    assert proc.returncode == 0, proc.stderr[-2000:]
