"""Continuous scene batching: the packing scheduler's tier-1 matrix.

Unit coverage for the admission queue's same-bucket hunt
(``next_batch``), the worker's solo/batch routing gates, the warm-pad
demux (pad lanes excluded from results and accounting), single-member
fault isolation inside a fused batch, and packed-vs-sequential artifact
identity at the worker level. The end-to-end gate — two real daemons,
exported artifact CRCs, zero post-warm compiles under a frozen retrace
sanitizer, occupancy > 1 — lives in ``scripts/load_gen.py --pack-drill``
(ci.sh exit code 11); the heavier supervisor plumbing is pinned in
tests/test_serve_supervisor.py.
"""

import json
import math
import time

import numpy as np
import pytest

from maskclustering_tpu.config import load_config
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.utils import faults

# the shared tiny fused-batch fixture shapes (test_parallel.py sizes —
# NOT fresh full-depth scenes; tier-1 wall budget)
SPEC_P = {"num_boxes": 3, "num_frames": 8, "image_hw": (32, 48),
          "spacing": 0.08, "seed": 60}
SPEC_Q = {"num_boxes": 3, "num_frames": 8, "image_hw": (32, 48),
          "spacing": 0.08, "seed": 61}


def _cfg(data_root, **kw):
    base = dict(data_root=str(data_root), config_name="batched", step=1,
                distance_threshold=0.05, mask_pad_multiple=32,
                frame_pad_multiple=8)
    base.update(kw)
    return load_config("scannet").replace(**base)


def _req(scene, i, *, synthetic=None, deadline_s=0.0, **kw):
    doc = {"op": "scene", "scene": scene}
    if synthetic is not None:
        doc["synthetic"] = {k: list(v) if isinstance(v, tuple) else v
                            for k, v in synthetic.items()}
    if deadline_s:
        doc["deadline_s"] = deadline_s
    doc.update(kw)
    return protocol.build_request(protocol.parse_line(json.dumps(doc)),
                                  f"r-{i:06d}")


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.set_plan(None)
    faults.clear_stop()
    yield
    faults.set_plan(None)
    faults.clear_stop()


# ---------------------------------------------------------------------------
# units: AdmissionQueue.next_batch (pure scheduling, no jax)
# ---------------------------------------------------------------------------


def test_next_batch_groups_same_bucket_and_requeues_skipped():
    q = AdmissionQueue(8, metered=False)
    key = {"a": ("A",), "b": ("B",)}
    for i, s in enumerate(["a", "b", "a", "a", "b"]):
        q.submit(_req(s, i))
    batch = q.next_batch(lambda r: key[r.scene], max_n=3, linger_s=0.0,
                         timeout_s=0.1)
    # head's bucket wins; same-bucket company joins up to max_n, in order
    assert [r.id for r in batch] == ["r-000000", "r-000002", "r-000003"]
    # skipped B requests kept THEIR arrival order, ahead of the queue
    batch2 = q.next_batch(lambda r: key[r.scene], max_n=3, linger_s=0.0,
                          timeout_s=0.1)
    assert [r.id for r in batch2] == ["r-000001", "r-000004"]
    assert q.next_batch(lambda r: key[r.scene], max_n=3, linger_s=0.0,
                        timeout_s=0.05) is None
    assert q.depth() == 0


def test_next_batch_respects_max_n_and_stash_survives_drain():
    q = AdmissionQueue(8, metered=False)
    for i in range(5):
        q.submit(_req("a" if i != 1 else "b", i))
    batch = q.next_batch(lambda r: (r.scene,), max_n=2, linger_s=0.0,
                         timeout_s=0.1)
    assert [r.id for r in batch] == ["r-000000", "r-000002"]
    # the skipped "b" head plus the unclaimed "a" tail are all still owed:
    # drain (the shutdown path) must surface stash + queue, in order
    assert [r.id for r in q.drain()] == ["r-000001", "r-000003", "r-000004"]


def test_next_batch_unbatchable_key_dispatches_solo_immediately():
    q = AdmissionQueue(4, metered=False)
    q.submit(_req("solo", 0))
    q.submit(_req("solo", 1))
    t0 = time.monotonic()
    batch = q.next_batch(lambda r: None, max_n=4, linger_s=5.0,
                         timeout_s=0.1)
    # key None (stream / resume / unknown bucket) must NOT linger
    assert [r.id for r in batch] == ["r-000000"]
    assert time.monotonic() - t0 < 1.0
    # max_n <= 1 (batching off) is the plain pop, also linger-free
    batch = q.next_batch(lambda r: (r.scene,), max_n=1, linger_s=5.0,
                         timeout_s=0.1)
    assert [r.id for r in batch] == ["r-000001"]


def test_next_batch_linger_clipped_by_member_deadline():
    q = AdmissionQueue(4, metered=False)
    q.submit(_req("a", 0, deadline_s=0.2))
    t0 = time.monotonic()
    batch = q.next_batch(lambda r: ("A",), max_n=4, linger_s=30.0,
                         timeout_s=0.1)
    waited = time.monotonic() - t0
    assert [r.id for r in batch] == ["r-000000"]
    # the window is linger clipped to HALF the member's remaining budget
    # (0.1s here), never the raw 30s linger: a lone request must not burn
    # its latency budget waiting for company
    assert waited < 2.0, waited


def test_next_batch_lingers_for_late_same_bucket_company():
    import threading

    q = AdmissionQueue(4, metered=False)
    q.submit(_req("a", 0))

    def late_submit():
        time.sleep(0.15)
        q.submit(_req("a", 1))

    t = threading.Thread(target=late_submit)
    t.start()
    batch = q.next_batch(lambda r: ("A",), max_n=4, linger_s=2.0,
                         timeout_s=0.1)
    t.join()
    # the linger window existed to catch exactly this arrival
    assert [r.id for r in batch] == ["r-000000", "r-000001"]


# ---------------------------------------------------------------------------
# units: the worker's batch gates (no dispatch)
# ---------------------------------------------------------------------------


def _make_worker(tmp_path, **cfg_kw):
    from maskclustering_tpu.serve.worker import ServeWorker

    cfg = _cfg(tmp_path, **cfg_kw)
    queue = AdmissionQueue(8, metered=False)
    router = Router(cfg)
    return ServeWorker(cfg, queue, router), cfg, queue, router


def test_worker_batch_key_gates_streams_resume_crashes_and_faults(tmp_path):
    worker, _cfg_, _q, router = _make_worker(tmp_path, serve_batch_max=3)
    bucket = (7, 8, 4096)
    router.remember("known", bucket)
    assert worker._batch_key(_req("known", 0)) == bucket
    # unknown bucket -> solo (classification happens on the sequential path)
    assert worker._batch_key(_req("novel", 1)) is None
    # resume requests skip execution entirely -> never packed
    assert worker._batch_key(_req("known", 2, resume=True)) is None
    # crash-requeued requests rerun their own degradation ladder -> solo
    crashed = _req("known", 3)
    crashed.crashes = 1
    assert worker._batch_key(crashed) is None
    # a scene with a pending FaultPlan entry must stay solo so the drill
    # lands on the sequential path's retry ladder, not on batchmates —
    # including unlimited entries (remaining=None)
    faults.set_plan(faults.FaultPlan.from_spec("flaky:known:1"))
    assert worker._batch_key(_req("known", 4)) is None
    faults.set_plan(faults.FaultPlan.from_spec("load:known"))
    assert worker._batch_key(_req("known", 5)) is None
    faults.set_plan(faults.FaultPlan.from_spec("flaky:other:1"))
    assert worker._batch_key(_req("known", 6)) == bucket


# ---------------------------------------------------------------------------
# fused dispatch: warm-pad demux + fault isolation + byte identity
# (one module-scoped worker; tiny 32x48 scenes — the shared cheap shapes)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def benv(tmp_path_factory):
    from maskclustering_tpu.run import init_backend_or_die
    from maskclustering_tpu.serve.worker import ServeWorker

    init_backend_or_die(120.0, platform="cpu")
    tmp = tmp_path_factory.mktemp("serve_batch")
    cfg = _cfg(tmp, serve_batch_max=3, serve_batch_linger_s=0.02)
    queue = AdmissionQueue(8, metered=False)
    router = Router(cfg)
    worker = ServeWorker(cfg, queue, router)
    return worker, cfg, router


def _run_capture(fn, *reqs):
    """Bind capture sinks to the requests, run, return events per request."""
    sinks = []
    for r in reqs:
        events = []
        r.send = events.append
        sinks.append(events)
    fn(list(reqs))
    return sinks


def _terminal(events):
    out = [e for e in events if e.get("kind") == "result"]
    assert len(out) == 1, events
    return out[0]


def test_packed_batch_byte_identical_to_sequential_with_warm_pad(benv):
    worker, cfg, router = benv
    # sequential reference first: classifies + remembers both buckets and
    # yields the per-scene artifact digests the packed run must reproduce
    seq = {}
    for i, (scene, spec) in enumerate([("bt-p", SPEC_P), ("bt-q", SPEC_Q)]):
        req = _req(scene, 10 + i, synthetic=spec)
        events = _run_capture(lambda b: worker._serve_one(b[0]), req)[0]
        term = _terminal(events)
        assert term["status"] == "ok", term
        assert "batch" not in term  # sequential results carry no width
        seq[scene] = term
    bucket = router.bucket_for("bt-p")
    assert bucket is not None and bucket == router.bucket_for("bt-q")

    # packed: 2 members, serve_batch_max=3 -> one width-3 dispatch with a
    # warm pad lane; per-lane demux must hand each member its own ok +
    # digest, byte-identical to its sequential run
    reqs = [_req("bt-p", 20, synthetic=SPEC_P),
            _req("bt-q", 21, synthetic=SPEC_Q)]
    sinks = _run_capture(worker._serve_batch, *reqs)
    stats = worker.batch_stats()
    # one width-2-occupancy dispatch (hist keys are JSON-friendly strings)
    assert stats["hist"].get("2") == 1, stats
    for req, events in zip(reqs, sinks):
        term = _terminal(events)
        assert term["status"] == "ok", term
        assert term["batch"] == 2
        # the artifact fingerprint is the cross-path identity claim (the
        # fused path materializes no DeviceHandoff, so `plane` is
        # sequential-only by design)
        assert term["digest"]["artifact"] == \
            seq[req.scene]["digest"]["artifact"]
        assert seq[req.scene]["digest"]["artifact"]
        # the census coordinate survives the fused path, stamped with the
        # fused bucket label and the full 5-field grammar
        coord = term["digest_coord"]
        assert coord.startswith("fused|") and len(coord.split("|")) == 5
    # the pad lane came from the router's retained warm tensors path
    assert router.pad_tensors_for(bucket) is not None


def test_single_member_export_fault_isolated_to_its_lane(benv):
    worker, cfg, router = benv
    before = dict(worker.batch_stats())
    # the fault fires at the EXPORT seam inside the demux loop — after the
    # fused dispatch succeeded — so exactly one lane may fail
    faults.set_plan(faults.FaultPlan.from_spec("fail:bt-p.export:1"))
    reqs = [_req("bt-p", 30, synthetic=SPEC_P),
            _req("bt-q", 31, synthetic=SPEC_Q)]
    sinks = _run_capture(worker._serve_batch, *reqs)
    term_p, term_q = _terminal(sinks[0]), _terminal(sinks[1])
    assert term_p["status"] == "failed" and term_p["batch"] == 2
    assert term_q["status"] == "ok" and term_q["batch"] == 2
    after = worker.batch_stats()
    # the dispatch itself succeeded: one more fused dispatch, no fallback
    assert after["dispatches"] == before["dispatches"] + 1


def test_batch_dispatch_failure_falls_back_to_sequential(benv, monkeypatch):
    import maskclustering_tpu.parallel.batch as pb

    worker, cfg, router = benv

    def boom(*a, **kw):
        raise RuntimeError("scripted dispatch failure")

    monkeypatch.setattr(pb, "cluster_scene_batch", boom)
    before = dict(worker.batch_stats())
    reqs = [_req("bt-p", 40, synthetic=SPEC_P),
            _req("bt-q", 41, synthetic=SPEC_Q)]
    sinks = _run_capture(worker._serve_batch, *reqs)
    for events in sinks:
        term = _terminal(events)
        # every member still answers ok — via its own sequential ladder
        assert term["status"] == "ok", term
        assert "batch" not in term
    after = worker.batch_stats()
    assert after["dispatches"] == before["dispatches"]


def test_warm_batch_executable_noop_when_batching_off(tmp_path):
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    to_scene_tensors)

    worker, cfg, _q, router = _make_worker(tmp_path)  # serve_batch_max=1
    tensors = to_scene_tensors(make_scene(**SPEC_P))
    worker.warm_batch_executable("w", tensors)
    assert worker.batch_stats() is None
    assert router.pad_tensors_for(router.classify_tensors(tensors)) is None


def test_cluster_scene_batch_pad_lanes_never_returned():
    """parallel/batch contract the scheduler leans on: width pins the
    dispatch shape, pad_tensors fill the extra lanes, and exactly
    len(tensors_list) results come back."""
    import jax

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.parallel.batch import cluster_scene_batch
    from maskclustering_tpu.parallel.mesh import make_mesh
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    to_scene_tensors)

    cfg = PipelineConfig(
        config_name="padtest", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=1024, frame_pad_multiple=8,
        mask_pad_multiple=8)
    tensors = [to_scene_tensors(make_scene(
        num_boxes=3, num_frames=8, image_hw=(32, 48), spacing=0.08, seed=s))
        for s in (60, 61)]
    pad = to_scene_tensors(make_scene(
        num_boxes=3, num_frames=8, image_hw=(32, 48), spacing=0.08, seed=99))
    mesh = make_mesh((1, 1), devices=jax.devices()[:1])
    objs = cluster_scene_batch(cfg, mesh, tensors, k_max=7, width=3,
                               pad_tensors=pad)
    assert len(objs) == 2  # the pad lane's output is discarded, not demuxed
    for t, om in zip(tensors, objs):
        ref = run_scene(t, cfg, k_max=7).objects
        assert len(om.point_ids_list) == len(ref.point_ids_list)
        for a, b in zip(om.point_ids_list, ref.point_ids_list):
            np.testing.assert_array_equal(a, b)
        assert om.mask_list == ref.mask_list
