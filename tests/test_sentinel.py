"""mct-sentinel acceptance: invariant digests, goldens, and the drift plane.

Pins the correctness-observability contract (obs/digest.py + obs/canary.py
+ the retrace goldens ratchet + the SLO correctness objective):

- the scene digest is DETERMINISTIC: repeat runs are byte-identical, and
  every coordinate that claims identity (count_dtype encodings,
  degradation-ladder rungs, overlapped vs sequential executor) produces
  digests that match byte-for-byte — the runtime form of the repo's
  exact-integer view-consensus invariant;
- a scripted ``corrupt`` fault flips ONLY the plane digest (the artifact
  was computed before the bit-flip) and never raises — the retry ladder
  stays blind by design, the sentinel is the only thing that can see it;
- goldens round-trip through write/load, and any version skew (file
  format OR digest schema) invalidates the whole file to None rather
  than turning every probe into a false drift;
- the committed canary_goldens.json covers EXACTLY the canonical
  workload's digest coordinates, and retrace.check_goldens flags growth,
  shrinkage, version skew and unreadability as mct-check findings;
- one CanarySentinel drift trips the whole chain: typed ``canary.drift``
  event on the armed sink, FlightRecorder postmortem naming the
  coordinate, and the zero-tolerance ``correctness`` SLO objective pages
  on a single occurrence in the long window (``obs.slo --check`` exits 2
  — the ci.sh canary-drill gate shape), while a lone post-warm compile
  still does not.

Scene runs use the TINY shape bucket (2 boxes, 6 frames, 40x56,
point_chunk 2048, frame_pad 4 — test_faults.py's bucket) so warm device
phases are ~2 s of dispatch overhead on CPU; the full goldens
regeneration (census-bucket scenes, ~40 s) is slow-marked.
"""

import glob
import json
import os

import numpy as np
import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.config import load_config
from maskclustering_tpu.obs import canary
from maskclustering_tpu.obs import digest as digest_mod
from maskclustering_tpu.obs import flight, slo
from maskclustering_tpu.utils import faults
from maskclustering_tpu.utils.synthetic import (make_scene, to_scene_tensors,
                                                write_scannet_layout)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COORD = "k63:f32:n16384|bf16|single|r0|c0"


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


def _cfg(**kw):
    return load_config("scannet").replace(
        step=1, distance_threshold=0.05, mask_pad_multiple=32,
        frame_pad_multiple=4, point_chunk=2048, **kw)


def _golden_doc(goldens):
    return {"version": canary.GOLDENS_VERSION,
            "digest_version": digest_mod.DIGEST_VERSION,
            "config": {}, "goldens": goldens}


# ---------------------------------------------------------------------------
# unit: digest schema, coordinates, comparison
# ---------------------------------------------------------------------------


def test_digest_coord_and_comparison_units():
    d = {"v": 1, "bucket": "k63:f32:n16384", "count_dtype": "bf16",
         "plane": "57810067", "artifact": "0ae5783a", "nan_inf": 0}
    assert digest_mod.digest_coord(d) == COORD
    assert digest_mod.digest_coord(d, mesh="m4x2", rung=2, chunk=3) \
        == "k63:f32:n16384|bf16|m4x2|r2|c3"
    assert digest_mod.digest_coord(None) == ""
    assert digest_mod.digests_match(d, dict(d))
    # count_dtype/bucket are coordinate axes, not digest content — two
    # coordinates that claim identity must still MATCH
    other = dict(d, count_dtype="int8", bucket="fused")
    assert digest_mod.digests_match(d, other)
    assert digest_mod.diff_digests(d, dict(d, plane="deadbeef")) == ["plane"]
    assert digest_mod.diff_digests(d, dict(d, v=2, nan_inf=4)) \
        == ["v", "nan_inf"]
    assert digest_mod.diff_digests(d, None) == ["missing"]
    assert not digest_mod.digests_match(d, None)


def test_artifact_only_digest_shape():
    class _Obj:
        point_ids_list = [np.array([1, 2, 3], np.int64)]
        mask_list = [[("f0", 4, 0.5)]]
        num_points = 3

    d = digest_mod.artifact_only_digest(_Obj(), bucket="fused",
                                        count_dtype="bf16")
    assert d["plane"] == "" and d["bucket"] == "fused"
    assert len(d["artifact"]) == 8 and int(d["artifact"], 16) >= 0
    # artifact-only digests still participate in comparison: a second
    # computation over the same objects is byte-equal
    assert digest_mod.digests_match(
        d, digest_mod.artifact_only_digest(_Obj(), bucket="fused",
                                           count_dtype="int8"))


# ---------------------------------------------------------------------------
# unit: goldens file round-trip + version invalidation
# ---------------------------------------------------------------------------


def test_goldens_roundtrip_and_version_invalidation(tmp_path):
    path = str(tmp_path / "goldens.json")
    assert canary.load_goldens(path) is None  # absent -> no goldens
    row = {"v": 1, "bucket": "k63:f32:n16384", "count_dtype": "bf16",
           "plane": "57810067", "artifact": "0ae5783a", "nan_inf": 0,
           "scene": "A"}
    doc = canary.write_goldens(path, {COORD: row}, config={"backend": "cpu"})
    assert doc["version"] == canary.GOLDENS_VERSION
    loaded = canary.load_goldens(path)
    assert loaded is not None and loaded["goldens"][COORD] == row
    assert loaded["config"] == {"backend": "cpu"}

    # any version skew invalidates the WHOLE file — stale goldens must
    # read as "no goldens", never as a wall of false drift
    for skew in ({"version": 99}, {"digest_version": 99}):
        bad = dict(loaded, **skew)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(bad, f)
        assert canary.load_goldens(path) is None
    with open(path, "w", encoding="utf-8") as f:
        f.write("not json{")
    assert canary.load_goldens(path) is None


def test_probes_to_goldens_filters_malformed():
    good = {"coord": COORD, "scene": "A",
            "digest": {"v": 1, "plane": "aa", "artifact": "bb", "nan_inf": 0}}
    out = canary.probes_to_goldens(
        [good, {"coord": "", "digest": {}}, {"scene": "x"}, {}])
    assert set(out) == {COORD}
    assert out[COORD]["scene"] == "A" and out[COORD]["plane"] == "aa"


def test_compare_probe_verdicts():
    golden = {"v": 1, "plane": "aa", "artifact": "bb", "nan_inf": 0}
    doc = _golden_doc({COORD: golden})
    ok = canary.compare_probe(
        {"coord": COORD, "scene": "A", "digest": dict(golden)}, doc)
    assert ok["status"] == "ok" and ok["fields"] == []
    drift = canary.compare_probe(
        {"coord": COORD, "scene": "A",
         "digest": dict(golden, plane="dead")}, doc)
    assert drift["status"] == "drift" and drift["fields"] == ["plane"]
    assert drift["golden"] == golden
    unc = canary.compare_probe(
        {"coord": "k1:f1:n1|bf16|single|r0|c0", "digest": dict(golden)}, doc)
    assert unc["status"] == "uncovered"


# ---------------------------------------------------------------------------
# the committed goldens + the mct-check ratchet
# ---------------------------------------------------------------------------


def test_committed_goldens_cover_canonical_workload():
    """The file in the repo root is current-version and covers EXACTLY the
    coordinates the ratchet derives from the canonical workload."""
    from maskclustering_tpu.analysis.retrace import expected_goldens_coords

    doc = canary.load_goldens(os.path.join(REPO_ROOT,
                                           canary.DEFAULT_GOLDENS_PATH))
    assert doc is not None, "committed canary_goldens.json must load clean"
    assert set(doc["goldens"]) == expected_goldens_coords()
    for coord, row in doc["goldens"].items():
        assert row["v"] == digest_mod.DIGEST_VERSION
        assert len(row["plane"]) == 8 and len(row["artifact"]) == 8
        assert coord.startswith(row["bucket"] + "|" + row["count_dtype"])


def test_check_goldens_ratchets_growth_and_shrinkage(tmp_path):
    from maskclustering_tpu.analysis.retrace import (check_goldens,
                                                     expected_goldens_coords)

    root = str(tmp_path)
    path = os.path.join(root, canary.DEFAULT_GOLDENS_PATH)
    ids = lambda fs: [f.id for f in fs]  # noqa: E731

    assert ids(check_goldens(root)) == ["RETRACE.GOLDENS:missing"]
    with open(path, "w", encoding="utf-8") as f:
        f.write("{broken")
    assert ids(check_goldens(root)) == ["RETRACE.GOLDENS:unreadable"]
    expected = sorted(expected_goldens_coords())
    row = {"v": 1, "plane": "aa", "artifact": "bb", "nan_inf": 0}
    doc = _golden_doc({c: dict(row) for c in expected})
    with open(path, "w", encoding="utf-8") as f:
        json.dump(dict(doc, version=99), f)
    assert ids(check_goldens(root)) == ["RETRACE.GOLDENS:version"]

    # exact coverage -> clean; a dropped coordinate AND a bogus one both
    # fail loudly (shrinkage un-guards a bucket, growth describes
    # executables the workload no longer produces)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    assert check_goldens(root) == []
    skewed = {c: dict(row) for c in expected[1:]}
    skewed["k1:f1:n1|bf16|single|r0|c0"] = dict(row)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(_golden_doc(skewed), f)
    got = ids(check_goldens(root))
    assert f"RETRACE.GOLDENS:uncovered:{expected[0]}" in got
    assert "RETRACE.GOLDENS:stale:k1:f1:n1|bf16|single|r0|c0" in got
    assert len(got) == 2


# ---------------------------------------------------------------------------
# the idle-aware scheduler
# ---------------------------------------------------------------------------


def test_sentinel_scheduler_units():
    golden = {"v": 1, "plane": "aa", "artifact": "bb", "nan_inf": 0}
    doc = _golden_doc({COORD: golden})
    probe = {"coord": COORD, "scene": "A", "digest": dict(golden)}
    idle = [False]
    rounds = [None]
    sent = canary.CanarySentinel(run_round=lambda: rounds[0], goldens=doc,
                                 interval_s=60.0, is_idle=lambda: idle[0])

    # busy daemon: the tick is SKIPPED — canaries never add latency
    assert sent.tick() is None
    idle[0] = True
    # run_round returning None (worker busy mid-handshake) also skips
    assert sent.tick() is None
    st = sent.stats()
    assert st["rounds"] == 0 and st["skipped_busy"] == 2

    rounds[0] = [probe]
    res = sent.tick()
    assert [r["status"] for r in res] == ["ok"]
    st = sent.stats()
    assert st["rounds"] == 1 and st["drift_total"] == 0
    assert st["coords"] == [COORD]
    assert st["last_verified_age_s"][COORD] >= 0.0

    rounds[0] = [{"coord": COORD, "scene": "A",
                  "digest": dict(golden, artifact="dead")}]
    res = sent.tick()
    assert res[0]["status"] == "drift" and res[0]["fields"] == ["artifact"]
    st = sent.stats()
    assert st["rounds"] == 2 and st["drift_total"] == 1
    assert st["drift_coords"] == {COORD: 1}
    assert st["last_results"][0]["status"] == "drift"
    # interval clamps away from a busy-loop
    assert canary.CanarySentinel(run_round=lambda: None, goldens=doc,
                                 interval_s=0.0).interval_s >= 0.05


# ---------------------------------------------------------------------------
# drift -> typed event -> flight dump -> SLO page (the drill's chain)
# ---------------------------------------------------------------------------


def test_drift_trips_event_flight_and_slo(tmp_path, capsys):
    from maskclustering_tpu.obs.events import (KIND_DRIFT, KIND_TELEMETRY,
                                               read_events)

    events = str(tmp_path / "events.jsonl")
    fdir = str(tmp_path / "flight")
    golden = {"v": 1, "plane": "aa", "artifact": "bb", "nan_inf": 0}
    doc = _golden_doc({COORD: golden})
    probe = {"coord": COORD, "scene": "A",
             "digest": dict(golden, plane="dead")}
    obs.configure(events, sample_memory=False, truncate=True,
                  meta={"tool": "test_sentinel"})
    flight.arm(fdir)
    try:
        sent = canary.CanarySentinel(run_round=lambda: [probe], goldens=doc,
                                     interval_s=60.0)
        res = sent.tick()
        assert res[0]["status"] == "drift"
        # the window row a sentinel-armed daemon's aggregator would fold
        # (obs/telemetry.py "drift") — makes this events file the exact
        # offline input `obs.slo --events --check` gates on
        obs.emit_event(KIND_TELEMETRY, {"requests": 0, "drift": 1})
    finally:
        flight.arm(None)
        obs.disable()

    drift_rows = [e for e in read_events(events)
                  if e.get("kind") == KIND_DRIFT]
    assert drift_rows and drift_rows[0]["coord"] == COORD
    assert drift_rows[0]["fields"] == ["plane"]
    assert drift_rows[0]["golden"]["plane"] == "aa"

    dumps = glob.glob(os.path.join(fdir, "*canary_drift*.jsonl"))
    assert len(dumps) == 1, "drift must dump a postmortem immediately"
    _meta, rows = flight.read_dump(dumps[0])
    marks = [r for r in rows if r.get("kind") == "canary.drift"]
    assert marks and marks[0]["coord"] == COORD

    # the CI gate shape: offline SLO over this file pages on correctness
    rc = slo.main(["--events", events, "--check"])
    cap = capsys.readouterr()
    assert rc == 2 and "correctness" in cap.err


def test_slo_drift_zero_tolerance_semantics():
    """drift_count at threshold 0 pages on ONE occurrence in the long
    window; other zero-threshold counts keep the strict burn rule."""
    spec = slo.load_spec(None)

    def win(drift=0, pwc=0):
        return {"requests": 0, "drift": drift, "post_warm_compiles": pwc}

    one_drift = slo.evaluate(spec, {"windows": [win(), win(1), win(), win()]})
    assert slo.violated(one_drift) == ["correctness"]
    assert not one_drift["ok"]
    clean = slo.evaluate(spec, {"windows": [win(), win()]})
    assert "correctness" not in slo.violated(clean)
    # a lone post-warm compile burns at exactly 1.0 — not a page
    # (pinned in test_blackbox.py; the sentinel carve-out must not leak)
    assert slo.violated(slo.evaluate(
        spec, {"windows": [win(pwc=1), win()]})) == []
    # drift older than the long window has aged out of the verdict
    aged = slo.evaluate(spec, {"windows": [win(1)] + [win()] * 5})
    assert slo.violated(aged) == []


# ---------------------------------------------------------------------------
# integration: determinism across coordinates on the tiny bucket
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_runs():
    """One tiny scene through run_scene at several coordinates (shared
    compile cache: every variant lands in the same shape bucket)."""
    from maskclustering_tpu.models.pipeline import run_scene

    scene = make_scene(num_boxes=2, num_frames=6, image_hw=(40, 56),
                       spacing=0.06, seed=7)

    def run(**kw):
        return run_scene(to_scene_tensors(scene), _cfg(**kw), k_max=15,
                         seq_name="tiny0")

    return {"scene": scene, "run": run, "base": run(), "repeat": run()}


def test_digest_deterministic_across_runs(tiny_runs):
    base, repeat = tiny_runs["base"].digest, tiny_runs["repeat"].digest
    assert base is not None
    assert base == repeat  # full dict byte-identity, coordinate included
    assert base["v"] == digest_mod.DIGEST_VERSION
    assert base["nan_inf"] == 0
    assert len(base["plane"]) == 8 and len(base["artifact"]) == 8
    assert digest_mod.digest_coord(base) \
        == f"{base['bucket']}|bf16|single|r0|c0"


def test_digest_matches_across_count_dtypes_and_rungs(tiny_runs):
    """Every coordinate that claims identity produces MATCHING digests:
    the count_dtype axis and each applicable degradation rung (the
    scannet config is mesh-less, so the ladder's rungs are donation-off
    and host-postprocess) — byte-stability is what makes one golden per
    bucket sufficient."""
    base = tiny_runs["base"].digest
    alt = tiny_runs["run"](count_dtype="int8").digest
    assert alt["count_dtype"] == "int8"  # its own coordinate...
    assert digest_mod.digests_match(base, alt)  # ...same bytes
    for overrides in ({"donate_buffers": False},
                      {"donate_buffers": False,
                       "device_postprocess": False}):
        rung = tiny_runs["run"](**overrides).digest
        assert digest_mod.digests_match(base, rung), \
            f"digest drifted under {overrides}"


def test_corrupt_fault_flips_plane_only(tiny_runs):
    """The scripted silent bit-flip: no exception (the retry ladder never
    heals it), the artifact hash is untouched (objects were computed
    before the flip), and ONLY the plane digest moves — exactly the
    signal shape the canary drill detects."""
    clean = tiny_runs["base"]
    faults.set_plan(faults.FaultPlan.from_spec("corrupt:tiny0.host"))
    try:
        bad = tiny_runs["run"]()
    finally:
        faults.set_plan(None)
    assert digest_mod.diff_digests(bad.digest, clean.digest) == ["plane"]
    assert bad.digest["artifact"] == clean.digest["artifact"]
    assert bad.assignment[0] == clean.assignment[0] ^ 0x1
    np.testing.assert_array_equal(bad.assignment[1:], clean.assignment[1:])


def test_executors_stamp_identical_digests(tmp_path):
    """cluster_scenes stamps digest + full census coordinate on every
    SceneStatus, and the overlapped executor's digests are byte-identical
    to the sequential loop's — the executor reorders execution, never
    results, and now the sentinel can SEE that at runtime."""
    from maskclustering_tpu.run import cluster_scenes

    root = str(tmp_path)
    names = []
    for i in range(2):
        scene = make_scene(num_boxes=2, num_frames=6, image_hw=(40, 56),
                           spacing=0.06, seed=30 + i)
        names.append(f"scene{i:04d}_00")
        write_scannet_layout(scene, root, names[-1])
    over = cluster_scenes(_cfg(data_root=root, config_name="sovl"), names,
                          resume=False)
    seq = cluster_scenes(_cfg(data_root=root, config_name="sseq",
                              scene_overlap=False), names, resume=False)
    assert [s.status for s in over] == ["ok", "ok"]
    for a, b in zip(over, seq):
        assert a.digest is not None and a.digest == b.digest
        assert a.digest_coord == b.digest_coord
        assert a.digest_coord == digest_mod.digest_coord(a.digest)
        assert a.digest_coord.endswith("|single|r0|c0")


# ---------------------------------------------------------------------------
# slow: the full goldens regeneration reproduces the committed file
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_isolated_worker_canary_matches_committed(tmp_path):
    """Cross-topology identity: a REAL --isolate-worker child (jax in a
    subprocess, worker_main's canary op over the supervisor pipe)
    reproduces the committed goldens byte-for-byte — the same coordinates
    and the same bytes the in-process round produces."""
    from maskclustering_tpu.serve.admission import AdmissionQueue
    from maskclustering_tpu.serve.router import Router
    from maskclustering_tpu.serve.supervisor import WorkerSupervisor

    baseline = os.path.join(REPO_ROOT, "compile_surface_baseline.json")
    committed = canary.load_goldens(os.path.join(
        REPO_ROOT, canary.DEFAULT_GOLDENS_PATH))
    assert committed is not None
    # the drill's daemon cfg: scannet's math knobs ARE the goldens cfg
    cfg = load_config("scannet").replace(
        data_root=str(tmp_path), worker_heartbeat_s=60.0)
    sup = WorkerSupervisor(cfg, AdmissionQueue(4),
                           Router(cfg, baseline_path=baseline),
                           warm_baseline=baseline, freeze_after_warm=True,
                           start_timeout_s=600.0, poll_s=0.1)
    try:
        sup.start()
        probes = sup.run_canary(timeout_s=300.0)
    finally:
        sup.stop(timeout_s=60.0)
    assert probes, "isolated worker produced no canary probes"
    got = canary.probes_to_goldens(probes)
    assert set(got) == set(committed["goldens"])
    for coord, row in got.items():
        assert digest_mod.digests_match(row, committed["goldens"][coord]), \
            f"isolated-worker digest drifted at {coord}"


@pytest.mark.slow
def test_regenerated_goldens_match_committed():
    """The cross-topology canary e2e: an in-process canary round over the
    census-bucket warm vocabulary (the exact flow behind --write-goldens
    AND behind a sentinel-armed daemon's probes) reproduces the committed
    goldens byte-for-byte."""
    committed = canary.load_goldens(os.path.join(
        REPO_ROOT, canary.DEFAULT_GOLDENS_PATH))
    assert committed is not None
    observed = canary.generate_goldens(
        canary.goldens_config(),
        baseline_path=os.path.join(REPO_ROOT,
                                   "compile_surface_baseline.json"))
    assert set(observed) == set(committed["goldens"])
    for coord, row in observed.items():
        assert digest_mod.digests_match(row, committed["goldens"][coord]), \
            f"regenerated golden drifted at {coord}"
