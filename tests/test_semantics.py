"""Semantics layer tests: crop geometry, pooling math, open-vocab query.

Pin the OpenMask3D crop policy (reference get_open-voc_features.py:44-99) and
the query math (open-voc_query.py:30-53) with a deterministic fake encoder —
no CLIP weights needed (SURVEY.md §4's fake-backend strategy).
"""

import numpy as np

from maskclustering_tpu.semantics import (
    HashEncoder,
    assign_labels,
    classify_objects,
    extract_label_features,
    extract_mask_features,
    l2_normalize,
    mask_to_box,
    multiscale_crops,
    object_features,
    pad_to_square,
    pool_scale_features,
    representative_mask_index,
)


def test_mask_to_box_levels():
    mask = np.zeros((100, 200), dtype=bool)
    mask[40:61, 50:91] = True  # rows 40..60, cols 50..90
    assert mask_to_box(mask, 0) == (50, 40, 90, 60)
    # level 1: expand by int(extent * 0.1) per side, extent_x=40, extent_y=20
    assert mask_to_box(mask, 1) == (46, 38, 94, 62)
    # level 2 expands twice as far, clamped to the image
    left, top, right, bottom = mask_to_box(mask, 2)
    assert (left, top, right, bottom) == (42, 36, 98, 64)


def test_mask_to_box_clamps_to_image():
    mask = np.zeros((20, 20), dtype=bool)
    mask[0:20, 0:20] = True
    # tight box is 0..19; expansion int(19*0.1)*2 = 2 clamps to the image
    assert mask_to_box(mask, 2) == (0, 0, 20, 20)


def test_pad_to_square_centers_content():
    img = np.full((10, 4, 3), 7, dtype=np.uint8)
    sq = pad_to_square(img)
    assert sq.shape == (10, 10, 3)
    assert (sq[:, 3:7] == 7).all()  # content centered
    assert (sq[:, :3] == 255).all() and (sq[:, 7:] == 255).all()


def test_multiscale_crops_shapes_grow():
    rgb = np.random.default_rng(0).integers(0, 255, (100, 200, 3), dtype=np.uint8)
    mask = np.zeros((100, 200), dtype=bool)
    mask[40:61, 50:91] = True
    crops = multiscale_crops(rgb, mask)
    assert len(crops) == 3
    sizes = [c.shape[0] for c in crops]
    assert sizes == sorted(sizes)  # larger level -> larger (square) crop
    assert all(c.shape[0] == c.shape[1] and c.shape[2] == 3 for c in crops)


def test_multiscale_crops_resizes_lowres_mask():
    rgb = np.zeros((100, 200, 3), dtype=np.uint8)
    mask = np.zeros((50, 100), dtype=bool)  # half-resolution segmentation
    mask[20:31, 25:46] = True
    crops = multiscale_crops(rgb, mask)
    assert len(crops) == 3  # scaled up to RGB resolution without error


def test_pool_scale_features_means_over_scales():
    f = np.arange(12, dtype=np.float32).reshape(6, 2)  # 2 masks x 3 scales
    pooled = pool_scale_features(f, num_scales=3)
    assert pooled.shape == (2, 2)
    np.testing.assert_allclose(pooled[0], f[0:3].mean(axis=0))
    np.testing.assert_allclose(pooled[1], f[3:6].mean(axis=0))


def test_classify_objects_picks_nearest_text():
    rng = np.random.default_rng(1)
    text = l2_normalize(rng.standard_normal((5, 16)).astype(np.float32))
    objs = text[[3, 0, 4]] + 0.01 * rng.standard_normal((3, 16)).astype(np.float32)
    idx = classify_objects(objs, text)
    assert idx.tolist() == [3, 0, 4]


def test_object_features_and_missing_masks():
    object_dict = {
        0: {"repre_mask_list": [("f1", 2, 0.9), ("f2", 3, 0.8)], "point_ids": [0, 1]},
        1: {"repre_mask_list": [], "point_ids": [2]},
    }
    mask_features = {"f1_2": np.ones(4, np.float32), "f2_3": 3 * np.ones(4, np.float32)}
    feats, valid = object_features(object_dict, mask_features, 4)
    np.testing.assert_allclose(feats[0], 2 * np.ones(4))
    assert valid.tolist() == [True, False]


def test_assign_labels_end_to_end():
    enc = HashEncoder(feature_dim=32)
    labels = ["chair", "table"]
    text = enc.encode_texts(labels)
    label_features = {l: text[i] for i, l in enumerate(labels)}
    # object 0's masks carry exactly the "table" text feature
    mask_features = {"f1_1": text[1], "f2_5": text[1]}
    object_dict = {
        7: {"repre_mask_list": [("f1", 1, 0.9), ("f2", 5, 0.7)],
            "point_ids": np.array([0, 3, 4])},
    }
    pred = assign_labels(object_dict, mask_features, label_features,
                         {"chair": 11, "table": 22}, num_points=6)
    assert pred["pred_classes"].tolist() == [22]
    assert pred["pred_masks"].shape == (6, 1)
    assert pred["pred_masks"][:, 0].tolist() == [True, False, False, True, True, False]
    assert pred["pred_score"].tolist() == [1.0]


def test_assign_labels_featureless_object_keeps_empty_mask():
    """Objects without representative-mask features must keep an all-False
    mask column (reference open-voc_query.py:33-35 skips them entirely), so
    the evaluator drops them instead of seeing a confidence-1.0 prediction."""
    object_dict = {
        0: {"repre_mask_list": [], "point_ids": np.array([1, 2])},
    }
    pred = assign_labels(object_dict, {}, {"chair": np.ones(4, np.float32)},
                         {"chair": 11}, num_points=4)
    assert not pred["pred_masks"].any()
    assert pred["pred_classes"].tolist() == [0]


def test_representative_mask_index_dedupes():
    object_dict = {
        0: {"repre_mask_list": [("f1", 1, 0.9), ("f2", 2, 0.8)]},
        1: {"repre_mask_list": [("f1", 1, 0.5)]},  # shared mask
    }
    assert representative_mask_index(object_dict) == [("f1", 1), ("f2", 2)]


class _DiskDataset:
    """Minimal duck-typed dataset over temp rgb/seg PNGs."""

    def __init__(self, root):
        self.root = root

    def get_frame_path(self, frame_id):
        return (f"{self.root}/rgb_{frame_id}.png", f"{self.root}/seg_{frame_id}.png")


def test_extract_mask_features_from_disk(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(2)
    rgb = rng.integers(0, 255, (60, 80, 3), dtype=np.uint8)
    seg = np.zeros((60, 80), dtype=np.uint8)
    seg[10:30, 20:50] = 1
    seg[35:55, 5:25] = 2
    Image.fromarray(rgb).save(tmp_path / "rgb_000.png")
    Image.fromarray(seg).save(tmp_path / "seg_000.png")

    object_dict = {
        0: {"repre_mask_list": [("000", 1, 0.9)], "point_ids": [0]},
        1: {"repre_mask_list": [("000", 2, 0.9)], "point_ids": [1]},
    }
    feats = extract_mask_features(_DiskDataset(tmp_path), object_dict,
                                  HashEncoder(16), batch_size=2, io_workers=2)
    assert set(feats) == {"000_1", "000_2"}
    assert all(v.shape == (16,) for v in feats.values())
    # deterministic: same inputs, same features
    feats2 = extract_mask_features(_DiskDataset(tmp_path), object_dict,
                                   HashEncoder(16), batch_size=1, io_workers=1)
    np.testing.assert_allclose(feats["000_1"], feats2["000_1"], atol=1e-6)


def test_extract_label_features_artifact(tmp_path):
    path = extract_label_features(["chair", "sofa"], HashEncoder(8),
                                  str(tmp_path / "text" / "scannet.npy"))
    d = np.load(path, allow_pickle=True).item()
    assert set(d) == {"chair", "sofa"}
    np.testing.assert_allclose(np.linalg.norm(d["chair"]), 1.0, atol=1e-5)


def test_find_local_clip_checkpoint(tmp_path, monkeypatch):
    """Finder semantics: env override wins, hub cache is scanned for clip
    model dirs, a config.json without weights is not a checkpoint."""
    from maskclustering_tpu.semantics.encoder import find_local_clip_checkpoint

    monkeypatch.delenv("MCT_CLIP_PATH", raising=False)
    hub = tmp_path / "hub"
    snap = hub / "models--openai--clip-vit-base" / "snapshots" / "abc"
    snap.mkdir(parents=True)
    monkeypatch.setenv("HF_HUB_CACHE", str(hub))

    # config without weights: not a usable checkpoint
    (snap / "config.json").write_text("{}")
    assert find_local_clip_checkpoint() is None

    (snap / "pytorch_model.bin").write_bytes(b"x")
    assert find_local_clip_checkpoint() == str(snap)

    # a non-clip model dir is never picked up
    other = hub / "models--bert-base" / "snapshots" / "zzz"
    other.mkdir(parents=True)
    (other / "config.json").write_text("{}")
    (other / "model.safetensors").write_bytes(b"x")
    assert find_local_clip_checkpoint() == str(snap)

    # the open_clip cache layout of the reference's exact checkpoint
    # (ViT-H-14 laion2b_s32b_b79k) is also a hit
    oc = (hub / "models--laion--CLIP-ViT-H-14-laion2B-s32B-b79K"
          / "snapshots" / "def")
    oc.mkdir(parents=True)
    (oc / "open_clip_config.json").write_text("{}")
    (oc / "open_clip_pytorch_model.bin").write_bytes(b"x")
    assert find_local_clip_checkpoint() in (str(snap), str(oc))

    # explicit env path takes precedence
    override = tmp_path / "local_clip"
    override.mkdir()
    (override / "config.json").write_text("{}")
    (override / "flax_model.msgpack").write_bytes(b"x")
    monkeypatch.setenv("MCT_CLIP_PATH", str(override))
    assert find_local_clip_checkpoint() == str(override)


def test_run_report_records_clip_fact(tmp_path, monkeypatch):
    """run_report.json carries the clip_checkpoint environment fact."""
    import json

    from maskclustering_tpu.run import RunReport

    r = RunReport(config_name="x", clip_checkpoint=None)
    r.save(str(tmp_path / "rep.json"))
    assert json.load(open(tmp_path / "rep.json"))["clip_checkpoint"] is None
