"""Shape-bucket policy + persistent compilation cache wiring.

A heterogeneous scene batch must land on a handful of padded jit shapes
(VERDICT r3 task 5: <= 3 buckets for a 10-scene heterogeneous run), and the
padded pipeline must produce the same objects as the exact-shape pipeline.
"""

import numpy as np

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.pipeline import pad_scene_tensors, run_scene
from maskclustering_tpu.utils.compile_cache import (
    record_shape_bucket,
    reset_shape_buckets,
    seen_shape_buckets,
    setup_compilation_cache,
)
from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors


def _config(**kw):
    base = dict(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.03, step=1, mask_pad_multiple=64,
        point_chunk=2048, frame_pad_multiple=8,
    )
    base.update(kw)
    return PipelineConfig(**base)


def test_bucket_size_ladder():
    from maskclustering_tpu.models.pipeline import bucket_size

    assert bucket_size(1, 8) == 8
    assert bucket_size(40, 8) == 48  # m=5 -> 6 (3*2^1)
    assert bucket_size(55296, 2048) == 65536  # m=27 -> 32
    assert bucket_size(250, 32) == 256  # m=8
    assert bucket_size(100, 32) == 128  # m=4
    # values on the ladder stay put
    assert bucket_size(65536, 2048) == 65536
    assert bucket_size(6 * 2048, 2048) == 6 * 2048


def test_mask_table_pad_is_geometric():
    """M_pad must ride the same 2-significant-bit ladder as F/N pads.

    Linear 256-rounding gave nearly every real scene a fresh M_pad, so the
    (M_pad,)/(M_pad, M_pad)-shaped stages (graph stats, clustering,
    postprocess) recompiled per scene: 25-40 s each in the round-5
    northstar sweep. Scenes in the same mask-count octave must share one
    compile unit.
    """
    from maskclustering_tpu.models.graph import build_mask_table

    def m_pad_for(num_masks):
        mask_valid = np.zeros((num_masks, 1), dtype=bool)
        mask_valid[:, 0] = True
        return build_mask_table(mask_valid, pad_multiple=256).m_pad

    # 125x16=2000 and 128x20=2560 masks (northstar scenes 1 vs 2) now land
    # in adjacent ladder steps instead of per-scene fresh values
    assert m_pad_for(2000) == 2048
    assert m_pad_for(2560) == 3072
    assert m_pad_for(2561) == 3072  # same bucket across the octave
    assert m_pad_for(3072) == 3072
    # tiny scenes still get the floor
    assert m_pad_for(1) == 256
    # ladder values are always multiples of the pad multiple (mesh row
    # sharding over 8 frames relies on divisibility)
    for n in (1, 300, 2000, 5000, 9000, 16000):
        assert m_pad_for(n) % 256 == 0


def test_bucket_accounting():
    reset_shape_buckets()
    assert record_shape_bucket("scene", 63, 32, 8192)
    assert not record_shape_bucket("scene", 63, 32, 8192)
    assert record_shape_bucket("scene", 63, 64, 8192)
    assert len(seen_shape_buckets()) == 2
    reset_shape_buckets()


def test_heterogeneous_scenes_share_buckets():
    """10 scenes with frame counts 5..14 and varying cloud sizes must hit
    at most 3 (k_max, F_pad, N_pad) buckets."""
    cfg = _config()
    reset_shape_buckets()
    for i in range(10):
        scene = make_scene(num_boxes=3, num_frames=5 + i, seed=i, spacing=0.05)
        run_scene(to_scene_tensors(scene), cfg, k_max=15)
    buckets = {b for b in seen_shape_buckets() if b[0] == "scene"}
    assert 1 <= len(buckets) <= 3, buckets
    reset_shape_buckets()


def test_padded_pipeline_matches_exact_shapes():
    """Bucket padding must not change the artifacts.

    The baseline run must be truly UNPADDED: the scene is trimmed to 6144
    points (= 6*1024, on the two-significant-bit ladder for multiple 1024)
    with 12 frames (= 3*4, on the ladder for multiple 1), so the baseline
    config pads nothing, while the second config pads frames to 16 and
    points to 8192."""
    from maskclustering_tpu.models.pipeline import bucket_size

    scene = make_scene(num_boxes=4, num_frames=12, seed=21, spacing=0.04)
    t = to_scene_tensors(scene)
    keep = 6144
    t.scene_points = np.ascontiguousarray(t.scene_points[:keep])
    assert bucket_size(keep, 1024) == keep
    assert bucket_size(12, 1) == 12

    reset_shape_buckets()
    res_exact = run_scene(t, _config(frame_pad_multiple=1, point_chunk=1024), k_max=15)
    assert ("scene", 15, 12, keep) in seen_shape_buckets()  # unpadded bucket
    res_pad = run_scene(t, _config(frame_pad_multiple=16, point_chunk=8192), k_max=15)
    assert ("scene", 15, 16, 8192) in seen_shape_buckets()
    reset_shape_buckets()
    oh, od = res_exact.objects, res_pad.objects
    assert oh.num_points == od.num_points == t.num_points
    assert len(oh.point_ids_list) == len(od.point_ids_list)
    for ph, pd in zip(oh.point_ids_list, od.point_ids_list):
        np.testing.assert_array_equal(ph, pd)
    assert oh.mask_list == od.mask_list


def test_pad_scene_tensors_invariants():
    scene = make_scene(num_boxes=2, num_frames=5, seed=1)
    t = to_scene_tensors(scene)
    p = pad_scene_tensors(t, 8, t.num_points + 100)
    assert p.num_frames == 8 and p.num_points == t.num_points + 100
    assert not np.asarray(p.frame_valid)[5:].any()
    assert (p.scene_points[t.num_points:] == 1.0e4).all()
    assert p.frame_ids[5:] == [None, None, None]
    # no-op when already at the bucket
    assert pad_scene_tensors(t, t.num_frames, t.num_points) is t


def test_setup_compilation_cache(tmp_path):
    d = str(tmp_path / "xla")
    assert setup_compilation_cache(d) == d
    import os

    assert os.path.isdir(d)
    assert setup_compilation_cache("") is None  # disabled
