"""Visualization subsystem tests: scene PLYs, mask colorization, z-buffer
projection (vs brute-force oracle), bbox drawing, debug grids."""

import os

import numpy as np
import jax.numpy as jnp

from maskclustering_tpu.io.ply import read_ply_points
from maskclustering_tpu.visualize import (
    bbox_by_projection,
    colorize_id_map,
    create_colormap,
    draw_bbox,
    frames_to_gif,
    instance_palette,
    project_zbuffer,
    save_debug_grids,
    vis_mask_frame,
    vis_scene,
)
from maskclustering_tpu.visualize.top_images import stitch_grid


class TestVisScene:
    def test_writes_instance_and_rgb_plys(self, tmp_path):
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(200, 3))
        masks = np.zeros((200, 2), dtype=bool)
        masks[:50, 0] = True
        masks[50:120, 1] = True
        out = vis_scene(pts, masks, str(tmp_path), scene_colors=rng.uniform(size=(200, 3)))
        inst, colors = read_ply_points(out["instances"], return_colors=True)
        assert len(inst) == 120  # only labeled points
        assert len(np.unique(colors, axis=0)) == 2
        rgb_pts = read_ply_points(out["rgb"])
        assert len(rgb_pts) == 200

    def test_palette_deterministic(self):
        np.testing.assert_array_equal(instance_palette(7), instance_palette(7))


class TestMask2D:
    def test_colorize(self):
        seg = np.array([[0, 1], [2, 1]], dtype=np.uint8)
        cmap = create_colormap(16)
        out = colorize_id_map(seg, cmap)
        np.testing.assert_array_equal(out[0, 0], [0, 0, 0])
        np.testing.assert_array_equal(out[0, 1], cmap[1])
        np.testing.assert_array_equal(out[1, 0], cmap[2])

    def test_vis_mask_frame_and_gif(self, tmp_path):
        class FakeDS:
            def get_segmentation(self, fid, align_with_depth=True):
                seg = np.zeros((40, 60), dtype=np.uint8)
                seg[5:25, 5:30] = 1
                return seg

            def get_rgb(self, fid):
                return np.full((40, 60, 3), 128, dtype=np.uint8)

        ds = FakeDS()
        paths = [vis_mask_frame(ds, fid, str(tmp_path / "vis")) for fid in (0, 1)]
        from PIL import Image

        im = np.asarray(Image.open(paths[0]))
        assert im.shape == (20, 60, 3)  # concat x2 width, half scale
        gif = frames_to_gif(paths, str(tmp_path / "anim.gif"), fps=5)
        assert os.path.exists(gif)


class TestProjectZbuffer:
    def _cam(self):
        intr = np.array([[50.0, 0, 32], [0, 50.0, 24], [0, 0, 1]])
        return intr, np.eye(4)

    def test_matches_bruteforce_oracle(self):
        rng = np.random.default_rng(3)
        pts = np.stack([rng.uniform(-0.5, 0.5, 300), rng.uniform(-0.4, 0.4, 300),
                        rng.uniform(1.0, 3.0, 300)], axis=1)
        cols = rng.uniform(size=(300, 3))
        intr, c2w = self._cam()
        h, w = 48, 64
        img, zbuf, visible = project_zbuffer(
            jnp.asarray(pts, jnp.float32), jnp.asarray(cols, jnp.float32),
            jnp.asarray(intr, jnp.float32), jnp.asarray(c2w, jnp.float32), h, w)
        # brute-force oracle (the reference's serial loop semantics)
        zb = np.full((h, w), np.inf)
        for p in pts:
            u = int(round(50 * p[0] / p[2] + 32))
            v = int(round(50 * p[1] / p[2] + 24))
            if 0 <= u < w and 0 <= v < h and p[2] < zb[v, u]:
                zb[v, u] = p[2]
        np.testing.assert_allclose(np.asarray(zbuf), zb, rtol=1e-5)
        # every visible point attains its pixel's min depth
        vis_np = np.asarray(visible)
        assert vis_np.any()
        img_np = np.asarray(img)
        assert img_np[np.isfinite(zb)].sum() > 0

    def test_behind_camera_invisible(self):
        intr, c2w = self._cam()
        pts = np.array([[0, 0, -1.0], [0, 0, 2.0]])
        img, zbuf, visible = project_zbuffer(
            jnp.asarray(pts, jnp.float32), jnp.ones((2, 3), jnp.float32),
            jnp.asarray(intr, jnp.float32), jnp.asarray(c2w, jnp.float32), 48, 64)
        assert not bool(visible[0]) and bool(visible[1])

    def test_bbox_by_projection(self):
        intr, c2w = self._cam()
        pts = np.array([[0.0, 0.0, 2.0], [0.2, 0.1, 2.0]])
        bbox = bbox_by_projection(pts, intr, c2w, (48, 64))
        x0, y0, x1, y1 = bbox
        assert (x0, y0) == (32, 24)  # center pixel
        # 50*0.2/2+32 = 37; 50*0.1/2+24 = 26.5 -> 26 (round-half-even, same
        # as the reference's Python round())
        assert x1 == 37 and y1 == 26
        assert bbox_by_projection(np.array([[0, 0, -5.0]]), intr, c2w, (48, 64)) is None


class TestGrids:
    def test_draw_bbox(self):
        rgb = np.zeros((30, 30, 3), dtype=np.uint8)
        out = draw_bbox(rgb, (5, 5, 20, 20), thickness=2)
        assert tuple(out[5, 10]) == (255, 0, 0)
        assert tuple(out[10, 10]) == (0, 0, 0)
        np.testing.assert_array_equal(draw_bbox(rgb, None), rgb)

    def test_stitch_grid_shapes(self):
        imgs = [np.full((10, 10, 3), i * 30, dtype=np.uint8) for i in range(5)]
        grid = stitch_grid(imgs, cell=64)
        assert grid.shape == (128, 192, 3)  # 2 rows x 3 cols
        single = stitch_grid(imgs[:1], cell=64)
        assert single.shape == (64, 64, 3)

    def test_save_debug_grids(self, tmp_path):
        class FakeDS:
            def get_rgb(self, fid):
                return np.full((48, 64, 3), 90, dtype=np.uint8)

            def get_intrinsics(self, fid):
                return np.array([[50.0, 0, 32], [0, 50.0, 24], [0, 0, 1]])

            def get_extrinsic(self, fid):
                return np.eye(4)

        scene_points = np.array([[0, 0, 2.0], [0.1, 0.1, 2.0], [5, 5, -1.0]])
        object_dict = {0: {
            "point_ids": np.array([0, 1]),
            "mask_list": [(0, 1, 0.9)],
            "repre_mask_list": [(0, 1, 0.9), (1, 2, 0.8)],
        }}
        grids = save_debug_grids(FakeDS(), object_dict, scene_points, str(tmp_path))
        assert len(grids) == 1 and os.path.exists(grids[0])
        bboxes = os.listdir(tmp_path / "bbox")
        assert len(bboxes) == 2


class TestDebugViewers:
    """Headless analogs of the reference's tasmap debug viewers
    (vis_depth.py:127-148, compare_masks.py, visualize_preprocessed.py:54-105)."""

    @staticmethod
    def _dataset(tmp_path):
        from maskclustering_tpu.datasets import get_dataset
        from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

        scene = make_scene(num_boxes=2, num_frames=4, image_hw=(48, 64), seed=5)
        root = str(tmp_path / "data")
        write_scannet_layout(scene, root, "scene0400_00")
        return get_dataset("scannet", "scene0400_00", data_root=root), scene

    def test_depth_preview(self, tmp_path):
        from maskclustering_tpu.visualize import depth_preview

        ds, scene = self._dataset(tmp_path)
        fid = ds.get_frame_list(1)[0]
        png, ply = depth_preview(ds, fid, str(tmp_path / "dbg"))
        assert os.path.exists(png) and os.path.exists(ply)
        pts = read_ply_points(ply)
        assert len(pts) == (scene.depths[0] > 0).sum()
        # backprojected depth must land near the scene geometry extents
        assert np.abs(pts).max() < 10.0

    def test_compare_mask_dirs(self, tmp_path):
        from PIL import Image

        from maskclustering_tpu.visualize import compare_mask_dirs

        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(); b.mkdir()
        for d, val in ((a, 60), (b, 180)):
            for name in ("0.png", "1.png"):
                Image.fromarray(np.full((10, 16, 3), val, np.uint8)).save(d / name)
        Image.fromarray(np.zeros((10, 16, 3), np.uint8)).save(a / "only_a.png")
        out = compare_mask_dirs(str(a), str(b), str(tmp_path / "cmp"))
        assert len(out) == 2  # only common names
        img = np.asarray(Image.open(out[0]))
        assert img.shape == (22, 16, 3)  # 10 + 2 separator + 10
        assert (img[10:12] == 0).all()  # black rule
        assert (img[:10] == 60).all() and (img[12:] == 180).all()

    def test_fused_cloud_preview(self, tmp_path):
        from maskclustering_tpu.visualize import fused_cloud_preview

        ds, scene = self._dataset(tmp_path)
        out = fused_cloud_preview(ds, str(tmp_path / "fused.ply"), stride=2,
                                  max_points_per_frame=500)
        pts, cols = read_ply_points(out, return_colors=True)
        assert 0 < len(pts) <= 2 * 500
        assert cols.shape == (len(pts), 3)
