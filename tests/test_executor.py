"""Overlapped scene executor: identity, overlap ratio, sync budget, prefetch.

Pins the acceptance contract of the async double-buffered pipeline:

- artifacts from the overlapped executor are byte-identical to the
  sequential loop on the same scenes;
- the obs run report measures an overlap ratio (sum of per-stage span time
  over scene-loop wall time) > 1 on a >= 4-scene CPU run — overlap is
  measured, not argued;
- the per-scene pipeline performs exactly ONE blocking host pull (the
  mask table; the assignment pull moved on device with the
  device-resident post-process), pinned by span counting;
- the disk-prefetch lookahead depth is configurable with deterministic
  ordering and failure attribution at depth 0/1/2.
"""

import os

import numpy as np
import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.config import load_config
from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout

N_SCENES = 4


def _cfg(data_root, **kw):
    return load_config("scannet").replace(
        data_root=data_root, step=1, distance_threshold=0.05,
        mask_pad_multiple=32, **kw)


@pytest.fixture(scope="module")
def pipelined_run(tmp_path_factory):
    """Four disk scenes, clustered twice: overlapped (obs-armed) and
    sequential. One heavy fixture; the tests below read its artifacts.

    No warmup on purpose: jit compiles land inside the measured loop,
    where they OVERLAP like any other stage work (scene 1's postprocess
    kernels compile under scene 2's association compile) — the cold ratio
    (~1.6x measured) carries more margin than the warm steady state.
    """
    from maskclustering_tpu.run import cluster_scenes

    root = str(tmp_path_factory.mktemp("data"))
    names = []
    for i in range(N_SCENES):
        scene = make_scene(num_boxes=3, num_frames=10, image_hw=(60, 80),
                           spacing=0.06,
                           seed=40 + i)
        names.append(f"scene{i:04d}_00")
        write_scannet_layout(scene, root, names[-1])

    events = os.path.join(root, "events.jsonl")
    obs.configure(events, sample_memory=False, truncate=True,
                  meta={"tool": "test_executor"})
    try:
        over = cluster_scenes(_cfg(root, config_name="ovl"), names,
                              resume=False)
    finally:
        obs.disable()
    seq = cluster_scenes(_cfg(root, config_name="seq", scene_overlap=False),
                         names, resume=False)
    return {"root": root, "names": names, "events": events,
            "over": over, "seq": seq}


def test_overlapped_matches_sequential_artifacts(pipelined_run):
    """Byte-identity: the overlapped executor reorders EXECUTION, never
    results — npz predictions and object dicts match the sequential loop
    exactly (same contract the mesh path is held to, test_run.py)."""
    root, names = pipelined_run["root"], pipelined_run["names"]
    assert [s.status for s in pipelined_run["over"]] == ["ok"] * N_SCENES
    assert [s.status for s in pipelined_run["seq"]] == ["ok"] * N_SCENES
    assert ([s.seq_name for s in pipelined_run["over"]]
            == names)  # report order follows the scene list
    pred = os.path.join(root, "prediction")
    for name in names:
        a = np.load(os.path.join(pred, "ovl_class_agnostic", f"{name}.npz"))
        b = np.load(os.path.join(pred, "seq_class_agnostic", f"{name}.npz"))
        for key in ("pred_masks", "pred_score", "pred_classes"):
            np.testing.assert_array_equal(a[key], b[key])
        od_dir = os.path.join(root, "scannet", "processed", name,
                              "output", "object")
        od_a = np.load(os.path.join(od_dir, "ovl", "object_dict.npy"),
                       allow_pickle=True).item()
        od_b = np.load(os.path.join(od_dir, "seq", "object_dict.npy"),
                       allow_pickle=True).item()
        assert od_a.keys() == od_b.keys()
        for k in od_a:
            np.testing.assert_array_equal(od_a[k]["point_ids"],
                                          od_b[k]["point_ids"])
            assert od_a[k]["mask_list"] == od_b[k]["mask_list"]


def test_overlap_ratio_measured(pipelined_run):
    """The acceptance number: on a >= 4-scene CPU run the report's overlap
    ratio (sum of per-stage span time / scene-loop wall) is >= 1.2x —
    stage work genuinely ran concurrently. Also pins the report surfaces:
    summary() carries the overlap section and the rendered table says so."""
    from maskclustering_tpu.obs.report import RunData, render_report

    run = RunData(pipelined_run["events"])
    ov = run.overlap()
    assert ov is not None and ov["mode"] == "overlapped"
    assert ov["scene_loop_s"] > 0
    # load + device stages + host tail all appear as timelines
    assert {"associate", "graph", "cluster", "postprocess"} <= set(ov["stages"])
    assert "exec.load" in ov["stages"]
    assert ov["ratio"] >= 1.2, ov
    assert run.summary()["overlap"]["ratio"] == ov["ratio"]
    assert "scene overlap [overlapped]" in render_report(run)


def test_host_sync_budget(pipelined_run):
    """Span-counting acceptance: exactly ONE pipeline host sync per scene
    (graph's mask-table pull). The cluster stage's former assignment pull
    is gone — the device post-process consumes the assignment in HBM and
    the report copy rides the post-process drain (PR 8); the graph stage's
    former observer-histogram pull is long gone too."""
    run_events = [e for e in obs.read_events(pipelined_run["events"])
                  if e.get("kind") == "span"]
    pulls = [e for e in run_events if (e.get("attrs") or {}).get("host_pull")]
    # 1 per scene, and only ever in the graph stage
    assert len(pulls) == 1 * N_SCENES
    assert {e["name"] for e in pulls} == {"graph"}
    by_scene = {}
    for e in pulls:
        by_scene.setdefault(e["attrs"].get("scene"), []).append(e["name"])
    assert all(v == ["graph"] for v in by_scene.values())

    from maskclustering_tpu.obs.report import RunData

    counters = RunData(pipelined_run["events"]).summary()["counters"]
    assert counters.get("pipeline.host_sync") == 1 * N_SCENES
    # the schedule no longer crosses to host mid-pipeline
    summary_stages = RunData(pipelined_run["events"]).stage_rows()
    graph_row = next(r for r in summary_stages if r["stage"] == "graph")
    assert not graph_row["d2h_bytes"]


def test_exec_timeline_spans_present(pipelined_run):
    """The three executor timelines land as spans: exec.device on the
    dispatch thread, exec.host_tail on the worker, exec.load on the
    prefetch daemons, under one exec.scene_loop."""
    spans = [e for e in obs.read_events(pipelined_run["events"])
             if e.get("kind") == "span"]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["exec.scene_loop"]) == 1
    assert len(by_name["exec.device"]) == N_SCENES
    assert len(by_name["exec.host_tail"]) == N_SCENES
    assert len(by_name["exec.load"]) == N_SCENES
    # host tails carry the postprocess stage as a child span
    assert all(e.get("parent") == "exec.host_tail"
               for e in by_name["postprocess"])


class TestPrefetchDepth:
    """--prefetch-depth semantics at depth 0/1/2 (satellite)."""

    def _run(self, monkeypatch, depth, seq_names, fail=()):
        import maskclustering_tpu.run as run_mod

        started = []

        def fake_load(cfg, seq, resume, prediction_root):
            started.append(seq)
            if seq in fail:
                raise OSError(f"disk gone for {seq}")
            return ("ds-" + seq, "tensors-" + seq)

        monkeypatch.setattr(run_mod, "_load_for_cluster", fake_load)
        cfg = load_config("scannet").replace(prefetch_depth=depth)
        out = []
        for seq, resolve in run_mod._prefetched_loads(cfg, seq_names, True,
                                                      depth=depth):
            # bounded lookahead: nothing beyond i + depth can have started
            horizon = seq_names[: seq_names.index(seq) + depth + 1]
            assert set(started) <= set(horizon), (seq, started)
            try:
                out.append((seq, resolve()))
            except OSError as e:
                out.append((seq, e))
        return started, out

    @pytest.mark.parametrize("depth", [0, 1, 2])
    def test_ordering(self, monkeypatch, depth):
        names = [f"s{i}" for i in range(5)]
        started, out = self._run(monkeypatch, depth, names)
        assert [seq for seq, _ in out] == names  # yield order == list order
        assert sorted(started) == names  # every scene loaded exactly once
        for seq, val in out:
            assert val == ("ds-" + seq, "tensors-" + seq)

    @pytest.mark.parametrize("depth", [0, 1, 2])
    def test_error_reraises_at_owning_scene(self, monkeypatch, depth):
        names = ["s0", "s1", "s2", "s3"]
        _, out = self._run(monkeypatch, depth, names, fail={"s1"})
        assert isinstance(out[1][1], OSError) and "s1" in str(out[1][1])
        # neighbors are unaffected: the failure attributes to s1 alone
        assert out[0][1] == ("ds-s0", "tensors-s0")
        assert out[2][1] == ("ds-s2", "tensors-s2")

    def test_depth_config_validation(self):
        cfg = load_config("scannet").replace(prefetch_depth=2)
        assert cfg.prefetch_depth == 2
        with pytest.raises(ValueError):
            load_config("scannet").replace(prefetch_depth=-1)


def test_failed_scene_attributed_in_overlapped_loop(tmp_path):
    """A scene that explodes mid-queue is captured as ITS failure without
    sinking the loop — parity with the sequential path's contract."""
    from maskclustering_tpu.run import cluster_scenes

    root = str(tmp_path / "data")
    names = []
    for i in range(2):
        # same shape bucket as the module fixture: the scene runs here hit
        # the jit cache the fixture already paid for
        scene = make_scene(num_boxes=3, num_frames=10, image_hw=(60, 80),
                           seed=50 + i)
        names.append(f"scene{i:04d}_00")
        write_scannet_layout(scene, root, names[-1])
    queue = [names[0], "scene_missing_00", names[1]]
    statuses = cluster_scenes(_cfg(root, config_name="fovl"), queue,
                              resume=False)
    assert [s.seq_name for s in statuses] == queue
    assert [s.status for s in statuses] == ["ok", "failed", "ok"]
    assert "Error" in statuses[1].error or "Traceback" in statuses[1].error
