"""Separated synthetic clutter (VERDICT r5 Weak #3).

The historical generator packed grid centers so tightly at >= ~10 boxes
that neighboring boxes interpenetrated — full-depth scenes no segmenter
could solve, which made full-depth parity numbers meaningless. The new
placement guarantees a minimum inter-box gap (expanding the room and the
camera orbit together when needed) while reproducing the historical
geometry bit-for-bit for the small scenes every other test pins.
"""

import numpy as np
import pytest

from maskclustering_tpu.utils.synthetic import _place_boxes, make_scene


def _pairwise_gaps(boxes_arr):
    gaps = []
    for i in range(len(boxes_arr)):
        for j in range(i + 1, len(boxes_arr)):
            dx = max(boxes_arr[i, 0, 0] - boxes_arr[j, 1, 0],
                     boxes_arr[j, 0, 0] - boxes_arr[i, 1, 0])
            dy = max(boxes_arr[i, 0, 1] - boxes_arr[j, 1, 1],
                     boxes_arr[j, 0, 1] - boxes_arr[i, 1, 1])
            gaps.append(max(dx, dy))
    return gaps


@pytest.mark.parametrize("k", [9, 16, 36])
def test_separated_placement_at_any_box_count(k):
    """Every pair of boxes keeps a positive gap — the interpenetrating
    regime (>= ~10 boxes in the default room) is gone."""
    boxes, room_half_eff, scale = _place_boxes(k, 2.0, np.random.default_rng(1))
    arr = np.array([[b[0], b[1]] for b in boxes])
    assert min(_pairwise_gaps(arr)) >= 0.15
    if k > 9:
        assert scale > 1.0  # the room actually expanded
        assert room_half_eff == pytest.approx(2.0 * scale)


def test_small_scene_geometry_unchanged():
    """Bit-compat pin: scenes small enough to satisfy the gap in the
    requested room reproduce the pre-fix layout exactly (every seeded
    test scene in this suite depends on that)."""
    scene = make_scene(num_boxes=4, num_frames=10, seed=21)
    # checksum of the historical generator's cloud for this exact call
    assert float(scene.scene_points.sum()) == pytest.approx(8057.688, abs=1e-2)
    _, _, scale = _place_boxes(5, 2.0, np.random.default_rng(0))
    assert scale == 1.0


def test_expanded_room_stays_in_frustum():
    """When the room scales up, the camera orbit scales with it: every box
    is still observed (its mask id appears in some frame's id map)."""
    scene = make_scene(num_boxes=16, num_frames=12, image_hw=(96, 128),
                       spacing=0.05, seed=9)
    seen = set(np.unique(scene.segmentations)) - {0}
    assert len(seen) == 16
    # and every box contributes visible GEOMETRY, not just a sliver: each
    # object id claims a meaningful pixel share somewhere
    for perm_id in sorted(seen):
        assert (scene.segmentations == perm_id).sum() >= 50


def test_exact_path_solves_separated_deep_scene(tmp_path):
    """The acceptance pin for Weak #3: at full depth (12 objects, 24
    frames, the percentile ladder walking deep), the EXACT reference path
    reaches AP50 >= 0.7 on the separated layout — full-depth parity now
    runs on scenes that can actually be solved. Depth carries sensor-like
    noise (as scripts/parity_ab.py applies): the reference pipeline's bbox
    crop assumes non-degenerate view clouds, which analytic depth does not
    produce."""
    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.evaluation.ap import evaluate_scans
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.models.postprocess import export_artifacts
    from maskclustering_tpu.utils.synthetic import to_scene_tensors

    scene = make_scene(num_boxes=12, num_frames=24, image_hw=(96, 128),
                       spacing=0.035, seed=77)
    assert min(_pairwise_gaps(scene.boxes)) >= 0.15
    rng = np.random.default_rng(7)
    noisy = scene.depths + rng.normal(
        scale=0.004, size=scene.depths.shape).astype(np.float32)
    scene.depths[:] = np.where(scene.depths > 0, np.maximum(noisy, 1e-3), 0.0)

    cfg = PipelineConfig(config_name="deepexact", dataset="demo", backend="cpu",
                         distance_threshold=0.05, step=1, mask_pad_multiple=64,
                         point_chunk=4096, use_exact_ball_query=True)
    res = run_scene(to_scene_tensors(scene), cfg, k_max=15)
    paths = export_artifacts(res.objects, "scene0000_00", "deepexact",
                             object_dict_dir=str(tmp_path / "od"),
                             prediction_root=str(tmp_path / "pred"))
    gt = np.where(scene.gt_instance > 0, 3000 + scene.gt_instance + 1, 1)
    gt_path = str(tmp_path / "scene0000_00.txt")
    np.savetxt(gt_path, gt, fmt="%d")
    avgs = evaluate_scans([paths["npz"]], [gt_path], "scannet",
                          no_class=True, verbose=False)
    assert avgs["all_ap_50%"] >= 0.7, avgs
