import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.models.pipeline import run_scene
from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors, visibility_count


def _config():
    return PipelineConfig(
        config_name="synthetic", dataset="demo", backend="cpu",
        distance_threshold=0.03, step=1, mask_pad_multiple=64,
        point_chunk=2048,
    )


def _iou(pred_ids, gt_mask):
    pred = np.zeros_like(gt_mask)
    pred[pred_ids] = True
    inter = (pred & gt_mask).sum()
    union = (pred | gt_mask).sum()
    return inter / max(union, 1)


@pytest.fixture(scope="module")
def result_and_scene(tmp_path_factory):
    """One module-scoped scene run, with obs capture armed: the span tests
    piggyback on this run instead of paying for another one."""
    from maskclustering_tpu import obs

    scene = make_scene(num_boxes=4, num_frames=10, seed=21)
    cfg = _config()
    events = str(tmp_path_factory.mktemp("obs") / "events.jsonl")
    obs.configure(events, fence=True, sample_memory=False)
    try:
        res = run_scene(to_scene_tensors(scene), cfg, k_max=15)
    finally:
        obs.disable()
    return scene, res, events


def test_pipeline_recovers_objects(result_and_scene):
    scene, res, _ = result_and_scene
    objs = res.objects
    n_gt = scene.gt_instance.max()
    assert len(objs.point_ids_list) == n_gt, (
        f"expected {n_gt} objects, got {len(objs.point_ids_list)}"
    )
    # the pipeline can only segment observed geometry: compare against the
    # gt restricted to points visible in at least one frame
    visible = visibility_count(scene) >= 1
    matched = set()
    for gt in range(1, n_gt + 1):
        gt_mask = (scene.gt_instance == gt) & visible
        ious = [_iou(p, gt_mask) for p in objs.point_ids_list]
        best = int(np.argmax(ious))
        assert max(ious) > 0.8, f"gt {gt}: best IoU {max(ious):.3f}"
        assert best not in matched
        matched.add(best)


def test_pipeline_mask_lists(result_and_scene):
    scene, res, _ = result_and_scene
    for mlist in res.objects.mask_list:
        assert len(mlist) >= 2
        for frame_id, mask_id, cov in mlist:
            assert frame_id in scene.frame_ids
            assert 0 < cov <= 1.0
            # the mask id must map to a real object in that frame
            assert scene.object_of_mask[frame_id, mask_id] > 0


def test_auto_k_max_handles_ids_beyond_128(result_and_scene):
    """run_scene derives k_max from the data: relabeling the id-maps with
    sparse ids > 127 (CropFormer id-maps are uint16) must reproduce the
    exact same object point sets, with no cross-mask contamination."""
    from dataclasses import replace

    from maskclustering_tpu.models.pipeline import bucket_k_max

    assert bucket_k_max(0) == 63
    assert bucket_k_max(63) == 63
    assert bucket_k_max(64) == 127
    assert bucket_k_max(200) == 255

    scene, res_ref, _ = result_and_scene
    t = to_scene_tensors(scene)
    # order-preserving relabel 1..15 -> 120..400: ids now exceed 127
    seg = t.segmentations
    t_big = replace(t, segmentations=np.where(seg > 0, seg * 20 + 100, 0).astype(np.int32))
    res = run_scene(t_big, _config())  # k_max=None -> derived (bucket of 400)
    assert len(res.objects.point_ids_list) == len(res_ref.objects.point_ids_list)
    for a, b in zip(res.objects.point_ids_list, res_ref.objects.point_ids_list):
        np.testing.assert_array_equal(a, b)
    for ml_big, ml_ref in zip(res.objects.mask_list, res_ref.objects.mask_list):
        assert [(fr, m * 20 + 100, cov) for fr, m, cov in ml_ref] == ml_big


def test_export_artifacts(tmp_path, result_and_scene):
    from maskclustering_tpu.models.postprocess import export_artifacts

    scene, res, _ = result_and_scene
    paths = export_artifacts(
        res.objects, "synth0", "synthetic",
        object_dict_dir=str(tmp_path / "object"),
        prediction_root=str(tmp_path / "prediction"),
    )
    data = np.load(paths["npz"])
    n_inst = len(res.objects.point_ids_list)
    assert data["pred_masks"].shape == (len(scene.gt_instance), n_inst)
    assert data["pred_masks"].dtype == bool
    np.testing.assert_array_equal(data["pred_score"], np.ones(n_inst))
    np.testing.assert_array_equal(data["pred_classes"], np.zeros(n_inst, dtype=np.int32))

    od = np.load(paths["object_dict"], allow_pickle=True).item()
    assert set(od.keys()) == set(range(n_inst))
    for i in range(n_inst):
        np.testing.assert_array_equal(np.sort(od[i]["point_ids"]),
                                      np.nonzero(data["pred_masks"][:, i])[0])
        assert od[i]["repre_mask_list"] == sorted(
            od[i]["mask_list"], key=lambda t: t[2], reverse=True)[:5]


def test_run_scene_timings_come_from_spans(result_and_scene):
    """The per-stage ``timings`` dict is derived from obs spans now: with
    capture armed (the module fixture runs its scene that way), every
    legacy timings key appears as a span in the events file with a
    matching duration — and the legacy key set itself is unchanged (bench
    stage breakdowns and run_report consumers keep their schema)."""
    from maskclustering_tpu import obs

    _, res, path = result_and_scene
    legacy_keys = {"associate", "graph", "cluster", "postprocess",
                   "post.claims", "post.dbscan", "post.mask_assign",
                   "post.emit", "post.merge"}
    assert set(res.timings) == legacy_keys
    spans = [e for e in obs.read_events(path) if e["kind"] == "span"]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    # every timings key is backed by a span of the same name...
    assert legacy_keys <= set(by_name)
    for key, secs in res.timings.items():
        assert by_name[key][-1]["dur_s"] == pytest.approx(secs, rel=1e-6, abs=1e-6)
    # ...the post.* phases attribute to their parent stage...
    for name, evs in by_name.items():
        if name.startswith("post.") and not name.endswith(".kernel") \
                and not name.endswith(".pull"):
            assert evs[-1]["parent"] == "postprocess", name
    # ...and the stage spans carry the scene-shape attrs the report keys on
    assoc = by_name["associate"][-1]["attrs"]
    assert assoc["num_frames"] == 10 and assoc["k_max"] == 15
    assert "n_pad" in assoc and "f_pad" in assoc


def test_device_renderer_matches_numpy():
    """make_scene_device's jitted renderer agrees with the host ray tracer
    (same seed -> same boxes/cloud/perms; f32 vs f64 ray math may flip a
    few silhouette pixels)."""
    from maskclustering_tpu.utils.synthetic import make_scene, make_scene_device

    kw = dict(num_boxes=4, num_frames=6, image_hw=(96, 128), spacing=0.02,
              seed=7, room_half=2.0, camera_radius=3.2)
    ref = make_scene(camera_height=2.5, **kw)
    tensors, gt, oom = make_scene_device(floor_spacing=None, camera_height=2.5, **kw)

    np.testing.assert_array_equal(ref.scene_points, tensors.scene_points)
    np.testing.assert_array_equal(ref.gt_instance, gt)
    np.testing.assert_array_equal(ref.object_of_mask[:, :5], oom)
    seg_dev = np.asarray(tensors.segmentations)
    dep_dev = np.asarray(tensors.depths)
    agree = (seg_dev == ref.segmentations).mean()
    assert agree > 0.999, agree
    both = (dep_dev > 0) & (ref.depths > 0) & (seg_dev == ref.segmentations)
    np.testing.assert_allclose(dep_dev[both], ref.depths[both], rtol=1e-4, atol=1e-3)
