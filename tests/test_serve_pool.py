"""Multi-worker serving (serve/pool.py): carve grammar + validation,
weighted-fair tenant QoS, quota rejects, bucket-affine routing, crash
reroute to a warm neighbor, stream pinning/loss and live recarve — all on
the jax-free worker stub (tests/worker_stub.py), so the whole scheduler
plane runs in milliseconds. The real-subprocess pool acceptance is the
slow-marked test at the bottom; ci.sh gates the same contract end to end
via the rc-12 pool drill.
"""

import os
import sys
import threading
import time

import pytest

from maskclustering_tpu.config import (load_config, parse_carve_spec,
                                       parse_tenant_spec)
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue
from maskclustering_tpu.serve.pool import (QuotaReject, WorkerPool,
                                           check_carve)
from maskclustering_tpu.serve.router import Router

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO_ROOT, "tests", "worker_stub.py")


def _cfg(tmp_path, **kw):
    base = dict(data_root=str(tmp_path), config_name="pool", step=1,
                distance_threshold=0.05, mask_pad_multiple=32,
                worker_heartbeat_s=1.0, retry_backoff_s=0.05)
    base.update(kw)
    return load_config("scannet").replace(**base)


class _Client:
    def __init__(self):
        self.events = []
        self.done = threading.Event()

    def send(self, ev):
        self.events.append(ev)
        if ev.get("kind") in ("result", "reject"):
            self.done.set()

    @property
    def terminal(self):
        return self.events[-1] if self.events else None

    def states(self):
        return [e.get("state") for e in self.events
                if e.get("kind") == "status"]


def _admit(pool, scene, i, *, op="scene", tenant="", **kw):
    client = _Client()
    doc = {"op": op, "scene": scene, **kw}
    if tenant:
        doc["tenant"] = tenant
    req = protocol.build_request(doc, f"p-{i:06d}")
    req.send = client.send
    pool.admit(req)
    return client


def _make_pool(tmp_path, queue=None, **cfg_kw):
    cfg = _cfg(tmp_path, **cfg_kw)
    queue = queue or AdmissionQueue(32)
    pool = WorkerPool(cfg, queue, Router(cfg),
                      journal_dir=str(tmp_path / "journals"),
                      child_argv=[sys.executable, STUB],
                      start_timeout_s=15.0, poll_s=0.05)
    return pool, queue


@pytest.fixture()
def stub_pool(tmp_path, monkeypatch):
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    pool, queue = _make_pool(tmp_path, serve_workers=2)
    pool.start()
    yield pool, queue
    pool.stop(timeout_s=15.0)


# ---------------------------------------------------------------------------
# carve / tenant grammar + typed config validation
# ---------------------------------------------------------------------------


def test_parse_carve_spec_grammar():
    assert parse_carve_spec("4x2") == (4, 2)
    assert parse_carve_spec("1x8") == (1, 8)
    for bad in ("", "x", "4x", "x2", "4x2x1", "ax2", "4xb"):
        with pytest.raises(ValueError):
            parse_carve_spec(bad)
    for bad in ("0x2", "4x0", "-1x2"):
        with pytest.raises(ValueError):
            parse_carve_spec(bad)


def test_parse_tenant_spec_grammar():
    spec = parse_tenant_spec("heavy:3,light:1:4")
    assert spec == {"heavy": (3.0, None), "light": (1.0, 4)}
    assert parse_tenant_spec("a:0.5") == {"a": (0.5, None)}
    for bad in ("a", "a:1:2:3", ":1", "a:x", "a:0", "a:-1", "a:1:0",
                "a:1:1.5", "a:1,a:2", "a/b:1"):
        with pytest.raises(ValueError):
            parse_tenant_spec(bad)


def test_config_validates_pool_knobs(tmp_path):
    with pytest.raises(ValueError, match="serve_workers"):
        _cfg(tmp_path, serve_workers=0)
    with pytest.raises(ValueError, match="must equal serve_workers"):
        _cfg(tmp_path, serve_workers=2, serve_carve="3x2")
    with pytest.raises(ValueError):
        _cfg(tmp_path, serve_tenants="a:1:2:3")
    cfg = _cfg(tmp_path, serve_workers=2, serve_carve="2x4",
               serve_tenants="heavy:3,light:1:4")
    assert cfg.serve_workers == 2


def test_check_carve_divides_device_product():
    check_carve(2, 4, 8)          # 2x4 on 8 chips: exact
    check_carve(2, 2, 8)          # 2x2 on 8: divides
    check_carve(2, 0, 8)          # no carve: every slice whole-backend
    check_carve(2, 4, None)       # backend not inspectable: skip
    with pytest.raises(ValueError, match="divide"):
        check_carve(2, 8, 8)      # 16 > 8
    with pytest.raises(ValueError, match="divide"):
        check_carve(3, 2, 8)      # 6 does not divide 8


# ---------------------------------------------------------------------------
# the scheduler plane, on the stub pool
# ---------------------------------------------------------------------------


def test_pool_serves_on_both_workers(stub_pool):
    pool, _ = stub_pool
    clients = [_admit(pool, "stub-ok", i) for i in range(6)]
    for c in clients:
        assert c.done.wait(15.0) and c.terminal["status"] == "ok"
    assert pool.wait_idle(10.0)
    st = pool.stats()
    assert st["counts"]["ok"] == 6
    assert st["pool"]["scheduler"]["dispatched"] == 6
    assert len(st["pool"]["workers"]) == 2
    assert st["worker"]["pool"] == 2 and st["worker"]["alive"] == 2
    # both slices took work (least-loaded routing spreads an idle pool)
    assert sum(w["dispatched"] for w in st["pool"]["workers"]) == 6


def test_weighted_fair_three_to_one_dispatch_order(tmp_path, monkeypatch):
    """Under saturation a 3:1 weight ratio dequeues 3:1 by virtual-time
    stride scheduling — asserted on the dispatch ORDER (deterministic),
    not on wall-clock completion races."""
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    pool, _ = _make_pool(tmp_path, serve_workers=2,
                         serve_tenants="heavy:3,light:1")
    order = []
    book = pool._book_dispatch
    pool._book_dispatch = lambda req, wid: (order.append(req.tenant),
                                            book(req, wid))[1]
    pool._pause.set()  # hold dispatch until the whole burst is queued
    pool.start()
    try:
        clients = []
        for i in range(12):
            clients.append(_admit(pool, "stub-ok", i, tenant="heavy"))
        for i in range(12, 24):
            clients.append(_admit(pool, "stub-ok", i, tenant="light"))
        pool._pause.clear()
        for c in clients:
            assert c.done.wait(30.0) and c.terminal["status"] == "ok"
        # stride scheduling: every 4-dispatch window is 3 heavy + 1 light
        # until the heavy queue drains
        assert order[:4].count("heavy") == 3
        assert order[:8].count("heavy") == 6
        assert order[:12].count("heavy") == 9
        st = pool.stats()["pool"]["tenants"]
        assert st["heavy"]["dispatched"] == 12
        assert st["heavy"]["weight"] == 3.0
        assert st["light"]["dispatched"] == 12
    finally:
        pool.stop(timeout_s=15.0)


def test_quota_exhaustion_rejects_typed(tmp_path, monkeypatch):
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    pool, _ = _make_pool(tmp_path, serve_workers=2,
                         serve_tenants="capped:1:2")
    pool._pause.set()  # keep the queued count at its admitted level
    pool.start()
    try:
        c1 = _admit(pool, "stub-ok", 1, tenant="capped")
        c2 = _admit(pool, "stub-ok", 2, tenant="capped")
        with pytest.raises(QuotaReject) as ei:
            _admit(pool, "stub-ok", 3, tenant="capped")
        assert ei.value.tenant == "capped"
        assert ei.value.limit == 2 and ei.value.queued == 2
        # an unknown tenant has no quota: admission proceeds
        c4 = _admit(pool, "stub-ok", 4, tenant="other")
        pool._pause.clear()
        for c in (c1, c2, c4):
            assert c.done.wait(15.0) and c.terminal["status"] == "ok"
        # dispatch released the quota slots: the tenant admits again
        assert pool.wait_idle(10.0)
        c5 = _admit(pool, "stub-ok", 5, tenant="capped")
        assert c5.done.wait(15.0) and c5.terminal["status"] == "ok"
    finally:
        pool.stop(timeout_s=15.0)


def test_affinity_warm_bucket_routes_to_warm_slice(stub_pool):
    pool, _ = stub_pool
    assert pool.wait_idle(10.0)
    bucket = (63, 32, 16384)
    pool.router.remember("warm-scene", bucket)
    pool._warm[1].add(bucket)
    req = protocol.build_request({"op": "scene", "scene": "warm-scene"},
                                 "r-route-1")
    verdict, wid = pool._route(req)
    assert (verdict, wid) == ("dispatch", 1)
    # a cold bucket falls back to least-loaded (tie -> lowest id), and
    # dispatch marks the slice warm for its successors
    pool.router.remember("cold-scene", (7, 8, 1024))
    cold = protocol.build_request({"op": "scene", "scene": "cold-scene"},
                                  "r-route-2")
    verdict, wid = pool._route(cold)
    assert verdict == "dispatch" and wid == 0
    c = _admit(pool, "cold-scene", 990)
    assert c.done.wait(15.0)
    assert any((7, 8, 1024) in w for w in pool._warm)
    hits = pool.stats()["pool"]["scheduler"]
    assert hits["affinity_misses"] >= 1


def test_pool_streams_pin_to_owner_slice(stub_pool):
    pool, _ = stub_pool
    c1 = _admit(pool, "stream-a", 1, op="stream_chunk")
    assert c1.done.wait(15.0) and c1.terminal["status"] == "ok"
    assert c1.terminal["done"] is False
    owner = pool._stream_owner["stream-a"]
    req = protocol.build_request({"op": "stream_chunk", "scene": "stream-a"},
                                 "r-pin-2")
    verdict, wid = pool._route(req)
    assert (verdict, wid) == ("dispatch", owner)
    c2 = _admit(pool, "stream-a", 2, op="stream_end")
    assert c2.done.wait(15.0) and c2.terminal["status"] == "ok"
    assert c2.terminal["done"] is True


def test_pool_stream_on_retired_owner_answers_stream_lost(stub_pool):
    pool, _ = stub_pool
    c1 = _admit(pool, "stream-b", 1, op="stream_chunk")
    assert c1.done.wait(15.0) and c1.terminal["status"] == "ok"
    owner = pool._stream_owner["stream-b"]
    with pool._lock:
        pool._dead.add(owner)  # simulate a retired slice
    try:
        c2 = _admit(pool, "stream-b", 2, op="stream_chunk")
        assert c2.done.wait(15.0)
        assert "stream_lost" in c2.states()
        assert c2.terminal["status"] == "failed"
        assert c2.terminal["error_class"] == "stream_lost"
        assert "stream-b" not in pool._stream_owner
        # a restarted stream opens FRESH on a surviving slice
        c3 = _admit(pool, "stream-b", 3, op="stream_chunk")
        assert c3.done.wait(15.0) and c3.terminal["status"] == "ok"
        assert pool._stream_owner["stream-b"] != owner
    finally:
        with pool._lock:
            pool._dead.discard(owner)


@pytest.mark.slow  # ~2.5s of stub subprocess lifecycles; ci.sh's exit-12
# pool drill gates the same reroute contract on REAL workers out of tier-1
def test_crash_reroutes_victim_to_neighbor(tmp_path, monkeypatch):
    """A SIGKILL mid-request on slice 0: the victim reroutes to slice 1
    (warm neighbor) instead of waiting out the respawn; the neighbor's
    own work is untouched."""
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    pool, _ = _make_pool(tmp_path, serve_workers=2)
    pool.start()
    try:
        assert pool.wait_idle(10.0)
        crash = _admit(pool, "stub-crash", 1)
        neighbor = _admit(pool, "stub-ok", 2)
        assert crash.done.wait(30.0), "crash victim never answered"
        assert neighbor.done.wait(30.0)
        assert "worker_crash" in crash.states()
        assert crash.terminal["status"] == "ok"
        assert neighbor.terminal["status"] == "ok"
        st = pool.stats()
        # exactly one slice crashed; the victim's heal came from the pool
        assert st["worker"]["crashes"] == 1
        assert (st["pool"]["scheduler"]["crash_reroutes"] >= 1
                or st["counts"]["ok"] == 2)
    finally:
        pool.stop(timeout_s=15.0)


def test_recarve_with_inflight_drains_cleanly(tmp_path, monkeypatch):
    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    pool, _ = _make_pool(tmp_path, serve_workers=2)
    pool.start()
    try:
        slow = _admit(pool, "stub-slow", 1)
        time.sleep(0.3)  # let it dispatch
        out = pool.recarve(workers=1, timeout_s=30.0)
        # the in-flight request drained BEFORE the old slices stopped
        assert slow.done.wait(5.0) and slow.terminal["status"] == "ok"
        assert out["ok"] is True and out["workers"] == 1
        assert pool.workers == 1 and len(pool._sups) == 1
        assert pool.stats()["pool"]["scheduler"]["recarves"] == 1
        # the recarved pool serves
        c = _admit(pool, "stub-ok", 2)
        assert c.done.wait(15.0) and c.terminal["status"] == "ok"
        # pre-recarve history survives the carve (retired-slice baseline);
        # parent-side count booking trails the client answer, so poll
        deadline = time.monotonic() + 5.0
        while (pool.stats()["counts"]["ok"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert pool.stats()["counts"]["ok"] >= 2
        with pytest.raises(ValueError, match="contradicts"):
            pool.recarve(workers=2, carve="3x1")
        with pytest.raises(ValueError, match="recarve needs"):
            pool.recarve()
    finally:
        pool.stop(timeout_s=15.0)


def test_pool_merged_retrace_and_canary(stub_pool):
    pool, _ = stub_pool
    c = _admit(pool, "stub-ok", 1)
    assert c.done.wait(15.0)
    digest = pool.child_retrace()
    assert digest.get("compiles") == 0  # sum of zeros across slices
    assert set(digest.get("workers", {})) == {"0", "1"}
    probes = pool.run_canary(timeout_s=10.0)
    assert probes and probes[0]["digest"]["plane"] == "aaaaaaaa"


# ---------------------------------------------------------------------------
# stream loss across a worker crash (supervisor-level, stub)
# ---------------------------------------------------------------------------


def _submit_q(queue, scene, i, *, op="scene", **kw):
    client = _Client()
    req = protocol.build_request({"op": op, "scene": scene, **kw},
                                 f"s-{i:06d}")
    req.send = client.send
    queue.submit(req)
    return client


def test_supervisor_stream_lost_on_crash(tmp_path, monkeypatch):
    """An open stream session dies with its worker: the next op answers a
    TYPED stream_lost (status + failed result) instead of silently
    reopening at chunk 0 — and a restarted stream serves fresh."""
    from maskclustering_tpu.serve.supervisor import WorkerSupervisor

    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    cfg = _cfg(tmp_path)
    queue = AdmissionQueue(8)
    sup = WorkerSupervisor(cfg, queue, Router(cfg),
                           journal_dir=str(tmp_path / "journals"),
                           child_argv=[sys.executable, STUB],
                           start_timeout_s=15.0, poll_s=0.05)
    sup.start()
    try:
        opened = _submit_q(queue, "stream-x", 1, op="stream_chunk")
        assert opened.done.wait(15.0) and opened.terminal["status"] == "ok"
        assert sup.stats()["worker"]["open_streams"] == 1
        # the crash takes the child (and the device-resident session)
        crash = _submit_q(queue, "stub-crash", 2)
        assert crash.done.wait(30.0) and crash.terminal["status"] == "ok"
        assert sup.stats()["worker"]["lost_streams"] == 1
        lost = _submit_q(queue, "stream-x", 3, op="stream_chunk")
        assert lost.done.wait(15.0)
        assert "stream_lost" in lost.states()
        assert lost.terminal["status"] == "failed"
        assert lost.terminal["error_class"] == "stream_lost"
        # answered = cleared: the client restarts the stream from scratch
        fresh = _submit_q(queue, "stream-x", 4, op="stream_chunk")
        assert fresh.done.wait(15.0) and fresh.terminal["status"] == "ok"
        assert sup.stats()["worker"]["lost_streams"] == 0
    finally:
        sup.stop(timeout_s=10.0)


@pytest.mark.slow  # ~2.4s of stub subprocess lifecycles; the tier-1 twin
# (test_supervisor_stream_lost_on_crash) keeps the stream_lost contract hot
def test_stream_crash_mid_op_answers_stream_lost(tmp_path, monkeypatch):
    """The crash lands ON the stream op itself: never requeued across the
    crash (the wire chunk parameter is frames-per-chunk, not a cursor —
    a silent replay would corrupt the session), answered stream_lost."""
    from maskclustering_tpu.serve.supervisor import WorkerSupervisor

    monkeypatch.setenv("STUB_DIR", str(tmp_path))
    cfg = _cfg(tmp_path)
    queue = AdmissionQueue(8)
    sup = WorkerSupervisor(cfg, queue, Router(cfg),
                           journal_dir=str(tmp_path / "journals"),
                           child_argv=[sys.executable, STUB],
                           start_timeout_s=15.0, poll_s=0.05)
    sup.start()
    try:
        c = _submit_q(queue, "stub-crash", 1, op="stream_chunk")
        assert c.done.wait(30.0)
        assert "stream_lost" in c.states()
        assert c.terminal["status"] == "failed"
        assert c.terminal["error_class"] == "stream_lost"
        # the supervisor healed: the next request serves
        ok = _submit_q(queue, "stub-ok", 2)
        assert ok.done.wait(20.0) and ok.terminal["status"] == "ok"
    finally:
        sup.stop(timeout_s=10.0)


# ---------------------------------------------------------------------------
# observability plumbing
# ---------------------------------------------------------------------------


def test_telemetry_window_rows_carry_worker_map():
    from maskclustering_tpu.obs.telemetry import WindowAggregator

    agg = WindowAggregator(window_s=60.0)
    agg.record_request((63, 32, 16384), 0.05, tenant="a", worker=0)
    agg.record_request((63, 32, 16384), 0.06, tenant="a", worker=1)
    agg.record_request((63, 32, 16384), 0.07, tenant="b", worker=1)
    row = agg.roll()
    assert row["workers"] == {"0": 1, "1": 2}
    # single-worker daemons (worker=None) never grow the key
    agg.record_request((63, 32, 16384), 0.05)
    assert "workers" not in agg.roll()


def test_fold_telem_tags_spans_with_worker_id():
    from maskclustering_tpu import obs
    from maskclustering_tpu.obs.telemetry import fold_telem

    events = []
    orig = obs.record_span

    def capture(name, dur_s, **kw):
        events.append(kw)
        return orig(name, dur_s, **kw)

    obs.record_span, saved = capture, orig
    try:
        fold_telem({"kind": "telem", "v": 1, "seq": 1,
                    "metrics": {"counters": {}, "gauges": {}},
                    "spans": [{"name": "serve.request", "dur_s": 0.05,
                               "sync_s": 0.0, "depth": 0,
                               "ts": time.time(), "attrs": {"request": "r1"}}]},
                   worker_id=3)
    finally:
        obs.record_span = saved
    assert events and events[0]["worker_id"] == 3


def test_report_renders_pool_lines():
    from maskclustering_tpu.obs.report import render_pool

    class _Run:
        _counters = {"serve.pool.dispatched": 10,
                     "serve.pool.affinity_hits": 9,
                     "serve.pool.affinity_misses": 1,
                     "serve.pool.crash_reroutes": 1}
        telemetry_rows = [
            {"workers": {"0": 4, "1": 6},
             "tenants": {"heavy": {"requests": 7}, "light": {"requests": 3}}},
        ]

    lines = render_pool(_Run())
    text = "\n".join(lines)
    assert "affinity 9/10 warm (90%)" in text
    assert "worker 0: completions 4 (40%)" in text
    assert "worker 1: completions 6 (60%)" in text
    assert "heavy 7 (70%)" in text and "light 3 (30%)" in text

    class _Empty:
        _counters = {}
        telemetry_rows = []

    assert render_pool(_Empty()) == []  # single-worker reports unchanged


def test_top_renders_pool_panel():
    from maskclustering_tpu.obs.top import render_top

    stats = {
        "config": "pool", "uptime_s": 12.0,
        "queue": {"depth": 0, "capacity": 8},
        "worker": {"isolated": True, "pool": 2, "alive": 2, "spawns": 2,
                   "respawns": 0, "crashes": 0, "inflight_width": 0},
        "pool": {
            "carve": "2x4",
            "workers": [
                {"worker_id": 0, "pid": 11, "hb_age_s": 0.1, "retired": False,
                 "feed_depth": 0, "dispatched": 4, "warm_buckets": 3,
                 "consecutive_respawns": 0, "open_streams": 1,
                 "lost_streams": 0},
                {"worker_id": 1, "pid": 12, "hb_age_s": 0.2, "retired": True,
                 "feed_depth": 0, "dispatched": 6, "warm_buckets": 3,
                 "consecutive_respawns": 2, "open_streams": 0,
                 "lost_streams": 1}],
            "scheduler": {"dispatched": 10, "affinity_hits": 9,
                          "affinity_misses": 1, "crash_reroutes": 1,
                          "recarves": 0},
            "tenants": {"heavy": {"dispatched": 7, "weight": 3.0},
                        "light": {"dispatched": 3, "weight": 1.0,
                                  "quota": 4, "queued": 0}}},
    }
    out = render_top(stats)
    assert "pool: carve 2x4 | alive 2/2" in out
    assert "worker 0: up" in out and "worker 1: RETIRED" in out
    assert "affinity 9/10 warm (90%)" in out
    assert "dequeue share: heavy 7 (w=3.0) | light 3 (w=1.0, quota 4)" in out
    # an empty pool never reaches the panel (single-worker daemons)
    solo = dict(stats)
    solo.pop("pool")
    assert "pool: carve" not in render_top(solo)


def test_protocol_recarve_grammar():
    assert protocol.parse_line(
        '{"op": "recarve", "workers": 2, "carve": "2x4"}')["op"] == "recarve"
    with pytest.raises(protocol.ProtocolError):
        protocol.parse_line('{"op": "recarve", "workers": "two"}')
    with pytest.raises(protocol.ProtocolError):
        protocol.parse_line('{"op": "recarve", "carve": 4}')


# ---------------------------------------------------------------------------
# acceptance: a real 2-worker pool, end to end
# ---------------------------------------------------------------------------


@pytest.mark.slow  # two real subprocess warm-ups; ci.sh gates the same
# contract end to end via the rc-12 pool drill
def test_real_two_worker_pool_serves_warm_and_byte_identical(tmp_path):
    """The pool acceptance on real worker subprocesses: a 2-slice CPU
    carve serves a mixed-bucket, weighted-tenant burst with BOTH slices
    dispatching, artifact digests unanimous per scene across slices,
    zero post-warm compiles on every worker's digest, and the pool
    stats/scheduler plane populated."""
    from maskclustering_tpu.analysis import retrace_sanitizer
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    root = str(tmp_path / "data")
    scenes = {
        "pl-a": dict(num_boxes=3, num_frames=6, image_hw=(48, 64),
                     spacing=0.08, seed=11),
        "pl-b": dict(num_boxes=4, num_frames=6, image_hw=(48, 64),
                     spacing=0.07, seed=12),
    }
    for name, spec in scenes.items():
        write_scannet_layout(make_scene(**spec), root, name)

    cfg = _cfg(tmp_path, data_root=root, serve_workers=2,
               serve_tenants="heavy:3,light:1",
               aot_cache_dir=str(tmp_path / "aot"),
               worker_heartbeat_s=30.0, retry_backoff_s=0.1)
    prev_armed = retrace_sanitizer.enabled()
    retrace_sanitizer.arm(True)  # children inherit --retrace-sanitizer
    queue = AdmissionQueue(32)
    pool = WorkerPool(cfg, queue, Router(cfg),
                      journal_dir=str(tmp_path / "journals"),
                      warm_scenes=tuple(scenes), freeze_after_warm=True,
                      start_timeout_s=600.0, poll_s=0.1)
    try:
        pool.start()
        names = sorted(scenes)
        clients = [
            _admit(pool, names[i % 2], i,
                   tenant="heavy" if i % 4 else "light")
            for i in range(8)]
        for c in clients:
            assert c.done.wait(600.0), "request never answered"
            assert c.terminal["status"] == "ok", c.terminal
        # byte-identity across slices: whichever worker (and however
        # many times) served a scene, its artifact digest is unanimous
        by_scene = {}
        for i, c in enumerate(clients):
            dg = (c.terminal.get("digest") or {}).get("artifact")
            by_scene.setdefault(names[i % 2], set()).add(dg)
        for scene, digests in by_scene.items():
            assert len(digests) == 1 and None not in digests, (scene,
                                                               digests)
        stats = pool.stats()
        workers = stats["pool"]["workers"]
        assert len(workers) == 2
        assert all(w["alive"] for w in workers)
        assert all(w["dispatched"] for w in workers), \
            "a slice never dispatched — the scheduler is not spreading"
        sched = stats["pool"]["scheduler"]
        assert sched["dispatched"] >= 8
        assert stats["pool"]["tenants"]["heavy"]["dispatched"] == 6
        # zero post-warm compiles on EVERY worker's own digest
        retrace = pool.child_retrace()
        assert retrace.get("frozen") is True
        assert retrace.get("post_freeze", 0) == 0, retrace
        per = retrace.get("workers") or {}
        assert sorted(per) == ["0", "1"]
        for wid, dg in per.items():
            assert dg.get("post_freeze", 0) == 0, (wid, dg)
    finally:
        pool.stop(timeout_s=60.0)
        retrace_sanitizer.arm(prev_armed)
