import jax.numpy as jnp
import numpy as np

from maskclustering_tpu.ops.geometry import (
    invert_se3,
    project_points,
    transform_points,
    unproject_depth,
    voxel_downsample_np,
)


def random_pose(rng):
    # random rotation via QR
    q, _ = np.linalg.qr(rng.normal(size=(3, 3)))
    if np.linalg.det(q) < 0:
        q[:, 0] *= -1
    pose = np.eye(4)
    pose[:3, :3] = q
    pose[:3, 3] = rng.normal(size=3)
    return pose


def test_invert_se3_roundtrip():
    rng = np.random.default_rng(1)
    pose = random_pose(rng)
    inv = np.asarray(invert_se3(jnp.asarray(pose)))
    np.testing.assert_allclose(inv @ pose, np.eye(4), atol=1e-6)


def test_unproject_matches_manual():
    rng = np.random.default_rng(2)
    h, w = 12, 16
    depth = rng.uniform(0.5, 3.0, size=(h, w)).astype(np.float32)
    intr = np.array([[20.0, 0, 7.5], [0, 21.0, 5.5], [0, 0, 1]])
    pose = random_pose(rng)
    pts, valid = unproject_depth(jnp.asarray(depth), jnp.asarray(intr), jnp.asarray(pose))
    pts = np.asarray(pts)
    assert bool(np.all(np.asarray(valid)))
    u, v = 9, 4
    z = depth[v, u]
    cam = np.array([(u - 7.5) * z / 20.0, (v - 5.5) * z / 21.0, z])
    expect = pose[:3, :3] @ cam + pose[:3, 3]
    np.testing.assert_allclose(pts[v, u], expect, atol=1e-5)


def test_unproject_respects_trunc_and_zero():
    depth = np.array([[0.0, 5.0], [25.0, 1.0]], dtype=np.float32)
    intr = np.eye(3)
    _, valid = unproject_depth(jnp.asarray(depth), jnp.asarray(intr), jnp.asarray(np.eye(4)),
                               depth_trunc=20.0)
    np.testing.assert_array_equal(np.asarray(valid), [[False, True], [False, True]])


def test_project_unproject_roundtrip():
    rng = np.random.default_rng(3)
    h, w = 10, 14
    depth = rng.uniform(1.0, 4.0, size=(h, w)).astype(np.float32)
    intr = np.array([[30.0, 0, 6.5], [0, 30.0, 4.5], [0, 0, 1]])
    pose = random_pose(rng)
    pts, _ = unproject_depth(jnp.asarray(depth), jnp.asarray(intr), jnp.asarray(pose))
    uv, z = project_points(pts.reshape(-1, 3), jnp.asarray(intr), invert_se3(jnp.asarray(pose)))
    vv, uu = np.mgrid[0:h, 0:w]
    np.testing.assert_allclose(np.asarray(uv[:, 0]), uu.ravel(), atol=1e-3)
    np.testing.assert_allclose(np.asarray(uv[:, 1]), vv.ravel(), atol=1e-3)
    np.testing.assert_allclose(np.asarray(z), depth.ravel(), atol=1e-4)


def test_transform_points_matches_matmul():
    rng = np.random.default_rng(4)
    pose = random_pose(rng)
    pts = rng.normal(size=(17, 3))
    out = np.asarray(transform_points(jnp.asarray(pts), jnp.asarray(pose)))
    expect = pts @ pose[:3, :3].T + pose[:3, 3]
    np.testing.assert_allclose(out, expect, atol=1e-6)


def test_voxel_downsample_merges_within_voxel():
    pts = np.array([
        [0.001, 0.001, 0.001],
        [0.004, 0.004, 0.004],  # same 1cm voxel as above
        [0.5, 0.5, 0.5],
    ])
    out = voxel_downsample_np(pts, 0.01)
    assert out.shape == (2, 3)
    merged = out[np.argmin(np.linalg.norm(out, axis=1))]
    np.testing.assert_allclose(merged, [0.0025, 0.0025, 0.0025], atol=1e-9)
