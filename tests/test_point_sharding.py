"""Point-axis sharding (ISSUE 14): million-point scenes as a mesh knob.

Tier 1 pins the contract at the small shared fixture shapes: a 2-shard
point split of the fused step is byte-identical to the unsharded program
under BOTH counting encodings, the batch path's artifacts match the
single-chip pipeline with the (F, N) planes actually sharded, and the
knob threads through config validation, mesh construction, the AOT-cache
key and the perf-ledger attribution. The synthetic 1M-point end-to-end
run and the full (scene x frame x point) divisor-lattice sweep are
``slow``-marked (ROADMAP tier-1 wall note).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.parallel import (
    build_fused_step,
    fused_step_example_args,
    make_mesh,
    mesh_label,
    point_axis_size,
    point_spec,
)

# the SAME statics as tests/test_parallel.py's mesh tests, so the
# single-chip reference jits (module-level lru caches) are warm when this
# file runs after it in the suite
_CFG = PipelineConfig(
    config_name="meshtest", dataset="demo", distance_threshold=0.06,
    few_points_threshold=10, point_chunk=1024, frame_pad_multiple=8,
    mask_pad_multiple=8,
)


# ---------------------------------------------------------------------------
# knob plumbing (no compiles)
# ---------------------------------------------------------------------------


def test_point_shards_config_validation():
    with pytest.raises(ValueError, match="point_shards"):
        PipelineConfig(point_shards=0)
    # the point axis is the mesh's third axis, never a single-chip mode
    with pytest.raises(ValueError, match="mesh_shape"):
        PipelineConfig(point_shards=2)
    cfg = PipelineConfig(mesh_shape=(1, 2), point_shards=4)
    assert cfg.point_shards == 4
    # config transport round-trip (the isolated serving worker's seam)
    from maskclustering_tpu.config import config_from_json

    assert config_from_json(cfg.to_json()).point_shards == 4


def test_mesh_helpers_and_make_run_mesh():
    from maskclustering_tpu.parallel.batch import make_run_mesh

    m2 = make_mesh((2, 4))
    assert point_spec(m2) is None and point_axis_size(m2) == 1
    m3 = make_mesh((1, 2, 4))
    assert m3.axis_names == ("scene", "frame", "point")
    assert point_spec(m3) == "point" and point_axis_size(m3) == 4
    assert mesh_label((1, 2, 4)) == "1x2x4"
    with pytest.raises(ValueError):
        make_mesh((1, 1, 2, 4))  # no fourth axis in the ladder

    run_mesh = make_run_mesh(_CFG.replace(mesh_shape=(1, 4), point_shards=2))
    assert dict(run_mesh.shape) == {"scene": 1, "frame": 4, "point": 2}
    # point_shards == 1 keeps the historical 2-axis mesh (same programs,
    # same compile-cache keys)
    assert make_run_mesh(_CFG.replace(mesh_shape=(2, 4))).axis_names == \
        ("scene", "frame")


def test_batch_and_bucket_pads_divide_by_point_shards():
    from maskclustering_tpu.parallel.batch import batch_shapes
    from maskclustering_tpu.utils.compile_cache import scene_pads
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    t = to_scene_tensors(make_scene(num_boxes=3, num_frames=8,
                                    image_hw=(32, 48), spacing=0.08, seed=0))
    # a deliberately shard-hostile chunk: lcm(6, 4) = 12 must carry the pad
    cfg = _CFG.replace(point_chunk=6, mesh_shape=(1, 2), point_shards=4)
    mesh = make_mesh((1, 2, 4))
    _, n_pad = batch_shapes([t], cfg, mesh)
    assert n_pad % 4 == 0 and n_pad % 6 == 0
    # the ONE bucket vocabulary (serving router + retrace census) agrees
    _, n_bucket = scene_pads(cfg, t.num_frames, t.num_points)
    assert n_bucket % 4 == 0
    # pow2 shards divide the default chunk: historical pads unchanged
    base = _CFG.replace(mesh_shape=(1, 2))
    assert scene_pads(base.replace(point_shards=4), 8, 3000) == \
        scene_pads(base, 8, 3000)


def test_aot_cache_key_carries_point_shards():
    from maskclustering_tpu.parallel.sharded import fused_step_aot_key
    from maskclustering_tpu.utils import aot_cache

    args = fused_step_example_args(num_scenes=1, num_frames=8)
    k2 = fused_step_aot_key(make_mesh((1, 8)), _CFG, 7, args)
    k3 = fused_step_aot_key(make_mesh((1, 4, 2)), _CFG, 7, args)
    assert dict(k2.statics)["mesh"] == "1x8"
    assert dict(k3.statics)["mesh"] == "1x4x2"
    assert k2.digest() != k3.digest()
    # warm-start's config statics speak the same mesh vocabulary
    statics = aot_cache._cfg_statics(
        _CFG.replace(mesh_shape=(1, 4), point_shards=2))
    assert statics["mesh"] == "1x4x2"


def test_ledger_rows_and_regress_attribute_point_shards():
    from maskclustering_tpu.obs import ledger as led

    row = led.bench_row({"metric": "m", "value": 1.0, "point_shards": 4})
    assert row["point_shards"] == 4
    srow = led.serve_row({"value": 0.5, "point_shards": 2})
    assert srow["point_shards"] == 2
    ok, lines = led.check_regression(
        {"value": 1.2, "point_shards": 4}, {"value": 1.0})
    text = "\n".join(lines)
    assert "point_shards: 1 -> 4" in text and "knob flip" in text


# ---------------------------------------------------------------------------
# byte identity: 2-shard point split vs the unsharded program
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def example_args():
    return fused_step_example_args(num_scenes=2, num_frames=8)


@pytest.fixture(scope="module")
def base_out(example_args):
    """Unsharded-points reference: the (2, 4) mesh the parallel tests pin."""
    step = build_fused_step(make_mesh((2, 4)), _CFG, k_max=7)
    return jax.block_until_ready(step(*map(jnp.asarray, example_args)))


@pytest.mark.parametrize("count_dtype", ["bf16", "int8"])
def test_fused_step_two_shard_point_split_byte_identity(
        example_args, base_out, count_dtype):
    """The ISSUE acceptance at tier-1 scale: a 2-shard point split of the
    fused step returns byte-identical counts/planes/assignments under
    both counting encodings (partial-count psums are exact-integer sums
    in f32/s32 — order cannot move a byte), and the (F, N) residents are
    genuinely sharded over the point axis."""
    mesh = make_mesh((2, 2, 2))
    step = build_fused_step(mesh, _CFG.replace(count_dtype=count_dtype),
                            k_max=7)
    out = jax.block_until_ready(step(*map(jnp.asarray, example_args)))
    for name, a, b in zip(base_out._fields, base_out, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"{count_dtype}:{name}")
    # residency, not just math: the claim planes' N columns must shard
    for plane in (out.first_id, out.last_id, out.mask_of_point):
        assert "point" in (plane.sharding.spec or ()), plane.sharding


def test_mesh_batch_point_sharded_artifacts_and_drain(example_args):
    """End-to-end through the device postprocess: the point-sharded batch
    path emits byte-identical artifacts to the single-chip pipeline, and
    the emit-only drain never materializes an O(F*N) host buffer (the
    max-chunk gauge stays under one claim plane; nothing books to the
    host-pull stage; zero mid-pipeline host syncs on the fused path)."""
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.obs.metrics import registry
    from maskclustering_tpu.parallel.batch import (
        batch_shapes,
        cluster_scene_batch,
        make_run_mesh,
    )
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    cfg = _CFG.replace(mesh_shape=(1, 4), point_shards=2)
    tensors = [to_scene_tensors(make_scene(
        num_boxes=3, num_frames=8, image_hw=(32, 48), spacing=0.08, seed=s))
        for s in (0, 1)]
    mesh = make_run_mesh(cfg)
    reg = registry()
    reg.reset()
    objs = cluster_scene_batch(cfg, mesh, tensors, k_max=7)
    counters = reg.snapshot()["counters"]
    gauges = reg.snapshot()["gauges"]
    f_pad, n_pad = batch_shapes(tensors, cfg, mesh)
    plane_bytes = f_pad * n_pad * 2  # one (F, N) int16 plane
    assert counters.get("pipeline.host_sync", 0) == 0
    assert "d2h.bytes.postprocess" not in counters  # no host-path pull
    assert 0 < gauges["post.drain.max_chunk_bytes"] < plane_bytes
    for t, om in zip(tensors, objs):
        ref = run_scene(t, cfg, k_max=7).objects
        assert om.num_points == ref.num_points
        assert len(om.point_ids_list) == len(ref.point_ids_list)
        for a, b in zip(om.point_ids_list, ref.point_ids_list):
            np.testing.assert_array_equal(a, b)
        assert om.mask_list == ref.mask_list


def test_point_mesh_census_is_psum_shaped(fused_lattice_aot):
    """The canonical point-sharded lattice cell (1x2x4, shared session
    AOT sweep) moves partial-count psums + small gathers — bounded by the
    IR gate's envelope — and NO all-to-all (the reshard pathology the
    estimate-spacing fix removed)."""
    from maskclustering_tpu.analysis.ir_checks import (
        POINT_SHARDED_ICI_BUDGET_BYTES,
    )

    row = fused_lattice_aot[(1, 2, 4)]
    census = row["collectives"]
    assert "all-to-all" not in census, census
    assert census.get("all-reduce", {}).get("count", 0) > 0  # the psums
    assert 0 < row["ici_bytes"] <= POINT_SHARDED_ICI_BUDGET_BYTES


# ---------------------------------------------------------------------------
# slow tier: the full 3-axis lattice + the 1M-point acceptance scene
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_point_lattice_sweep():
    """Every (scene, frame, point) factorization of 8 with a non-trivial
    point axis executes byte-identically to the unsharded reference.
    Four scenes so the deepest scene axis (4) divides the batch."""
    args = fused_step_example_args(num_scenes=4, num_frames=8)
    base = jax.block_until_ready(
        build_fused_step(make_mesh((2, 4)), _CFG, k_max=7)(
            *map(jnp.asarray, args)))
    for shape in ((1, 1, 8), (1, 2, 4), (1, 4, 2), (2, 2, 2), (2, 1, 4),
                  (4, 1, 2)):
        step = build_fused_step(make_mesh(shape), _CFG, k_max=7)
        out = jax.block_until_ready(step(*map(jnp.asarray, args)))
        for name, a, b in zip(base._fields, base, out):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"{shape}:{name}")


@pytest.mark.slow
def test_million_point_scene_completes_point_sharded():
    """ISSUE acceptance: a synthetic 1M-point scene completes on a CPU
    virtual-device mesh with point_shards >= 4 — artifacts land, the
    claim planes stay in HBM (no host-path pull booked, zero mid-pipeline
    host syncs), and the largest single drain materialization stays far
    under one (F, N) plane (per-shard chunked drain, counter-pinned).

    Cloud density stays at the honest ~0.05 spacing with the default
    split eps (0.1 = 2x spacing — eps AT the spacing fragments instances
    into thousands of DBSCAN groups): a large room supplies ~115k real
    points and tiling to 2^20 multiplies in-eps occupancy ~9x, so the
    neighbor window gets one knob notch of headroom (512; the capacity
    posture the knob exists for).
    """
    from maskclustering_tpu.obs.metrics import registry
    from maskclustering_tpu.parallel.batch import (
        batch_shapes,
        cluster_scene_batch,
        make_run_mesh,
    )
    from maskclustering_tpu.utils.synthetic import (
        make_scene,
        resize_scene_points,
        to_scene_tensors,
    )

    n = 1 << 20  # 1,048,576 points
    cfg = PipelineConfig(
        config_name="million", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=8192, frame_pad_multiple=8,
        post_neighbor_cap=512, mesh_shape=(1, 2), point_shards=4,
    )
    scene = make_scene(num_boxes=12, num_frames=8, image_hw=(48, 64),
                       spacing=0.05, room_half=8.0, seed=0)
    t = to_scene_tensors(scene)
    assert t.num_points > 80_000  # honest density before tiling
    t.scene_points = resize_scene_points(t.scene_points, n)
    mesh = make_run_mesh(cfg)
    assert point_axis_size(mesh) == 4

    reg = registry()
    reg.reset()
    objs = cluster_scene_batch(cfg, mesh, [t])
    counters = reg.snapshot()["counters"]
    gauges = reg.snapshot()["gauges"]

    assert len(objs) == 1
    assert objs[0].num_points == n
    assert len(objs[0].point_ids_list) >= 1  # found real instances
    for pids in objs[0].point_ids_list:
        assert pids.size and int(pids.max()) < n

    f_pad, n_pad = batch_shapes([t], cfg, mesh)
    assert n_pad == n  # 2^20 is already chunk- and shard-aligned
    plane_bytes = f_pad * n_pad * 2  # one (F, N) int16 plane = 16 MB
    # emit-only drain contract at 1M points: no (F, N)-sized host buffer
    assert counters.get("pipeline.host_sync", 0) == 0
    assert "d2h.bytes.postprocess" not in counters
    assert 0 < gauges["post.drain.max_chunk_bytes"] < plane_bytes
    assert counters["d2h.bytes.post.drain"] < plane_bytes
