"""Reference-semantics oracles for A/B testing the tensorized stages.

Set/dict/bincount implementations that follow the reference algorithms
literally (graph/construction.py, graph/iterative_clustering.py), used to
verify that the dense MXU formulations in maskclustering_tpu.models produce
identical decisions. Deliberately slow and simple.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

import networkx as nx
import numpy as np


def oracle_graph_stats(
    point_in_mask: np.ndarray,  # (F, N) int zeroed at boundary
    mask_sets: Dict[Tuple[int, int], Set[int]],  # (frame, id) -> point ids (incl boundary)
    boundary: Set[int],
    mask_visible_threshold: float,
    contained_threshold: float,
    undersegment_filter_threshold: float,
    big_mask_point_count: int = 500,
):
    """Reference construction.py:80-171 semantics on explicit sets."""
    masks = sorted(mask_sets.keys())  # (frame, id) ascending — matches table order
    idx = {mk: i for i, mk in enumerate(masks)}
    f_num = point_in_mask.shape[0]
    m_num = len(masks)
    visible = np.zeros((m_num, f_num), dtype=bool)
    contained = np.zeros((m_num, m_num), dtype=bool)
    undersegment = np.zeros(m_num, dtype=bool)

    for mi, (mf, mid) in enumerate(masks):
        valid_pts = sorted(mask_sets[(mf, mid)] - boundary)
        info = point_in_mask[:, valid_pts]  # (F, P)
        n_tot = len(valid_pts)
        visible_num = 0
        split_num = 0
        for j in range(f_num):
            if n_tot == 0:
                continue
            col = info[j]
            n_vis = int(np.sum(col > 0))
            if n_vis == 0:
                continue
            if (n_vis / n_tot) < mask_visible_threshold and n_vis < big_mask_point_count:
                continue
            visible_num += 1
            counts = np.bincount(col[col > 0])
            top = int(np.argmax(counts))
            if counts[top] / n_vis > contained_threshold:
                visible[mi, j] = True
                contained[mi, idx[(j, top)]] = True
            else:
                split_num += 1
        if visible_num == 0 or split_num / visible_num > undersegment_filter_threshold:
            undersegment[mi] = True

    # undo undersegmented observers (construction.py:163-169)
    for mi in np.nonzero(undersegment)[0]:
        mf, _ = masks[mi]
        supporters = np.nonzero(contained[:, mi])[0]
        contained[:, mi] = False
        visible[supporters, mf] = False

    return masks, visible, contained, undersegment


def oracle_observer_thresholds(visible: np.ndarray) -> List[float]:
    """Reference construction.py:80-96."""
    v = visible.astype(np.float64)
    return oracle_observer_thresholds_from_counts((v @ v.T).flatten())


def oracle_observer_thresholds_from_counts(counts: np.ndarray) -> List[float]:
    """Reference construction.py:80-96 over an explicit count multiset."""
    flat = np.asarray(counts, np.float64)
    flat = flat[flat > 0]
    out = []
    for percentile in range(95, -5, -5):
        val = float(np.percentile(flat, percentile)) if len(flat) else 0.0
        if val <= 1:
            if percentile < 50:
                break
            val = 1.0
        out.append(val)
    return out


def oracle_clustering(
    visible: np.ndarray,  # (M, F) bool — only active masks' rows meaningful
    contained: np.ndarray,  # (M, M) bool
    active: np.ndarray,  # (M,) bool
    thresholds: Sequence[float],
    view_consensus_threshold: float,
) -> np.ndarray:
    """Reference iterative_clustering.py via explicit node lists + networkx.

    Returns a partition label per mask (label = min member index), inactive
    masks keep their own index.
    """
    nodes: List[Dict] = [
        {"members": [i], "visible": visible[i].copy(), "contained": contained[i].copy()}
        for i in np.nonzero(active)[0]
    ]
    for thr in thresholds:
        if not nodes:
            break
        v = np.stack([n["visible"] for n in nodes]).astype(np.float64)
        c = np.stack([n["contained"] for n in nodes]).astype(np.float64)
        observers = v @ v.T
        supporters = c @ c.T
        rate = supporters / (observers + 1e-7)
        disconnect = np.eye(len(nodes), dtype=bool) | (observers < thr)
        adj = (rate >= view_consensus_threshold) & ~disconnect
        graph = nx.from_numpy_array(adj)
        new_nodes = []
        for comp in nx.connected_components(graph):
            members = sorted(m for ni in comp for m in nodes[ni]["members"])
            new_nodes.append({
                "members": members,
                "visible": np.any([nodes[ni]["visible"] for ni in comp], axis=0),
                "contained": np.any([nodes[ni]["contained"] for ni in comp], axis=0),
            })
        nodes = new_nodes

    labels = np.arange(visible.shape[0])
    for n in nodes:
        labels[n["members"]] = min(n["members"])
    return labels
