"""mct-serve: the long-lived scene-serving daemon (ISSUE-11 acceptance).

Unit tier: protocol validation, bounded-admission typed rejects, router
classification against the committed surface baseline, per-request
journal round-trips, serve ledger rows + the --regress fence, and the
Serving report section.

Integration tier (one module-scoped daemon over the tier-1 suite's two
warm tiny shape buckets — scene A is byte-identical to test_executor /
test_retrace's seed-40 scene, so in a full run its programs are
process-warm): the concurrent mixed-bucket soak with byte-identical
artifacts vs one-shot run.py and ZERO post-warm compiles under the
frozen retrace sanitizer, FaultPlan healing without neighbor poisoning,
admission-edge behavior (queue-full, deadline expiry in queue and
mid-device-phase), SIGTERM drain with a request in flight, and the
second-daemon warm start pinned via retrace.* counters. A larger
load_gen-driven soak is slow-marked; the cross-process warm start lives
in scripts/ci.sh's serve smoke gate.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from maskclustering_tpu import obs
from maskclustering_tpu.config import load_config
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue, QueueFullReject
from maskclustering_tpu.serve.client import ServeClient
from maskclustering_tpu.serve.daemon import ServeDaemon
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.utils import faults
from maskclustering_tpu.utils.synthetic import (make_scene, to_scene_tensors,
                                                write_scannet_layout)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the two warm tiny buckets (shared shapes: test_executor scene0 == A)
SPEC_A = {"num_boxes": 3, "num_frames": 10, "image_hw": (60, 80),
          "spacing": 0.06, "seed": 40}
SPEC_B = {"num_boxes": 4, "num_frames": 10, "image_hw": (60, 80),
          "spacing": 0.05, "seed": 50}
SCENE_A, SCENE_B = "scene0000_00", "scene0001_00"


def _cfg(data_root, **kw):
    base = dict(data_root=data_root, config_name="served", step=1,
                distance_threshold=0.05, mask_pad_multiple=32)
    base.update(kw)
    return load_config("scannet").replace(**base)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.set_plan(None)
    faults.clear_stop()
    yield
    faults.set_plan(None)
    faults.clear_stop()


# ---------------------------------------------------------------------------
# units: protocol
# ---------------------------------------------------------------------------


def test_protocol_parse_validate_and_build():
    doc = protocol.parse_line(
        '{"op": "scene", "scene": "s1", "deadline_s": 2.5, "tag": "t",'
        ' "synthetic": {"num_boxes": 2, "seed": 3}}')
    req = protocol.build_request(doc, "r-000007")
    assert (req.scene, req.tag, req.deadline_s) == ("s1", "t", 2.5)
    assert req.synthetic == {"num_boxes": 2, "seed": 3}
    assert not req.expired() and 0 < req.remaining_s() <= 2.5
    nodl = protocol.build_request(protocol.parse_line(
        '{"op": "scene", "scene": "s2"}'), "r-000008")
    assert not nodl.expired() and nodl.remaining_s() > 1e9

    for bad in ('not json', '[]', '{"op": "nope"}',
                '{"op": "scene"}', '{"op": "scene", "scene": ""}',
                '{"op": "scene", "scene": "a/b"}',
                '{"op": "scene", "scene": "a", "deadline_s": -1}',
                '{"op": "scene", "scene": "a", "synthetic": {"bogus": 1}}',
                '{"op": "scene", "scene": "a", "resume": "yes"}',
                # supervisor-internal field: a client must not pre-degrade
                # (or type-crash) its own request
                '{"op": "scene", "scene": "a", "crashes": 1}',
                '{"op": "scene", "scene": "a", "crashes": "abc"}'):
        with pytest.raises(protocol.ProtocolError):
            protocol.parse_line(bad)

    ev = protocol.result(req, "ok", seconds=1.25)
    assert (ev["kind"], ev["id"], ev["tag"], ev["status"]) == \
        ("result", "r-000007", "t", "ok")
    line = protocol.encode(ev)
    assert line.endswith(b"\n") and json.loads(line) == ev
    rej = protocol.reject("queue_full", detail="4/4", tag="t2")
    assert rej["reason"] == "queue_full" and rej["tag"] == "t2"


def test_admission_queue_bounded_and_typed():
    q = AdmissionQueue(2)
    reqs = [protocol.build_request(
        protocol.parse_line(json.dumps({"op": "scene", "scene": f"s{i}"})),
        f"r-{i:06d}") for i in range(3)]
    assert q.submit(reqs[0]) == 1
    assert q.submit(reqs[1]) == 2
    with pytest.raises(QueueFullReject) as ei:
        q.submit(reqs[2])
    assert ei.value.capacity == 2
    assert q.high_water == 2 and q.admitted == 2
    assert q.next(0.01).id == "r-000000"
    assert q.submit(reqs[2]) == 2  # capacity freed by the pop
    assert [r.id for r in q.drain()] == ["r-000001", "r-000002"]
    assert q.next(0.01) is None
    with pytest.raises(ValueError):
        AdmissionQueue(0)


def test_router_classifies_via_scene_bucket_and_fits_baseline(tmp_path):
    from maskclustering_tpu.utils.compile_cache import scene_bucket

    cfg = _cfg(str(tmp_path))
    baseline = os.path.join(REPO_ROOT, "compile_surface_baseline.json")
    router = Router(cfg, baseline_path=baseline)
    assert router.vocabulary, "committed baseline must carry a workload"

    t = to_scene_tensors(make_scene(**SPEC_A))
    bucket = router.classify_tensors(t)
    assert bucket == scene_bucket(cfg, t.num_frames, t.num_points,
                                  int(np.max(t.segmentations)))
    assert not router.is_warm(bucket)
    assert router.note_served(bucket) is True
    assert router.note_served(bucket) is False  # repeat = already warm
    assert router.is_warm(bucket)

    # baseline-driven warm-up scenes land EXACTLY on the baseline's bucket
    # coordinates (classification only — execution is the daemon's job)
    workload = list(router.warmup_workload())
    expected = {router.classify(e["frames"], e["points"], e["max_id"])
                for e in router.vocabulary}
    assert workload, "baseline workload produced no warm-up scenes"
    got = {router.classify_tensors(t) for _, t in workload}
    assert got == expected
    # dedup: the baseline's deliberate A-repeat entry emits once
    assert len(workload) == len(expected)


def test_run_journal_per_request_roundtrip(tmp_path):
    path = str(tmp_path / "serve_journal.jsonl")
    for rid, seq, status in (("r-000001", "sceneX", "ok"),
                             ("r-000002", "sceneX", "failed")):
        j = faults.RunJournal(path, "served", request_id=rid)
        j.begin_run()
        j.attempt(seq, 1, 0)
        j.outcome(seq, status, attempt=1, rung=0,
                  error="boom" if status == "failed" else "")
        j.end_run()
        j.close()
    # one shared path, two requests, zero clobbering: per-request replay
    r1 = faults.replay_journal(path, config="served", request="r-000001")
    r2 = faults.replay_journal(path, config="served", request="r-000002")
    assert r1["sceneX"]["status"] == "ok"
    assert r2["sceneX"]["status"] == "failed"
    assert faults.resume_done(path, config="served",
                              request="r-000001") == {"sceneX"}
    assert faults.resume_done(path, config="served",
                              request="r-000002") == set()
    # a request-free read still round-trips (last outcome wins), so the
    # one-shot replay tooling keeps working on daemon journals
    merged = faults.replay_journal(path, config="served")
    assert merged["sceneX"]["status"] == "failed"
    # and a request-free journal is untouched by the new field
    solo = str(tmp_path / "solo.jsonl")
    j = faults.RunJournal(solo, "served")
    j.outcome("sceneY", "ok", attempt=1)
    j.close()
    assert faults.resume_done(solo, config="served") == {"sceneY"}
    assert "request" not in faults.read_journal(solo)[0]


def test_serve_ledger_row_and_regress_fence(tmp_path):
    from maskclustering_tpu.obs import ledger as led
    from maskclustering_tpu.obs.report import _regress_eval

    path = str(tmp_path / "ledger.jsonl")
    bench_metric = "mask-clustering s/scene"
    led.append_row(path, {"tool": "bench", "metric": bench_metric,
                          "value": 3.2, "unit": "s/scene"})
    verdict = {"metric": "serve s/request (p50 of 8 synthetic requests)",
               "value": 1.5, "p95_s": 2.0, "throughput_rps": 2.5,
               "requests": 8, "concurrency": 4,
               "retrace_post_freeze": 0}
    row = led.serve_row(verdict)
    assert row["tool"] == "serve" and row["unit"] == "s/request"
    assert led.append_row(path, row)

    # a bench baseline gates the BENCH row even though the serve row is
    # newer — no cross-metric misattribution
    base = str(tmp_path / "base.json")
    with open(base, "w") as f:
        json.dump({"metric": bench_metric, "value": 3.0,
                   "unit": "s/scene"}, f)
    rc, lines, record = _regress_eval(path, base, 0.15)
    assert rc == 0 and record["current"]["tool"] == "bench"

    # a metric-less bench-style baseline must STILL not pick the serve row
    with open(base, "w") as f:
        json.dump({"value": 3.0}, f)
    rc, lines, record = _regress_eval(path, base, 0.15)
    assert record["current"]["tool"] == "bench"

    # a serve baseline gates serve rows (50% regression -> exit 2)
    led.append_row(path, led.serve_row(dict(verdict, value=2.6)))
    serve_base = str(tmp_path / "serve_base.jsonl")
    led.append_row(serve_base, led.serve_row(verdict))
    rc, lines, record = _regress_eval(path, serve_base, 0.15)
    assert rc == 2 and record["current"]["tool"] == "serve"
    assert record["baseline"]["tool"] == "serve"


def test_render_serving_section(tmp_path):
    from maskclustering_tpu.obs.report import RunData, render_report

    events = str(tmp_path / "serve_events.jsonl")
    obs.configure(events, truncate=True, meta={"tool": "serve"})
    try:
        for _ in range(4):
            obs.count("serve.requests")
            obs.count("serve.requests_ok")
            with obs.span("serve.request"):
                time.sleep(0.002)
        obs.count("serve.requests")
        obs.count("serve.requests_failed")
        obs.count("serve.admission.admitted", 5)
        obs.count("serve.admission.rejects.queue_full", 2)
        obs.count("retrace.post_freeze_compiles", 1)
        obs.count("serve.worker_crashes", 1)
        obs.count("serve.worker_respawns", 2)
        obs.count("serve.requests_requeued", 1)
        obs.count("aot_cache.restored", 3)
        obs.count("aot_cache.hits", 4)
        obs.count("aot_cache.invalidated", 1)
        obs.gauge("serve.queue_depth_high_water", 3)
        obs.gauge("serve.warm_buckets", 2)
        obs.flush_metrics()
    finally:
        obs.disable()
    text = render_report(RunData(events))
    assert "== serving (mct-serve) ==" in text
    assert "requests 5" in text and "ok 4" in text and "failed 1" in text
    assert "queue high-water 3" in text
    assert "queue_full x2" in text
    assert "request latency: p50" in text
    assert "warm buckets 2" in text
    assert "compiles post-warm-up: 1 [VIOLATION" in text
    # crash containment + AOT cache digests (PR-12)
    assert "worker crashes 1 | respawns 2 | requests requeued 1" in text
    assert "aot cache: 3 restored | 4 hit(s)" in text
    assert "1 invalidated" in text
    # a serve-free events file renders no Serving section
    other = str(tmp_path / "plain.jsonl")
    obs.configure(other, truncate=True)
    try:
        obs.count("run.scenes_ok")
        obs.flush_metrics()
    finally:
        obs.disable()
    assert "== serving (mct-serve) ==" not in render_report(RunData(other))


# ---------------------------------------------------------------------------
# integration: one warm daemon, the soak, the edges, the warm start
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def rs():
    """Module-armed retrace sanitizer (the daemon freezes it post-warm-up)."""
    from maskclustering_tpu.analysis import retrace_sanitizer

    retrace_sanitizer.reset()
    retrace_sanitizer.install()
    yield retrace_sanitizer
    retrace_sanitizer.uninstall()
    retrace_sanitizer.reset()


@pytest.fixture(scope="module")
def serve_env(tmp_path_factory, rs):
    """Disk scenes + a one-shot reference run + a warm serving daemon.

    The one-shot pass (config "oneshot") is the byte-identity reference;
    the daemon (config "served") starts with both buckets as warm scenes,
    after which the sanitizer freezes — from there, every compile is a
    post-warm violation.
    """
    from maskclustering_tpu.run import run_pipeline

    root = str(tmp_path_factory.mktemp("serve_data"))
    for seq, spec in ((SCENE_A, SPEC_A), (SCENE_B, SPEC_B)):
        write_scannet_layout(make_scene(**spec), root, seq)

    ref = run_pipeline(_cfg(root, config_name="oneshot"), [SCENE_A, SCENE_B],
                       steps=("cluster",), resume=False, journal=False,
                       ledger=False)
    assert [s.status for s in ref.scenes] == ["ok", "ok"]

    sock = os.path.join(root, "mct.sock")
    daemon = ServeDaemon(
        _cfg(root), socket_path=sock, capacity=8,
        journal_dir=os.path.join(root, "journals"),
        warm_scenes=(SCENE_A, SCENE_B), freeze_after_warm=True)
    daemon.start()
    assert rs.digest()["frozen"], "daemon must freeze the sanitizer post-warm"
    try:
        yield {"root": root, "daemon": daemon, "sock": sock, "rs": rs}
    finally:
        daemon.request_stop()
        daemon.shutdown()


def _wait(predicate, timeout_s: float = 30.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError("condition not reached within the poll budget")


def _request_thread(sock, scene, spec, out, i, **kw):
    def run():
        with ServeClient(sock, timeout_s=300.0) as c:
            terminal, statuses, latency = c.run_scene(
                scene, synthetic=dict(spec, image_hw=list(spec["image_hw"])),
                tag=f"t{i}", **kw)
            out[i] = (terminal, statuses, latency)

    t = threading.Thread(target=run, daemon=True, name=f"soak-client-{i}")
    t.start()
    return t


def test_soak_concurrent_mixed_buckets_byte_identical(serve_env):
    """ISSUE-11 acceptance: >= 8 concurrent mixed-bucket requests all
    complete, artifacts byte-identical to one-shot run.py, ZERO post-warm
    compiles under the frozen retrace sanitizer, and an injected FaultPlan
    fault heals via the supervisor without poisoning neighbor requests."""
    rs = serve_env["rs"]
    sock = serve_env["sock"]
    keys_before = rs.snapshot_keys()
    viol_before = len(rs.violations())

    # one scripted flaky device fault: the FIRST scene-B request retries
    # once and heals; every other request must be untouched (rung 0,
    # attempts 1)
    faults.set_plan(faults.FaultPlan.from_spec(f"flaky:{SCENE_B}:1"))
    out = {}
    threads = []
    specs = [(SCENE_A, SPEC_A), (SCENE_B, SPEC_B)]
    try:
        for i in range(8):
            scene, spec = specs[i % 2]
            kw = {"deadline_s": 240.0} if i == 0 else {}
            threads.append(_request_thread(sock, scene, spec, out, i, **kw))
        for t in threads:
            t.join(300.0)
            assert not t.is_alive(), "a soak client wedged"
    finally:
        faults.set_plan(None)

    assert sorted(out) == list(range(8))
    terminals = {i: out[i][0] for i in out}
    assert all(tv["kind"] == "result" and tv["status"] == "ok"
               for tv in terminals.values()), terminals
    # the flaky fault healed on a retry somewhere in the B lane...
    assert max(tv["attempts"] for tv in terminals.values()) == 2
    # ...and poisoned nobody: no request degraded a rung, exactly one
    # request retried (flaky is retryable-class: no ladder involvement)
    assert all(tv["rung"] == 0 for tv in terminals.values())
    assert sum(1 for tv in terminals.values() if tv["attempts"] > 1) == 1
    # every request ran warm: no scene bucket was new to the process
    assert all(tv["buckets_new"] == 0 for tv in terminals.values())

    # zero post-warm compiles: the frozen sanitizer saw no new keys and
    # booked no violations across 8 concurrent mixed-bucket requests
    assert rs.snapshot_keys() == keys_before
    assert len(rs.violations()) == viol_before

    # byte-identical artifacts vs the one-shot run.py pass
    pred = os.path.join(serve_env["root"], "prediction")
    for seq in (SCENE_A, SCENE_B):
        a = np.load(os.path.join(pred, "served_class_agnostic", f"{seq}.npz"))
        b = np.load(os.path.join(pred, "oneshot_class_agnostic", f"{seq}.npz"))
        assert set(a.files) == set(b.files)
        for key in a.files:
            np.testing.assert_array_equal(a[key], b[key])

    # per-request journals replay the per-request outcome
    stats = serve_env["daemon"].stats()
    assert stats["counts"]["ok"] >= 8
    journals = os.listdir(os.path.join(serve_env["root"], "journals"))
    assert len(journals) >= 8
    rid = terminals[0]["id"]
    replay = faults.replay_journal(
        os.path.join(serve_env["root"], "journals", f"{rid}.jsonl"),
        request=rid)
    assert replay[SCENE_A]["status"] == "ok"


def test_admission_edges_queue_full_and_queue_deadline(serve_env, tmp_path):
    """Queue-full typed reject at the wire, and a deadline that expires
    while queued answering a typed deadline reject at dequeue. The
    blocking requests use a watchdog-free 2s device-phase stall — they
    hold the worker and then answer ok, so no retry/degradation noise."""
    root = serve_env["root"]
    sock = os.path.join(str(tmp_path), "edge.sock")
    daemon = ServeDaemon(
        _cfg(root, config_name="edge"), socket_path=sock, capacity=1,
        journal_dir=os.path.join(str(tmp_path), "journals"),
        freeze_after_warm=False)
    daemon.start()
    syn = dict(SPEC_A, image_hw=list(SPEC_A["image_hw"]))
    try:
        # queue-full: a stalled request holds the worker, capacity-1 queue
        # holds one more, the third answers a typed queue_full reject.
        # Sync on the stall entry's consumption: it decrements exactly
        # when the worker enters r1's device phase (no stale-idle races)
        plan = faults.FaultPlan.from_spec(
            "stall:edge-block.device:1", stall_s=2.0)
        faults.set_plan(plan)
        out = {}
        t1 = _request_thread(sock, "edge-block", SPEC_A, out, 1)
        _wait(lambda: plan.entries[0].remaining == 0)  # r1 mid-device-phase
        t2 = _request_thread(sock, "edge-q", SPEC_A, out, 2)
        _wait(lambda: daemon.queue.depth() == 1)  # r2 queued behind r1
        with ServeClient(sock, timeout_s=30.0) as c3:
            rej = c3.request_scene("edge-q2", synthetic=syn)
        assert rej["kind"] == "reject" and rej["reason"] == "queue_full"
        assert "retry" in rej["detail"]
        t1.join(60.0)
        t2.join(60.0)
        assert out[1][0]["status"] == "ok" and out[1][0]["attempts"] == 1
        assert out[2][0]["status"] == "ok"

        # deadline expiry IN QUEUE: a 0.5s budget parked behind a 2s
        # stall answers a typed deadline reject at dequeue — no device
        # work is burned on a result nobody can use
        plan2 = faults.FaultPlan.from_spec(
            "stall:edge-block2.device:1", stall_s=2.0)
        faults.set_plan(plan2)
        out2 = {}
        tb = _request_thread(sock, "edge-block2", SPEC_A, out2, 1)
        _wait(lambda: plan2.entries[0].remaining == 0)  # mid-device-phase
        with ServeClient(sock, timeout_s=60.0) as c:
            terminal, _, _ = c.run_scene("edge-dl", synthetic=syn,
                                         deadline_s=0.5)
        assert terminal["kind"] == "reject" and \
            terminal["reason"] == "deadline", terminal
        assert "expired" in terminal["detail"]
        tb.join(60.0)
        assert out2[1][0]["status"] == "ok"
    finally:
        faults.set_plan(None)
        daemon.request_stop()
        daemon.shutdown()
    # the edge daemon's stats carried the accounting
    assert daemon.stats()["counts"]["ok"] >= 3


@pytest.mark.slow
def test_deadline_mid_device_phase_watchdog_degrade_and_answer(serve_env,
                                                               tmp_path):
    """Deadline/watchdog expiry MID-DEVICE-PHASE: a scripted 60s stall
    trips the config's 8s device watchdog (DeviceStallError in budget),
    the per-request ladder degrades one rung, and the retried attempt —
    stall consumed — still answers ok. A second request whose DEADLINE is
    tighter than the watchdog instead answers a typed ``deadline`` result
    once its budget is gone (no retry past the deadline).

    The 8s watchdog follows the PR-5 budget note: a warm tiny-bucket
    device phase is ~1s of CPU dispatch but spikes several-fold on a
    loaded box (4.2s observed), so only the STALLED attempts may trip it
    — and the watchdog wait IS this test's wall cost (~13s, mostly the
    deliberate stall), which is why it rides the slow tier per the
    ROADMAP wall note; the watchdog/deadline mechanics stay tier-1 via
    test_faults' sub-second units and the admission-edge cases above."""
    root = serve_env["root"]
    sock = os.path.join(str(tmp_path), "mid.sock")
    daemon = ServeDaemon(
        _cfg(root, config_name="mid", watchdog_device_s=8.0),
        socket_path=sock, capacity=2,
        journal_dir=os.path.join(str(tmp_path), "journals"),
        freeze_after_warm=False)
    daemon.start()
    syn = dict(SPEC_A, image_hw=list(SPEC_A["image_hw"]))
    try:
        faults.set_plan(faults.FaultPlan.from_spec(
            "stall:mid-heal.device:1", stall_s=60.0))
        with ServeClient(sock, timeout_s=120.0) as c:
            terminal, statuses, _ = c.run_scene("mid-heal", synthetic=syn,
                                                deadline_s=90.0)
        assert terminal["status"] == "ok", terminal
        assert terminal["attempts"] == 2 and terminal["rung"] == 1
        assert any(s.get("state") == "degraded" for s in statuses)
        assert any(s.get("state") == "retrying" for s in statuses)

        # deadline tighter than the watchdog: the stall is aborted at the
        # ~3s remaining budget, the budget is then gone, and the request
        # answers `deadline` with device-class attribution instead of
        # burning retries past its deadline
        faults.set_plan(faults.FaultPlan.from_spec(
            "stall:mid-dl.device:1", stall_s=60.0))
        with ServeClient(sock, timeout_s=60.0) as c:
            terminal, _, _ = c.run_scene("mid-dl", synthetic=syn,
                                         deadline_s=3.0)
        assert terminal["kind"] == "result" and \
            terminal["status"] == "deadline", terminal
        assert terminal["error_class"] == "device"
        assert terminal["attempts"] == 1
    finally:
        faults.set_plan(None)
        daemon.request_stop()
        daemon.shutdown()


def test_sigterm_drains_in_flight_and_rejects_queued(serve_env, tmp_path):
    """SIGTERM with one request mid-device-phase: the in-flight request
    answers, the queued one gets a typed draining reject, shutdown is
    clean, and the per-request journal survives."""
    root = serve_env["root"]
    sock = os.path.join(str(tmp_path), "drain.sock")
    jdir = os.path.join(str(tmp_path), "journals")
    daemon = ServeDaemon(_cfg(root, config_name="drain"), socket_path=sock,
                         capacity=4, journal_dir=jdir,
                         freeze_after_warm=False)
    daemon.start()
    old_handler = faults.install_sigterm_handler()
    # a 2s device-phase sleep (no watchdog armed) holds the request in
    # flight long enough to land the signal mid-phase, deterministically:
    # the stall entry's consumption marks the phase entry exactly
    plan = faults.FaultPlan.from_spec("stall:drain-s.device:1", stall_s=2.0)
    faults.set_plan(plan)
    out = {}
    try:
        t1 = _request_thread(sock, "drain-s", SPEC_A, out, 1)
        _wait(lambda: plan.entries[0].remaining == 0)  # r1 mid-device-phase
        t2 = _request_thread(sock, "drain-q", SPEC_A, out, 2)
        _wait(lambda: daemon.queue.depth() == 1)  # r2 admitted behind r1
        os.kill(os.getpid(), signal.SIGTERM)  # the real handler, real signal
        assert faults.stop_requested()
        daemon.shutdown(timeout_s=120.0)
        t1.join(60.0)
        t2.join(60.0)
        assert out[1][0]["kind"] == "result" and \
            out[1][0]["status"] == "ok", out[1][0]
        assert out[2][0]["kind"] == "reject" and \
            out[2][0]["reason"] == "draining", out[2][0]
    finally:
        faults.set_plan(None)
        signal.signal(signal.SIGTERM, old_handler)
        faults.clear_stop()
        daemon.request_stop()
        daemon.shutdown()
    # the in-flight request's journal survived the drain
    rid = out[1][0]["id"]
    replay = faults.replay_journal(os.path.join(jdir, f"{rid}.jsonl"),
                                   request=rid)
    assert replay["drain-s"]["status"] == "ok"
    # new connections are refused once the socket is gone
    assert not os.path.exists(sock)


def test_second_daemon_warm_start_books_zero_retrace(serve_env, tmp_path):
    """ISSUE-11 acceptance: a second daemon start on the warm cache
    reaches first request dispatch without re-tracing the served buckets
    — pinned via retrace.* state: no new compile keys, no violations.
    (The cross-process half on a persistent AOT cache is ROADMAP item 3;
    scripts/ci.sh's serve smoke pins the cross-process drain today.)"""
    rs = serve_env["rs"]
    root = serve_env["root"]
    keys_before = rs.snapshot_keys()
    viol_before = len(rs.violations())

    sock = os.path.join(str(tmp_path), "warm2.sock")
    daemon = ServeDaemon(_cfg(root, config_name="warm2"), socket_path=sock,
                         capacity=2, warm_scenes=(SCENE_A,),
                         freeze_after_warm=True)
    daemon.start()  # warm-up runs scene A end to end: zero compiles
    try:
        syn_b = dict(SPEC_B, image_hw=list(SPEC_B["image_hw"]))
        with ServeClient(sock, timeout_s=120.0) as c:
            terminal, _, _ = c.run_scene(SCENE_B, synthetic=syn_b)
        assert terminal["status"] == "ok"
        assert terminal["buckets_new"] == 0
    finally:
        daemon.request_stop()
        daemon.shutdown()
    assert rs.snapshot_keys() == keys_before
    assert len(rs.violations()) == viol_before
    # the serving counters survived into the report plumbing
    assert daemon.stats()["counts"]["ok"] == 1


@pytest.mark.slow
def test_full_soak_load_gen_throughput(serve_env):
    """The load_gen-driven soak: 16 requests at concurrency 8 through the
    REAL client/load-gen code path, sustained throughput with bounded
    p95 and zero failures."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "load_gen", os.path.join(REPO_ROOT, "scripts", "load_gen.py"))
    load_gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(load_gen)

    # point load_gen's bucket specs at the fixture's materialized scenes
    load_gen.BUCKET_SPECS = (
        (SCENE_A, dict(SPEC_A, image_hw=list(SPEC_A["image_hw"]))),
        (SCENE_B, dict(SPEC_B, image_hw=list(SPEC_B["image_hw"]))),
    )
    verdict = load_gen.run_load(serve_env["sock"], requests=16,
                                concurrency=8, buckets=2, deadline_s=0.0,
                                resume=False)
    assert verdict["ok"] == 16 and verdict["failed"] == 0
    assert verdict["value"] is not None and verdict["p95_s"] is not None
    # bounded p95: the burst must pipeline, not serialize-with-overhead —
    # p95 latency stays under the whole-burst wall
    assert verdict["p95_s"] < verdict["wall_s"]
    assert verdict["throughput_rps"] > 0
