import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig, load_config
from maskclustering_tpu.io.ply import read_ply_points, write_ply_points
from maskclustering_tpu.io.image import resize_nearest
from maskclustering_tpu.semantics.vocab import get_vocab


def test_load_config_known():
    cfg = load_config("scannet")
    assert cfg.dataset == "scannet"
    assert cfg.step == 10
    assert cfg.view_consensus_threshold == 0.9


def test_load_config_per_dataset_thresholds():
    cfg = load_config("scannetpp")
    assert cfg.view_consensus_threshold == 1.0
    assert cfg.contained_threshold == 0.9
    assert cfg.step == 2


def test_config_validation():
    with pytest.raises(ValueError):
        PipelineConfig(mask_visible_threshold=2.0)
    with pytest.raises(ValueError):
        PipelineConfig(step=0)


def test_config_override():
    cfg = load_config("demo", step=5, backend="cpu")
    assert cfg.step == 5
    assert cfg.backend == "cpu"


def test_ply_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(100, 3)).astype(np.float32)
    colors = rng.integers(0, 255, size=(100, 3)).astype(np.uint8)
    path = str(tmp_path / "cloud.ply")
    write_ply_points(path, pts, colors)
    rp, rc = read_ply_points(path, return_colors=True)
    np.testing.assert_allclose(rp, pts, atol=1e-6)
    np.testing.assert_array_equal(rc, colors)


def test_ply_ascii(tmp_path):
    path = str(tmp_path / "a.ply")
    with open(path, "w") as f:
        f.write("ply\nformat ascii 1.0\nelement vertex 2\n"
                "property float x\nproperty float y\nproperty float z\nend_header\n"
                "0 1 2\n3 4 5\n")
    pts = read_ply_points(path)
    np.testing.assert_allclose(pts, [[0, 1, 2], [3, 4, 5]])


def test_resize_nearest_preserves_ids():
    ids = np.arange(12, dtype=np.uint16).reshape(3, 4)
    out = resize_nearest(ids, (8, 6))
    assert out.shape == (6, 8)
    assert set(np.unique(out)) <= set(np.unique(ids))


def test_vocab():
    labels, ids = get_vocab("scannet")
    assert len(labels) == len(ids) > 100
    labels2, _ = get_vocab("demo")  # alias
    assert labels2 == labels
    with pytest.raises(KeyError):
        get_vocab("nope")


def test_load_config_missing_raises():
    with pytest.raises(FileNotFoundError):
        load_config("scannet_typo")


def test_no_unread_config_fields():
    """Tripwire: every PipelineConfig field must be read somewhere outside
    config.py (dead knobs accumulate silently otherwise)."""
    import dataclasses
    import pathlib
    import re

    import maskclustering_tpu
    from maskclustering_tpu.config import PipelineConfig

    pkg = pathlib.Path(maskclustering_tpu.__file__).parent
    src = "\n".join(p.read_text() for p in pkg.rglob("*.py")
                    if p.name != "config.py")
    unread = [f.name for f in dataclasses.fields(PipelineConfig)
              if not re.search(rf"\.{f.name}\b", src)]
    assert not unread, f"config fields never read outside config.py: {unread}"
