"""Fused multi-chip step on a virtual 8-device CPU mesh.

Validates that the sharded pipeline (parallel/sharded.py) compiles and
executes under real meshes (scene x frame), and that its clustering output
matches the single-device pipeline semantics on a synthetic scene whose
ground truth is known (SURVEY.md §4 CPU-device test strategy).
"""

import jax
import numpy as np
import pytest

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.parallel import build_fused_step, fused_step_example_args, make_mesh


def _cluster_quality(assignment, mask_active, object_of_masks, mask_frame_id):
    """Check clusters are pure w.r.t. ground-truth object ids and cover all objects."""
    reps = {}
    n_impure = 0
    for slot in np.nonzero(mask_active)[0]:
        f, k = mask_frame_id(slot)
        gt = object_of_masks[f, k]
        if gt == 0:
            continue
        rep = int(assignment[slot])
        if rep in reps and reps[rep] != gt:
            n_impure += 1
        reps.setdefault(rep, gt)
    return reps, n_impure


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2)])
def test_fused_step_meshes(mesh_shape):
    cfg = PipelineConfig(
        config_name="test", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=1024, max_cluster_iterations=20,
    )
    mesh = make_mesh(mesh_shape)
    k_max = 7
    # the scene batch axis must fill the mesh's scene axis
    n_scenes = max(2, mesh_shape[0])
    step = build_fused_step(mesh, cfg, k_max=k_max)
    args = fused_step_example_args(num_scenes=n_scenes, num_frames=8)
    out = jax.block_until_ready(step(*map(jax.numpy.asarray, args)))

    assert out.assignment.shape == (n_scenes, 8 * k_max)
    assert out.mask_of_point.shape[0] == n_scenes
    # every scene finds at least the 3 boxes (floor may add one more object)
    n_obj = np.asarray(out.num_objects)
    assert (n_obj >= 3).all(), n_obj
    assert (n_obj <= 8).all(), n_obj


@pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2), (8, 1)])
def test_mesh_batch_matches_single_chip_artifacts(mesh_shape):
    """The fused mesh path must produce the exact objects (point sets, mask
    lists, coverages) of the single-chip pipeline on the same scenes —
    scenes-to-artifacts parity with reference run.py:33-50 scene sharding."""
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.parallel.batch import cluster_scene_batch
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    cfg = PipelineConfig(
        config_name="meshtest", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=1024, frame_pad_multiple=8,
        mask_pad_multiple=8,
    )
    tensors = [to_scene_tensors(make_scene(
        num_boxes=3, num_frames=8, image_hw=(32, 48), spacing=0.08, seed=s))
        for s in (0, 1, 2)]  # 3 scenes: exercises short-batch padding on (2, 4)
    mesh = make_mesh(mesh_shape)
    objs_mesh = cluster_scene_batch(cfg, mesh, tensors, k_max=7)
    assert len(objs_mesh) == 3
    for t, om in zip(tensors, objs_mesh):
        ref = run_scene(t, cfg, k_max=7).objects
        assert om.num_points == ref.num_points
        assert len(om.point_ids_list) == len(ref.point_ids_list)
        for a, b in zip(om.point_ids_list, ref.point_ids_list):
            np.testing.assert_array_equal(a, b)
        assert om.mask_list == ref.mask_list


def test_fused_step_matches_gt_objects():
    """On an easy synthetic scene the fused step recovers the GT instances."""
    from maskclustering_tpu.utils.synthetic import make_scene

    cfg = PipelineConfig(
        config_name="test", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=1024,
    )
    mesh = make_mesh((1, 8))
    k_max = 7
    num_frames = 8
    scene = make_scene(num_boxes=3, num_frames=num_frames, image_hw=(32, 48),
                       spacing=0.08, seed=0)
    step = build_fused_step(mesh, cfg, k_max=k_max)
    n = 4096
    pts = scene.scene_points
    reps_n = -(-n // pts.shape[0])
    pts = np.tile(pts, (reps_n, 1))[:n]
    out = jax.block_until_ready(step(
        jax.numpy.asarray(pts[None]),
        jax.numpy.asarray(scene.depths[None]),
        jax.numpy.asarray(scene.segmentations[None]),
        jax.numpy.asarray(scene.intrinsics[None]),
        jax.numpy.asarray(scene.cam_to_world[None]),
        jax.numpy.asarray(scene.frame_valid[None]),
    ))
    assignment = np.asarray(out.assignment[0])
    active = np.asarray(out.mask_active[0])
    reps, n_impure = _cluster_quality(
        assignment, active, scene.object_of_mask,
        lambda slot: (slot // k_max, slot % k_max + 1))
    # all 3 boxes present as distinct clusters, no cluster mixes two objects
    assert n_impure == 0
    assert len(set(reps.values())) >= 3


def test_fused_step_donate_path_identity():
    """The `donate=True` fused step (parallel/sharded.py:197): results are
    byte-identical to the non-donating step, the donated depth/seg frame
    buffers are consumed (never touched host-side afterwards — on backends
    implementing donation the handles are dead), and non-donated operands
    survive untouched."""
    import jax.numpy as jnp

    cfg = PipelineConfig(
        config_name="test", dataset="demo", distance_threshold=0.06,
        few_points_threshold=10, point_chunk=1024, max_cluster_iterations=20,
    )
    mesh = make_mesh((2, 4))
    k_max = 7
    args = fused_step_example_args(num_scenes=2, num_frames=8)

    base = jax.block_until_ready(
        build_fused_step(mesh, cfg, k_max=k_max)(*map(jnp.asarray, args)))

    # donation consumes the buffer the jit actually executes on: inputs
    # must already be placed with the step's in_shardings, else the
    # resharding copy (not the caller's array) would be the donatable one
    from maskclustering_tpu.parallel.mesh import sharding

    specs = [("scene",)] + [("scene", "frame")] * 5
    dev_args = [jax.device_put(a, sharding(mesh, *s))
                for a, s in zip(args, specs)]
    step_d = build_fused_step(mesh, cfg, k_max=k_max, donate=True)
    out = jax.block_until_ready(step_d(*dev_args))

    for name, a, b in zip(base._fields, base, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)

    # donated operands: depths (1) and segs (2). Where the backend
    # implements donation the handles are invalidated and any later read
    # raises — so this call completing proves the step never touches them
    # again. A backend may decline donation (multi-device CPU does); the
    # caller's buffers must then survive bit-exact.
    for i in (1, 2):
        if dev_args[i].is_deleted():
            with pytest.raises((RuntimeError, ValueError)):
                np.asarray(dev_args[i])
        else:
            np.testing.assert_array_equal(np.asarray(dev_args[i]), args[i])
    # everything NOT in donate_argnums is untouched and still readable
    for i in (0, 3, 4, 5):
        assert not dev_args[i].is_deleted()
        np.testing.assert_array_equal(np.asarray(dev_args[i]), args[i])
