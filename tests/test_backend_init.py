"""Shared watchdog-guarded backend init (utils/backend_init.py).

The wedged-chip timeout path needs a subprocess (the watchdog os._exit(3)s
the whole process); the success and failure paths run in-process.
"""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from maskclustering_tpu.utils.backend_init import (
    INIT_TIMEOUT_EXIT_CODE,
    init_backend,
)


def test_init_backend_success_returns_devices():
    devices = init_backend("cpu", timeout_s=120.0, tag="t")
    assert len(devices) >= 1
    assert devices[0].platform == "cpu"


def test_init_backend_bad_platform_raises():
    """Subprocess: once a backend is up in-process (the success test, or
    conftest), jax serves cached devices and a bad platform no longer
    raises — the child must hit init fresh."""
    code = rf"""
import sys
sys.path.insert(0, {REPO_ROOT!r})
from maskclustering_tpu.utils.backend_init import init_backend
try:
    init_backend("nosuch", timeout_s=30.0, tag="t")
except Exception as e:
    assert "nosuch" in str(e), e
    print("RAISED-OK")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, timeout=120)
    assert b"RAISED-OK" in proc.stdout, proc.stderr[-500:]


def test_init_backend_timeout_exits_3_and_runs_hook():
    """A stalled init must os._exit(3) from the watchdog thread and run the
    on_timeout hook first. Simulated by an init that sleeps past the
    timeout (monkeypatched jax.devices in a child process)."""
    code = rf"""
import sys, time, types
sys.path.insert(0, {REPO_ROOT!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.devices()  # real init done; now stall the guarded call
from maskclustering_tpu.utils import backend_init
orig = jax.devices
jax.devices = lambda *a: time.sleep(30)
backend_init.init_backend(None, timeout_s=1.0, tag="t",
                          on_timeout=lambda: print("HOOK-RAN", flush=True))
print("UNREACHABLE")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, timeout=120)
    assert proc.returncode == INIT_TIMEOUT_EXIT_CODE
    assert b"HOOK-RAN" in proc.stdout
    assert b"UNREACHABLE" not in proc.stdout
