"""Persistent AOT executable cache (utils/aot_cache.py; ROADMAP item 3).

Unit tier: key schema stability, index/blob round-trip, version-stamp
invalidation + prune, capture -> restore byte identity on a tiny program,
and the association dispatch seam serving the restored executable.

Acceptance tier: the cross-process warm start — a SECOND process against
the same cache directories reaches first dispatch with a ``compiles: 0``
retrace digest (every compile-log event either served by the persistent
compilation cache or replaced outright by a restored export), identical
results, and a version-stamp mismatch falls back to a clean compile with
the miss counted.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maskclustering_tpu.config import load_config
from maskclustering_tpu.utils import aot_cache

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(tmp_path, **kw):
    base = dict(data_root=str(tmp_path / "data"), config_name="aot",
                step=1, distance_threshold=0.05, mask_pad_multiple=32,
                aot_cache_dir=str(tmp_path / "aot"))
    base.update(kw)
    return load_config("scannet").replace(**base)


@pytest.fixture(autouse=True)
def _clean_registry():
    aot_cache.reset()
    yield
    aot_cache.reset()


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_key_schema_digest_stability():
    sds = [jax.ShapeDtypeStruct((4, 8), jnp.float32),
           jax.ShapeDtypeStruct((8,), jnp.int16)]
    k1 = aot_cache.key_for("fn", sds, statics={"k_max": 63, "window": 1},
                           count_dtype="bf16", donate=True)
    k2 = aot_cache.key_for("fn", sds, statics={"window": 1, "k_max": 63},
                           count_dtype="bf16", donate=True)
    assert k1.digest() == k2.digest()  # statics order-insensitive
    # every census axis changes the key
    for other in (
        aot_cache.key_for("fn2", sds, statics={"k_max": 63, "window": 1},
                          count_dtype="bf16", donate=True),
        aot_cache.key_for("fn", sds[:1], statics={"k_max": 63, "window": 1},
                          count_dtype="bf16", donate=True),
        aot_cache.key_for("fn", sds, statics={"k_max": 127, "window": 1},
                          count_dtype="bf16", donate=True),
        aot_cache.key_for("fn", sds, statics={"k_max": 63, "window": 1},
                          count_dtype="int8", donate=True),
        aot_cache.key_for("fn", sds, statics={"k_max": 63, "window": 1},
                          count_dtype="bf16", donate=False),
    ):
        assert other.digest() != k1.digest()
    desc = k1.describe()
    assert desc["fn"] == "fn" and desc["count_dtype"] == "bf16"
    assert desc["avals"] == ["float32[4, 8]", "int16[8]"]


def test_store_lookup_version_invalidation_and_prune(tmp_path):
    cache = aot_cache.AotCache(str(tmp_path / "c"))
    key = aot_cache.key_for(
        "fn", [jax.ShapeDtypeStruct((2,), jnp.float32)],
        statics={}, count_dtype="bf16", donate=False)
    assert cache.lookup(key) is None
    assert cache.store(key, b"blob-bytes", donate_argnums=(1,))
    assert cache.lookup(key) == b"blob-bytes"
    meta = cache.entries()[key.digest()]
    assert meta["stamp"] == aot_cache.version_stamp()
    assert meta["donate_argnums"] == [1]

    # a mismatched stamp (a jax upgrade) invalidates cleanly: lookup says
    # miss, the blob stays until prune() deletes it
    idx_path = os.path.join(cache.path, aot_cache.INDEX_NAME)
    with open(idx_path) as f:
        doc = json.load(f)
    doc["entries"][key.digest()]["stamp"]["jax"] = "0.0.0-other"
    with open(idx_path, "w") as f:
        json.dump(doc, f)
    assert cache.lookup(key) is None
    assert os.path.exists(os.path.join(cache.path, f"{key.digest()}.bin"))
    assert cache.prune() == 1
    assert cache.entries() == {}
    assert not os.path.exists(os.path.join(cache.path, f"{key.digest()}.bin"))


def test_resolve_cache_dir_policy(tmp_path, monkeypatch):
    monkeypatch.delenv(aot_cache.ENV_DIR, raising=False)
    cfg = _cfg(tmp_path, aot_cache_dir="")
    assert aot_cache.resolve_cache_dir(cfg) is None  # off by default
    assert aot_cache.warm_start(cfg) == {"restored": 0, "invalidated": 0,
                                         "failed": 0}
    explicit = _cfg(tmp_path, aot_cache_dir=str(tmp_path / "x"))
    assert aot_cache.resolve_cache_dir(explicit) == str(tmp_path / "x")
    # "auto" and the env var land next to the perf ledger (hermetic via
    # the conftest MCT_PERF_LEDGER tmp redirect)
    auto = aot_cache.resolve_cache_dir(_cfg(tmp_path, aot_cache_dir="auto"))
    assert auto == os.path.join(
        os.path.dirname(os.environ["MCT_PERF_LEDGER"]), "aot_cache")
    monkeypatch.setenv(aot_cache.ENV_DIR, str(tmp_path / "envdir"))
    assert aot_cache.resolve_cache_dir(cfg) == str(tmp_path / "envdir")


def test_capture_restore_byte_identity_and_warm_start(tmp_path):
    cfg = _cfg(tmp_path)
    assert aot_cache.configure(cfg) is not None

    f = jax.jit(lambda x, y: jnp.sin(x) @ y + 1.0)
    sds = [jax.ShapeDtypeStruct((16, 16), jnp.float32)] * 2
    key = aot_cache.key_for("tiny", sds, statics={"k": 1},
                           count_dtype=cfg.count_dtype,
                           donate=bool(cfg.donate_buffers))
    assert aot_cache.restored(key) is None  # cold miss
    assert aot_cache.capture(key, f, sds)
    restored = aot_cache.restored(key)  # capture self-restores
    assert restored is not None
    x = jnp.ones((16, 16)), jnp.full((16, 16), 2.0, jnp.float32)
    np.testing.assert_array_equal(np.asarray(restored(*x)),
                                  np.asarray(f(*x)))

    # a "fresh process" (registry reset): warm_start reinstalls from disk
    aot_cache.reset()
    stats = aot_cache.warm_start(cfg)
    assert stats == {"restored": 1, "invalidated": 0, "failed": 0}
    again = aot_cache.restored(key)
    assert again is not None
    np.testing.assert_array_equal(np.asarray(again(*x)), np.asarray(f(*x)))

    # other-coordinate entries are left alone (a different count_dtype is
    # some other config's warm start)
    aot_cache.reset()
    stats = aot_cache.warm_start(cfg.replace(count_dtype="int8"))
    assert stats["restored"] == 0


@pytest.mark.slow
def test_association_seam_serves_restored_executable(tmp_path):
    """The dispatch seam end to end, in process: first call compiles +
    captures, second call runs the RESTORED executable — byte-identical
    SceneAssociation, and the aot hit counter books it."""
    from maskclustering_tpu.models.backprojection import associate_scene_tensors
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors
    from maskclustering_tpu import obs

    cfg = _cfg(tmp_path)
    aot_cache.configure(cfg)

    def run_once():
        t = to_scene_tensors(make_scene(num_boxes=3, num_frames=6,
                                        image_hw=(48, 64), spacing=0.08,
                                        seed=11))
        return associate_scene_tensors(t, cfg, k_max=63)

    first = run_once()
    hits_before = obs.registry().snapshot()["counters"].get(
        "aot_cache.hits", 0)
    second = run_once()
    hits_after = obs.registry().snapshot()["counters"].get(
        "aot_cache.hits", 0)
    assert hits_after > hits_before, "second dispatch must hit the cache"
    for name in ("mask_of_point", "first_id", "last_id", "mask_valid",
                 "boundary"):
        np.testing.assert_array_equal(np.asarray(getattr(first, name)),
                                      np.asarray(getattr(second, name)))


# ---------------------------------------------------------------------------
# acceptance: the cross-process warm start (ROADMAP item 3)
# ---------------------------------------------------------------------------


def _run_driver(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tests",
                                      "aot_warm_driver.py"),
         str(tmp_path / "aot"), str(tmp_path / "xla"),
         str(tmp_path / "data")],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cross_process_warm_start_zero_compiles_and_invalidation(tmp_path):
    """The item-3 acceptance, one cold subprocess amortized three ways:

    - process 2 against the same cache dirs reaches first dispatch
      WITHOUT recompiling (digest ``compiles: 0`` — restored export +
      persistent-compilation-cache hits), identical results;
    - the cold process captured the association program's export under
      its census coordinates;
    - a version-stamp mismatch invalidates cleanly in process 3: the
      entry is skipped + counted, the run falls back to a compile path
      (still cache-hit-served, never a crash), results unchanged.
    """
    p1 = _run_driver(tmp_path)
    assert p1["compiles"] > 0 and p1["cache_hits"] == 0  # honest cold start
    assert p1["violations"] == 0
    # the cold process captured the association export
    index = json.load(open(tmp_path / "aot" / aot_cache.INDEX_NAME))
    fns = {e["fn"] for e in index["entries"].values()}
    assert "_associate_scene_impl" in fns

    p2 = _run_driver(tmp_path)
    assert p2["compiles"] == 0, p2
    assert p2["warm"]["restored"] >= 1
    assert p2["cache_hits"] > 0
    assert p2["violations"] == 0
    # same answer either way (restored executable + cache-served builds)
    assert p2["num_objects"] == p1["num_objects"]
    assert p2["assignment_sum"] == p1["assignment_sum"]

    # version-stamp mismatch: invalidated + clean fallback, no crash
    idx_path = tmp_path / "aot" / aot_cache.INDEX_NAME
    doc = json.load(open(idx_path))
    for entry in doc["entries"].values():
        entry["stamp"]["jax"] = "0.0.0-mismatch"
    with open(idx_path, "w") as f:
        json.dump(doc, f)
    p3 = _run_driver(tmp_path)
    assert p3["warm"]["restored"] == 0
    assert p3["warm"]["invalidated"] >= 1
    assert p3["violations"] == 0
    assert p3["assignment_sum"] == p1["assignment_sum"]
