"""Fault-matrix smoke: three canned FaultPlans through a 2-scene CPU run.

CI's drill of the fault-tolerance layer (scripts/ci.sh, budgeted < 60 s):
every path a wedged chip would exercise — retry-after-flaky, watchdog
stall + degradation ladder, persistent failure + journal replay — runs
deterministically on CPU against a tiny synthetic layout. The plans:

1. ``flaky:<scene0>:1``          one failure, heals on retry
2. ``stall:<scene0>.device``     a device stall: DeviceStallError within
                                 the watchdog budget, one ladder rung
                                 dropped, heals on the degraded retry
3. ``load:<scene1>``             a persistent load failure: the scene ends
                                 failed after the retry budget, the other
                                 scene is untouched, and the run journal
                                 replays to the executor's exact verdict

Exit 0 = every expectation held; any assertion prints and exits 1.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# the image preloads the TPU plugin via sitecustomize: the env var is too
# late, the config flag is not (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

from maskclustering_tpu.config import load_config  # noqa: E402
from maskclustering_tpu.run import cluster_scenes  # noqa: E402
from maskclustering_tpu.utils import faults  # noqa: E402
from maskclustering_tpu.utils.synthetic import (make_scene,  # noqa: E402
                                                write_scannet_layout)

SCENES = ("scene0000_00", "scene0001_00")
# ~5-10x the warm tiny-scene device phase (a loaded box spikes phases
# several-fold; a healthy dispatch must never lose this race), while one
# stall detection still fits the step's 60 s ci.sh budget
WATCHDOG_S = 10.0


def _cfg(root, name, **kw):
    return load_config("scannet").replace(
        data_root=root, config_name=name, step=1, distance_threshold=0.05,
        mask_pad_multiple=32, frame_pad_multiple=4, point_chunk=2048,
        retry_backoff_s=0.01, **kw)


def _run(root, name, plan_spec, **cfg_kw):
    faults.set_plan(faults.FaultPlan.from_spec(plan_spec, stall_s=60.0)
                    if plan_spec else None)
    try:
        t0 = time.perf_counter()
        out = cluster_scenes(_cfg(root, name, **cfg_kw), list(SCENES),
                             resume=False,
                             journal=faults.RunJournal(
                                 os.path.join(root, f"{name}_journal.jsonl"),
                                 name))
        print(f"[fault_smoke] {name}: "
              f"{[(s.seq_name, s.status, s.attempts, s.degradation_rung) for s in out]} "
              f"({time.perf_counter() - t0:.1f}s)", flush=True)
        return out
    finally:
        faults.set_plan(None)


def main() -> int:
    t_start = time.time()
    with tempfile.TemporaryDirectory(prefix="fault_smoke_") as root:
        for i, seq in enumerate(SCENES):
            write_scannet_layout(
                make_scene(num_boxes=2, num_frames=6, image_hw=(40, 56),
                           seed=70 + i), root, seq)
        print(f"[fault_smoke] layout ready ({time.time() - t_start:.1f}s)",
              flush=True)

        # plan 1: flaky-then-ok — one retry heals the scene
        out = _run(root, "smk1", f"flaky:{SCENES[0]}:1")
        assert [s.status for s in out] == ["ok", "ok"], out
        assert out[0].attempts == 2 and out[1].attempts == 1, out

        # plan 2: a device stall — the watchdog raises DeviceStallError
        # within its budget, the ladder drops one rung (overlapped ->
        # sequential), and the degraded retry succeeds
        t0 = time.perf_counter()
        out = _run(root, "smk2", f"stall:{SCENES[0]}.device",
                   watchdog_device_s=WATCHDOG_S)
        stall_wall = time.perf_counter() - t0
        assert [s.status for s in out] == ["ok", "ok"], out
        assert out[0].attempts == 2, out
        assert out[0].degradation_rung == 1, out  # retried one rung down
        assert stall_wall < 60.0, f"stall handling took {stall_wall:.1f}s"

        # plan 3: a persistent load failure — retries exhaust, exactly one
        # scene fails, and the journal replays the executor's verdict
        out = _run(root, "smk3", f"load:{SCENES[1]}", scene_retries=1)
        by = {s.seq_name: s for s in out}
        assert by[SCENES[0]].status == "ok", out
        assert by[SCENES[1]].status == "failed", out
        assert by[SCENES[1]].error_class == "retryable", out
        assert by[SCENES[1]].attempts == 2, out
        replay = faults.replay_journal(
            os.path.join(root, "smk3_journal.jsonl"), config="smk3")
        for s in out:
            r = replay[s.seq_name]
            assert (r["status"], r["attempts"], r["error_class"]) \
                == (s.status, s.attempts, s.error_class), (r, s)

    print(f"[fault_smoke] OK: 3 plans, {time.time() - t_start:.1f}s total",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
