"""Dense-vs-exact association A/B at REFERENCE thresholds -> PARITY.md.

The flagship projective association (models/backprojection.py) deliberately
reformulates the reference's ball-query pipeline (search direction inverted,
voxel-count coverage denominator, window-limited claiming). This harness
quantifies what that costs at the reference's own operating point
(distance_threshold = 0.01 m, reference utils/mask_backprojection.py:10) on
noisy synthetic RGB-D at ScanNet-like density:

- both association paths run through the FULL pipeline (graph -> clustering
  -> postprocess -> npz export) on the same scenes;
- class-agnostic AP of each against the synthetic GT (the reference's
  de-facto integration metric, run.py:93);
- Jaccard of per-mask claimed point sets between the paths (SURVEY.md §7
  stage 3's parity metric).

Usage: python scripts/parity_ab.py [--points shallow,deep]
       [--out PARITY.md]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import os
import sys
import tempfile
import time

import numpy as np


def mask_sets_from_association(assoc, k_max):
    """{(frame, id): sorted point ids} from a SceneAssociation's claims."""
    first = np.asarray(assoc.first_id)
    last = np.asarray(assoc.last_id)
    valid = np.asarray(assoc.mask_valid)
    sets = {}
    f_num = first.shape[0]
    for f in range(f_num):
        for arr in (first, last):
            ids = arr[f]
            for mid in np.unique(ids):
                if mid <= 0 or mid > k_max or not valid[f, mid]:
                    continue
                pts = np.nonzero(ids == mid)[0]
                key = (f, int(mid))
                sets[key] = np.union1d(sets[key], pts) if key in sets else pts
    return sets


def jaccard_stats(sets_a, sets_b):
    keys = sorted(set(sets_a) | set(sets_b))
    vals = []
    only_a = only_b = 0
    for k in keys:
        if k not in sets_a:
            only_b += 1
            continue
        if k not in sets_b:
            only_a += 1
            continue
        a, b = sets_a[k], sets_b[k]
        inter = np.intersect1d(a, b).size
        union = np.union1d(a, b).size
        vals.append(inter / max(union, 1))
    return (float(np.mean(vals)) if vals else 0.0,
            float(np.median(vals)) if vals else 0.0, len(vals), only_a, only_b)


# Two operating points (VERDICT r4 task 4): "shallow" = the original r3
# config; "deep" = real schedule depth, where the observer-percentile ladder
# (reference graph/construction.py:80-96) walks its full 95->0 range and
# undersegmentation/containment dynamics actually engage.
OPERATING_POINTS = {
    "shallow": dict(scenes=3, frames=16, boxes=4, k_max=15),
    "deep": dict(scenes=2, frames=64, boxes=16, k_max=31),
    # half a real ScanNet scene's schedule depth at the honest mask budget;
    # CPU-hours heavy — run on demand, not in the default pair
    "full": dict(scenes=1, frames=128, boxes=24, k_max=63),
}


def run_point(point_name, pt, args):
    """Run one operating point -> (rows, ap_dense, ap_exact)."""
    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.evaluation.ap import evaluate_scans
    from maskclustering_tpu.models.backprojection import associate_scene_tensors
    from maskclustering_tpu.models.exact_backprojection import associate_scene_exact
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    # REFERENCE operating point (utils/mask_backprojection.py:8-14 + configs)
    cfg = PipelineConfig(config_name=f"parity_{point_name}", dataset="demo",
                         distance_threshold=0.01, few_points_threshold=25,
                         coverage_threshold=0.3, point_chunk=8192)
    k_max = pt["k_max"]

    workdir = tempfile.mkdtemp(prefix=f"parity_{point_name}_")
    gt_files, dense_npz, exact_npz = [], [], []
    rows = []
    for s in range(pt["scenes"]):
        rng = np.random.default_rng(1000 + s)
        scene = make_scene(num_boxes=pt["boxes"], num_frames=pt["frames"],
                           image_hw=(args.image_h, args.image_w),
                           spacing=args.spacing, floor_spacing=args.floor_spacing,
                           seed=100 + s)
        noisy = scene.depths + rng.normal(
            scale=args.noise, size=scene.depths.shape).astype(np.float32)
        scene.depths[:] = np.where(scene.depths > 0, np.maximum(noisy, 1e-3), 0.0)
        tensors = to_scene_tensors(scene)
        n_pts = tensors.num_points
        print(f"[parity:{point_name}] scene {s}: {n_pts} points, "
              f"{pt['frames']} frames", file=sys.stderr, flush=True)

        t0 = time.time()
        assoc_dense = associate_scene_tensors(tensors, cfg, k_max=k_max)
        sets_dense = mask_sets_from_association(assoc_dense, k_max)
        t_dense = time.time() - t0
        t0 = time.time()
        assoc_exact = associate_scene_exact(tensors, cfg, k_max=k_max)
        sets_exact = mask_sets_from_association(assoc_exact, k_max)
        t_exact = time.time() - t0

        jac_mean, jac_med, n_common, only_d, only_e = jaccard_stats(
            sets_dense, sets_exact)
        rows.append((s, n_pts, jac_mean, jac_med, n_common, only_d, only_e,
                     t_dense, t_exact))
        print(f"[parity:{point_name}] scene {s}: mask Jaccard mean={jac_mean:.3f} "
              f"median={jac_med:.3f} common={n_common} dense-only={only_d} "
              f"exact-only={only_e} ({t_dense:.0f}s vs {t_exact:.0f}s)",
              file=sys.stderr, flush=True)

        # full pipeline + export for both paths
        for name, use_exact, bucket in (("dense", False, dense_npz),
                                        ("exact", True, exact_npz)):
            res = run_scene(tensors, cfg.replace(
                config_name=f"parity_{point_name}_{name}",
                use_exact_ball_query=use_exact),
                k_max=k_max, seq_name=f"scene{s:04d}_00", export=True,
                object_dict_dir=os.path.join(workdir, name, f"scene{s:04d}_00"),
                prediction_root=os.path.join(workdir, "prediction"))
            bucket.append(os.path.join(
                workdir, "prediction", f"parity_{point_name}_{name}_class_agnostic",
                f"scene{s:04d}_00.npz"))
            print(f"[parity:{point_name}] scene {s} {name}: "
                  f"{len(res.objects.point_ids_list)} objects",
                  file=sys.stderr, flush=True)

        gt = np.where(scene.gt_instance > 0, 3000 + scene.gt_instance + 1, 1)
        gt_path = os.path.join(workdir, f"scene{s:04d}_00.txt")
        np.savetxt(gt_path, gt, fmt="%d")
        gt_files.append(gt_path)

    ap_dense = evaluate_scans(dense_npz, gt_files, "scannet", no_class=True,
                              verbose=False)
    ap_exact = evaluate_scans(exact_npz, gt_files, "scannet", no_class=True,
                              verbose=False)
    return rows, ap_dense, ap_exact


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--spacing", type=float, default=0.008)
    p.add_argument("--floor-spacing", type=float, default=0.016)
    p.add_argument("--noise", type=float, default=0.002, help="depth noise sigma (m)")
    # 480x640 = ScanNet depth size; at r = 0.01 the pixel grid must be finer
    # than the radius or NEITHER path can claim (pixel 3D spacing ~5 mm at 3 m)
    p.add_argument("--image-h", type=int, default=480)
    p.add_argument("--image-w", type=int, default=640)
    p.add_argument("--ap50-bound", type=float, default=0.05,
                   help="max |AP50 gap| per operating point for PASS (exit 0)")
    p.add_argument("--jaccard-bound", type=float, default=0.85,
                   help="min per-scene mean mask Jaccard for PASS")
    p.add_argument("--points", default="shallow,deep",
                   help="comma-separated operating points to run")
    p.add_argument("--out", default="PARITY.md")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    lines = [
        "# PARITY — dense projective association vs reference ball-query path",
        "",
        "A/B at the REFERENCE thresholds: distance_threshold = 0.01 m",
        "(utils/mask_backprojection.py:10), synthetic scenes at ScanNet-like",
        f"density (spacing {args.spacing} m), {args.image_h}x{args.image_w} depth",
        f"frames with sigma = {args.noise * 1000:.0f} mm Gaussian noise.",
        "Both paths run the full pipeline to npz; generated by",
        "`scripts/parity_ab.py` (CPU, deterministic seeds). Two operating",
        "points: *shallow* (16 fr x 4 obj, the r3 config) and *deep* (64 fr x",
        "16 obj + floor, k_max 31): at depth the observer-percentile schedule",
        "(reference graph/construction.py:80-96) walks its full 95->0 ladder",
        "and undersegment/containment dynamics engage.",
        "",
    ]
    verdicts = []
    for point_name in args.points.split(","):
        pt = OPERATING_POINTS[point_name]
        t0 = time.time()
        rows, ap_dense, ap_exact = run_point(point_name, pt, args)
        elapsed = time.time() - t0

        def _ap3(res):
            return res["all_ap"], res["all_ap_50%"], res["all_ap_25%"]

        d_ap, d_ap50, d_ap25 = _ap3(ap_dense)
        e_ap, e_ap50, e_ap25 = _ap3(ap_exact)
        jms = [r[2] for r in rows]
        ap_ok = abs(d_ap50 - e_ap50) <= args.ap50_bound
        jac_ok = float(np.min(jms)) >= args.jaccard_bound
        verdicts.append((point_name, ap_ok and jac_ok,
                         abs(d_ap50 - e_ap50), float(np.min(jms))))

        lines += [
            f"## Operating point: {point_name} — {pt['scenes']} scenes x "
            f"{pt['frames']} frames x {pt['boxes']} objects (k_max {pt['k_max']})",
            "",
            "### Class-agnostic AP vs synthetic GT",
            "",
            "| path | AP | AP50 | AP25 |",
            "|---|---|---|---|",
            f"| dense (flagship) | {d_ap:.4f} | {d_ap50:.4f} | {d_ap25:.4f} |",
            f"| exact (reference semantics) | {e_ap:.4f} | {e_ap50:.4f} | {e_ap25:.4f} |",
            f"| **gap (dense - exact)** | {d_ap - e_ap:+.4f} | "
            f"{d_ap50 - e_ap50:+.4f} | {d_ap25 - e_ap25:+.4f} |",
            "",
            "### Per-mask claimed-point-set Jaccard (dense vs exact)",
            "",
            "| scene | points | mean J | median J | common masks | dense-only | exact-only |",
            "|---|---|---|---|---|---|---|",
        ]
        for s, n_pts, jm, jmed, nc, od, oe, td, te in rows:
            lines.append(
                f"| {s} | {n_pts} | {jm:.3f} | {jmed:.3f} | {nc} | {od} | {oe} |")
        lines += [
            "",
            f"Aggregate mask-set Jaccard: mean {np.mean(jms):.3f} "
            f"(min scene {np.min(jms):.3f}). Point completed in "
            f"{elapsed / 60:.0f} min.",
            "",
        ]

    lines += [
        "## Bound and verdict",
        "",
        f"Pass criterion per operating point: |AP50 gap| <= {args.ap50_bound:.2f}"
        f" and per-scene mean mask Jaccard >= {args.jaccard_bound:.2f}"
        " (VERDICT r4 task 4).",
        "",
        "| point | AP50 gap | min mean Jaccard | verdict |",
        "|---|---|---|---|",
    ]
    for name, ok, gap, jmin in verdicts:
        lines.append(f"| {name} | {gap:.4f} | {jmin:.3f} | "
                     f"{'PASS' if ok else 'FAIL'} |")
    all_ok = all(ok for _, ok, _, _ in verdicts)
    lines += [
        "",
        "The two association paths stay selectable per run via",
        "`use_exact_ball_query` for real-data validation.",
        "",
        f"**Overall: {'PASS' if all_ok else 'FAIL'}**",
        "",
    ]
    with open(args.out, "w") as f:
        f.write("\n".join(lines))
    print(f"[parity] wrote {args.out}", file=sys.stderr)
    print("\n".join(lines))
    sys.exit(0 if all_ok else 1)


if __name__ == "__main__":
    main()
