#!/usr/bin/env bash
# CI gate: tier-1 tests + fault-matrix smoke + perf regression, one command.
#
#   scripts/ci.sh [BASELINE] [LEDGER]
#
# 1. runs the tier-1 suite (ROADMAP.md "Tier-1 verify": CPU backend, not
#    slow-marked, collection errors tolerated but failures are not), with
#    --durations=10 on record and a NON-FATAL warning when the suite wall
#    exceeds 800 s of the 870 s timeout budget (MCT_TIER1_WALL_WARN to
#    override) — new tests must reuse the small shared synthetic fixtures,
#    not fresh full-depth scenes, and this is the tripwire that says so
#    before the hard timeout does (the fault-tolerance tests are counted
#    by the same --durations table);
# 2. runs the fault-matrix smoke (scripts/fault_smoke.py): three canned
#    FaultPlans — flaky-then-ok, device stall + degradation ladder,
#    persistent load failure + journal replay — through a 2-scene
#    synthetic CPU run, budgeted under 60 s (MCT_FAULT_SMOKE=0 skips);
# 3. gates the perf ledger's newest headline p50 against BASELINE via
#    `python -m maskclustering_tpu.obs.report --regress` (exit 2 on a >15%
#    regression — override the threshold with MCT_REGRESS_THRESHOLD).
#
# BASELINE defaults to BENCH_builder_r05.json (the newest committed bench
# verdict with a numeric headline; any JSON doc with a `value` or a ledger
# JSONL works). LEDGER defaults to PERF_LEDGER.jsonl / $MCT_PERF_LEDGER.
# Exits non-zero on test failures (1), a fault-matrix failure (3) or a
# perf regression (2), so it gates correctness, fault tolerance AND the
# trajectory.
set -u -o pipefail

cd "$(dirname "$0")/.."
BASELINE="${1:-BENCH_builder_r05.json}"
LEDGER="${2:-${MCT_PERF_LEDGER:-PERF_LEDGER.jsonl}}"
THRESHOLD="${MCT_REGRESS_THRESHOLD:-0.15}"
rc=0

WALL_WARN="${MCT_TIER1_WALL_WARN:-800}"
echo "== ci: tier-1 tests =="
t0=$(date +%s)
if ! timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors --durations=10 \
        -p no:cacheprovider -p no:xdist -p no:randomly; then
    echo "ci: tier-1 tests FAILED" >&2
    rc=1
fi
wall=$(( $(date +%s) - t0 ))
echo "== ci: tier-1 wall ${wall}s (budget: warn >${WALL_WARN}s of the 870s timeout) =="
if [ "$wall" -gt "$WALL_WARN" ]; then
    # non-fatal: the suite still passed, but the headroom is gone — trim
    # the slowest tests (see the --durations table above) onto the shared
    # small fixtures before the 870 s hard timeout starts eating the run
    echo "ci: WARNING tier-1 wall ${wall}s exceeds the ${WALL_WARN}s soft budget" >&2
fi

if [ "${MCT_FAULT_SMOKE:-1}" != "0" ]; then
    echo "== ci: fault-matrix smoke (3 canned FaultPlans, 2-scene CPU run, <60s) =="
    if ! timeout -k 10 60 env JAX_PLATFORMS=cpu python scripts/fault_smoke.py; then
        echo "ci: fault-matrix smoke FAILED" >&2
        rc=3
    fi
fi

echo "== ci: perf regression gate ($LEDGER vs $BASELINE, >$THRESHOLD p50) =="
if [ ! -f "$LEDGER" ]; then
    echo "ci: no ledger at $LEDGER; skipping the perf gate" >&2
elif ! python -m maskclustering_tpu.obs.report --ledger "$LEDGER" \
        --regress "$BASELINE" --regress-threshold "$THRESHOLD"; then
    echo "ci: perf regression gate FAILED" >&2
    rc=2
fi

exit $rc
