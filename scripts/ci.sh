#!/usr/bin/env bash
# CI gate: tier-1 tests + fault-matrix smoke + perf regression, one command.
#
#   scripts/ci.sh [BASELINE] [LEDGER]
#
# 1. runs the tier-1 suite (ROADMAP.md "Tier-1 verify": CPU backend, not
#    slow-marked, collection errors tolerated but failures are not), with
#    --durations=10 on record and a NON-FATAL warning when the suite wall
#    exceeds 800 s of the 870 s timeout budget (MCT_TIER1_WALL_WARN to
#    override) — new tests must reuse the small shared synthetic fixtures,
#    not fresh full-depth scenes, and this is the tripwire that says so
#    before the hard timeout does (the fault-tolerance tests are counted
#    by the same --durations table). Appends one `tier1` row (suite wall
#    + pass count) to the perf ledger so the 870 s budget trajectory is
#    machine-checkable via the same --regress machinery (fenced: a tier1
#    baseline gates tier1 rows only, never the bench/run headline);
# 2. runs the fault-matrix smoke (scripts/fault_smoke.py): three canned
#    FaultPlans — flaky-then-ok, device stall + degradation ladder,
#    persistent load failure + journal replay — through a 2-scene
#    synthetic CPU run, budgeted under 60 s (MCT_FAULT_SMOKE=0 skips);
# 3. runs mct-check (python -m maskclustering_tpu.analysis): the static
#    IR + AST invariant gates — counting-dtype policy, 2-sync census,
#    donation aliasing/wiring, collective budgets, host-sync/thread lint —
#    against analysis_baseline.json, CPU-only, budgeted under 90 s
#    (MCT_CHECK=0 skips). FATAL: an unsuppressed finding fails CI.
# 3b. runs the mct-check CONCURRENCY family as its own gate (distinct
#    exit code 5, so triage points at thread safety, not dtype/sync):
#    thread topology, shared-state reachability, lock-order acyclicity,
#    blocking-under-lock, signal-handler and join/abandon contracts —
#    pure stdlib AST, sub-5 s (MCT_CHECK=0 skips this too). FATAL.
# 3c. runs the mct-check RETRACE family as its own gate (distinct exit
#    code 6, so triage points at the compile surface): traced-closure
#    captures, trace-time shape branching, jit-site hygiene, and the
#    compile-surface census ratchet against compile_surface_baseline.json
#    — an accidental new compile variant fails here with its exact
#    (fn, bucket, dtype, donation) coordinate. Lowers the fused lattice
#    on CPU (~15 s); FATAL (MCT_CHECK=0 skips this too).
# 4. runs ruff (the style/correctness front-end pinned in pyproject.toml)
#    when the PINNED version is installed (fatal); an unpinned ruff runs
#    advisory-only — a floating linter's new rules must not flip CI red,
#    that is exactly what the pin exists to prevent — and a missing ruff
#    is skipped with a notice (the container image does not bake it in).
# 5. gates the perf ledger's newest headline p50 against BASELINE via
#    `python -m maskclustering_tpu.obs.report --regress` (exit 2 on a >15%
#    regression — override the threshold with MCT_REGRESS_THRESHOLD).
#
# 3d. runs the serve daemon smoke (distinct exit code 7): spawns a
#    retrace-sanitizer-armed mct-serve daemon subprocess (AOT executable
#    cache armed — the capture half of the round-trip rides every smoke),
#    warms two tiny shape buckets, fires a small mixed-bucket burst
#    through scripts/load_gen.py while POLLING the telemetry op mid-burst
#    (an empty/torn snapshot fails the gate; the verdict stamps the
#    window p95), SIGTERMs it, and asserts a clean drain (exit 143,
#    final digest line) with ZERO post-warm compiles — the
#    compile-once/serve-many contract, end to end (MCT_SERVE_SMOKE=0
#    skips). FATAL. The full concurrent soak is slow-marked in
#    tests/test_serve.py.
#
# 3e. runs the crash-respawn smoke (distinct exit code 8): the same
#    daemon with the PROCESS-ISOLATED device worker and a scripted
#    SIGKILL under a request (crash:lg-b.device:1). Asserts the daemon
#    survives, the request is requeued with a typed worker_crash status
#    and answers ok, neighbors are untouched, and the RESPAWNED worker's
#    digest books zero compiles (persistent AOT cache + compilation-cache
#    warm start) — the crash-containment contract, end to end
#    (MCT_SERVE_CRASH_SMOKE=0 skips). FATAL. The mid-burst telemetry poll
#    additionally asserts the cross-process relay delivered the child's
#    counters (worker.telem_messages / serve.requests_ok /
#    pipeline.host_sync present in the parent's cumulative snapshot) —
#    an isolated worker with a dark relay fails here. The drill also
#    asserts the FLIGHT-RECORDER postmortem (obs/flight.py): the
#    supervisor must dump a black box at SIGKILL time (parent ring +
#    the child's relayed flight deltas), the dump must name the victim
#    request with its child-side lifecycle rows, and
#    `obs.trace --blackbox` must fold it into a causal timeline that
#    reaches crash -> requeue -> respawn. These assertions live INSIDE
#    scripts/load_gen.py's crash-drill path — same gate, same exit
#    code 8, first-failing-gate-wins unchanged.
#
# 3f. runs the streaming smoke (distinct exit code 9): a 2-scene CPU run
#    at chunk 8 through the chunked streaming accumulator
#    (scripts/stream_smoke.py) — asserts the convergence digest
#    (chunk>=F artifacts byte-identical to batch, multi-chunk instance
#    count matches), ZERO post-warm compiles across chunks 2..K under a
#    frozen retrace sanitizer, and the per-chunk residency cap
#    (stream.max_plane_bytes strictly under the full-scene plane set) —
#    the live-scan contract, end to end (MCT_STREAM_SMOKE=0 skips).
#    FATAL. The full acceptance matrix lives in tests/test_streaming.py.
#
# 3g. runs the canary sentinel drill (distinct exit code 10): a
#    sentinel-armed warm-baseline daemon soaks clean against the
#    COMMITTED canary_goldens.json (>= 2 canary rounds, zero drift,
#    every goldens coordinate verified, zero post-warm compiles), then
#    a scripted silent bit-flip (corrupt:A.host — no exception, so the
#    retry/degradation ladder CANNOT heal it) must be detected on the
#    first canary round, dump a canary_drift postmortem naming the
#    coordinate, and page `obs.slo --check`'s zero-tolerance
#    `correctness` objective (exit 2) — the correctness-observability
#    contract, end to end (MCT_CANARY_DRILL=0 skips). FATAL. The
#    cross-topology digest pins live in tests/test_sentinel.py.
#
# 3h. runs the continuous-batching pack drill (distinct exit code 11):
#    the same 8-request mixed-bucket burst through a sequential daemon
#    and through a packing daemon (serve_batch_max=3, open-loop
#    arrivals via load_gen --rate). Asserts per-scene artifact digests
#    and exported artifact CRCs byte-identical across the two paths,
#    zero post-warm compiles in the packed daemon (warm pad lanes keep
#    partial batches on the one width-S executable), and batch
#    occupancy > 1.0 — the continuous-batching contract, end to end
#    (MCT_PACK_SMOKE=0 skips). FATAL. The scheduler unit matrix lives
#    in tests/test_serve_batch.py.
#
# 3i. runs the multi-worker pool drill (distinct exit code 12): one
#    daemon carves the (virtual) mesh into a 2x1 pool — two supervised
#    worker subprocesses behind one socket — and the drill asserts the
#    whole pool contract: >= 90% bucket-warm routing post-warm (the
#    affinity scheduler), 3:1 weighted-fair dequeue under saturation,
#    typed quota rejects at the admission limit, a mid-request SIGKILL
#    of worker 0 contained to its slice (neighbor traffic untouched,
#    victim requeued and answered ok, flight recorder + journal record
#    the hop, respawn warm off the shared AOT cache), per-scene artifact
#    digests unanimous across slices, cross-worker device-phase span
#    overlap (the single-device CI form of the throughput claim), and
#    ZERO post-warm compiles on EVERY slice (MCT_POOL_DRILL=0 skips).
#    FATAL. The scheduler/carve unit matrix lives in
#    tests/test_serve_pool.py.
#
# 3j. runs the durability chaos drill (distinct exit code 13): three
#    daemon generations over ONE shared admission WAL + AOT cache +
#    stream_state directory. Generation 1 SIGKILLs a pool child with a
#    live-scan session open — the session must RE-OPEN from its
#    per-chunk snapshot on a warm slice (serve.streams_resumed) and
#    finish, not answer stream_lost. Generation 2 dies by a scripted
#    die:*.admission FaultPlan SIGKILL of the WHOLE daemon between the
#    WAL admit row and the queue — the worst torn state — while
#    idempotency-keyed requests are mid-flight. Generation 3 restarts
#    over the same journal dir: the WAL replays every journaled-but-
#    unanswered request, resubmits of ALL keys answer ok (cached
#    terminal stamped `deduped`, live re-attach, or fresh run), the
#    stream re-runs end to end, artifact CRCs are byte-identical to
#    the pre-death baseline, and the restarted daemon books ZERO
#    compiles (shared AOT cache -> warm restart) — the durability
#    contract, end to end (MCT_CHAOS_DRILL=0 skips). FATAL. The WAL /
#    failover unit matrix lives in tests/test_durable.py.
#
# BASELINE defaults to BENCH_builder_r05.json (the newest committed bench
# verdict with a numeric headline; any JSON doc with a `value` or a ledger
# JSONL works). LEDGER defaults to PERF_LEDGER.jsonl / $MCT_PERF_LEDGER.
# Exits non-zero on test failures (1), a fault-matrix failure (3), an
# mct-check finding or ruff violation (4), a concurrency-family finding
# (5), a retrace-family finding (6), a serve-smoke failure (7), a
# crash-respawn smoke failure (8), a streaming-smoke failure (9), a
# canary-drill failure (10), a pack-drill failure (11), a pool-drill
# failure (12), a chaos-drill failure (13), or a perf regression (2), so
# it gates correctness, fault tolerance, the invariants, thread safety,
# the compile surface, the serving layer, crash containment, the
# streaming contract, correctness observability, the packing scheduler,
# multi-worker serving, durability across process death AND the
# trajectory.
# Every gate still RUNS after a failure, but the exit code is the FIRST
# failing gate's — triage by exit code points at the right gate.
set -u -o pipefail

cd "$(dirname "$0")/.."
BASELINE="${1:-BENCH_builder_r05.json}"
LEDGER="${2:-${MCT_PERF_LEDGER:-PERF_LEDGER.jsonl}}"
THRESHOLD="${MCT_REGRESS_THRESHOLD:-0.15}"
rc=0
fail() { [ "$rc" -eq 0 ] && rc=$1 || true; }  # first failure wins the exit code

WALL_WARN="${MCT_TIER1_WALL_WARN:-800}"
T1LOG=$(mktemp /tmp/mct_tier1_XXXX.log)
# the point-axis sharding identity path (tests/test_point_sharding.py:
# 2-shard fused-step byte identity + sharded batch artifacts + drain
# counter pins) rides THIS gate — no separate gate needed; the 1M-point
# acceptance scene and the 3-axis lattice sweep are slow-marked
echo "== ci: tier-1 tests =="
t0=$(date +%s)
if ! timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors --durations=10 \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee "$T1LOG"; then
    echo "ci: tier-1 tests FAILED" >&2
    fail 1
fi
wall=$(( $(date +%s) - t0 ))
# pytest's summary line ("N passed ... in Ns") -> the pass count
t1_passed=$(grep -aoE '[0-9]+ passed' "$T1LOG" | tail -1 | grep -oE '[0-9]+' || echo 0)
rm -f "$T1LOG"
echo "== ci: tier-1 wall ${wall}s, ${t1_passed} passed (budget: warn >${WALL_WARN}s of the 870s timeout) =="
if [ "$wall" -gt "$WALL_WARN" ]; then
    # non-fatal: the suite still passed, but the headroom is gone — trim
    # the slowest tests (see the --durations table above) onto the shared
    # small fixtures before the 870 s hard timeout starts eating the run
    echo "ci: WARNING tier-1 wall ${wall}s exceeds the ${WALL_WARN}s soft budget" >&2
fi
# durable trajectory: one tier1 ledger row per CI run, fenced from the
# bench/run --regress pick (obs/ledger.FENCED_TOOLS) so the 870s budget is
# tracked by the same machinery as perf (gate it with a tier1 baseline:
# python -m maskclustering_tpu.obs.report --regress <tier1 row/ledger>)
env JAX_PLATFORMS=cpu python - "$LEDGER" "$wall" "$t1_passed" <<'EOF' || \
    echo "ci: WARNING tier1 ledger row append failed (non-fatal)" >&2
import sys
from maskclustering_tpu.obs import ledger as led
path, wall, passed = sys.argv[1], float(sys.argv[2]), int(sys.argv[3])
led.append_row(path, led.tier1_row(wall, passed))
EOF

if [ "${MCT_FAULT_SMOKE:-1}" != "0" ]; then
    echo "== ci: fault-matrix smoke (3 canned FaultPlans, 2-scene CPU run, <60s) =="
    if ! timeout -k 10 60 env JAX_PLATFORMS=cpu python scripts/fault_smoke.py; then
        echo "ci: fault-matrix smoke FAILED" >&2
        fail 3
    fi
fi

if [ "${MCT_CHECK:-1}" != "0" ]; then
    echo "== ci: mct-check static invariant gate (IR + AST, CPU, <90s) =="
    if ! timeout -k 10 90 env JAX_PLATFORMS=cpu \
            python -m maskclustering_tpu.analysis --families ast,ir; then
        echo "ci: mct-check FAILED (fix the finding at its file:line, or" \
             "baseline it in analysis_baseline.json with a justification)" >&2
        fail 4
    fi
    echo "== ci: mct-check concurrency gate (thread topology + lock order, <30s) =="
    if ! timeout -k 10 30 env JAX_PLATFORMS=cpu \
            python -m maskclustering_tpu.analysis --families concurrency; then
        echo "ci: mct-check concurrency FAILED (fix the thread-safety" \
             "finding, annotate with # mct-thread:, or baseline it in" \
             "analysis_baseline.json with a justification)" >&2
        fail 5
    fi
    echo "== ci: mct-check retrace gate (compile-surface census + capture lint, <240s) =="
    if ! timeout -k 10 240 env JAX_PLATFORMS=cpu \
            python -m maskclustering_tpu.analysis --families retrace; then
        echo "ci: mct-check retrace FAILED (a compile variant joined or" \
             "left the surface: fix the capture/branch/jit-site finding," \
             "or audit the census diff and regenerate" \
             "compile_surface_baseline.json with --write-surface)" >&2
        fail 6
    fi
fi

if [ "${MCT_SERVE_SMOKE:-1}" != "0" ]; then
    echo "== ci: serve daemon smoke (spawn daemon + load_gen burst, SIGTERM drain, <300s) =="
    # bounded end-to-end gate on the serving layer: a sanitizer-armed
    # daemon warms two tiny buckets, serves a mixed-bucket burst through
    # scripts/load_gen.py (smoke default tenant mix A:3,B:1 — per-tenant
    # accounting must sum back to the global window, and the healthy
    # soak must pass the default SLO spec), and must drain SIGTERM-clean
    # with ZERO post-warm compiles (the serve-many contract) — the full
    # soak lives slow-marked in tests/test_serve.py
    if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
            python scripts/load_gen.py --smoke --requests 6 \
            --concurrency 3 --no-ledger; then
        echo "ci: serve daemon smoke FAILED (daemon wedged, a request" \
             "failed, or the retrace sanitizer booked post-warm compiles)" >&2
        fail 7
    fi
fi

if [ "${MCT_SERVE_CRASH_SMOKE:-1}" != "0" ]; then
    echo "== ci: crash-respawn smoke (isolated worker, SIGKILL drill + zero-compile respawn, <420s) =="
    # the crash-containment gate: a real SIGKILL of the device-owning
    # worker subprocess under a request must cost a respawn + requeue,
    # not the daemon — and the respawned worker must reach first dispatch
    # warm off the persistent AOT/compilation caches (zero compiles)
    if ! timeout -k 10 420 env JAX_PLATFORMS=cpu \
            python scripts/load_gen.py --smoke --crash-drill --requests 4 \
            --concurrency 2 --no-ledger; then
        echo "ci: crash-respawn smoke FAILED (daemon died with its worker," \
             "the request was not requeued, or the respawned worker" \
             "recompiled)" >&2
        fail 8
    fi
fi

if [ "${MCT_STREAM_SMOKE:-1}" != "0" ]; then
    echo "== ci: streaming smoke (2-scene chunked run, convergence + zero post-warm compiles, <240s) =="
    # the live-scan gate: chunk>=F byte identity, multi-chunk convergence,
    # frozen-sanitizer zero compiles across chunks 2..K, residency cap
    if ! timeout -k 10 240 env JAX_PLATFORMS=cpu python scripts/stream_smoke.py; then
        echo "ci: streaming smoke FAILED (streaming diverged from batch," \
             "a post-warm chunk compiled, or the residency cap broke)" >&2
        fail 9
    fi
fi

if [ "${MCT_CANARY_DRILL:-1}" != "0" ]; then
    echo "== ci: canary sentinel drill (clean soak + scripted corruption, <600s) =="
    # the correctness-observability gate: a sentinel-armed warm-baseline
    # daemon must soak clean against the COMMITTED canary_goldens.json
    # (zero drift, zero post-warm compiles — probes replay warm
    # executables), then a scripted corrupt:A.host bit-flip (silent — the
    # retry ladder never sees it) must drift on the FIRST canary round,
    # emit the typed canary.drift event + canary_drift flight dump, and
    # page obs.slo's zero-tolerance correctness objective (exit 2)
    if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
            python scripts/load_gen.py --canary-drill --no-ledger; then
        echo "ci: canary sentinel drill FAILED (drift on a clean soak" \
             "means outputs changed or goldens are stale — audit, then" \
             "regenerate with load_gen --write-goldens; an undetected" \
             "corruption means the sentinel plane is dark)" >&2
        fail 10
    fi
fi

if [ "${MCT_PACK_SMOKE:-1}" != "0" ]; then
    echo "== ci: continuous-batching pack drill (packed vs sequential byte identity, <560s) =="
    # the packing-scheduler gate: the same 8-request mixed-bucket burst
    # runs once through the sequential path and once (open-loop arrivals)
    # through the scene-axis packing scheduler — per-scene artifact
    # digests and exported artifact CRCs must match byte for byte, the
    # packed daemon must book ZERO post-warm compiles at every occupancy
    # (warm synthetic pad lanes keep partial batches on the width-S
    # executable), and occupancy must exceed 1.0 (the scheduler actually
    # fused) — the continuous-batching contract, end to end
    if ! timeout -k 10 560 env JAX_PLATFORMS=cpu \
            python scripts/load_gen.py --pack-drill --requests 8 \
            --no-ledger; then
        echo "ci: pack drill FAILED (packed artifacts diverged from" \
             "sequential, a partial batch recompiled, or the scheduler" \
             "never fused a batch)" >&2
        fail 11
    fi
fi

if [ "${MCT_POOL_DRILL:-1}" != "0" ]; then
    echo "== ci: multi-worker pool drill (2x1 carve: affinity + QoS + SIGKILL containment, <600s) =="
    # the worker-pool gate: one daemon carves the (virtual) mesh into two
    # slices and must route >= 90% bucket-warm post-warm, front-load the
    # heavy:3 tenant's completions 3:1 under saturation, answer typed
    # quota rejects over capped's admission limit, contain a mid-request
    # SIGKILL of worker 0 (neighbor untouched, victim requeued + ok,
    # black box + journal record the hop, respawn warm off the shared
    # AOT cache), serve byte-identical artifacts on every slice, and
    # overlap device phases across workers — zero post-warm compiles on
    # EVERY slice
    if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
            python scripts/load_gen.py --pool-drill --no-ledger; then
        echo "ci: pool drill FAILED (a slice went cold/unbalanced, QoS or" \
             "quota broke, the crash leaked past its slice, or a worker" \
             "recompiled post-warm)" >&2
        fail 12
    fi
fi

if [ "${MCT_CHAOS_DRILL:-1}" != "0" ]; then
    echo "== ci: durability chaos drill (killed worker mid-stream + killed daemon mid-queue, <600s) =="
    # the durability gate: a SIGKILLed pool child must NOT lose its open
    # live-scan session (snapshot failover, serve.streams_resumed >= 1),
    # a SIGKILLed daemon must NOT lose its admitted queue (WAL replay on
    # restart), idempotent resubmits of every key must answer ok
    # (deduped / re-attached / fresh), artifacts must stay byte-identical
    # across both deaths, and the restarted daemon must book ZERO
    # compiles off the shared AOT cache — eventual completion through
    # process death, end to end
    if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
            python scripts/load_gen.py --chaos-drill --no-ledger; then
        echo "ci: chaos drill FAILED (a killed worker lost its stream, a" \
             "killed daemon lost journaled requests, a resubmit did not" \
             "dedupe, artifacts diverged across the death, or the warm" \
             "restart recompiled)" >&2
        fail 13
    fi
fi

if command -v ruff >/dev/null 2>&1; then
    RUFF_PIN=$(grep -oE 'ruff==[0-9.]+' pyproject.toml | head -1)
    RUFF_HAVE="ruff==$(ruff --version 2>/dev/null | awk '{print $2}')"
    if [ "$RUFF_HAVE" = "$RUFF_PIN" ]; then
        echo "== ci: ruff ($RUFF_PIN, config: pyproject.toml [tool.ruff]) =="
        if ! ruff check .; then
            echo "ci: ruff FAILED" >&2
            fail 4
        fi
    else
        # only the pinned version gates: a floating ruff's new/changed
        # rules turning CI red is what the pyproject pin exists to prevent
        echo "== ci: ruff $RUFF_HAVE != pinned $RUFF_PIN — ADVISORY only" \
             "(pip install -e '.[dev]' for the gating version) =="
        ruff check . || echo "ci: WARNING unpinned ruff found violations" \
                             "(non-fatal; verify against $RUFF_PIN)" >&2
    fi
else
    echo "== ci: ruff not installed; skipping the lint front-end" \
         "(pip install -e '.[dev]' to enable) =="
fi

echo "== ci: perf regression gate ($LEDGER vs $BASELINE, >$THRESHOLD p50) =="
if [ ! -f "$LEDGER" ]; then
    echo "ci: no ledger at $LEDGER; skipping the perf gate" >&2
elif ! python -m maskclustering_tpu.obs.report --ledger "$LEDGER" \
        --regress "$BASELINE" --regress-threshold "$THRESHOLD"; then
    echo "ci: perf regression gate FAILED" >&2
    fail 2
fi

exit $rc
