"""One-command demo: the reference demo.sh golden path with zero downloads.

The reference's fast demo (reference demo.sh, README.md:24-48) needs a
1 GB drive download (scene0608_00 RGB-D + CropFormer masks) before
`main.py --config demo` can run. This script replaces the download with a
ray-traced synthetic apartment scene written in the exact on-disk ScanNet
layout (color/ depth/ pose/ intrinsic/ output/mask/ + vh_clean_2.ply + GT),
then drives the SAME seven-step orchestrator a real run uses — clustering,
class-agnostic export, AP evaluation against the scene's GT, open-vocab
semantics on the hash encoder, and the headless scene visualizer — and
prints where every artifact landed.

    python scripts/demo.py                 # TPU if available, else CPU
    python scripts/demo.py --platform cpu  # force CPU (~1 min)

Everything is written under --out (default ./output/demo_data); re-running
resumes from artifacts like the real orchestrator.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="./output/demo_data",
                   help="data_root for the generated scene + all artifacts")
    p.add_argument("--seq", default="demo0001_00")
    p.add_argument("--frames", type=int, default=32)
    p.add_argument("--objects", type=int, default=6)
    p.add_argument("--image-h", type=int, default=240)
    p.add_argument("--image-w", type=int, default=320)
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu); default = real TPU")
    args = p.parse_args()

    from maskclustering_tpu.utils.backend_init import init_backend
    init_backend(args.platform, timeout_s=120.0, tag="demo")

    from maskclustering_tpu import load_config
    from maskclustering_tpu.run import run_pipeline
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    data_root = os.path.abspath(args.out)
    scene_dir = os.path.join(data_root, "scannet", "processed", args.seq)
    gen_params = {"frames": args.frames, "objects": args.objects,
                  "image_h": args.image_h, "image_w": args.image_w}
    meta_path = os.path.join(scene_dir, "demo_scene_meta.json")
    if os.path.isdir(scene_dir):
        import json
        stamped = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                stamped = json.load(f)
        if stamped != gen_params:
            print(f"[demo] ERROR: {scene_dir} holds a scene generated with "
                  f"{stamped}, but this run asked for {gen_params}.\n"
                  f"[demo] pick a different --out or delete that directory "
                  f"to regenerate.", file=sys.stderr)
            return 2
        print(f"[demo] reusing generated scene at {scene_dir}")
    else:
        print(f"[demo] generating a {args.frames}-frame synthetic scene "
              f"({args.objects} objects) ...")
        scene = make_scene(num_boxes=args.objects, num_frames=args.frames,
                           image_hw=(args.image_h, args.image_w), seed=608)
        write_scannet_layout(scene, data_root, args.seq)
        import json
        with open(meta_path, "w") as f:
            json.dump(gen_params, f)
        print(f"[demo] wrote ScanNet-layout scene to {scene_dir}")

    cfg = load_config("scannet").replace(
        config_name="demo", data_root=data_root, step=1,
        distance_threshold=0.03, mask_pad_multiple=64)

    steps = ("masks", "cluster", "eval_ca", "features", "label_features",
             "query", "eval", "vis", "top_images")
    t0 = time.time()
    report = run_pipeline(cfg, [args.seq], steps=steps, encoder_spec="hash:64",
                          report_path=os.path.join(data_root, "report.json"))
    dt = time.time() - t0

    scene_status = report.scenes[0] if report.scenes else None
    n_obj = scene_status.num_objects if scene_status else 0
    print(f"\n[demo] pipeline finished in {dt:.1f}s; "
          f"{n_obj} objects recovered (planted: {args.objects})")
    for name, secs in report.step_seconds.items():
        err = " FAILED" if name in report.step_errors else ""
        print(f"[demo]   step {name:<14} {secs:6.1f}s{err}")

    print("[demo] artifacts:")
    for rel in (f"prediction/demo_class_agnostic/{args.seq}.npz",
                f"scannet/processed/{args.seq}/output/object/demo/object_dict.npy",
                "evaluation/scannet/demo_class_agnostic.txt",
                f"vis/{args.seq}/instances.ply",
                f"vis/{args.seq}/top_images/grid",
                "report.json"):
        path = os.path.join(data_root, rel)
        mark = "ok" if os.path.exists(path) else "MISSING"
        print(f"[demo]   [{mark:^7}] {path}")

    eval_txt = os.path.join(data_root, "evaluation", "scannet",
                            "demo_class_agnostic.txt")
    if os.path.exists(eval_txt):
        with open(eval_txt) as f:
            lines = [ln.rstrip() for ln in f if ln.strip()]
        print("[demo] class-agnostic AP vs the generated GT "
              "(non-nan classes + average):")
        for ln in lines:
            if "nan" not in ln:
                print(f"[demo]   {ln}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
