"""Micro-benchmarks of TPU primitive costs that drive postprocess/association design.

Run on the live chip: python scripts/micro_tpu.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync(out):
    leaves = jax.tree_util.tree_leaves(out)
    # force a real device->host roundtrip of one element: block_until_ready
    # can be a no-op on tunneled platforms
    for x in leaves:
        np.asarray(jax.device_get(x.ravel()[:1] if hasattr(x, "ravel") else x))


def timeit(name, fn, *args, iters=5):
    _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        _sync(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:55s} {dt*1e3:9.2f} ms")
    return dt


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)
    F, N, R = 150, 192 * 1024, 128
    HW = 240 * 320

    # 1. segment_sum: claims-scale scatter into R*N segments
    ids_big = jnp.asarray(rng.integers(0, R * N, size=2 * F * N // 8, dtype=np.int32))  # 7.3M updates
    data = jnp.ones_like(ids_big, dtype=jnp.int32)
    f = jax.jit(lambda d, i: jax.ops.segment_sum(d, i, num_segments=R * N))
    timeit(f"segment_sum 7.3M -> {R*N/1e6:.1f}M segs", f, data, ids_big, iters=2)

    # 1b. segment_sum into small segment count (mask_assign slots)
    ids_small = jnp.asarray(rng.integers(0, 65536, size=2 * F * N, dtype=np.int32))  # 58M updates
    d2 = jnp.ones_like(ids_small, dtype=jnp.int32)
    f2 = jax.jit(lambda d, i: jax.ops.segment_sum(d, i, num_segments=65536))
    timeit("segment_sum 58M -> 64k segs", f2, d2, ids_small, iters=2)

    # 2. per-rep dense loop: R x (F,N) compares via lax.map
    A = jnp.asarray(rng.integers(-1, R, size=(F, N), dtype=np.int16))
    nv = jnp.asarray(rng.random((R, F)) < 0.5)

    def perrep(A, nv):
        def one(r):
            eq = A == r.astype(jnp.int16)
            claimed = jnp.any(eq, axis=0)
            num = jnp.sum(eq & nv[r][:, None], axis=0, dtype=jnp.int32)
            return claimed, num
        return jax.lax.map(one, jnp.arange(R))
    f3 = jax.jit(perrep)
    timeit(f"per-rep loop R={R} over (F,N) int16", f3, A, nv, iters=2)

    # 3. column sort along frame axis (2F, N)
    K2 = jnp.asarray(rng.integers(0, R, size=(2 * F, N), dtype=np.int32))
    f4 = jax.jit(lambda k: jnp.sort(k, axis=0))
    timeit("sort (300, 192k) along axis0", f4, K2, iters=2)

    # 4. big flat sort (claims sort, node_structs scale)
    flat = jnp.asarray(rng.integers(0, 2**31 - 1, size=2 * F * N, dtype=np.int32))
    f5 = jax.jit(jnp.sort)
    timeit("flat sort 58M int32", f5, flat, iters=1)

    # 5. random gather: association window reads (N gathers from HW table) x9 x3
    table = jnp.asarray(rng.random(HW, dtype=np.float32))
    idx = jnp.asarray(rng.integers(0, HW, size=N, dtype=np.int32))

    def gather9x3(t, i):
        acc = jnp.zeros(N)
        for k in range(27):
            acc = acc + jnp.take(t, (i + k) % HW)
        return acc
    f6 = jax.jit(gather9x3)
    timeit("27x take(192k from 76.8k)  [1 frame assoc]", f6, table, idx, iters=5)

    # 6. matmul (R,F)@(F,N) bf16
    nvb = nv.astype(jnp.bfloat16)
    pv = jnp.asarray(rng.random((F, N)) < 0.5).astype(jnp.bfloat16)
    f7 = jax.jit(lambda a, b: jnp.matmul(a, b, preferred_element_type=jnp.float32))
    timeit("matmul (128,150)@(150,192k) bf16", f7, nvb, pv, iters=5)

    # 7. one-hot matmul claims: onehot(A) per frame scan accumulate
    def onehot_scan(A, nv):
        def step(acc, fa):
            a, nvf = fa
            oh = jax.nn.one_hot(a, R, dtype=jnp.bfloat16, axis=0)  # (R, N)
            return acc + oh * nvf[:, None], None
        acc0 = jnp.zeros((R, N), jnp.bfloat16)
        out, _ = jax.lax.scan(step, acc0, (A.astype(jnp.int32), nv.T.astype(jnp.bfloat16)))
        return out
    f8 = jax.jit(onehot_scan)
    timeit("scan-F onehot accumulate (R,N)", f8, A, nv, iters=2)

    # 8. scatter .at[].add columns: (F scans of N-updates into (R,N))
    def scatter_cols(A, nv):
        def step(acc, fa):
            a, nvf = fa
            ac = jnp.clip(a, 0, R - 1).astype(jnp.int32)
            w = jnp.take(nvf, ac).astype(jnp.int32)
            return acc.at[ac, jnp.arange(N)].add(w), None
        out, _ = jax.lax.scan(step, jnp.zeros((R, N), jnp.int32),
                              (A, nv.T.astype(jnp.int32)))
        return out
    f9 = jax.jit(scatter_cols)
    timeit("scan-F scatter-add cols into (R,N)", f9, A, nv, iters=1)


if __name__ == "__main__":
    main()


def overhead():
    import jax, numpy as np, time
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(())
    _sync(f(x))
    t0 = time.perf_counter()
    for _ in range(10):
        _sync(f(x))
    print(f"sync+trivial-op roundtrip: {(time.perf_counter()-t0)/10*1e3:.2f} ms")
    # amortized: run op 10x chained inside one jit to separate compute from latency
