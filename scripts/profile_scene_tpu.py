"""Capture a jax.profiler trace of one bench-scale run_scene on a live chip.

Produces a TensorBoard-compatible trace directory with per-op device
timelines (the committed summary lives in PROFILE.md). Run on a machine
with a healthy TPU:

    python scripts/profile_scene_tpu.py --trace-dir /tmp/mct_trace

then `tensorboard --logdir /tmp/mct_trace` (or xprof) to inspect.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--trace-dir", default="/tmp/mct_trace")
    p.add_argument("--frames", type=int, default=250)
    p.add_argument("--points", type=int, default=196608)
    p.add_argument("--boxes", type=int, default=36)
    p.add_argument("--image-h", type=int, default=480)
    p.add_argument("--image-w", type=int, default=640)
    p.add_argument("--distance-threshold", type=float, default=0.01)
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    print(f"devices: {jax.devices()}", file=sys.stderr, flush=True)

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.utils.compile_cache import setup_compilation_cache
    from maskclustering_tpu.utils.synthetic import (make_scene_device,
                                                    resize_scene_points)

    setup_compilation_cache()
    tensors, _, _ = make_scene_device(
        num_boxes=args.boxes, num_frames=args.frames,
        image_hw=(args.image_h, args.image_w), seed=0)
    tensors.scene_points = resize_scene_points(tensors.scene_points,
                                               args.points)
    cfg = PipelineConfig(config_name="profile", dataset="demo",
                         distance_threshold=args.distance_threshold,
                         point_chunk=8192)

    t0 = time.time()
    run_scene(tensors, cfg, k_max=63)  # warm-up: compile outside the trace
    print(f"warm-up {time.time()-t0:.1f}s", file=sys.stderr, flush=True)

    with jax.profiler.trace(args.trace_dir):
        t0 = time.time()
        result = run_scene(tensors, cfg, k_max=63)
        dt = time.time() - t0
    print(f"traced run: {dt:.2f}s, timings "
          f"{ {k: round(v, 2) for k, v in result.timings.items()} }",
          file=sys.stderr, flush=True)
    print(f"trace written to {args.trace_dir}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
