#!/bin/bash
# One-shot live-chip capture session, priority-ordered for short recovery
# windows (round 4 lost its headline number to a wedge; round 5's second
# window lasted ~35 min). Runs each step with its own timeout and keeps
# going on failure, so whatever the window allows is captured.
#
#   bash scripts/chip_session.sh [OUTDIR]
#
# Env knobs (for smoke-testing the harness itself off-chip):
#   MCT_PLATFORM=cpu  force a jax platform on every step
#   MCT_QUICK=1       tiny shapes (validates plumbing, not performance)
#   MCT_NO_OBS=1      disable the default obs span/metrics capture
#   MCT_NO_PREFLIGHT=1        skip the wait-for-healthy preflight loop
#   MCT_PREFLIGHT_BUDGET=900  max seconds to wait for a healthy backend
#
# The session starts with a wait-for-healthy preflight: a bounded loop of
# 60 s backend probes (python -m maskclustering_tpu.utils.backend_init)
# with growing sleeps, so the session ARMS ITSELF and captures the moment
# a healthy window opens instead of burning the window on a failed fast
# start (VERDICT Next #1: "armed from session start"). An exhausted
# budget proceeds anyway — every step below has its own retries/timeouts.
#
# Steps, most valuable first (each writes OUTDIR/NAME.out + NAME.err):
#   1. bench.py (honest shape, 5 repeats)      -> bench_default.out (JSON line)
#      + obs events (default-armed)            -> bench_default_events.jsonl
#   2. claims_diag (kernel vs tunnel split,    -> claims_diag.out
#      + int16 claim-plane drain bytes)
#   3. fb_identity (frame-batch byte-identity  -> fb_identity.out
#      on the LIVE backend; CPU-only pinned by tests until this runs)
#   4. bench.py --count-dtype int8 (A/B vs     -> bench_int8.out (JSON line)
#      step 1's bf16 default: the s8-MXU counting-path wall-clock number;
#      flips cfg.count_dtype's default when it wins)
#   5. bench.py --frame-batch 8 (A/B; VERDICT  -> bench_fb8.out (JSON line)
#      Weak #4's decision record — this capture flips the
#      association_frame_batch default to 8 or kills the knob)
#   5b. point-shard A/B (ISSUE 14, advisory)   -> point_shard_{a,b}.out
#      mesh_bench 1M-point fused workload, frame-only 1x8 vs point-sharded
#      1x2x4 on the LIVE backend — the on-chip number next to
#      MESH_BENCH.md's static point-axis census
#   5c. streaming A/B (ISSUE 15, advisory)     -> stream_ab_{batch,chunk8}.out
#      batch vs chunk-8 accumulation, one PROCESS per variant (gauge_max
#      isolation) — wall + residency rows in STREAM_AB_{batch,chunk8}.json
#   6. northstar sweep (multi-bucket, ~3 min)  -> northstar.out + NORTHSTAR_live.md
#   7. obs report render of the bench captures -> obs_report.out
#      (+ per-stage diffs of both A/B runs against the default)
#   8. cost observatory (CPU AOT; no chip time) -> cost_census.out + cost_events.jsonl
#      + dtype census (bf16-vs-int8 AOT diff)  -> dtype_census.out
#      + mct-check advisories (ast/ir, concurrency, retrace) on their own
#        events files -> mct_check.out / conc_check.out / retrace_check.out
#   9. perf ledger history + regress gate      -> perf_ledger.out
#      (bench steps above append rows to PERF_LEDGER.jsonl by default;
#      rows carry count_dtype/plane_dtype so A/B deltas self-attribute)
#   MCT_XPROF=SPANS adds a 1-repeat xprof capture bench step (e.g.
#   MCT_XPROF=cluster,post.claims.kernel) -> xprof_trace.out + $OUT/xprof/
set -u
cd "$(dirname "$0")/.."
# date AND time in the default OUTDIR: same-minute sessions on later days
# must not silently overwrite earlier captures
OUT=${1:-/tmp/chip_session_$(date -u +%m%d_%H%M)}
mkdir -p "$OUT"
echo "[chip_session] output -> $OUT"

PLAT=()
[ -n "${MCT_PLATFORM:-}" ] && PLAT=(--platform "$MCT_PLATFORM")
TINY=()
DIAG_QUICK=()
NS_QUICK=()
if [ -n "${MCT_QUICK:-}" ]; then
  # one source of truth for the quick shape: DIAG_QUICK is the subset
  # claims_diag accepts
  DIAG_QUICK=(--frames 8 --points 4096 --boxes 3)
  TINY=("${DIAG_QUICK[@]}" --image-h 48 --image-w 64 --repeats 1 --spacing 0.08)
  NS_QUICK=(--quick)
fi
# obs capture armed by default: every bench step leaves a span/metrics JSONL
# that `python -m maskclustering_tpu.obs.report` renders per-stage — the
# kernel-vs-transfer split becomes a by-product of any session, not a
# bespoke diagnostic that needs its own recovery window
OBS_DEFAULT=(--obs-events "$OUT/bench_default_events.jsonl")
OBS_INT8=(--obs-events "$OUT/bench_int8_events.jsonl")
OBS_FB8=(--obs-events "$OUT/bench_fb8_events.jsonl")
if [ -n "${MCT_NO_OBS:-}" ]; then
  OBS_DEFAULT=(--no-obs)
  OBS_INT8=(--no-obs)
  OBS_FB8=(--no-obs)
fi
# flight recorder armed for the whole session (obs/flight.py reads
# $MCT_FLIGHT_DIR): a watchdog fire, capacity error or SIGTERM in ANY
# step leaves a postmortem ring under $OUT/flight — render it with
#   python -m maskclustering_tpu.obs.flight "$OUT/flight"
# A wedged round-4-style window then costs a dump, not the whole story.
if [ -z "${MCT_NO_OBS:-}" ]; then
  export MCT_FLIGHT_DIR="$OUT/flight"
  mkdir -p "$MCT_FLIGHT_DIR"
fi

preflight() { # wait-for-healthy: bounded probe-retry before the first bench
  local budget=${MCT_PREFLIGHT_BUDGET:-900} t0 attempt=1 elapsed pause
  t0=$(date +%s)
  while :; do
    if timeout 90 python -m maskclustering_tpu.utils.backend_init --timeout 60 \
        ${PLAT[@]+"${PLAT[@]}"} >"$OUT/preflight.out" 2>"$OUT/preflight.err"; then
      echo "[chip_session] preflight: backend healthy after $attempt probe(s)" \
           "($(( $(date +%s) - t0 ))s) — window open, capturing now"
      return 0
    fi
    elapsed=$(( $(date +%s) - t0 ))
    if [ "$elapsed" -ge "$budget" ]; then
      echo "[chip_session] preflight: no healthy window within ${budget}s;" \
           "proceeding anyway (steps carry their own retries)"
      return 1
    fi
    pause=$(( attempt * 15 )); [ "$pause" -gt 60 ] && pause=60
    echo "[chip_session] preflight: probe $attempt unhealthy" \
         "(${elapsed}s/${budget}s); re-probing in ${pause}s"
    sleep "$pause"
    attempt=$(( attempt + 1 ))
  done
}
[ -z "${MCT_NO_PREFLIGHT:-}" ] && preflight

run() { # run NAME TIMEOUT CMD...
  local name=$1 tmo=$2; shift 2
  echo "[chip_session] === $name (timeout ${tmo}s) ==="
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.err"
  local rc=$?
  echo "[chip_session] $name rc=$rc"
  tail -3 "$OUT/$name.out" 2>/dev/null
  return 0
}

run bench_default 900 python bench.py --retry-budget 300 --init-attempts 2 "${OBS_DEFAULT[@]}" ${PLAT[@]+"${PLAT[@]}"} ${TINY[@]+"${TINY[@]}"}
run claims_diag   600 python scripts/claims_diag.py ${PLAT[@]+"${PLAT[@]}"} ${DIAG_QUICK[@]+"${DIAG_QUICK[@]}"}
run fb_identity   600 python scripts/fb_identity.py --frame-batch 8 ${PLAT[@]+"${PLAT[@]}"}
# the two knob A/Bs, run back-to-back against step 1's default record:
# int8 counting path (tentpole — the s8-MXU wall-clock number) and the
# frame-batch hypothesis (VERDICT Weak #4 — this record settles the knob)
run bench_int8    700 python bench.py --retry-budget 200 --init-attempts 2 --count-dtype int8 "${OBS_INT8[@]}" ${PLAT[@]+"${PLAT[@]}"} ${TINY[@]+"${TINY[@]}"}
run bench_fb8     700 python bench.py --retry-budget 200 --init-attempts 2 --frame-batch 8 "${OBS_FB8[@]}" ${PLAT[@]+"${PLAT[@]}"} ${TINY[@]+"${TINY[@]}"}
# mct-sentinel on-chip check (ADVISORY, ISSUE 17): one canary round on
# the LIVE backend, byte-compared against the committed CPU-generated
# canary_goldens.json — the digests are exact integer reductions, so a
# mismatch here is real silent data corruption on the chip or a
# nondeterministic lowering, the first thing to read after a session.
# Advisory by design (the `run` helper never aborts the window);
# scripts/ci.sh's canary drill (exit 10) is the fatal CPU half.
cat > "$OUT/sentinel_check.py" <<'PYEOF'
import json, sys
from maskclustering_tpu.obs import canary
from maskclustering_tpu.run import init_backend_or_die
doc = canary.load_goldens()
if doc is None:
    print(json.dumps({"sentinel": "skipped", "reason":
                      "no usable canary_goldens.json at the repo root — "
                      "regenerate via scripts/load_gen.py --write-goldens"}))
    sys.exit(0)
init_backend_or_die(120.0, platform=sys.argv[1] if len(sys.argv) > 1 else None)
observed = canary.generate_goldens(canary.goldens_config())
drift = 0
for coord in sorted(set(observed) | set(doc["goldens"])):
    row = observed.get(coord)
    verdict = canary.compare_probe(
        {"coord": coord, "scene": (row or {}).get("scene"), "digest": row},
        doc)
    drift += verdict["status"] != "ok"
    print(json.dumps({k: verdict.get(k)
                      for k in ("coord", "scene", "status", "fields")}))
print(json.dumps({"sentinel": "drift" if drift else "ok",
                  "coords": len(observed), "drift": drift}))
sys.exit(1 if drift else 0)
PYEOF
run sentinel_check 700 python "$OUT/sentinel_check.py" ${MCT_PLATFORM:-}
if [ -n "${MCT_XPROF:-}" ] && [ -z "${MCT_NO_OBS:-}" ]; then
  # span-triggered profiler capture: one repeat, first opening of each
  # named span is bracketed by start/stop_trace (obs/xprof.py)
  run xprof_trace 600 python bench.py --retry-budget 200 --init-attempts 2 --repeats 1 \
    --obs-events "$OUT/xprof_events.jsonl" --xprof "$MCT_XPROF" --xprof-dir "$OUT/xprof" \
    --no-ledger ${PLAT[@]+"${PLAT[@]}"} ${TINY[@]+"${TINY[@]}"}
fi
# point-shard A/B (ADVISORY, ISSUE 14): the on-chip half of the
# MESH_BENCH.md point-axis census — the same 1M-point fused workload over
# frame-only (1x8) vs point-sharded (1x2x4) meshes; the wall-clock delta
# is the ICI cost of the psum-over-point traffic the CPU census bounds
# statically. MCT_QUICK drops to the tiny 128k shape.
PS_SHAPE=(--scenes 2 --frames 8 --points 1048576 --image-h 48 --image-w 64)
[ -n "${MCT_QUICK:-}" ] && PS_SHAPE=(--scenes 2 --frames 8 --points 131072 --image-h 48 --image-w 64)
run point_shard_a 900 python scripts/mesh_bench.py --platform tpu --mesh 1 8 \
  --out "$OUT/POINT_SHARD_A.md" "${PS_SHAPE[@]}"
run point_shard_b 900 python scripts/mesh_bench.py --platform tpu --mesh 1 2 \
  --point-shards 4 --out "$OUT/POINT_SHARD_B.md" "${PS_SHAPE[@]}"
# streaming A/B (ADVISORY, ISSUE 15): batch vs chunked accumulation on
# one synthetic scene — the wall-clock delta prices the per-chunk
# re-cluster overhead, and the per-variant obs gauges carry the headline
# residency numbers (stream.max_plane_bytes vs the batch HBM high-water)
# for the next ROADMAP re-anchor. One PROCESS per variant: the registry's
# gauge_max values are process-cumulative, so a shared process would fold
# the batch peak into the chunked row and hide the residency win.
# MCT_QUICK halves the frame count.
SA_FRAMES=64; [ -n "${MCT_QUICK:-}" ] && SA_FRAMES=32
cat > "$OUT/stream_ab_variant.py" <<'PYEOF'
import json, os, sys, tempfile, time
out, frames, tag, chunk = (sys.argv[1], int(sys.argv[2]), sys.argv[3],
                           int(sys.argv[4]))
from maskclustering_tpu.config import load_config
from maskclustering_tpu.run import cluster_scenes
from maskclustering_tpu import obs
from maskclustering_tpu.utils.synthetic import make_scene, write_scannet_layout
root = os.path.join(out, "stream_ab_data")
scene_dir = os.path.join(root, "scannet", "processed", "scene0000_00")
if not os.path.isdir(scene_dir):
    write_scannet_layout(make_scene(num_boxes=6, num_frames=frames,
                                    image_hw=(120, 160), seed=7,
                                    spacing=0.04), root, "scene0000_00")
cfg = load_config("scannet").replace(
    data_root=root, config_name=f"ab_{tag}", step=1,
    distance_threshold=0.05, frame_pad_multiple=8, streaming_chunk=chunk)
t0 = time.perf_counter()
sts = cluster_scenes(cfg, ["scene0000_00"], resume=False)
wall = time.perf_counter() - t0
g = obs.registry().snapshot()["gauges"]
row = {"variant": tag, "streaming_chunk": chunk, "wall_s": round(wall, 3),
       "status": [s.status for s in sts],
       "stream_max_plane_bytes": g.get("stream.max_plane_bytes"),
       "hbm_high_water": g.get("hbm.high_water_bytes")}
with open(os.path.join(out, f"STREAM_AB_{tag}.json"), "w") as f:
    json.dump(row, f, indent=2)
print(json.dumps(row))
PYEOF
run stream_ab_batch  900 python "$OUT/stream_ab_variant.py" "$OUT" "$SA_FRAMES" batch 0
run stream_ab_chunk8 900 python "$OUT/stream_ab_variant.py" "$OUT" "$SA_FRAMES" chunk8 8
run northstar     1200 python scripts/northstar.py --out "$OUT/NORTHSTAR_live.md" ${PLAT[@]+"${PLAT[@]}"} ${NS_QUICK[@]+"${NS_QUICK[@]}"}
if [ -z "${MCT_NO_OBS:-}" ] && [ -f "$OUT/bench_default_events.jsonl" ]; then
  if [ -f "$OUT/bench_int8_events.jsonl" ]; then
    # same A-vs-B orientation as the fb8 diff below: default is always the
    # A side, so a positive delta reads "variant slower" in both files
    run obs_report_int8 120 python -m maskclustering_tpu.obs.report "$OUT/bench_default_events.jsonl" --diff "$OUT/bench_int8_events.jsonl"
  fi
  if [ -f "$OUT/bench_fb8_events.jsonl" ]; then
    run obs_report 120 python -m maskclustering_tpu.obs.report "$OUT/bench_default_events.jsonl" --diff "$OUT/bench_fb8_events.jsonl"
  else
    run obs_report 120 python -m maskclustering_tpu.obs.report "$OUT/bench_default_events.jsonl"
  fi
fi
# cost observatory: CPU AOT — costs no chip time, so it runs even in a
# dead window (the census is backend-shaped by the mesh, not chip-timed)
COST_SHAPE=(--frames 64 --points 65536 --image-h 240 --image-w 320 --k-max 63)
[ -n "${MCT_QUICK:-}" ] && COST_SHAPE=(--frames 8 --points 1024 --image-h 24 --image-w 32 --k-max 7)
run cost_census 900 env JAX_PLATFORMS=cpu python -m maskclustering_tpu.obs.cost \
  --events "$OUT/cost_events.jsonl" --mesh 1x8 --mesh 8x1 "${COST_SHAPE[@]}"
# dtype census: the static bf16-vs-int8 A/B (dot classes, operand bytes,
# memory plan) — the off-chip half of the bench_int8 story, also chip-free
run dtype_census 900 env JAX_PLATFORMS=cpu python -m maskclustering_tpu.obs.cost \
  --compare-dtypes --events "$OUT/dtype_census_events.jsonl" --mesh 1x8 "${COST_SHAPE[@]}"
# mct-check: the static invariant gates, CPU-side like the cost census —
# ADVISORY here (the `run` helper never aborts the session): a finding in
# a recovery window should be read in mct_check.out after the capture, not
# cost chip minutes; scripts/ci.sh is where the same check is fatal
run mct_check 120 env JAX_PLATFORMS=cpu python -m maskclustering_tpu.analysis \
  --families ast,ir --events "$OUT/analysis_events.jsonl"
# mct-threads: the concurrency family on its own (thread topology, lock
# order, blocking-under-lock, signal/join contracts) — pure stdlib AST,
# no compiles, so its verdict is one grep away in conc_check.out even
# when the full mct_check above timed out mid-lattice; fatal in ci.sh.
# Its OWN events file: obs.report renders only the newest analysis run
# per file, so appending here would mask the full run's IR/AST findings
run conc_check 60 env JAX_PLATFORMS=cpu python -m maskclustering_tpu.analysis \
  --families concurrency --events "$OUT/conc_events.jsonl"
# mct-retrace: the compile-surface family (closure-capture/branch lint +
# the census ratchet vs compile_surface_baseline.json) — CPU AOT like the
# cost census, ADVISORY here and fatal in ci.sh. Its OWN events file for
# the same reason as conc_check: obs.report renders one analysis run per
# file, and this verdict must not mask (or be masked by) the others
run retrace_check 300 env JAX_PLATFORMS=cpu python -m maskclustering_tpu.analysis \
  --families retrace --events "$OUT/retrace_events.jsonl"
# perf ledger: render the trajectory the bench steps above just appended
# to, and gate against the last committed good verdict when present
if [ -f BENCH_builder_r05.json ]; then
  run perf_ledger 120 python -m maskclustering_tpu.obs.report --history --regress BENCH_builder_r05.json
else
  run perf_ledger 120 python -m maskclustering_tpu.obs.report --history
fi
echo "[chip_session] done; JSON lines:"
grep -h '"value"' "$OUT"/bench_*.out 2>/dev/null
