"""Per-chip memory analysis of the fused multi-chip step at the honest bucket.

VERDICT r4 task 7: MESH_BENCH's 41 GB CPU RSS row needed an answer to "what
does one REAL chip hold?". This compiles (AOT, abstract shapes — nothing is
materialized) the fused step over a (scene=1, frame=8) mesh of 8 virtual
devices at the honest ScanNet operating point (250->256 frames, 480x640
uint16 feed, 192k points, k_max 63) and reports
``jax.stages.Compiled.memory_analysis()``: per-device argument / output /
temp bytes, i.e. the HBM footprint XLA's buffer assignment plans per chip.

Usage: python scripts/hbm_analysis.py [--frames 256] [--out -]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import argparse
import time

V5E_HBM_GB = 16.0  # v5e: 16 GB HBM per chip


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scenes", type=int, default=1)
    p.add_argument("--frames", type=int, default=256,
                   help="honest bucket 250 rounds to the next multiple of 8")
    p.add_argument("--points", type=int, default=196608)
    p.add_argument("--image-h", type=int, default=480)
    p.add_argument("--image-w", type=int, default=640)
    p.add_argument("--k-max", type=int, default=63)
    p.add_argument("--mesh", type=int, nargs=2, default=(1, 8),
                   metavar=("SCENE", "FRAME"))
    p.add_argument("--out", default="-",
                   help="markdown output path, or - for stdout only")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.parallel.mesh import make_mesh
    from maskclustering_tpu.parallel.sharded import build_fused_step

    cfg = PipelineConfig(config_name="hbm_analysis", dataset="demo",
                         distance_threshold=0.01, few_points_threshold=25,
                         point_chunk=8192)
    mesh = make_mesh(tuple(args.mesh))
    # same donation setting as the production batch path (batch._cached_step)
    # so the memory plan read here is the deployed program's
    step = build_fused_step(mesh, cfg, k_max=args.k_max,
                            donate=bool(cfg.donate_buffers))

    s, f = args.scenes, args.frames
    if f % args.mesh[1]:
        f = -(-f // args.mesh[1]) * args.mesh[1]
        print(f"[hbm] frames {args.frames} -> {f} (next multiple of the "
              f"frame mesh dim {args.mesh[1]})", file=sys.stderr, flush=True)
    h, w, n = args.image_h, args.image_w, args.points
    shapes = (
        jax.ShapeDtypeStruct((s, n, 3), jnp.float32),   # scene_points
        jax.ShapeDtypeStruct((s, f, h, w), jnp.uint16),  # depths (compact feed)
        jax.ShapeDtypeStruct((s, f, h, w), jnp.uint16),  # segs
        jax.ShapeDtypeStruct((s, f, 3, 3), jnp.float32),
        jax.ShapeDtypeStruct((s, f, 4, 4), jnp.float32),
        jax.ShapeDtypeStruct((s, f), jnp.bool_),
    )
    print(f"[hbm] lowering fused step: S={s} F={f} {h}x{w} N={n} "
          f"k_max={args.k_max} mesh={tuple(args.mesh)}",
          file=sys.stderr, flush=True)
    t0 = time.time()
    lowered = step.lower(*shapes)
    t_lower = time.time() - t0
    t0 = time.time()
    compiled = lowered.compile()
    t_compile = time.time() - t0
    print(f"[hbm] lower {t_lower:.1f}s, compile {t_compile:.1f}s",
          file=sys.stderr, flush=True)

    ma = compiled.memory_analysis()
    if ma is None:
        print("[hbm] memory_analysis() unavailable on this backend",
              file=sys.stderr)
        sys.exit(2)

    # the full static cost row (collective + op census, rooflines) comes
    # from the shared observatory rig — one extraction path for this
    # script, obs.cost, and the report CLI
    from maskclustering_tpu.obs.cost import analyze_compiled

    cost = analyze_compiled(compiled, lower_s=t_lower, compile_s=t_compile)

    def gb(x):
        return x / (1 << 30)

    # The CPU backend plans temps but reports zero for argument/output
    # buffers (they are externally allocated); compute those analytically
    # from the declared shardings so the per-chip total is backend-honest.
    # Every input/output is sharded over `scene` on dim 0, so a device holds
    # s/n_scene scenes' worth of its frame shard.
    n_scene, n_frame = args.mesh
    s_dev = -(-s // n_scene)  # scenes resident per device
    m_pad = f * args.k_max
    analytic_arg = (n * 3 * 4                      # scene_points, replicated
                    + 2 * (f // n_frame) * h * w * 2   # depth+seg u16 shards
                    + (f // n_frame) * (9 + 16) * 4    # intrinsics+c2w
                    + f // n_frame) * s_dev
    analytic_out = (3 * (f // n_frame) * n * 4     # mask_of_point/first/last
                    + (m_pad // n_frame) * f       # node_visible bool shard
                    + 2 * (m_pad // n_frame) * 4   # assignment+mask_active
                    + 4) * s_dev
    arg_gb = max(gb(ma.argument_size_in_bytes), gb(analytic_arg))
    out_gb = max(gb(ma.output_size_in_bytes), gb(analytic_out))
    tmp_gb = gb(ma.temp_size_in_bytes)
    alias_gb = gb(ma.alias_size_in_bytes)
    # peak per-device plan: args + outputs + temps - aliased (aliased bytes
    # are counted in both args and outputs)
    total_gb = arg_gb + out_gb + tmp_gb - alias_gb
    headroom = V5E_HBM_GB - total_gb

    lines = [
        f"shape: S={s} F={f} {h}x{w} N={n} k_max={args.k_max} "
        f"mesh=(scene={args.mesh[0]},frame={args.mesh[1]})",
        f"argument_size: {arg_gb:.3f} GB/device",
        f"output_size:   {out_gb:.3f} GB/device",
        f"temp_size:     {tmp_gb:.3f} GB/device",
        f"alias_size:    {alias_gb:.3f} GB/device",
        f"planned total: {total_gb:.3f} GB/device "
        f"(v5e HBM {V5E_HBM_GB:.0f} GB -> headroom {headroom:.1f} GB)",
        f"compile: lower {t_lower:.1f}s + compile {t_compile:.1f}s",
    ]
    census = cost.get("collectives") or {}
    if census:
        lines.append("collectives: " + ", ".join(
            f"{op} x{int(c['count'])} ({c['bytes']:.0f} B)"
            for op, c in sorted(census.items()))
            + f" -> ICI payload {cost['ici_bytes']:.0f} B")
    else:
        lines.append("collectives: none (no cross-chip traffic in the plan)")
    ops = cost.get("ops") or {}
    lines.append(f"op census: {ops.get('fusion', 0)} fusions, "
                 f"{ops.get('copy', 0)} copies, "
                 f"{ops.get('transpose', 0)} transposes; "
                 f"flops {cost.get('flops')}, "
                 f"hbm bytes {cost.get('hbm_bytes')}")
    print("\n".join(lines))
    if args.out != "-":
        with open(args.out, "a") as fh:
            fh.write("\n".join(lines) + "\n")
    sys.exit(0 if headroom > 0 else 1)


if __name__ == "__main__":
    main()
