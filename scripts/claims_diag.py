"""Attribute post.claims wall time: device kernel vs device->host drain.

The bench's ``post.claims`` phase (BENCH_builder_r05: ~0.97 s at the honest
shape) spans very different costs — the `_node_stats_kernel` dispatch +
execution and whatever crosses the host boundary. A back-of-envelope
HBM/FLOP floor for the kernel is tens of ms, so if the phase is ~1 s the
money is either in a fusion failure (visible to a profiler) or in the
driver rig's ~MB/s tunnel (invisible to one). This script separates them
on the live chip in one run:

    python scripts/claims_diag.py [--frames 250 --points 196608 --boxes 36]

It replays bench.py's scene through associate -> graph -> cluster, then
times, over 5 repeats each:
  kernel        `_node_stats_kernel` with a 1-element sync (device time)
  postprocess   the full device post-process (emit-only drain path)
  pull_plane16  np.asarray of one full (F, N) int16 claim plane — the
                RETIRED drain unit: the host-postprocess path pulls two of
                these per scene; the emit-only drain pulls none. Reported
                with its byte size so the chip-session record shows the
                before/after next to the emit-drain bytes line
  pull_calib    np.asarray of a fresh device buffer of the emit drain's
                byte size (pure tunnel rate at that size, for comparison)

The emit-only drain line reports the bytes the device path ACTUALLY moves
per scene (surviving objects' bit-packed point planes + the intersection
matrix + O(M_pad + S) scalars) next to the retired int16 plane-pull line.

Interpretation: if kernel >> floor, capture a trace (bench --profile-dir)
and look at the one-hot/dot fusion; if the drain ~ pull_calib dominates,
the phase is tunnel-bound — a rig artifact PCIe on a real TPU-VM removes.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _sync(x):
    np.asarray(x.ravel()[:1])


def timeit(name, fn, iters=5):
    fn()  # warm (compile / first dispatch)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    print(f"  {name:14s} {med*1e3:9.1f} ms  (runs: "
          + " ".join(f"{t*1e3:.0f}" for t in times) + ")", flush=True)
    return med


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=250)
    p.add_argument("--points", type=int, default=196608)
    p.add_argument("--boxes", type=int, default=36)
    p.add_argument("--k-max", type=int, default=63)
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    from maskclustering_tpu.utils.backend_init import init_backend

    init_backend(args.platform, timeout_s=120.0, tag="claims_diag")
    import jax.numpy as jnp

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.backprojection import associate_scene_tensors
    from maskclustering_tpu.models.clustering import iterative_clustering
    from maskclustering_tpu.models.graph import (build_mask_table,
                                                 compute_graph_stats,
                                                 observer_schedule)
    from maskclustering_tpu.models.pipeline import pad_scene_tensors
    from maskclustering_tpu.models.postprocess_device import (
        _live_rep_prep, _node_stats_kernel)
    from maskclustering_tpu.utils.compile_cache import setup_compilation_cache
    from maskclustering_tpu.utils.synthetic import (make_scene_device,
                                                    resize_scene_points)

    setup_compilation_cache()
    # donate_buffers=False: the script re-times the post-process (and the
    # retired plane pull) against the SAME first/last planes repeatedly —
    # the production donation would delete them after the first call on
    # any backend where the aliasing is usable
    cfg = PipelineConfig(config_name="bench", dataset="demo",
                         distance_threshold=0.01, few_points_threshold=25,
                         point_chunk=8192, donate_buffers=False)

    print(f"[claims_diag] scene: F={args.frames} N={args.points} "
          f"boxes={args.boxes}", flush=True)
    tensors, _, _ = make_scene_device(
        num_boxes=args.boxes, num_frames=args.frames, image_hw=(480, 640),
        spacing=0.025, seed=0)
    tensors.scene_points = resize_scene_points(tensors.scene_points,
                                               args.points)

    # ---- associate -> graph -> cluster, exactly as pipeline.run_scene ----
    from maskclustering_tpu.utils.compile_cache import bucket_size

    f_pad = bucket_size(tensors.num_frames, cfg.frame_pad_multiple)
    n_pad = bucket_size(tensors.num_points, cfg.point_chunk)
    tensors = pad_scene_tensors(tensors, f_pad, n_pad)
    assoc = associate_scene_tensors(tensors, cfg, k_max=args.k_max)
    table = build_mask_table(np.asarray(assoc.mask_valid),
                             pad_multiple=cfg.mask_pad_multiple)
    stats = compute_graph_stats(
        assoc.mask_of_point, assoc.boundary, jnp.asarray(table.frame),
        jnp.asarray(table.mask_id), jnp.asarray(table.valid),
        k_max=args.k_max, point_chunk=cfg.point_chunk,
        mask_visible_threshold=cfg.mask_visible_threshold,
        contained_threshold=cfg.contained_threshold,
        undersegment_filter_threshold=cfg.undersegment_filter_threshold,
        big_mask_point_count=cfg.big_mask_point_count)
    schedule = observer_schedule(stats.observer_hist,
                                 max_len=cfg.max_cluster_iterations)
    active = jnp.asarray(table.valid) & ~stats.undersegment
    result = iterative_clustering(
        stats.visible, stats.contained, active, jnp.asarray(schedule),
        view_consensus_threshold=cfg.view_consensus_threshold)
    assignment = np.asarray(result.assignment)
    mask_active = np.asarray(active)

    # ---- postprocess prep: the pipeline's own helper, same shapes ----
    f, n = assoc.first_id.shape
    k2 = args.k_max + 2
    prep = _live_rep_prep(table.frame, table.mask_id, mask_active, assignment,
                          f, k2, cfg.min_masks_per_object)
    if prep is None:
        print("[claims_diag] no live reps — nothing to time", flush=True)
        return
    reps, r_pad, _rep_lut, rep_tab, live_slots, live_valid, r_pull = prep
    print(f"[claims_diag] reps={len(reps)} r_pad={r_pad} r_pull={r_pull} "
          f"plane={(r_pull * (n // 8)) / 1e6:.2f} MB", flush=True)

    rep_tab_d = jnp.asarray(rep_tab)
    slots_d = jnp.asarray(live_slots)
    valid_d = jnp.asarray(live_valid)

    def kernel():
        out = _node_stats_kernel(
            assoc.first_id, assoc.last_id, rep_tab_d, result.node_visible,
            slots_d, valid_d, r_pad=r_pad,
            point_filter_threshold=float(cfg.point_filter_threshold))
        _sync(out[0])
        return out

    kernel()

    # the production emit-only drain: run the whole device post-process and
    # account its actual per-scene transfer payload
    from maskclustering_tpu.models.postprocess_device import (
        _bucket_pow2, run_postprocess)

    def postprocess():
        # n_real keeps the shape-bucket sentinel pads out of the voxel
        # grid (a pad run binned into one cell would blow cell_cap up by
        # orders of magnitude and poison exactly this timing)
        return run_postprocess(
            cfg, np.asarray(tensors.scene_points), assoc.first_id,
            assoc.last_id, table.frame, table.mask_id, jnp.asarray(active),
            result.assignment, result.node_visible,
            list(range(f)), k_max=args.k_max, n_real=args.points)

    # measure the drain bytes the path ACTUALLY books (obs counters are
    # unconditional), not an estimate — the group axis is sized from the
    # true total at runtime, so a static guess would overstate the drain
    from maskclustering_tpu.obs.metrics import registry

    postprocess()  # warm (compile) outside the measured call
    registry().reset()
    objects = postprocess()
    emit_b = int(registry().snapshot()["counters"].get(
        "d2h.bytes.post.drain", 0))
    registry().reset()
    o = len(objects.point_ids_list)
    o_pad = _bucket_pow2(o, minimum=8)
    emit_mb = emit_b / 1e6

    # calibration source: XOR with a fresh constant per call so every
    # np.asarray transfers a NEW device array of the same byte size —
    # jax.Array caches its host copy, so re-pulling one array is ~free
    # and would read as a fantasy tunnel rate
    calib_seq = iter(range(1, 1000))
    calib_rows = max(1, emit_b // max(n // 8, 1))
    calib_src = jnp.zeros((calib_rows, n // 8), jnp.uint8)

    def pull_calib():
        return np.asarray(calib_src ^ np.uint8(next(calib_seq)))

    # full (F, N) int16 claim plane: the RETIRED drain unit of the
    # host-postprocess path (and the byte size the int16 narrowing halved).
    # Same fresh-buffer XOR trick — jax.Array caches its host copy.
    def pull_plane16():
        return np.asarray(assoc.first_id ^ jnp.int16(next(calib_seq)))

    assert assoc.first_id.dtype == jnp.int16, assoc.first_id.dtype
    plane_mb = (f * n * 2) / 1e6
    print("[claims_diag] timings (median of 5):", flush=True)
    t_kernel = timeit("kernel", kernel)
    t_post = timeit("postprocess", postprocess)
    t_plane = timeit("pull_plane16", pull_plane16)
    t_calib = timeit("pull_calib", pull_calib)
    print(f"[claims_diag] kernel={t_kernel*1e3:.0f}ms "
          f"postprocess={t_post*1e3:.0f}ms "
          f"calib({emit_mb:.2f}MB)={t_calib*1e3:.0f}ms "
          f"-> tunnel {emit_mb/max(t_calib,1e-9):.1f} MB/s", flush=True)
    print(f"[claims_diag] emit-only drain: {emit_mb:.2f} MB/scene "
          f"({o} objects -> {o_pad} x {n//8}B packed planes + "
          f"{o}x{o} inter + O(M+S) scalars); claim planes stay in HBM",
          flush=True)
    print(f"[claims_diag] retired int16 claim plane pull: {plane_mb:.1f} "
          f"MB/plane x2/scene on the host-postprocess path (int32 layout "
          f"would be {plane_mb*2:.1f} MB) in {t_plane*1e3:.0f}ms -> "
          f"{plane_mb/max(t_plane,1e-9):.1f} MB/s; the emit-only drain "
          f"moves {emit_mb:.2f} MB instead "
          f"({2*plane_mb/max(emit_mb,1e-9):.0f}x less)", flush=True)


if __name__ == "__main__":
    main()
