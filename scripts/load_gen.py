#!/usr/bin/env python
"""Load generator for the mct-serve daemon (+ the CI smoke gate).

Drives N concurrent synthetic scene requests — mixed shape buckets by
default, so the daemon's routing/warmth story is exercised, not just one
executable — against a running daemon, and prints ONE machine-readable
JSON verdict line on stdout (human progress goes to stderr):

    {"metric": "serve s/request (p50 of N synthetic requests)",
     "value": 1.92, "p95_s": 2.4, "throughput_rps": 1.4, "requests": 8,
     "concurrency": 4, "rejects": {"queue_full": 1}, ...}

and appends a ``serve`` row to the perf ledger (obs/ledger.serve_row;
``--no-ledger`` to skip) — the serving trajectory next to the bench one,
fenced by metric/tool so ``--regress`` never cross-gates them.

Modes::

    # against a running daemon (see README "Running the daemon"):
    python scripts/load_gen.py --socket /tmp/mct.sock --requests 16 \
        --concurrency 8

    # the CI smoke gate: self-contained — materializes two tiny warm
    # scenes, spawns a sanitizer-armed daemon subprocess, serves a small
    # mixed-bucket burst, SIGTERMs it, and asserts clean shutdown + ZERO
    # post-warm compiles (exit 0 pass / 1 fail):
    python scripts/load_gen.py --smoke [--fault-plan "flaky:lg-b:1"]

Requests repeat over the bucket scene set with ``resume=false`` so every
request executes (artifact resume would turn repeats into no-ops and the
throughput number into fiction).

``--tenant-mix A:3,B:1`` stamps a weighted tenant identity on every
request (``obs/telemetry.py`` attributes latency, device-seconds and d2h
bytes per tenant); the smoke asserts the per-tenant accounting sums back
to the global window and copies the tenant rows into the verdict. The
smoke also arms the flight recorder (``--flight-dir``) — the crash drill
asserts the supervisor's black-box dump reconstructs the victim request
through crash -> requeue -> respawn — and holds the healthy soak to the
default SLO spec (obs/slo.py).
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# the two tiny shape buckets the tier-1 suite keeps warm (test_executor /
# test_retrace use byte-identical scenes): bucket A and the denser B land
# on distinct (k_max, f_pad, n_pad) keys under the smoke config below
BUCKET_SPECS: Tuple[Tuple[str, Dict], ...] = (
    ("lg-a", {"num_boxes": 3, "num_frames": 10, "image_hw": [60, 80],
              "spacing": 0.06, "seed": 40}),
    ("lg-b", {"num_boxes": 4, "num_frames": 10, "image_hw": [60, 80],
              "spacing": 0.05, "seed": 50}),
)
SMOKE_CONFIG_SETS = ("step=1", "distance_threshold=0.05",
                     "mask_pad_multiple=32", "backend=cpu")


def log(msg: str) -> None:
    print(f"load_gen: {msg}", file=sys.stderr, flush=True)


def parse_tenant_mix(spec: Optional[str]) -> List[str]:
    """``"A:3,B:1"`` -> a weighted assignment cycle ``[A,A,A,B]``; request
    i gets ``cycle[i % len]``, so any request count splits 3:1. Empty/None
    means untenanted (the pre-tenant wire shape, byte-for-byte)."""
    if not spec:
        return []
    cycle: List[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant mix entry {part!r} has no tenant name")
        n = int(weight) if weight.strip() else 1
        if n < 1:
            raise ValueError(f"tenant mix weight for {name!r} must be >= 1")
        cycle.extend([name] * n)
    return cycle


def _address(args) -> object:
    if args.socket:
        return args.socket
    return (args.host, args.port)


def run_load(address, *, requests: int, concurrency: int, buckets: int,
             deadline_s: float, resume: bool,
             tenant_mix: Optional[List[str]] = None,
             rate: float = 0.0,
             collect: Optional[List[Dict]] = None) -> Dict:
    """Fire the burst; returns the aggregate verdict fields.

    ``rate > 0`` switches from the closed loop (``concurrency`` clients,
    each firing its next request the moment the previous returns) to an
    OPEN loop: request i is released at ``t0 + i/rate`` regardless of how
    many are still in flight, the arrival process a live deployment sees.
    ``collect`` (when given) receives every terminal result verbatim, for
    callers that need per-request digests (the pack drill).
    """
    from maskclustering_tpu.serve.client import ServeClient

    specs = list(BUCKET_SPECS[:max(1, min(buckets, len(BUCKET_SPECS)))])
    cycle = list(tenant_mix or [])
    sent_tenants: Dict[str, int] = {}
    plan: List[Tuple[int, str, Dict, str]] = []
    for i in range(requests):
        name, params = specs[i % len(specs)]
        tenant = cycle[i % len(cycle)] if cycle else ""
        if tenant:
            sent_tenants[tenant] = sent_tenants.get(tenant, 0) + 1
        plan.append((i, name, params, tenant))
    results: List[Dict] = []
    latencies: List[float] = []
    rejects: Dict[str, int] = {}
    crash_events = [0]  # worker_crash status events seen (crash drills)
    lock = threading.Lock()

    def one_request(client, i: int, name: str, params: Dict,
                    tenant: str) -> None:
        attempts = 0
        while True:
            terminal, _statuses, latency = client.run_scene(
                name, synthetic=params, deadline_s=deadline_s,
                resume=resume, tag=f"lg-{i:04d}", tenant=tenant)
            ncrash = sum(1 for s in _statuses
                         if s.get("state") == "worker_crash")
            if ncrash:
                with lock:
                    crash_events[0] += ncrash
            if terminal.get("kind") == "reject" \
                    and terminal.get("reason") == "queue_full" \
                    and attempts < 10:
                # backpressure is the CONTRACT: count it, back off,
                # resubmit — a full queue is not a failed request
                attempts += 1
                with lock:
                    rejects["queue_full"] = \
                        rejects.get("queue_full", 0) + 1
                time.sleep(0.2 * attempts)
                continue
            break
        with lock:
            if terminal.get("kind") == "reject":
                rejects[terminal.get("reason", "?")] = \
                    rejects.get(terminal.get("reason", "?"), 0) + 1
            else:
                terminal.setdefault("scene", name)
                results.append(terminal)
                if terminal.get("status") == "ok":
                    latencies.append(latency)

    work: "queue.Queue[Tuple[int, str, Dict, str]]" = queue.Queue()
    for item in plan:
        work.put(item)

    def client_loop() -> None:
        with ServeClient(address, timeout_s=600.0) as client:
            while True:
                try:
                    i, name, params, tenant = work.get_nowait()
                except queue.Empty:
                    return
                one_request(client, i, name, params, tenant)

    def open_loop_one(item: Tuple[int, str, Dict, str]) -> None:
        with ServeClient(address, timeout_s=600.0) as client:
            one_request(*((client,) + item))

    t0 = time.monotonic()
    threads = []
    if rate > 0:
        # open loop: each request gets its own thread + connection,
        # started on the arrival clock — completions never gate arrivals
        for item in plan:
            due = t0 + item[0] / rate
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=open_loop_one, args=(item,),
                                 daemon=True, name=f"load-gen-{item[0]}")
            t.start()
            threads.append(t)
    else:
        for i in range(max(1, concurrency)):
            t = threading.Thread(target=client_loop, daemon=True,
                                 name=f"load-gen-{i}")
            t.start()
            threads.append(t)
    for t in threads:
        t.join(900.0)
    wall = time.monotonic() - t0

    from maskclustering_tpu.obs.report import percentile

    ok = [r for r in results if r.get("status") == "ok"]
    failed = [r for r in results if r.get("status") not in ("ok", "skipped")]
    vals = sorted(latencies)
    if collect is not None:
        collect.extend(results)

    def pct(q: float) -> Optional[float]:
        return round(percentile(vals, q), 4) if vals else None

    verdict = {
        "metric": f"serve s/request (p50 of {requests} synthetic requests)",
        "value": pct(50),
        "unit": "s/request",
        "p95_s": pct(95),
        "throughput_rps": round(len(ok) / wall, 3) if wall > 0 else None,
        "wall_s": round(wall, 2),
        "requests": requests,
        "concurrency": concurrency,
        "buckets": len(specs),
        "ok": len(ok),
        "failed": len(failed),
        "rejects": rejects or None,
        "max_attempts": max((r.get("attempts", 1) for r in results),
                            default=0),
        "max_rung": max((r.get("rung", 0) for r in results), default=0),
        "worker_crash_events": crash_events[0],
        "tenant_mix_sent": sent_tenants or None,
    }
    if rate > 0:
        verdict["arrival_rate_rps"] = rate
        verdict["arrival"] = "open-loop"
    # batch-occupancy histogram: every packed member's terminal carries
    # batch=k, so each width-k fused dispatch contributes exactly k
    # results; solo dispatches (width 1) have no batch field. Stamped
    # only when packing was actually observed — a sequential run must
    # NOT grow the batch dimension (obs.ledger.batch_dimension fence).
    hist: Dict[int, int] = {}
    for r in results:
        w = int(r.get("batch", 1) or 1)
        hist[w] = hist.get(w, 0) + 1
    if any(w > 1 for w in hist):
        dispatches = hist.get(1, 0) + sum(
            max(1, int(round(n / w))) for w, n in hist.items() if w > 1)
        verdict["batch_hist"] = {str(w): hist[w] for w in sorted(hist)}
        verdict["batch_dispatches"] = dispatches
        verdict["batch_occupancy"] = round(len(results) / dispatches, 3)
    return verdict


def append_ledger_row(verdict: Dict, path: Optional[str]) -> None:
    from maskclustering_tpu.obs import ledger as led

    row = led.serve_row(verdict)
    led.append_row(path or led.default_ledger_path(), row)


def check_tenant_accounting(tel: Dict, sent: Dict[str, int],
                            failures: List[str]) -> Optional[Dict]:
    """Per-tenant accounting must sum back to the global window: every
    completion books globally AND under exactly one tenant, so any drift
    means attribution was lost or double-booked. Returns the cumulative
    tenant rows (for the verdict) when present.

    The cumulative equality is asserted exactly (the caller runs this on
    a quiesced post-burst snapshot); closed-window rows only need to
    SHOW attribution — a completion racing the roll tick may book its
    counter and its tenant slot across a window boundary, so strict
    per-window parity is pinned at the aggregator unit level instead.
    """
    cum = (tel or {}).get("cumulative") or {}
    cum_tenants = cum.get("tenants") or {}
    counters = cum.get("counters") or {}
    total = sum(int((t or {}).get("requests", 0))
                for t in cum_tenants.values())
    global_reqs = int(counters.get("serve.requests", 0))
    if total != global_reqs:
        failures.append(
            f"tenant accounting: per-tenant requests sum to {total} but "
            f"the global serve.requests counter says {global_reqs}")
    missing = sorted(t for t in sent if t not in cum_tenants)
    if missing:
        failures.append(f"tenant accounting: tenant(s) {missing} sent "
                        f"requests but never appeared in the snapshot")
    windows = (tel or {}).get("windows") or []
    tenanted = [w for w in windows if w.get("tenants")]
    if windows and global_reqs and not tenanted:
        failures.append("tenant accounting: no closed window carries a "
                        "tenants sub-row — window attribution is dark")
    return cum_tenants or None


def check_healthy_slo(tel: Dict, verdict: Dict,
                      failures: List[str]) -> None:
    """The healthy-soak SLO gate: the canned default spec (obs/slo.py)
    evaluated over the burst's closed windows must pass — a healthy
    8-request soak that burns error budget means the spec or the
    accounting broke, and CI should say which objective."""
    from maskclustering_tpu.obs import slo as _slo

    result = _slo.evaluate(_slo.load_spec(None), tel or {})
    verdict["slo_ok"] = bool(result.get("ok"))
    violated = [o.get("name") for o in result.get("objectives") or ()
                if o.get("state") == "violated"]
    if violated:
        failures.append(f"healthy soak violated the default SLO spec: "
                        f"{', '.join(map(str, violated))}")


def check_blackbox(flight_dir: str, events: str, journal_dir: str,
                   verdict: Dict, failures: List[str]) -> None:
    """The crash-drill postmortem contract, end to end: the supervisor
    dumped a black box at SIGKILL time, the dump names the victim request
    and holds child-side rows the live relay shipped pre-crash, the
    ``obs.flight`` renderer reads it, and ``obs.trace --blackbox`` folds
    it into a causal timeline that reaches crash -> requeue -> respawn
    (a post-crash execution attempt for the same request)."""
    from maskclustering_tpu.obs import flight as _flight
    from maskclustering_tpu.obs import trace as _trace

    dumps = sorted(os.listdir(flight_dir)) if os.path.isdir(flight_dir) \
        else []
    crash_dumps = [n for n in dumps if "worker_crash" in n]
    verdict["blackbox_dumps"] = len(dumps)
    if not crash_dumps:
        failures.append(f"crash drill: no worker_crash flight dump under "
                        f"{flight_dir} (found: {dumps or 'nothing'})")
        return
    path = os.path.join(flight_dir, crash_dumps[-1])
    meta, rows = _flight.read_dump(path)
    crash_rows = [r for r in rows if r.get("kind") == _flight.KIND_CRASH]
    victim = next((r.get("request") for r in crash_rows
                   if r.get("request")), None)
    if not crash_rows:
        failures.append(f"crash drill: {path} holds no {_flight.KIND_CRASH} "
                        f"row")
    if victim is None:
        failures.append("crash drill: the crash row names no victim "
                        "request")
        return
    child_rows = [r for r in rows
                  if r.get("kind") == _flight.KIND_REQUEST
                  and r.get("request") == victim]
    if not child_rows:
        failures.append(f"crash drill: the dump holds no child-side "
                        f"lifecycle row for {victim} — the flight-delta "
                        f"relay never delivered the victim's ring")
    rendered = _flight.render_dump(meta, rows, request=victim)
    for needle in (victim, "worker_crash"):
        if needle not in rendered:
            failures.append(f"crash drill: obs.flight rendering of {path} "
                            f"never mentions {needle!r}")
    trace = _trace.assemble_trace(victim, events, journal_dir=journal_dir,
                                  blackbox=flight_dir)
    segs = trace.get("segments") or []
    crash_at = next((s["t0"] for s in segs if s.get("kind") == "crash"),
                    None)
    attempts_after = [s for s in segs if s.get("kind") == "attempt"
                      and crash_at is not None and s["t1"] > crash_at]
    verdict["blackbox_trace_segments"] = len(segs)
    if crash_at is None:
        failures.append(f"crash drill: obs.trace --blackbox timeline for "
                        f"{victim} shows no crash segment")
    elif not attempts_after:
        failures.append(f"crash drill: obs.trace --blackbox timeline for "
                        f"{victim} never reaches a post-crash execution "
                        f"attempt (requeue/respawn invisible)")
    blackbox_marks = [s for s in segs if s.get("kind") == "blackbox"]
    if not blackbox_marks:
        failures.append(f"crash drill: the merged timeline for {victim} "
                        f"carries no black-box marks — the dump "
                        f"contributed nothing the live events lacked")


def worst_window_p95(windows) -> Optional[float]:
    """Max per-bucket p95 across telemetry window rows (None when none)."""
    p95s = [h.get("p95_s")
            for w in windows or ()
            for h in (w.get("latency") or {}).values()
            if (h or {}).get("p95_s") is not None]
    return max(p95s) if p95s else None


class TelemetryPoller:
    """Polls the daemon's telemetry op WHILE the burst runs.

    Watching a daemon under load is the telemetry plane's whole point, so
    the smoke exercises it mid-burst, not post-hoc: every poll must answer
    a well-formed snapshot (windows ring + cumulative digest) — an
    unreachable op or a torn document is a gate failure. Tracks the worst
    per-bucket window p95 seen, which the verdict stamps.
    """

    def __init__(self, address, interval_s: float = 0.5):
        self.address = address
        self.interval_s = interval_s
        self.polls = 0
        self.torn = 0
        self.errors = 0
        self.window_p95: Optional[float] = None
        self.last: Optional[Dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _ingest(self, stats: Dict) -> None:
        tel = stats.get("telemetry")
        if not isinstance(tel, dict) or "windows" not in tel \
                or "cumulative" not in tel:
            self.torn += 1
            return
        self.last = stats
        p95 = worst_window_p95(tel["windows"])
        if p95 is not None and (self.window_p95 is None
                                or p95 > self.window_p95):
            self.window_p95 = p95

    def poll_once(self) -> None:
        from maskclustering_tpu.serve.client import ServeClient

        self.polls += 1
        try:
            with ServeClient(self.address, timeout_s=30.0) as client:
                self._ingest(client.telemetry())
        except Exception:  # noqa: BLE001 — counted; the gate decides
            self.errors += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.poll_once()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,  # mct-thread: abandon(bounded-joined in stop(); the spawn/join pair spans methods, which the scope-local check cannot see)
                                        name="telemetry-poller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self.poll_once()  # one final full snapshot after the burst


# ---------------------------------------------------------------------------
# the CI smoke gate: daemon subprocess + a bounded mixed-bucket burst
# ---------------------------------------------------------------------------


def _wait_for_socket(path: str, proc: subprocess.Popen,
                     timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return False
        if os.path.exists(path):
            try:
                from maskclustering_tpu.serve.client import ServeClient

                with ServeClient(path, timeout_s=5.0) as c:
                    c.stats()
                return True
            except OSError:
                pass
        time.sleep(0.25)
    return False


def run_smoke(args) -> int:
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    tmp = tempfile.mkdtemp(prefix="mct_serve_smoke_")
    sock = os.path.join(tmp, "mct.sock")
    events = os.path.join(tmp, "serve_events.jsonl")
    flight_dir = os.path.join(tmp, "flight")
    journal_dir = os.path.join(tmp, "journals")
    warm_names = []
    for name, params in BUCKET_SPECS:
        kw = dict(params)
        kw["image_hw"] = tuple(kw["image_hw"])
        write_scannet_layout(make_scene(**kw), tmp, name)
        warm_names.append(name)
    log(f"smoke: materialized warm scenes {warm_names} under {tmp}")

    cmd = [sys.executable, "-m", "maskclustering_tpu.serve",
           "--config", "scannet", "--socket", sock, "--data_root", tmp,
           "--capacity", "4", "--retrace-sanitizer",
           # the AOT executable cache rides every smoke: capture on the
           # cold path, restore on respawns/restarts (the crash drill
           # asserts the cross-process half)
           "--aot-cache", os.path.join(tmp, "aot"),
           "--obs_events", events, "--warm", "+".join(warm_names),
           "--telemetry-window", "1.0",
           # the always-on flight recorder: every smoke arms it, the
           # crash drill asserts the postmortem reconstructs
           "--flight-dir", flight_dir,
           "--journal-dir", journal_dir]
    for kv in SMOKE_CONFIG_SETS:
        cmd += ["--set", kv]
    fault_plan = args.fault_plan
    if args.crash_drill and not fault_plan:
        # one SIGKILL of the device worker under the first B-bucket
        # request: the supervisor must respawn, requeue and finish warm
        fault_plan = "crash:lg-b.device:1"
    if args.isolate_worker or args.crash_drill:
        cmd += ["--isolate-worker", "--set", "worker_heartbeat_s=30"]
    if fault_plan:
        cmd += ["--fault-plan", fault_plan]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log(f"smoke: starting daemon: {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, cwd=REPO_ROOT,
                            env=env, text=True)
    try:
        if not _wait_for_socket(sock, proc, timeout_s=args.smoke_startup_s):
            log("smoke: FAIL — daemon never became reachable")
            proc.kill()
            return 1
        # the telemetry op is polled WHILE the burst runs: an empty or
        # torn snapshot mid-load is a gate failure (obs/telemetry.py)
        poller = TelemetryPoller(sock)
        poller.start()
        # the smoke always drives a weighted tenant mix (unless the
        # caller names one): the accounting-sums-to-global assertion
        # below rides every gate run, both topologies
        tenant_mix = parse_tenant_mix(args.tenant_mix or "A:3,B:1")
        try:
            verdict = run_load(sock, requests=args.requests,
                               concurrency=args.concurrency, buckets=2,
                               deadline_s=args.deadline, resume=False,
                               tenant_mix=tenant_mix)
        finally:
            poller.stop()
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=90.0)
    except subprocess.TimeoutExpired:
        log("smoke: FAIL — daemon did not drain within 90s of SIGTERM")
        proc.kill()
        return 1
    finally:
        if proc.poll() is None:
            proc.kill()

    digest = None
    for line in (out or "").splitlines():
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if doc.get("kind") == "digest":
            digest = doc
    failures = []
    if proc.returncode != 143:
        failures.append(f"daemon exit code {proc.returncode} (expected 143 "
                        f"— SIGTERM-clean drain)")
    if digest is None:
        failures.append("daemon printed no final digest line")
    else:
        verdict["warmup_s"] = digest.get("warmup_s")
        if digest.get("point_shards") is not None:
            # serve rows carry the shard-count coordinate so --regress
            # attributes a resharded daemon's latency delta to the knob
            verdict["point_shards"] = int(digest["point_shards"])
        if digest.get("streaming_chunk") is not None:
            # same move for the chunked-accumulation knob (ISSUE 15)
            verdict["streaming_chunk"] = int(digest["streaming_chunk"])
        retrace = digest.get("retrace") or {}
        verdict["retrace_compiles"] = retrace.get("compiles")
        verdict["retrace_repeats"] = retrace.get("repeats")
        verdict["retrace_post_freeze"] = retrace.get("post_freeze")
        verdict["retrace_cache_hits"] = retrace.get("cache_hits")
        if retrace.get("post_freeze"):
            failures.append(f"{retrace['post_freeze']} post-warm compile(s) "
                            f"— the serve-many contract broke")
        if retrace.get("repeats"):
            failures.append(f"{retrace['repeats']} repeat compile(s) — "
                            f"jit-cache thrash in the daemon")
        if not retrace.get("frozen"):
            failures.append("retrace sanitizer never froze after warm-up")
        worker = digest.get("worker") or {}
        if worker:
            verdict["worker_crashes"] = worker.get("crashes")
            verdict["worker_respawns"] = worker.get("respawns")
        if args.crash_drill:
            # the crash-containment contract, end to end: a real SIGKILL
            # under a request, a respawn, a typed status on the wire, and
            # a respawned worker that reached first dispatch warm
            if not worker.get("crashes"):
                failures.append("crash drill: no worker crash was recorded")
            if not worker.get("respawns"):
                failures.append("crash drill: worker never respawned")
            if verdict.get("worker_crash_events", 0) < 1:
                failures.append("crash drill: no client saw a typed "
                                "worker_crash status event")
            if retrace.get("compiles", 0) != 0:
                failures.append(
                    f"respawned worker booked {retrace.get('compiles')} "
                    f"compile(s) — the AOT/persistent-cache warm start "
                    f"did not deliver a zero-compile respawn")
    # live telemetry plane checks (the mid-burst poller): the op must have
    # answered well-formed snapshots under load, windows must have closed,
    # and — under the isolated worker — the relay must have delivered the
    # child's counters to the parent (the topology-invariance contract)
    isolated = bool(args.isolate_worker or args.crash_drill)
    tel = ((poller.last or {}).get("telemetry") or {})
    windows = tel.get("windows") or []
    tel_counters = (tel.get("cumulative") or {}).get("counters") or {}
    verdict["telemetry_polls"] = poller.polls
    verdict["telemetry_windows"] = len(windows)
    verdict["window_p95"] = poller.window_p95
    if poller.last is None:
        failures.append("telemetry op never answered a well-formed "
                        "snapshot mid-burst")
    if poller.torn:
        failures.append(f"{poller.torn} torn/empty telemetry snapshot(s) "
                        f"mid-burst")
    if poller.errors:
        failures.append(f"{poller.errors} telemetry poll(s) could not "
                        f"reach the daemon mid-burst")
    if poller.last is not None:
        if not windows:
            failures.append("no telemetry window ever closed during the "
                            "burst")
        if tel_counters.get("serve.requests", 0) < args.requests:
            failures.append(
                f"telemetry cumulative counters saw "
                f"{tel_counters.get('serve.requests', 0)} request(s) of "
                f"{args.requests} — the snapshot is stale or torn")
        if isolated:
            missing = [k for k in ("worker.telem_messages",
                                   "serve.requests_ok",
                                   "pipeline.host_sync")
                       if not tel_counters.get(k)]
            if missing:
                failures.append(
                    f"isolated worker relayed no {missing} counter(s) — "
                    f"the cross-process telemetry relay is dark")
    # tenant accounting: the per-tenant rows must sum back to the global
    # counters in the final (quiesced) snapshot, identically in-process
    # and under the isolated worker
    if tenant_mix:
        tenants = check_tenant_accounting(
            tel, verdict.get("tenant_mix_sent") or {}, failures)
        if tenants:
            verdict["tenants"] = tenants
    if args.crash_drill:
        check_blackbox(flight_dir, events, journal_dir, verdict, failures)
    elif not fault_plan:
        # healthy soak: the canned default SLO spec must hold (drills
        # are allowed to burn budget; that path is pinned in tests)
        check_healthy_slo(tel, verdict, failures)
    if verdict["ok"] != args.requests:
        failures.append(f"only {verdict['ok']}/{args.requests} requests "
                        f"answered ok")
    if args.fault_plan and "flaky" in args.fault_plan \
            and verdict["max_attempts"] < 2:
        # the daemon suspends the plan during warm-up precisely so the
        # drill lands on the SERVING path; a flaky that nobody retried
        # means it never fired there
        failures.append("fault plan never exercised a serving-path retry")
    verdict["smoke"] = True
    if failures:
        verdict["error"] = "; ".join(failures)
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if not args.no_ledger:
        append_ledger_row(verdict, args.ledger)
    if failures:
        for f in failures:
            log(f"smoke: FAIL — {f}")
        return 1
    log(f"smoke: PASS — {verdict['ok']} requests, p50 "
        f"{verdict['value']}s, p95 {verdict['p95_s']}s, zero post-warm "
        f"compiles, SIGTERM-clean drain")
    return 0


# ---------------------------------------------------------------------------
# the pack drill: packed scheduler vs sequential path, byte for byte
# ---------------------------------------------------------------------------


def _artifact_crcs(root: str) -> Dict[str, str]:
    """CRC32 every artifact under ``root`` keyed by relative path.

    ``.npz`` members are hashed per-array (bytes + dtype + shape): the
    zip container embeds write timestamps, so raw file bytes differ
    between two runs that produced identical arrays."""
    import zlib

    import numpy as np

    out: Dict[str, str] = {}
    if not os.path.isdir(root):
        return out
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            p = os.path.join(dirpath, fn)
            rel = os.path.relpath(p, root)
            if fn.endswith(".npz"):
                with np.load(p, allow_pickle=True) as z:
                    for key in sorted(z.files):
                        arr = np.asarray(z[key])
                        crc = zlib.crc32(arr.tobytes())
                        crc = zlib.crc32(str(arr.dtype).encode(), crc)
                        crc = zlib.crc32(str(arr.shape).encode(), crc)
                        out[f"{rel}:{key}"] = f"{crc & 0xffffffff:08x}"
            else:
                with open(p, "rb") as fh:
                    out[rel] = f"{zlib.crc32(fh.read()) & 0xffffffff:08x}"
    return out


def _pack_phase(tag: str, *, requests: int, extra_sets: Tuple[str, ...],
                rate: float, concurrency: int, startup_s: float,
                collect: List[Dict]):
    """One drill phase: fresh daemon over its own tmp data_root (the
    synthetic scenes are seed-deterministic, so artifacts compare across
    phases), bounded burst, SIGTERM drain.

    Returns ``(verdict, final_digest, artifact_crcs, failures)``."""
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    tmp = tempfile.mkdtemp(prefix=f"mct_pack_{tag}_")
    sock = os.path.join(tmp, "mct.sock")
    warm_names = []
    for name, params in BUCKET_SPECS:
        kw = dict(params)
        kw["image_hw"] = tuple(kw["image_hw"])
        write_scannet_layout(make_scene(**kw), tmp, name)
        warm_names.append(name)
    cmd = [sys.executable, "-m", "maskclustering_tpu.serve",
           "--config", "scannet", "--socket", sock, "--data_root", tmp,
           "--capacity", str(max(8, requests)), "--retrace-sanitizer",
           "--aot-cache", os.path.join(tmp, "aot"),
           "--obs_events", os.path.join(tmp, "serve_events.jsonl"),
           "--warm", "+".join(warm_names), "--telemetry-window", "1.0"]
    for kv in SMOKE_CONFIG_SETS + tuple(extra_sets):
        cmd += ["--set", kv]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log(f"pack-drill[{tag}]: starting daemon: {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, cwd=REPO_ROOT,
                            env=env, text=True)
    failures: List[str] = []
    verdict: Dict = {}
    digest = None
    out = ""
    try:
        if not _wait_for_socket(sock, proc, timeout_s=startup_s):
            proc.kill()
            return verdict, None, {}, [f"{tag}: daemon never became "
                                       f"reachable"]
        verdict = run_load(sock, requests=requests, concurrency=concurrency,
                           buckets=2, deadline_s=0.0, resume=False,
                           rate=rate, collect=collect)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=90.0)
    except subprocess.TimeoutExpired:
        proc.kill()
        return verdict, None, {}, [f"{tag}: daemon did not drain within "
                                   f"90s of SIGTERM"]
    finally:
        if proc.poll() is None:
            proc.kill()
    for line in (out or "").splitlines():
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if doc.get("kind") == "digest":
            digest = doc
    if proc.returncode != 143:
        failures.append(f"{tag}: daemon exit code {proc.returncode} "
                        f"(expected 143 — SIGTERM-clean drain)")
    if digest is None:
        failures.append(f"{tag}: daemon printed no final digest line")
    else:
        retrace = digest.get("retrace") or {}
        if retrace.get("post_freeze"):
            failures.append(f"{tag}: {retrace['post_freeze']} post-warm "
                            f"compile(s) — the serve-many contract broke")
        if not retrace.get("frozen"):
            failures.append(f"{tag}: retrace sanitizer never froze")
    if verdict.get("ok") != requests:
        failures.append(f"{tag}: only {verdict.get('ok')}/{requests} "
                        f"requests answered ok")
    return verdict, digest, _artifact_crcs(os.path.join(tmp, "prediction")), \
        failures


def run_pack_drill(args) -> int:
    """The continuous-batching CI gate: the same mixed-bucket burst runs
    once through the sequential path and once (open-loop) through the
    packing scheduler; the packed run must be byte-identical — per-scene
    artifact digests AND exported artifact CRCs — with zero post-warm
    compiles, occupancy > 1, and per-request p50 strictly below
    batch_max x the sequential p50."""
    S = max(2, int(args.pack_batch_max))
    rate = args.rate if args.rate > 0 else 12.0
    seq_results: List[Dict] = []
    pack_results: List[Dict] = []
    v_seq, _d_seq, crc_seq, failures = _pack_phase(
        "seq", requests=args.requests, extra_sets=(),
        rate=0.0, concurrency=args.concurrency,
        startup_s=args.smoke_startup_s, collect=seq_results)
    v_pack, _d_pack, crc_pack, fail_pack = _pack_phase(
        "packed", requests=args.requests,
        extra_sets=(f"serve_batch_max={S}",
                    f"serve_batch_linger_s={args.pack_linger}"),
        rate=rate, concurrency=args.concurrency,
        startup_s=args.smoke_startup_s, collect=pack_results)
    failures += fail_pack

    def by_scene(rows: List[Dict]) -> Dict[str, set]:
        m: Dict[str, set] = {}
        for r in rows:
            if r.get("status") == "ok":
                m.setdefault(str(r.get("scene")), set()).add(
                    (r.get("digest") or {}).get("artifact"))
        return m

    # invariant-digest identity: the `artifact` fingerprint is the one
    # digest field both paths compute (the fused mesh path materializes
    # no DeviceHandoff, so `plane` is sequential-only by design)
    seq_dg, pack_dg = by_scene(seq_results), by_scene(pack_results)
    for scene in sorted(set(seq_dg) | set(pack_dg)):
        sa = seq_dg.get(scene, set())
        sb = pack_dg.get(scene, set())
        for label, s in (("sequential", sa), ("packed", sb)):
            if len(s) != 1 or None in s:
                failures.append(f"{label} artifact digests for {scene} not "
                                f"unanimous: {sorted(map(str, s))}")
        if sa and sb and sa != sb:
            failures.append(f"artifact digest DIVERGED for {scene}: "
                            f"sequential {sorted(map(str, sa))} vs packed "
                            f"{sorted(map(str, sb))}")
    if crc_seq != crc_pack:
        diff = sorted(k for k in set(crc_seq) | set(crc_pack)
                      if crc_seq.get(k) != crc_pack.get(k))
        failures.append(f"artifact CRCs diverged between the paths: "
                        f"{diff[:8]}{'...' if len(diff) > 8 else ''}")
    elif not crc_seq:
        failures.append("no artifacts found to compare — both prediction "
                        "trees are empty")
    occ = v_pack.get("batch_occupancy")
    if not occ or occ <= 1.0:
        failures.append(f"batch occupancy {occ} — the packing scheduler "
                        f"never fused a batch (hist "
                        f"{v_pack.get('batch_hist')})")
    # the S-x latency bound is a SCENE-AXIS-PARALLEL claim: with >= S
    # devices each lane runs on its own hardware and a width-S dispatch
    # must beat S sequential runs. On fewer devices (single-CPU CI) the
    # fused dispatch serializes its lanes over the fused step's
    # worst-case mask capacity, so the bound cannot hold — the byte
    # identity / zero-compile / occupancy gates above still do, and the
    # latency comparison degrades to an advisory log.
    try:
        import jax
        n_dev = len(jax.devices())
    except Exception:  # noqa: BLE001 — no backend: advisory only
        n_dev = 1
    latency_gated = n_dev >= S
    verdict_gate = "enforced" if latency_gated else "advisory"
    p50_seq, p50_pack = v_seq.get("value"), v_pack.get("value")
    if p50_seq and p50_pack is not None and p50_pack >= S * p50_seq:
        msg = (f"packed p50 {p50_pack}s >= {S}x sequential p50 {p50_seq}s "
               f"— batching lost to the sequential path outright")
        if latency_gated:
            failures.append(msg)
        else:
            log(f"pack-drill: ADVISORY ({n_dev} device(s) < width {S}) — "
                f"{msg}")
    verdict = dict(v_pack)
    verdict["latency_gate"] = verdict_gate
    verdict["pack_drill"] = True
    verdict["batch_max"] = S
    verdict["arrival_rate_rps"] = rate
    verdict["sequential_p50_s"] = p50_seq
    verdict["sequential_wall_s"] = v_seq.get("wall_s")
    verdict["crc_entries"] = len(crc_pack)
    if failures:
        verdict["error"] = "; ".join(failures)
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if not args.no_ledger:
        append_ledger_row(verdict, args.ledger)
    if failures:
        for f in failures:
            log(f"pack-drill: FAIL — {f}")
        return 1
    log(f"pack-drill: PASS — occupancy {occ} (hist "
        f"{verdict.get('batch_hist')}), {len(crc_pack)} artifact CRCs + "
        f"per-scene digests byte-identical to sequential, zero post-warm "
        f"compiles, p50 {p50_pack}s vs sequential {p50_seq}s")
    return 0


# ---------------------------------------------------------------------------
# the pool drill: multi-worker serving — affinity, QoS, crash containment
# ---------------------------------------------------------------------------


def _pool_span_overlap(events: str) -> Tuple[int, int]:
    """Concurrency evidence on single-device CI: relayed ``serve.request``
    spans stamped with DIFFERENT ``worker_id`` whose wall windows overlap
    prove two slices executed device phases at the same time, even when
    wall-clock throughput cannot 2x on one shared CPU. Returns
    ``(overlapping_pairs, worker_tagged_spans)``."""
    from maskclustering_tpu.obs.events import KIND_SPAN, read_events

    spans: List[Tuple[float, float, int]] = []
    try:
        for ev in read_events(events, kinds=[KIND_SPAN]):
            if ev.get("name") != "serve.request":
                continue
            attrs = ev.get("attrs") or {}
            wid = attrs.get("worker_id")
            if wid is None:
                continue
            end = attrs.get("end_ts")
            if not isinstance(end, (int, float)):
                end = ev.get("ts", 0.0)
            dur = float(ev.get("dur_s", 0.0))
            spans.append((float(end) - dur, float(end), int(wid)))
    except OSError:
        return 0, 0
    overlaps = 0
    for i, (a0, a1, wa) in enumerate(spans):
        for b0, b1, wb in spans[i + 1:]:
            if wa != wb and min(a1, b1) - max(a0, b0) > 0.0:
                overlaps += 1
    return overlaps, len(spans)


def _pool_sched(sock: str) -> Tuple[Dict, Dict]:
    """One stats poll: (pool plane, scheduler counters) — both empty when
    the daemon is not pooled (itself a drill failure downstream)."""
    from maskclustering_tpu.serve.client import ServeClient

    with ServeClient(sock, timeout_s=30.0) as client:
        pool = client.stats().get("pool") or {}
    return pool, dict(pool.get("scheduler") or {})


def run_pool_drill(args) -> int:
    """The multi-worker serving CI gate (serve/pool.py), end to end on a
    real 2x1 CPU carve:

    1. warm burst  — mixed buckets x weighted tenants over both slices;
       every request ok, both workers alive and dispatching.
    2. affinity    — a second burst must route >= 90% bucket-warm (the
       scheduler's hit counters, measured as a post-warm delta).
    3. QoS         — an open-loop saturated burst under ``heavy:3`` vs
       ``light:1``: the stride scheduler must front-load heavy's
       completions 3:1 (+-25% over the burst's first half).
    4. quota       — a burst over ``capped``'s admission quota must
       answer typed ``quota`` rejects while admitted work still lands.
    5. crash       — SIGKILL worker 0's child mid-request: worker 1's
       traffic is untouched, the victim requeues and finishes ok, the
       black box + journal record the hop, and the respawned slice
       reaches first dispatch with ZERO compiles (shared AOT cache).

    Plus, over the whole run: per-scene artifact digests unanimous
    across slices (byte-identity is worker-independent), zero post-warm
    compiles on EVERY worker, and concurrency overlap between
    worker-tagged device spans (the single-device CI stand-in for the
    2-worker throughput claim).
    """
    from maskclustering_tpu.serve.client import ServeClient
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    tmp = tempfile.mkdtemp(prefix="mct_pool_drill_")
    sock = os.path.join(tmp, "mct.sock")
    events = os.path.join(tmp, "serve_events.jsonl")
    flight_dir = os.path.join(tmp, "flight")
    journal_dir = os.path.join(tmp, "journals")
    warm_names = []
    for name, params in BUCKET_SPECS:
        kw = dict(params)
        kw["image_hw"] = tuple(kw["image_hw"])
        write_scannet_layout(make_scene(**kw), tmp, name)
        warm_names.append(name)

    cmd = [sys.executable, "-m", "maskclustering_tpu.serve",
           "--config", "scannet", "--socket", sock, "--data_root", tmp,
           "--capacity", "64", "--retrace-sanitizer",
           # the shared AOT cache is the drill's warm-respawn lever: both
           # slices capture/restore from one directory
           "--aot-cache", os.path.join(tmp, "aot"),
           "--obs_events", events, "--warm", "+".join(warm_names),
           "--telemetry-window", "1.0",
           "--flight-dir", flight_dir,
           "--journal-dir", journal_dir,
           "--isolate-worker",
           "--workers", str(args.pool_workers),
           "--carve", f"{args.pool_workers}x1",
           "--tenants", "heavy:3,light:1,capped:1:2",
           "--set", "worker_heartbeat_s=30"]
    for kv in SMOKE_CONFIG_SETS:
        cmd += ["--set", kv]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log(f"pool-drill: starting daemon: {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, cwd=REPO_ROOT,
                            env=env, text=True)
    failures: List[str] = []
    all_results: List[Dict] = []
    verdict: Dict = {"metric": "serve s/request (pool drill p50)",
                     "value": None, "unit": "s/request",
                     "pool_drill": True, "pool_workers": args.pool_workers}
    try:
        if not _wait_for_socket(sock, proc, timeout_s=args.smoke_startup_s):
            log("pool-drill: FAIL — daemon never became reachable")
            proc.kill()
            return 1

        # -- phase 1: warm burst over both slices ---------------------------
        v_warm = run_load(sock, requests=8, concurrency=4, buckets=2,
                          deadline_s=0.0, resume=False,
                          tenant_mix=parse_tenant_mix("heavy:3,light:1"),
                          collect=all_results)
        verdict["value"] = v_warm.get("value")
        if v_warm["ok"] != 8:
            failures.append(f"warm burst: {v_warm['ok']}/8 ok")
        pool, sched_warm = _pool_sched(sock)
        workers = pool.get("workers") or []
        if len(workers) != args.pool_workers:
            failures.append(f"pool reports {len(workers)} worker(s), "
                            f"expected {args.pool_workers}")
        alive = sum(1 for w in workers if w.get("alive"))
        if alive != args.pool_workers:
            failures.append(f"only {alive}/{args.pool_workers} slices "
                            f"alive after the warm burst")
        idle_workers = [w["worker_id"] for w in workers
                        if not w.get("dispatched")]
        if idle_workers:
            failures.append(f"slice(s) {idle_workers} never dispatched — "
                            f"the scheduler is not spreading load")

        # -- phase 2: post-warm affinity ------------------------------------
        v_aff = run_load(sock, requests=8, concurrency=4, buckets=2,
                         deadline_s=0.0, resume=False,
                         tenant_mix=parse_tenant_mix("heavy:3,light:1"),
                         collect=all_results)
        if v_aff["ok"] != 8:
            failures.append(f"affinity burst: {v_aff['ok']}/8 ok")
        _pool2, sched_aff = _pool_sched(sock)
        d_hits = sched_aff.get("affinity_hits", 0) \
            - sched_warm.get("affinity_hits", 0)
        d_miss = sched_aff.get("affinity_misses", 0) \
            - sched_warm.get("affinity_misses", 0)
        # optimistic warmth bounds TOTAL misses at buckets x workers: a
        # (slice, bucket) pair phase 1 never happened to exercise pays its
        # one first-sight miss whenever it first dispatches — allow those
        # residual cold bookings, then everything else must route warm
        total_miss = sched_aff.get("affinity_misses", 0)
        bound = 2 * args.pool_workers
        if total_miss > bound:
            failures.append(f"{total_miss} affinity misses ever > the "
                            f"optimistic-warmth bound {bound} (buckets x "
                            f"workers) — warmth is not sticking")
        allowed_cold = max(0, bound - sched_warm.get("affinity_misses", 0))
        adj_miss = max(0, d_miss - allowed_cold)
        routed = d_hits + adj_miss
        rate = (d_hits / routed) if routed else 0.0
        verdict["affinity_hit_rate"] = round(rate, 3)
        if routed and rate < 0.9:
            failures.append(f"post-warm affinity hit rate {rate:.0%} "
                            f"({d_hits}/{routed} beyond first-sight) < 90% "
                            f"— bucket-warm routing is not sticking")
        if not routed:
            failures.append("affinity burst dispatched nothing through "
                            "the pool scheduler")

        # -- phase 3: weighted-fair QoS under saturation --------------------
        # open loop, arrivals ~instant: a real backlog forms, so dequeue
        # order IS the stride scheduler's. heavy (w=3) must front-load
        # its completions ~3:1 while light's backlog waits.
        qos_results: List[Dict] = []
        v_qos = run_load(sock, requests=32, concurrency=4, buckets=2,
                         deadline_s=0.0, resume=False,
                         tenant_mix=parse_tenant_mix("heavy:1,light:1"),
                         rate=200.0, collect=qos_results)
        all_results.extend(qos_results)
        if v_qos["ok"] != 32:
            failures.append(f"QoS burst: {v_qos['ok']}/32 ok")
        # completion order: tag lg-%04d maps back to the arrival index,
        # the [heavy, light] cycle maps index -> tenant
        heavy_first_half = 0
        order = [r for r in qos_results if r.get("status") == "ok"]
        for r in order[:16]:
            tag = str(r.get("tag") or "")
            try:
                idx = int(tag.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if idx % 2 == 0:
                heavy_first_half += 1
        verdict["qos_heavy_first_half"] = heavy_first_half
        # 3:1 target = 12 of 16; -25% floor = 9. An unweighted scheduler
        # completes the alternating arrivals ~8/16.
        if heavy_first_half < 9:
            failures.append(
                f"QoS: only {heavy_first_half}/16 of the first-half "
                f"completions were heavy's (3:1 weight demands >= 9) — "
                f"weighted-fair dequeue is not honoring weights")

        # -- phase 4: admission quota ---------------------------------------
        # 12 simultaneous requests for capped (quota 2): the slices'
        # feed + in-flight slots absorb the first few, the next two
        # queue (filling the quota), the rest MUST answer the typed
        # quota reject while admitted work still completes.
        quota_terms: List[Dict] = []
        qlock = threading.Lock()

        def _capped(i: int) -> None:
            kw = dict(BUCKET_SPECS[i % 2][1])
            with ServeClient(sock, timeout_s=600.0) as client:
                term, _st, _lat = client.run_scene(
                    BUCKET_SPECS[i % 2][0], synthetic=kw,
                    tag=f"cap-{i:02d}", tenant="capped")
            with qlock:
                quota_terms.append(term)

        qthreads = []
        for i in range(12):
            t = threading.Thread(target=_capped, args=(i,), daemon=True)
            qthreads.append(t)
            t.start()
        for t in qthreads:
            t.join(600.0)
        q_rejects = [t for t in quota_terms if t.get("kind") == "reject"
                     and t.get("reason") == "quota"]
        q_ok = [t for t in quota_terms if t.get("status") == "ok"]
        verdict["quota_rejects"] = len(q_rejects)
        if not q_rejects:
            failures.append("quota: 12 simultaneous requests over a "
                            "2-slot admission quota produced no typed "
                            "'quota' reject")
        elif not (q_rejects[0].get("detail") or ""):
            failures.append("quota: the reject carries no detail naming "
                            "the limit")
        if not q_ok:
            failures.append("quota: no capped request was admitted at "
                            "all — the quota gate is rejecting below the "
                            "limit")

        # -- phase 5: SIGKILL worker 0 mid-request --------------------------
        pool3, _ = _pool_sched(sock)
        pids = {w["worker_id"]: w.get("pid")
                for w in pool3.get("workers") or []}
        victim_pid = pids.get(0)
        crash_results: List[Dict] = []
        crash_box: Dict[str, Dict] = {}

        def _crash_burst() -> None:
            crash_box["verdict"] = run_load(
                sock, requests=6, concurrency=3, buckets=2,
                deadline_s=0.0, resume=False,
                tenant_mix=parse_tenant_mix("heavy:3,light:1"),
                collect=crash_results)

        burst_t = threading.Thread(target=_crash_burst, daemon=True)
        burst_t.start()
        # kill only once worker 0 is actually under a request — the drill
        # is crash containment mid-flight, not an idle-respawn exercise
        killed = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and victim_pid:
            try:
                pool_now, _ = _pool_sched(sock)
                w0 = next((w for w in pool_now.get("workers") or []
                           if w.get("worker_id") == 0), {})
                if w0.get("inflight") and w0.get("inflight_logged"):
                    # the victim's receive-time flight delta has reached
                    # the parent — `inflight_logged` counts exactly the
                    # in-flight ids the child's relayed ring acknowledged
                    # — so the black-box assertion's child-side rows are
                    # on the parent and the kill can land NOW (an explicit
                    # gate where a fixed post-inflight sleep raced the
                    # relay)
                    os.kill(int(victim_pid), signal.SIGKILL)
                    killed = True
                    log(f"pool-drill: SIGKILLed worker 0 child "
                        f"(pid {victim_pid}) mid-request")
                    break
            except (OSError, ProcessLookupError):
                break
            time.sleep(0.05)
        burst_t.join(600.0)
        v_crash = crash_box.get("verdict") or {}
        if not killed:
            failures.append("crash: worker 0 never held an in-flight "
                            "request to kill (or its pid was missing "
                            "from stats)")
        if v_crash.get("ok") != 6:
            failures.append(f"crash burst: {v_crash.get('ok')}/6 ok — a "
                            f"neighbor's request was NOT unaffected, or "
                            f"the victim never finished")
        if killed and v_crash.get("worker_crash_events", 0) < 1:
            failures.append("crash: no client saw a typed worker_crash "
                            "status event")
        verdict["worker_crash_events"] = v_crash.get("worker_crash_events")
        all_results.extend(crash_results)

        # -- drain + final digest -------------------------------------------
        digest = _drain_daemon(proc, failures, "pool drill")
    finally:
        if proc.poll() is None:
            proc.kill()

    if digest is None:
        failures.append("no final digest to assert the pool plane on")
    else:
        retrace = digest.get("retrace") or {}
        verdict["retrace_post_freeze"] = retrace.get("post_freeze")
        if retrace.get("post_freeze"):
            failures.append(f"{retrace['post_freeze']} post-warm "
                            f"compile(s) across the pool — the serve-many "
                            f"contract broke under multi-worker")
        if not retrace.get("frozen"):
            failures.append("retrace sanitizer never froze on some slice")
        per_worker = retrace.get("workers") or {}
        if len(per_worker) != args.pool_workers:
            failures.append(f"final digest carries retrace for "
                            f"{sorted(per_worker)} — expected all "
                            f"{args.pool_workers} slices")
        for wid, dg in sorted(per_worker.items()):
            if dg.get("post_freeze"):
                failures.append(f"worker {wid}: {dg['post_freeze']} "
                                f"post-warm compile(s)")
        # the respawned slice must have warm-started: its (fresh) child's
        # digest shows zero compiles, delivered by the shared AOT cache
        if killed and (per_worker.get("0") or {}).get("compiles", 0) != 0:
            failures.append(
                f"respawned worker 0 booked "
                f"{(per_worker.get('0') or {}).get('compiles')} "
                f"compile(s) — the AOT warm respawn did not deliver")
        worker = digest.get("worker") or {}
        verdict["worker_crashes"] = worker.get("crashes")
        verdict["worker_respawns"] = worker.get("respawns")
        if killed and not worker.get("crashes"):
            failures.append("crash: the pool digest recorded no crash")
        if killed and not worker.get("respawns"):
            failures.append("crash: worker 0 never respawned")
        dpool = digest.get("pool") or {}
        dsched = dpool.get("scheduler") or {}
        verdict["pool_dispatched"] = dsched.get("dispatched")
        tenants = dpool.get("tenants") or {}
        for t in ("heavy", "light", "capped"):
            if t not in tenants:
                failures.append(f"pool digest carries no QoS row for "
                                f"tenant {t!r}")

    # cross-slice byte identity: every scene's artifact digest must be
    # unanimous no matter which worker (or respawn generation) served it
    by_scene: Dict[str, set] = {}
    for r in all_results:
        if r.get("status") == "ok":
            by_scene.setdefault(str(r.get("scene")), set()).add(
                (r.get("digest") or {}).get("artifact"))
    for scene in sorted(by_scene):
        if len(by_scene[scene]) != 1 or None in by_scene[scene]:
            failures.append(
                f"artifact digests for {scene} not unanimous across "
                f"slices: {sorted(map(str, by_scene[scene]))}")
    if not by_scene:
        failures.append("no ok results carried artifact digests")

    # concurrency overlap: device phases on DIFFERENT workers must have
    # run simultaneously (the single-device CI form of the 2-worker
    # throughput claim; on real multi-chip hosts wall-clock also shows it)
    overlaps, tagged = _pool_span_overlap(events)
    verdict["span_overlaps"] = overlaps
    verdict["worker_tagged_spans"] = tagged
    if not tagged:
        failures.append("no serve.request span carries a worker_id tag — "
                        "per-worker attribution is dark")
    elif not overlaps:
        failures.append("no two spans from different workers ever "
                        "overlapped — the pool never actually served "
                        "concurrently")

    if killed:
        check_blackbox(flight_dir, events, journal_dir, verdict, failures)

    if failures:
        verdict["error"] = "; ".join(failures)
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if not args.no_ledger:
        append_ledger_row(verdict, args.ledger)
    if failures:
        for f in failures:
            log(f"pool-drill: FAIL — {f}")
        return 1
    log(f"pool-drill: PASS — {args.pool_workers} slices, affinity "
        f"{verdict['affinity_hit_rate']:.0%}, heavy front-loaded "
        f"{verdict['qos_heavy_first_half']}/16, {verdict['quota_rejects']} "
        f"typed quota reject(s), crash contained "
        f"({verdict['worker_crashes']} crash / {verdict['worker_respawns']} "
        f"respawn), {overlaps} cross-worker span overlap(s), zero "
        f"post-warm compiles on every slice")
    return 0


# ---------------------------------------------------------------------------
# mct-durable: the chaos drill — a killed worker mid-stream, a killed
# daemon mid-queue, and a byte-identical warm recovery through the WAL
# ---------------------------------------------------------------------------

# the streamed scene rides bucket A's shapes (same executables, so the
# classic warm vocabulary covers it) with its own content seed: stream-
# path artifacts land under their own scene directory and never collide
# with classic-path bytes on disk, so CRCs compare stream-to-stream and
# classic-to-classic across daemon generations
CHAOS_STREAM_SPEC: Tuple[str, Dict] = (
    "lg-s", {"num_boxes": 3, "num_frames": 10, "image_hw": [60, 80],
             "spacing": 0.06, "seed": 41})
CHAOS_IDEM_KEYS = 6


def _chaos_daemon(tmp: str, sock: str, *, events: str, retrace: bool,
                  fault_plan: Optional[str], workers: int,
                  warm_names: List[str]):
    """One chaos-drill daemon generation over the SHARED tmp state
    (data_root, AOT cache, journal dir + WAL, stream_state): only the
    socket and events file are per-generation. ``retrace=False`` is the
    cold capture pass (the stream path pays its compiles once, into the
    shared caches); armed generations must book zero."""
    cmd = [sys.executable, "-m", "maskclustering_tpu.serve",
           "--config", "scannet", "--socket", sock, "--data_root", tmp,
           "--capacity", "64",
           "--aot-cache", os.path.join(tmp, "aot"),
           "--obs_events", events, "--warm", "+".join(warm_names),
           "--telemetry-window", "1.0",
           "--flight-dir", os.path.join(tmp, "flight"),
           "--journal-dir", os.path.join(tmp, "journals"),
           "--stream-state", os.path.join(tmp, "stream_state"),
           "--isolate-worker", "--workers", str(workers),
           "--carve", f"{workers}x1",
           "--set", "worker_heartbeat_s=30"]
    if retrace:
        cmd.insert(cmd.index("--capacity"), "--retrace-sanitizer")
    if fault_plan:
        cmd += ["--fault-plan", fault_plan]
    for kv in SMOKE_CONFIG_SETS:
        cmd += ["--set", kv]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log(f"chaos-drill: starting daemon: {' '.join(cmd)}")
    return subprocess.Popen(cmd, stdout=subprocess.PIPE, cwd=REPO_ROOT,
                            env=env, text=True)


def _chaos_counter(sock: str, name: str, want: int,
                   timeout_s: float = 30.0) -> int:
    """Poll the cumulative telemetry counter ``name`` until >= want (the
    child books it; the cross-process relay delivers it on its own
    cadence, so a single immediate read would race)."""
    from maskclustering_tpu.serve.client import ServeClient

    seen = 0
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with ServeClient(sock, timeout_s=30.0) as client:
                tel = client.telemetry().get("telemetry") or {}
            counters = (tel.get("cumulative") or {}).get("counters") or {}
            seen = int(counters.get(name, 0))
        except OSError:
            pass
        if seen >= want:
            return seen
        time.sleep(0.25)
    return seen


def run_chaos_drill(args) -> int:
    """The mct-durable CI gate (exit 0 pass / 1 fail), three phases over
    ONE shared data_root + AOT cache + admission WAL + stream_state:

    1. cold capture + worker death mid-stream — a 2x1 pool serves a
       classic burst, then a live-scan stream; the stream owner's child
       is SIGKILLed with the session open. The session must RE-OPEN from
       its per-chunk snapshot (``serve.streams_resumed``) instead of
       answering ``stream_lost``, and the whole stream finishes ok.
    2. daemon death mid-queue — a fresh daemon under a scripted
       ``die:*.admission`` fault: idempotency-keyed requests are
       submitted until the FaultPlan SIGKILLs the whole daemon between
       the WAL admit row and the queue — the worst torn state.
    3. warm recovery — a restarted daemon over the same journal dir
       replays every journaled-but-unanswered request from the WAL;
       clients resubmit ALL keys and every one must answer ok (cached
       terminal stamped ``deduped``, live re-attach, or a fresh run),
       the stream re-runs end to end, the final digest books ZERO
       compiles (shared AOT cache -> restarted daemon warm), and every
       artifact CRC is byte-identical to the pre-death baseline.

    The verdict row stamps ``streams_resumed`` / ``wal_replayed`` /
    ``wal_deduped`` so ``obs.report --regress`` fences failover rows
    from plain serving rows (obs/ledger.durability_dimension).
    """
    from maskclustering_tpu.serve.client import ServeClient
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                    write_scannet_layout)

    tmp = tempfile.mkdtemp(prefix="mct_chaos_drill_")
    warm_names = []
    for name, params in BUCKET_SPECS:
        kw = dict(params)
        kw["image_hw"] = tuple(kw["image_hw"])
        write_scannet_layout(make_scene(**kw), tmp, name)
        warm_names.append(name)
    sname, sparams = CHAOS_STREAM_SPEC
    skw = dict(sparams)
    skw["image_hw"] = tuple(skw["image_hw"])
    write_scannet_layout(make_scene(**skw), tmp, sname)

    failures: List[str] = []
    verdict: Dict = {"metric": "serve s/request (chaos drill p50)",
                     "value": None, "unit": "s/request",
                     "chaos_drill": True}
    klock = threading.Lock()

    def keyed_round(sockpath: str, suffix: str, outcomes: List) -> None:
        """CHAOS_IDEM_KEYS concurrent keyed submissions; daemon death
        mid-round is the script, so transport errors record as dropped."""

        def one(i: int) -> None:
            # keys 0-2 ride lg-b, keys 3-5 lg-a: submitted in index order
            # under the phase-2 die:lg-a plan, the lg-b keys are already
            # WAL-journaled (queued/running) when key 3's admission
            # SIGKILLs the daemon — the mapping must stay FIXED across
            # rounds (an idempotent resubmit is the same work item)
            name, params = BUCKET_SPECS[1 if i < 3 else 0]
            try:
                with ServeClient(sockpath, timeout_s=600.0) as client:
                    term, _st, lat = client.run_scene(
                        name, synthetic=dict(params),
                        tag=f"chaos-{i:02d}{suffix}", idem=f"chaos-{i:02d}")
            except Exception as e:  # noqa: BLE001 — the daemon dying IS the drill
                term, lat = {"kind": "dropped", "error": str(e)[:160]}, None
            with klock:
                outcomes.append((i, term, lat))

        threads = []
        for i in range(CHAOS_IDEM_KEYS):
            t = threading.Thread(target=one, args=(i,), daemon=True,
                                 name=f"chaos-key-{i}{suffix}")
            threads.append(t)
            t.start()
            time.sleep(0.2)  # admission-order stagger, not a correctness gate
        for t in threads:
            t.join(600.0)

    # -- phase 1: cold capture + SIGKILL the stream owner mid-stream --------
    sock1 = os.path.join(tmp, "mct1.sock")
    events1 = os.path.join(tmp, "serve_events_1.jsonl")
    proc = _chaos_daemon(tmp, sock1, events=events1, retrace=False,
                         fault_plan=None, workers=args.pool_workers,
                         warm_names=warm_names)
    streams_resumed = 0
    digest1 = None
    try:
        if not _wait_for_socket(sock1, proc, timeout_s=args.smoke_startup_s):
            log("chaos-drill: FAIL — phase-1 daemon never became reachable")
            proc.kill()
            return 1
        v_base = run_load(sock1, requests=6, concurrency=3, buckets=2,
                          deadline_s=0.0, resume=False)
        verdict["value"] = v_base.get("value")
        verdict["p95_s"] = v_base.get("p95_s")
        verdict["requests"] = v_base.get("requests")
        verdict["concurrency"] = v_base.get("concurrency")
        if v_base.get("ok") != 6:
            failures.append(f"baseline burst: {v_base.get('ok')}/6 ok")
        with ServeClient(sock1, timeout_s=600.0) as sc:
            ev1, _st = sc.stream_chunk(sname, chunk=5, synthetic=dict(skw))
            if ev1.get("status") != "ok" or ev1.get("done"):
                failures.append(f"stream chunk 1 answered "
                                f"{ev1.get('kind')}/{ev1.get('status')} "
                                f"done={ev1.get('done')} (want ok, not done)")
            owner_pid = None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and owner_pid is None:
                pool_now, _sched = _pool_sched(sock1)
                for w in pool_now.get("workers") or []:
                    if w.get("open_streams") and w.get("pid"):
                        owner_pid = int(w["pid"])
                        break
                if owner_pid is None:
                    time.sleep(0.1)
            if owner_pid is None:
                failures.append("stream owner slice never showed an open "
                                "session in stats — nothing to kill")
            else:
                os.kill(owner_pid, signal.SIGKILL)
                log(f"chaos-drill: SIGKILLed stream owner child "
                    f"(pid {owner_pid}) with the session open")
            # the continuation op: the snapshot (stream_journal_every
            # cadence) must re-open the session on a warm slice — a
            # stream_lost reject here is the pre-WAL behavior regressing
            ev2, _st2 = sc.stream_chunk(sname, chunk=5, synthetic=dict(skw))
            if ev2.get("status") != "ok" or not ev2.get("done"):
                failures.append(
                    f"post-kill stream chunk answered "
                    f"{ev2.get('kind')}/{ev2.get('status') or ev2.get('reason')}"
                    f" — the session did not fail over from its snapshot")
            fin, _stf = sc.stream_end(sname)
            if fin.get("status") != "ok":
                failures.append(f"stream_end after failover answered "
                                f"{fin.get('kind')}/{fin.get('status')}")
        streams_resumed = _chaos_counter(sock1, "serve.streams_resumed", 1)
        if streams_resumed < 1:
            failures.append("serve.streams_resumed never booked — the "
                            "session was rebuilt from scratch (or lost), "
                            "not resumed from its snapshot")
        digest1 = _drain_daemon(proc, failures, "chaos phase 1")
    finally:
        if proc.poll() is None:
            proc.kill()
    if digest1 is not None:
        worker1 = digest1.get("worker") or {}
        if not worker1.get("crashes"):
            failures.append("phase 1: the pool digest recorded no worker "
                            "crash for the SIGKILLed stream owner")
        if not worker1.get("respawns"):
            failures.append("phase 1: the killed slice never respawned")
    crc_base = _artifact_crcs(os.path.join(tmp, "prediction"))
    if not crc_base:
        failures.append("phase 1 exported no artifacts to baseline")

    # -- phase 2: a scripted daemon SIGKILL mid-queue -----------------------
    sock2 = os.path.join(tmp, "mct2.sock")
    events2 = os.path.join(tmp, "serve_events_2.jsonl")
    # the die fires at the FIRST lg-a admission (count = firings, and one
    # SIGKILL is terminal): the staggered lg-b keys before it are WAL-
    # journaled but unanswered, the lg-a key itself is journaled (admit
    # flushes BEFORE the inject seam), later keys never reach admission
    # at all — every torn state the restart must reconcile
    proc = _chaos_daemon(tmp, sock2, events=events2, retrace=True,
                         fault_plan="die:lg-a.admission:1",
                         workers=args.pool_workers, warm_names=warm_names)
    outcomes2: List[Tuple[int, Dict, Optional[float]]] = []
    child_pids: List[int] = []
    try:
        if not _wait_for_socket(sock2, proc, timeout_s=args.smoke_startup_s):
            log("chaos-drill: FAIL — phase-2 daemon never became reachable")
            proc.kill()
            return 1
        pool2, _sched2 = _pool_sched(sock2)
        child_pids = [int(w["pid"]) for w in pool2.get("workers") or []
                      if w.get("pid")]
        keyed_round(sock2, "", outcomes2)
        try:
            proc.wait(timeout=60.0)
        except subprocess.TimeoutExpired:
            failures.append("phase 2: the die FaultPlan never killed the "
                            "daemon (still alive after the keyed burst)")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30.0)
    if proc.returncode != -signal.SIGKILL:
        failures.append(f"phase 2: daemon exit {proc.returncode} (expected "
                        f"-{int(signal.SIGKILL)} — the scripted admission-"
                        f"seam SIGKILL)")
    for pid in child_pids:
        # the daemon died uncleanly by design; its orphaned slice children
        # exit on pipe EOF, but the drill must not race that against
        # phase 3's artifact writes — reap them explicitly
        try:
            os.kill(pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass
    dropped = sum(1 for _i, t, _l in outcomes2 if t.get("kind") == "dropped")
    verdict["chaos_dropped"] = dropped
    if not dropped:
        failures.append("phase 2: every keyed request answered before the "
                        "daemon died — nothing was left mid-queue for the "
                        "WAL to prove")

    # -- phase 3: warm restart, WAL replay, keyed resubmit, byte identity ---
    sock3 = os.path.join(tmp, "mct3.sock")
    events3 = os.path.join(tmp, "serve_events_3.jsonl")
    proc = _chaos_daemon(tmp, sock3, events=events3, retrace=True,
                         fault_plan=None, workers=args.pool_workers,
                         warm_names=warm_names)
    outcomes3: List[Tuple[int, Dict, Optional[float]]] = []
    digest3 = None
    try:
        if not _wait_for_socket(sock3, proc, timeout_s=args.smoke_startup_s):
            log("chaos-drill: FAIL — restarted daemon never became reachable")
            proc.kill()
            return 1
        with ServeClient(sock3, timeout_s=30.0) as client:
            durable = client.stats().get("durable") or {}
        if not durable.get("wal_replayed"):
            failures.append(f"restart replayed nothing from the WAL "
                            f"(durable panel: {durable}) — the journaled "
                            f"mid-queue requests were lost")
        keyed_round(sock3, "-r2", outcomes3)
        ok3 = sum(1 for _i, t, _l in outcomes3 if t.get("status") == "ok")
        deduped3 = sum(1 for _i, t, _l in outcomes3 if t.get("deduped"))
        verdict["chaos_resubmit_ok"] = ok3
        verdict["chaos_deduped_terminals"] = deduped3
        if ok3 != CHAOS_IDEM_KEYS:
            bad = [(i, t.get("kind"), t.get("status") or t.get("reason")
                    or t.get("error")) for i, t, _l in sorted(outcomes3)
                   if t.get("status") != "ok"]
            failures.append(f"resubmit round: {ok3}/{CHAOS_IDEM_KEYS} keys "
                            f"answered ok ({bad}) — eventual completion "
                            f"across the daemon death does not hold")
        # the stream re-runs end to end on the restarted daemon (fresh
        # session: phase 1's stream_end deleted its settled snapshot)
        with ServeClient(sock3, timeout_s=600.0) as sc:
            final, chunk_events = sc.stream_scene(sname, chunk=5,
                                                  synthetic=dict(skw))
        if final.get("status") != "ok" or any(
                e.get("status") != "ok" for e in chunk_events):
            failures.append(f"restarted-daemon stream answered "
                            f"{final.get('kind')}/{final.get('status')} — "
                            f"the warm restart does not serve streams")
        digest3 = _drain_daemon(proc, failures, "chaos phase 3")
    finally:
        if proc.poll() is None:
            proc.kill()

    if digest3 is None:
        failures.append("no phase-3 digest to assert durability on")
    else:
        durable3 = digest3.get("durable") or {}
        verdict["wal_replayed"] = durable3.get("wal_replayed")
        verdict["wal_deduped"] = durable3.get("wal_deduped")
        verdict["journals_pruned"] = durable3.get("journals_pruned")
        if not durable3.get("wal_replayed"):
            failures.append("phase 3 digest books wal_replayed=0")
        if not (durable3.get("wal_deduped", 0)
                or durable3.get("wal_reattached", 0)):
            failures.append("no resubmitted key deduped or re-attached — "
                            "the idempotency contract never engaged")
        retrace3 = digest3.get("retrace") or {}
        verdict["retrace_compiles"] = retrace3.get("compiles")
        if retrace3.get("compiles", 0) != 0:
            failures.append(
                f"restarted daemon booked {retrace3.get('compiles')} "
                f"compile(s) — the shared AOT cache did not deliver a "
                f"zero-compile recovery")
        if retrace3.get("post_freeze"):
            failures.append(f"{retrace3['post_freeze']} post-warm "
                            f"compile(s) on the restarted daemon")
    verdict["streams_resumed"] = max(
        streams_resumed,
        int(((digest1 or {}).get("worker") or {}).get("streams_resumed")
            or 0))

    crc_final = _artifact_crcs(os.path.join(tmp, "prediction"))
    if crc_base and crc_final != crc_base:
        diff = sorted(k for k in set(crc_base) | set(crc_final)
                      if crc_base.get(k) != crc_final.get(k))
        failures.append(f"artifact CRCs diverged across the daemon death: "
                        f"{diff[:8]}{'...' if len(diff) > 8 else ''}")
    verdict["crc_entries"] = len(crc_final)

    if failures:
        verdict["error"] = "; ".join(failures)
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if not args.no_ledger:
        append_ledger_row(verdict, args.ledger)
    if failures:
        for f in failures:
            log(f"chaos-drill: FAIL — {f}")
        return 1
    log(f"chaos-drill: PASS — stream failed over "
        f"({verdict['streams_resumed']} resume(s)), daemon death replayed "
        f"{verdict['wal_replayed']} request(s) from the WAL "
        f"({verdict['chaos_deduped_terminals']} deduped terminal(s)), "
        f"{verdict['crc_entries']} artifact CRCs byte-identical, zero "
        f"compiles on the restarted daemon")
    return 0


# ---------------------------------------------------------------------------
# mct-sentinel: the audited goldens regeneration + the canary drill
# ---------------------------------------------------------------------------

DEFAULT_GOLDENS = os.path.join(REPO_ROOT, "canary_goldens.json")
SURFACE_BASELINE = os.path.join(REPO_ROOT, "compile_surface_baseline.json")


def run_write_goldens(args) -> int:
    """Regenerate canary_goldens.json: ONE in-process canary round under
    the census cfg (obs/canary.goldens_config — the same knobs the
    compile-surface census pins) over the committed surface baseline's
    workload. The resulting git diff IS the audit artifact: inspect it
    before committing (a changed digest at an unchanged coordinate is a
    correctness change, not a refresh)."""
    from maskclustering_tpu.obs import canary as _canary
    from maskclustering_tpu.run import init_backend_or_die

    init_backend_or_die(120.0, platform="cpu")  # goldens are CPU-generated
    cfg = _canary.goldens_config()
    path = args.write_goldens
    log(f"write-goldens: census cfg ({cfg.count_dtype}, fpad "
        f"{cfg.frame_pad_multiple}, mpad {cfg.mask_pad_multiple}), "
        f"workload from {SURFACE_BASELINE}")
    t0 = time.monotonic()
    try:
        goldens = _canary.generate_goldens(cfg,
                                           baseline_path=SURFACE_BASELINE)
    except (RuntimeError, ValueError) as e:
        log(f"write-goldens: FAIL — {e}")
        return 1
    doc = _canary.write_goldens(path, goldens, config={
        "count_dtype": cfg.count_dtype,
        "distance_threshold": cfg.distance_threshold,
        "frame_pad_multiple": cfg.frame_pad_multiple,
        "mask_pad_multiple": cfg.mask_pad_multiple,
        "point_chunk": cfg.point_chunk,
        "backend": "cpu",
    })
    print(json.dumps({"kind": "goldens", "path": path,
                      "coords": sorted(doc["goldens"]),
                      "seconds": round(time.monotonic() - t0, 1)},
                     sort_keys=True), flush=True)
    log(f"write-goldens: wrote {len(doc['goldens'])} coordinate(s) to "
        f"{path} — audit the diff before committing")
    return 0


def _spawn_sentinel_daemon(tmp: str, *, goldens: str, interval_s: float,
                           fault_plan: Optional[str] = None):
    """A warm-baseline daemon with the sentinel armed (census knobs are
    the scannet config's own — the drill must probe under EXACTLY the
    goldens' cfg, so no --set overrides here)."""
    sock = os.path.join(tmp, "mct.sock")
    events = os.path.join(tmp, "serve_events.jsonl")
    flight_dir = os.path.join(tmp, "flight")
    cmd = [sys.executable, "-m", "maskclustering_tpu.serve",
           "--config", "scannet", "--socket", sock, "--data_root", tmp,
           "--retrace-sanitizer",
           "--aot-cache", os.path.join(tmp, "aot"),
           "--obs_events", events,
           "--warm-baseline", SURFACE_BASELINE,
           "--telemetry-window", "1.0",
           "--flight-dir", flight_dir,
           "--journal-dir", os.path.join(tmp, "journals"),
           "--canary-interval", str(interval_s),
           "--canary-goldens", goldens]
    if fault_plan:
        cmd += ["--fault-plan", fault_plan]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log(f"canary-drill: starting daemon: {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, cwd=REPO_ROOT,
                            env=env, text=True)
    return proc, sock, events, flight_dir


def _poll_sentinel(sock: str, done, timeout_s: float) -> Optional[Dict]:
    """Poll ``status detail=sentinel`` until ``done(stats)`` or timeout;
    returns the last sentinel snapshot (None when never reachable)."""
    from maskclustering_tpu.serve.client import ServeClient

    deadline = time.monotonic() + timeout_s
    snap = None
    while time.monotonic() < deadline:
        try:
            with ServeClient(sock, timeout_s=30.0) as client:
                snap = client.sentinel().get("sentinel") or snap
        except OSError:
            pass
        if snap is not None and done(snap):
            return snap
        time.sleep(0.2)
    return snap


def _drain_daemon(proc, failures: List[str], phase: str):
    """SIGTERM -> communicate; returns the parsed final digest line."""
    proc.send_signal(signal.SIGTERM)
    try:
        out, _ = proc.communicate(timeout=120.0)
    except subprocess.TimeoutExpired:
        proc.kill()
        failures.append(f"{phase}: daemon did not drain within 120s of "
                        f"SIGTERM")
        return None
    if proc.returncode != 143:
        failures.append(f"{phase}: daemon exit code {proc.returncode} "
                        f"(expected 143 — SIGTERM-clean drain)")
    for line in (out or "").splitlines():
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if doc.get("kind") == "digest":
            return doc
    failures.append(f"{phase}: daemon printed no final digest line")
    return None


def _slo_check(events: str) -> Tuple[int, str]:
    """Offline SLO verdict over the daemon's events file (the CI shape:
    ``obs.slo --events ... --check``)."""
    r = subprocess.run(
        [sys.executable, "-m", "maskclustering_tpu.obs.slo",
         "--events", events, "--check"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120.0)
    return r.returncode, (r.stdout or "") + (r.stderr or "")


def run_canary_drill(args) -> int:
    """The end-to-end sentinel gate, two phases against the COMMITTED
    goldens:

    1. clean soak — a sentinel-armed warm-baseline daemon idles through
       >= 2 canary rounds: zero drift, every goldens coordinate verified,
       zero post-warm compiles (probes replay warm executables), and the
       offline SLO check passes.
    2. corrupt drill — the same daemon under ``corrupt:A.host`` (a silent
       deterministic bit-flip of scene A's pulled assignment — no
       exception, so the retry ladder CANNOT heal it): drift must be
       detected on the FIRST canary round, the typed ``canary.drift``
       event and the ``canary_drift`` flight dump must name the
       coordinate, and ``obs.slo --check`` must exit 2 naming the
       zero-tolerance ``correctness`` objective.
    """
    from maskclustering_tpu.analysis.retrace import expected_goldens_coords
    from maskclustering_tpu.obs import flight as _flight

    goldens = args.canary_goldens or DEFAULT_GOLDENS
    if not os.path.exists(goldens):
        log(f"canary-drill: FAIL — no goldens at {goldens}; generate with "
            f"--write-goldens and commit")
        return 1
    expected = expected_goldens_coords()
    failures: List[str] = []
    verdict: Dict = {"metric": "serve canary time-to-detection (s)",
                     "value": None, "unit": "s", "canary_drill": True}

    # -- phase 1: clean soak ------------------------------------------------
    tmp = tempfile.mkdtemp(prefix="mct_canary_clean_")
    proc, sock, events, _fd = _spawn_sentinel_daemon(
        tmp, goldens=goldens, interval_s=args.canary_interval)
    try:
        if not _wait_for_socket(sock, proc, timeout_s=args.smoke_startup_s):
            log("canary-drill: FAIL — clean-soak daemon never became "
                "reachable")
            proc.kill()
            return 1
        snap = _poll_sentinel(sock, lambda s: int(s.get("rounds", 0)) >= 2,
                              timeout_s=180.0)
        digest = _drain_daemon(proc, failures, "clean soak")
    finally:
        if proc.poll() is None:
            proc.kill()
    if snap is None or int(snap.get("rounds", 0)) < 2:
        failures.append(f"clean soak: sentinel completed "
                        f"{int((snap or {}).get('rounds', 0))} round(s) in "
                        f"180s (need >= 2)")
    if snap:
        if int(snap.get("drift_total", 0)):
            failures.append(f"clean soak: {snap['drift_total']} drift "
                            f"event(s) against committed goldens — "
                            f"outputs changed or goldens are stale")
        seen = set(snap.get("coords") or ())
        if seen != expected:
            failures.append(f"clean soak: verified coordinates {sorted(seen)} "
                            f"!= goldens coordinates {sorted(expected)}")
        verdict["canary_probes"] = int(snap.get("rounds", 0)) * len(expected)
        verdict["digest_coord"] = ",".join(sorted(seen))
    if digest:
        retrace = digest.get("retrace") or {}
        if retrace.get("post_freeze"):
            failures.append(f"clean soak: {retrace['post_freeze']} post-warm "
                            f"compile(s) — canary probes must replay warm "
                            f"executables, never compile")
        canary = digest.get("canary") or {}
        if not canary.get("rounds"):
            failures.append("clean soak: the final digest carries no canary "
                            "round count — the sentinel summary is dark")
    rc, out = _slo_check(events)
    if rc != 0:
        failures.append(f"clean soak: offline SLO check exited {rc} "
                        f"(want 0): {out.strip()[:200]}")

    # -- phase 2: the corrupt drill -----------------------------------------
    tmp2 = tempfile.mkdtemp(prefix="mct_canary_corrupt_")
    proc, sock, events2, flight_dir = _spawn_sentinel_daemon(
        tmp2, goldens=goldens, interval_s=args.canary_interval,
        fault_plan="corrupt:A.host")
    t_start = time.monotonic()
    try:
        if not _wait_for_socket(sock, proc, timeout_s=args.smoke_startup_s):
            log("canary-drill: FAIL — corrupt-drill daemon never became "
                "reachable")
            proc.kill()
            return 1
        # >= 2 drift events: the burn-rate rule pages on repeated
        # occurrences, a single blip never does (obs/slo.py)
        snap2 = _poll_sentinel(
            sock, lambda s: int(s.get("drift_total", 0)) >= 2,
            timeout_s=180.0)
        detect_s = time.monotonic() - t_start
        _drain_daemon(proc, failures, "corrupt drill")
    finally:
        if proc.poll() is None:
            proc.kill()
    if snap2 is None:
        failures.append("corrupt drill: sentinel op never answered")
    else:
        rounds2 = int(snap2.get("rounds", 0))
        drift2 = int(snap2.get("drift_total", 0))
        verdict["canary_drift"] = drift2
        verdict["value"] = round(detect_s, 1)
        if drift2 < 2:
            failures.append(f"corrupt drill: only {drift2} drift event(s) "
                            f"after {rounds2} round(s) — the bit-flip went "
                            f"undetected")
        elif rounds2 and drift2 < rounds2:
            # every round probes the corrupted scene; fewer drifts than
            # rounds means some probe of A silently passed
            failures.append(f"corrupt drill: {drift2} drift(s) over "
                            f"{rounds2} round(s) — detection missed "
                            f"round(s)")
        drift_coords = snap2.get("drift_coords") or {}
        if not drift_coords:
            failures.append("corrupt drill: no drift coordinate recorded")
    # the typed event on the armed sink
    drift_events = []
    try:
        with open(events2, "r", encoding="utf-8") as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("kind") == "canary.drift":
                    drift_events.append(ev)
    except OSError:
        pass
    if not drift_events:
        failures.append(f"corrupt drill: no typed canary.drift event in "
                        f"{events2}")
    elif not (drift_events[0].get("coord")
              and drift_events[0].get("fields")):
        failures.append("corrupt drill: the canary.drift event names no "
                        "coordinate/fields — drift is unattributable")
    # the postmortem flight dump naming the coordinate
    dumps = sorted(os.listdir(flight_dir)) if os.path.isdir(flight_dir) \
        else []
    drift_dumps = [n for n in dumps if "canary_drift" in n]
    if not drift_dumps:
        failures.append(f"corrupt drill: no canary_drift flight dump under "
                        f"{flight_dir} (found: {dumps or 'nothing'})")
    else:
        _meta, rows = _flight.read_dump(
            os.path.join(flight_dir, drift_dumps[-1]))
        if not any(r.get("kind") == "canary.drift" and r.get("coord")
                   for r in rows):
            failures.append("corrupt drill: the flight dump carries no "
                            "canary.drift row naming the coordinate")
    # the SLO plane must page, naming the zero-tolerance objective
    rc2, out2 = _slo_check(events2)
    if rc2 != 2:
        failures.append(f"corrupt drill: offline SLO check exited {rc2} "
                        f"(want 2 — the correctness objective must page)")
    elif "correctness" not in out2:
        failures.append(f"corrupt drill: SLO violation names no "
                        f"'correctness' objective: {out2.strip()[:200]}")

    if failures:
        verdict["error"] = "; ".join(failures)
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if not args.no_ledger:
        append_ledger_row(verdict, args.ledger)
    if failures:
        for f in failures:
            log(f"canary-drill: FAIL — {f}")
        return 1
    log(f"canary-drill: PASS — clean soak held goldens, corruption "
        f"detected in {verdict['value']}s "
        f"({verdict.get('canary_drift')} drift event(s)), SLO paged on "
        f"correctness")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="mct-serve load generator (+ --smoke CI gate)")
    parser.add_argument("--socket", default=None,
                        help="daemon AF_UNIX socket path")
    parser.add_argument("--host", default=None, help="daemon TCP host")
    parser.add_argument("--port", type=int, default=0, help="daemon TCP port")
    parser.add_argument("--requests", type=int, default=8,
                        help="total requests to fire (default 8)")
    parser.add_argument("--concurrency", type=int, default=4,
                        help="concurrent client connections (default 4)")
    parser.add_argument("--buckets", type=int, default=2,
                        help="how many synthetic shape buckets to mix "
                             "(1..2, default 2)")
    parser.add_argument("--deadline", type=float, default=0.0,
                        help="per-request deadline_s (0 = none)")
    parser.add_argument("--tenant-mix", default=None, metavar="A:3,B:1",
                        help="weighted tenant identities stamped on the "
                             "burst (name:weight, comma-joined); arms the "
                             "per-tenant accounting assertions (smoke "
                             "default: A:3,B:1)")
    parser.add_argument("--rate", type=float, default=0.0,
                        help="open-loop arrival rate in requests/s: request "
                             "i is released at t0 + i/rate regardless of "
                             "in-flight count (0 = closed loop driven by "
                             "--concurrency)")
    parser.add_argument("--resume", action="store_true",
                        help="send resume=true (repeats become artifact "
                             "skips — throughput numbers then measure "
                             "admission, not execution)")
    parser.add_argument("--ledger", default=None,
                        help="perf ledger path (default: PERF_LEDGER.jsonl "
                             "/ $MCT_PERF_LEDGER)")
    parser.add_argument("--no-ledger", action="store_true",
                        help="do not append a serve ledger row")
    parser.add_argument("--shutdown", action="store_true",
                        help="send a shutdown op after the burst")
    parser.add_argument("--smoke", action="store_true",
                        help="self-contained CI smoke: spawn a daemon "
                             "subprocess, assert clean drain + zero "
                             "post-warm compiles")
    parser.add_argument("--isolate-worker", action="store_true",
                        help="smoke: run the daemon with the process-"
                             "isolated device worker (serve/supervisor.py)")
    parser.add_argument("--crash-drill", action="store_true",
                        help="smoke: SIGKILL the isolated worker under a "
                             "request (crash:lg-b.device:1 unless "
                             "--fault-plan overrides) and assert respawn, "
                             "requeue, all-ok, and a ZERO-compile "
                             "respawned worker (implies --isolate-worker)")
    parser.add_argument("--smoke-startup-s", type=float, default=180.0,
                        help="smoke: max seconds for daemon warm-up "
                             "before first request")
    parser.add_argument("--fault-plan", default=None,
                        help="smoke only: FaultPlan spec passed to the "
                             "daemon (e.g. 'flaky:lg-b:1')")
    parser.add_argument("--pack-drill", action="store_true",
                        help="the continuous-batching CI gate: one "
                             "sequential daemon + one packing daemon over "
                             "the same mixed-bucket burst; artifact CRCs "
                             "and per-scene digests must match byte for "
                             "byte, zero post-warm compiles, occupancy > 1")
    parser.add_argument("--pack-batch-max", type=int, default=3,
                        help="pack drill: serve_batch_max for the packing "
                             "daemon (default 3)")
    parser.add_argument("--pack-linger", type=float, default=0.3,
                        help="pack drill: serve_batch_linger_s for the "
                             "packing daemon (default 0.3)")
    parser.add_argument("--pool-drill", action="store_true",
                        help="the multi-worker serving CI gate: a real "
                             "2x1-carved CPU pool must route >= 90% "
                             "bucket-warm, honor 3:1 weighted-fair "
                             "dequeue and admission quotas, contain a "
                             "mid-request SIGKILL of worker 0 (neighbor "
                             "untouched, victim requeued, warm respawn), "
                             "serve byte-identical artifacts on every "
                             "slice, and overlap device phases across "
                             "workers — with zero post-warm compiles")
    parser.add_argument("--pool-workers", type=int, default=2,
                        help="pool drill: slice count (default 2)")
    parser.add_argument("--chaos-drill", action="store_true",
                        help="the mct-durable CI gate, three daemon "
                             "generations over one shared WAL + AOT cache "
                             "+ stream_state: SIGKILL a pool child mid-"
                             "stream (session must resume from its "
                             "snapshot), SIGKILL the whole daemon mid-"
                             "queue via a die:*.admission FaultPlan, then "
                             "restart — WAL replay + idempotent resubmit "
                             "must answer EVERY key ok with byte-identical "
                             "artifacts and zero compiles")
    parser.add_argument("--write-goldens", nargs="?", const=DEFAULT_GOLDENS,
                        default=None, metavar="PATH",
                        help="regenerate canary_goldens.json (flag alone: "
                             "the repo-root file) via one in-process canary "
                             "round under the census cfg — audit the git "
                             "diff before committing")
    parser.add_argument("--canary-drill", action="store_true",
                        help="the mct-sentinel CI gate: clean soak (zero "
                             "drift, zero post-warm compiles) then a "
                             "scripted corrupt:A.host bit-flip that must "
                             "be detected within one canary round, dump a "
                             "postmortem and page the SLO correctness "
                             "objective")
    parser.add_argument("--canary-goldens", default=None, metavar="PATH",
                        help="committed goldens for --canary-drill "
                             "(default: the repo-root canary_goldens.json)")
    parser.add_argument("--canary-interval", type=float, default=1.0,
                        help="--canary-drill scheduler period seconds "
                             "(default 1.0)")
    args = parser.parse_args(argv)

    if args.write_goldens:
        return run_write_goldens(args)
    if args.canary_drill:
        return run_canary_drill(args)
    if args.chaos_drill:
        return run_chaos_drill(args)
    if args.pool_drill:
        return run_pool_drill(args)
    if args.pack_drill:
        return run_pack_drill(args)
    if args.smoke:
        return run_smoke(args)
    if not args.socket and not args.host:
        parser.error("need --socket or --host/--port (or --smoke)")
    tenant_mix = parse_tenant_mix(args.tenant_mix)
    verdict = run_load(_address(args), requests=args.requests,
                       concurrency=args.concurrency, buckets=args.buckets,
                       deadline_s=args.deadline, resume=args.resume,
                       tenant_mix=tenant_mix, rate=args.rate)
    from maskclustering_tpu.serve.client import ServeClient

    tenant_failures: List[str] = []
    with ServeClient(_address(args), timeout_s=30.0) as client:
        stats = client.telemetry()
        tel = stats.get("telemetry") or {}
        if tel:
            verdict["telemetry_windows"] = len(tel.get("windows") or [])
            verdict["window_p95"] = worst_window_p95(tel.get("windows"))
            if tenant_mix:
                tenants = check_tenant_accounting(
                    tel, verdict.get("tenant_mix_sent") or {},
                    tenant_failures)
                if tenants:
                    verdict["tenants"] = tenants
        retrace = stats.get("retrace") or {}
        if retrace:
            verdict["retrace_compiles"] = retrace.get("compiles")
            verdict["retrace_repeats"] = retrace.get("repeats")
            verdict["retrace_post_freeze"] = retrace.get("post_freeze")
        if args.shutdown:
            client.shutdown()
    for f in tenant_failures:
        # against a long-lived daemon prior (possibly untenanted) traffic
        # legitimately skews the cumulative sums — warn, don't gate (the
        # smoke runs the same check against a fresh daemon and gates)
        log(f"WARNING — {f}")
    print(json.dumps(verdict, sort_keys=True), flush=True)
    if not args.no_ledger:
        append_ledger_row(verdict, args.ledger)
    if verdict["failed"] or verdict["ok"] < args.requests:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
