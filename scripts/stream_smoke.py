"""Streaming smoke: the chunked-accumulation contract on a 2-scene CPU run.

CI's drill of the streaming layer (scripts/ci.sh, budgeted < 180 s),
exercising the three acceptance claims deterministically at chunk 8:

1. **convergence digest** — a scene one chunk covers entirely produces
   BYTE-IDENTICAL artifacts to the batch path, and a 3-chunk scene's
   final instances match the batch object count (the AP-equivalence
   proxy the tier-1 suite pins in full);
2. **zero post-warm compiles across chunks 2..K** — the retrace
   sanitizer freezes after chunk 1 of a fresh stream; chunks 2..K must
   book no post-freeze compile violations (a chunk is just another
   bucket coordinate, so the steady state dispatches warm);
3. **capped residency** — ``stream.max_plane_bytes`` stays strictly
   under the full-scene claim-plane set.

Exit 0 = every expectation held; any assertion prints and exits 1.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# the image preloads the TPU plugin via sitecustomize: the env var is too
# late, the config flag is not (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

from maskclustering_tpu import obs  # noqa: E402
from maskclustering_tpu.analysis import retrace_sanitizer  # noqa: E402
from maskclustering_tpu.config import load_config  # noqa: E402
from maskclustering_tpu.run import cluster_scenes  # noqa: E402
from maskclustering_tpu.utils.compile_cache import scene_pads  # noqa: E402
from maskclustering_tpu.utils.synthetic import (make_scene,  # noqa: E402
                                                to_scene_tensors,
                                                write_scannet_layout)

SCENE_ONE = "scene0000_00"  # 8 frames: one chunk covers it (byte identity)
SCENE_MULTI = "scene0001_00"  # 24 frames: 3 chunks at chunk 8
CHUNK = 8


def _cfg(root, name, **kw):
    return load_config("scannet").replace(
        data_root=root, config_name=name, step=1, distance_threshold=0.05,
        mask_pad_multiple=32, frame_pad_multiple=4, point_chunk=2048,
        retry_backoff_s=0.01, **kw)


def _artifact(root, name, scene):
    return os.path.join(root, "prediction", name + "_class_agnostic",
                        f"{scene}.npz")


def main() -> int:
    root = tempfile.mkdtemp(prefix="mct_stream_smoke_")
    failures = []

    def check(ok, msg):
        print(("ok   " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    write_scannet_layout(
        make_scene(num_boxes=3, num_frames=8, image_hw=(48, 64), seed=7,
                   spacing=0.05), root, SCENE_ONE)
    scene_b = make_scene(num_boxes=3, num_frames=24, image_hw=(48, 64),
                         seed=11, spacing=0.05)
    write_scannet_layout(scene_b, root, SCENE_MULTI)
    scenes = [SCENE_ONE, SCENE_MULTI]

    # batch reference
    batch = cluster_scenes(_cfg(root, "smoke_batch"), scenes, resume=False)
    check(all(s.status == "ok" for s in batch),
          f"batch run ok ({[s.status for s in batch]})")
    batch_objects = {s.seq_name: s.num_objects for s in batch}

    # streaming run at chunk 8 (sanitizer armed for the whole drill)
    retrace_sanitizer.arm(True)
    retrace_sanitizer.install()
    stream = cluster_scenes(_cfg(root, "smoke_stream", streaming_chunk=CHUNK),
                            scenes, resume=False)
    check(all(s.status == "ok" for s in stream),
          f"streaming run ok ({[s.status for s in stream]})")
    stream_objects = {s.seq_name: s.num_objects for s in stream}

    # 1a. single-chunk convergence: byte-identical artifacts
    with open(_artifact(root, "smoke_batch", SCENE_ONE), "rb") as f:
        a = f.read()
    with open(_artifact(root, "smoke_stream", SCENE_ONE), "rb") as f:
        b = f.read()
    check(a == b, f"chunk>=F artifacts byte-identical ({len(a)} bytes)")
    # 1b. multi-chunk convergence digest: same instance count as batch
    check(stream_objects[SCENE_MULTI] == batch_objects[SCENE_MULTI],
          f"multi-chunk instance count {stream_objects[SCENE_MULTI]} == "
          f"batch {batch_objects[SCENE_MULTI]}")

    # 2. zero post-warm compiles across chunks 2..K: fresh stream, freeze
    # after chunk 1 (which compiles the stream's programs), then the
    # remaining chunks must dispatch entirely warm
    from maskclustering_tpu.models.pipeline import bucket_k_max
    from maskclustering_tpu.models.streaming import (StreamAccumulator,
                                                     slice_scene_frames)
    from maskclustering_tpu.utils.compile_cache import max_seg_id

    cfg = _cfg(root, "smoke_freeze", streaming_chunk=CHUNK)
    tensors = to_scene_tensors(scene_b)
    acc = StreamAccumulator(
        cfg, total_frames=tensors.num_frames,
        num_points=tensors.num_points,
        k_max=bucket_k_max(max_seg_id(tensors.segmentations)),
        seq_name="freeze-drill")
    acc.push_chunk(slice_scene_frames(tensors, 0, CHUNK))
    retrace_sanitizer.freeze()
    for ci in range(1, acc.n_chunks):
        acc.push_chunk(slice_scene_frames(
            tensors, ci * CHUNK, min((ci + 1) * CHUNK, tensors.num_frames)))
    digest = retrace_sanitizer.digest()
    post_freeze = [v for v in digest["violations"]
                   if v["kind"] == "post_freeze"]
    repeats = [v for v in digest["violations"] if v["kind"] == "repeat"]
    check(not post_freeze,
          f"zero post-warm compiles across chunks 2..{acc.n_chunks} "
          f"(violations: {post_freeze or 'none'})")
    check(not repeats, f"zero repeat compiles (violations: "
                       f"{repeats or 'none'})")
    retrace_sanitizer.thaw()

    # 3. residency: the largest chunk-plane materialization stays strictly
    # under the full-scene plane set the batch path keeps resident
    mx = obs.registry().snapshot()["gauges"].get("stream.max_plane_bytes")
    f_full, n_pad = scene_pads(cfg, tensors.num_frames, tensors.num_points)
    full_set = f_full * n_pad * (4 + 2 + 2 + 1) + n_pad
    check(mx is not None and mx < full_set,
          f"stream.max_plane_bytes {mx} < full-scene plane set {full_set}")

    print(f"stream_smoke: {'PASS' if not failures else 'FAIL'} "
          f"({len(failures)} failure(s))")
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
