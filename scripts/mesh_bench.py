"""Mesh-path benchmark on an 8-virtual-device CPU mesh -> MESH_BENCH.md.

Validates + measures the multi-chip fused path (parallel/batch.py) the same
way the driver's dryrun does — N virtual CPU devices standing in for a TPU
slice — but at real scale (>= 128k points/scene) and end-to-end through
post-process, recording s/scene and peak RSS (VERDICT r3 task 4).

Usage: python scripts/mesh_bench.py [--scenes 8] [--points 131072]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import os
import resource
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

import numpy as np  # noqa: E402


def peak_rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scenes", type=int, default=8)
    p.add_argument("--points", type=int, default=131072)
    p.add_argument("--frames", type=int, default=64)
    p.add_argument("--boxes", type=int, default=12)
    p.add_argument("--image-h", type=int, default=240)
    p.add_argument("--image-w", type=int, default=320)
    p.add_argument("--mesh", type=int, nargs=2, default=(2, 4),
                   metavar=("SCENE", "FRAME"))
    p.add_argument("--point-shards", type=int, default=1,
                   help="shard the point axis over this many chips (third "
                        "mesh axis; scene*frame*point must equal the "
                        "device count — e.g. --mesh 1 2 --point-shards 4). "
                        "Artifacts are byte-identical at any value; this "
                        "is the on-chip A/B knob chip_session.sh's "
                        "point_shard_ab step drives")
    p.add_argument("--platform", default="cpu", choices=("cpu", "tpu"),
                   help="cpu (default): 8 virtual host devices — the "
                        "orchestration harness; tpu: the real backend "
                        "(chip_session's on-chip point-shard A/B)")
    p.add_argument("--out", default="MESH_BENCH.md")
    args = p.parse_args()
    # the platform must be pinned through jax.config BEFORE backend init
    # (the environment may preload a TPU plugin — see tests/conftest.py);
    # nothing touches a device until jax.devices() below, so deciding it
    # here from the parsed flag covers both values
    jax.config.update("jax_platforms", args.platform)

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.parallel.batch import cluster_scene_batch, make_run_mesh
    from maskclustering_tpu.utils.synthetic import (make_scene,
                                                      resize_scene_points,
                                                      to_scene_tensors)

    devices = jax.devices()
    print(f"[mesh-bench] {len(devices)} virtual devices: {devices[0].platform}",
          file=sys.stderr, flush=True)

    cfg = PipelineConfig(
        config_name="mesh_bench", dataset="demo", backend=args.platform,
        distance_threshold=0.03, point_chunk=8192, frame_pad_multiple=8,
        mesh_shape=tuple(args.mesh), point_shards=args.point_shards,
    )
    mesh = make_run_mesh(cfg)

    t0 = time.time()
    tensors = []
    for i in range(args.scenes):
        s = make_scene(num_boxes=args.boxes, num_frames=args.frames,
                       image_hw=(args.image_h, args.image_w), spacing=0.02,
                       seed=i)
        t = to_scene_tensors(s)
        t.scene_points = resize_scene_points(t.scene_points, args.points,
                                             seed=i)
        tensors.append(t)
    gen_s = time.time() - t0
    print(f"[mesh-bench] {args.scenes} scenes generated in {gen_s:.1f}s",
          file=sys.stderr, flush=True)

    s_axis = tuple(args.mesh)[0]
    t0 = time.time()
    objs = cluster_scene_batch(cfg, mesh, tensors[:s_axis])
    warm_s = time.time() - t0
    print(f"[mesh-bench] warm-up batch ({s_axis} scenes, incl. compile): "
          f"{warm_s:.1f}s", file=sys.stderr, flush=True)

    t0 = time.time()
    objs = cluster_scene_batch(cfg, mesh, tensors)
    run_s = time.time() - t0
    per_scene = run_s / args.scenes
    rss = peak_rss_gb()
    counts = [len(o.point_ids_list) for o in objs]
    print(f"[mesh-bench] {args.scenes} scenes in {run_s:.1f}s "
          f"({per_scene:.2f} s/scene), objects {counts}, peak RSS {rss:.2f} GB",
          file=sys.stderr, flush=True)

    # the generated record must say where its numbers came from: the CPU
    # harness measures orchestration, a --platform tpu run (chip_session's
    # point_shard_ab) is the real wall-clock — mislabeling either poisons
    # the A/B archive
    dev_desc = (f"{len(devices)} virtual CPU devices"
                if args.platform == "cpu"
                else f"{len(devices)} {devices[0].platform} device(s)")
    notes = (
        "Notes: virtual CPU devices measure the orchestration + sharding "
        "path\n(compile-correctness, padding invariants, per-scene "
        "artifact fan-out), not\nTPU arithmetic; absolute s/scene on CPU "
        "is not comparable to the\nsingle-chip TPU bench (bench.py)."
        if args.platform == "cpu" else
        "Notes: LIVE-backend run (--platform tpu) — these are real "
        "accelerator\nwall-clock numbers, comparable across meshes within "
        "this session.")
    with open(args.out, "w") as f:
        f.write(f"""# Mesh-path benchmark ({dev_desc})

Fused multi-chip pipeline (`parallel/batch.cluster_scene_batch`) over a
`(scene={args.mesh[0]}, frame={args.mesh[1]}, point={args.point_shards})`
mesh of {dev_desc} — the same code path the driver dry-runs, at real
scale and end-to-end through device post-process + host DBSCAN/merge.

| quantity | value |
|---|---|
| scenes | {args.scenes} ({args.boxes} objects, {args.frames} frames, {args.image_h}x{args.image_w}, {args.points // 1024}k pts each) |
| mesh | scene={args.mesh[0]} x frame={args.mesh[1]} x point={args.point_shards} ({dev_desc}) |
| warm-up batch (incl. compile) | {warm_s:.1f} s |
| full run | {run_s:.1f} s |
| **s/scene** | **{per_scene:.2f}** |
| peak RSS | {rss:.2f} GB |
| objects recovered | {counts} |

{notes} Generated by `scripts/mesh_bench.py`.
""")
    print(f"[mesh-bench] wrote {args.out}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
