"""Measure (not extrapolate) the ScanNet-val north star -> NORTHSTAR.md.

VERDICT r4 task 2: the <10 min / 311 scenes / v5e-8 target had only ever
been projected from a single-bucket bench. This pushes a multi-scene,
multi-bucket synthetic sweep with a realistic ScanNet-val-like spread of
frame counts / cloud sizes / object counts through ``run_scene`` on the
live chip in ONE process with the persistent compile cache, and records:

- distinct (k_max, F_pad, N_pad) shape buckets hit (compile-unit count);
- per-bucket warm-up (first scene in bucket) vs steady-state s/scene;
- scenes/hour, total and steady-state;
- the v5e-8 311-scene projection with the scene-DP factor, pass/fail.

The reference's cost at this stage: 6.5 GPU-h / 311 scenes (README.md:205)
~= 75 s/scene on an RTX 3090; its per-GPU process model is the same
scene-DP shape this projection uses (reference run.py:33-50).

Usage: python scripts/northstar.py [--quick] [--out NORTHSTAR.md]
(the script puts the repo root on sys.path itself; do NOT override
PYTHONPATH — on this rig it carries the TPU plugin's site dir, and
replacing it leaves JAX_PLATFORMS=axon pointing at an unregistered
backend)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BASELINE_S_PER_SCENE = 75.0
NORTH_STAR_SCENES = 311
NORTH_STAR_CHIPS = 8
NORTH_STAR_MINUTES = 10.0

# Realistic ScanNet-val-like spread (stride-10 frame counts cluster around
# 100-350; clouds 80k-400k points; CropFormer ~20-40 masks/frame). True
# sizes deliberately differ WITHIN a bucket to prove bucket reuse.
SCENE_SPECS = [
    # (frames, points, boxes) -> bucket (f_pad, n_pad) via geometric rounding
    (118, 98304, 16), (125, 90000, 16), (128, 98304, 20),
    (170, 150000, 24), (180, 163840, 24), (190, 160000, 28),
    (245, 190000, 36), (250, 196608, 36), (255, 196608, 32),
    (310, 280000, 36), (320, 294912, 36), (350, 290000, 36),
]
QUICK_SPECS = [(8, 4096, 3), (9, 4096, 3), (14, 6000, 4), (15, 6144, 4)]


def _init_backend(platform, timeout_s=120.0):
    from maskclustering_tpu.utils.backend_init import init_backend

    init_backend(platform, timeout_s=timeout_s, tag="northstar")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes on CPU (script smoke test)")
    p.add_argument("--platform", default=None)
    p.add_argument("--image-h", type=int, default=480)
    p.add_argument("--image-w", type=int, default=640)
    p.add_argument("--out", default="NORTHSTAR.md")
    args = p.parse_args()

    specs = QUICK_SPECS if args.quick else SCENE_SPECS
    if args.quick and args.platform is None:
        args.platform = "cpu"
    if args.quick:
        args.image_h, args.image_w = 60, 80

    _init_backend(args.platform)

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.pipeline import bucket_size, run_scene
    from maskclustering_tpu.utils.compile_cache import (seen_shape_buckets,
                                                        setup_compilation_cache)
    from maskclustering_tpu.utils.synthetic import (make_scene_device,
                                                    resize_scene_points)

    cache = setup_compilation_cache()
    print(f"[northstar] persistent compile cache: {cache}",
          file=sys.stderr, flush=True)

    cfg = PipelineConfig(config_name="northstar", dataset="demo",
                         distance_threshold=0.01, few_points_threshold=25,
                         point_chunk=8192)

    t_sweep0 = time.time()
    rows = []  # (scene_idx, frames, points, boxes, bucket, gen_s, run_s, objects)
    bucket_first: dict = {}
    truncated = False
    # per-scene flush: an external kill (timeout(1), driver, Ctrl-C) during
    # a chip wedge must not lose the scenes already measured — the sweep's
    # exception handler can't see a hang that never raises
    partial_path = args.out + ".partial.jsonl"
    with open(partial_path, "w"):
        pass
    for i, (frames, points, boxes) in enumerate(specs):
        # the whole body touches the accelerator (make_scene_device renders
        # frames with a jitted ray tracer): a mid-sweep chip stall anywhere
        # must not lose the scenes already measured
        try:
            t0 = time.time()
            tensors, _, _ = make_scene_device(
                num_boxes=boxes, num_frames=frames,
                image_hw=(args.image_h, args.image_w),
                spacing=0.025 if not args.quick else 0.08, seed=i)
            tensors.scene_points = resize_scene_points(
                tensors.scene_points, points, seed=i)
            gen_s = time.time() - t0

            bucket = (bucket_size(frames, cfg.frame_pad_multiple),
                      bucket_size(points, cfg.point_chunk))
            pre_buckets = seen_shape_buckets()
            t0 = time.time()
            result = run_scene(tensors, cfg, k_max=None if args.quick else 63)
            # a scene pays compile when it lands ANY new jit shape bucket —
            # the (F_pad, N_pad) scene bucket or the M_pad mask bucket
            new_buckets = seen_shape_buckets() - pre_buckets
            first = bool(new_buckets)
        except Exception as e:  # noqa: BLE001
            detail = str(e).splitlines()[0][:200] if str(e) else repr(e)
            print(f"[northstar] scene {i} FAILED ({type(e).__name__}: "
                  f"{detail}); writing partial results",
                  file=sys.stderr, flush=True)
            truncated = True
            break
        run_s = time.time() - t0
        if first:
            bucket_first[tuple(sorted(new_buckets))] = run_s
        n_obj = len(result.objects.point_ids_list)
        rows.append((i, frames, points, boxes, bucket, gen_s, run_s, n_obj, first))
        with open(partial_path, "a") as f:
            f.write(json.dumps({
                "scene": i, "frames": frames, "points": points,
                "objects": boxes, "bucket": list(bucket),
                "gen_s": round(gen_s, 2), "run_s": round(run_s, 2),
                "found": n_obj, "warm": first,
                "new_buckets": sorted(map(list, new_buckets))}) + "\n")
            f.flush()
        print(f"[northstar] scene {i}: F={frames} N={points} obj={boxes} "
              f"bucket={bucket}"
              + (f" WARM (new jit buckets: {sorted(new_buckets)})" if first
                 else "")
              + f" gen={gen_s:.1f}s run={run_s:.2f}s objects={n_obj}",
              file=sys.stderr, flush=True)
    sweep_s = time.time() - t_sweep0
    if not rows:
        print(json.dumps({"error": "no scene completed", "pass": False}))
        sys.exit(2)

    buckets = sorted({r[4] for r in rows})
    steady = [r[6] for r in rows if not r[8]]
    steady_median = float(np.median(steady)) if steady else float("nan")
    warm_total = float(sum(bucket_first.values()))
    compute_s = float(sum(r[6] for r in rows))
    scenes_per_hour_total = len(rows) / (sweep_s / 3600.0)
    scenes_per_hour_compute = len(rows) / (compute_s / 3600.0)

    # v5e-8 projection, scene-DP (the reference's own parallel shape):
    # each chip warm-compiles its buckets once (persistent cache makes this
    # a first-run-only cost) then streams 311/8 scenes at steady state.
    proj_s = warm_total + (NORTH_STAR_SCENES / NORTH_STAR_CHIPS) * steady_median
    proj_warm_cached = (NORTH_STAR_SCENES / NORTH_STAR_CHIPS) * steady_median
    ok = proj_s / 60.0 < NORTH_STAR_MINUTES and not truncated
    ok_cached = proj_warm_cached / 60.0 < NORTH_STAR_MINUTES

    lines = [
        "# NORTHSTAR — measured multi-scene, multi-bucket sweep",
        "",
        f"{len(rows)} synthetic scenes with a ScanNet-val-like spread, one",
        "process, persistent compile cache, on "
        + ("CPU (--quick smoke)" if args.quick else "the live TPU chip")
        + f" ({args.image_h}x{args.image_w} frames, radius 0.01).",
        "Generated by `scripts/northstar.py`; reference cost at this stage:",
        "75 s/scene (6.5 GPU-h / 311 scenes, reference README.md:205).",
        "",
        "## Per-scene measurements",
        ""] + ([f"**TRUNCATED SWEEP**: only {len(rows)}/{len(specs)} scenes "
                "completed before a failure (see run log); verdict is FAIL "
                "by construction.", ""] if truncated else []) + [
        "| scene | frames | points | objects | bucket (F_pad, N_pad) | warm? | run (s) |",
        "|---|---|---|---|---|---|---|",
    ]
    for i, frames, points, boxes, bucket, gen_s, run_s, n_obj, first in rows:
        warm = "compile" if first else ""
        lines.append(f"| {i} | {frames} | {points} | {n_obj}/{boxes} | "
                     f"{bucket} | {warm} | {run_s:.2f} |")
    lines += [
        "",
        "## Aggregates",
        "",
        f"- distinct (F_pad, N_pad) scene buckets hit: **{len(buckets)}** "
        f"({buckets})",
        f"- all jit shape buckets (incl. M_pad mask buckets): "
        f"{sorted(seen_shape_buckets())}",
        f"- per-compile-event warm-up (scene that landed new buckets): "
        + ", ".join(f"{list(b)}: {v:.1f}s"
                    for b, v in bucket_first.items()),
        f"- warm-up total: **{warm_total:.1f} s** (persistent cache makes "
        "this a first-run-only cost per host)",
        f"- steady-state s/scene (median of {len(steady)} non-warm scenes): "
        f"**{steady_median:.2f} s** (vs reference 75 s/scene -> "
        f"**{BASELINE_S_PER_SCENE / steady_median:.1f}x**)",
        f"- sweep wall time: {sweep_s / 60.0:.1f} min "
        f"({scenes_per_hour_total:.0f} scenes/hour incl. synthetic scene "
        f"generation; {scenes_per_hour_compute:.0f} scenes/hour counting "
        "pipeline compute only — real runs overlap IO via the prefetcher)",
        "",
        "## 311-scene v5e-8 projection (scene data parallelism)",
        "",
        f"- cold cache: {warm_total:.0f} s warm-up + 311/8 x "
        f"{steady_median:.2f} s = **{proj_s / 60.0:.1f} min** -> "
        f"{'PASS' if ok else 'FAIL'} vs < {NORTH_STAR_MINUTES:.0f} min",
        f"- warm persistent cache (steady only): **{proj_warm_cached / 60.0:.1f} "
        f"min** -> {'PASS' if ok_cached else 'FAIL'}",
        "",
        "Scene-DP is the reference's own scaling shape (one scene stream per",
        "accelerator, reference run.py:33-50); no cross-chip communication is",
        "on the critical path, so the /8 factor is exact up to bucket-warmup",
        "skew (each chip compiles only the buckets its scenes hit, and the",
        "persistent cache de-duplicates across chips sharing a host).",
        "",
    ]
    out_text = "\n".join(lines)
    with open(args.out, "w") as f:
        f.write(out_text)
    print(out_text)
    print(json.dumps({
        "buckets": len(buckets),
        "jit_buckets": len(seen_shape_buckets()),
        "warm_total_s": round(warm_total, 1),
        "steady_median_s": round(steady_median, 3),
        "proj_cold_min": round(proj_s / 60.0, 2),
        "proj_warm_min": round(proj_warm_cached / 60.0, 2),
        "pass": bool(ok),
        "scenes_completed": len(rows),
        "truncated": bool(truncated),
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
