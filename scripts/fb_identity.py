"""On-chip --frame-batch identity check (chip_session step).

The ``association_frame_batch`` knob is pinned byte-identical by a
CPU-backend test only (tests/test_backprojection.py
test_frame_batch_matches_sequential); on TPU the batched path also flips
``full_tile_table`` to the strip table, and cross-backend byte-identity of
the float distance compares has never been measured on a live chip
(ADVICE round 5). This runs the same A/B on whatever backend is live and
prints one verdict line:

    python scripts/fb_identity.py [--frame-batch 8] [--platform cpu]

Exit 0 = byte-identical, 1 = mismatch (with the first differing field),
2 = backend init failed.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=24)
    p.add_argument("--points", type=int, default=32768)
    p.add_argument("--boxes", type=int, default=6)
    p.add_argument("--frame-batch", type=int, default=8)
    p.add_argument("--k-max", type=int, default=63)
    p.add_argument("--distance-threshold", type=float, default=0.01)
    p.add_argument("--spacing", type=float, default=0.025)
    p.add_argument("--init-timeout", type=float, default=120.0)
    p.add_argument("--platform", default=None)
    args = p.parse_args()

    from maskclustering_tpu.utils.backend_init import init_backend

    try:
        init_backend(args.platform, timeout_s=args.init_timeout,
                     tag="fb_identity")
    except Exception as e:  # noqa: BLE001 — one-line verdict contract
        print(f"[fb_identity] FAIL: backend init: {e}", flush=True)
        return 2

    import jax
    import numpy as np

    from maskclustering_tpu.models.backprojection import associate_scene
    from maskclustering_tpu.utils.synthetic import (make_scene_device,
                                                    resize_scene_points)

    tensors, _, _ = make_scene_device(
        num_boxes=args.boxes, num_frames=args.frames,
        image_hw=(96, 128), spacing=args.spacing, seed=3)
    tensors.scene_points = resize_scene_points(tensors.scene_points,
                                               args.points)
    a = (np.asarray(tensors.scene_points), tensors.depths,
         tensors.segmentations, np.asarray(tensors.intrinsics),
         np.asarray(tensors.cam_to_world), np.asarray(tensors.frame_valid))
    kw = dict(k_max=args.k_max, window=1,
              distance_threshold=args.distance_threshold,
              few_points_threshold=25, coverage_threshold=0.3)
    seq = associate_scene(*a, frame_batch=1, **kw)
    bat = associate_scene(*a, frame_batch=args.frame_batch, **kw)
    for field in type(seq)._fields:
        got = np.asarray(getattr(bat, field))
        want = np.asarray(getattr(seq, field))
        if not np.array_equal(got, want):
            ndiff = int((got != want).sum())
            print(f"[fb_identity] FAIL on {jax.default_backend()}: "
                  f"{field} differs in {ndiff} cells at "
                  f"frame_batch={args.frame_batch}", flush=True)
            return 1
    print(f"[fb_identity] OK: frame_batch={args.frame_batch} byte-identical "
          f"to sequential on backend={jax.default_backend()} "
          f"(F={args.frames}, N={args.points}, boxes={args.boxes})",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
