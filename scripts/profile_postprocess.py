"""Profile postprocess_scene at bench scale (host-side; device platform irrelevant).

Run from the repo root:  JAX_PLATFORMS=cpu python scripts/profile_postprocess.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import sys
import time

from maskclustering_tpu.config import PipelineConfig
from maskclustering_tpu.utils.synthetic import (make_scene,
                                                  resize_scene_points,
                                                  to_scene_tensors)


def main():
    frames, points, boxes, k_max = 150, 196608, 12, 63
    t0 = time.time()
    scene = make_scene(num_boxes=boxes, num_frames=frames, image_hw=(240, 320),
                       spacing=0.02, seed=0)
    tensors = to_scene_tensors(scene)
    tensors.scene_points = resize_scene_points(tensors.scene_points, points)
    print(f"scene ready {time.time()-t0:.1f}s", file=sys.stderr)

    cfg = PipelineConfig(config_name="bench", dataset="demo",
                         distance_threshold=0.03, few_points_threshold=25,
                         point_chunk=8192)

    from maskclustering_tpu.models.pipeline import run_scene

    for i in range(3):
        t0 = time.time()
        result = run_scene(tensors, cfg, k_max=k_max)
        print(f"run {i}: {time.time()-t0:.2f}s  "
              f"{ {k: round(v, 2) for k, v in result.timings.items()} }",
              file=sys.stderr)


if __name__ == "__main__":
    main()
