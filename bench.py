"""End-of-round benchmark: per-scene mask-clustering wall time on one chip.

Measures the full per-scene pipeline (projective association -> mask-graph
stats -> iterative clustering -> post-process) at the REAL ScanNet operating
point: 480x640 depth frames, 250 frames (stride-10 of a ~2.5k-frame scan),
~192k scene points, 36 objects (~36 masks/frame, ~9k masks/scene), radius
0.01 — the reference's constants (utils/mask_backprojection.py:8-14,
configs/scannet.json). The reference's published cost for this stage is
6.5 GPU-h for 311 ScanNet-val scenes on an RTX 3090 ~= 75 s/scene
(reference README.md:205); vs_baseline = 75 / measured_s_per_scene.

Depth/seg frames are rendered by a jitted ray tracer directly in HBM: on a
TPU-VM the real pipeline's host->device feed overlaps compute trivially
(~300 MB/scene over PCIe), but this driver reaches the chip through a
~40 MB/s tunnel that would add ~8 s/scene of pure rig artifact.

Prints exactly ONE JSON line on stdout — even on failure or partial runs
(value = median of whatever repeats completed, or null with an "error" key).

Chip-contention hardening: a wedged/busy TPU makes backend init hang with no
exception, and a hung client can only be abandoned by killing the process.
So the default entrypoint is a thin PARENT that runs the real bench as a
fresh subprocess (--worker) and, when the worker dies in backend init
(exit 2/3) OR hangs after init without ever emitting its JSON line (the
wedge can also land between a fast init and the first device op), retries
with a new process and exponential backoff — up to
--init-attempts tries within a --retry-budget wall-clock budget. Exactly one
JSON line still reaches stdout: the parent swallows failed workers' lines and
forwards only the final one, annotated with "attempts". (BENCH_r04 was lost
to a single 120 s init timeout; this makes that unrepeatable.)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

BASELINE_S_PER_SCENE = 75.0  # reference: 6.5 GPU-h / 311 ScanNet-val scenes

# worker exit codes that mean "backend never came up" (safe to retry fresh)
_INIT_FAILED_RCS = (2, 3)
# worker stdout line proving backend init completed (supervisor-internal)
_INIT_OK_SENTINEL = "[bench-worker] INIT_OK"


def _retry_policy(args):
    """The supervisor's backoff schedule, on the SHARED retry primitive.

    utils/faults.RetryPolicy (stdlib-only: safe in this chip-free process)
    with the historical linear shape — min(20s * attempt, 120s) — and the
    MCT_BENCH_BACKOFF_SCALE test knob (malformed values fall back to 1.0,
    never negative, so a bad knob cannot break the one-JSON-line contract
    mid-supervision). run.py's scene supervisor uses the same class with
    the exponential style; one copy of the backoff semantics.
    """
    from maskclustering_tpu.utils.faults import RetryPolicy

    return RetryPolicy(attempts=max(args.init_attempts, 1), base_s=20.0,
                       cap_s=120.0, style="linear",
                       scale_env="MCT_BENCH_BACKOFF_SCALE")


def _metric_name(args) -> str:
    return (f"mask-clustering s/scene (synthetic scene: {args.frames}fr x "
            f"{args.points // 1024}k pts x {args.boxes} objects)")


def _emit(args, times, error=None, stage_timings=None):
    import numpy as np

    if times:
        s_per_scene = float(np.median(times))
        line = {
            "metric": _metric_name(args),
            "value": round(s_per_scene, 3),
            "unit": "s/scene",
            "vs_baseline": round(BASELINE_S_PER_SCENE / s_per_scene, 2),
            # per-run times + spread: the run-to-run stability criterion
            # (three consecutive runs within +-15%) lands in the driver's
            # BENCH json without extra artifacts
            "runs": [round(float(t), 3) for t in times],
            "spread_pct": round(
                100.0 * (max(times) - min(times)) / s_per_scene, 1),
        }
        if stage_timings:
            # median per stage across completed repeats: puts the breakdown
            # on record in the driver's BENCH json without extra artifacts
            keys = sorted({k for t in stage_timings for k in t})
            line["stages"] = {k: round(float(np.median(
                [t.get(k, 0.0) for t in stage_timings])), 3) for k in keys}
    else:
        line = {"metric": _metric_name(args), "value": None, "unit": "s/scene",
                "vs_baseline": None}
    if getattr(args, "frame_batch", 1) != 1:
        # attribute A/B records to their knob setting; the default record's
        # shape stays unchanged for the driver
        line["frame_batch"] = args.frame_batch
    # dtype attribution (always recorded): perf deltas across rows must be
    # assignable to a count_dtype flip vs code drift, and plane_dtype marks
    # the int16 claim-plane layout era in the trajectory
    line["count_dtype"] = getattr(args, "count_dtype", "bf16")
    line["plane_dtype"] = "int16"
    # same for the post-process path: the --host-postprocess A/B knob must
    # be attributable in the trajectory (obs.report --regress flags flips)
    line["postprocess_path"] = (
        "host" if getattr(args, "host_postprocess", False) else "device")
    # point-shard attribution: bench.py itself is the single-chip harness
    # (point_shards lives on the fused mesh path — scripts/mesh_bench.py
    # carries the knob), so the stamp records the era's unsharded baseline
    # the same way plane_dtype does; mesh rows stamp their true count
    line["point_shards"] = 1
    if getattr(args, "obs_events", None) and not getattr(args, "no_obs", False):
        # point the record at its own span stream (report CLI renders it)
        line["obs_events"] = args.obs_events
    from maskclustering_tpu.analysis import retrace_sanitizer

    if retrace_sanitizer.enabled():
        # compile-surface attribution (armed runs only): the warm-up wall
        # and any post-freeze retrace ride the verdict so obs.report
        # --regress can attribute a compile-count delta before blaming
        # code drift for the headline
        d = retrace_sanitizer.digest()
        line["retrace_compiles"] = d["compiles"]
        repeats = sum(1 for v in d["violations"] if v["kind"] == "repeat")
        frozen = sum(1 for v in d["violations"]
                     if v["kind"] == "post_freeze")
        if repeats:
            line["retrace_repeats"] = repeats
        if frozen:
            line["retrace_post_freeze"] = frozen
    if error is not None:
        line["error"] = str(error)[:300]
        if times:
            line["partial"] = True
    print(json.dumps(line))
    sys.stdout.flush()
    if not os.environ.get("MCT_BENCH_SUPERVISED"):
        # direct --worker invocations own their verdict; under supervision
        # the parent appends the FINAL line instead (a retried worker's
        # failed line must not pollute the trajectory)
        _ledger_append(args, line)


def _ledger_append(args, line, fast=False):
    """One perf-ledger row per bench verdict (schema-versioned, crash-safe).

    Never endangers the one-JSON-line stdout contract: failures print a
    stderr warning and move on. ``fast=True`` (the signal-handler path)
    skips the git-rev subprocess — a handler must not block up to 10 s on
    a hung filesystem before os._exit while a supervisor escalates to
    SIGKILL.
    """
    if getattr(args, "no_ledger", False):
        return
    try:
        from maskclustering_tpu.obs import ledger as led

        path = getattr(args, "ledger", None) or led.default_ledger_path()
        row = led.bench_row(line)
        if fast:
            row["git"] = None  # presence of the key skips _git_rev
        led.append_row(path, row)
    except Exception as e:  # noqa: BLE001 — the ledger must never sink the bench
        print(f"[bench] WARNING: perf ledger append failed: {e}",
              file=sys.stderr, flush=True)


def _init_backend(args):
    """Initialize the JAX backend, failing fast and loudly.

    Shared watchdog logic lives in maskclustering_tpu.utils.backend_init;
    this wrapper adds the bench's JSON-line contract on every failure path.
    """
    from maskclustering_tpu.utils.backend_init import init_backend

    try:
        devices = init_backend(
            args.platform, timeout_s=args.init_timeout, tag="bench",
            on_timeout=lambda: _emit(
                args, [], error=f"backend init timed out after "
                                f"{args.init_timeout}s"))
    except Exception as e:  # noqa: BLE001 — one-line diagnosis beats a 30-frame traceback
        print(f"[bench] FATAL: jax backend init failed: {type(e).__name__}: "
              f"{str(e).splitlines()[0] if str(e) else e}", file=sys.stderr, flush=True)
        _emit(args, [], error=f"backend init failed: {e}")
        # ImportError can never heal across retries; rc 4 tells the
        # supervisor to fail fast instead of burning the retry budget.
        sys.exit(4 if isinstance(e, ImportError) else 2)
    # stdout sentinel for the supervisor: proves init completed even if the
    # worker later dies by signal with no JSON line. Gated on the env var the
    # supervisor sets, so a direct --worker invocation keeps the documented
    # one-JSON-line stdout contract.
    if os.environ.get("MCT_BENCH_SUPERVISED"):
        print(_INIT_OK_SENTINEL, flush=True)
    hang_flag = os.environ.get("MCT_BENCH_TEST_HANG_AFTER_INIT")
    if hang_flag and not os.path.exists(hang_flag):
        # test knob: simulate the observed wedge mode where init answers in
        # seconds and the first device op then stalls indefinitely. The
        # value is a flag-file path so only the FIRST worker hangs — the
        # retry then proceeds, mirroring a wedge that cleared.
        with open(hang_flag, "w"):
            pass
        while True:
            time.sleep(3600)
    return devices


def _validate_pallas_on_tpu():
    """Mosaic-lower the ball-query kernel on the live chip (non-interpret).

    Every CI test runs interpret=True on CPU; this is the hook that catches
    a lowering regression the first time a real TPU is available.
    """
    import jax

    if jax.default_backend() != "tpu":
        return
    import jax.numpy as jnp
    import numpy as np

    from maskclustering_tpu.ops.neighbor import ball_query
    from maskclustering_tpu.ops.pallas.ball_query import ball_query_pallas

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.random((2, 200, 3)), jnp.float32)
    c = jnp.asarray(rng.random((2, 500, 3)), jnp.float32)
    ql = jnp.asarray([200, 150], jnp.int32)
    cl = jnp.asarray([500, 333], jnp.int32)
    try:
        got = np.asarray(ball_query_pallas(q, c, ql, cl, k=8, radius=0.1,
                                           interpret=False))
        want = np.asarray(ball_query(q, c, ql, cl, k=8, radius=0.1))
        ok = bool((got == want).all())
        print(f"[bench] pallas ball_query non-interpret: "
              f"{'OK' if ok else 'MISMATCH vs jnp path'}",
              file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — validation must not sink the bench
        print(f"[bench] pallas ball_query non-interpret FAILED: "
              f"{type(e).__name__}: {str(e).splitlines()[0] if str(e) else e}",
              file=sys.stderr, flush=True)


def _build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=250)
    p.add_argument("--points", type=int, default=196608)  # 192k, ScanNet-ish
    p.add_argument("--boxes", type=int, default=36)  # ~36 masks/frame
    p.add_argument("--image-h", type=int, default=480)  # ScanNet depth size
    p.add_argument("--image-w", type=int, default=640)
    p.add_argument("--spacing", type=float, default=0.025)  # cloud density (m)
    p.add_argument("--distance-threshold", type=float, default=0.01)  # ref radius
    # 5 so the median absorbs the chip's degraded first dispatch streams
    # after a tunnel recovery (observed 19/9/4.5 s settle, PROFILE.md
    # round-5 recovery) — with 3 repeats one bad run skews the median
    p.add_argument("--repeats", type=int, default=5)
    p.add_argument("--k-max", type=int, default=63)
    p.add_argument("--init-timeout", type=float, default=120.0)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu) before backend init")
    p.add_argument("--worker", action="store_true",
                   help="run the bench in-process (no retry supervisor)")
    p.add_argument("--init-attempts", type=int, default=8,
                   help="max fresh-subprocess attempts when backend init fails")
    p.add_argument("--retry-budget", type=float, default=1500.0,
                   help="total wall-clock budget (s) across init retries")
    p.add_argument("--worker-timeout", type=float, default=900.0,
                   help="post-init run allowance (s) before the supervisor "
                        "kills a worker outright (GIL-proof hang backstop); "
                        "worst legitimate cold run is ~250s, so 900 leaves "
                        "budget for a fresh attempt after a post-init wedge")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the timed repeats")
    def _positive_int(v):
        iv = int(v)
        if iv < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return iv

    # validated at parse time: a bad value must fail BEFORE backend init
    # burns minutes of a chip recovery window (PipelineConfig would only
    # reject it after init + scene render, outside the JSON-line guard)
    # choices mirror ops/counting.COUNT_DTYPES as a LITERAL: the parser is
    # built before backend init, and importing the counting module here
    # would pull jax into the supervisor process pre-watchdog (the one
    # import this file defers everywhere). config.py still validates the
    # value against the canonical tuple, so drift fails loudly there.
    p.add_argument("--count-dtype", default="bf16", choices=("bf16", "int8"),
                   help="operand encoding of the counting contractions "
                        "(ops/counting.py): int8 rides the MXU's s8 path "
                        "with half the operand bytes; artifacts are byte-"
                        "identical either way (the chip A/B decides the "
                        "default)")
    p.add_argument("--host-postprocess", action="store_true",
                   help="A/B knob: run the host numpy post-process "
                        "(device_postprocess=False) instead of the "
                        "device-resident split/merge kernels with the "
                        "emit-only drain. Artifacts are byte-identical "
                        "either way (tests/test_postprocess_device.py); "
                        "the verdict line and ledger row stamp "
                        "postprocess_path so --regress attributes the "
                        "flip, not code drift")
    p.add_argument("--frame-batch", type=_positive_int, default=1,
                   help="association_frame_batch (frames vectorized per "
                        "association-scan step; A/B knob. Results are "
                        "byte-identical at any value on the CPU backend "
                        "(pinned by tests/test_backprojection.py); on TPU "
                        "the batched path also switches tile tables, so "
                        "verify once on chip via chip_session's fb_identity "
                        "step)")
    p.add_argument("--obs-events", default=None,
                   help="arm obs span/metrics capture to this JSONL path "
                        "(default: off in bench mode so honest-shape "
                        "numbers carry zero instrumentation cost); render "
                        "with python -m maskclustering_tpu.obs.report")
    p.add_argument("--no-obs", action="store_true",
                   help="force obs capture off even if --obs-events is set")
    p.add_argument("--ledger", default=None,
                   help="perf ledger JSONL the verdict appends to (default: "
                        "PERF_LEDGER.jsonl / $MCT_PERF_LEDGER; render with "
                        "obs.report --history)")
    p.add_argument("--no-ledger", action="store_true",
                   help="do not append this verdict to the perf ledger")
    p.add_argument("--xprof", default=None, metavar="SPANS",
                   help="comma-joined span names to bracket with a "
                        "jax.profiler trace (needs --obs-events; e.g. "
                        "cluster,post.claims.kernel; * = every span)")
    p.add_argument("--xprof-dir", default=None,
                   help="trace output dir for --xprof (default: next to "
                        "--obs-events)")
    return p


def _supervise(args):
    """Run the bench as fresh --worker subprocesses until one delivers.

    Retries the chip-wedge classes only: init-phase deaths (exit 2/3, or a
    signal death before the INIT_OK sentinel) and a post-init hang that
    never emitted a JSON line (init can answer in seconds and the first
    device op still stall). A worker that emitted its JSON line — success,
    partial, or in-run error — owns the verdict. Worker stderr streams
    through; worker stdout (the JSON line) is captured so exactly one line
    reaches our stdout.
    """
    child_argv = [sys.executable, os.path.abspath(__file__), "--worker"]
    child_argv += [a for a in sys.argv[1:] if a != "--worker"]
    policy = _retry_policy(args)
    t_start = time.time()
    # single source of truth for BOTH emission paths (the loop tail and the
    # signal handler): shadow locals desynchronize them
    state = {"last_line": None, "attempt": 0, "rc": 3, "proc": None,
             "out": [], "emitted": False}

    def _final_line(kill_msg=None):
        """The one JSON line. ``kill_msg`` (signal path) is attributed
        carefully: a WORKER-emitted error record keeps its own error field
        (the kill is not that verdict's story), while the synthetic
        no-JSON-line fallback and an error-less null verdict take the kill
        message — there the kill IS the story."""
        last = state["last_line"]
        if last is None and state["out"]:
            # verdict emitted by the CURRENT attempt's worker but not yet
            # promoted (it still sat in the drain buffer when a signal hit)
            last = state["out"][-1]
        try:
            line = json.loads(last)
            if not isinstance(line, dict):
                raise ValueError("not a JSON object")
            if kill_msg and line.get("value") is None and "error" not in line:
                line["error"] = kill_msg
        except (TypeError, ValueError):
            no_line = f"worker produced no JSON line (rc={state['rc']})"
            line = {"metric": _metric_name(args), "value": None,
                    "unit": "s/scene", "vs_baseline": None,
                    "error": f"{kill_msg}; {no_line}" if kill_msg else no_line}
        line["attempts"] = state["attempt"]
        if args.frame_batch != 1 and "frame_batch" not in line:
            # the fallback record must stay attributable to its A/B setting
            line["frame_batch"] = args.frame_batch
        # same for the dtype knobs: a synthetic fallback line must carry
        # the A/B attribution the worker would have stamped
        line.setdefault("count_dtype", args.count_dtype)
        line.setdefault("plane_dtype", "int16")
        line.setdefault("postprocess_path",
                        "host" if args.host_postprocess else "device")
        line.setdefault("point_shards", 1)
        return line

    def _on_term(signum, frame):
        # An external kill (driver timeout) mid-retry must still leave one
        # JSON line on stdout — otherwise a long retry loop degrades the
        # round's record from value=null to NOTHING. SIGKILL is the only
        # unrecoverable case.
        if state["emitted"]:
            os._exit(3)  # the one line is already out; never print a second
        state["emitted"] = True
        proc = state["proc"]
        if proc is not None and proc.poll() is None:
            proc.kill()
        line = _final_line(kill_msg=f"supervisor killed by signal {signum}")
        print(json.dumps(line))
        sys.stdout.flush()
        _ledger_append(args, line, fast=True)
        # mirror the tail's exit contract: only a CLEAN preserved verdict
        # (value non-null, no error) is a pass for set -e shell callers —
        # a partial/errored record exits nonzero from the tail path too
        os._exit(0 if (line.get("value") is not None
                       and "error" not in line) else 3)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    for attempt in range(1, max(args.init_attempts, 1) + 1):
        state["attempt"] = attempt
        elapsed = time.time() - t_start
        if attempt > 1 and elapsed >= args.retry_budget:
            print(f"[bench] budget exhausted before attempt {attempt} "
                  f"({elapsed:.0f}s >= {args.retry_budget:.0f}s)",
                  file=sys.stderr, flush=True)
            state["attempt"] = attempt - 1  # this attempt never launched
            break
        print(f"[bench] attempt {attempt}/{args.init_attempts} "
              f"(elapsed {elapsed:.0f}s of {args.retry_budget:.0f}s budget)",
              file=sys.stderr, flush=True)
        env = dict(os.environ, MCT_BENCH_SUPERVISED="1")
        # Phase-aware hard caps, GIL-proof: the worker's own init watchdog is
        # a Python thread and cannot fire if native backend init wedges while
        # holding the GIL — only the parent can kill that. Worker stdout is
        # streamed so the INIT_OK sentinel flips the deadline from the short
        # init cap (init_timeout + grace; keeps a wedged init retryable
        # within the budget) to the long run allowance (worker_timeout).
        proc = subprocess.Popen(child_argv, stdout=subprocess.PIPE, env=env)
        state["proc"] = proc
        out = state["out"] = []  # handler-visible: a signal mid-attempt must
        # not drop a verdict still sitting in the drain buffer
        init_ok_evt = threading.Event()

        def _drain(stream=proc.stdout):
            for raw_line in stream:
                ln = raw_line.decode("utf-8", "replace").rstrip("\n")
                if ln.strip() == _INIT_OK_SENTINEL:
                    init_ok_evt.set()
                elif ln.strip():
                    out.append(ln.strip())

        drain = threading.Thread(target=_drain, daemon=True)
        drain.start()
        deadline = time.time() + args.init_timeout + 30.0
        while (time.time() < deadline and proc.poll() is None
               and not init_ok_evt.is_set()):
            init_ok_evt.wait(1.0)
        # final grace before any kill decision: the sentinel may sit in the
        # pipe ahead of the drain thread (a dead worker needs no grace — its
        # classification re-reads the event after the drain join below)
        init_ok = (init_ok_evt.wait(2.0) if proc.poll() is None
                   else init_ok_evt.is_set())
        killed = False
        if not init_ok and proc.poll() is None:
            print("[bench] worker stuck in backend init past the "
                  f"{args.init_timeout:.0f}s cap with the watchdog unable "
                  "to fire (GIL held); killed", file=sys.stderr, flush=True)
            proc.kill()
            killed = True
        if init_ok:
            try:
                proc.wait(args.worker_timeout)
            except subprocess.TimeoutExpired:
                print(f"[bench] worker exceeded the {args.worker_timeout:.0f}s "
                      "post-init run allowance; killed",
                      file=sys.stderr, flush=True)
                proc.kill()
                killed = True
        rc = proc.wait()
        drain.join(10.0)
        init_ok = init_ok_evt.is_set()  # re-read: drain may have caught up
        if killed:
            # a GIL-wedged init is the retryable class (rc 3, like the
            # in-worker watchdog)
            rc = 3 if not init_ok else 1
        last_line = out[-1] if out else None
        state["last_line"], state["rc"], state["out"] = last_line, rc, []
        # Retryable = chip-wedge deaths: the explicit init rcs, a signal
        # death (negative rc, e.g. libtpu SIGABRT on a wedged chip) BEFORE
        # the init-ok sentinel, or a post-init hang that produced NO JSON
        # line — the observed wedge mode where init answers in seconds and
        # the first device op then stalls indefinitely (PROFILE.md round 5).
        # A post-init signal death or a worker that emitted its JSON line
        # (even a failure line) is terminal: the backend came up and the
        # verdict — success, partial, or in-run error — is the worker's.
        post_init_hang = killed and init_ok and last_line is None
        retryable = (rc in _INIT_FAILED_RCS or (rc < 0 and not init_ok)
                     or post_init_hang)
        if not retryable:
            break  # backend came up (or a permanent failure): verdict is final
        remaining = args.retry_budget - (time.time() - t_start)
        if attempt >= args.init_attempts or remaining <= 0:
            print("[bench] giving up: chip never delivered a result "
                  f"({attempt} attempts, {time.time()-t_start:.0f}s; "
                  f"last failure: {'post-init hang' if post_init_hang else 'backend init'})",
                  file=sys.stderr, flush=True)
            break
        backoff = policy.backoff(attempt)
        if remaining <= backoff:
            # the promised retry could never launch: don't sleep into the wall
            print(f"[bench] giving up: {remaining:.0f}s of budget left "
                  f"< {backoff:.0f}s backoff", file=sys.stderr, flush=True)
            break
        print(f"[bench] {'post-init hang' if post_init_hang else f'backend init failed (rc={rc})'}; "
              f"retrying in {backoff:.0f}s with a fresh process",
              file=sys.stderr, flush=True)
        time.sleep(backoff)
    # a signal from here on must not produce a SECOND line
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_DFL)
    if state["emitted"]:
        os._exit(3)  # handler won the race and already printed
    state["emitted"] = True
    if args.obs_events and not args.no_obs:
        # append the supervision story to the worker's event stream so the
        # report shows attempt/retry counts next to the stage tables. The
        # obs import stays chip-free (configure never touches jax), keeping
        # the supervisor's no-backend-init guarantee.
        try:
            from maskclustering_tpu import obs as _obs

            _obs.configure(args.obs_events, sample_memory=False,
                           meta={"tool": "bench-supervisor"})
            _obs.count("bench.attempts", state["attempt"])
            _obs.count("bench.retries", max(state["attempt"] - 1, 0))
            _obs.flush_metrics()
            _obs.disable()
        except Exception as oe:  # noqa: BLE001 — never endanger the JSON line
            print(f"[bench] WARNING: obs supervisor flush failed: {oe}",
                  file=sys.stderr, flush=True)
    line = _final_line()
    print(json.dumps(line))
    _ledger_append(args, line)
    # Preserve the worker's verdict for shell callers (setup_tpu_vm.sh runs
    # under set -e): partial/errored runs must not look like clean passes.
    rc = state["rc"]
    sys.exit(rc if rc != 0 else (0 if line.get("value") is not None else 3))


def main():
    args = _build_parser().parse_args()
    if not args.worker:
        _supervise(args)
        return

    from maskclustering_tpu.analysis import retrace_sanitizer

    if retrace_sanitizer.enabled():
        # hook the compile log before backend init so the warm-up's
        # compiles are on the books; the supervisor's workers inherit
        # MCT_RETRACE_SANITIZER through the environment
        retrace_sanitizer.install()
    _init_backend(args)

    import numpy as np

    obs_armed = bool(args.obs_events) and not args.no_obs
    if args.xprof and not obs_armed:
        print("[bench] WARNING: --xprof needs obs capture (--obs-events, "
              "without --no-obs); ignored", file=sys.stderr, flush=True)
    if obs_armed:
        import jax

        from maskclustering_tpu import obs

        # armed only on request: the default bench keeps the no-op tracer so
        # honest-shape numbers carry zero instrumentation cost (no fences,
        # no event I/O); with capture on, every run_scene stage span and
        # transfer counter streams to the JSONL, crash-safe per line
        xprof_dir, xprof_spans = None, None
        if args.xprof and args.profile_dir:
            print("[bench] WARNING: --xprof ignored (jax has one profiler "
                  "session and --profile-dir already owns it)",
                  file=sys.stderr, flush=True)
        elif args.xprof:
            from maskclustering_tpu.obs.xprof import parse_spans

            xprof_spans = parse_spans(args.xprof)
            xprof_dir = args.xprof_dir or os.path.join(
                os.path.dirname(os.path.abspath(args.obs_events)), "xprof")
        obs.configure(args.obs_events, annotations=bool(args.profile_dir),
                      meta={"tool": "bench", "backend": jax.default_backend(),
                            "frames": args.frames, "points": args.points,
                            "frame_batch": args.frame_batch},
                      xprof_dir=xprof_dir, xprof_spans=xprof_spans)

    from maskclustering_tpu.utils.compile_cache import setup_compilation_cache

    cache = setup_compilation_cache()
    print(f"[bench] persistent compile cache: {cache}", file=sys.stderr, flush=True)
    _validate_pallas_on_tpu()

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.utils.synthetic import (make_scene_device,
                                                    resize_scene_points)

    print(f"[bench] generating synthetic scene: F={args.frames} "
          f"N={args.points} boxes={args.boxes} {args.image_h}x{args.image_w} "
          f"r={args.distance_threshold}",
          file=sys.stderr, flush=True)
    t0 = time.time()
    tensors, _, _ = make_scene_device(
        num_boxes=args.boxes, num_frames=args.frames,
        image_hw=(args.image_h, args.image_w), spacing=args.spacing, seed=0)
    tensors.scene_points = resize_scene_points(tensors.scene_points,
                                               args.points)
    print(f"[bench] scene ready in {time.time()-t0:.1f}s "
          f"(frames rendered in HBM)", file=sys.stderr, flush=True)

    cfg = PipelineConfig(config_name="bench", dataset="demo",
                         distance_threshold=args.distance_threshold,
                         few_points_threshold=25, point_chunk=8192,
                         association_frame_batch=args.frame_batch,
                         count_dtype=args.count_dtype,
                         device_postprocess=not args.host_postprocess)

    times = []
    stage_timings = []
    try:
        # warm-up (compile)
        t0 = time.time()
        run_scene(tensors, cfg, k_max=args.k_max)
        print(f"[bench] warm-up (incl. compile): {time.time()-t0:.1f}s",
              file=sys.stderr, flush=True)
        if retrace_sanitizer.enabled():
            # the bench IS the serve-many workload (one bucket, repeated):
            # after warm-up, any further compile is a retrace — recorded
            # as a post-freeze violation and stamped on the verdict line
            retrace_sanitizer.freeze()

        if args.profile_dir:
            # manual start/stop rather than the (equivalent) jax.profiler
            # .trace contextmanager so a trace-flush failure below can be
            # swallowed instead of masking the run's real exception
            import jax.profiler

            jax.profiler.start_trace(args.profile_dir)
            print(f"[bench] profiler trace -> {args.profile_dir}",
                  file=sys.stderr, flush=True)
        try:
            for i in range(args.repeats):
                t0 = time.time()
                result = run_scene(tensors, cfg, k_max=args.k_max)
                times.append(time.time() - t0)
                if obs_armed:
                    from maskclustering_tpu import obs

                    obs.record_span("bench.repeat", times[-1], repeat=i)
                stage_timings.append(dict(result.timings))
                print(f"[bench] run {i}: {times[-1]:.2f}s "
                      f"({len(result.objects.point_ids_list)} objects, "
                      f"timings {['%s=%.2f' % kv for kv in result.timings.items()]})",
                      file=sys.stderr, flush=True)
        finally:
            if args.profile_dir:
                try:
                    jax.profiler.stop_trace()
                except Exception as te:  # noqa: BLE001 — a flush failure
                    # (disk full, dead rig) must not mask the loop's error
                    print(f"[bench] WARNING: profiler trace flush failed: "
                          f"{te}", file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — always emit the JSON line
        print(f"[bench] ERROR after {len(times)} completed runs: {e}",
              file=sys.stderr, flush=True)
        if obs_armed:
            from maskclustering_tpu import obs

            obs.count("bench.run_errors")
            obs.flush_metrics()
        _emit(args, times, error=e, stage_timings=stage_timings)
        sys.exit(1)

    if obs_armed:
        from maskclustering_tpu import obs

        obs.flush_metrics()
    _emit(args, times, stage_timings=stage_timings)


if __name__ == "__main__":
    main()
