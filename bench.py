"""End-of-round benchmark: per-scene mask-clustering wall time on one chip.

Measures the full per-scene pipeline (projective association -> mask-graph
stats -> iterative clustering -> post-process/export math) on a synthetic
posed-RGB-D scene at ScanNet-like scale (~200k points, 150 frames stride-10
equivalent, ~2k masks). The reference's published cost for this exact stage
is 6.5 GPU-h for 311 ScanNet-val scenes on an RTX 3090 ~= 75 s/scene
(reference README.md:205); vs_baseline = 75 / measured_s_per_scene.

Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=150)
    p.add_argument("--points", type=int, default=196608)  # 192k, ScanNet-ish
    p.add_argument("--boxes", type=int, default=12)
    p.add_argument("--image-h", type=int, default=240)
    p.add_argument("--image-w", type=int, default=320)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--k-max", type=int, default=63)
    args = p.parse_args()

    import jax
    import numpy as np

    from maskclustering_tpu.config import PipelineConfig
    from maskclustering_tpu.models.pipeline import run_scene
    from maskclustering_tpu.utils.synthetic import make_scene, to_scene_tensors

    print(f"[bench] generating synthetic scene: F={args.frames} "
          f"N={args.points} boxes={args.boxes} {args.image_h}x{args.image_w}",
          file=sys.stderr)
    t0 = time.time()
    scene = make_scene(num_boxes=args.boxes, num_frames=args.frames,
                       image_hw=(args.image_h, args.image_w), spacing=0.02, seed=0)
    tensors = to_scene_tensors(scene)
    # pad/trim the cloud to the requested static size (tile = harmless dups)
    pts = tensors.scene_points
    n = args.points
    if pts.shape[0] < n:
        pts = np.tile(pts, (-(-n // pts.shape[0]), 1))[:n]
    else:
        pts = pts[np.random.default_rng(0).choice(pts.shape[0], n, replace=False)]
    tensors.scene_points = np.ascontiguousarray(pts, dtype=np.float32)
    print(f"[bench] scene ready in {time.time()-t0:.1f}s "
          f"({len(jax.devices())}x {jax.devices()[0].device_kind})", file=sys.stderr)

    cfg = PipelineConfig(config_name="bench", dataset="demo",
                         distance_threshold=0.03, few_points_threshold=25,
                         point_chunk=8192)

    # warm-up (compile)
    t0 = time.time()
    run_scene(tensors, cfg, k_max=args.k_max)
    print(f"[bench] warm-up (incl. compile): {time.time()-t0:.1f}s", file=sys.stderr)

    times = []
    for i in range(args.repeats):
        t0 = time.time()
        result = run_scene(tensors, cfg, k_max=args.k_max)
        times.append(time.time() - t0)
        print(f"[bench] run {i}: {times[-1]:.2f}s "
              f"({len(result.objects.point_ids_list)} objects, "
              f"timings {['%s=%.2f' % kv for kv in result.timings.items()]})",
              file=sys.stderr)

    s_per_scene = float(np.median(times))
    baseline = 75.0  # reference: 6.5 GPU-h / 311 ScanNet-val scenes (README.md:205)
    print(json.dumps({
        "metric": f"mask-clustering s/scene (synthetic scene: {args.frames}fr x "
                  f"{args.points // 1024}k pts x {args.boxes} objects)",
        "value": round(s_per_scene, 3),
        "unit": "s/scene",
        "vs_baseline": round(baseline / s_per_scene, 2),
    }))


if __name__ == "__main__":
    main()
