"""Crash-contained serving: the device worker as a supervised subprocess.

PR 10's daemon owned the device from a worker THREAD: a hard XLA/TPU
crash (segfault, OOM-kill) took the whole daemon down, and a wedged
native call — the recurring failure mode that kept BENCH_r04/r05 null —
leaked the device behind an abandoned ``DeviceStallError`` thread
forever. This module moves the device owner into a SUBPROCESS
(serve/worker_main.py) speaking the existing JSONL protocol over stdio
pipes, supervised from the daemon with the PR-5 watchdog vocabulary:

- **heartbeats** — the child emits ``{"kind": "hb"}`` at a fixed cadence
  from a dedicated thread; the parent re-arms a ``faults.Heartbeat``
  (budget ``cfg.worker_heartbeat_s``) on every child line. A GIL-held
  native hang stops every Python thread in the child, so silence IS the
  wedge signal — and unlike the in-process watchdog, the parent can
  actually clear it: **SIGKILL**, not an abandoned thread.
- **bounded respawn with backoff** — ``cfg.worker_respawns`` consecutive
  failed spawns (shared ``faults.RetryPolicy`` backoff) before the
  supervisor declares the device unserveable and asks the daemon to stop;
  the counter resets every time a child reaches ``ready``.
- **requeue, neighbors untouched** — the in-flight request gets a typed
  ``worker_crash`` status event, an ``interrupted`` outcome row in its
  per-request RunJournal (crash-stamped attribution on disk), and goes
  back into the admission queue for the respawned worker — pre-degraded
  by its crash count (SceneSupervisor ``initial_rungs``). A request that
  crashes ``MAX_REQUEST_CRASHES`` workers answers a typed ``failed``
  result (``error_class: "device"``) instead of crash-looping the fleet.
  Queued neighbors never notice: they are the parent's, not the child's.
- **instant warm respawn** — the child's startup runs the persistent AOT
  cache's ``warm_start`` plus the ordinary warm-up against the warm
  compilation cache, then freezes the retrace sanitizer; its ``ready``
  line carries the digest proving the respawn reached first dispatch
  with ZERO compiles (the acceptance test pins it).

The supervisor exposes ServeWorker's exact surface (start/stop/
wait_idle/stats/latency_quantiles) so ``ServeDaemon`` swaps topologies
with one flag; the admission queue, router, protocol and report wiring
are unchanged.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from maskclustering_tpu import obs
from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.obs import flight as _flight
from maskclustering_tpu.obs import telemetry
from maskclustering_tpu.serve import protocol
from maskclustering_tpu.serve.admission import AdmissionQueue
from maskclustering_tpu.serve.router import Router
from maskclustering_tpu.serve.worker import _send
from maskclustering_tpu.utils import faults

log = logging.getLogger("maskclustering_tpu")

# how many device workers one request may take down before it answers a
# typed failure instead of burning the whole respawn budget on a
# poison-pill scene
MAX_REQUEST_CRASHES = 2


def _closed_safe(lines):
    """Iterate a child's stdout, treating a closed-under-us pipe as EOF
    (the kill path closes streams while the reader may still drain)."""
    while True:
        try:
            line = next(lines)
        except StopIteration:
            return
        except (OSError, ValueError):
            return
        yield line


class WorkerSupervisor:
    """Parent-side supervision of one device-owning worker subprocess."""

    def __init__(self, cfg, queue: AdmissionQueue, router: Router, *,
                 journal_dir: Optional[str] = None,
                 prediction_root: Optional[str] = None,
                 stream_state_dir: Optional[str] = None,
                 warm_scenes: Tuple[str, ...] = (),
                 warm_baseline: Optional[str] = None,
                 freeze_after_warm: bool = True,
                 fault_plan_spec: Optional[str] = None,
                 child_argv: Optional[list] = None,
                 start_timeout_s: float = 600.0,
                 poll_s: float = 0.25,
                 on_fatal=None,
                 worker_id: int = 0,
                 pooled: bool = False,
                 child_env: Optional[Dict[str, str]] = None):
        self.cfg = cfg
        self.queue = queue
        self.router = router
        # pool identity: 0 is the classic single-worker topology; a
        # WorkerPool numbers its slices (pooled=True) and only THEN do
        # relayed spans / telemetry rows carry the id — a lone supervisor
        # must stay report-identical to the in-process topology
        self.worker_id = int(worker_id)
        self.pooled = bool(pooled)
        # per-child environment overlay (the pool's device carve: each
        # slice's child sees only its own chips)
        self.child_env = dict(child_env) if child_env else None
        self.journal_dir = journal_dir
        self.prediction_root = prediction_root
        # shared snapshot directory for stream failover: the child ships
        # per-chunk accumulator snapshots here (models/streaming
        # save_state, stream_journal_every cadence), and a crashed
        # stream requeues onto the next child instead of answering
        # stream_lost whenever a snapshot exists to resume from
        self.stream_state_dir = stream_state_dir
        self.warm_scenes = tuple(warm_scenes)
        self.warm_baseline = warm_baseline
        self.freeze_after_warm = freeze_after_warm
        self.fault_plan_spec = fault_plan_spec
        self.child_argv = child_argv
        self.start_timeout_s = float(start_timeout_s)
        self.poll_s = poll_s
        self.on_fatal = on_fatal
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._lock = mct_lock("serve.WorkerSupervisor._lock")
        self._thread: Optional[threading.Thread] = None
        self._child: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._heartbeat = faults.Heartbeat(
            max(getattr(cfg, "worker_heartbeat_s", 0.0), 0.0), seam="worker")
        # in-flight request state keyed by request id, written by the
        # pump, relayed to by the reader. One entry per member:
        # {"req": SceneRequest, "terminal": dict|None, "done": Event}.
        # The packing pump (serve_batch_max > 1) forwards same-bucket
        # batches as ONE pipe envelope, so several entries can ride a
        # single dispatch — a crash requeues exactly the members whose
        # terminal events never landed.
        self._inflight: Dict[str, Dict] = {}
        self._latencies: Deque[float] = deque(maxlen=4096)
        self._counts = {"requests": 0, "ok": 0, "failed": 0, "deadline": 0,
                        "skipped": 0, "interrupted": 0}
        self.respawns = 0
        # respawns since the last child reached ready — the pre-wedge
        # visibility counter (resets on every healthy ready, so a climbing
        # value in `status` means the respawn budget is being eaten NOW)
        self.consecutive_respawns = 0
        self.crashes = 0
        self.spawns = 0
        self.last_ready: Dict = {}
        self.last_bye: Dict = {}
        # the child's black-box delta: worker_main ships its flight-ring
        # events on the heartbeat cadence (kind "flight" pipe lines), so
        # when heartbeat silence forces a SIGKILL the parent still holds
        # the victim's final spans — the rows the result-driven telem
        # relay never got to ship. Bounded; _on_crash dumps them.
        self._child_flight: Deque[Dict] = deque(maxlen=1024)
        self._last_child_telem: Optional[Dict] = None
        # mct-sentinel pipe plumbing: the child's stdin keeps its
        # SINGLE-WRITER invariant — the sentinel never touches the pipe;
        # run_canary posts _canary_req and the pump thread ships the op
        # between requests (so no lock ever wraps pipe IO). _canary_busy
        # (under _lock) admits one round at a time; a second tick skips.
        self._canary_req = threading.Event()
        self._canary_done = threading.Event()
        self._canary_busy = False
        self._canary_probes: Optional[list] = None
        # stream sessions under crash containment: scenes whose
        # device-resident _StreamSession lives in the CURRENT child
        # (grown from stream_chunk results, shrunk on done/stream_end).
        # A crash moves them to _lost_streams — the accumulator died with
        # the child, and the wire `chunk` is frames-per-chunk (not a
        # cursor), so a respawned child would silently reopen at chunk 0.
        # Lost scenes answer a typed stream_lost (in-flight at crash, or
        # at dequeue for queued/later ops), then clear so the client can
        # restart the stream from its own source.
        self._open_streams: set = set()
        self._lost_streams: set = set()
        # failover bookkeeping: streams this supervisor requeued onto a
        # fresh child from a snapshot instead of answering stream_lost
        self._streams_resumed = 0
        self._cfg_path = self._write_cfg()

    # -- child plumbing ------------------------------------------------------

    def _write_cfg(self) -> str:
        d = self.journal_dir or tempfile.gettempdir()
        os.makedirs(d, exist_ok=True)
        fd, path = tempfile.mkstemp(prefix="worker_cfg_", suffix=".json",
                                    dir=d)
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(self.cfg.to_json())
        return path

    def _child_cmd(self, first_spawn: bool) -> list:
        if self.child_argv is not None:
            return list(self.child_argv)
        from maskclustering_tpu.analysis import retrace_sanitizer

        cmd = [sys.executable, "-m", "maskclustering_tpu.serve.worker_main",
               "--cfg-json", self._cfg_path]
        if self.worker_id:
            cmd += ["--worker-id", str(self.worker_id)]
        if self.journal_dir:
            cmd += ["--journal-dir", self.journal_dir]
        if self.prediction_root:
            cmd += ["--prediction-root", self.prediction_root]
        if self.stream_state_dir:
            cmd += ["--stream-state", self.stream_state_dir]
        if self.warm_scenes:
            cmd += ["--warm", "+".join(self.warm_scenes)]
        if self.warm_baseline:
            cmd += ["--warm-baseline", self.warm_baseline]
        if not self.freeze_after_warm:
            cmd += ["--no-freeze"]
        if retrace_sanitizer.enabled():
            cmd += ["--retrace-sanitizer"]
        if first_spawn and self.fault_plan_spec:
            # drills target the FIRST worker; a respawn is the recovery
            # under test — re-arming the plan there would crash-loop it
            cmd += ["--fault-plan", self.fault_plan_spec]
        return cmd

    def _spawn(self, first_spawn: bool) -> bool:
        """One child spawn; blocks (bounded) until its ready line."""
        self._ready.clear()
        cmd = self._child_cmd(first_spawn)
        log.info("worker supervisor: spawning device worker%s",
                 "" if first_spawn else f" (respawn {self.respawns})")
        env = None
        if self.child_env:
            env = dict(os.environ)
            env.update(self.child_env)
        try:
            child = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                     stdout=subprocess.PIPE, text=True,
                                     bufsize=1, env=env)
        except OSError:
            log.exception("worker supervisor: spawn failed")
            return False
        self._child = child
        self.spawns += 1
        reader = threading.Thread(  # mct-thread: abandon(one reader per child, exits on the child's stdout EOF; the kill/respawn path closes the pipe, which IS the bounded join)
            target=self._read_child, args=(child,), daemon=True,
            name="worker-reader")
        reader.start()
        self._reader = reader
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            if self._ready.wait(0.25):
                self._heartbeat.beat()
                self.consecutive_respawns = 0
                return True
            if child.poll() is not None:
                log.error("worker supervisor: child died during startup "
                          "(rc %s)", child.returncode)
                return False
            if self._stop.is_set():
                return False
        log.error("worker supervisor: child never answered ready within "
                  "%.0fs; killing", self.start_timeout_s)
        self._kill_child()
        return False

    def _read_child(self, child: subprocess.Popen) -> None:
        """Reader: heartbeats re-arm the watchdog, request events relay to
        the in-flight client, terminal events wake the pump."""
        stream = child.stdout
        if stream is None:
            return
        try:
            lines = iter(stream)
        except (OSError, ValueError):
            return
        for line in _closed_safe(lines):
            if not line.strip():
                continue
            self._heartbeat.beat()
            try:
                doc = json.loads(line)
            except ValueError:
                log.warning("worker supervisor: unreadable child line %r",
                            line[:200])
                continue
            kind = doc.get("kind")
            if kind == "hb":
                continue
            if kind == telemetry.KIND_TELEM:
                # the cross-process relay: the child's counter deltas fold
                # into THIS registry under their own names and its spans
                # replay here — the Serving report and the telemetry
                # windows read topology-invariant (obs/telemetry.py)
                try:
                    telemetry.fold_telem(
                        doc, child_pid=child.pid,
                        worker_id=self.worker_id if self.pooled else None)
                except Exception:  # noqa: BLE001 — telemetry never faults
                    log.exception("worker supervisor: telem fold failed")
                with self._lock:
                    self._last_child_telem = doc
                continue
            if kind == _flight.KIND_DELTA:
                # child flight-ring delta (heartbeat cadence): retain, so
                # a SIGKILL postmortem still shows the victim's last spans
                with self._lock:
                    for row in doc.get("rows") or ():
                        if isinstance(row, dict):
                            row.setdefault("pid", child.pid)
                            self._child_flight.append(row)
                continue
            if kind == "ready":
                with self._lock:
                    self.last_ready = doc
                self._ready.set()
                continue
            if kind == "bye":
                with self._lock:
                    self.last_bye = doc
                continue
            if kind == "canary":
                # the canary round's answer (worker_main's canary op)
                with self._lock:
                    self._canary_probes = doc.get("probes")
                self._canary_done.set()
                continue
            rid = doc.get("id")
            if rid is None:
                continue
            with self._lock:
                entry = self._inflight.get(rid)
            if entry is None or entry["done"].is_set():
                log.warning("worker supervisor: dropping stray child event "
                            "for %s", rid)
                continue
            if kind in ("result", "reject"):
                entry["terminal"] = doc
                self._track_stream(entry["req"], doc)
                _send(entry["req"], doc)
                entry["done"].set()
            else:
                _send(entry["req"], doc)

    def _track_stream(self, req: protocol.SceneRequest, doc: Dict) -> None:
        """Mirror the child's live _StreamSession set from its terminal
        events: an ok stream_chunk that is not ``done`` opens (or keeps)
        the scene's session; a finished stream or an ok stream_end drops
        it. This parent-side shadow is what crash containment consults —
        the child's own session table dies with it."""
        if req.op not in ("stream_chunk", "stream_end"):
            return
        ok = doc.get("kind") == "result" and doc.get("status") == "ok"
        with self._lock:
            if req.op == "stream_chunk" and ok and not doc.get("done"):
                self._open_streams.add(req.scene)
            elif ok:  # finished stream (done=True) or successful end
                self._open_streams.discard(req.scene)

    def _kill_child(self) -> None:
        child = self._child
        if child is None:
            return
        if child.poll() is None:
            try:
                child.kill()
            except OSError:
                pass
        try:
            child.wait(10.0)
        except subprocess.TimeoutExpired:
            pass
        for stream in (child.stdin, child.stdout):
            try:
                if stream:
                    stream.close()
            except OSError:
                pass
        self._child = None  # the pump's respawn trigger

    # -- lifecycle (ServeWorker surface) ------------------------------------

    def start(self) -> None:
        """Spawn the first worker (blocking until warm) + the pump thread.

        Raises RuntimeError when the first spawn cannot reach ready within
        the respawn budget — a daemon that cannot own a device must fail
        its startup loudly, not accept requests it can never serve.
        """
        if self._thread is not None:
            return
        if not self._spawn(first_spawn=True) and not self._respawn():
            raise RuntimeError(
                "device worker failed to start within the respawn budget; "
                "see worker stderr above")
        self._thread = threading.Thread(  # mct-thread: abandon(daemon-lifetime pump, bounded-joined in stop(); the spawn/join pair spans methods, which the scope-local check cannot see)
            target=self._run, daemon=True, name="serve-supervisor")
        self._thread.start()

    def stop(self, timeout_s: float = 60.0) -> bool:
        """Drain: finish the in-flight request, stop the child, join."""
        self._stop.set()
        # the SIGTERM drain contract: the request in flight finishes in
        # the child and answers before the child is asked to exit
        idle = self._idle.wait(timeout_s)
        child = self._child
        drained = True
        if child is not None and child.poll() is None:
            try:
                if child.stdin:
                    child.stdin.write(json.dumps({"op": "shutdown"}) + "\n")
                    child.stdin.flush()
                    child.stdin.close()
            except OSError:
                pass
            try:
                child.wait(max(timeout_s, 5.0))
            except subprocess.TimeoutExpired:
                log.error("worker supervisor: child outlived the drain "
                          "budget; SIGKILL")
                drained = False
        # drain the reader BEFORE closing the pipes — whether the child
        # exited on request or on its own: the final `bye` digest (the
        # zero-compile evidence the daemon's digest line and the ci.sh
        # crash gate read) may still sit buffered in the pipe
        reader = self._reader
        if reader is not None:
            reader.join(5.0)
        self._kill_child()
        t = self._thread
        if t is not None:
            t.join(10.0)
        try:
            os.unlink(self._cfg_path)  # one cfg transport file per daemon
        except OSError:
            pass
        return idle and drained and (t is None or not t.is_alive())

    def wait_idle(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.queue.depth() == 0 and self._idle.is_set():
                return True
            time.sleep(0.01)
        return False

    def busy(self) -> bool:
        """A dispatch unit is in flight (the pool's load metric)."""
        return not self._idle.is_set()

    # -- the pump ------------------------------------------------------------

    def _child_dead(self) -> Optional[str]:
        """A crash signal, if any: process death or heartbeat silence.
        (``self._child is None`` means an already-handled crash awaiting
        respawn — not a NEW crash signal.)"""
        child = self._child
        if child is None:
            return None
        if child.poll() is not None:
            return f"worker process died (rc {child.returncode})"
        if self._heartbeat.expired():
            _flight.record(_flight.KIND_HB, what="heartbeat_silent",
                           age_s=round(self._heartbeat.age_s(), 3),
                           budget_s=self._heartbeat.budget_s)
            return (f"worker heartbeat silent past "
                    f"{self._heartbeat.budget_s:.3g}s (wedged); SIGKILL")
        return None

    def _respawn(self) -> bool:
        """Bounded respawn loop; False = budget exhausted (fatal)."""
        policy = faults.RetryPolicy(
            attempts=int(getattr(self.cfg, "worker_respawns", 2)) + 1,
            base_s=self.cfg.retry_backoff_s,
            cap_s=max(self.cfg.retry_backoff_s * 8.0, 0.0))
        for attempt in range(1, policy.attempts + 1):
            if self._stop.is_set():
                return False
            self.respawns += 1
            self.consecutive_respawns += 1
            obs.count("serve.worker_respawns")
            if self._spawn(first_spawn=False):
                return True
            if attempt < policy.attempts:
                delay = policy.backoff(attempt)
                log.warning("worker supervisor: respawn failed; retrying "
                            "in %.2fs (%d/%d)", delay, attempt + 1,
                            policy.attempts)
                time.sleep(delay)
        return False

    def _run(self) -> None:
        while not self._stop.is_set():
            detail = self._child_dead()
            if detail is not None:
                # idle crash/wedge (no request harmed): contain first
                self._on_crash(None, detail)
            if self._child is None:
                # a crash was handled (here or under a request): respawn
                if not self._respawn():
                    self._fatal()
                    break
                continue
            self._maybe_send_canary()
            batch = self._next_work()
            if batch is None:
                continue
            if self._stop.is_set():
                for req in batch:
                    if not self.queue.requeue(req):
                        obs.count("serve.admission.rejects.draining")
                        _send(req, protocol.reject(
                            "draining", req=req,
                            detail="daemon shutting down before dispatch"))
                break
            self._idle.clear()
            try:
                self._serve_batch(batch)
            except Exception:  # noqa: BLE001 — one batch, not the daemon
                log.exception("worker supervisor: batch %s crashed the "
                              "pump", [r.id for r in batch])
                for req in batch:
                    with self._lock:
                        entry = self._inflight.pop(req.id, None)
                    if entry is not None and entry["terminal"] is not None:
                        continue  # answered before the pump tripped
                    _send(req, protocol.result(
                        req, "failed", error="internal supervisor error",
                        error_class="terminal"))
            finally:
                self._idle.set()

    def _next_work(self) -> Optional[list]:
        """One dispatch unit off the admission queue: a single request,
        or — when continuous batching is on — up to ``serve_batch_max``
        same-bucket requests packed by the shared scheduler
        (AdmissionQueue.next_batch). The parent's key fn only needs the
        router's memory: the CHILD's own packing scheduler re-derives
        buckets (and peeks its fault plan) before fusing, so an over-eager
        parent key costs nothing but a wider pipe envelope."""
        batch_max = max(int(getattr(self.cfg, "serve_batch_max", 1)), 1)
        if batch_max <= 1:
            req = self.queue.next(timeout_s=self.poll_s)
            return None if req is None else [req]
        return self.queue.next_batch(
            self._batch_key, max_n=batch_max,
            linger_s=float(getattr(self.cfg, "serve_batch_linger_s", 0.0)),
            timeout_s=self.poll_s)

    def _batch_key(self, req: protocol.SceneRequest) -> Optional[tuple]:
        """Same-bucket grouping key for the pipe pump; None = solo (never
        batched): streams, resumes, crash-requeued requests, and scenes
        the router has not classified yet."""
        if req.op != "scene" or req.resume or req.crashes:
            return None
        return self.router.bucket_for(req.scene)

    def _book_arrival(self, req: protocol.SceneRequest) -> bool:
        """Parent-side dequeue bookkeeping; False = expired at dequeue
        (typed deadline reject — the child never sees the request)."""
        with self._lock:
            self._counts["requests"] += 1
        telemetry.record_queue_wait(
            req, max(time.monotonic() - req.admitted_at, 0.0))
        if req.expired():
            obs.count("serve.requests")
            obs.count("serve.rejects.deadline")
            telemetry.record_reject(req.tenant)
            with self._lock:
                self._counts["deadline"] += 1
            _send(req, protocol.reject(
                "deadline", req=req,
                detail=f"deadline_s={req.deadline_s:g} expired after "
                       f"{time.monotonic() - req.admitted_at:.2f}s in queue"))
            return False
        if req.op in ("stream_chunk", "stream_end"):
            with self._lock:
                lost = req.scene in self._lost_streams
                self._lost_streams.discard(req.scene)
            if lost and self._stream_resumable(req.scene):
                # the session died with a worker but the child shipped a
                # snapshot: forward normally — the respawned child's
                # _open_stream resumes the accumulator from it
                with self._lock:
                    self._streams_resumed += 1
                return True
            if lost:
                # the session this op was continuing died with a worker
                # and no snapshot exists to resume from; answer typed,
                # clear the mark so a restarted stream (fresh chunk 1)
                # serves normally. serve.requests books parent-side: the
                # child never sees this op
                obs.count("serve.requests")
                self._answer_stream_lost(
                    req, "stream session lost to a worker crash before "
                         "this op dispatched")
                return False
        return True

    def _stream_resumable(self, scene: str) -> bool:
        """A snapshot exists for this scene's stream: the crashed session
        can re-open on a fresh (or surviving pool) child from disk instead
        of answering the typed stream_lost fallback."""
        if not self.stream_state_dir:
            return False
        from maskclustering_tpu.models.streaming import stream_state_path
        try:
            return os.path.exists(
                stream_state_path(self.stream_state_dir, scene))
        except OSError:
            return False

    def _answer_stream_lost(self, req: protocol.SceneRequest,
                            detail: str) -> None:
        """Typed stream-loss terminal: the scene's device-resident
        accumulator died with its worker and the stream CANNOT silently
        continue (frames-per-chunk wire field, not a cursor — a respawn
        would reopen at chunk 0). status stream_lost + failed result."""
        obs.count("serve.streams_lost")
        obs.count("serve.requests_failed")
        with self._lock:
            self._counts["failed"] += 1
        _send(req, protocol.status(req, "stream_lost", detail=detail))
        _send(req, protocol.result(
            req, "failed",
            error=f"stream session for {req.scene!r} lost: {detail}",
            error_class="stream_lost"))

    def _serve_batch(self, batch) -> None:
        # NB: serve.requests / serve.requests_<status> obs counters for
        # forwarded requests are booked by the CHILD and arrive via the
        # telem relay — booking them here too would double-count the fold.
        # Only the paths the child never sees (expired-at-dequeue, the
        # crash cap in _contain_crash) book parent-side.
        live = [req for req in batch if self._book_arrival(req)]
        if not live:
            return
        t0 = time.monotonic()
        entries = {req.id: {"req": req, "terminal": None,
                            "done": threading.Event()} for req in live}
        with self._lock:
            self._inflight.update(entries)
        child = self._child
        doc = (protocol.forward_request(live[0]) if len(live) == 1
               else protocol.forward_batch(live))
        try:
            child.stdin.write(json.dumps(doc, sort_keys=True) + "\n")
            child.stdin.flush()
        except (OSError, ValueError, AttributeError):
            self._crash_batch(entries, "pipe to worker broke on forward")
            return
        # the deadline backstop spans the batch (the child enforces each
        # member's own folded deadline; this only catches a child that
        # ignores them outright) and only arms when EVERY member carries
        # one — an unbounded member legitimately runs as long as it needs
        deadlines = [req.deadline_s for req in live if req.deadline_s > 0]
        backstop = (max(deadlines) + max(self.cfg.watchdog_device_s, 30.0)
                    + 5.0) if len(deadlines) == len(live) else None
        # wait for every member's terminal event, watching the child the
        # whole time: a crash mid-batch is the supervised case, not an
        # exception (a drain keeps waiting here — in-flight must answer)
        while True:
            pending = [e for e in entries.values()
                       if not e["done"].is_set()]
            if not pending:
                break
            pending[0]["done"].wait(0.25)
            detail = self._child_dead()
            if detail is not None:
                # the child may have ANSWERED (some or all members) and
                # then died: give the reader a bounded window to drain
                # buffered results before declaring members crashed — a
                # completed scene must never be re-executed (or worse,
                # converted into a typed failure at the crash cap)
                grace = time.monotonic() + 2.0
                for e in entries.values():
                    e["done"].wait(max(grace - time.monotonic(), 0.0))
                self._crash_batch(entries, detail)
                break
            if backstop is not None and time.monotonic() - t0 > backstop:
                self._crash_batch(entries,
                                  "worker ignored the request deadline")
                break
        for entry in entries.values():
            if entry["terminal"] is not None:
                with self._lock:
                    self._inflight.pop(entry["req"].id, None)
                self._book_result(entry["req"], entry["terminal"], t0)

    def _book_result(self, req: protocol.SceneRequest, terminal: Dict,
                     t0: float) -> None:
        status = terminal.get("status") or terminal.get("reason") or "failed"
        key = status if status in self._counts else "failed"
        if terminal.get("kind") == "reject":
            key = "deadline" if status == "deadline" else "failed"
        # per-status obs counters arrive via the relay (the child booked
        # them); only the internal stats digest and the telemetry window's
        # latency-by-bucket are parent-side bookings here
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
        latency = time.monotonic() - t0
        self._latencies.append(latency)
        bucket = terminal.get("bucket")
        if bucket is not None:
            b = tuple(bucket)
            self.router.remember(req.scene, b)
            self.router.note_served(b)
        # window-latency parity with the in-process worker: it records
        # only requests that reached the end of execution (its results
        # carry `seconds`) — not rejects, not early-exit materialization
        # failures — and bucket-less terminals (disk scenes) fall back to
        # the router's memory for the same per-bucket keys
        if terminal.get("kind") == "result" and "seconds" in terminal:
            telemetry.record_request(
                tuple(bucket) if bucket is not None
                else self.router.bucket_for(req.scene), latency,
                tenant=req.tenant, status=key,
                worker=self.worker_id if self.pooled else None)

    def _crash_batch(self, entries: Dict, detail: str) -> None:
        """The in-flight batch's worker died: contain ONCE (kill + dump),
        then requeue (or answer at the crash cap) exactly the members
        WITHOUT terminal events. A batchmate whose result landed before
        the death is booked normally by the caller — a completed scene is
        never re-executed, and never converted into a typed failure."""
        victims = []
        with self._lock:
            for entry in entries.values():
                if entry["done"].is_set():
                    continue  # terminal landed; the caller books it
                entry["done"].set()  # the reader must not relay stale events
                self._inflight.pop(entry["req"].id, None)
                victims.append(entry["req"])
        self._contain_crash(victims, detail)

    def _on_crash(self, req: Optional[protocol.SceneRequest],
                  detail: str) -> None:
        """Idle-crash shim: contain with zero (or one) harmed requests."""
        self._contain_crash([req] if req is not None else [], detail)

    def _contain_crash(self, reqs, detail: str) -> None:
        self.crashes += 1
        obs.count("serve.worker_crashes")
        log.error("worker supervisor: %s", detail)
        with self._lock:
            # every open session died with the child; in-flight stream
            # victims are answered below (and clear their own mark), the
            # rest answer stream_lost at their next op's dequeue
            self._lost_streams |= self._open_streams
            self._open_streams.clear()
        child = self._child
        child_pid = child.pid if child is not None else None
        self._kill_child()
        _flight.record(_flight.KIND_CRASH, detail=detail,
                       request=",".join(r.id for r in reqs) or None,
                       scene=",".join(r.scene for r in reqs) or None,
                       child_pid=child_pid, crashes=self.crashes)
        self._dump_blackbox(child_pid)
        for req in reqs:
            self._requeue_crashed(req, detail)

    def _requeue_crashed(self, req: protocol.SceneRequest,
                         detail: str) -> None:
        # zero-width trace marker: obs.trace renders the crash between the
        # dead attempt and the requeue's second queue-wait segment
        obs.record_span("serve.worker_crash", 0.0, request=req.id,
                        scene=req.scene, detail=detail, end_ts=time.time())
        telemetry.record_crash(req.tenant)
        req.crashes += 1
        err = faults.WorkerCrashError(req.scene, detail)
        if req.op in ("stream_chunk", "stream_end"):
            resumable = self._stream_resumable(req.scene)
            with self._lock:
                self._lost_streams.discard(req.scene)
            if resumable and req.crashes < MAX_REQUEST_CRASHES \
                    and not self._stop.is_set():
                # the session's device accumulator died with the child,
                # but the child shipped per-chunk snapshots: requeue the
                # op — the next child's _open_stream resumes from disk
                # (coordinate-checked load_state) and the already-pushed
                # replay chunk dedupes worker-side. Failover is stamped
                # on the journal (stream_resumed) and the worker_crash
                # status carries resuming=True for the obs.trace timeline.
                req.admitted_at = time.monotonic()
                if self.queue.requeue(req):
                    self._journal_crash(req, err,
                                        error_class="stream_resumed")
                    with self._lock:
                        self._streams_resumed += 1
                    obs.count("serve.requests_requeued")
                    _send(req, protocol.status(
                        req, "worker_crash", requeued=True, resuming=True,
                        crashes=req.crashes, detail=detail))
                    return
            # no snapshot (or retries exhausted / draining): typed loss —
            # frames-per-chunk wire semantics mean a respawned child
            # would silently reopen the stream at chunk 0
            self._journal_crash(req, err)
            self._answer_stream_lost(req, detail)
            return
        self._journal_crash(req, err)
        # re-admission stamp: the SECOND queue-wait segment measures from
        # the requeue, not the original ack (the first attempt's wall is
        # its own trace segment, not queue time); deadline_at is absolute
        # and unaffected
        req.admitted_at = time.monotonic()
        if req.crashes < MAX_REQUEST_CRASHES \
                and not self._stop.is_set() and self.queue.requeue(req):
            obs.count("serve.requests_requeued")
            _send(req, protocol.status(req, "worker_crash", requeued=True,
                                       crashes=req.crashes, detail=detail))
            return
        obs.count("serve.requests_failed")
        with self._lock:
            self._counts["failed"] += 1
        _send(req, protocol.result(req, "failed", error=str(err),
                                   error_class="device",
                                   worker_crashes=req.crashes))

    def _dump_blackbox(self, child_pid: Optional[int]) -> None:
        """The SIGKILL postmortem: the parent's own ring plus the child's
        last relayed flight delta and telemetry doc — the only record of
        what the dead worker was doing when the live relay went silent."""
        with self._lock:
            extra = [dict(row) for row in self._child_flight]
            telem = self._last_child_telem
        # racing child shippers (hb thread vs receive-time flush) may land
        # deltas out of ring order; the per-pid seq restores it
        extra.sort(key=lambda r: (r.get("pid") or 0, r.get("seq") or 0))
        if telem is not None:
            extra.append({"kind": _flight.KIND_CHILD_TELEM,
                          "pid": child_pid, "doc": telem})
        _flight.dump("worker_crash", extra_rows=extra)

    def _journal_crash(self, req: protocol.SceneRequest, err: Exception,
                       error_class: str = "device") -> None:
        """Crash-stamp the request's journal: an ``interrupted`` outcome
        row next to the child's orphaned attempt row, so replay shows
        exactly which attempt the worker died under. ``stream_resumed``
        stamps a stream failover (requeued onto a fresh child from a
        snapshot) instead of plain device loss."""
        if not self.journal_dir:
            return
        try:
            path = os.path.join(self.journal_dir, f"{req.id}.jsonl")
            j = faults.RunJournal(path, self.cfg.config_name,
                                  request_id=req.id)
            j.outcome(req.scene, "interrupted", attempt=req.crashes,
                      error_class=error_class, error=str(err))
            j.close()
        except Exception:  # noqa: BLE001 — attribution must not sink recovery
            log.exception("worker supervisor: crash journal row failed")

    def _fatal(self) -> None:
        log.error("worker supervisor: respawn budget exhausted — the "
                  "device is unserveable; requesting daemon stop")
        obs.count("serve.worker_fatal")
        if self.on_fatal is not None:
            try:
                self.on_fatal()
            except Exception:  # noqa: BLE001
                log.exception("worker supervisor: on_fatal callback failed")

    def _maybe_send_canary(self) -> None:
        """Ship a posted canary op — PUMP THREAD ONLY, preserving the
        child stdin's single-writer invariant (no lock ever wraps the
        pipe IO). A dead child or broken pipe releases the waiter with
        no probes — the sentinel books that tick as skipped."""
        if not self._canary_req.is_set():
            return
        self._canary_req.clear()
        child = self._child
        if child is None or child.stdin is None:
            self._canary_done.set()
            return
        try:
            child.stdin.write(json.dumps({"op": "canary"}) + "\n")
            child.stdin.flush()
        except (OSError, ValueError, AttributeError):
            self._canary_done.set()

    def run_canary(self, timeout_s: float = 120.0) -> Optional[list]:
        """One mct-sentinel probe round over the pipe (ServeWorker
        surface): post the canary op for the pump thread to ship, wait
        (bounded, lock-free) for the child's ``canary`` answer. None on
        a busy round / dead child / broken pipe / timeout — the sentinel
        books those ticks as skipped, never as drift."""
        child = self._child
        if child is None or child.poll() is not None or child.stdin is None:
            return None
        with self._lock:
            if self._canary_busy:
                return None  # one round at a time; this tick skips
            self._canary_busy = True
            self._canary_probes = None
        self._canary_done.clear()
        self._canary_req.set()
        try:
            if not self._canary_done.wait(timeout_s):
                self._canary_req.clear()  # never let a stale op fire later
                log.warning("worker supervisor: canary round timed out "
                            "after %.0fs", timeout_s)
                return None
            with self._lock:
                return self._canary_probes
        finally:
            with self._lock:
                self._canary_busy = False

    # -- introspection (ServeWorker surface) --------------------------------

    def latency_quantiles(self) -> Dict[str, Optional[float]]:
        from maskclustering_tpu.obs.report import percentile

        vals = sorted(self._latencies)
        if not vals:
            return {"p50_s": None, "p95_s": None, "count": 0}
        return {"p50_s": round(percentile(vals, 50), 4),
                "p95_s": round(percentile(vals, 95), 4),
                "count": len(vals)}

    def child_retrace(self) -> Dict:
        """The worker's retrace digest (ready/bye lines), for the daemon's
        stats + the Serving report — compiles happen in the CHILD, so the
        parent's own sanitizer has nothing to say here."""
        with self._lock:
            src = self.last_bye or self.last_ready
        return dict(src.get("retrace") or {})

    def stats(self) -> Dict:
        with self._lock:
            counts = dict(self._counts)
            ready = dict(self.last_ready)
            inflight = list(self._inflight.values())
            inflight_id = inflight[0]["req"].id if inflight else None
            inflight_width = len(inflight)
            inflight_crashes = max((e["req"].crashes for e in inflight),
                                   default=0)
            # deterministic drill evidence: how many in-flight requests
            # the child has ACKNOWLEDGED via its relayed flight ring — a
            # kill drill waits for this instead of sleeping (load_gen)
            flight_ids = {row.get("request") for row in self._child_flight
                          if row.get("kind") == _flight.KIND_REQUEST}
            inflight_ids = [e["req"].id for e in inflight]
            streams_resumed = self._streams_resumed
        inflight_logged = sum(1 for r in inflight_ids if r in flight_ids)
        child = self._child
        alive = child is not None and child.poll() is None
        return {"counts": counts,
                "latency": self.latency_quantiles(),
                "warm_buckets": sorted(self.router.warm_buckets()),
                # the pre-wedge liveness panel: heartbeat age (vs budget),
                # consecutive respawns and the in-flight crash count make
                # a wedging worker visible in `status` BEFORE the SIGKILL
                "worker": {"isolated": True, "alive": alive,
                           "worker_id": self.worker_id,
                           "open_streams": len(self._open_streams),
                           "lost_streams": len(self._lost_streams),
                           "streams_resumed": streams_resumed,
                           "inflight_logged": inflight_logged,
                           "spawns": self.spawns,
                           "respawns": self.respawns,
                           "consecutive_respawns": self.consecutive_respawns,
                           "crashes": self.crashes,
                           "hb_age_s": round(self._heartbeat.age_s(), 3),
                           "hb_budget_s": self._heartbeat.budget_s,
                           "inflight": inflight_id,
                           "inflight_width": inflight_width,
                           "inflight_crashes": inflight_crashes,
                           "warmup_s": ready.get("warmup_s"),
                           "aot": ready.get("aot"),
                           "pid": ready.get("pid")}}
