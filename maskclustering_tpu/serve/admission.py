"""mct-serve admission layer: a bounded queue with typed rejects.

Admission is the daemon's backpressure contract: the queue holds at most
``capacity`` requests, a full queue rejects IMMEDIATELY with a typed
``queue_full`` (the client retries elsewhere/later instead of silently
waiting on an unbounded backlog), and every admitted request carries its
deadline so the worker can refuse to start work that can no longer
finish in budget (``deadline`` reject at dequeue).

Built on ``queue.Queue`` (internally locked; the handler threads submit,
the single worker thread consumes) plus one small ``mct_lock``-named lock
for the depth high-water bookkeeping — the ``serve.queue_depth`` gauge
and ``serve.admission.*`` counters are the Serving report's source of
truth.
"""

from __future__ import annotations

import collections
import queue
import time
from typing import Callable, List, Optional

from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.obs import flight as _flight
from maskclustering_tpu.serve.protocol import SceneRequest


def _flight_admit(event: str, req: SceneRequest, **fields) -> None:
    """One queue-transition mark in the always-on flight ring — the
    postmortem's admission history (obs/flight.py; never raises, no IO)."""
    _flight.record(_flight.KIND_ADMIT, event=event, request=req.id,
                   scene=req.scene, **{k: v for k, v in fields.items()
                                       if v not in (None, "", 0)})


class QueueFullReject(Exception):
    """Typed admission reject: the bounded queue is at capacity."""

    def __init__(self, depth: int, capacity: int):
        self.depth = depth
        self.capacity = capacity
        super().__init__(f"admission queue full ({depth}/{capacity})")


def _count(name: str, delta: float = 1.0) -> None:
    from maskclustering_tpu.obs import metrics

    metrics.count(name, delta)


def _gauge(name: str, value: float) -> None:
    from maskclustering_tpu.obs import metrics

    metrics.gauge(name, value)


class AdmissionQueue:
    """Bounded FIFO of admitted ``SceneRequest``s.

    ``submit`` never blocks: a full queue raises ``QueueFullReject`` so
    the caller (a connection handler thread) answers the client within
    one lock acquisition. ``next`` is the worker's bounded-wait pop (the
    timeout doubles as the worker's stop-flag poll interval).
    """

    def __init__(self, capacity: int = 8, *, metered: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        # metered=False: no serve.admission.*/queue-depth bookings — for
        # INTERNAL queues (the isolated worker's two-slot stdin buffer)
        # whose plumbing must not relay up as admission accounting and
        # break topology invariance (the parent's queue is THE admission)
        self.metered = metered
        self._q: "queue.Queue[SceneRequest]" = queue.Queue(maxsize=capacity)
        self._lock = mct_lock("serve.AdmissionQueue._lock")
        self._high_water = 0
        self._admitted = 0
        # the batch scheduler's look-aside: requests popped while hunting
        # for same-bucket company but belonging to a DIFFERENT bucket wait
        # here, ahead of the queue (FIFO preserved at head granularity).
        # Touched only by the single consumer thread (the worker / the
        # supervisor pump — the same thread that calls next/next_batch),
        # so it needs no lock of its own; deque ops are atomic regardless.
        self._stash: "collections.deque[SceneRequest]" = collections.deque()

    def submit(self, req: SceneRequest) -> int:
        """Admit one request; returns the post-admission depth."""
        try:
            self._q.put_nowait(req)
        except queue.Full:
            if self.metered:
                _count("serve.admission.rejects.queue_full")
                _flight_admit("reject_queue_full", req,
                              depth=self._q.qsize(), tenant=req.tenant)
            raise QueueFullReject(self._q.qsize(), self.capacity) from None
        depth = self._q.qsize()
        if self.metered:
            _flight_admit("admit", req, depth=depth, tenant=req.tenant)
        with self._lock:
            self._admitted += 1
            if depth > self._high_water:
                self._high_water = depth
        if self.metered:
            _count("serve.admission.admitted")
            _gauge("serve.queue_depth", float(depth))
            _gauge("serve.queue_depth_high_water", float(self._high_water))
        return depth

    def next(self, timeout_s: float = 0.25) -> Optional[SceneRequest]:
        """The worker's pop: one request, or None after ``timeout_s``.

        Stashed requests (left behind by an earlier ``next_batch`` hunt)
        go first — they were admitted before anything still in the queue.
        """
        if self._stash:
            req = self._stash.popleft()
        else:
            try:
                req = self._q.get(timeout=timeout_s)
            except queue.Empty:
                return None
        if self.metered:
            _gauge("serve.queue_depth", float(self.depth()))
            _flight_admit("dequeue", req, depth=self.depth())
        return req

    def next_batch(self, key_fn: Callable[[SceneRequest], Optional[tuple]],
                   *, max_n: int, linger_s: float,
                   timeout_s: float = 0.25) -> Optional[List[SceneRequest]]:
        """The packing scheduler's pop: up to ``max_n`` same-key requests.

        Pops the FIFO head, then hunts the stash and the queue for
        requests whose ``key_fn`` matches the head's (a shape-bucket
        tuple; ``None`` marks an unbatchable request — streams, resumes,
        unknown buckets — which always dispatches solo). Non-matching
        requests return to the stash IN ORDER, ahead of the queue, so the
        hunt never reorders heads. The hunt is bounded by the linger
        window: ``linger_s``, clipped to half the smallest remaining
        deadline budget in the batch — a lone request never waits past
        its latency budget for company that may not come.

        Returns None after ``timeout_s`` with nothing queued; else a
        non-empty list whose first element is the FIFO head.
        """
        head = self.next(timeout_s=timeout_s)
        if head is None:
            return None
        if max_n <= 1:
            return [head]
        key = key_fn(head)
        if key is None:
            return [head]
        batch = [head]
        skipped: List[SceneRequest] = []

        def _window_end(now: float, end: float, req: SceneRequest) -> float:
            rem = req.remaining_s()
            return end if rem is None else min(end, now + 0.5 * max(rem, 0.0))

        now = time.monotonic()
        end = _window_end(now, now + max(linger_s, 0.0), head)
        # the stash first (older admissions), then the queue
        for _ in range(len(self._stash)):
            req = self._stash.popleft()
            if len(batch) < max_n and key_fn(req) == key:
                batch.append(req)
                end = _window_end(time.monotonic(), end, req)
            else:
                skipped.append(req)
        while len(batch) < max_n:
            now = time.monotonic()
            try:
                # drain without waiting first; linger only on an empty queue
                req = self._q.get_nowait()
            except queue.Empty:
                if now >= end:
                    break
                try:
                    req = self._q.get(timeout=min(end - now, 0.02))
                except queue.Empty:
                    continue
            if key_fn(req) == key:
                batch.append(req)
                end = _window_end(time.monotonic(), end, req)
            else:
                skipped.append(req)
        # skipped requests go back IN ORDER, ahead of the queue
        self._stash.extendleft(reversed(skipped))
        if self.metered:
            _gauge("serve.queue_depth", float(self.depth()))
            for req in batch[1:]:
                _flight_admit("dequeue_batch", req, depth=self.depth(),
                              batch=len(batch))
        return batch

    def requeue(self, req: SceneRequest) -> bool:
        """Hand a popped-but-unserved request back (the worker's stop path:
        it must not execute work the drain promised a typed reject for).
        False when a racing submit refilled the slot — the caller then
        answers the request itself."""
        try:
            self._q.put_nowait(req)
        except queue.Full:
            return False
        if self.metered:
            _gauge("serve.queue_depth", float(self._q.qsize()))
            _flight_admit("requeue", req, depth=self._q.qsize(),
                          crashes=req.crashes)
        return True

    def drain(self) -> List[SceneRequest]:
        """Everything still queued (shutdown: answer, don't run). Called
        after the consumer thread has stopped, so the stash is quiescent."""
        out: List[SceneRequest] = list(self._stash)
        self._stash.clear()
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        if self.metered:
            _gauge("serve.queue_depth", 0.0)
            for req in out:
                _flight_admit("drain", req)
        return out

    def depth(self) -> int:
        return self._q.qsize() + len(self._stash)

    @property
    def high_water(self) -> int:
        with self._lock:
            return self._high_water

    @property
    def admitted(self) -> int:
        with self._lock:
            return self._admitted
