"""Minimal mct-serve client: one connection, blocking request/response.

Stdlib-only (socket + json via serve/protocol): load_gen, the CI smoke
gate and the tests all talk to the daemon through this one client, so the
wire shapes have exactly one reader implementation. A ``ServeClient`` is
single-threaded by design — concurrent load uses one client (one
connection) per in-flight request, which keeps event demultiplexing out
of the protocol entirely.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Dict, List, Optional, Tuple, Union

from maskclustering_tpu.serve import protocol


class ServeClientError(RuntimeError):
    """The daemon closed the connection or sent something unreadable."""


class ServeClient:
    def __init__(self, address: Union[str, Tuple[str, int]],
                 timeout_s: float = 120.0):
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(timeout_s)
        self._sock.connect(address)
        self._buf = b""

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- wire ---------------------------------------------------------------

    def send(self, doc: Dict) -> None:
        self._sock.sendall(protocol.encode(doc))

    def recv_event(self) -> Dict:
        """One response line (blocking up to the socket timeout)."""
        while b"\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ServeClientError("daemon closed the connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        try:
            return json.loads(line.decode("utf-8", "replace"))
        except ValueError as e:
            raise ServeClientError(f"unreadable response line: {e}") from e

    # -- requests -----------------------------------------------------------

    def request_scene(self, scene: str, *, synthetic: Optional[Dict] = None,
                      deadline_s: float = 0.0, resume: bool = False,
                      tag: str = "", tenant: str = "",
                      idem: str = "") -> Dict:
        """Submit one scene request; returns the ack or reject event.

        ``idem`` (optional) arms the daemon's WAL dedupe contract: a
        resubmit with the same key after a reconnect re-attaches to the
        running request or replays the cached terminal (``deduped``).
        """
        doc: Dict = {"op": "scene", "scene": scene}
        if synthetic is not None:
            doc["synthetic"] = synthetic
        if deadline_s:
            doc["deadline_s"] = deadline_s
        if resume:
            doc["resume"] = True
        if tag:
            doc["tag"] = tag
        if tenant:
            doc["tenant"] = tenant
        if idem:
            doc["idem"] = idem
        self.send(doc)
        return self.recv_event()

    def wait_result(self, *, collect: Optional[List[Dict]] = None) -> Dict:
        """Read events until the terminal one (result or reject).

        ``collect`` (optional) receives every intermediate status event.
        """
        while True:
            ev = self.recv_event()
            if ev.get("kind") in ("result", "reject"):
                return ev
            if collect is not None:
                collect.append(ev)

    def run_scene(self, scene: str, **kw) -> Tuple[Dict, List[Dict], float]:
        """request + wait: (terminal event, status events, latency seconds)."""
        t0 = time.monotonic()
        first = self.request_scene(scene, **kw)
        if first.get("kind") == "reject":
            return first, [], time.monotonic() - t0
        assert first.get("kind") == "ack", first
        statuses: List[Dict] = []
        terminal = self.wait_result(collect=statuses)
        return terminal, statuses, time.monotonic() - t0

    # -- live-scan streaming ------------------------------------------------

    def stream_chunk(self, scene: str, *, chunk: int = 0,
                     synthetic: Optional[Dict] = None, deadline_s: float = 0.0,
                     tag: str = "", tenant: str = "",
                     idem: str = "") -> Tuple[Dict, List[Dict]]:
        """Accumulate the scene's next frame chunk on the daemon.

        Returns ``(terminal event, status events)`` — the terminal result
        carries ``partial_instances`` (the anytime instance count) and
        ``done`` (all frames consumed). ``chunk`` (frames per chunk) only
        matters on the FIRST op of a stream; 0 uses the daemon's config.
        """
        doc: Dict = {"op": "stream_chunk", "scene": scene}
        if chunk:
            doc["chunk"] = chunk
        if synthetic is not None:
            doc["synthetic"] = synthetic
        if deadline_s:
            doc["deadline_s"] = deadline_s
        if tag:
            doc["tag"] = tag
        if tenant:
            doc["tenant"] = tenant
        if idem:
            doc["idem"] = idem
        self.send(doc)
        first = self.recv_event()
        if first.get("kind") == "reject":
            return first, []
        assert first.get("kind") == "ack", first
        statuses: List[Dict] = []
        return self.wait_result(collect=statuses), statuses

    def stream_end(self, scene: str, *, tag: str = "") -> Tuple[Dict, List[Dict]]:
        """Finalize a stream: export artifacts, drop the session."""
        doc: Dict = {"op": "stream_end", "scene": scene}
        if tag:
            doc["tag"] = tag
        self.send(doc)
        first = self.recv_event()
        if first.get("kind") == "reject":
            return first, []
        assert first.get("kind") == "ack", first
        statuses: List[Dict] = []
        return self.wait_result(collect=statuses), statuses

    def stream_scene(self, scene: str, *, chunk: int = 0,
                     synthetic: Optional[Dict] = None,
                     max_chunks: int = 10000) -> Tuple[Dict, List[Dict]]:
        """Drive a whole scan: stream_chunk until ``done``, then
        stream_end. Returns the final result plus EVERY per-chunk
        terminal event (the partial-instance trajectory) — the one
        streaming flow load_gen, CI and the tests share."""
        chunk_events: List[Dict] = []
        for _ in range(max_chunks):
            ev, _st = self.stream_chunk(scene, chunk=chunk,
                                        synthetic=synthetic)
            chunk_events.append(ev)
            if ev.get("kind") != "result" or ev.get("status") != "ok":
                return ev, chunk_events
            if ev.get("done"):
                break
        else:
            # never finalize a stream the server has not reported done —
            # a silent partial export would be indistinguishable from a
            # complete scan to the caller
            raise ServeClientError(
                f"stream {scene!r} not done after {max_chunks} chunk "
                f"op(s); raise max_chunks or send stream_end yourself")
        final, _st = self.stream_end(scene)
        return final, chunk_events

    def stats(self, detail: str = "") -> Dict:
        doc: Dict = {"op": "status"}
        if detail:
            doc["detail"] = detail
        self.send(doc)
        while True:
            ev = self.recv_event()
            if ev.get("kind") == "stats":
                return ev

    def telemetry(self) -> Dict:
        """The stats snapshot plus the windowed telemetry ring (the
        ``obs.top`` dashboard's poll)."""
        return self.stats(detail="telemetry")

    def slo(self) -> Dict:
        """Telemetry plus the armed SLO spec's burn-rate verdict
        (obs/slo.py) under the ``slo`` key."""
        return self.stats(detail="slo")

    def sentinel(self) -> Dict:
        """The stats snapshot plus the canary sentinel's drift-plane
        matrix (obs/canary.py) under the ``sentinel`` key."""
        return self.stats(detail="sentinel")

    def recarve(self, workers: int = 0, carve: str = "") -> Dict:
        """Re-carve a pooled daemon's device mesh live (``recarve`` op).

        Returns the ``{"kind": "recarve", "ok": ...}`` answer; a
        single-worker daemon answers a ``bad_request`` reject instead."""
        doc: Dict = {"op": "recarve"}
        if workers:
            doc["workers"] = int(workers)
        if carve:
            doc["carve"] = carve
        self.send(doc)
        while True:
            ev = self.recv_event()
            if ev.get("kind") in ("recarve", "reject"):
                return ev

    def shutdown(self) -> Dict:
        self.send({"op": "shutdown"})
        return self.recv_event()
