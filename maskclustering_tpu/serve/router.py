"""mct-serve router: shape-bucket classification + serving-vocabulary warm-up.

"Bucket" means exactly one thing across the whole serve-many stack:
``utils/compile_cache.scene_bucket`` — the (k_max, f_pad, n_pad) key
``run_scene_device`` routes every scene through, the retrace family's
census coordinate, and now the daemon's routing/warmth vocabulary. The
router

- **classifies** requests through that one classifier (synthetic requests
  at materialization, disk scenes as the worker's executor records their
  buckets);
- tracks which buckets this process has **served warm** (first dispatch of
  a bucket compiles; every later request against it must not — the
  retrace sanitizer enforces, the router reports);
- builds **warm-up workloads**: either explicit scene names, or synthetic
  tensors fitted to the bucket coordinates of
  ``compile_surface_baseline.json``'s canonical workload, so a daemon
  started with ``--warm-baseline`` pays the serving vocabulary's compiles
  at startup instead of on the first unlucky request.

Baseline-driven warm-up fits a small synthetic scene to each workload
entry's exact (frames, points, max_id): frame count is exact by
construction, the cloud is tiled/trimmed to the point count (duplicate
points are geometrically harmless), and one border pixel of one frame's
id-map is raised to ``max_id`` (a 1-pixel mask the coverage filter
rejects — it exists only to pin ``bucket_k_max``).
"""

from __future__ import annotations

import json
import logging
from typing import Dict, Iterable, List, Optional, Set, Tuple

import numpy as np

from maskclustering_tpu.analysis.lock_sanitizer import mct_lock
from maskclustering_tpu.utils.compile_cache import scene_bucket

log = logging.getLogger("maskclustering_tpu")

Bucket = Tuple[int, int, int]  # (k_max, f_pad, n_pad)


def fit_tensors_to_bucket(tensors, frames: int, points: int, max_id: int):
    """Reshape a synthetic scene's tensors to exact bucket coordinates.

    ``frames`` must already match (make_scene's num_frames is exact); the
    cloud is resized by cyclic tiling/trimming and the id-map's [0, 0]
    pixel of frame 0 is raised to ``max_id`` when the scene's own ids
    fall short. Returns a new SceneTensors; never mutates the input.
    """
    import dataclasses

    if tensors.num_frames != frames:
        raise ValueError(f"warm-up scene has {tensors.num_frames} frames, "
                         f"bucket needs {frames} (generate, don't resize)")
    pts = tensors.scene_points
    if pts.shape[0] != points:
        pts = np.resize(pts, (points, pts.shape[1]))
    seg = tensors.segmentations
    if int(np.max(seg)) < max_id:
        seg = seg.copy()
        seg[0, 0, 0] = max_id
    return dataclasses.replace(tensors, scene_points=pts, segmentations=seg)


class Router:
    """Bucket bookkeeping for one daemon (one cfg, one process)."""

    def __init__(self, cfg, baseline_path: Optional[str] = None):
        self.cfg = cfg
        self._lock = mct_lock("serve.Router._lock")
        self._warm: Set[Bucket] = set()
        # scene name -> bucket, filled as requests classify: repeat
        # synthetic requests must not regenerate a whole scene host-side
        # just to re-derive a bucket that cannot have changed
        self._by_scene: Dict[str, Bucket] = {}
        # bucket -> warm synthetic SceneTensors: the packing scheduler's
        # pad-lane source (serve/worker.py fills it at warm-up/first-serve;
        # a partial batch pads to full width with THESE tensors so every
        # occupancy reuses the one full-width executable)
        self._pad_tensors: Dict[Bucket, object] = {}
        self.vocabulary: List[Dict] = []  # baseline workload entries
        if baseline_path:
            self.vocabulary = self._load_vocabulary(baseline_path)

    @staticmethod
    def _load_vocabulary(path: str) -> List[Dict]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            log.warning("serve router: no usable surface baseline at %s; "
                        "starting with an empty serving vocabulary", path)
            return []
        out = []
        for entry in doc.get("workload", ()):
            if all(isinstance(entry.get(k), int)
                   for k in ("frames", "points", "max_id")):
                out.append({k: entry[k]
                            for k in ("scene", "frames", "points", "max_id")
                            if k in entry})
        return out

    def classify(self, frames: int, points: int, max_id: int) -> Bucket:
        return scene_bucket(self.cfg, frames, points, max_id)

    def classify_tensors(self, tensors) -> Bucket:
        from maskclustering_tpu.utils.compile_cache import scene_bucket_of

        return scene_bucket_of(self.cfg, tensors)

    def bucket_for(self, scene: str) -> Optional[Bucket]:
        with self._lock:
            return self._by_scene.get(scene)

    def remember(self, scene: str, bucket: Bucket) -> None:
        with self._lock:
            self._by_scene[scene] = bucket

    def is_warm(self, bucket: Bucket) -> bool:
        with self._lock:
            return bucket in self._warm

    def note_served(self, bucket: Bucket) -> bool:
        """Record a served bucket; True when it was new (cold dispatch)."""
        with self._lock:
            if bucket in self._warm:
                return False
            self._warm.add(bucket)
        return True

    def warm_buckets(self) -> Set[Bucket]:
        with self._lock:
            return set(self._warm)

    def vocabulary_buckets(self) -> Set[Bucket]:
        """The baseline workload's bucket set — what EVERY worker warms at
        startup (the pool seeds each slice's affinity set with these:
        a vocabulary bucket is warm on every slice by construction)."""
        return {self.classify(e["frames"], e["points"], e["max_id"])
                for e in self.vocabulary}

    def remember_pad_tensors(self, bucket: Bucket, tensors) -> None:
        """Retain one scene's tensors as the bucket's warm pad lane (first
        writer wins — pad bytes must stay stable across a daemon's life so
        partial-batch dispatches are reproducible)."""
        with self._lock:
            self._pad_tensors.setdefault(bucket, tensors)

    def pad_tensors_for(self, bucket: Bucket):
        """The bucket's warm pad-lane tensors, or None before any scene of
        that bucket has been warmed/served."""
        with self._lock:
            return self._pad_tensors.get(bucket)

    def warmup_workload(self) -> Iterable[Tuple[str, "object"]]:
        """(name, SceneTensors) per DISTINCT baseline-vocabulary bucket.

        Tensors are synthetic scenes fitted to each entry's exact
        coordinates; repeated buckets (the baseline workload includes a
        deliberate repeat) are emitted once.
        """
        from maskclustering_tpu.utils.synthetic import (make_scene,
                                                        to_scene_tensors)

        seen: Set[Bucket] = set()
        for i, entry in enumerate(self.vocabulary):
            bucket = self.classify(entry["frames"], entry["points"],
                                   entry["max_id"])
            if bucket in seen:
                continue
            seen.add(bucket)
            scene = make_scene(num_boxes=3, num_frames=entry["frames"],
                               image_hw=(60, 80), spacing=0.06,
                               seed=1000 + i)
            tensors = fit_tensors_to_bucket(
                to_scene_tensors(scene), entry["frames"], entry["points"],
                entry["max_id"])
            fitted = self.classify_tensors(tensors)
            if fitted != bucket:
                # a mis-fitted warm-up scene would silently warm the WRONG
                # executable; skip it loudly rather than lie about warmth
                log.warning("serve router: warm-up scene for %s landed in "
                            "bucket %s; skipping", entry, fitted)
                continue
            yield entry.get("scene", f"warm-{i}"), tensors
