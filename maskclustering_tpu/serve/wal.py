"""Admission write-ahead log: the daemon's crash-safe request ledger.

A SIGKILL'd daemon loses its admission queue — every acked-but-unanswered
request simply vanishes, and the client's only recourse is to resubmit
blind (risking a double run of work that was already in flight). This
module makes admission durable with the same torn-line-tolerant JSONL
machinery as the run journal and the perf ledger (obs/events.py): one
append-only WAL per daemon, one flushed line per state transition.

Row kinds (all ride the events envelope, ``v``/``ts``/``pid``):

- ``wal.admit``    — a request passed admission; carries the validated
  client doc verbatim so a restarted daemon can rebuild the work item.
- ``wal.dispatch`` — the request reached a worker (first ``running``
  status observed). Advisory: replay treats dispatched-but-unanswered
  exactly like queued (the worker died with the daemon).
- ``wal.terminal`` — the one terminal event (result or typed reject)
  left the daemon. A request with a terminal row is settled; when the
  admit carried an idempotency key, the terminal event is retained so a
  reconnect-and-resubmit can be answered from cache without re-running.

Recovery (``read_wal``) folds the rows into: the ordered list of
journaled-but-unanswered requests to replay into the queue, the
idempotency-key -> cached-terminal map, and the highest daemon-assigned
request id (so the restarted daemon's id counter never collides with
journal files left by its predecessor). ``compact`` rewrites the WAL to
exactly that recovered state at startup, bounding growth across restarts
without ever truncating mid-run.

Same discipline as every durable plane here: append-only, one flush per
line, never the failure source (EventSink disables itself on write
errors), torn final lines skipped-with-a-count by the reader.
"""

from __future__ import annotations

import logging
import os
import re
import time
from typing import Dict, List, Optional, Tuple

from maskclustering_tpu.obs.events import (EventSink, ReadStats,
                                           SCHEMA_VERSION, iter_jsonl_rows)

log = logging.getLogger("maskclustering_tpu")

KIND_ADMIT = "wal.admit"
KIND_DISPATCH = "wal.dispatch"
KIND_TERMINAL = "wal.terminal"

# the one WAL file a daemon owns, living beside the per-request journals
# (journal pruning skips it by name — see prune_journal_dir)
WAL_FILENAME = "admission.wal.jsonl"

_ID_RE = re.compile(r"^r-(\d+)$")


class AdmissionWal:
    """Append-only admission WAL writer (thread-safe via EventSink)."""

    def __init__(self, path: str):
        self.path = path
        self._sink = EventSink(path)

    def admit(self, request_id: str, doc: Dict, *, idem: str = "") -> None:
        """Journal one admitted request: the validated client doc rides
        verbatim so replay can rebuild the exact work item."""
        row = {"request": request_id, "doc": doc}
        if idem:
            row["idem"] = idem
        self._sink.emit(KIND_ADMIT, row)

    def dispatch(self, request_id: str) -> None:
        self._sink.emit(KIND_DISPATCH, {"request": request_id})

    def terminal(self, request_id: str, event: Dict, *,
                 idem: str = "") -> None:
        """Journal the request's one terminal event (result or typed
        reject). With an idempotency key the event is retained for the
        dedupe cache; without one only the settlement matters."""
        row = {"request": request_id, "event": event}
        if idem:
            row["idem"] = idem
        self._sink.emit(KIND_TERMINAL, row)

    def close(self) -> None:
        self._sink.close()


class WalState:
    """What recovery extracted from a predecessor daemon's WAL."""

    __slots__ = ("pending", "answered", "max_id", "rows", "stats")

    def __init__(self):
        # journaled-but-unanswered, admission order: (request_id, doc, idem)
        self.pending: List[Tuple[str, Dict, str]] = []
        # idempotency key -> the cached terminal event (keyed admits only)
        self.answered: Dict[str, Dict] = {}
        self.max_id = 0  # highest daemon-assigned numeric request id seen
        self.rows = 0
        self.stats = ReadStats()


def read_wal(path: str) -> WalState:
    """Fold a WAL file into replayable state (missing file = empty state).

    Torn/unknown lines are skipped-with-a-count (``state.stats``), the
    shared tolerant-reader policy — a crash can tear at most the final
    line, and recovery must never be the thing that refuses to recover.
    """
    state = WalState()
    if not path or not os.path.exists(path):
        return state
    open_admits: Dict[str, Tuple[Dict, str]] = {}
    order: List[str] = []
    for row in iter_jsonl_rows(path, version=SCHEMA_VERSION,
                               stats=state.stats):
        state.rows += 1
        kind = row.get("kind")
        rid = row.get("request")
        if not isinstance(rid, str):
            continue
        m = _ID_RE.match(rid)
        if m:
            state.max_id = max(state.max_id, int(m.group(1)))
        if kind == KIND_ADMIT:
            doc = row.get("doc")
            if isinstance(doc, dict) and rid not in open_admits:
                open_admits[rid] = (doc, str(row.get("idem") or ""))
                order.append(rid)
        elif kind == KIND_TERMINAL:
            adm = open_admits.pop(rid, None)
            idem = str(row.get("idem") or (adm[1] if adm else ""))
            event = row.get("event")
            if idem and isinstance(event, dict):
                state.answered[idem] = event
        # wal.dispatch is advisory: a dispatched-but-unanswered request
        # replays exactly like a queued one (its worker died too)
    state.pending = [(rid,) + open_admits[rid] for rid in order
                     if rid in open_admits]
    return state


def compact(path: str, state: WalState) -> None:
    """Rewrite the WAL to exactly the recovered state (startup only).

    Atomic via tmp + rename so a crash mid-compaction leaves the old WAL
    intact; failure is logged and ignored — compaction is an optimization,
    never a correctness step (replay already happened from the old file).
    """
    tmp = path + ".tmp"
    try:
        sink = EventSink(tmp, truncate=True)
        for rid, doc, idem in state.pending:
            row = {"request": rid, "doc": doc}
            if idem:
                row["idem"] = idem
            sink.emit(KIND_ADMIT, row)
        for idem, event in sorted(state.answered.items()):
            sink.emit(KIND_TERMINAL,
                      {"request": str(event.get("id") or ""),
                       "event": event, "idem": idem})
        sink.close()
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — compaction must never sink recovery
        log.exception("WAL compaction failed; keeping the old file (%s)",
                      path)
        try:
            os.remove(tmp)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# retention: journal_dir/ and stream_state/ grow one file per request /
# per live stream — prune the settled tail so a long-lived daemon's disk
# footprint is bounded (config-validated knobs, counted as
# serve.journals_pruned)
# ---------------------------------------------------------------------------

# files younger than this are never pruned regardless of the keep-N
# policy: an in-flight request's journal and a live stream's snapshot are
# both "recent" by construction, and retention must never eat live state
MIN_PRUNE_AGE_S = 60.0


def prune_dir(dirpath: str, *, keep: int, max_age_s: float,
              suffixes: Tuple[str, ...],
              skip: Tuple[str, ...] = (WAL_FILENAME,),
              now: Optional[float] = None) -> int:
    """Delete the oldest matching files beyond ``keep`` and anything older
    than ``max_age_s`` (0 disables either policy). Returns files removed.

    Never raises: a scan or unlink error logs once and the pruner moves
    on — retention is housekeeping, not a failure source.
    """
    if not dirpath or not os.path.isdir(dirpath) \
            or (keep <= 0 and max_age_s <= 0):
        return 0
    now = time.time() if now is None else now
    entries: List[Tuple[float, str]] = []
    try:
        for name in os.listdir(dirpath):
            if name in skip or not name.endswith(suffixes):
                continue
            full = os.path.join(dirpath, name)
            try:
                mtime = os.path.getmtime(full)
            except OSError:
                continue
            if now - mtime < MIN_PRUNE_AGE_S:
                continue  # never prune live-looking state
            entries.append((mtime, full))
    except OSError:
        log.exception("journal retention scan failed (%s)", dirpath)
        return 0
    entries.sort()  # oldest first
    doomed = []
    if max_age_s > 0:
        doomed.extend(p for m, p in entries if now - m > max_age_s)
    if keep > 0 and len(entries) > keep:
        doomed.extend(p for _, p in entries[:len(entries) - keep])
    removed = 0
    for path in dict.fromkeys(doomed):  # de-dup, preserve oldest-first order
        try:
            os.remove(path)
            removed += 1
        except OSError:
            pass  # raced with a concurrent unlink / still open elsewhere
    return removed
